"""Static validation of the ``HOROVOD_FAULT_INJECT`` chaos grammar.

The C side (``ParseFaultSpec``, csrc/operations.cc) is deliberately
strict: ANY malformed spec keeps the trigger disarmed, because a
lenient parse that read garbage as ``0:0`` would kill rank 0 at its
first collective. Strictness has a flip side — a typo'd spec in a CI
chaos job silently tests *nothing*. This module mirrors the grammar
decision-for-decision so test authors and CI can reject a bad spec
*before* launching a multi-rank job:

    from horovod_tpu import analysis
    analysis.validate_chaos_spec("1:5:flip:17:2:0")   # -> FaultSpec
    analysis.validate_chaos_spec("1:5:flip:17:")      # ChaosSpecError

or from the shell::

    python -m horovod_tpu.analysis.model --chaos-spec "1:5:flip:17:2:0"

Grammar (docs/elastic.md, docs/wire.md)::

    <rank>:<op>[:<action>[:<param>[:<skip>[:<chan>]]]]

    kill                 hard-exit at collective #op (no param)
    stop:<ms>            freeze ms > 0 (the stalled-not-dead shape)
    reset[:<chan>]       RST peer sockets; optional single stripe chan
    flip:<bit>           corrupt one wire frame (negative = persistent)
    flip:<bit>:<skip>    ... after skipping <skip> data frames
    flip:<bit>:<skip>:<chan>  ... counting only on one stripe channel
    delay:<ms>           inject a straggler stall ms > 0

The numeric fields follow C ``strtoll`` base-10: optional leading
whitespace and sign, full consume required. One deliberate divergence:
values that overflow int64 are *rejected* here (the C parse clamps to
``LLONG_MAX`` and arms with a garbage value — strictly worse for CI).

Every constant below is pinned against the C sources by the ABI drift
guards (``analysis.model.abi``), so the two parsers cannot silently
diverge.
"""

import dataclasses
import re

# Order is ABI: index i is csrc/operations.cc FaultAction value i
# (kFaultKill=0 .. kFaultDelay=4). Pinned by analysis.model.abi.
ACTIONS = ("kill", "stop", "reset", "flip", "delay")

# csrc/wire.h kMaxWireChannels (ABI-guarded).
MAX_WIRE_CHANNELS = 8

# flip's packed param layout (csrc/operations.cc kFlipSkipShift /
# kFlipChanShift): low 20 bits = bit index, bits 20..43 = frames to
# skip before flipping, bits 44+ = (stripe channel + 1), 0 = no filter.
FLIP_SKIP_SHIFT = 20
FLIP_CHAN_SHIFT = 44
FLIP_BIT_MASK = (1 << FLIP_SKIP_SHIFT) - 1
FLIP_SKIP_MASK = (1 << (FLIP_CHAN_SHIFT - FLIP_SKIP_SHIFT)) - 1

_INT64_MAX = (1 << 63) - 1

# strtoll base 10 with mandatory full consume: optional leading
# whitespace, optional sign, digits, nothing after.
_INT_RE = re.compile(r"[ \t\n\v\f\r]*[+-]?[0-9]+\Z")


class ChaosSpecError(ValueError):
    """A HOROVOD_FAULT_INJECT spec the C parser would leave disarmed."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """A validated fault-inject spec, fields as the C core decodes them.

    ``param`` carries the same packed value ``ParseFaultSpec`` would
    produce (for ``flip`` the bit/skip/chan fields are packed; the
    ``flip_*`` properties unpack them again).
    """

    rank: int
    op: int
    action: str
    param: int

    @property
    def flip_bit(self):
        if self.action != "flip":
            return None
        return self.param if self.param < 0 else self.param & FLIP_BIT_MASK

    @property
    def flip_skip(self):
        if self.action != "flip" or self.param < 0:
            return None
        return (self.param >> FLIP_SKIP_SHIFT) & FLIP_SKIP_MASK

    @property
    def flip_channel(self):
        """Stripe channel filter, or None when unfiltered."""
        if self.action != "flip" or self.param < 0:
            return None
        chan = self.param >> FLIP_CHAN_SHIFT
        return chan - 1 if chan > 0 else None


def _parse_i64(field, text):
    if not text:
        raise ChaosSpecError(f"{field}: empty numeric field")
    if not _INT_RE.match(text):
        raise ChaosSpecError(
            f"{field}: {text!r} is not a base-10 integer "
            "(strtoll full-consume)")
    v = int(text)
    if not -_INT64_MAX - 1 <= v <= _INT64_MAX:
        raise ChaosSpecError(f"{field}: {text!r} overflows int64")
    return v


def validate_chaos_spec(spec):
    """Validate a ``HOROVOD_FAULT_INJECT`` spec string.

    Returns a :class:`FaultSpec` on success; raises
    :class:`ChaosSpecError` (a ``ValueError``) with the reason the C
    parser would reject it — i.e. the reason the trigger would silently
    stay disarmed — on failure. Accept/reject agrees with
    ``ParseFaultSpec`` for every int64-representable spec (pinned by
    the cross-validation test in tests/single/test_analysis_model.py).
    """
    if not isinstance(spec, str):
        raise ChaosSpecError(f"spec must be a string, got {type(spec)!r}")
    parts = spec.split(":")
    if len(parts) < 2 or len(parts) > 6:
        raise ChaosSpecError(
            f"expected <rank>:<op>[:<action>[:<param>[:<skip>[:<chan>]]]], "
            f"got {len(parts)} field(s)")
    rank = _parse_i64("rank", parts[0])
    if rank < 0:
        raise ChaosSpecError(f"rank: must be >= 0, got {rank}")
    op = _parse_i64("op", parts[1])
    if op < 0:
        raise ChaosSpecError(f"op: must be >= 0, got {op}")
    if len(parts) >= 5 and parts[2] != "flip":
        raise ChaosSpecError(
            f"only flip takes <skip>/<chan> fields, not {parts[2]!r}")

    action = "kill"
    param = 0
    has_param = len(parts) >= 4
    if len(parts) >= 3:
        action = parts[2]
        if action == "kill":
            if has_param:
                raise ChaosSpecError("kill takes no param")
        elif action in ("stop", "delay"):
            param = _parse_i64("ms", parts[3]) if has_param else None
            if param is None or param <= 0:
                raise ChaosSpecError(f"{action} requires a positive ms param")
        elif action == "reset":
            param = -1
            if has_param:
                param = _parse_i64("chan", parts[3])
                if not 0 <= param < MAX_WIRE_CHANNELS:
                    raise ChaosSpecError(
                        f"reset channel must be in [0, {MAX_WIRE_CHANNELS}), "
                        f"got {param}")
        elif action == "flip":
            if not has_param:
                raise ChaosSpecError("flip requires a bit index")
            param = _parse_i64("bit", parts[3])
            # A non-negative bit must fit the packed low field even
            # WITHOUT a skip (negative = persistent |bit|, never
            # packed).
            if param > FLIP_BIT_MASK:
                raise ChaosSpecError(
                    f"flip bit must be <= {FLIP_BIT_MASK}, got {param}")
            if len(parts) >= 5:
                if param < 0:
                    raise ChaosSpecError(
                        "persistent (negative-bit) flip cannot take "
                        "<skip>/<chan> — one-shot only")
                skip = _parse_i64("skip", parts[4])
                if not 0 <= skip <= FLIP_SKIP_MASK:
                    raise ChaosSpecError(
                        f"flip skip must be in [0, {FLIP_SKIP_MASK}], "
                        f"got {skip}")
                param |= skip << FLIP_SKIP_SHIFT
                if len(parts) == 6:
                    chan = _parse_i64("chan", parts[5])
                    if not 0 <= chan < MAX_WIRE_CHANNELS:
                        raise ChaosSpecError(
                            f"flip channel must be in "
                            f"[0, {MAX_WIRE_CHANNELS}), got {chan}")
                    param |= (chan + 1) << FLIP_CHAN_SHIFT
        else:
            raise ChaosSpecError(
                f"unknown action {action!r} "
                f"(expected one of {', '.join(ACTIONS)})")
    return FaultSpec(rank=rank, op=op, action=action, param=param)
