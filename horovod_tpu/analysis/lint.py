"""hvdlint CLI: ``python -m horovod_tpu.analysis.lint``.

::

    python -m horovod_tpu.analysis.lint --all
    python -m horovod_tpu.analysis.lint --program pipeline_interleaved_1f1b
    python -m horovod_tpu.analysis.lint --program llama_train_step \
        --config tiny_moe
    python -m horovod_tpu.analysis.lint --all --allow C3

Exit status 1 when any error-severity diagnostic survives the
allowlist. The library API is ``horovod_tpu.analysis.lint(fn, args,
mesh=...)`` (implemented in ``analysis/api.py`` — this module is the
CLI shim so the two can share the dotted name).
"""

import argparse
import sys
import types

from horovod_tpu.analysis.api import errors, lint  # noqa: F401


class _CallableModule(types.ModuleType):
    """Importing this submodule rebinds the package attribute
    ``horovod_tpu.analysis.lint`` from the API function to the module
    (standard import-machinery behaviour). Making the module itself
    callable keeps ``analysis.lint(fn, args, mesh=...)`` working in
    both resolution states."""

    def __call__(self, *args, **kwargs):
        return lint(*args, **kwargs)


sys.modules[__name__].__class__ = _CallableModule


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.lint",
        description="hvdlint: static SPMD collective-consistency "
                    "analyzer (checks C1-C8; see docs/analysis.md)")
    p.add_argument("--program", action="append", default=[],
                   help="registered program name (repeatable); see "
                        "--list")
    p.add_argument("--all", action="store_true",
                   help="lint every registered shipped program "
                        "(default when no --program is given)")
    p.add_argument("--list", action="store_true",
                   help="list registered programs and exit")
    p.add_argument("--config", default="tiny",
                   help="model config preset for model-backed programs "
                        "(tiny, tiny_moe; default tiny)")
    p.add_argument("--allow", action="append", default=[],
                   help="suppress a diagnostic id (e.g. C3) or id:path")
    args = p.parse_args(argv)

    from horovod_tpu.analysis import programs

    if args.list:
        for name in programs.program_names():
            print(name)
        return 0
    names = list(args.program)
    if args.all or not names:
        names = programs.program_names()

    rc = 0
    for name in names:
        diags = programs.lint_program(name, config=args.config,
                                      allow=tuple(args.allow))
        status = "clean" if not diags else f"{len(diags)} diagnostic(s)"
        print(f"[hvdlint] {name}: {status}")
        for d in diags:
            print("  " + d.format())
        if errors(diags):
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
