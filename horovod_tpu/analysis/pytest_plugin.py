"""Pytest plugin: lint fixtures for hvdlint.

Registered by ``tests/conftest.py`` (``pytest_plugins``); gives every
test file two fixtures:

- ``hvdlint`` — assert one program is collective-consistent::

      def test_my_step(hvdlint):
          diags = hvdlint(step_fn, (carry, batch), mesh=mesh)

  Raises (pytest-fails) on any error-severity diagnostic; returns the
  full diagnostic list so tests can additionally assert on warnings.

- ``hvdlint_shipped`` — the registry hook: lints one named shipped
  program from ``analysis.programs`` and asserts it clean. The
  quick-lane model tests run their programs through this, so every
  future PR's programs are linted for free.
"""

import pytest


@pytest.fixture()
def hvdlint():
    from horovod_tpu import analysis
    from horovod_tpu.analysis.api import lint

    def check(fn, args=(), allow=(), **kw):
        diags = lint(fn, args, allow=allow, **kw)
        errs = analysis.errors(diags)
        if errs:
            pytest.fail(
                "hvdlint found collective-consistency errors:\n"
                + "\n".join(d.format() for d in errs))
        return diags

    return check


@pytest.fixture()
def hvdlint_shipped():
    from horovod_tpu.analysis import programs

    def check(name, config="tiny", allow=()):
        diags = programs.lint_program(name, config=config, allow=allow)
        if diags:
            pytest.fail(
                f"hvdlint: shipped program {name!r} is not clean:\n"
                + "\n".join(d.format() for d in diags))
        return diags

    return check
