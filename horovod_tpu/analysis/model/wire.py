"""hvdcheck model: the striped wire framing protocol (CRC/NAK/DONE).

Abstracts one sender->receiver link of the host ring (csrc/wire.cc,
docs/wire.md) to the decisions that carry the protocol's invariants:

- framing: each stripe channel is a self-framing stream of
  ``D1|idx|crc|payload`` data frames closed by a ``5E`` DONE marker;
  chunk ``i`` of a transfer rides channel ``i % K`` with its GLOBAL
  index (a lane-mismatched idx is a protocol error, r20).
- CRC verify-before-reduce: a chunk is handed to ReduceInto ONLY
  after its CRC verifies; a bad frame costs a NAK and an idempotent
  resend (the heal ladder's backoff is timing, not ordering, so the
  NAK/resend cycle models it), and the same chunk failing more than
  ``retries + 1`` times escalates to a typed WireCorruption — a
  legitimate terminal, never a hang.
- the reader-stops-at-slot-satisfied rule (r14): back-to-back
  transfers share the sockets with no ack gap, so once a slot has
  every chunk verified and the DONE marker on every channel, the
  reader must STOP — the next bytes in the stream belong to the next
  transfer, and reading them here misfiles them as duplicates of the
  already-verified chunks.

Safety invariants: no chunk reduced before its CRC verified; no chunk
reduced twice; no lane-mismatched frame accepted. Liveness: every
execution reaches all-transfers-complete or a typed escalation.

Seeded mutants:

- ``reduce_before_verify``: the receiver reduces a frame's payload on
  receipt and only then checks the CRC — one bit-flip and corrupt
  data is already in the accumulator.
- ``read_past_slot`` (r14): the reader keeps draining the stream
  after its slot is satisfied; the next transfer's first frame is
  consumed and discarded as a duplicate, and that transfer can then
  never complete — the checker reports the deadlock.
"""

from typing import NamedTuple

DATA, DONE = "data", "done"


class Frame(NamedTuple):
    transfer: int   # ground truth; the receiver must NOT look at this
    kind: str       # DATA | DONE
    idx: int        # global chunk index within the transfer
    good: bool      # CRC will verify


class State(NamedTuple):
    sent: tuple        # per channel: pointer into the send schedule
    fifo: tuple        # per channel: tuple of in-flight Frames
    naks: frozenset    # (transfer, idx) awaiting idempotent resend
    slot: int          # receiver's current transfer slot
    verified: frozenset  # idx verified in the current slot
    done_seen: frozenset  # channels whose DONE arrived, current slot
    fails: tuple       # per idx: CRC failures in the current slot
    reduced: tuple     # per transfer: per idx: times handed to reduce
    corrupts: int      # remaining bit-flip budget
    escalated: bool    # typed WireCorruption raised (terminal)
    protocol_error: str


class WireModel:
    """Bounded striped-transfer instance.

    ``mutation`` is None for the real protocol, or one of
    ``"reduce_before_verify"`` / ``"read_past_slot"``.
    """

    def __init__(self, n_chunks=2, channels=2, transfers=1, corrupts=1,
                 retries=0, mutation=None):
        assert mutation in (None, "reduce_before_verify", "read_past_slot")
        self.n_chunks = n_chunks
        self.channels = channels
        self.transfers = transfers
        self.retries = retries
        self.mutation = mutation
        self._corrupts = corrupts
        # Per-channel send schedule: each transfer's chunks striped
        # idx % K, each channel's stream closed by that transfer's
        # DONE. The sender does NOT wait for any receiver ack between
        # transfers — that gap is exactly the r14 bug window.
        self.sched = [[] for _ in range(channels)]
        for t in range(transfers):
            for idx in range(n_chunks):
                self.sched[idx % channels].append((t, DATA, idx))
            for c in range(channels):
                self.sched[c].append((t, DONE, 0))
        self.name = (f"wire(chunks={n_chunks},chans={channels},"
                     f"transfers={transfers},corrupts={corrupts}"
                     + (f",mutant={mutation})" if mutation else ")"))

    def initial(self):
        yield State(
            sent=(0,) * self.channels,
            fifo=((),) * self.channels,
            naks=frozenset(), slot=0, verified=frozenset(),
            done_seen=frozenset(), fails=(0,) * self.n_chunks,
            reduced=((0,) * self.n_chunks,) * self.transfers,
            corrupts=self._corrupts, escalated=False, protocol_error="")

    # -- helpers ---------------------------------------------------------

    def _satisfied(self, st):
        return (len(st.verified) == self.n_chunks
                and len(st.done_seen) == self.channels)

    def _push(self, st, c, frame):
        fifo = list(st.fifo)
        fifo[c] = fifo[c] + (frame,)
        return st._replace(fifo=tuple(fifo))

    def _reduce(self, st, idx):
        reduced = [list(row) for row in st.reduced]
        reduced[st.slot][idx] = min(reduced[st.slot][idx] + 1, 2)
        return st._replace(reduced=tuple(tuple(r) for r in reduced))

    # -- transitions -----------------------------------------------------

    def actions(self, st):
        if st.escalated or st.protocol_error:
            return []   # connection torn down: typed error, not a hang
        out = []

        # Sender: next scheduled frame, any channel interleaving.
        for c in range(self.channels):
            if st.sent[c] < len(self.sched[c]):
                t, kind, idx = self.sched[c][st.sent[c]]
                sent = list(st.sent)
                sent[c] += 1
                nxt = self._push(st._replace(sent=tuple(sent)), c,
                                 Frame(t, kind, idx, True))
                label = (f"sender: transfer{t} chunk{idx} -> chan{c}"
                         if kind == DATA else
                         f"sender: transfer{t} DONE -> chan{c}")
                out.append((label, nxt))

        # Sender: idempotent NAK resend.
        for t, idx in sorted(st.naks):
            c = idx % self.channels
            nxt = self._push(st._replace(naks=st.naks - {(t, idx)}), c,
                             Frame(t, DATA, idx, True))
            out.append((f"sender: NAK resend transfer{t} chunk{idx} "
                        f"-> chan{c}", nxt))

        # Environment: flip a bit in any in-flight data frame.
        if st.corrupts > 0:
            for c in range(self.channels):
                for pos, f in enumerate(st.fifo[c]):
                    if f.kind == DATA and f.good:
                        fifo = list(st.fifo)
                        fifo[c] = (fifo[c][:pos]
                                   + (f._replace(good=False),)
                                   + fifo[c][pos + 1:])
                        out.append((
                            f"env: bit-flip chan{c} pos{pos} "
                            f"(transfer{f.transfer} chunk{f.idx})",
                            st._replace(fifo=tuple(fifo),
                                        corrupts=st.corrupts - 1)))

        # Receiver: pop the head of a channel's stream. The real
        # reader STOPS once the slot is satisfied; the read_past_slot
        # mutant keeps draining.
        may_read = (not self._satisfied(st)
                    or self.mutation == "read_past_slot")
        if may_read:
            for c in range(self.channels):
                if st.fifo[c]:
                    out.append(self._pop(st, c))

        # Receiver: slot satisfied -> stop reading, open the next
        # slot. The bytes still in the streams belong to it.
        if self._satisfied(st) and st.slot < self.transfers - 1:
            out.append((
                f"receiver: slot{st.slot} satisfied -> stop reading, "
                f"open slot{st.slot + 1}",
                st._replace(slot=st.slot + 1, verified=frozenset(),
                            done_seen=frozenset(),
                            fails=(0,) * self.n_chunks)))
        return out

    def _pop(self, st, c):
        frame = st.fifo[c][0]
        fifo = list(st.fifo)
        fifo[c] = fifo[c][1:]
        st = st._replace(fifo=tuple(fifo))
        past = " (slot already satisfied)" if self._satisfied(st) else ""

        if frame.kind == DONE:
            return (f"receiver: chan{c} DONE marker{past}",
                    st._replace(done_seen=st.done_seen | {c}))

        if frame.idx % self.channels != c:
            return (f"receiver: chan{c} frame idx{frame.idx} "
                    f"LANE MISMATCH",
                    st._replace(protocol_error=(
                        f"chunk{frame.idx} arrived on chan{c}, expected "
                        f"chan{frame.idx % self.channels}")))

        if frame.idx in st.verified:
            # Idempotent-dup path. When the frame actually belongs to
            # the NEXT transfer (read past a satisfied slot) this
            # discard is the r14 data loss.
            stale = (" of NEXT transfer" if frame.transfer != st.slot
                     else "")
            return (f"receiver: chan{c} chunk{frame.idx}{stale} already "
                    f"verified -> discarded as duplicate{past}", st)

        if self.mutation == "reduce_before_verify":
            st = self._reduce(st, frame.idx)   # BEFORE the CRC check

        if frame.good:
            st = st._replace(verified=st.verified | {frame.idx})
            if self.mutation != "reduce_before_verify":
                st = self._reduce(st, frame.idx)
            return (f"receiver: chan{c} chunk{frame.idx} CRC ok -> "
                    f"verified + reduced{past}", st)

        fails = list(st.fails)
        fails[frame.idx] += 1
        st = st._replace(fails=tuple(fails))
        if fails[frame.idx] > self.retries + 1:
            return (f"receiver: chan{c} chunk{frame.idx} CRC fail "
                    f"#{fails[frame.idx]} -> retries exhausted, raise "
                    f"WireCorruption", st._replace(escalated=True))
        return (f"receiver: chan{c} chunk{frame.idx} CRC fail "
                f"#{fails[frame.idx]} -> NAK",
                st._replace(naks=st.naks | {(st.slot, frame.idx)}))

    # -- properties ------------------------------------------------------

    def invariant(self, st):
        if st.protocol_error:
            return f"lane discipline: {st.protocol_error}"
        for t, row in enumerate(st.reduced):
            for idx, n in enumerate(row):
                if n > 1:
                    return (f"exactly-once: transfer{t} chunk{idx} "
                            f"reduced {n} times")
        for idx, n in enumerate(st.reduced[st.slot]):
            if n > 0 and idx not in st.verified:
                return (f"verify-before-reduce: transfer{st.slot} "
                        f"chunk{idx} was handed to ReduceInto without "
                        f"a verified CRC")
        return None

    def done(self, st):
        if st.escalated:
            return True
        return (st.slot == self.transfers - 1 and self._satisfied(st))
