"""ABI drift guards: pin the Python twins against the C sources.

Several tables cross the C/Python boundary *by index*, with no runtime
negotiation — the event vocabulary (``csrc/events.h`` ``EventType``
and its ``kEventSpecs`` name/arg table), the serving-request lifecycle
(``RequestPhase`` / ``kRequestPhaseNames`` mirrored by
``telemetry.reqtrace.REQUEST_PHASES``), the control-plane phase table
(``metrics.h`` ``ControlPhase`` / ``HorovodBasics.CONTROL_PHASES``),
the autotuner knob ids (``EventKnob`` / ``kKnobNames`` / the
``ResponseList`` knob fields and their serialization order), the
cross-plane mode names, and the chaos-grammar constants mirrored by
``analysis.chaos``. A silent edit on either side of any of them is a
wire-format or telemetry corruption that no unit test of one side can
see.

This module scrapes the C sources with regexes (:func:`scrape_all`)
and verifies every pinned relationship (:func:`verify`) — including
the relationships into hvdcheck's own model vocabulary, so the model
checker's specs cannot drift from the runtime they describe either.
``verify`` takes the scraped tables as a plain dict precisely so the
test suite can mutate one entry and prove the guard trips
(tests/single/test_analysis_model.py round-trips every table).
"""

import os
import re

from horovod_tpu.analysis import chaos

# -- Python-side twin tables pinned here (the models' grammars) ---------

# EventKnob id i <-> kKnobNames[i] <-> the rank-uniform ResponseList
# field the coordinator syncs for it (message.h order == serialization
# order == this order). kKnobCycleTimeMs deliberately maps to
# "cycle_time_us": event args are integral, so the event value is in
# microseconds while the message field stays a double in ms.
KNOB_TABLE = (
    ("fusion_bytes", "fusion_threshold_bytes"),
    ("cycle_time_us", "cycle_time_ms"),
    ("ring_chunk", "ring_chunk_bytes"),
    ("wire_compression", "wire_compression"),
    ("hier_split", "hier_split"),
    ("wire_channels", "wire_channels"),
)

# The post-mortem merge tags every timeline entry with its source rank
# under this key; no event arg may shadow it (csrc/events.cc NB).
RESERVED_ARG = "rank"


def _repo_root():
    here = os.path.dirname(os.path.abspath(__file__))  # .../analysis/model
    return os.path.dirname(os.path.dirname(os.path.dirname(here)))


def _read(root, name):
    with open(os.path.join(root, "csrc", name), "r", encoding="utf-8") as f:
        return f.read()


def _snake(camel):
    return re.sub(r"(?<!^)(?=[A-Z0-9])", "_", camel).lower()


def _enum_members(text, enum_re, stop=None):
    m = re.search(enum_re + r"\s*\{(.*?)\};", text, re.S)
    if not m:
        return []
    body = re.sub(r"//[^\n]*", "", m.group(1))
    names = re.findall(r"\b(k\w+)\b\s*(?:=\s*[\w:]+)?\s*(?:,|$)", body)
    if stop and stop in names:
        names = names[:names.index(stop)]
    return names


def _strings(text, anchor):
    m = re.search(re.escape(anchor) + r"[^{]*\{(.*?)\};", text, re.S)
    if not m:
        return []
    body = re.sub(r"//[^\n]*", "", m.group(1))
    return re.findall(r'"([^"]*)"', body)


def scrape_all(root=None):
    """Scrape every ABI-bearing table out of the C sources."""
    root = root or _repo_root()
    events_h = _read(root, "events.h")
    events_cc = _read(root, "events.cc")
    metrics_h = _read(root, "metrics.h")
    message_h = _read(root, "message.h")
    message_cc = _read(root, "message.cc")
    operations_cc = _read(root, "operations.cc")
    wire_h = _read(root, "wire.h")
    common_h = _read(root, "common.h")

    t = {}
    t["event_types"] = _enum_members(
        events_h, r"enum class EventType : int32_t", stop="kTypeCount")
    specs_m = re.search(r"kEventSpecs\[[^\]]*\]\s*=\s*\{(.*?)\n\};",
                        events_cc, re.S)
    specs_body = re.sub(r"//[^\n]*", "", specs_m.group(1)) if specs_m else ""
    t["event_specs"] = re.findall(
        r'\{\s*"([^"]*)"\s*,\s*"([^"]*)"\s*,\s*"([^"]*)"\s*,'
        r'\s*"([^"]*)"\s*,\s*"([^"]*)"\s*\}', specs_body)
    t["request_phase_enum"] = _enum_members(
        events_h, r"enum RequestPhase : int32_t", stop="kReqPhaseCount")
    t["request_phase_names"] = _strings(events_cc, "kRequestPhaseNames")
    t["knob_enum"] = _enum_members(events_h, r"enum EventKnob : int32_t")
    t["knob_names"] = _strings(events_cc, "kKnobNames")
    t["slo_objective_enum"] = _enum_members(
        events_h, r"enum SloObjective : int32_t", stop="kSloObjectiveCount")
    t["slo_objective_names"] = _strings(events_cc, "kSloObjectiveNames")
    t["rank_bucket_names"] = _strings(events_cc, "kRankBucketNames")
    t["control_phase_enum"] = _enum_members(
        metrics_h, r"enum ControlPhase : int32_t", stop="kPhaseCount")
    t["cross_plane_modes"] = _strings(common_h, "CrossPlaneModeNames")

    struct_m = re.search(r"struct ResponseList\s*\{(.*?)\n\};",
                         message_h, re.S)
    struct_body = struct_m.group(1) if struct_m else ""
    t["response_fields"] = re.findall(
        r"^\s*(?:std::vector<[^>]+>|std::string|int64_t|int32_t|double|"
        r"bool)\s+(\w+)\s*(?:=[^;]*)?;", struct_body, re.M)
    ser_m = re.search(
        r"std::string SerializeResponseList\((.*?)\n\}", message_cc, re.S)
    t["response_serial_order"] = re.findall(
        r"list\.(\w+)\)", ser_m.group(1)) if ser_m else []

    t["fault_actions"] = _enum_members(
        operations_cc, r"enum FaultAction : int32_t")
    shift = dict(re.findall(
        r"constexpr int (kFlip\w+Shift) = (\d+);", operations_cc))
    t["flip_skip_shift"] = int(shift.get("kFlipSkipShift", -1))
    t["flip_chan_shift"] = int(shift.get("kFlipChanShift", -1))
    chan_m = re.search(r"constexpr int kMaxWireChannels = (\d+);", wire_h)
    t["max_wire_channels"] = int(chan_m.group(1)) if chan_m else -1
    return t


def verify(t):
    """Check every pinned C<->Python relationship; returns failures."""
    # The Python twins (imported lazily so a scrape-only caller works
    # even if the package half is being refactored).
    from horovod_tpu.common.basics import HorovodBasics
    from horovod_tpu.telemetry import reqtrace

    errs = []

    def expect(cond, msg):
        if not cond:
            errs.append(msg)

    # -- event vocabulary ------------------------------------------------
    expect(len(t["event_types"]) >= 22,
           f"EventType scrape too small: {t['event_types']}")
    derived = tuple(_snake(n[1:]) for n in t["event_types"])
    spec_names = tuple(s[0] for s in t["event_specs"])
    expect(derived == spec_names,
           f"EventType enum vs kEventSpecs name drift: "
           f"{derived} != {spec_names}")
    for s in t["event_specs"]:
        expect(RESERVED_ARG not in s[1:],
               f"event {s[0]!r} uses reserved arg name {RESERVED_ARG!r} "
               f"(the post-mortem merge owns that key)")

    # -- serving-request lifecycle ---------------------------------------
    phases = tuple(t["request_phase_names"])
    derived = tuple(_snake(n[len("kReq"):]) for n in t["request_phase_enum"])
    expect(derived == phases,
           f"RequestPhase enum vs kRequestPhaseNames drift: "
           f"{derived} != {phases}")
    expect(tuple(reqtrace.REQUEST_PHASES) == phases,
           f"reqtrace.REQUEST_PHASES {tuple(reqtrace.REQUEST_PHASES)} != "
           f"csrc kRequestPhaseNames {phases}")
    expect(phases and reqtrace.TERMINAL_PHASE == phases[-1],
           "reqtrace.TERMINAL_PHASE is not the last RequestPhase")

    # -- SLO objectives + rank-seconds buckets (docs/fleet.md) -----------
    from horovod_tpu.telemetry import fleet, slo

    expect(len(t["slo_objective_enum"]) == len(t["slo_objective_names"]),
           f"SloObjective has {len(t['slo_objective_enum'])} members, "
           f"kSloObjectiveNames {len(t['slo_objective_names'])}")
    expect(tuple(slo.OBJECTIVES) == tuple(t["slo_objective_names"]),
           f"slo.OBJECTIVES {tuple(slo.OBJECTIVES)} != csrc "
           f"kSloObjectiveNames {tuple(t['slo_objective_names'])}")
    expect(tuple(fleet.BUCKETS) == tuple(t["rank_bucket_names"]),
           f"fleet.BUCKETS {tuple(fleet.BUCKETS)} != csrc "
           f"kRankBucketNames {tuple(t['rank_bucket_names'])}")

    # -- control-plane phases --------------------------------------------
    derived = tuple(_snake(n[len("kPhase"):])
                    for n in t["control_phase_enum"])
    expect(tuple(HorovodBasics.CONTROL_PHASES) == derived,
           f"HorovodBasics.CONTROL_PHASES "
           f"{tuple(HorovodBasics.CONTROL_PHASES)} != metrics.h "
           f"ControlPhase {derived}")

    # -- cross-plane modes -----------------------------------------------
    expect(tuple(HorovodBasics.CROSS_PLANE_MODES)
           == tuple(t["cross_plane_modes"]),
           f"HorovodBasics.CROSS_PLANE_MODES != common.h "
           f"CrossPlaneModeNames {t['cross_plane_modes']}")

    # -- autotuner knobs: enum <-> names <-> message fields <-> wire ----
    expect(tuple(t["knob_names"]) == tuple(k for k, _ in KNOB_TABLE),
           f"kKnobNames {t['knob_names']} != pinned KNOB_TABLE")
    expect(len(t["knob_enum"]) == len(KNOB_TABLE),
           f"EventKnob has {len(t['knob_enum'])} members, KNOB_TABLE "
           f"pins {len(KNOB_TABLE)}")
    fields = t["response_fields"]
    knob_fields = [f for _, f in KNOB_TABLE]
    expect(all(f in fields for f in knob_fields),
           f"ResponseList is missing knob field(s): "
           f"{[f for f in knob_fields if f not in fields]}")
    present = [f for f in fields if f in knob_fields]
    expect(present == knob_fields,
           f"ResponseList declares knob fields as {present}, KNOB_TABLE "
           f"pins {knob_fields} (order is the knob-id ABI)")
    ser = [f for f in t["response_serial_order"] if f in knob_fields]
    expect(ser == knob_fields,
           f"SerializeResponseList writes knobs as {ser}, expected "
           f"{knob_fields} (field order IS the wire format)")

    # -- chaos grammar ---------------------------------------------------
    derived = tuple(_snake(n[len("kFault"):]) for n in t["fault_actions"])
    expect(tuple(chaos.ACTIONS) == derived,
           f"chaos.ACTIONS {chaos.ACTIONS} != operations.cc FaultAction "
           f"{derived}")
    expect(chaos.FLIP_SKIP_SHIFT == t["flip_skip_shift"],
           f"chaos.FLIP_SKIP_SHIFT {chaos.FLIP_SKIP_SHIFT} != "
           f"kFlipSkipShift {t['flip_skip_shift']}")
    expect(chaos.FLIP_CHAN_SHIFT == t["flip_chan_shift"],
           f"chaos.FLIP_CHAN_SHIFT {chaos.FLIP_CHAN_SHIFT} != "
           f"kFlipChanShift {t['flip_chan_shift']}")
    expect(chaos.MAX_WIRE_CHANNELS == t["max_wire_channels"],
           f"chaos.MAX_WIRE_CHANNELS {chaos.MAX_WIRE_CHANNELS} != "
           f"wire.h kMaxWireChannels {t['max_wire_channels']}")
    return errs


def check_abi(root=None):
    """Scrape the tree and verify; returns a list of drift messages."""
    return verify(scrape_all(root))
