"""CLI for hvdcheck: ``python -m horovod_tpu.analysis.model``.

``--all`` (what ``make model-check`` runs) checks every bounded real
model, proves every seeded mutant is caught with a counterexample
interleaving, and runs the ABI drift guards. Individual pieces:
``--model elastic|wire|serving``, ``--mutants``, ``--abi``,
``--chaos-spec SPEC``, ``--list``.
"""

import argparse
import sys
import time

from horovod_tpu.analysis import chaos
from horovod_tpu.analysis import model as hvdcheck
from horovod_tpu.analysis.model import abi


def _family(name):
    return name.name.split("(", 1)[0]


def _check_real(models):
    failed = 0
    for m in models:
        t0 = time.monotonic()
        res = hvdcheck.check(m)
        dt = time.monotonic() - t0
        print(f"{res.format()}  [{dt:.2f}s]")
        if not res.ok:
            failed += 1
    return failed


def _check_mutants():
    failed = 0
    for name, (factory, history) in hvdcheck.MUTANTS.items():
        model = factory()
        res = hvdcheck.check(model)
        if res.ok:
            print(f"mutant {name}: NOT CAUGHT -- the checker no longer "
                  f"detects this historical bug ({history})")
            failed += 1
        else:
            v = res.violation
            print(f"mutant {name}: caught ({v.kind}) -- {history}")
            print(f"  {v.message}")
            print(hvdcheck.format_trace(v.trace))
    return failed


def _check_abi():
    errs = abi.check_abi()
    if errs:
        for e in errs:
            print(f"ABI drift: {e}")
        return len(errs)
    print("ABI drift guards: all Python twins pinned to csrc -- OK")
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.analysis.model",
        description="hvdcheck: exhaustive protocol model checking "
                    "(docs/analysis.md)")
    p.add_argument("--all", action="store_true",
                   help="real models + seeded mutants + ABI guards "
                        "(what `make model-check` runs)")
    p.add_argument("--model", metavar="FAMILY",
                   help="check one family's real model(s): "
                        "elastic | wire | serving")
    p.add_argument("--mutants", action="store_true",
                   help="prove every seeded historical bug is caught")
    p.add_argument("--abi", action="store_true",
                   help="run the ABI drift guards only")
    p.add_argument("--chaos-spec", metavar="SPEC",
                   help="validate a HOROVOD_FAULT_INJECT spec and exit")
    p.add_argument("--list", action="store_true",
                   help="list models and seeded mutants")
    args = p.parse_args(argv)

    if args.chaos_spec is not None:
        try:
            spec = chaos.validate_chaos_spec(args.chaos_spec)
        except chaos.ChaosSpecError as e:
            print(f"chaos-spec: REJECTED (would stay disarmed): {e}")
            return 1
        extra = ""
        if spec.action == "flip" and spec.flip_bit is not None:
            extra = (f" bit={spec.flip_bit} skip={spec.flip_skip}"
                     f" chan={spec.flip_channel}")
        print(f"chaos-spec: ok -- rank={spec.rank} op={spec.op} "
              f"action={spec.action} param={spec.param}{extra}")
        return 0

    if args.list:
        for m in hvdcheck.real_models():
            print(f"model   {m.name}")
        for name, (_, history) in hvdcheck.MUTANTS.items():
            print(f"mutant  {name}: {history}")
        return 0

    if not (args.all or args.model or args.mutants or args.abi):
        p.print_help()
        return 2

    failed = 0
    t0 = time.monotonic()
    if args.all or args.model:
        models = hvdcheck.real_models()
        if args.model:
            models = [m for m in models if _family(m) == args.model]
            if not models:
                print(f"unknown model family {args.model!r} "
                      f"(expected elastic | wire | serving)")
                return 2
        failed += _check_real(models)
    if args.all or args.mutants:
        failed += _check_mutants()
    if args.all or args.abi:
        failed += _check_abi()
    status = "FAIL" if failed else "OK"
    print(f"hvdcheck: {status} [{time.monotonic() - t0:.2f}s]")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
