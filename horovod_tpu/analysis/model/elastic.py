"""hvdcheck model: epoch-fenced elastic re-formation + the parole door.

Abstracts the driverless recovery path of ``common/elastic.py`` /
docs/elastic.md down to the decisions that have actually gone wrong:

- fault attribution: a dead peer is discovered as *certain* (EOF/RST)
  or *suspected* (timeout); the MSG_PEEK probe sweep must converge
  every survivor on the SAME dead set before re-forming.
- the keep-old-sockets-open ordering rule (r12): survivors keep the
  OLD ring's sockets open until the new ring is up, because a probe
  hitting an already-torn-down socket reads as EOF — certain death —
  for a rank that is alive and mid-reinit.
- the parole door (r14): joiners knock while an epoch is running;
  ``freeze(epoch)`` snapshots the pending set ONCE per epoch and the
  frozen entries are never popped, so a survivor that polls AFTER
  rank 0 released the assignments still sees the same joiner count.

Per-rank lifecycle: ``run -> probe -> freeze -> run@epoch+1``. Every
ordering of probes, polls, the coordinator's release, commits and
socket teardown across ranks is explored, with the fault and the
joiner knock injectable at every point.

Safety invariants:

- attribution: no live rank ever holds a live peer in its dead set.
- agreement: any two live ranks running the same epoch agree on the
  membership (world set + admitted-joiner count) of that epoch.

Liveness: every execution can reach a state where all live ranks run
the same epoch with equal membership.

Seeded mutants (the historical bugs, re-introduced):

- ``parole_refreeze`` (r14): ``release`` pops the frozen snapshot, so
  a survivor polling after release re-freezes the (now empty) pending
  set and commits a smaller world — split-brain on world size.
- ``early_socket_close`` (r12): a survivor tears down old sockets as
  soon as IT commits instead of waiting for the ring to be up; a
  slower survivor's probe then reads false EOF and excludes a live
  rank from its membership.
"""

from typing import NamedTuple

from horovod_tpu.analysis.model import checker

# Per-rank phases, in lifecycle order.
RUN, PROBE, FREEZE = "run", "probe", "freeze"

CERTAIN, SUSPECTED = "certain", "suspected"


class Rank(NamedTuple):
    alive: bool
    phase: str            # RUN | PROBE | FREEZE
    epoch: int
    dead: frozenset       # this rank's converged-so-far dead set
    probed: frozenset     # peers already probed this recovery
    joiners: int          # admitted joiner count (-1 = not yet polled)
    members: frozenset    # membership committed for `epoch`
    old_open: bool        # old ring's sockets still open


class Door(NamedTuple):
    pending: int          # joiners knocking, not yet frozen
    frozen: int           # snapshot for the recovery epoch (-1 = none)
    released: bool        # rank 0 released the assignments


class State(NamedTuple):
    ranks: tuple          # tuple of Rank
    door: Door
    kills: int            # remaining fault budget
    knocks: int           # remaining joiner-arrival budget


class ElasticModel:
    """Bounded elastic re-formation instance.

    ``mutation`` is None for the real protocol, or one of
    ``"parole_refreeze"`` / ``"early_socket_close"``.
    """

    def __init__(self, n_ranks=3, kills=1, knocks=1, mutation=None):
        assert mutation in (None, "parole_refreeze", "early_socket_close")
        self.n = n_ranks
        self.mutation = mutation
        self._kills = kills
        self._knocks = knocks
        self.name = f"elastic(n={n_ranks},kills={kills},knocks={knocks}" + (
            f",mutant={mutation})" if mutation else ")")

    # -- state helpers ---------------------------------------------------

    def initial(self):
        full = frozenset(range(self.n))
        rank = Rank(alive=True, phase=RUN, epoch=0, dead=frozenset(),
                    probed=frozenset(), joiners=0, members=full,
                    old_open=True)
        yield State(ranks=(rank,) * self.n,
                    door=Door(pending=0, frozen=-1, released=False),
                    kills=self._kills, knocks=self._knocks)

    def _set(self, st, i, **kw):
        ranks = list(st.ranks)
        ranks[i] = ranks[i]._replace(**kw)
        return st._replace(ranks=tuple(ranks))

    def _truly_dead(self, st):
        return frozenset(i for i, r in enumerate(st.ranks) if not r.alive)

    # -- transitions -----------------------------------------------------

    def actions(self, st):
        out = []
        dead = self._truly_dead(st)
        new_epoch = 1  # one fault budget => at most one recovery epoch

        # Environment: a joiner knocks at the door.
        if st.knocks > 0:
            out.append((
                "env: joiner knocks at the parole door",
                st._replace(knocks=st.knocks - 1,
                            door=st.door._replace(
                                pending=st.door.pending + 1))))

        # Environment: kill a non-coordinator rank (rank 0 survives;
        # elastic.py's driverless path requires the coordinator).
        if st.kills > 0:
            for i in range(1, self.n):
                if st.ranks[i].alive:
                    out.append((
                        f"env: rank{i} dies (SIGKILL)",
                        self._set(st, i, alive=False)._replace(
                            kills=st.kills - 1)))

        for i, r in enumerate(st.ranks):
            if not r.alive:
                continue

            # run@0 -> probe: notice a fault. EOF/RST gives a CERTAIN
            # first attribution; a timeout gives SUSPECTED — either
            # way the probe sweep must confirm every peer.
            if r.phase == RUN and r.epoch == 0 and dead:
                j = min(dead)
                out.append((
                    f"rank{i}: detects fault on rank{j} via EOF (certain)",
                    self._set(st, i, phase=PROBE, dead=frozenset([j]),
                              probed=frozenset([j]))))
                out.append((
                    f"rank{i}: detects fault on rank{j} via timeout "
                    f"(suspected)",
                    self._set(st, i, phase=PROBE)))

            # probe sweep: MSG_PEEK each unprobed peer, one action per
            # peer so every probe ordering interleaves with every
            # other rank's progress.
            if r.phase == PROBE:
                unprobed = [j for j in range(self.n)
                            if j != i and j not in r.probed]
                for j in unprobed:
                    if j in dead:
                        out.append((
                            f"rank{i}: probe rank{j} -> EOF, certain-dead",
                            self._set(st, i, dead=r.dead | {j},
                                      probed=r.probed | {j})))
                    elif not st.ranks[j].old_open:
                        # The r12 bug window: peer is alive but its OLD
                        # sockets are gone, so the probe reads EOF.
                        # Unreachable in the real model (teardown waits
                        # for the ring to be up, i.e. everyone past
                        # probing).
                        out.append((
                            f"rank{i}: probe rank{j} -> EOF on torn-down "
                            f"socket, FALSELY certain-dead",
                            self._set(st, i, dead=r.dead | {j},
                                      probed=r.probed | {j})))
                    else:
                        out.append((
                            f"rank{i}: probe rank{j} -> alive "
                            f"(old socket open)",
                            self._set(st, i, probed=r.probed | {j})))
                if not unprobed:
                    # joiners=-1 flags "door not yet polled for the
                    # recovery epoch".
                    out.append((
                        f"rank{i}: probe sweep converged "
                        f"(dead={sorted(r.dead)})",
                        self._set(st, i, phase=FREEZE, joiners=-1)))

            # freeze: poll the parole door (_ParoleDoor.freeze). The
            # snapshot happens once per epoch; later polls must read
            # the SAME count — unless the refreeze mutant popped it.
            if r.phase == FREEZE and r.joiners < 0:
                door = st.door
                if door.frozen < 0:
                    door = door._replace(frozen=door.pending, pending=0)
                out.append((
                    f"rank{i}: polls parole door -> {door.frozen} "
                    f"joiner(s) frozen for epoch {new_epoch}",
                    self._set(st, i, joiners=door.frozen)._replace(
                        door=door)))

            # freeze -> run@new: commit the re-formed ring. Membership
            # = surviving old ranks per MY dead set, plus MY frozen
            # joiner count. The early-close mutant tears down the old
            # sockets here, at its own commit.
            if r.phase == FREEZE and r.joiners >= 0:
                members = frozenset(range(self.n)) - r.dead
                nxt = self._set(
                    st, i, phase=RUN, epoch=new_epoch, members=members,
                    old_open=(self.mutation != "early_socket_close"))
                out.append((
                    f"rank{i}: commits epoch {new_epoch} "
                    f"(members={sorted(members)}, joiners={r.joiners})",
                    nxt))

            # coordinator releases the door assignments after ITS
            # reinit. Real _ParoleDoor.release keeps the frozen
            # snapshot forever; the refreeze mutant pops it, so the
            # next poll re-freezes whatever is pending now.
            if (i == 0 and r.phase == RUN and r.epoch == new_epoch
                    and st.door.frozen >= 0 and not st.door.released):
                door = st.door._replace(released=True)
                if self.mutation == "parole_refreeze":
                    door = door._replace(frozen=-1)
                out.append((
                    "rank0: releases parole assignments "
                    + ("and POPS the frozen snapshot"
                       if self.mutation == "parole_refreeze"
                       else "(frozen snapshot retained)"),
                    st._replace(door=door)))

            # new ring up -> tear down the OLD ring's sockets. Real
            # rule (r12): only once every survivor in my membership
            # has committed the new epoch.
            if (r.phase == RUN and r.epoch == new_epoch and r.old_open
                    and self.mutation != "early_socket_close"):
                ring_up = all(
                    st.ranks[j].phase == RUN
                    and st.ranks[j].epoch == new_epoch
                    for j in r.members if st.ranks[j].alive)
                if ring_up:
                    out.append((
                        f"rank{i}: new ring up -> closes old sockets",
                        self._set(st, i, old_open=False)))

        return out

    # -- properties ------------------------------------------------------

    def invariant(self, st):
        dead = self._truly_dead(st)
        live = [(i, r) for i, r in enumerate(st.ranks) if r.alive]
        for i, r in live:
            wrong = r.dead - dead
            if wrong:
                j = min(wrong)
                return (f"attribution: rank{i} holds LIVE rank{j} in its "
                        f"dead set (false EOF from a torn-down socket)")
        for i, ri in live:
            for j, rj in live:
                if j <= i or ri.phase != RUN or rj.phase != RUN:
                    continue
                if ri.epoch != rj.epoch:
                    continue
                if ri.members != rj.members or ri.joiners != rj.joiners:
                    return (
                        f"agreement: rank{i} and rank{j} both run epoch "
                        f"{ri.epoch} with different membership "
                        f"(rank{i}: {sorted(ri.members)}+{ri.joiners} "
                        f"joiners, rank{j}: {sorted(rj.members)}"
                        f"+{rj.joiners} joiners) -- split-brain")
        return None

    def done(self, st):
        live = [r for r in st.ranks if r.alive]
        if any(r.phase != RUN for r in live):
            return False
        epochs = {r.epoch for r in live}
        if len(epochs) != 1:
            return False
        if len({(r.members, r.joiners) for r in live}) != 1:
            return False
        # A knocked joiner may legitimately wait for the next epoch,
        # but a fault must not strand mid-recovery state.
        return True
