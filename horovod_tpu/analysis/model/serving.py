"""hvdcheck model: the serving control round and its fault contract.

Abstracts ``serving/service.py``'s per-round pickled control allgather
(rank 0 frontend + decode ranks) to the decisions hardened in r18:

- two-stage outboxes: a decode rank re-sends its completion/ack
  buffers EVERY round; a buffer entry moves ``sent -> inflight`` when
  a round's allgather succeeds and is *retired* only by the NEXT
  successful round (which proves the frontend processed it). The
  frontend deduplicates, so re-sends are free — but draining a buffer
  before delivery is proven loses the only copy.
- cancel-before-adopt: a recovery can cancel a rid's possibly-admitted
  survivor copy AND re-assign the same rid in one control message;
  the decode rank must apply cancels BEFORE adopting this round's
  payload, so the stale copy dies and the fresh one lives.
- fault re-alignment: on a failed round nothing that was in flight is
  confirmed; recovery requeues every assignment that is unacked or
  whose rank died, cancels possibly-admitted survivor copies, resets
  the round counter, and keeps every outbox intact.
- evict/requeue: a decode rank may reject an assignment (pool full);
  the frontend re-queues the rid at the head of the pending line.

One round is one atomic transition (that is what an allgather is);
every interleaving of local decode completions, per-round assignment
targets, accept/reject choices and mid-round faults is explored.

Safety invariants: every request completes at most once on the
scoreboard; an *acked* assignment on a live rank is always backed by
a copy of the request somewhere on that rank (adopted, done-outbox or
inflight) — the no-lost-completion property. Liveness: every
execution can still reach all-requests-completed.

Seeded mutants (both r18 bugs):

- ``retire_on_send``: outboxes are drained when a round's payload is
  built instead of when delivery is proven; a round that faults
  mid-allgather loses the completion forever.
- ``cancel_after_adopt``: cancels are applied after payload adoption;
  a same-round cancel+reassign kills the fresh copy instead of the
  stale one.
"""

from typing import NamedTuple


class Decode(NamedTuple):
    rank: int
    adopted: frozenset    # admitted, not yet finished
    outbox: frozenset     # finished rids, re-sent until retired
    acks: frozenset       # admission acks, re-sent until delivered
    inflight: frozenset   # outbox entries sent on the last ok round


class State(NamedTuple):
    pending: tuple        # frontend's pending line (head = next)
    assigned: tuple       # sorted ((rid, rank, acked), ...)
    completed: frozenset  # the scoreboard
    compl_count: tuple    # per rid: scoreboard commits (exactly-once)
    cancel_out: frozenset  # cancels riding the next control round
    decodes: tuple        # sorted Decode per LIVE decode rank
    kills: int
    rejects: int


class ServingModel:
    """Bounded serving-round instance.

    ``mutation`` is None for the real protocol, or one of
    ``"retire_on_send"`` / ``"cancel_after_adopt"``.
    """

    def __init__(self, n_decode=2, n_requests=2, kills=1, rejects=1,
                 mutation=None):
        assert mutation in (None, "retire_on_send", "cancel_after_adopt")
        self.n_decode = n_decode
        self.n_requests = n_requests
        self.mutation = mutation
        self._kills = kills
        self._rejects = rejects
        self.name = (f"serving(decode={n_decode},requests={n_requests},"
                     f"kills={kills},rejects={rejects}"
                     + (f",mutant={mutation})" if mutation else ")"))

    def initial(self):
        yield State(
            pending=tuple(range(self.n_requests)),
            assigned=(), completed=frozenset(),
            compl_count=(0,) * self.n_requests,
            cancel_out=frozenset(),
            decodes=tuple(
                Decode(rank=d, adopted=frozenset(), outbox=frozenset(),
                       acks=frozenset(), inflight=frozenset())
                for d in range(1, self.n_decode + 1)),
            kills=self._kills, rejects=self._rejects)

    # -- transitions -----------------------------------------------------

    def actions(self, st):
        out = []

        # Local decode progress: finish an adopted request -> the
        # completion report enters the done outbox.
        for i, dec in enumerate(st.decodes):
            for rid in sorted(dec.adopted):
                decs = list(st.decodes)
                decs[i] = dec._replace(adopted=dec.adopted - {rid},
                                       outbox=dec.outbox | {rid})
                out.append((
                    f"decode{dec.rank}: finishes rid{rid} -> done outbox",
                    st._replace(decodes=tuple(decs))))

        # A successful control round, one branch per assignment choice.
        if st.pending and st.decodes:
            for dec in st.decodes:
                out.append(self._round_ok(st, target=dec.rank,
                                          reject=False))
                if st.rejects > 0:
                    out.append(self._round_ok(st, target=dec.rank,
                                              reject=True))
        else:
            out.append(self._round_ok(st, target=None, reject=False))

        # A round that faults mid-allgather: one decode rank dies, the
        # collective aborts, nobody's payload is delivered. (Rank 0
        # must survive -- service.py raises otherwise -- and at least
        # one decode rank must remain for the service to mean
        # anything, so the bounded config faults only when >= 2 decode
        # ranks are up.)
        if st.kills > 0 and len(st.decodes) >= 2:
            for victim in st.decodes:
                out.append(self._round_fault(st, victim.rank))

        return out

    def _round_ok(self, st, target, reject):
        # -- build the frontend's control payload
        cancels = st.cancel_out
        assign_rid = st.pending[0] if target is not None else None

        # -- frontend processes the gathered decode reports (it built
        # its ctl first, so this round's assignment is visible to the
        # stale-ack path, exactly as in service.py).
        assigned = {rid: (rank, acked) for rid, rank, acked in st.assigned}
        pending = list(st.pending)
        if assign_rid is not None and not reject:
            assigned[assign_rid] = (target, False)
            pending.pop(0)
        completed = set(st.completed)
        counts = list(st.compl_count)
        new_cancels = set()
        for dec in st.decodes:
            for rid in sorted(dec.acks):
                if rid in assigned and assigned[rid][0] == dec.rank:
                    assigned[rid] = (dec.rank, True)
            for rid in sorted(dec.outbox):
                if rid in completed:
                    continue   # idempotent: first completion wins
                completed.add(rid)
                counts[rid] = min(counts[rid] + 1, 2)
                if rid in assigned:
                    rank, _ = assigned.pop(rid)
                    if rank != dec.rank:
                        # duplicate guard: cancel the assigned copy
                        new_cancels.add(rid)
                if rid in pending:
                    pending.remove(rid)

        # -- decode ranks: retire, apply cancels, adopt.
        decs = []
        for dec in st.decodes:
            sent = dec.outbox
            if self.mutation == "retire_on_send":
                outbox = frozenset()          # drained at send time
                inflight = frozenset()
            else:
                # two-stage: retire what the frontend provably
                # processed (last round's inflight), promote this
                # round's send.
                outbox = sent - dec.inflight
                inflight = sent
            adopted = dec.adopted
            adopts = frozenset(
                [assign_rid] if (assign_rid is not None and not reject
                                 and target == dec.rank) else [])
            if self.mutation == "cancel_after_adopt":
                adopted = (adopted | adopts) - cancels
            else:
                adopted = (adopted - cancels) | adopts
            decs.append(dec._replace(
                adopted=adopted, outbox=outbox, inflight=inflight,
                acks=adopts))   # delivered acks cleared; fresh ack staged
        label = "round: ctl allgather ok"
        if assign_rid is not None:
            label += (f"; rid{assign_rid} -> decode{target}"
                      + (" REJECTED (pool full), stays at head"
                         if reject else ""))
        if cancels:
            label += f"; cancels={sorted(cancels)}"
        return label, st._replace(
            pending=tuple(pending),
            assigned=tuple(sorted((rid, rk, ack)
                           for rid, (rk, ack) in assigned.items())),
            completed=frozenset(completed), compl_count=tuple(counts),
            cancel_out=frozenset(new_cancels),
            decodes=tuple(decs),
            rejects=st.rejects - (1 if reject else 0))

    def _round_fault(self, st, victim):
        survivors = []
        for dec in st.decodes:
            if dec.rank == victim:
                continue
            outbox = (frozenset() if self.mutation == "retire_on_send"
                      else dec.outbox)   # real: nothing confirmed, keep
            survivors.append(dec._replace(outbox=outbox,
                                          inflight=frozenset()))
        alive = {d.rank for d in survivors}
        # frontend recovery: requeue anything unacked or on the dead
        # rank; cancel possibly-admitted survivor copies.
        assigned = []
        requeue = []
        cancels = set(st.cancel_out)
        for rid, rank, acked in st.assigned:
            if rank not in alive or not acked:
                requeue.append(rid)
                if rank in alive:
                    cancels.add(rid)
            else:
                assigned.append((rid, rank, acked))
        pending = tuple(sorted(requeue)) + st.pending
        return (f"round: decode{victim} dies mid-allgather -> recovery "
                f"(requeue={sorted(requeue)})",
                st._replace(pending=pending, assigned=tuple(assigned),
                            cancel_out=frozenset(cancels),
                            decodes=tuple(survivors),
                            kills=st.kills - 1))

    # -- properties ------------------------------------------------------

    def invariant(self, st):
        for rid, n in enumerate(st.compl_count):
            if n > 1:
                return (f"exactly-once: rid{rid} committed to the "
                        f"scoreboard {n} times")
        for rid, rank, acked in st.assigned:
            if not acked:
                continue
            dec = next((d for d in st.decodes if d.rank == rank), None)
            if dec is None:
                continue   # dead rank: recovery will requeue
            if rid not in dec.adopted | dec.outbox | dec.inflight:
                return (f"no-lost-completion: rid{rid} is acked on live "
                        f"decode{rank} but no copy exists there "
                        f"(not adopted, not in the done outbox, not "
                        f"inflight) -- it can never complete")
        return None

    def done(self, st):
        return len(st.completed) == self.n_requests
