"""hvdcheck — exhaustive protocol model checking for the control planes.

hvdlint (checks C1–C8) statically covers the jaxpr/SPMD lane; this
package covers the other place the hard bugs live: the distributed
control protocols. Each protocol family ships as a small transition
system over hashable states, and :mod:`checker` explores EVERY
interleaving of a bounded configuration — local steps, message
orderings, and injected faults — checking safety invariants, deadlock
freedom, and done-reachability, with the shortest counterexample
interleaving printed on failure.

Families (one module each, real protocol + seeded mutants):

- :mod:`elastic` — epoch-fenced re-formation, fault attribution, the
  keep-old-sockets-open rule, the parole door.
- :mod:`wire` — striped CRC/NAK/DONE chunk streams, verify-before-
  reduce, NAK-exhaustion escalation, reader-stops-at-slot-satisfied.
- :mod:`serving` — the control-round allgather, two-stage outboxes,
  cancel-before-adopt, evict/requeue, fault re-alignment.

The **seeded mutants** re-introduce this repo's historical protocol
bugs (the same discipline test_analysis_lint.py applies to C1–C8):
``make model-check`` fails unless hvdcheck both passes every real
model AND catches every mutant with a counterexample trace.

:mod:`abi` adds the drift guards pinning the Python twin tables
(reqtrace phases, basics knob/phase/mode tables, chaos-grammar
constants, the models' vocabularies) bit-for-bit against the C
sources.

Entry points: ``python -m horovod_tpu.analysis.model --all`` /
``make model-check``; docs/analysis.md ("hvdcheck") is the manual.
"""

from horovod_tpu.analysis.model.checker import (  # noqa: F401
    CheckResult, Violation, check, format_trace, replay)
from horovod_tpu.analysis.model.elastic import ElasticModel
from horovod_tpu.analysis.model.serving import ServingModel
from horovod_tpu.analysis.model.wire import WireModel

# Bounded wire configs: A exercises striping + NAK + escalation, B the
# back-to-back-transfer slot handoff (the r14 window).
_WIRE_A = dict(n_chunks=3, channels=2, transfers=1, corrupts=2, retries=0)
_WIRE_B = dict(n_chunks=2, channels=1, transfers=2, corrupts=0)


def real_models():
    """The bounded real-protocol instances ``--all`` checks."""
    return [
        ElasticModel(n_ranks=3, kills=1, knocks=1),
        WireModel(**_WIRE_A),
        WireModel(**_WIRE_B),
        ServingModel(n_decode=2, n_requests=2, kills=1, rejects=1),
    ]


# name -> (model factory, the historical bug it re-introduces). Every
# entry must be CAUGHT (checker returns a violation) for model-check
# to pass.
MUTANTS = {
    "elastic.parole_refreeze": (
        lambda: ElasticModel(mutation="parole_refreeze"),
        "r14: release() popped the frozen snapshot; a survivor polling "
        "after release re-froze an empty pending set -> split-brain "
        "world size"),
    "elastic.early_socket_close": (
        lambda: ElasticModel(mutation="early_socket_close"),
        "r12: survivor tore down old-ring sockets at its own commit; "
        "a slower survivor's probe read false EOF -> live rank marked "
        "certain-dead"),
    "wire.reduce_before_verify": (
        lambda: WireModel(**_WIRE_A, mutation="reduce_before_verify"),
        "wire contract: payload handed to ReduceInto before its CRC "
        "verified -> corrupt data in the accumulator"),
    "wire.read_past_slot": (
        lambda: WireModel(**_WIRE_B, mutation="read_past_slot"),
        "r14: reader kept draining after its slot was satisfied; the "
        "next transfer's first frame was misfiled as a duplicate -> "
        "transfer never completes"),
    "serving.retire_on_send": (
        lambda: ServingModel(mutation="retire_on_send"),
        "r18: done outbox drained when the round's payload was built, "
        "not when delivery was proven; a mid-allgather fault lost the "
        "only copy of a completion"),
    "serving.cancel_after_adopt": (
        lambda: ServingModel(mutation="cancel_after_adopt"),
        "r18: cancels applied after payload adoption; a same-round "
        "cancel+reassign dropped the fresh copy instead of the stale "
        "one"),
}
