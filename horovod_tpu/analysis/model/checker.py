"""hvdcheck's explicit-state engine: exhaustive BFS over interleavings.

A *model* is a small transition system over hashable states (nested
tuples / NamedTuples). The engine enumerates EVERY reachable state of
a bounded configuration — every interleaving of local steps, message
deliveries, and injected faults — and checks three properties:

safety
    ``model.invariant(state)`` returns a violation message (or None)
    for each reachable state. One violated state = one counterexample.
deadlock-freedom
    a reachable state with no enabled actions that is not ``done`` is
    a deadlock (the distributed system is wedged: e.g. a receiver
    waiting on a frame the sender already consumed).
liveness (reform/done reachability)
    every reachable state must be able to reach a ``done`` state.
    Computed by reverse reachability over the explored graph: any
    reachable state outside the backward-closure of the done set is a
    livelock — the execution can still take steps forever, but
    completion has become unreachable (e.g. a completion report
    drained from its outbox before delivery can never be re-sent).

Counterexamples are the point. Every violation carries the exact
interleaving that produced it — the shortest one, since the search is
breadth-first — as a list of action labels, printable with
:func:`format_trace` as the numbered schedule a human (or the next
protocol PR's author) can replay against the real code.

Model protocol (duck-typed)::

    model.name        -> str
    model.initial()   -> iterable of initial states
    model.actions(s)  -> iterable of (label, next_state)
    model.invariant(s)-> None | violation message
    model.done(s)     -> bool

Determinism matters: ``actions`` must be a pure function of the state
(all nondeterminism — scheduling, faults, message orderings — is
expressed as multiple actions), which is what makes the search
exhaustive and the traces replayable (see :func:`replay`).
"""

import dataclasses
from collections import deque


@dataclasses.dataclass(frozen=True)
class Violation:
    kind: str          # "invariant" | "deadlock" | "livelock"
    message: str
    trace: tuple       # action labels, initial state -> violating state

    def format(self):
        return (f"{self.kind}: {self.message}\n"
                + format_trace(self.trace))


@dataclasses.dataclass(frozen=True)
class CheckResult:
    model: str
    ok: bool
    states: int
    transitions: int
    violation: object = None   # Violation | None

    def format(self):
        head = (f"{self.model}: {self.states} states, "
                f"{self.transitions} transitions")
        if self.ok:
            return f"{head} -- OK"
        return f"{head} -- FAIL\n{self.violation.format()}"


def format_trace(trace):
    """Render a counterexample as a numbered interleaving."""
    if not trace:
        return "  (violated in an initial state)"
    width = len(str(len(trace)))
    return "\n".join(f"  #{i + 1:<{width}} {label}"
                     for i, label in enumerate(trace))


def _trace_to(state, parents):
    labels = []
    while True:
        entry = parents[state]
        if entry is None:
            break
        state, label = entry
        labels.append(label)
    labels.reverse()
    return tuple(labels)


def check(model, max_states=2_000_000):
    """Exhaustively check ``model``; returns a :class:`CheckResult`.

    Raises ``RuntimeError`` if the reachable space exceeds
    ``max_states`` — bounded configs are part of a model's contract
    (ISSUE: keep ``make model-check`` in the seconds).
    """
    parents = {}     # state -> None | (pred_state, label)
    edges = {}       # state -> tuple of successor states
    queue = deque()
    n_transitions = 0

    def fail(kind, message, state):
        return CheckResult(
            model=model.name, ok=False, states=len(parents),
            transitions=n_transitions,
            violation=Violation(kind=kind, message=message,
                                trace=_trace_to(state, parents)))

    for s0 in model.initial():
        if s0 not in parents:
            parents[s0] = None
            queue.append(s0)

    while queue:
        state = queue.popleft()
        msg = model.invariant(state)
        if msg:
            return fail("invariant", msg, state)
        succs = []
        for label, nxt in model.actions(state):
            n_transitions += 1
            succs.append(nxt)
            if nxt not in parents:
                if len(parents) >= max_states:
                    raise RuntimeError(
                        f"{model.name}: state space exceeds "
                        f"{max_states} states -- tighten the config")
                parents[nxt] = (state, label)
                queue.append(nxt)
        edges[state] = tuple(succs)
        if not succs and not model.done(state):
            return fail(
                "deadlock",
                "no enabled actions in a non-terminal state", state)

    # Liveness: reverse reachability from the done set.
    rev = {s: [] for s in parents}
    for state, succs in edges.items():
        for nxt in succs:
            rev[nxt].append(state)
    can_finish = set()
    stack = [s for s in parents if model.done(s)]
    can_finish.update(stack)
    while stack:
        for pred in rev[stack.pop()]:
            if pred not in can_finish:
                can_finish.add(pred)
                stack.append(pred)
    for state in parents:
        if state not in can_finish:
            return fail(
                "livelock",
                "completion is unreachable from this state "
                "(no continuation reaches a done state)", state)

    return CheckResult(model=model.name, ok=True, states=len(parents),
                       transitions=n_transitions)


def replay(model, trace):
    """Re-execute a counterexample trace label-by-label.

    Returns the state reached. Raises ``AssertionError`` if any label
    is not enabled where the trace claims it is — the test suite uses
    this to prove printed counterexamples are real executions, not
    artifacts of the search.
    """
    states = list(model.initial())
    assert states, f"{model.name}: no initial states"
    state = states[0]
    for step, wanted in enumerate(trace):
        for label, nxt in model.actions(state):
            if label == wanted:
                state = nxt
                break
        else:
            raise AssertionError(
                f"{model.name}: step #{step + 1} {wanted!r} "
                f"not enabled in replayed state")
    return state
