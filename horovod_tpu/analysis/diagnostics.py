"""Diagnostic objects for hvdlint (the static SPMD analyzer).

A :class:`Diagnostic` is one finding against one program location. The
check ids are stable API (tests assert them, allowlists name them):

- **C1** collective-divergence — cond/switch branches whose collective
  sequences differ (the classic SPMD deadlock shape; Horovod catches
  this class at RUNTIME via the controller's negotiation — see
  csrc/controller.cc — hvdlint catches it before launch).
- **C2** axis validity — a collective over an axis name absent from the
  declared mesh.
- **C3** width waste — an fp32 reduction whose operand was upcast from
  a sub-fp32 dtype and whose result is consumed at fp32 (the wire
  carries 2x the bytes the data has; see docs/analysis.md for the
  EQuARX/compressed-lane connection). The f32-accumulate ROUNDTRIP
  (bf16 -> f32 -> psum -> bf16) is deliberately exempt.
- **C4** donation hazard — a donated invar that no eqn consumes, or
  more donated buffers of a (shape, dtype) class than outputs that
  could alias them (XLA's "Some donated buffers were not usable"
  warning-class, promoted to a pre-commit error).
- **C5** schedule conformance — a pipeline program whose traced
  ppermute/psum sequence deviates from the host-built schedule table's
  prediction.
- **C6** shard-collective pairing — a reduce-scatter with no matching
  allgather on the same axis (the ZeRO apply invariant: scatter grads,
  update shards, gather params — docs/zero.md); unpaired scatters
  leave state silently sharded under replicated-semantics consumers.
- **C7** collective interleaving — every scatter-family collective in
  a compute-bearing program sits after the flop tail (bunched after
  the backward instead of interleaved with it), so no remaining
  compute can hide the wire time (docs/fusion.md: the static twin of
  the eager lane's overlap ledger; ``parallel.fusion``'s reorder pass
  is the fix, ``HOROVOD_JIT_FUSION=0`` the deliberate opt-out).
- **C8** rank-divergent trip count — a collective inside a
  ``while_loop`` whose cond derives (transitively, through the carry)
  from ``lax.axis_index``: ranks run different iteration counts, so
  the extra iterations' collectives rendezvous with nothing — the
  cross-iteration deadlock C1's per-branch analysis cannot see.
"""

import dataclasses

ERROR = "error"
WARNING = "warning"

#: check id -> default severity
SEVERITIES = {
    "C1": ERROR,
    "C2": ERROR,
    "C3": WARNING,
    "C4": ERROR,
    "C5": ERROR,
    "C6": ERROR,
    "C7": ERROR,
    "C8": ERROR,
}


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One hvdlint finding.

    ``path`` is the structural location inside the traced program
    (e.g. ``"pjit:apply_fn"`` or ``"scan/cond"``); ``source`` is the
    user ``file:line`` jax recorded for the offending equation when
    available.
    """

    id: str              # "C1".."C8"
    severity: str        # ERROR or WARNING
    path: str            # structural jaxpr path
    message: str         # what is wrong
    hint: str = ""       # how to fix it
    source: str = ""     # user file:line (best effort)

    def format(self):
        loc = self.path or "<program>"
        src = f" [{self.source}]" if self.source else ""
        hint = f"\n    hint: {self.hint}" if self.hint else ""
        return f"{self.id} {self.severity}: {loc}{src}: {self.message}{hint}"


def make(check_id, path, message, hint="", source="", severity=None):
    """Build a Diagnostic with the check's default severity."""
    return Diagnostic(
        id=check_id,
        severity=severity or SEVERITIES[check_id],
        path=path,
        message=message,
        hint=hint,
        source=source,
    )


def filter_allowed(diags, allow=()):
    """Drop diagnostics named by ``allow`` (check ids, e.g. ``("C3",)``,
    or exact ``"C3:path"`` pairs — the allowlist mechanism documented in
    docs/analysis.md)."""
    allow = frozenset(allow)
    return [d for d in diags
            if d.id not in allow and f"{d.id}:{d.path}" not in allow]


def errors(diags):
    """The error-severity subset (what CI gates on)."""
    return [d for d in diags if d.severity == ERROR]
