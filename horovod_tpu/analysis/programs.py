"""Registry of the repo's shipped jitted programs, lint-ready.

Every program the training stack jits — the monolithic and split llama
train steps, both fused optimizer applies, and all three pipeline
schedule engines — is buildable here with abstract inputs, so the CLI
(``python -m horovod_tpu.analysis.lint --all``), ``make lint``,
``bench.py --lint``, and the pytest fixture all lint the SAME set.
Adding a program here is how a future subsystem buys pre-launch
collective-consistency checking for free.

Pipeline programs are linted at the per-device ``inner`` level (built
by ``parallel.pipeline.build_pipeline_inner`` from the same
``models.llama`` stage/loss programs the engines run) with the
host-schedule prediction attached — no mesh, devices, or shard_map
required, which is what keeps the full check suite running on the
jax 0.4.x CPU boxes that execute the schedules under vmap emulation.
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp

from horovod_tpu.analysis.api import lint

# Pipeline lint geometry: S stages x V virtual chunks x M microbatches.
_S, _V, _M = 2, 2, 4
_BATCH, _SEQ = 4, 8


@dataclasses.dataclass
class LintSpec:
    """One program plus everything ``lint`` needs to analyze it."""

    fn: object
    args: tuple
    mesh: object = None
    axis_env: object = None
    expect_collectives: object = None
    donate_argnums: tuple = ()

    def run(self, allow=()):
        return lint(self.fn, self.args, mesh=self.mesh,
                    axis_env=self.axis_env,
                    donate_argnums=self.donate_argnums,
                    expect_collectives=self.expect_collectives,
                    allow=allow)


def _config(name):
    from horovod_tpu.models.llama import LlamaConfig

    # n_layers=4 so the layer stack divides into S*V=4 pipeline chunks.
    presets = {
        "tiny": lambda: LlamaConfig.tiny(n_layers=4),
        "tiny_moe": lambda: LlamaConfig.tiny_moe(n_layers=4),
    }
    if name not in presets:
        raise ValueError(f"unknown config {name!r}: expected one of "
                         f"{sorted(presets)}")
    return presets[name]()


def _abstract_params(cfg):
    from horovod_tpu.models.llama import llama_init

    return jax.eval_shape(
        lambda: llama_init(cfg, jax.random.PRNGKey(0)))


def _abstract_batch():
    tok = jax.ShapeDtypeStruct((_BATCH, _SEQ), jnp.int32)
    return {"tokens": tok, "targets": tok,
            "mask": jax.ShapeDtypeStruct((_BATCH, _SEQ), jnp.float32)}


def _mesh():
    """A trivial mesh over whatever devices exist: lint only needs the
    axis NAMES declared; every axis can be size 1."""
    from horovod_tpu.parallel.mesh import create_mesh

    return create_mesh()


def _loss_fn(cfg, mesh):
    from horovod_tpu.models.llama import llama_loss

    return functools.partial(llama_loss, config=cfg, mesh=mesh)


def _monolithic(config):
    cfg = _config(config)
    mesh = _mesh()
    loss = _loss_fn(cfg, mesh)
    step = jax.jit(lambda p, b: jax.value_and_grad(loss)(p, b))
    return LintSpec(fn=step, args=(_abstract_params(cfg),
                                   _abstract_batch()), mesh=mesh)


def _split(config, optimizer_name):
    import optax

    from horovod_tpu.parallel.precision import (
        fused_adam,
        fused_master_adam,
    )
    from horovod_tpu.parallel.train_step import make_split_train_step

    cfg = _config(config)
    mesh = _mesh()
    optimizer = {
        "adam": lambda: optax.adam(1e-3),
        "fused_adam": lambda: fused_adam(1e-3),
        "fused_master_adam": lambda: fused_master_adam(1e-3),
    }[optimizer_name]()
    ts = make_split_train_step(_loss_fn(cfg, mesh), optimizer,
                               microbatches=2)
    carry = jax.eval_shape(ts.init, _abstract_params(cfg))
    return LintSpec(fn=ts.step, args=(carry, _abstract_batch()),
                    mesh=mesh)


def _split_telemetry(config):
    """The telemetry-instrumented split step: identical jitted programs
    with a StepTimer wrapped around them. Registered so ``make lint``
    proves the host-side instrumentation never perturbs the traced
    collective signature (the StepTimer lives entirely outside jit)."""
    import optax

    from horovod_tpu.parallel.train_step import make_split_train_step
    from horovod_tpu.telemetry import StepTimer

    cfg = _config(config)
    mesh = _mesh()
    # flops preset: lint traces with abstract args, so the first-call
    # cost-analysis registration must not trigger (it lowers programs).
    timer = StepTimer(flops_per_step=1.0, block=False)
    ts = make_split_train_step(_loss_fn(cfg, mesh), optax.adam(1e-3),
                               microbatches=2, telemetry=timer)
    carry = jax.eval_shape(ts.init, _abstract_params(cfg))
    return LintSpec(fn=ts.step, args=(carry, _abstract_batch()),
                    mesh=mesh)


_ZERO_SHARDS = 4


def _split_zero(config):
    """The ZeRO-1 split step (``make_split_train_step(zero=...)``),
    traced end-to-end under the vmap emulation: proves the restructured
    step traces cleanly and that its apply program's donations (full
    params + sharded opt state) alias 1:1 (C4). The vmap emulation
    lowers the named-axis collectives away at trace time, so the REAL
    collective signature is linted separately via
    ``zero1_shard_apply``."""
    from horovod_tpu.parallel.precision import fused_adam
    from horovod_tpu.parallel.train_step import make_split_train_step
    from horovod_tpu.parallel.zero import ZeroConfig

    cfg = _config(config)
    mesh = _mesh()
    ts = make_split_train_step(
        _loss_fn(cfg, mesh), fused_adam(1e-3), microbatches=2,
        zero=ZeroConfig(axis="data", size=_ZERO_SHARDS,
                        bucket_bytes=1 << 20))
    carry = jax.eval_shape(ts.init, _abstract_params(cfg))
    return LintSpec(fn=ts.step, args=(carry, _abstract_batch()),
                    mesh=mesh)


def _zero_shard_apply(config):
    """The per-rank ZeRO apply program at the llama geometry, traced
    with ``axis_env`` exactly like the pipeline inners — psum_scatter /
    all_gather stay visible to the walker, so C2 (axis validity), C3
    (width), and C6 (every reduce-scatter pairs with an allgather on
    the same axis) run against the program the TPU lanes execute."""
    from horovod_tpu.parallel.ops import predicted_zero_collectives
    from horovod_tpu.parallel.precision import fused_adam
    from horovod_tpu.parallel.zero import (
        ZeroAdamState,
        build_zero_apply_inner,
        zero_bucket_layout,
    )

    cfg = _config(config)
    params = _abstract_params(cfg)
    leaves, _ = jax.tree.flatten(params)
    layout = zero_bucket_layout(leaves, _ZERO_SHARDS, 1 << 20)
    inner = build_zero_apply_inner(fused_adam(1e-3).hyper, layout,
                                   "data", _ZERO_SHARDS)
    flat = tuple(jax.ShapeDtypeStruct((b.padded,), b.dtype)
                 for b in layout.buckets)
    shard = tuple(
        jax.ShapeDtypeStruct((b.shard_elems(_ZERO_SHARDS),), b.dtype)
        for b in layout.buckets)
    opt = ZeroAdamState(
        count=jax.ShapeDtypeStruct((1,), jnp.int32),
        mu=shard, nu=shard)
    return LintSpec(fn=inner, args=(flat, flat, opt),
                    axis_env=[("data", _ZERO_SHARDS)],
                    expect_collectives=predicted_zero_collectives(
                        len(layout.buckets), "data"))


_HIER_INTRA, _HIER_INTER = 2, 2


def _hier_allreduce(config):
    """The composed-plane allreduce (``parallel.ops.hier_allreduce``):
    reduce-scatter over the intra (ICI) axis, psum of the 1/L shard
    over the inter (DCN) axis, allgather back — traced with BOTH axes
    in the env so C2 validates the composed axes and C5 pins the plane
    sequence against ``predicted_hier_collectives`` (the same
    three-step table csrc's HierarchicalAllreduce executes)."""
    del config
    from horovod_tpu.parallel.ops import (
        hier_allreduce,
        predicted_hier_collectives,
    )

    def fn(x):
        return hier_allreduce(x, "intra", "inter")

    x = jax.ShapeDtypeStruct((8 * _HIER_INTRA, 4), jnp.float32)
    return LintSpec(
        fn=fn, args=(x,),
        axis_env=[("intra", _HIER_INTRA), ("inter", _HIER_INTER)],
        expect_collectives=predicted_hier_collectives("intra", "inter"))


def _zero_shard_apply_hier(config):
    """The cross-plane ZeRO apply (``ZeroConfig(inter_axis=...)``): the
    RS/AG pair rides the intra axis while the 1/N gradient shard psums
    over the inter axis between them. C6 must still see every
    reduce-scatter paired with a same-axis allgather (the interleaved
    cross-plane psum sits between, which order-based counting
    tolerates), and C2 validates both axes."""
    from horovod_tpu.parallel.ops import predicted_zero_collectives
    from horovod_tpu.parallel.precision import fused_adam
    from horovod_tpu.parallel.zero import (
        ZeroAdamState,
        build_zero_apply_inner,
        zero_bucket_layout,
    )

    cfg = _config(config)
    params = _abstract_params(cfg)
    leaves, _ = jax.tree.flatten(params)
    layout = zero_bucket_layout(leaves, _ZERO_SHARDS, 1 << 20)
    inner = build_zero_apply_inner(
        fused_adam(1e-3).hyper, layout, "data", _ZERO_SHARDS,
        inter_axis="cross", inter_size=_HIER_INTER)
    flat = tuple(jax.ShapeDtypeStruct((b.padded,), b.dtype)
                 for b in layout.buckets)
    shard = tuple(
        jax.ShapeDtypeStruct((b.shard_elems(_ZERO_SHARDS),), b.dtype)
        for b in layout.buckets)
    opt = ZeroAdamState(
        count=jax.ShapeDtypeStruct((1,), jnp.int32),
        mu=shard, nu=shard)
    return LintSpec(fn=inner, args=(flat, flat, opt),
                    axis_env=[("data", _ZERO_SHARDS),
                              ("cross", _HIER_INTER)],
                    expect_collectives=predicted_zero_collectives(
                        len(layout.buckets), "data", inter_axis="cross"))


def _zero_fused_step(config):
    """The fused one-program ZeRO-1 step AFTER
    ``parallel.fusion.interleave_collectives`` reschedules it: the
    per-member grad+apply program is traced once with ``axis_env`` (so
    the per-bucket reduce-scatter / all-gather chains stay visible),
    reordered, then replayed through ``jaxpr_as_fun`` — the lint walker
    sees exactly the equation order the jit lane hands XLA. C7 proves
    the scatters sit interleaved with the backward dot_generals rather
    than bunched at the tail, and C6 still pairs every scatter with its
    same-axis allgather."""
    from horovod_tpu.parallel.fusion import (
        _jcore,
        fused_zero_inner,
        interleave_collectives,
    )
    from horovod_tpu.parallel.precision import fused_adam
    from horovod_tpu.parallel.zero import (
        _optimizer_hyper,
        zero_bucket_layout,
        zero_state_init,
    )

    cfg = _config(config)
    params = _abstract_params(cfg)
    leaves, treedef = jax.tree.flatten(params)
    # Small buckets so the tiny config splits into MANY of them — C7's
    # interleaving verdict is only meaningful with multiple scatters
    # (one bucket has nothing to interleave with and gates the check).
    layout = zero_bucket_layout(leaves, _ZERO_SHARDS, 1 << 15)
    hyper = _optimizer_hyper(fused_adam(1e-3))
    _, opt = jax.eval_shape(
        lambda p: zero_state_init(hyper, layout, p, _ZERO_SHARDS),
        params)
    inner, example, _, env = fused_zero_inner(
        _loss_fn(cfg, None), params, _abstract_batch(), opt, hyper,
        layout, treedef, "data", _ZERO_SHARDS)
    closed = jax.make_jaxpr(inner, axis_env=env)(*example)
    fn = _jcore.jaxpr_as_fun(interleave_collectives(closed))
    return LintSpec(fn=fn, args=tuple(example), axis_env=env)


def _redistribute_to_replicated(config):
    """The registered redistribute program for the sharded->replicated
    plan: the in-graph equivalent of one allgatherv, with C5's expected
    sequence taken from the PLAN itself
    (``ReshardPlan.expected_collectives``) — a plan edit that changes
    the collective mix without this program following along (or vice
    versa) fails lint before it ships."""
    del config
    from jax import lax

    from horovod_tpu.parallel.reshard import Layout, plan_redistribute

    shards, rows = 4, 16
    plan = plan_redistribute((rows, 4), jnp.float32,
                             Layout.sharded(rows, shards),
                             Layout.replicated(shards))

    def fn(x):
        return lax.all_gather(x, "shard", axis=0, tiled=True)

    x = jax.ShapeDtypeStruct((rows // shards, 4), jnp.float32)
    return LintSpec(fn=fn, args=(x,), axis_env=[("shard", shards)],
                    expect_collectives=plan.expected_collectives("shard"))


def _pipeline(config, schedule):
    from horovod_tpu.models.llama import llama_pipeline_programs
    from horovod_tpu.parallel.pipeline import (
        build_pipeline_inner,
        predicted_collectives,
    )

    cfg = _config(config)
    stage_fn, loss_fn, aux_ct = llama_pipeline_programs(
        cfg, mesh=None, microbatches=_M, denom=float(_BATCH * _SEQ))
    inner = build_pipeline_inner(schedule, stage_fn, loss_fn, S=_S,
                                 M=_M, num_virtual=_V,
                                 aux_cotangent=aux_ct)
    expect = predicted_collectives(schedule, S=_S, M=_M,
                                   num_virtual=_V, n_head_leaves=2)

    params = _abstract_params(cfg)
    layers = params["layers"]
    # Per-device stage block: leading stacked-layer axis / S (the
    # interleaved engine holds the same total as V chunks of L/(S*V)).
    sp = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(
            (l.shape[0] // _S,) + l.shape[1:], l.dtype), layers)
    mb = _BATCH // _M
    d = cfg.d_model
    xs = jax.ShapeDtypeStruct((_M, mb, _SEQ, d), cfg.compute_dtype)
    if schedule == "gpipe":
        return LintSpec(fn=inner, args=(sp, xs),
                        axis_env=[("pipe", _S)],
                        expect_collectives=expect)
    hp = (params["final_norm"], params["lm_head"])
    largs = (jax.ShapeDtypeStruct((_M, mb, _SEQ), jnp.int32),
             jax.ShapeDtypeStruct((_M, mb, _SEQ), jnp.float32))
    return LintSpec(fn=inner, args=(sp, hp, xs, largs),
                    axis_env=[("pipe", _S)], expect_collectives=expect)


def _ring_attention(config):
    """The sequence-parallel exact-attention ring
    (``parallel.ring_attention``, XLA blockwise path): n-1 ppermute
    hops of the K/V shards around the ``sp`` axis with a
    rank-dependent causal mask per step. Exercises the walkers C8
    leans on — rank-tainted VALUES (``lax.axis_index`` feeds the mask)
    inside rank-INVARIANT control flow must stay quiet."""
    del config
    from horovod_tpu.parallel.ring_attention import ring_attention

    def fn(q, k, v):
        return ring_attention(q, k, v, "sp", causal=True, use_flash=False)

    # GQA geometry: 4 query heads over 2 KV heads, bf16 activations.
    q = jax.ShapeDtypeStruct((2, 8, 4, 8), jnp.bfloat16)
    kv = jax.ShapeDtypeStruct((2, 8, 2, 8), jnp.bfloat16)
    return LintSpec(fn=fn, args=(q, kv, kv), axis_env=[("sp", 2)])


_REGISTRY = {
    "llama_train_step": _monolithic,
    "llama_train_step_split":
        functools.partial(_split, optimizer_name="adam"),
    "llama_train_step_split_fused_adam":
        functools.partial(_split, optimizer_name="fused_adam"),
    "llama_train_step_split_fused_master_adam":
        functools.partial(_split, optimizer_name="fused_master_adam"),
    "llama_train_step_split_telemetry": _split_telemetry,
    "llama_train_step_split_zero1": _split_zero,
    "zero1_shard_apply": _zero_shard_apply,
    "zero1_shard_apply_hier": _zero_shard_apply_hier,
    "zero1_fused_step": _zero_fused_step,
    "hier_allreduce": _hier_allreduce,
    "redistribute_to_replicated": _redistribute_to_replicated,
    "pipeline_gpipe":
        functools.partial(_pipeline, schedule="gpipe"),
    "pipeline_1f1b":
        functools.partial(_pipeline, schedule="1f1b"),
    "pipeline_interleaved_1f1b":
        functools.partial(_pipeline, schedule="interleaved_1f1b"),
    "ring_attention_sp": _ring_attention,
}


def program_names():
    return sorted(_REGISTRY)


def build_program(name, config="tiny"):
    """Build a registered program's :class:`LintSpec`."""
    if name not in _REGISTRY:
        raise ValueError(f"unknown program {name!r}: expected one of "
                         f"{program_names()}")
    return _REGISTRY[name](config)


def lint_program(name, config="tiny", allow=()):
    """Build and lint one registered program."""
    return build_program(name, config).run(allow=allow)


def lint_all(config="tiny", allow=()):
    """Lint every registered program; returns ``{name: [Diagnostic]}``."""
    return {name: lint_program(name, config, allow)
            for name in program_names()}
