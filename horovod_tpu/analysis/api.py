"""hvdlint library API (the CLI lives in ``analysis/lint.py``).

::

    from horovod_tpu import analysis
    diags = analysis.lint(step_fn, (carry, batch), mesh=mesh)
    assert not analysis.errors(diags)

The analyzer traces with ``jax.make_jaxpr(fn, axis_env=...)`` so
collective axis names bind WITHOUT shard_map or real devices — the same
code path works on jax 0.4.x CPU boxes (where the pipeline schedules
run under vmap emulation) and on the jax>=0.6 TPU substrate.
"""

import re

import jax

from horovod_tpu.analysis import checks
from horovod_tpu.analysis import diagnostics as D
from horovod_tpu.analysis.extract import extract

_UNBOUND_RE = re.compile(r"unbound axis name:?\s*([\w./-]+)")

#: how many distinct undeclared axis names one trace may reveal before
#: we give up retrying (each retry binds one more name)
_MAX_UNDECLARED = 8


def _axis_env_from_mesh(mesh):
    if mesh is None:
        return []
    return [(str(name), int(size))
            for name, size in dict(mesh.shape).items()]


def _trace(fn, args, kwargs, axis_env):
    """Trace ``fn`` to a ClosedJaxpr, auto-binding undeclared axis
    names (size 1) so C2 can report them with a real location instead
    of dying on jax's trace-time NameError. Returns
    ``(closed_jaxpr, undeclared_names, trace_error)``."""
    env = list(axis_env)
    undeclared = []
    for _ in range(_MAX_UNDECLARED + 1):
        try:
            closed = jax.make_jaxpr(
                lambda *a: fn(*a, **kwargs) if kwargs else fn(*a),
                axis_env=env)(*args)
            return closed, undeclared, None
        except NameError as e:
            m = _UNBOUND_RE.search(str(e))
            if not m:
                return None, undeclared, e
            name = m.group(1)
            if name in (n for n, _ in env):
                return None, undeclared, e
            undeclared.append(name)
            env.append((name, 1))
    return None, undeclared, None


def lint(fn, args=(), kwargs=None, *, mesh=None, axis_env=None,
         donate_argnums=(), expect_collectives=None, allow=()):
    """Statically analyze one program for SPMD collective-consistency.

    ``fn`` is any function the repo jits (the train step, a pipeline
    engine's inner program, an optimizer apply...); ``args`` are real
    arrays or ``jax.ShapeDtypeStruct`` placeholders. ``mesh`` declares
    the valid collective axes (or pass ``axis_env`` as
    ``[(name, size), ...]`` to lint a manual per-device program such as
    a pipeline inner without building a mesh). ``donate_argnums``
    applies check C4 to ``fn``'s own top-level arguments; donations
    inside jitted sub-programs are discovered automatically from their
    pjit equations. ``expect_collectives`` (from
    ``parallel.pipeline.predicted_collectives``) enables check C5.
    ``allow`` suppresses diagnostics by id (``"C3"``) or id:path.

    Returns a list of :class:`~horovod_tpu.analysis.diagnostics.Diagnostic`.
    """
    kwargs = dict(kwargs or {})
    env = list(axis_env) if axis_env is not None \
        else _axis_env_from_mesh(mesh)
    declared = [n for n, _ in env]

    closed, undeclared, err = _trace(fn, args, kwargs, env)
    if closed is None:
        diags = [D.make(
            "C2", "<trace>",
            f"program could not be traced: {err}",
            hint="collectives reference axis names the mesh does not "
                 "declare")]
        return D.filter_allowed(diags, allow)

    ex = extract(closed)
    if donate_argnums:
        _add_top_level_donation(ex, closed, fn, args, donate_argnums)

    ctx = {
        # When the caller declared no axes at all, C2 has no ground
        # truth — skip it rather than flagging everything. Auto-bound
        # undeclared names stay OUT of the declared set so the
        # collectives that referenced them are flagged with their
        # real location.
        "mesh_axes": declared if (declared or undeclared) else None,
        "expect_collectives": expect_collectives,
    }
    diags = checks.run_all(ex, ctx)
    return D.filter_allowed(diags, allow)


def _add_top_level_donation(ex, closed, fn, args, donate_argnums):
    """Model explicit donate_argnums on a non-jitted ``fn`` as a
    donation site over the top-level jaxpr (C4 handles the rest)."""
    from horovod_tpu.analysis.extract import DonationSite

    flags = []
    for i, a in enumerate(args):
        n = len(jax.tree.leaves(a))
        flags.extend([i in set(donate_argnums)] * n)
    jaxpr = closed.jaxpr
    if len(flags) != len(jaxpr.invars):
        # kwargs or non-pytree args shifted the flat arity; refuse to
        # guess rather than misattribute donation.
        return
    ex.donation_sites.append(DonationSite(
        name=getattr(fn, "__name__", "<fn>"),
        path="<top>", source="", jaxpr=closed, donated=tuple(flags)))


def errors(diags):
    """Error-severity subset (what CI gates on)."""
    return D.errors(diags)
