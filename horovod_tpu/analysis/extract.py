"""Jaxpr walking: turn any traced program into a collective signature.

The walker recursively descends every sub-jaxpr jax can produce —
``pjit`` bodies, ``scan``/``while`` loops, ``cond``/``switch`` branches,
``remat``/``checkpoint`` wrappers, custom-vjp calls — and records every
cross-device collective as a :class:`Collective` in program order,
preserving control-flow structure (:class:`Loop`, :class:`Branches`)
so the checks can reason per path. Alongside, it tracks:

- **rank taint**: which values derive (transitively) from
  ``lax.axis_index`` — a branch predicate tainted this way is
  device-varying, so differing branch signatures are a GUARANTEED
  cross-rank divergence, not just a possible one;
- **width provenance**: whether a reduction's operand was upcast from a
  sub-fp32 dtype, and whether its result is immediately cast back down
  (the deliberate f32-accumulate roundtrip) — check C3's raw material;
- **donation sites**: every ``pjit`` equation carrying donated invars,
  with its body jaxpr — check C4's raw material;
- **compute/collective profile**: a flattened program-order event list
  interleaving flop mass with collective issue points, so check C7 can
  tell a schedule that hides reduce-scatter wire time under remaining
  backward compute from one that bunches every scatter after the last
  flop — check C7's raw material.

Nothing here needs ``jax.shard_map``: programs are traced by the caller
with ``jax.make_jaxpr(fn, axis_env=...)``, which binds collective axis
names on every jax this repo supports (0.4.x through current), so the
analyzer runs identically on the old-jax CPU boxes that drive the
pipeline schedules through the vmap-emulation path.
"""

import dataclasses

#: collective primitive name -> reduce op it applies (None = pure data
#: movement). ``axis_index`` is deliberately absent: it is local.
COLLECTIVE_PRIMS = {
    "psum": "sum",
    "pmax": "max",
    "pmin": "min",
    "psum_scatter": "sum",
    "reduce_scatter": "sum",
    "ppermute": None,
    "pbroadcast": None,
    "all_gather": None,
    "all_to_all": None,
    "pgather": None,
}

#: dtypes whose fp32 promotion before a reduction doubles wire bytes
_NARROW = ("bfloat16", "float16")


@dataclasses.dataclass(frozen=True)
class Collective:
    """One collective equation in the traced program."""

    prim: str              # primitive name, e.g. "psum"
    axes: tuple            # axis names it runs over, in order
    dtype: str             # operand dtype(s), comma-joined if mixed
    nelems: int            # total elements across operands
    reduce_op: str         # "sum"/"max"/... or "" for data movement
    path: str              # structural path, e.g. "pjit:f/scan"
    source: str            # user file:line (best effort)
    upcast_from: str = ""  # operand was convert_element_type'd from this
    roundtrip: bool = False  # every consumer casts straight back down

    @property
    def key(self):
        """Identity for sequence comparison: what must match across
        ranks for the collective to rendezvous."""
        return (self.prim, self.axes, self.dtype, self.nelems,
                self.reduce_op)


@dataclasses.dataclass(frozen=True)
class Loop:
    """A scan/while body; its signature repeats ``length`` times
    (``None`` when the trip count is not static — while loops).

    ``trip_rank_dependent`` marks a while loop whose cond output is
    (transitively) derived from ``lax.axis_index``: ranks run
    DIFFERENT iteration counts, so any collective in the body
    rendezvouses across mismatched iterations (C8). Scans always have
    a static trip count and stay False."""

    body: tuple            # tuple of signature nodes
    length: "int | None"
    path: str
    source: str
    trip_rank_dependent: bool = False


@dataclasses.dataclass(frozen=True)
class Branches:
    """A cond/switch: one signature list per branch, plus whether the
    predicate is (transitively) derived from ``lax.axis_index``."""

    options: tuple         # tuple of tuples of signature nodes
    pred_rank_dependent: bool
    path: str
    source: str


@dataclasses.dataclass(frozen=True)
class DonationSite:
    """A pjit equation with donated invars (check C4's input)."""

    name: str              # pjit name param
    path: str
    source: str
    jaxpr: object          # the pjit's ClosedJaxpr
    donated: tuple         # per-invar donation flags


@dataclasses.dataclass
class Extraction:
    """Everything the checks consume, from one traced program."""

    signature: tuple       # nested Collective/Loop/Branches nodes
    donation_sites: list
    axis_names_seen: set   # every axis name any collective referenced
    #: program-order event list for C7: ``("flops", weight)`` runs
    #: (consecutive compute merged) interleaved with
    #: ``("coll", prim, axes, path, source)`` issue points.
    profile: tuple = ()


def _source_of(eqn):
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return ""


def _closed(j):
    """Normalize Jaxpr vs ClosedJaxpr (remat2 carries a raw Jaxpr)."""
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _axis_names(eqn):
    params = eqn.params
    axes = params.get("axes", params.get("axis_name", ()))
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _aval(v):
    return v.aval


def _is_literal(v):
    return not hasattr(v, "count")


class _Walker:
    """One recursive walk over a jaxpr tree, threading the rank-taint
    environment through every sub-jaxpr."""

    def __init__(self):
        self.donation_sites = []
        self.axis_names_seen = set()

    def walk(self, closed_jaxpr, in_taint, path=""):
        """Returns ``(signature_nodes, out_taints)`` for one jaxpr given
        per-invar taint flags."""
        jaxpr = _closed(closed_jaxpr)
        taint = {}

        def get_t(v):
            return False if _is_literal(v) else taint.get(v, False)

        def set_t(v, t):
            taint[v] = bool(t)

        for var, t in zip(jaxpr.invars, in_taint):
            set_t(var, t)
        for var in jaxpr.constvars:
            set_t(var, False)

        nodes = []
        producers = {}
        consumers = {}
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                if not _is_literal(v):
                    consumers.setdefault(v, []).append(eqn)
            for v in eqn.outvars:
                producers[v] = eqn

        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            in_t = [get_t(v) for v in eqn.invars]
            out_t = any(in_t) or prim == "axis_index"
            per_out_t = None  # vector taint when a handler provides one

            if prim in COLLECTIVE_PRIMS:
                nodes.append(self._collective(
                    eqn, path, producers, consumers, jaxpr))
            elif prim == "scan":
                sub_nodes, per_out_t = self._scan(eqn, in_t, path)
                nodes.extend(sub_nodes)
            elif prim == "while":
                sub_nodes, per_out_t = self._while(eqn, in_t, path)
                nodes.extend(sub_nodes)
            elif prim == "cond":
                node, out_t = self._cond(eqn, in_t, path)
                if node is not None:
                    nodes.append(node)
            else:
                sub = self._sub_jaxprs(eqn)
                if sub:
                    # pjit / remat2 / custom_{jvp,vjp}_call / anything
                    # else carrying a body: inline it (transparent
                    # control flow). Taints map positionally when arity
                    # lines up; otherwise fall back to the conservative
                    # any() join.
                    if prim == "pjit":
                        self._record_donation(eqn, path)
                    label = (f"{prim}:{eqn.params['name']}"
                             if prim == "pjit" and "name" in eqn.params
                             else prim)
                    sub_path = f"{path}/{label}" if path else label
                    merged_out = False
                    for s in sub:
                        sj = _closed(s)
                        st = (in_t if len(sj.invars) == len(in_t)
                              else [any(in_t)] * len(sj.invars))
                        sub_nodes, sub_out = self.walk(s, st, sub_path)
                        nodes.extend(sub_nodes)
                        merged_out = merged_out or any(sub_out)
                    out_t = out_t or merged_out

            if per_out_t is not None and len(per_out_t) == len(eqn.outvars):
                for v, t in zip(eqn.outvars, per_out_t):
                    set_t(v, t)
            else:
                for v in eqn.outvars:
                    set_t(v, out_t)

        return tuple(nodes), [get_t(v) for v in jaxpr.outvars]

    # ---- per-primitive handlers --------------------------------------

    def _collective(self, eqn, path, producers, consumers, jaxpr):
        axes = _axis_names(eqn)
        self.axis_names_seen.update(axes)
        prim = eqn.primitive.name
        operands = [v for v in eqn.invars if not _is_literal(v)]
        dtypes = []
        nelems = 0
        for v in operands:
            aval = _aval(v)
            dtypes.append(str(aval.dtype))
            nelems += int(max(1, _size(aval)))
        dtype = ",".join(sorted(set(dtypes))) if dtypes else ""

        upcast_from = ""
        roundtrip = False
        if COLLECTIVE_PRIMS[prim] is not None and operands:
            src = producers.get(operands[0])
            if (src is not None
                    and src.primitive.name == "convert_element_type"
                    and src.invars and not _is_literal(src.invars[0])):
                from_dt = str(_aval(src.invars[0]).dtype)
                if (from_dt in _NARROW
                        and str(_aval(operands[0]).dtype) == "float32"):
                    upcast_from = from_dt
                    roundtrip = self._is_roundtrip(
                        eqn, from_dt, consumers, jaxpr)

        return Collective(
            prim=prim, axes=axes, dtype=dtype, nelems=nelems,
            reduce_op=COLLECTIVE_PRIMS[prim] or "",
            path=path or "<top>", source=_source_of(eqn),
            upcast_from=upcast_from, roundtrip=roundtrip)

    def _is_roundtrip(self, eqn, from_dt, consumers, jaxpr):
        """True iff every use of the reduction's result immediately
        casts back to the pre-upcast dtype and the raw f32 value never
        escapes as a program output — the deliberate f32-accumulate
        pattern the pipeline ``share()`` uses."""
        outs = set(jaxpr.outvars)
        for v in eqn.outvars:
            if v in outs:
                return False
            uses = consumers.get(v, [])
            if not uses:
                continue
            for use in uses:
                if (use.primitive.name != "convert_element_type"
                        or str(use.params.get("new_dtype")) != from_dt):
                    return False
        return True

    def _scan(self, eqn, in_t, path):
        p = eqn.params
        body = p["jaxpr"]
        n_in = len(_closed(body).invars)
        taints = (in_t if len(in_t) == n_in else [any(in_t)] * n_in)
        # Fixpoint over the carry: a tainted carry output taints the
        # next iteration's carry input.
        nc, ncar = p.get("num_consts", 0), p.get("num_carry", 0)
        sub_path = f"{path}/scan" if path else "scan"
        n_donations = len(self.donation_sites)
        for _ in range(3):
            # Re-walks during the taint fixpoint must not duplicate
            # recorded donation sites.
            del self.donation_sites[n_donations:]
            nodes, out_t = self.walk(body, taints, sub_path)
            new = list(taints)
            carried = out_t[:ncar]
            changed = False
            for i, t in enumerate(carried):
                if t and not new[nc + i]:
                    new[nc + i] = True
                    changed = True
            taints = new
            if not changed:
                break
        # Scan outputs = [carries..., stacked ys...]; the body's out
        # taints align 1:1, so loop-computed rank dependence survives
        # into downstream predicates (C1's guaranteed-divergence
        # classification needs this).
        if not nodes:
            return [], out_t
        return [Loop(body=nodes, length=p.get("length"), path=sub_path,
                     source=_source_of(eqn))], out_t

    def _while(self, eqn, in_t, path):
        p = eqn.params
        sub_path = f"{path}/while" if path else "while"
        n_carry = len(_closed(p["body_jaxpr"]).outvars)
        taints = list(in_t)
        n_donations = len(self.donation_sites)
        trip_rank_dep = False
        out = []
        body_out_t = None
        # Fixpoint over the carry (mirrors _scan): a tainted carry
        # output taints the next iteration's carry input — and,
        # through the cond, possibly the trip count itself.
        for _ in range(3):
            # Re-walks during the taint fixpoint must not duplicate
            # recorded donation sites.
            del self.donation_sites[n_donations:]
            out = []
            for key in ("cond_jaxpr", "body_jaxpr"):
                body = p[key]
                n_in = len(_closed(body).invars)
                sub_t = (taints[-n_in:] if len(taints) >= n_in
                         else [any(taints)] * n_in)
                nodes, o_t = self.walk(body, sub_t, sub_path)
                out.extend(nodes)
                if key == "cond_jaxpr":
                    # The cond's output IS the loop predicate: taint
                    # here means the trip count diverges by rank (C8).
                    trip_rank_dep = trip_rank_dep or any(o_t)
                else:
                    # While outputs are the carry, which the body
                    # re-emits.
                    body_out_t = o_t
            changed = False
            if len(taints) >= n_carry and len(body_out_t) == n_carry:
                base = len(taints) - n_carry
                for i, t in enumerate(body_out_t):
                    if t and not taints[base + i]:
                        taints[base + i] = True
                        changed = True
            if not changed:
                break
        if not out:
            return [], body_out_t
        return [Loop(body=tuple(out), length=None, path=sub_path,
                     source=_source_of(eqn),
                     trip_rank_dependent=trip_rank_dep)], body_out_t

    def _cond(self, eqn, in_t, path):
        branches = eqn.params["branches"]
        pred_t = in_t[0] if in_t else False
        sub_path = f"{path}/cond" if path else "cond"
        options = []
        out_t = pred_t
        for b in branches:
            n_in = len(_closed(b).invars)
            args_t = in_t[1:]
            taints = (args_t if len(args_t) == n_in
                      else [any(args_t)] * n_in)
            nodes, b_out = self.walk(b, taints, sub_path)
            options.append(nodes)
            out_t = out_t or any(b_out)
        if not any(options):
            return None, out_t
        return Branches(options=tuple(options),
                        pred_rank_dependent=bool(pred_t),
                        path=sub_path, source=_source_of(eqn)), out_t

    def _record_donation(self, eqn, path):
        donated = eqn.params.get("donated_invars")
        if donated and any(donated):
            name = str(eqn.params.get("name", ""))
            self.donation_sites.append(DonationSite(
                name=name,
                path=f"{path}/pjit:{name}" if path else f"pjit:{name}",
                source=_source_of(eqn), jaxpr=eqn.params["jaxpr"],
                donated=tuple(donated)))

    @staticmethod
    def _sub_jaxprs(eqn):
        """Every Jaxpr/ClosedJaxpr reachable from this eqn's params
        (generic: covers pjit, remat2, custom_vjp_call, and any future
        primitive that carries a body)."""
        found = []
        for v in eqn.params.values():
            if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
                found.append(v)
            elif isinstance(v, (tuple, list)):
                found.extend(x for x in v
                             if hasattr(x, "eqns") or hasattr(x, "jaxpr"))
        return found


def _size(aval):
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    return n


#: elementwise / reduction primitives whose flop weight is their output
#: element count. Deliberately coarse: C7 reasons about WHERE the
#: arithmetic mass sits relative to the collectives, not about absolute
#: flop counts, so one-flop-per-output-element is plenty.
_FLOP_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "max", "min", "pow", "integer_pow",
    "exp", "log", "log1p", "tanh", "logistic", "erf", "rsqrt", "sqrt",
    "neg", "abs", "sign", "select_n", "clamp",
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "cumsum",
})


def _flop_weight(eqn):
    """Static flop estimate for one equation (0 = not compute).

    ``dot_general`` counts ``2 * out_elems * K`` (one multiply-add per
    contracted element); conv counts ``2 * out_elems`` per-position;
    the elementwise/reduction allowlist counts one flop per output
    element. Movement, layout, and control-flow primitives weigh zero —
    the profile measures where the arithmetic sits, not how many bytes
    shuffle around it.
    """
    name = eqn.primitive.name
    out = 0
    for v in eqn.outvars:
        aval = getattr(v, "aval", None)
        if hasattr(aval, "shape"):
            out += _size(aval)
    if name == "dot_general":
        (lhs_contract, _), _ = eqn.params["dimension_numbers"]
        lhs = _aval(eqn.invars[0])
        k = 1
        for d in lhs_contract:
            k *= int(lhs.shape[d])
        return 2 * out * max(1, k)
    if name == "conv_general_dilated":
        return 2 * out
    if name in _FLOP_ELEMENTWISE:
        return out
    return 0


def build_profile(closed_jaxpr, path=""):
    """Flatten a jaxpr into C7's program-order compute/collective
    profile: ``("flops", weight)`` events (consecutive compute merged)
    interleaved with ``("coll", prim, axes, path, source)`` issue
    points. Control flow mirrors :func:`linearize`: scan bodies repeat
    by their static trip count, while loops expand once, cond takes the
    first branch (a diverging branch is C1's to reject), and every
    body-carrying primitive (pjit / remat2 / custom-vjp) inlines."""
    jaxpr = _closed(closed_jaxpr)
    out = []

    def emit_flops(n):
        if n <= 0:
            return
        if out and out[-1][0] == "flops":
            out[-1] = ("flops", out[-1][1] + n)
        else:
            out.append(("flops", n))

    def emit_all(events, repeat=1):
        for _ in range(repeat):
            for ev in events:
                if ev[0] == "flops":
                    emit_flops(ev[1])
                else:
                    out.append(ev)

    def sub(label):
        return f"{path}/{label}" if path else label

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in COLLECTIVE_PRIMS:
            out.append(("coll", prim, _axis_names(eqn),
                        path or "<top>", _source_of(eqn)))
        elif prim == "scan":
            body = build_profile(eqn.params["jaxpr"], sub("scan"))
            emit_all(body, repeat=int(eqn.params.get("length") or 1))
        elif prim == "while":
            for key in ("cond_jaxpr", "body_jaxpr"):
                emit_all(build_profile(eqn.params[key], sub("while")))
        elif prim == "cond":
            branches = eqn.params["branches"]
            if branches:
                emit_all(build_profile(branches[0], sub("cond")))
        else:
            bodies = _Walker._sub_jaxprs(eqn)
            if bodies:
                label = (f"{prim}:{eqn.params['name']}"
                         if prim == "pjit" and "name" in eqn.params
                         else prim)
                for s in bodies:
                    emit_all(build_profile(s, sub(label)))
            else:
                emit_flops(_flop_weight(eqn))
    return tuple(out)


def extract(closed_jaxpr):
    """Walk a ClosedJaxpr and return its :class:`Extraction`."""
    w = _Walker()
    jaxpr = _closed(closed_jaxpr)
    sig, _ = w.walk(closed_jaxpr, [False] * len(jaxpr.invars))
    return Extraction(signature=sig, donation_sites=w.donation_sites,
                      axis_names_seen=w.axis_names_seen,
                      profile=build_profile(closed_jaxpr))


def linearize(nodes, _depth=0):
    """Flatten a signature tree into the ordered list of collectives one
    rank executes: loops expand by their trip count (unknown trip counts
    expand once — good enough for presence checks, and pipeline
    programs always scan with static length), branches inline when all
    options agree (a diverging branch is C1's job to reject first — here
    the first option stands in)."""
    if _depth > 64:
        raise RecursionError("signature nesting too deep")
    out = []
    for node in nodes:
        if isinstance(node, Collective):
            out.append(node)
        elif isinstance(node, Loop):
            body = linearize(node.body, _depth + 1)
            out.extend(body * (node.length if node.length else 1))
        elif isinstance(node, Branches):
            if node.options:
                out.extend(linearize(node.options[0], _depth + 1))
    return out


def iter_nodes(nodes):
    """Depth-first iteration over every node in a signature tree."""
    for node in nodes:
        yield node
        if isinstance(node, Loop):
            yield from iter_nodes(node.body)
        elif isinstance(node, Branches):
            for opt in node.options:
                yield from iter_nodes(opt)
