"""horovod_tpu.analysis — hvdlint, the static SPMD analyzer.

Horovod's classic production failure is silent cross-worker divergence:
ranks issuing different collective sequences and deadlocking at scale.
Upstream catches it at RUNTIME (the controller negotiation +
response-cache consistency checks, csrc/controller.cc); a TPU-native
rebuild can catch the whole class BEFORE launch by analyzing the jitted
program. This package lowers any function the repo jits to a
ClosedJaxpr, walks every sub-jaxpr, extracts the ordered collective
signature per control-flow path, and runs the C1-C8 check catalog over
it — see docs/analysis.md.

Library entry point::

    from horovod_tpu import analysis
    diags = analysis.lint(step_fn, (carry, batch), mesh=mesh)
    assert not analysis.errors(diags)

CLI: ``python -m horovod_tpu.analysis.lint --all``.

Two further static gates live here (both jax-free):

- :mod:`horovod_tpu.analysis.model` — **hvdcheck**, exhaustive
  protocol model checking for the elastic/wire/serving control planes
  plus the csrc<->Python ABI drift guards
  (``python -m horovod_tpu.analysis.model --all`` / ``make
  model-check``).
- :func:`validate_chaos_spec` — the strict ``HOROVOD_FAULT_INJECT``
  grammar parse (``analysis/chaos.py``), so CI rejects malformed
  chaos specs that would silently stay disarmed.
"""

from horovod_tpu.analysis.diagnostics import (  # noqa: F401
    ERROR,
    SEVERITIES,
    WARNING,
    Diagnostic,
    errors,
    filter_allowed,
)
from horovod_tpu.analysis.chaos import (  # noqa: F401
    ChaosSpecError,
    FaultSpec,
    validate_chaos_spec,
)
from horovod_tpu.analysis.extract import (  # noqa: F401
    Branches,
    Collective,
    Extraction,
    Loop,
    extract,
    linearize,
)

def __getattr__(name):
    # Lazy: ``analysis.lint`` is BOTH the entry-point function and the
    # CLI submodule (``python -m horovod_tpu.analysis.lint``). The
    # function lives in api.py; resolving it lazily from there (and
    # caching it into the package namespace) keeps the attribute a
    # callable even though a same-named CLI submodule exists, and keeps
    # runpy from warning about double imports when the CLI runs.
    if name == "lint":
        from horovod_tpu.analysis.api import lint

        globals()["lint"] = lint
        return lint
    raise AttributeError(name)
