"""Install horovod_tpu. Builds the native core via make.

Reference analog: horovod's setup.py drives CMake to build per-framework
extensions (horovod setup.py + CMakeLists.txt). We build one
framework-agnostic core .so, loaded via ctypes.
"""

import subprocess

from setuptools import find_packages, setup
from setuptools.command.build_py import build_py


class BuildWithCore(build_py):
    def run(self):
        subprocess.run(["make", "core"], check=True)
        # Best effort: the TF op library needs the installed TF's
        # headers; when absent it builds on demand at first use instead.
        subprocess.run(["make", "tf"], check=False,
                       capture_output=True)
        super().run()


setup(
    name="horovod_tpu",
    version="0.1.0",
    description="TPU-native distributed training framework (Horovod-compatible API)",
    packages=find_packages(include=["horovod_tpu", "horovod_tpu.*"]),
    package_data={"horovod_tpu": ["lib/*.so"]},
    python_requires=">=3.10",
    cmdclass={"build_py": BuildWithCore},
    entry_points={
        "console_scripts": [
            "horovodrun = horovod_tpu.runner.launch:main",
        ],
    },
)
