"""Benchmark entry point (driver contract): JSON lines to stdout.

Measures llama train steps on the available accelerator and reports
model-FLOPs utilization. MFU is the single-chip analog of the
reference's headline metric (scaling efficiency ≈ how close to hardware
roofline the framework runs — docs/benchmarks.rst cites ~90% of linear
at 128 GPUs); ``vs_baseline`` is measured MFU / 0.40, i.e. 1.0 marks the
40% MFU bar a well-tuned transformer stack hits on TPU at this scale.

A plain run emits FOUR rows (the driver tail-parses the LAST line, so
the pure-bf16 flagship stays last):

1. ``llama_train_step_mfu_mixed`` — 809M, fp32 master weights + fp32
   adam moments (``parallel.master_weights``): the numerically safe
   recipe.
2. ``llama_train_step_mfu_809m`` — the SAME 809M size in pure bf16:
   the safety cost at fixed size is one subtraction against row 1.
3. ``llama_train_step_mfu_eager`` — the flagship trained through the
   EAGER Horovod path: jitted fwd/bwd, then ``hvd.grouped_allreduce``
   of every gradient over the xla_ici device plane (size=1 exercises
   enqueue → negotiate → cached-program replay each step, the
   reference's `DistributedOptimizer` shape — docs/benchmarks.rst
   measures hvd-wrapped training, not a raw-framework program), then a
   jitted optimizer apply.
4. ``llama_train_step_mfu`` — the 1.43B pure-bf16 flagship, split
   grad/apply SPMD step. Measured FIRST in a fresh subprocess (virgin
   heap; see _flagship_row) but EMITTED last so the driver's tail-parse
   gets the headline. The subprocess measures BOTH optimizer-apply
   formulations (optax split apply vs the single-pass
   ``parallel.fused_adam``), records them in a ``llama_update_sweep``
   row emitted just before the headline, and headlines the winner.

``--mixed`` emits only row 1 (back-compat); ``--quick`` only the
flagship rows; ``--sweep`` runs the on-chip tuning lane (remat
save-set, flash block shapes, microbatch accumulation — see
_run_sweep).
"""

import functools
import gc
import json
import sys
import time

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.models import (
    LlamaConfig,
    llama_init,
    llama_loss,
)

# bf16 peak FLOP/s per chip by generation.
_PEAK = {"v4": 275e12, "v5e": 197e12, "v5 lite": 197e12, "v5": 459e12,
         "v5p": 459e12, "v6e": 918e12, "cpu": 5e11}

# Row-format version stamped on every emitted row (emit() below): bump
# when a field is renamed or its meaning moves, so `--diff` and
# `python -m horovod_tpu.telemetry.perfwatch` can refuse mismatched row
# formats loudly instead of mis-comparing (schema 1 = the r17 format).
BENCH_SCHEMA = 1


def match_device_table(device, table, default_key="cpu"):
    """Longest-key-first substring match of device_kind against a
    per-generation table (shared by the MFU and MBU benches)."""
    kind = getattr(device, "device_kind", default_key).lower()
    for key, val in sorted(table.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return table[default_key]


def _peak_flops(device):
    return match_device_table(device, _PEAK)


# 1.4B decoder: profiled sweet spot for one 16G-HBM chip. Pure-bf16
# parameter storage (param_dtype) halves param/grad/optimizer HBM and is
# what lets >1B params fit at all; larger d_model raises matmul
# efficiency (0.50 MFU at d2048 vs 0.47 at d1536/667M fp32 params vs
# 0.45 at d1024/319M); remat="attn" beats full remat (the flash kernel
# makes saving one attention output per layer enough); d2560 regresses
# (0.45). head_dim 128 (16 heads, not 32) feeds the MXU full-depth
# contractions in the flash kernel: 0.525 -> 0.63 MFU at identical
# param count (r4 sweep, docs/benchmarks.md). Round-5 geometry sweep at
# fixed ~1.4B params: fewer-but-wider layers amortize the per-layer
# fixed costs (norm/rope/residual chains, flash launches, scan
# overhead) — L14/d_ff 13312 beats L20/8192 by ~2 MFU points — and 4:1
# GQA (n_kv 4, the llama-3/mistral ratio) trims the kv projections and
# flash dkv work for another ~1.5 (docs/benchmarks.md r5 table).
# Donated buffers throughout.
def _flagship_cfg():
    return LlamaConfig(vocab_size=32768, d_model=2048, n_layers=14,
                       n_heads=16, n_kv_heads=4, d_ff=13312,
                       dtype="bfloat16", remat="attn+gate",
                       param_dtype="bfloat16")


# TPU compiler options for the fused train-step jits: the stock 16 MB
# scoped-VMEM budget under-buffers the big fused matmuls at bench
# shapes (+~1 MFU point at 64 MB, measured r5; 96 MB regresses).
def _step_jit_kwargs():
    if jax.devices()[0].platform == "cpu":
        return {}
    return {"compiler_options": {"xla_tpu_scoped_vmem_limit_kib":
                                 "65536"}}


# 809M: the largest size whose fp32 master + fp32 adam moments (12B HBM
# per param, parallel.master_weights) fit one 16G chip — and therefore
# the size where mixed-vs-pure compares apples to apples. Same
# head_dim-128 recipe as the flagship (12 heads at d1536).
def _same_size_cfg(param_dtype):
    return LlamaConfig(vocab_size=32768, d_model=1536, n_layers=20,
                       n_heads=12, n_kv_heads=6, d_ff=6144,
                       dtype="bfloat16", remat="attn+gate",
                       param_dtype=param_dtype)


def _mfu_row(metric, label_extra, n_params, cfg, batch, seq, dt):
    tokens_per_step = batch * seq
    # Standard (PaLM appendix B) model-FLOPs: 6N per token plus the
    # 12*L*T*d attention term; remat recompute is NOT credited.
    flops_per_token = (6 * n_params
                       + 12 * cfg.n_layers * seq * cfg.d_model)
    mfu = (flops_per_token * tokens_per_step / dt
           / _peak_flops(jax.devices()[0]))
    return {
        "metric": metric,
        "value": round(mfu, 4),
        "unit": f"MFU ({n_params/1e6:.0f}M params, {label_extra}, "
                f"{tokens_per_step} tok/step, "
                f"{tokens_per_step/dt:.0f} tok/s, "
                f"{dt*1e3:.0f} ms/step, "
                f"{jax.devices()[0].device_kind})",
        "vs_baseline": round(mfu / 0.40, 3),
    }


def _data(cfg, batch, seq):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    return {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}


def _timed(step, carry, data, steps, what):
    t0 = time.perf_counter()
    loss, carry = step(carry, data)
    # Block on the whole output tree: some PJRT transports surface the
    # scalar loss before the step's trailing ops finish.
    jax.block_until_ready((loss, carry))
    print(f"{what}: compile+first step "
          f"{time.perf_counter() - t0:.1f}s loss={float(loss):.3f}",
          file=sys.stderr)
    t0 = time.perf_counter()
    inflight = []
    for _ in range(steps):
        loss, carry = step(carry, data)
        # Throttle async dispatch to ~2 steps ahead: a split grad/apply
        # step holds a params-sized gradient tree per ENQUEUED step
        # (apply cannot alias-donate grads), so unbounded run-ahead
        # OOMs at flagship scale. Blocking on a loss from two steps ago
        # costs nothing — it has long been computed.
        inflight.append(loss)
        if len(inflight) > 2:
            jax.block_until_ready(inflight.pop(0))
    jax.block_until_ready((loss, carry))
    dt = (time.perf_counter() - t0) / steps
    del carry
    return dt


def run_spmd(cfg, batch, seq, steps, metric, label, update="split",
             microbatches=1):
    """Split-program train step (``parallel.make_split_train_step``):
    one jitted grad program — called once per microbatch, accumulating
    into donated gradient buffers — and one jitted optimizer-apply
    program. Splitting the adam update out of the grad program measures
    ~3% FASTER than the single fused-into-grad jit at flagship shape
    (573 -> 552 ms, r5) — the monolith's interleaved update schedules
    worse — and it is the same program structure the eager-Horovod row
    uses minus the collective.

    ``update``: "split" = optax adam (updates tree + apply_updates, the
    r5 baseline), "fused" = ``parallel.fused_adam`` (the whole update
    as ONE elementwise pass per leaf — the r6 fewer-passes-over-params
    attack on the adam HBM tail). ``--quick`` measures both and
    headlines the winner (the ``llama_update_sweep`` row records the
    comparison)."""
    from horovod_tpu.parallel import fused_adam, make_split_train_step

    tx = fused_adam(3e-4) if update == "fused" else optax.adam(3e-4)

    # n_params from shapes only — no device allocation.
    shapes = jax.eval_shape(lambda k: llama_init(cfg, k),
                           jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(shapes))

    ts = make_split_train_step(
        lambda p, d: llama_loss(p, d, cfg), tx,
        microbatches=microbatches, jit_kwargs=_step_jit_kwargs())

    # The initial carry is passed as a TEMPORARY on purpose: on the
    # axon transport a donated buffer is not returned to the heap while
    # the caller still holds a reference, and a params+opt-sized ghost
    # copy is exactly what OOMs the split step at flagship scale
    # (empirically bisected r5 — the module-level form worked, the
    # caller-held form failed).
    dt = _timed(ts.step, ts.init(llama_init(cfg, jax.random.PRNGKey(0))),
                _data(cfg, batch, seq), steps, metric)
    return _mfu_row(metric, label, n_params, cfg, batch, seq, dt)


def run_spmd_fused(cfg, batch, seq, steps, metric, label):
    """Single fused jit step (loss + grads + adam in one program).
    ~3% slower than run_spmd's split layout at flagship shape but
    tolerant of a fragmented heap — the fallback when the flagship
    row cannot get a fresh process/heap."""
    tx = optax.adam(3e-4)
    shapes = jax.eval_shape(lambda k: llama_init(cfg, k),
                           jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(shapes))

    @functools.partial(jax.jit, donate_argnums=(0,),
                       **_step_jit_kwargs())
    def step(carry, data):
        params, opt = carry
        loss, grads = jax.value_and_grad(llama_loss)(params, data, cfg)
        updates, opt = tx.update(grads, opt, params)
        return loss, (optax.apply_updates(params, updates), opt)

    def make_carry():
        params = llama_init(cfg, jax.random.PRNGKey(0))
        return (params, tx.init(params))

    # Temporary initial carry — see run_spmd for the donated-buffer
    # ghost-copy rationale.
    dt = _timed(step, make_carry(), _data(cfg, batch, seq), steps,
                metric)
    return _mfu_row(metric, label, n_params, cfg, batch, seq, dt)


def run_mixed(cfg, batch, seq, steps):
    """fp32 master weights + fp32 adam moments, bf16 compute
    (parallel.master_weights) — the numerically safe recipe.
    param_dtype fp32: the master aliases the init tree (no bf16 rounding
    of initial weights, no extra init transient)."""
    from horovod_tpu.parallel import master_weights

    params = llama_init(cfg, jax.random.PRNGKey(0))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    mw = master_weights(optax.adam(3e-4))
    carry = mw.init(params)
    del params

    @functools.partial(jax.jit, donate_argnums=(0,),
                       **_step_jit_kwargs())
    def step(carry, data):
        p = mw.compute_params(carry)
        loss, grads = jax.value_and_grad(llama_loss)(p, data, cfg)
        return loss, mw.apply(carry, grads)

    dt = _timed(step, carry, _data(cfg, batch, seq), steps,
                "llama_train_step_mfu_mixed")
    return _mfu_row("llama_train_step_mfu_mixed",
                    "fp32-master mixed precision", n_params, cfg, batch,
                    seq, dt)


def _eager_parts(cfg):
    """Shared scaffolding for the eager step builders: committed
    params/opt, the jitted grad program, and the params/opt-donating
    adam apply program. ONE copy so the grouped and ungrouped lanes can
    only ever differ by their allreduce granularity."""
    # COMMITTED to the device from the start: the data plane's staging
    # device_put commits the gradients, so apply_fn outputs would flip
    # params from uncommitted to committed after step one — a new jit
    # signature, i.e. a silent 12 s mid-loop recompile of grad_fn that
    # once cost this row half its MFU.
    dev = jax.devices()[0]
    params = jax.device_put(llama_init(cfg, jax.random.PRNGKey(0)), dev)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    tx = optax.adam(3e-4)
    opt = jax.device_put(tx.init(params), dev)

    grad_fn = jax.jit(
        lambda p, d: jax.value_and_grad(llama_loss)(p, d, cfg),
        **_step_jit_kwargs())

    # Grads are NOT donated here: they arrive as donation-ALIASED
    # outputs of the device-plane identity program, and XLA refuses to
    # re-donate an aliased buffer (the "donated buffers were not
    # usable" warning) — listing them would only add noise. params/opt
    # donation is what matters for the peak.
    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def apply_fn(grads, params, opt):
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt

    return (params, opt), n_params, grad_fn, apply_fn


def make_eager_step(cfg):
    """Eager-Horovod step builder, shared with
    benchmarks/autotune_bench.py (hvd must already be initialized):
    jitted grad program, ``hvd.grouped_allreduce`` of the gradient tree
    over the device plane, jitted adam apply. Returns
    ``(step, (params, opt), n_params)`` with
    ``step(carry, data) -> (loss, carry)``."""
    import horovod_tpu.jax as hvd
    from horovod_tpu.jax.optimizer import allreduce_gradients

    carry0, n_params, grad_fn, apply_fn = _eager_parts(cfg)

    def step(carry, data):
        params, opt = carry
        loss, grads = grad_fn(params, data)
        # Donated: the fused device program reuses the gradients' HBM.
        grads = allreduce_gradients(grads, op=hvd.Average, donate=True)
        params, opt = apply_fn(grads, params, opt)
        return loss, (params, opt)

    return step, carry0, n_params


def make_eager_ungrouped_step(cfg):
    """UNGROUPED per-parameter eager step: every gradient is enqueued
    as its OWN allreduce — layer-stacked leaves are unstacked into
    per-layer tensors first, the granularity a per-parameter framework
    hands Horovod (183 small allreduces/step at the 809M 20-layer
    geometry) — so the core's fusion threshold and cycle time genuinely
    bind: the background loop must re-batch the flood of small tensors
    into fused buffers every cycle. This is the workload
    ``benchmarks/autotune_bench.py --ungrouped`` tunes (VERDICT r5 #4:
    the grouped row was a null because one pre-grouped allreduce leaves
    the knobs nothing to do). Returns ``(step, carry, n_params)`` like
    :func:`make_eager_step`."""
    import horovod_tpu.jax as hvd

    carry0, n_params, grad_fn, apply_fn = _eager_parts(cfg)

    def step(carry, data):
        params, opt = carry
        loss, grads = grad_fn(params, data)
        flat, treedef = jax.tree_util.tree_flatten_with_path(grads)
        handles, rebuild = [], []
        for i, (path, leaf) in enumerate(flat):
            stacked = "layers" in jax.tree_util.keystr(path)
            if stacked:
                # one allreduce PER LAYER, as a per-parameter frontend
                # would issue them (stable names keep the response
                # cache hot across steps)
                hs = [hvd.allreduce_async(leaf[j], name=f"ug{i}.{j}",
                                          op=hvd.Average)
                      for j in range(leaf.shape[0])]
                handles.extend(hs)
                rebuild.append((True, len(hs)))
            else:
                handles.append(hvd.allreduce_async(
                    leaf, name=f"ug{i}", op=hvd.Average))
                rebuild.append((False, 1))
        outs = [h.synchronize() for h in handles]
        leaves, k = [], 0
        for stacked, n in rebuild:
            if stacked:
                leaves.append(jnp.stack(outs[k:k + n]))
            else:
                leaves.append(outs[k])
            k += n
        grads = jax.tree.unflatten(treedef, leaves)
        params, opt = apply_fn(grads, params, opt)
        return loss, (params, opt)

    return step, carry0, n_params


def run_eager(cfg, batch, seq, steps, label):
    """The eager Horovod path: every step enqueues the full gradient
    tree on the core (one atomic group), the background thread
    negotiates it (response-cache bitvector in steady state) and
    replays the cached fused XLA allreduce program on the chip, then a
    jitted adam applies the averaged gradients. Reference analog:
    §3.2's hot loop (torch DistributedOptimizer + NCCL backend)."""
    import horovod_tpu.jax as hvd
    from horovod_tpu.jax import xla_ici

    hvd.init()
    if not xla_ici.active() and jax.devices()[0].platform != "cpu":
        xla_ici.enable()

    step, carry, n_params = make_eager_step(cfg)
    data = _data(cfg, batch, seq)
    try:
        from horovod_tpu import telemetry
        from horovod_tpu.telemetry import predict

        # Static predictor: the SAME grad-tree byte volume the
        # telemetry tests reconcile against (dtype-exact — eval_shape
        # of the true grad tree, not n_params x an assumed width).
        predicted = predict.grad_tree_bytes(
            lambda p, d: llama_loss(p, d, cfg), carry[0], data)
        # Wire-goodput rides along for free: the loop runs steps+1
        # steps (compile step included) and the core's byte counters
        # are read before/after (telemetry row below).
        snap0 = telemetry.total_collective_bytes()
        dt = _timed(step, carry, data, steps,
                    "llama_train_step_mfu_eager")
        moved = telemetry.total_collective_bytes() - snap0
        snap = telemetry.snapshot()
    finally:
        hvd.shutdown()
    per_step = moved / (steps + 1) if steps else moved
    telemetry_row = {
        "metric": "telemetry_eager",
        # Steady-state goodput: per-step payload over the post-compile
        # step time _timed measured (wall including the compile step
        # would underreport by the compile/step ratio).
        "wire_goodput_gbps": round(per_step / dt / 1e9, 4),
        "bytes_per_step": per_step,
        "predicted_bytes_per_step": predicted,
        "byte_reconciliation": round(per_step / predicted, 4)
        if predicted else None,
        "cache_hit_rate": round(snap["cache"]["hit_rate"], 4),
        "cycle_stalls": snap["cycle"]["stalls"],
        "unit": "steady-state collective payload GB/s, eager lane "
                "(hvd.metrics() deltas; predicted = grad-tree bytes "
                "via telemetry.predict)",
    }
    return [telemetry_row,
            _mfu_row("llama_train_step_mfu_eager", label, n_params, cfg,
                     batch, seq, dt)]


def full_run_plan(batch, seq, steps):
    """Ordered (name, thunk) rows of the full accelerator run.

    ROW ORDER IS LOAD-BEARING: the eager flagship must be the FIRST
    device-touching config — its peak HBM use is the highest of the
    four, and earlier runs fragment the device heap enough to OOM a
    config that fits cleanly on a virgin heap (observed r3: standalone
    fine, post-mixed/809m RESOURCE_EXHAUSTED with zero live arrays).
    The flagship SPMD row stays LAST because the driver tail-parses the
    final line. `_check_plan_order` (called by main, pinned by
    tests/single/test_bench_plan.py) refuses any reordering.
    """
    return [
        ("eager_flagship",
         lambda: run_eager(_flagship_cfg(), batch, seq, steps,
                           "pure-bf16 eager hvd")),
        ("mixed_809m",
         lambda: run_mixed(_same_size_cfg("float32"), batch, seq, steps)),
        ("spmd_809m",
         lambda: run_spmd(_same_size_cfg("bfloat16"), batch, seq, steps,
                          "llama_train_step_mfu_809m",
                          "pure-bf16 same-size")),
        ("spmd_flagship", _flagship_row),
    ]


def _quick_rows(batch, seq, steps):
    """Flagship rows for the fresh-heap subprocess: measure the r5
    split-apply baseline FIRST (known-good on a virgin heap), then the
    single-pass fused-adam variant; yield a ``llama_update_sweep`` row
    recording both, then the BETTER one as the headline (last line —
    the driver tail-parses it)."""
    base = run_spmd(_flagship_cfg(), batch, seq, steps,
                    "llama_train_step_mfu", "pure-bf16")
    fused = None
    gc.collect()
    try:
        fused = run_spmd(_flagship_cfg(), batch, seq, steps,
                         "llama_train_step_mfu", "pure-bf16 fused-adam",
                         update="fused")
    except Exception as e:  # noqa: BLE001 — the fused candidate runs on
        # a non-virgin heap; any failure keeps the measured baseline.
        print(f"fused-update flagship failed ({type(e).__name__}: {e}); "
              f"keeping the split-apply row", file=sys.stderr)
    sweep = {
        "metric": "llama_update_sweep",
        "update_split": base["value"],
        "update_fused": fused["value"] if fused else None,
        "unit": "MFU; optax split apply vs single-pass fused adam "
                "(parallel.fused_adam), flagship shape",
    }
    best = base if fused is None or base["value"] >= fused["value"] \
        else fused
    return [sweep, best]


def _flagship_row():
    """The headline flagship row (+ the update-sweep row riding along),
    measured in a FRESH SUBPROCESS (`bench.py --quick`): the split
    grad/apply step needs a virgin HBM heap — it OOMs both after three
    prior in-process configs AND in a child racing a live parent
    client, so main() runs this BEFORE the parent initializes its own
    TPU client, holds the rows, and emits them last (headline as the
    final line — the driver tail-parses it). Falls back to the
    in-process monolithic-jit step (~3% slower, fragmentation-tolerant)
    if the subprocess fails. Returns ``(headline_row, extra_rows)``."""
    import os
    import subprocess

    gc.collect()
    try:
        # 1500 s: the child now compiles the flagship grad program for
        # BOTH apply formulations (split then fused) before timing.
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--quick"],
            capture_output=True, text=True, timeout=1500, check=True)
        headline, extras = None, []
        for line in out.stdout.strip().splitlines():
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if (row.get("metric") == "llama_train_step_mfu"
                    # The child emits the CPU smoke row under the SAME
                    # metric if it lost the accelerator — a meaningless
                    # number that must not become the headline.
                    and "cpu smoke" not in row.get("unit", "")):
                headline = row
            elif row.get("metric") == "llama_update_sweep":
                extras.append(row)
        if headline is None:
            raise RuntimeError(f"no flagship row in --quick output: "
                               f"{out.stdout[-300:]!r}")
        return headline, extras
    except Exception as e:  # noqa: BLE001 — subprocess/OOM/parse: any
        # failure falls back to the monolithic in-process measurement.
        print(f"flagship subprocess failed ({type(e).__name__}: {e}); "
              f"falling back to the fused in-process step",
              file=sys.stderr)
        return run_spmd_fused(_flagship_cfg(), *_BENCH_SHAPE,
                              "llama_train_step_mfu", "pure-bf16"), []


# The one bench shape (batch, seq, steps): main() AND the --quick
# subprocess AND the fused fallback all read this constant, so the
# headline row can never silently run at a different shape than the
# comparison rows. 15 steps (~8.5 s of stepping per row) tightens the
# run-to-run spread the 10-step windows showed (±1.5%).
_BENCH_SHAPE = (4, 2048, 15)

_EXPECTED_PLAN = ("eager_flagship", "mixed_809m", "spmd_809m",
                  "spmd_flagship")


def _check_plan_order(plan):
    names = tuple(name for name, _ in plan)
    if not names or names[0] != "eager_flagship":
        raise RuntimeError(
            f"bench plan reordered: the eager flagship must run FIRST "
            f"(virgin-heap requirement, see full_run_plan docstring); "
            f"got {list(names)}")
    if names[-1] != "spmd_flagship":
        raise RuntimeError(
            f"bench plan reordered: the SPMD flagship must run LAST "
            f"(the driver tail-parses the final line); got {list(names)}")
    if names != _EXPECTED_PLAN:
        raise RuntimeError(
            f"bench plan changed: expected {list(_EXPECTED_PLAN)}, got "
            f"{list(names)} — if the change is intentional, update "
            f"_EXPECTED_PLAN and re-measure heap headroom on a real chip")


def _probe_platform():
    """Platform of device 0 WITHOUT initializing this process's jax
    client — the full run must keep the parent off the TPU until the
    flagship subprocess has measured on a virgin heap."""
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=300, check=True)
        return out.stdout.strip().splitlines()[-1]
    except Exception:  # noqa: BLE001 — on probe failure assume an
        # accelerator: initializing the parent's jax client here would
        # defeat the virgin-heap precondition _flagship_row protects
        # (a CPU-only box then just takes the slower full path).
        return "unknown"


def _smoke_row():
    cfg = LlamaConfig.tiny(dtype="float32")
    return run_spmd(cfg, 2, 128, 3, "llama_train_step_mfu", "cpu smoke")


# Child body for one events_overhead rank: the ungrouped eager shape
# (many small per-tensor allreduces per step, stable names riding the
# response-cache bitvector) — the workload where per-response event
# recording would hurt if it could. Pure host, no jax import.
_EVENTS_BENCH_CHILD = r"""
import json, os, sys, time
import numpy as np
sys.path.insert(0, os.environ["HVDTPU_REPO"])
from horovod_tpu.common import eager_ops as ops
from horovod_tpu.common.basics import HorovodBasics

cfg = json.loads(os.environ["EVENTS_BENCH_CFG"])
b = HorovodBasics()
b.init()
rank = b.rank()
tensors = [np.full(cfg["elems"], float(rank + 1 + i), np.float32)
           for i in range(cfg["tensors"])]

def step():
    hs = [ops.allreduce_async(t, f"ug{i}")
          for i, t in enumerate(tensors)]
    for h in hs:
        h.synchronize()

for _ in range(2):  # warmup: reach response-cache steady state
    step()
t0 = time.perf_counter()
for _ in range(cfg["steps"]):
    step()
dt = (time.perf_counter() - t0) / cfg["steps"]
if rank == 0:
    print("EVENTS_BENCH_POINT " + json.dumps(
        {"step_s": dt, "events_head": int(b.lib.hvdtpu_events_head())}))
b.shutdown()
"""


def _control_plane_scaling_rows(world_sizes=None):
    """The `control_plane_scaling` rows (docs/scale.md): flat-vs-tree
    negotiation latency curves from the simulated large-world harness
    (csrc/simworld.cc — thread-per-rank, in-process, no accelerator).
    Both curves per world size, so the tree gather's sub-linear claim
    is checkable against the sequential baseline from the same run."""
    from horovod_tpu.simworld import scaling_profile

    try:
        return scaling_profile(world_sizes=world_sizes) \
            if world_sizes else scaling_profile()
    except Exception as e:  # noqa: BLE001 — a starved CI box must not
        # lose the rest of the bench run to the 256-thread point
        return [{"metric": "control_plane_scaling",
                 "error": f"{type(e).__name__}: {e}"}]


def _events_overhead_rows(ranks=2, tensors=183, elems=2048, steps=8,
                          repeats=3):
    """Event-ring overhead on the eager ungrouped lane: `tensors` small
    per-parameter allreduces per step (the 183-allreduce r07 shape),
    measured with the flight recorder on (default) vs off
    (HOROVOD_EVENTS=0), best-of-`repeats` per config to shed loopback
    noise. The acceptance bar is < 2% regression with events on —
    recording is one fetch_add + a handful of relaxed stores on the
    paths that fire per response/chunk (csrc/events.h)."""
    cfg = json.dumps({"tensors": tensors, "elems": elems,
                      "steps": steps})
    best = {}
    heads = {}
    try:
        for _ in range(repeats):
            for name, knob in (("on", "1"), ("off", "0")):
                point = _run_loopback_ranks(
                    _EVENTS_BENCH_CHILD, "EVENTS_BENCH_POINT", ranks,
                    {"HOROVOD_EVENTS": knob, "EVENTS_BENCH_CFG": cfg})
                if name not in best or point["step_s"] < best[name]:
                    best[name] = point["step_s"]
                heads[name] = point["events_head"]
    except Exception as e:  # noqa: BLE001 — an unusable loopback box
        return [{"metric": "events_overhead",
                 "error": f"{type(e).__name__}: {e}"}]
    overhead = (best["on"] - best["off"]) / best["off"] * 100.0
    return [{
        "metric": "events_overhead",
        "ranks": ranks, "tensors_per_step": tensors,
        "elems_per_tensor": elems,
        "step_s_events_on": round(best["on"], 6),
        "step_s_events_off": round(best["off"], 6),
        "overhead_pct": round(overhead, 3),
        "events_recorded": heads["on"],
        "criterion": "overhead_pct < 2 (ungrouped eager lane, "
                     "best-of-%d)" % repeats,
        "pass": overhead < 2.0,
    }]


def _serving_rows():
    """Serving-lane rows (docs/serving.md): sustained tok/s and
    p50/p99 request latency of the continuous-batching decode engine
    under a seeded Poisson arrival trace, one row per paged-KV block
    format (f32 / int8), plus the `serving_trace_overhead` row
    (request-tracing on vs off on the closed-loop decode lane; the
    < 2% criterion mirrors --events-overhead). Runs
    horovod_tpu/serving/bench_lane.py as a CPU-pinned SUBPROCESS —
    substrate-independent like ring_busbw, and the flagship lane's
    virgin-device-heap requirement stays intact."""
    import os
    import subprocess

    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": os.path.dirname(os.path.abspath(__file__))
                + os.pathsep + env.get("PYTHONPATH", "")})
    try:
        out = subprocess.run(
            [sys.executable, "-m", "horovod_tpu.serving.bench_lane"],
            capture_output=True, text=True, timeout=600, env=env,
            check=True)
    except Exception as e:  # noqa: BLE001 — a failed serving lane
        # yields an error row; the rest of the bench run continues.
        detail = getattr(e, "stderr", "") or ""
        return [{"metric": "serving_latency",
                 "error": f"{type(e).__name__}: {e} {detail[-400:]}"}]
    rows = []
    for line in out.stdout.splitlines():
        if line.startswith("SERVING_ROW "):
            rows.append(json.loads(line.split(" ", 1)[1]))
    if not rows:
        return [{"metric": "serving_latency",
                 "error": "bench_lane emitted no rows",
                 "tail": out.stdout[-400:]}]
    return rows


# Child body for one ring_busbw rank: pure host — numpy + the native
# core over TCP loopback, no jax import, so children are safe to run
# before the flagship subprocess claims the virgin device heap.
# Alongside the end-to-end busbw (the NCCL-tests convention: includes
# negotiation, queueing, and the API path), each point reports
# `wire_gbps` — the same bus formula over the TRANSPORT time alone
# (the core's wire_us histogram delta), which is what the striped
# multi-channel engine actually moves; on a loopback box the fixed
# per-op API overhead (~5 ms) otherwise dilutes the transport win at
# large payloads. Warmup is 3 ops and large sizes run >= 6 timed
# iterations: the first ops after connect pay TCP ramp + page faults
# and a 2-iteration sample was dominated by them.
_RING_BUSBW_CHILD = r"""
import json, os, sys, time
import numpy as np
sys.path.insert(0, os.environ["HVDTPU_REPO"])
from horovod_tpu.common import basics, eager_ops
b = basics.HorovodBasics()
b.init()
rank, size = b.rank(), b.size()
points = []
try:
    for nbytes in json.loads(os.environ["RING_BUSBW_SIZES"]):
        elems = max(nbytes // 4, 1)
        x = np.full(elems, float(rank + 1), np.float32)
        iters = max(6, min(20, (1 << 26) // max(nbytes, 1)))
        for w in range(3):
            eager_ops.allreduce_async(x, f"bw.{nbytes}.w{w}").synchronize()
        snap0 = b.metrics_snapshot()
        t0 = time.perf_counter()
        for i in range(iters):
            eager_ops.allreduce_async(x, f"bw.{nbytes}.{i}").synchronize()
        dt = (time.perf_counter() - t0) / iters
        snap1 = b.metrics_snapshot()
        tx = snap1["wire"]["tx_bytes"] - snap0["wire"]["tx_bytes"]
        txl = (snap1["wire"]["tx_logical_bytes"]
               - snap0["wire"]["tx_logical_bytes"])
        wire_dt = (snap1["wire_us"]["sum_us"]
                   - snap0["wire_us"]["sum_us"]) / iters / 1e6
        bus = 2 * (size - 1) / size * nbytes
        points.append({
            "payload_bytes": nbytes,
            "busbw_gbps": round(bus / dt / 1e9, 4),
            "wire_gbps": round(bus / wire_dt / 1e9, 4) if wire_dt else None,
            "step_s": round(dt, 6),
            "wire_ratio": round(tx / txl, 4) if txl else None,
        })
finally:
    b.shutdown()
if rank == 0:
    print("RING_BUSBW_POINTS " + json.dumps(points), flush=True)
"""


def _run_loopback_ranks(child_src, sentinel, ranks, env_extra,
                        timeout=600):
    """Spawn ``ranks`` local subprocesses wired as ONE Horovod job over
    a fresh loopback port, run ``child_src`` in each, and return rank
    0's ``sentinel``-prefixed JSON payload. The shared launcher behind
    both subprocess-grid benches (`ring_busbw`, `zero_sweep`) — one
    place for the port probe, env plumbing, drain, and kill-on-error."""
    import os
    import socket
    import subprocess

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    repo = os.path.dirname(os.path.abspath(__file__))
    procs = []
    try:
        for r in range(ranks):
            env = dict(os.environ)
            env.update({
                "HOROVOD_RANK": str(r), "HOROVOD_SIZE": str(ranks),
                "HOROVOD_LOCAL_RANK": str(r),
                "HOROVOD_LOCAL_SIZE": str(ranks),
                "HOROVOD_CONTROLLER_ADDR": "127.0.0.1",
                "HOROVOD_CONTROLLER_PORT": str(port),
                "HVDTPU_REPO": repo,
            })
            env.update(env_extra)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", child_src],
                stdout=subprocess.PIPE if r == 0 else subprocess.DEVNULL,
                stderr=subprocess.DEVNULL, text=True, env=env))
        out, _ = procs[0].communicate(timeout=timeout)
        for p in procs[1:]:
            p.wait(timeout=60)
        payload = None
        for line in out.splitlines():
            if line.startswith(sentinel + " "):
                payload = json.loads(line.split(" ", 1)[1])
        if payload is None:
            raise RuntimeError(f"rank 0 emitted no {sentinel}")
        return payload
    except Exception:
        for p in procs:
            p.kill()
        raise


# Child body for one hier_busbw rank: like the ring child, but the
# worker first overrides its layout env to the emulated 2-slice
# topology (HIER_LOCAL ranks per slice) and additionally reports the
# cross-plane wire counters the hierarchical decomposition books.
_HIER_BUSBW_CHILD = r"""
import json, os, sys, time
import numpy as np
sys.path.insert(0, os.environ["HVDTPU_REPO"])
L = int(os.environ["HIER_LOCAL"])
rank = int(os.environ["HOROVOD_RANK"])
size = int(os.environ["HOROVOD_SIZE"])
os.environ.update({
    "HOROVOD_LOCAL_RANK": str(rank % L),
    "HOROVOD_LOCAL_SIZE": str(L),
    "HOROVOD_CROSS_RANK": str(rank // L),
    "HOROVOD_CROSS_SIZE": str(size // L),
})
from horovod_tpu.common import basics, eager_ops
b = basics.HorovodBasics()
b.init()
points = []
try:
    for nbytes in json.loads(os.environ["RING_BUSBW_SIZES"]):
        elems = max(nbytes // 4, 1)
        x = np.full(elems, float(rank + 1), np.float32)
        iters = max(2, min(20, (1 << 24) // nbytes))
        eager_ops.allreduce_async(x, f"bw.{nbytes}.warm").synchronize()
        snap0 = b.metrics_snapshot()["wire"]
        t0 = time.perf_counter()
        for i in range(iters):
            eager_ops.allreduce_async(x, f"bw.{nbytes}.{i}").synchronize()
        dt = (time.perf_counter() - t0) / iters
        snap1 = b.metrics_snapshot()["wire"]
        d = lambda k: snap1[k] - snap0[k]
        # Flat-ring DCN baseline: the locality-blind flat ring streams
        # 2(N-1)/N x payload per rank with no idea where the slice
        # boundary is, so all of it prices at DCN rates.
        flat_dcn = 2 * (size - 1) * nbytes
        cross = d("cross_tx_bytes") / iters
        points.append({
            "payload_bytes": nbytes,
            "busbw_gbps": round(2 * (size - 1) / size * nbytes / dt / 1e9,
                                4),
            "step_s": round(dt, 6),
            "wire_ratio": (round(d("tx_bytes") / d("tx_logical_bytes"), 4)
                           if d("tx_logical_bytes") else None),
            "cross_bytes_per_iter": int(cross),
            "cross_ratio_vs_flat": round(size * cross / flat_dcn, 4),
        })
finally:
    b.shutdown()
if rank == 0:
    print("HIER_BUSBW_POINTS " + json.dumps(points), flush=True)
"""


def _hier_busbw_rows(ranks=4, local=2):
    """Cross-plane allreduce bus-bandwidth sweep at an emulated
    ``ranks/local`` slices x ``local`` ranks topology: flat host ring
    vs hierarchical vs hierarchical with the bf16 codec on the
    cross-plane hop (docs/redistribute.md). ``cross_ratio_vs_flat`` is
    the world cross-plane tx bytes over the locality-blind flat ring's
    full stream — the ISSUE-8 acceptance wants <= ~(1/local + eps) at
    16 MiB on the hier rows (the bf16 row halves it again)."""
    sizes = [1 << 15, 1 << 20, 1 << 24]
    configs = [
        ("flat", {"HOROVOD_CROSS_PLANE": "ring"}),
        ("hier", {"HOROVOD_CROSS_PLANE": "hier"}),
        ("hier+bf16-cross", {"HOROVOD_CROSS_PLANE": "hier",
                             "HOROVOD_CROSS_PLANE_COMPRESSION": "1"}),
    ]
    rows = []
    for name, knobs in configs:
        row = {"metric": "hier_busbw", "config": name, "ranks": ranks,
               "slices": ranks // local,
               "unit": "host allreduce bus GB/s at an emulated "
                       f"{ranks // local}x{local} topology; "
                       "cross_ratio_vs_flat = world cross-plane tx / "
                       "flat-ring full stream"}
        try:
            row["points"] = _run_loopback_ranks(
                _HIER_BUSBW_CHILD, "HIER_BUSBW_POINTS", ranks,
                dict(knobs, HIER_LOCAL=str(local),
                     RING_BUSBW_SIZES=json.dumps(sizes)))
        except Exception as e:  # noqa: BLE001 — a failed transport
            # config yields an error row; the sweep continues.
            row["error"] = f"{type(e).__name__}: {e}"
        rows.append(row)
    return rows


def _ring_busbw_rows(ranks=4):
    """Host-ring allreduce bus-bandwidth sweep, one JSON row per
    transport config: bulk-synchronous (chunk knob 0 — the pre-r10
    engine), chunk-overlapped (default 256 KiB double-buffered
    pipeline), chunk-overlapped + bf16 wire compression, and the
    multi-channel striped transport (HOROVOD_WIRE_CHANNELS=K: chunk i
    rides socket i % K with one reduce worker per channel) at K in
    {2, 4}. Every row carries its ``channels`` so perfwatch series
    never cross-join K=1 and K=4 (ROW_IDENTITY_FIELDS). The striped
    win is per-LINK parallelism, and a loopback box saturates its
    aggregate fabric with >= 4 ranks pumping — so the sweep adds a
    2-rank lane (K=1 vs K=4) where the per-link headroom is visible;
    the `wire_gbps` column (transport time alone) is the striping
    acceptance number, busbw the end-to-end one. 1 KiB to 64 MiB
    payloads over local processes on TCP loopback —
    substrate-independent, so the driver's bench capture gets the
    overlap, compression, and striping wins as numbers on any box.
    busbw follows the NCCL-tests convention (2(N-1)/N x payload /
    time); wire_ratio is the measured transport/full-width byte
    quotient (~0.5 when bf16 engages — the core's wire-vs-logical
    counters)."""
    sizes = [1 << 10, 1 << 15, 1 << 20, 1 << 24, 1 << 26]
    unit = ("host-ring allreduce bus GB/s (2(N-1)/N x payload/time), "
            "TCP loopback; wire_gbps = same formula over transport "
            "(wire_us) time; wire_ratio = transport/full-width bytes")
    configs = [
        ("bulk", ranks, 1, {"HOROVOD_RING_CHUNK_BYTES": "0",
                            "HOROVOD_WIRE_COMPRESSION": "0"}),
        ("overlap", ranks, 1,
         {"HOROVOD_RING_CHUNK_BYTES": str(256 * 1024),
          "HOROVOD_WIRE_COMPRESSION": "0"}),
        ("overlap+bf16", ranks, 1,
         {"HOROVOD_RING_CHUNK_BYTES": str(256 * 1024),
          "HOROVOD_WIRE_COMPRESSION": "1"}),
        # Striped lanes: 1 MiB chunks (each channel still cuts multi-
        # chunk streams at 16 MiB), uncompressed — the pure transport
        # comparison against `overlap`.
        ("striped-k2", ranks, 2,
         {"HOROVOD_RING_CHUNK_BYTES": str(1024 * 1024),
          "HOROVOD_WIRE_COMPRESSION": "0",
          "HOROVOD_WIRE_CHANNELS": "2"}),
        ("striped-k4", ranks, 4,
         {"HOROVOD_RING_CHUNK_BYTES": str(1024 * 1024),
          "HOROVOD_WIRE_COMPRESSION": "0",
          "HOROVOD_WIRE_CHANNELS": "4"}),
        # Per-link lane: 2 ranks, where loopback aggregate bandwidth
        # does not mask the per-pair stripe win (K=1 baseline + K=4).
        ("overlap-n2", 2, 1,
         {"HOROVOD_RING_CHUNK_BYTES": str(256 * 1024),
          "HOROVOD_WIRE_COMPRESSION": "0"}),
        ("striped-k4-n2", 2, 4,
         {"HOROVOD_RING_CHUNK_BYTES": str(1024 * 1024),
          "HOROVOD_WIRE_COMPRESSION": "0",
          "HOROVOD_WIRE_CHANNELS": "4"}),
    ]
    rows = []
    for name, nranks, channels, knobs in configs:
        row = {"metric": "ring_busbw", "config": name, "ranks": nranks,
               "channels": channels, "unit": unit}
        try:
            row["points"] = _run_loopback_ranks(
                _RING_BUSBW_CHILD, "RING_BUSBW_POINTS", nranks,
                dict(knobs, RING_BUSBW_SIZES=json.dumps(sizes)))
        except Exception as e:  # noqa: BLE001 — a failed transport
            # config yields an error row; the sweep continues.
            row["error"] = f"{type(e).__name__}: {e}"
        rows.append(row)
    return rows


# Child body for one zero_sweep rank: jax pinned to CPU (subprocess, so
# the parent's device heap is untouched), the eager ZeRO lane against
# its replicated baseline at a synthetic ~8 MB f32 geometry.
_ZERO_SWEEP_CHILD = r"""
import json, os, sys, time
import numpy as np
sys.path.insert(0, os.environ["HVDTPU_REPO"])
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu.jax as hvd
from horovod_tpu.jax.compression import Compression
from horovod_tpu.parallel.zero import (
    optimizer_state_bytes, zero_bucket_layout)
from horovod_tpu.telemetry.predict import zero_layout_bytes

knobs = json.loads(os.environ["ZERO_SWEEP_KNOBS"])
steps = knobs["steps"]
hvd.init()
rank, size = hvd.rank(), hvd.size()
# ~2M f32 elements over a dozen leaves (layer-ish shapes, one ragged).
shapes = [(512, 256)] * 8 + [(256, 512)] * 6 + [(4099,), (257,)]
params = {f"p{i}": jnp.zeros(s, jnp.float32) + 0.1 * i
          for i, s in enumerate(shapes)}
grads = {f"p{i}": jnp.full(s, 0.01 * ((rank + i) % 5 - 2), jnp.float32)
         for i, s in enumerate(shapes)}
n_elems = sum(int(np.prod(s)) for s in shapes)
if knobs["zero"]:
    opt = hvd.DistributedFusedAdam(
        1e-3, zero=True, bucket_bytes=knobs["bucket_bytes"],
        overlap=knobs["overlap"],
        compression=getattr(Compression, knobs["compression"]))
    layout = zero_bucket_layout(list(params.values()), size,
                                knobs["bucket_bytes"])
    if knobs["compression"] == "bf16":
        # The param allgather's LOGICAL payload is genuinely bf16 wide
        # (the op ships a 2-byte tensor); only the reduce-scatter stays
        # f32-logical (bf16 on the wire rides below the op accounting).
        predicted = sum(b.padded * (4 + 2) for b in layout.buckets)
    else:
        predicted = zero_layout_bytes(layout)
else:
    opt = hvd.DistributedFusedAdam(1e-3)
    # allreduce logical volume per step: the full gradient tree.
    predicted = n_elems * 4
state = opt.init(params)
try:
    params, state = opt.apply(params, grads, state)  # warm (compiles)
    from horovod_tpu import telemetry
    snap0 = telemetry.snapshot()
    t0 = time.perf_counter()
    for _ in range(steps):
        params, state = opt.apply(params, grads, state)
    dt = (time.perf_counter() - t0) / steps
    snap1 = telemetry.snapshot()
    wire = (snap1["wire"]["tx_bytes"] - snap0["wire"]["tx_bytes"]) / steps
    ops = 0
    for op_name in ("allreduce", "reducescatter", "allgather"):
        ops += (snap1["ops"].get(op_name, {}).get("bytes", 0)
                - snap0["ops"].get(op_name, {}).get("bytes", 0))
    ops /= steps
    row = {
        "step_s": round(dt, 6),
        "per_rank_opt_bytes": optimizer_state_bytes(state),
        "param_bytes": n_elems * 4,
        "wire_tx_bytes_per_step": wire,
        "ops_logical_bytes_per_step": ops,
        "predicted_logical_bytes": predicted,
        "byte_reconciliation": round(ops / predicted, 4) if predicted
        else None,
    }
finally:
    hvd.shutdown()
if rank == 0:
    print("ZERO_SWEEP_ROW " + json.dumps(row), flush=True)
"""


def _zero_sweep_rows(ranks=4, steps=5):
    """The zero on/off x bucket-size tuning grid (`zero_sweep` JSON
    rows): the eager replicated-allreduce baseline vs ZeRO-1 sharded
    (phase-separated), ZeRO-1 overlapped (per-bucket reduce-scatter /
    allgather pipelined under the shard updates), and overlapped +
    bf16 wire (compressed reduce-scatter in the core + bf16 param
    allgather) — each zero config at two bucket granularities. Local
    CPU subprocesses over TCP loopback, so the grid runs on any box;
    rows carry per-rank optimizer bytes (the N-fold ZeRO-1 cut), step
    time (the overlap win), measured wire bytes (the ~0.5x compressed
    quotient vs the allreduce baseline), and the predicted-vs-measured
    logical-byte reconciliation (docs/zero.md)."""
    bucket_grid = [256 * 1024, 4 * 1024 * 1024]
    configs = [("replicated", {"zero": False}, None)]
    for bb in bucket_grid:
        configs += [
            ("zero1", {"zero": True, "overlap": False}, bb),
            ("zero1+overlap", {"zero": True, "overlap": True}, bb),
            ("zero1+overlap+bf16",
             {"zero": True, "overlap": True, "compression": "bf16",
              "wire": "1"}, bb),
        ]
    rows, base_wire = [], None
    for name, knobs, bb in configs:
        payload = {"zero": knobs.get("zero", False),
                   "overlap": knobs.get("overlap", False),
                   "compression": knobs.get("compression", "none"),
                   "bucket_bytes": bb or 0, "steps": steps}
        row = {"metric": "zero_sweep", "config": name, "ranks": ranks,
               "bucket_bytes": bb,
               "unit": "eager optimizer lane over TCP loopback; wire = "
                       "transport tx bytes/step (hvd.metrics), "
                       "reconciliation = ops-logical vs layout-"
                       "predicted bytes"}
        try:
            row.update(_run_loopback_ranks(
                _ZERO_SWEEP_CHILD, "ZERO_SWEEP_ROW", ranks,
                {"HOROVOD_WIRE_COMPRESSION": knobs.get("wire", "0"),
                 "JAX_PLATFORMS": "cpu",
                 "ZERO_SWEEP_KNOBS": json.dumps(payload)}))
            if name == "replicated":
                base_wire = row["wire_tx_bytes_per_step"]
            if base_wire:
                row["wire_ratio_vs_replicated"] = round(
                    row["wire_tx_bytes_per_step"] / base_wire, 4)
        except Exception as e:  # noqa: BLE001 — a failed grid point
            # yields an error row; the sweep continues.
            row["error"] = f"{type(e).__name__}: {e}"
        rows.append(row)
    return rows


# Child body for one jit_fusion rank: the host-lane fused train step
# (hvd.make_fused_train_step — segmented backward jits, per-bucket
# reduce-scatters fired at segment boundaries, allgathers deferred
# into the next step) vs the bulk-synchronous unfused schedule the
# HOROVOD_JIT_FUSION=0 escape hatch restores. A StepTimer brackets
# every step so the core's overlap ledger attributes exposed/hidden
# wire time per plane (docs/metrics.md), and each rank dumps its event
# ring for the parent's critical-path attribution.
_FUSION_CHILD = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, os.environ["HVDTPU_REPO"])
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import horovod_tpu.jax as hvd
from horovod_tpu.parallel.zero import zero_bucket_layout
from horovod_tpu.telemetry import critpath
from horovod_tpu.telemetry.step_timer import StepTimer

knobs = json.loads(os.environ["FUSION_KNOBS"])
steps, width, depth = knobs["steps"], knobs["width"], knobs["depth"]
hvd.init()
rank, size = hvd.rank(), hvd.size()
key = jax.random.PRNGKey(0)
params = {}
for i in range(depth):
    key, k = jax.random.split(key)
    params[f"w{i}"] = (jax.random.normal(k, (width, width))
                       / np.sqrt(width)).astype(jnp.float32)

def loss_fn(p, batch):
    h = batch["x"]
    for i in range(depth):
        h = jnp.tanh(h @ p[f"w{i}"])
    return jnp.mean((h - batch["y"]) ** 2)

batch = {"x": jax.random.normal(jax.random.PRNGKey(1),
                                (knobs["batch"], width)),
         "y": jax.random.normal(jax.random.PRNGKey(2),
                                (knobs["batch"], width))}
n_buckets = len(zero_bucket_layout(list(params.values()), size,
                                   knobs["bucket_bytes"]).buckets)
# The knob under test rides in via HOROVOD_JIT_FUSION (the env
# escape hatch itself, not set_jit_fusion — the bench exercises the
# operator-facing path).
init, step, finish = hvd.make_fused_train_step(
    loss_fn, 1e-3, bucket_bytes=knobs["bucket_bytes"])
carry = init(params)
timer = StepTimer()
try:
    loss, carry = step(carry, batch)  # warm: compiles every segment
    for _ in range(steps):
        timer.start_step()
        loss, carry = step(carry, batch)
        timer.end_step(loss)
    _, carry = finish(carry)
    ov = timer.overlap_summary() or {}
    intra = ov.get("intra", {})
    row = {
        "step_s": round(timer.mean_step_s(), 6),
        "n_buckets": n_buckets,
        "overlap_efficiency": round(ov.get("overlap_efficiency", 0.0),
                                    4),
        "mean_exposed_wire_ms": round(
            intra.get("mean_exposed_wire_ms", 0.0), 3),
        "mean_hidden_wire_ms": round(
            intra.get("mean_hidden_wire_ms", 0.0), 3),
        "mean_total_wire_ms": round(
            intra.get("mean_total_wire_ms", 0.0), 3),
    }
    dump = os.environ.get("FUSION_DUMP_DIR")
    if dump:
        critpath.write_event_dump(
            os.path.join(dump, f"blackbox-rank{rank}.jsonl"),
            rank, size, hvd.events())
finally:
    hvd.shutdown()
if rank == 0:
    print("JIT_FUSION_ROW " + json.dumps(row), flush=True)
"""


def _fusion_rows(ranks=2, steps=6):
    """The jit-lane compute/collective fusion rows (`jit_fusion`):
    the fused host-lane step (per-bucket reduce-scatters interleaved
    with the segmented backward, allgathers hidden under the next
    step's forward) vs the unfused bulk-synchronous schedule
    (`HOROVOD_JIT_FUSION=0`), 2 CPU loopback ranks. The headline
    column is the overlap ledger's ``overlap_efficiency`` — ~0 was
    the whole jit lane's value before the fusion work (every byte
    moved while the host sat between programs); perfwatch watches it
    (down = regression) like any other bench series. Each config also
    runs the critical-path attribution over the ranks' event dumps
    (`report.py --critical-path` on the same files): the acceptance
    signal is the blocking phase moving OFF wire on the fused config
    (docs/fusion.md)."""
    import shutil
    import tempfile

    from horovod_tpu.telemetry import critpath

    # Wire-heavy geometry on purpose (18 MB of params, small batch):
    # the schedule contrast shows in the ledger — bulk-synchronous
    # exposes ~20 ms of wire per step here, the fused schedule ~4 ms.
    # step_s is NOT the signal on this substrate: the loopback "wire"
    # is the same cores as the compute, so hidden wire doesn't come
    # free the way an independently-draining NIC/ICI makes it on TPU.
    payload = {"steps": steps, "width": 768, "depth": 8, "batch": 4,
               "bucket_bytes": 512 * 1024}
    rows = []
    for name, knob in (("unfused", "0"), ("fused", "1")):
        row = {"metric": "jit_fusion", "config": name, "ranks": ranks,
               "bucket_bytes": payload["bucket_bytes"],
               "unit": "host-lane fused train step over TCP loopback; "
                       "overlap_efficiency = hidden/total wire time "
                       "from the step-window overlap ledger; "
                       "blocking_phase from the cross-rank "
                       "critical-path attribution"}
        dump = tempfile.mkdtemp(prefix=f"hvd-fusion-{name}-")
        try:
            row.update(_run_loopback_ranks(
                _FUSION_CHILD, "JIT_FUSION_ROW", ranks,
                {"JAX_PLATFORMS": "cpu",
                 "HOROVOD_JIT_FUSION": knob,
                 "FUSION_DUMP_DIR": dump,
                 "FUSION_KNOBS": json.dumps(payload)}))
            analysis = critpath.critical_path(dump)
            pc = analysis.get("phase_counts", {})
            if pc:
                row["blocking_phase"] = max(pc, key=pc.get)
                row["phase_counts"] = pc
        except Exception as e:  # noqa: BLE001 — a failed config yields
            # an error row; the other config still measures.
            row["error"] = f"{type(e).__name__}: {e}"
        finally:
            shutil.rmtree(dump, ignore_errors=True)
        rows.append(row)
    return rows


def _fleet_util_rows(world_sizes=(64, 256), steps=8):
    """The fleet rank-seconds aggregation rows (`fleet_utilization`,
    docs/fleet.md; no accelerator needed): synthesize a simworld fleet
    with one straggler plus the full r23 evidence surface (wait blocks,
    serving request lifecycles, a recorded SLO breach), run the
    post-mortem fleet analysis over every rank's dump, and emit one row
    per world size. Watched columns: ``utilization`` (down =
    regression), ``unattributed_share`` (the ledger losing evidence),
    ``breaches`` (count growing), and ``analyze_s`` — the aggregation
    itself must stay interactive at 256 ranks (< 2 s acceptance bar)."""
    import shutil
    import tempfile

    from horovod_tpu.simworld import harness
    from horovod_tpu.telemetry import fleet

    rows = []
    for ranks in world_sizes:
        row = {"metric": "fleet_utilization", "config": "simworld",
               "ranks": ranks, "steps": steps,
               "unit": "rank-seconds ledger over synthesized per-rank "
                       "dumps (one straggler, fused-lane waits, one "
                       "serving request per step, one recorded "
                       "breach); utilization = attributed useful share "
                       "of every rank's window"}
        out = tempfile.mkdtemp(prefix=f"hvd-fleet-{ranks}-")
        try:
            harness.write_sim_step_dumps(
                out, ranks=ranks, steps=steps, slow_rank=ranks // 3,
                waits=True, serving=True,
                breach={"objective": 4, "rank": ranks // 3,
                        "value": 750, "phase": 6,
                        "objective_name": "stall_ms",
                        "phase_name": "stall"})
            t0 = time.perf_counter()
            analysis = fleet.analyze(out)
            dt = time.perf_counter() - t0
            f = analysis["fleet"]
            total_us = f["window_us"]
            row.update({
                "utilization": f["utilization"],
                "unattributed_share": round(
                    f["rank_seconds"]["unattributed"] * 1e6
                    / total_us, 6) if total_us else 0.0,
                "breaches": len(analysis["slo"]["breach_events"]),
                "worst_rank": f["worst_rank"],
                "analyze_s": round(dt, 4),
            })
        except Exception as e:  # noqa: BLE001 — a failed size yields
            # an error row; the other sizes still measure.
            row["error"] = f"{type(e).__name__}: {e}"
        finally:
            shutil.rmtree(out, ignore_errors=True)
        rows.append(row)
    return rows


def _sweep_points(batch):
    """The --sweep point table: (name, config, run_spmd kwargs)."""
    import dataclasses

    fc = _flagship_cfg()
    return [
        ("update-split-b4", fc, dict()),
        ("update-fused-b4", fc, dict(update="fused")),
        # Microbatch-accumulation lane: N-way accumulation at N-x batch
        # keeps the per-microbatch activation footprint of b4 while
        # amortizing the optimizer-apply pass over more tokens.
        ("fused-b8-accum2", fc,
         dict(update="fused", microbatches=2, batch=2 * batch)),
        ("fused-b16-accum4", fc,
         dict(update="fused", microbatches=4, batch=4 * batch)),
        ("remat-attn", dataclasses.replace(fc, remat="attn"), dict()),
        # attn+gate+qkv exceeded HBM monolithically at b4 (r5); under
        # 2-way accumulation the halved activation stash may fit.
        ("remat-attn+gate+qkv-accum2",
         dataclasses.replace(fc, remat="attn+gate+qkv"),
         dict(update="fused", microbatches=2)),
        ("flash-block-512", dataclasses.replace(fc, flash_block=512),
         dict(update="fused")),
        ("flash-block-2048", dataclasses.replace(fc, flash_block=2048),
         dict(update="fused")),
    ]


def _bubble_rows(S=4, microbatches=(8, 16), virtual=(1, 2, 4)):
    """One JSON row per (schedule, V, accum) pipeline point — the
    schedule-derived bubble fraction at ``S`` stages, straight from the
    slot tables the implementation executes, so the driver's bench
    capture can diff schedules without parsing prose. Pure host math:
    emitted by --sweep on ANY substrate (a single chip cannot raise a
    pipe axis, so these are the pipeline lane's portable numbers; the
    gradient equivalence behind them is pinned by
    tests/single/test_pipeline_interleaved.py).

    gpipe / lockstep-1f1b use their closed forms (in fwd+bwd subtick
    units, matching the interleaved engine's accounting); interleaved
    rows come from parallel.pipeline.build_interleaved_schedule.
    """
    from horovod_tpu.parallel.pipeline import build_interleaved_schedule

    rows = []

    def row(schedule, V, M, bubble, slots):
        return {
            "metric": "pipeline_bubble",
            "schedule": schedule, "V": V, "accum": M, "S": S,
            "slots": slots, "value": round(bubble, 4),
            "unit": f"idle fraction of fwd+bwd subticks, S={S} stages, "
                    f"M={M} microbatches, V={V} virtual chunks/device",
        }

    for M in microbatches:
        rows.append(row("gpipe", 1, M,
                        2 * (S - 1) / (2 * M + 2 * (S - 1)),
                        2 * (M + S - 1)))
        rows.append(row("1f1b", 1, M,
                        2 * (S - 1) / (M + 2 * (S - 1)),
                        2 * (M + 2 * (S - 1))))
        for V in virtual:
            s = build_interleaved_schedule(S, V, M)
            rows.append(row("interleaved_1f1b", V, M,
                            s.bubble_fraction, s.n_slots))
    return rows


def _run_sweep_point(name, batch, seq, steps, emit):
    """Measure ONE sweep point in THIS process (`--sweep-point NAME`,
    spawned by --sweep). Every row carries explicit (schedule, V,
    accum) fields so schedule diffs are machine-readable."""
    for pname, cfg, kw in _sweep_points(batch):
        if pname == name:
            b = kw.pop("batch", batch)
            row = run_spmd(cfg, b, seq, steps,
                           f"llama_sweep_{name}", name, **kw)
            row.update(schedule="none", V=1,
                       accum=kw.get("microbatches", 1))
            emit(row)
            return
    raise SystemExit(f"unknown sweep point {name!r}")


def _run_sweep(batch, seq, steps, emit):
    """On-chip tuning lane (`bench.py --sweep`, NOT part of the driver
    run): update formulation, microbatch accumulation, remat save-set,
    and flash (qkv-attention) block shapes at the flagship geometry.
    One JSON row per point, each measured in its OWN subprocess on a
    virgin heap: an in-process try/except would let one point's OOM
    fragment the device heap and poison every later measurement (the
    r3/r5 RESOURCE_EXHAUSTED-with-zero-live-arrays trap), so a crashing
    or hanging point yields an error row and the sweep continues."""
    import os
    import subprocess

    for name, _cfg, _kw in _sweep_points(batch):
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--sweep-point", name],
                capture_output=True, text=True, timeout=1500)
            row = None
            for line in out.stdout.strip().splitlines():
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
            if row is None or out.returncode != 0:
                tail = (out.stderr or out.stdout).strip()[-300:]
                row = {"metric": f"llama_sweep_{name}",
                       "error": f"rc={out.returncode}: {tail}"}
        except subprocess.TimeoutExpired:
            row = {"metric": f"llama_sweep_{name}",
                   "error": "HUNG: no result within 1500 s"}
        emit(row)


# ---- bench-row diffing (`bench.py --diff old.json new.json`) ----------
# The BENCH_r0*.json trajectory finally gets a tool instead of eyeballs:
# load two row files (bench JSONL, a JSON array, or a driver artifact
# whose `tail` embeds rows), match rows by their identity fields, and
# print a per-row delta table over every numeric measurement field.

# Fields that are neither identity nor comparable measurements. The
# identity (join-key) field list is shared with perfwatch
# (ROW_IDENTITY_FIELDS) so grouping and diffing can never disagree.
_DIFF_SKIP_FIELDS = {"schema", "unit", "error", "ts", "wall_s", "tail"}


def _diff_key(row, seen, key_fields):
    key = tuple((f, row.get(f)) for f in key_fields if f in row)
    n = seen.get(key, 0)
    seen[key] = n + 1
    return key + (("occurrence", n),) if n else key


def _diff_rows(old_path, new_path, threshold=0.0):
    """Compare two bench row files; returns (lines, worst_rel_change).
    Refuses mismatched `schema` stamps — a renamed column diffed by
    name is a silent lie, so format drift must fail loudly. Rows with a
    nested `points` list (ring_busbw/hier_busbw) are flattened to one
    pseudo-row per point first, so the per-size busbw measurements diff
    like any other field."""
    from horovod_tpu.telemetry.perfwatch import (
        ROW_IDENTITY_FIELDS,
        check_schema,
        flatten_rows,
        load_rows,
    )

    old_rows, new_rows = load_rows(old_path), load_rows(new_path)
    old_schema = check_schema(old_rows, what=old_path)
    new_schema = check_schema(new_rows, what=new_path)
    if old_schema != new_schema:
        raise SystemExit(
            f"bench --diff: refusing to compare schema {old_schema} "
            f"({old_path}) against schema {new_schema} ({new_path}) — "
            "row formats differ; re-run the older side on this tree")
    seen_old, seen_new = {}, {}
    old_by_key = {_diff_key(r, seen_old, ROW_IDENTITY_FIELDS): r
                  for r in flatten_rows(old_rows)}
    new_by_key = {_diff_key(r, seen_new, ROW_IDENTITY_FIELDS): r
                  for r in flatten_rows(new_rows)}
    lines = [f"{'row':<52} {'field':<24} {'old':>12} {'new':>12} "
             f"{'delta':>9}"]
    worst = 0.0
    for key in old_by_key:
        if key not in new_by_key:
            lines.append(f"{_key_str(key):<52} (only in {old_path})")
            continue
        old, new = old_by_key[key], new_by_key[key]
        for field in sorted(set(old) & set(new)):
            ov, nv = old[field], new[field]
            if (field in _DIFF_SKIP_FIELDS
                    or any(f == field for f, _ in key)
                    or not isinstance(ov, (int, float))
                    or not isinstance(nv, (int, float))
                    or isinstance(ov, bool) or isinstance(nv, bool)):
                continue
            if ov:
                rel = (nv - ov) / abs(ov)
                delta = f"{rel:>+8.1%}"
            elif nv:
                # 0 -> x has no finite relative change: shown, never
                # threshold-dropped, and it moves the worst tally (a
                # counter appearing — crc_errors, stalls — IS news).
                rel = None
                delta = "    (new)"
            else:
                rel = 0.0
                delta = f"{0.0:>+8.1%}"
            if rel is not None and abs(rel) < threshold:
                continue
            worst = max(worst, abs(rel) if rel is not None else 1.0)
            lines.append(f"{_key_str(key):<52} {field:<24} "
                         f"{ov:>12.6g} {nv:>12.6g} {delta}")
    for key in new_by_key:
        if key not in old_by_key:
            lines.append(f"{_key_str(key):<52} (only in {new_path})")
    return lines, worst


def _key_str(key):
    return "/".join(str(v) for _, v in key if v is not None)


def main():
    argv = sys.argv[1:]
    batch, seq, steps = _BENCH_SHAPE

    def emit(row):
        # Print each row AS PRODUCED: a later config failing must not
        # discard minutes of already-measured rows. gc between rows
        # returns every stale device buffer before the next config
        # allocates. A list is several rows (run_eager yields its
        # telemetry goodput row alongside the MFU headline). Every row
        # is stamped with the format version HERE — one choke point —
        # so --diff/perfwatch schema guards see a uniform stamp.
        for r in (row if isinstance(row, list) else [row]):
            r.setdefault("schema", BENCH_SCHEMA)
            print(json.dumps(r), flush=True)
        gc.collect()

    if "--diff" in argv:
        # Two-point trajectory comparison (no accelerator needed):
        # per-row delta table between any two bench row files.
        # --diff-threshold 0.05 hides deltas under 5% (0->x rows are
        # always shown — no finite relative change to threshold).
        i = argv.index("--diff")
        try:
            old_path, new_path = argv[i + 1], argv[i + 2]
        except IndexError:
            raise SystemExit("usage: bench.py --diff old.json new.json "
                             "[--diff-threshold 0.05]")
        threshold = 0.0
        if "--diff-threshold" in argv:
            threshold = float(argv[argv.index("--diff-threshold") + 1])
        lines, worst = _diff_rows(old_path, new_path,
                                  threshold=threshold)
        for line in lines:
            print(line)
        print(f"bench --diff: worst relative change {worst:+.1%}")
        return
    if "--lint" in argv:
        # hvdlint preflight: statically analyze every shipped program
        # (collective divergence, axis validity, donation hazards,
        # pipeline schedule conformance — docs/analysis.md) BEFORE
        # committing chip-hours. Exit nonzero on any error diagnostic;
        # a 256-chip deadlock this catches costs seconds here.
        from horovod_tpu.analysis.lint import main as lint_main

        rc = lint_main(["--all"])
        if rc != 0:
            sys.exit(rc)
        argv = [a for a in argv if a != "--lint"]
        if not argv:
            return
    if "--events-overhead" in argv:
        # Standalone event-ring overhead check (no accelerator needed):
        # the ungrouped eager lane with the flight recorder on vs off.
        for row in _events_overhead_rows():
            emit(row)
        return
    if "--scale" in argv:
        # Standalone control-plane scaling curves (no accelerator):
        # the full 8..256 ladder, flat star vs tree gather.
        for row in _control_plane_scaling_rows():
            emit(row)
        return
    if "--serving" in argv:
        # Standalone serving lane (no accelerator needed): the
        # continuous-batching decode engine under a Poisson trace,
        # f32 and int8 paged-KV rows + the request-tracing overhead
        # check (serving_trace_overhead, < 2% criterion).
        for row in _serving_rows():
            emit(row)
        return
    if "--ring-busbw" in argv:
        # Standalone host-ring transport sweep (no accelerator needed),
        # including the cross-plane hierarchical rows (dense/hier lane).
        for row in _ring_busbw_rows():
            emit(row)
        for row in _hier_busbw_rows():
            emit(row)
        return
    if "--zero-sweep" in argv:
        # Standalone ZeRO grid (CPU loopback subprocesses; any box).
        for row in _zero_sweep_rows():
            emit(row)
        return
    if "--fleet-util" in argv:
        # Standalone fleet rank-seconds aggregation rows (no
        # accelerator needed): simworld synthesized dumps at 64 and
        # 256 ranks through the post-mortem fleet analysis
        # (docs/fleet.md).
        for row in _fleet_util_rows():
            emit(row)
        return
    if "--fusion" in argv:
        # Standalone jit-lane fusion rows (CPU loopback subprocesses;
        # any box): fused vs unfused host-lane train step,
        # overlap_efficiency + critical-path blocking phase
        # (docs/fusion.md).
        for row in _fusion_rows():
            emit(row)
        return
    if "--quick" in argv:
        if jax.devices()[0].platform == "cpu":
            emit(_smoke_row())
            return
        for row in _quick_rows(batch, seq, steps):
            emit(row)
        return
    if "--mixed" in argv:
        if jax.devices()[0].platform == "cpu":
            emit(_smoke_row())
            return
        emit(run_mixed(_same_size_cfg("float32"), batch, seq, steps))
        return
    if "--sweep-point" in argv:
        if jax.devices()[0].platform == "cpu":
            print("--sweep-point needs an accelerator; skipping",
                  file=sys.stderr)
            return
        name = argv[argv.index("--sweep-point") + 1]
        _run_sweep_point(name, batch, seq, steps, emit)
        return
    if "--sweep" in argv:
        # Pipeline (schedule, V, accum) bubble rows are host math —
        # emitted on every substrate, before the measured lane. The
        # ZeRO grid (zero on/off x bucket size — docs/zero.md) runs on
        # CPU loopback subprocesses, so it is substrate-independent too.
        for row in _bubble_rows():
            emit(row)
        for row in _zero_sweep_rows():
            emit(row)
        for row in _fusion_rows():
            emit(row)
        if _probe_platform() == "cpu":
            print("--sweep: no accelerator; emitted the schedule-"
                  "derived pipeline and loopback zero_sweep rows only",
                  file=sys.stderr)
            return
        _run_sweep(batch, seq, steps, emit)
        return

    # Platform probe runs out-of-process: the flagship row must be the
    # FIRST client to touch the chip (virgin-heap requirement for the
    # split step — see _flagship_row).
    if _probe_platform() == "cpu":  # CI / no-accelerator smoke path
        for row in _ring_busbw_rows():
            emit(row)
        for row in _hier_busbw_rows():
            emit(row)
        for row in _events_overhead_rows():
            emit(row)
        for row in _control_plane_scaling_rows():
            emit(row)
        for row in _serving_rows():
            emit(row)
        emit(_smoke_row())
        return

    # Host-ring transport rows first: loopback subprocesses that never
    # import jax, so the flagship subprocess still gets a virgin heap.
    for row in _ring_busbw_rows():
        emit(row)
    for row in _hier_busbw_rows():
        emit(row)
    for row in _events_overhead_rows():
        emit(row)
    for row in _control_plane_scaling_rows():
        emit(row)
    for row in _serving_rows():
        emit(row)

    flagship_row, flagship_extras = _flagship_row()

    plan = full_run_plan(batch, seq, steps)
    _check_plan_order(plan)
    for name, thunk in plan:
        if name == "spmd_flagship":
            # Measured first (subprocess, virgin heap), emitted last
            # (the driver tail-parses the final line); the update-sweep
            # row measured alongside it lands just before.
            for extra in flagship_extras:
                emit(extra)
            emit(flagship_row)
        elif name == "eager_flagship":
            # Retries run OUTSIDE the except blocks — the live
            # exception's traceback pins the failed attempt's frames
            # (params, opt, the whole gradient tree).
            eager_failed = False
            try:
                emit(thunk())
            except Exception as e:  # noqa: BLE001 — HBM headroom is
                # config-dependent; fall back to the mixed-size config
                # rather than lose the eager row.
                print(f"eager flagship failed ({type(e).__name__}: {e});"
                      f" retrying at 809M", file=sys.stderr)
                eager_failed = True
            if eager_failed:
                gc.collect()
                try:
                    emit(run_eager(_same_size_cfg("bfloat16"), batch,
                                   seq, steps,
                                   "pure-bf16 eager hvd (809M)"))
                except Exception as e:  # noqa: BLE001
                    print(f"eager 809M also failed ({type(e).__name__}:"
                          f" {e}); continuing without an eager row",
                          file=sys.stderr)
                gc.collect()
        else:
            emit(thunk())


if __name__ == "__main__":
    main()
