"""Benchmark entry point (driver contract): ONE JSON line to stdout.

Measures the flagship llama train step (bf16 compute, remat, fused adam)
on the available accelerator and reports model-FLOPs utilization. MFU is
the single-chip analog of the reference's headline metric (scaling
efficiency ≈ how close to hardware roofline the framework runs —
docs/benchmarks.rst cites ~90% of linear at 128 GPUs); ``vs_baseline`` is
measured MFU / 0.40, i.e. 1.0 marks the 40% MFU bar a well-tuned
transformer stack hits on TPU at this scale.
"""

import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import optax

from horovod_tpu.models import (
    LlamaConfig,
    llama_init,
    llama_loss,
)

# bf16 peak FLOP/s per chip by generation.
_PEAK = {"v4": 275e12, "v5e": 197e12, "v5 lite": 197e12, "v5": 459e12,
         "v5p": 459e12, "v6e": 918e12, "cpu": 5e11}


def _peak_flops(device):
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in sorted(_PEAK.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return _PEAK["cpu"]


def main():
    mixed = "--mixed" in sys.argv[1:]
    on_accel = jax.devices()[0].platform != "cpu"
    if on_accel and mixed:
        # Mixed-precision flagship: fp32 master weights + fp32 adam
        # moments (parallel.master_weights), bf16 compute. 12B HBM per
        # param caps the size near ~850M on one 16G chip — the
        # numerically safe recipe benched alongside the pure-bf16 one.
        # param_dtype fp32: the master aliases the init tree (no bf16
        # rounding of initial weights, no extra init transient).
        cfg = LlamaConfig(vocab_size=32768, d_model=1536, n_layers=20,
                          n_heads=24, n_kv_heads=12, d_ff=6144,
                          dtype="bfloat16", remat="attn",
                          param_dtype="float32")
        batch, seq, steps = 4, 2048, 10
    elif on_accel:
        # 1.4B decoder: profiled sweet spot for one 16G-HBM chip.
        # Pure-bf16 parameter storage (param_dtype) halves param/grad/
        # optimizer HBM and is what lets >1B params fit at all; larger
        # d_model raises matmul efficiency (0.50 MFU at d2048 vs 0.47 at
        # d1536/667M fp32 params vs 0.45 at d1024/319M); remat="attn"
        # beats full remat (the flash kernel makes saving one attention
        # output per layer enough); d2560 regresses (0.45). Donated
        # buffers throughout.
        cfg = LlamaConfig(vocab_size=32768, d_model=2048, n_layers=20,
                          n_heads=32, n_kv_heads=16, d_ff=8192,
                          dtype="bfloat16", remat="attn",
                          param_dtype="bfloat16")
        batch, seq, steps = 4, 2048, 10
    else:  # CI / no-accelerator smoke path
        cfg = LlamaConfig.tiny(dtype="float32")
        batch, seq, steps = 2, 128, 3

    params = llama_init(cfg, jax.random.PRNGKey(0))
    tx = optax.adam(3e-4)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    data = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
    n_params = sum(x.size for x in jax.tree.leaves(params))

    if mixed:
        from horovod_tpu.parallel import master_weights

        mw = master_weights(tx)
        carry = mw.init(params)
        del params

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(carry, data):
            p = mw.compute_params(carry)
            loss, grads = jax.value_and_grad(llama_loss)(p, data, cfg)
            return loss, mw.apply(carry, grads)
    else:
        opt = tx.init(params)
        carry = (params, opt)

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(carry, data):
            params, opt = carry
            loss, grads = jax.value_and_grad(llama_loss)(params, data,
                                                         cfg)
            updates, opt = tx.update(grads, opt, params)
            return loss, (optax.apply_updates(params, updates), opt)

    t0 = time.perf_counter()
    loss, carry = step(carry, data)
    # Block on the whole output tree: some PJRT transports surface the
    # scalar loss before the step's trailing ops finish.
    jax.block_until_ready((loss, carry))
    print(f"compile+first step: {time.perf_counter() - t0:.1f}s "
          f"loss={float(loss):.3f}", file=sys.stderr)

    t0 = time.perf_counter()
    for _ in range(steps):
        loss, carry = step(carry, data)
    jax.block_until_ready((loss, carry))
    dt = (time.perf_counter() - t0) / steps
    tokens_per_step = batch * seq
    # Standard (PaLM appendix B) model-FLOPs: 6N per token plus the
    # 12*L*T*d attention term; remat recompute is NOT credited.
    flops_per_token = (6 * n_params
                       + 12 * cfg.n_layers * seq * cfg.d_model)
    flops_per_step = flops_per_token * tokens_per_step
    mfu = flops_per_step / dt / _peak_flops(jax.devices()[0])

    label = "fp32-master mixed precision" if mixed else "pure-bf16"
    print(json.dumps({
        "metric": ("llama_train_step_mfu_mixed" if mixed
                   else "llama_train_step_mfu"),
        "value": round(mfu, 4),
        "unit": f"MFU ({n_params/1e6:.0f}M params, {label}, "
                f"{tokens_per_step} tok/step, "
                f"{tokens_per_step/dt:.0f} tok/s, "
                f"{dt*1e3:.0f} ms/step, "
                f"{jax.devices()[0].device_kind})",
        "vs_baseline": round(mfu / 0.40, 3),
    }))


if __name__ == "__main__":
    main()
