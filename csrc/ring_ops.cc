#include "ring_ops.h"

#include <algorithm>
#include <cstring>

#include "half.h"
#include "wire.h"

namespace hvdtpu {

namespace {

template <typename T, typename Acc = T>
void ReduceTyped(T* dst, const T* src, int64_t count, ReduceOp op) {
  switch (op) {
    case ReduceOp::AVERAGE:  // accumulate as sum; caller scales
    case ReduceOp::SUM:
    case ReduceOp::ADASUM:  // Adasum blending handled above this layer
      for (int64_t i = 0; i < count; i++) {
        dst[i] = (T)((Acc)dst[i] + (Acc)src[i]);
      }
      break;
    case ReduceOp::MIN:
      for (int64_t i = 0; i < count; i++) dst[i] = std::min(dst[i], src[i]);
      break;
    case ReduceOp::MAX:
      for (int64_t i = 0; i < count; i++) dst[i] = std::max(dst[i], src[i]);
      break;
    case ReduceOp::PRODUCT:
      for (int64_t i = 0; i < count; i++) {
        dst[i] = (T)((Acc)dst[i] * (Acc)src[i]);
      }
      break;
  }
}

template <uint16_t (*ToBits)(float), float (*FromBits)(uint16_t)>
void ReduceHalfLike(uint16_t* dst, const uint16_t* src, int64_t count,
                    ReduceOp op) {
  for (int64_t i = 0; i < count; i++) {
    float a = FromBits(dst[i]);
    float b = FromBits(src[i]);
    float r;
    switch (op) {
      case ReduceOp::MIN: r = std::min(a, b); break;
      case ReduceOp::MAX: r = std::max(a, b); break;
      case ReduceOp::PRODUCT: r = a * b; break;
      default: r = a + b; break;
    }
    dst[i] = ToBits(r);
  }
}

}  // namespace

void ReduceInto(void* dst, const void* src, int64_t count, DataType dt,
                ReduceOp op) {
  switch (dt) {
    case DataType::HVDTPU_UINT8:
      ReduceTyped((uint8_t*)dst, (const uint8_t*)src, count, op);
      break;
    case DataType::HVDTPU_INT8:
      ReduceTyped((int8_t*)dst, (const int8_t*)src, count, op);
      break;
    case DataType::HVDTPU_INT32:
      ReduceTyped((int32_t*)dst, (const int32_t*)src, count, op);
      break;
    case DataType::HVDTPU_INT64:
      ReduceTyped((int64_t*)dst, (const int64_t*)src, count, op);
      break;
    case DataType::HVDTPU_FLOAT16:
      ReduceHalfLike<FloatToHalfBits, HalfBitsToFloat>(
          (uint16_t*)dst, (const uint16_t*)src, count, op);
      break;
    case DataType::HVDTPU_BFLOAT16:
      ReduceHalfLike<FloatToBF16Bits, BF16BitsToFloat>(
          (uint16_t*)dst, (const uint16_t*)src, count, op);
      break;
    case DataType::HVDTPU_FLOAT32:
      ReduceTyped((float*)dst, (const float*)src, count, op);
      break;
    case DataType::HVDTPU_FLOAT64:
      ReduceTyped((double*)dst, (const double*)src, count, op);
      break;
    case DataType::HVDTPU_BOOL: {
      // bool: SUM/PRODUCT behave as OR/AND (matches logical expectations).
      auto* d = (uint8_t*)dst;
      auto* s = (const uint8_t*)src;
      for (int64_t i = 0; i < count; i++) {
        switch (op) {
          case ReduceOp::MIN:
          case ReduceOp::PRODUCT: d[i] = d[i] && s[i]; break;
          default: d[i] = d[i] || s[i]; break;
        }
      }
      break;
    }
    case DataType::HVDTPU_UINT16:
      ReduceTyped((uint16_t*)dst, (const uint16_t*)src, count, op);
      break;
  }
}

void ScaleBuffer(void* buf, int64_t count, DataType dt, double factor) {
  if (factor == 1.0) return;
  switch (dt) {
    case DataType::HVDTPU_FLOAT32: {
      auto* p = (float*)buf;
      for (int64_t i = 0; i < count; i++) p[i] = (float)(p[i] * factor);
      break;
    }
    case DataType::HVDTPU_FLOAT64: {
      auto* p = (double*)buf;
      for (int64_t i = 0; i < count; i++) p[i] *= factor;
      break;
    }
    case DataType::HVDTPU_FLOAT16: {
      auto* p = (uint16_t*)buf;
      for (int64_t i = 0; i < count; i++) {
        p[i] = FloatToHalfBits((float)(HalfBitsToFloat(p[i]) * factor));
      }
      break;
    }
    case DataType::HVDTPU_BFLOAT16: {
      auto* p = (uint16_t*)buf;
      for (int64_t i = 0; i < count; i++) {
        p[i] = FloatToBF16Bits((float)(BF16BitsToFloat(p[i]) * factor));
      }
      break;
    }
    case DataType::HVDTPU_INT32: {
      auto* p = (int32_t*)buf;
      for (int64_t i = 0; i < count; i++) p[i] = (int32_t)(p[i] * factor);
      break;
    }
    case DataType::HVDTPU_INT64: {
      auto* p = (int64_t*)buf;
      for (int64_t i = 0; i < count; i++) p[i] = (int64_t)(p[i] * factor);
      break;
    }
    default:
      break;  // scaling integral small types is not meaningful
  }
}

DataPlane::DataPlane(int rank, int size, std::vector<int> peer_fds)
    : DataPlane(rank, size, std::move(peer_fds), /*owns_fds=*/true) {}

DataPlane::DataPlane(int rank, int size, std::vector<int> peer_fds,
                     bool owns_fds)
    : rank_(rank), size_(size), peer_fds_(std::move(peer_fds)),
      owns_fds_(owns_fds) {
  global_ranks_.resize(size_);
  for (int i = 0; i < size_; i++) global_ranks_[i] = i;
}

DataPlane::~DataPlane() {
  if (!owns_fds_) return;
  for (int fd : peer_fds_) TcpClose(fd);
}

DataPlane DataPlane::Subset(const std::vector<int32_t>& members) const {
  std::vector<int> fds(members.size(), -1);
  int my_idx = -1;
  for (size_t i = 0; i < members.size(); i++) {
    if (members[i] == rank_) {
      my_idx = (int)i;
    } else {
      fds[i] = peer_fds_[members[i]];
    }
  }
  // All ring algorithms index peer_fds_ by (group-relative) rank, so a
  // remapped fd table + group rank/size is a fully working data plane.
  DataPlane sub(my_idx, (int)members.size(), std::move(fds),
                /*owns_fds=*/false);
  sub.global_ranks_ = members;
  return sub;
}

Status DataPlane::HierarchicalAllreduce(void* buf, int64_t count, DataType dt,
                                        ReduceOp op, int local_size) {
  if (size_ == 1 || count == 0) return Status::OK();
  if (local_size <= 1 || size_ % local_size != 0 ||
      op == ReduceOp::ADASUM) {
    return Allreduce(buf, count, dt, op);
  }
  const int cross_size = size_ / local_size;
  if (cross_size <= 1) return Allreduce(buf, count, dt, op);
  const int local_rank = rank_ % local_size;
  const int node = rank_ / local_size;
  const int64_t elem = DataTypeSize(dt);

  // Local group: the ranks on this node; cross group: same local_rank on
  // every node (host-major layout).
  std::vector<int32_t> local_members(local_size);
  for (int i = 0; i < local_size; i++) {
    local_members[i] = node * local_size + i;
  }
  std::vector<int32_t> cross_members(cross_size);
  for (int k = 0; k < cross_size; k++) {
    cross_members[k] = k * local_size + local_rank;
  }
  DataPlane local = Subset(local_members);
  DataPlane cross = Subset(cross_members);

  // Phase 1: local reduce-scatter — this rank ends with its segment
  // reduced across the node.
  std::vector<int64_t> seg(local_size);
  int64_t q = count / local_size, r = count % local_size;
  for (int i = 0; i < local_size; i++) {
    seg[i] = q + (i < r ? 1 : 0);
  }
  std::vector<uint8_t> my_seg((size_t)(seg[local_rank] * elem));
  Status s = local.ReduceScatterv(buf, my_seg.data(), seg, dt, op,
                                  /*destructive=*/true);
  if (!s.ok()) return s;

  // Phase 2: allreduce the segment across nodes (1/local_size of the
  // payload crosses the node boundary).
  s = cross.Allreduce(my_seg.data(), seg[local_rank], dt, op);
  if (!s.ok()) return s;

  // Phase 3: local allgather of the fully-reduced segments — rank-order
  // concatenation is exactly the original buffer layout.
  std::vector<int64_t> seg_bytes(local_size);
  for (int i = 0; i < local_size; i++) seg_bytes[i] = seg[i] * elem;
  return local.Allgatherv(my_seg.data(), buf, seg_bytes);
}

Status DataPlane::Allreduce(void* buf, int64_t count, DataType dt,
                            ReduceOp op) {
  if (size_ == 1 || count == 0) return Status::OK();
  if (op == ReduceOp::ADASUM) return AdasumAllreduce(buf, count, dt);
  const int64_t elem = DataTypeSize(dt);
  auto* base = (uint8_t*)buf;
  // Segment the buffer into `size_` near-equal chunks.
  std::vector<int64_t> seg_count(size_), seg_off(size_);
  int64_t q = count / size_, r = count % size_, off = 0;
  for (int i = 0; i < size_; i++) {
    seg_count[i] = q + (i < r ? 1 : 0);
    seg_off[i] = off;
    off += seg_count[i];
  }
  int64_t max_seg_bytes = (q + (r ? 1 : 0)) * elem;
  if ((int64_t)scratch_.size() < max_seg_bytes) scratch_.resize(max_seg_bytes);

  // Phase 1: ring reduce-scatter.
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = (rank_ - step + size_) % size_;
    int recv_seg = (rank_ - step - 1 + size_) % size_;
    Status s = DuplexTransfer(
        right_fd(), base + seg_off[send_seg] * elem, seg_count[send_seg] * elem,
        left_fd(), scratch_.data(), seg_count[recv_seg] * elem);
    if (!s.ok()) return s;
    ReduceInto(base + seg_off[recv_seg] * elem, scratch_.data(),
               seg_count[recv_seg], dt, op);
  }
  // Phase 2: ring allgather of the reduced segments.
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = (rank_ - step + 1 + size_) % size_;
    int recv_seg = (rank_ - step + size_) % size_;
    Status s = DuplexTransfer(
        right_fd(), base + seg_off[send_seg] * elem, seg_count[send_seg] * elem,
        left_fd(), base + seg_off[recv_seg] * elem, seg_count[recv_seg] * elem);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status DataPlane::Allgatherv(const void* input, void* output,
                             const std::vector<int64_t>& bytes_per_rank) {
  auto* out = (uint8_t*)output;
  std::vector<int64_t> offs(size_);
  int64_t off = 0;
  for (int i = 0; i < size_; i++) {
    offs[i] = off;
    off += bytes_per_rank[i];
  }
  std::memcpy(out + offs[rank_], input, (size_t)bytes_per_rank[rank_]);
  if (size_ == 1) return Status::OK();
  for (int step = 0; step < size_ - 1; step++) {
    int send_blk = (rank_ - step + size_) % size_;
    int recv_blk = (rank_ - step - 1 + size_) % size_;
    Status s = DuplexTransfer(right_fd(), out + offs[send_blk],
                              (size_t)bytes_per_rank[send_blk], left_fd(),
                              out + offs[recv_blk],
                              (size_t)bytes_per_rank[recv_blk]);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status DataPlane::Broadcast(void* buf, int64_t bytes, int root) {
  if (size_ == 1 || bytes == 0) return Status::OK();
  // Pipelined ring from root: each rank receives from the left and forwards
  // to the right (unless the right neighbor is the root). Chunked so the
  // pipeline overlaps recv(i) with forward(i-1) via the duplex primitive.
  const int64_t CHUNK = 1 << 20;
  auto* base = (uint8_t*)buf;
  int right = (rank_ + 1) % size_;
  bool is_root = rank_ == root;
  bool forwards = !is_root && right != root;
  int64_t nchunks = (bytes + CHUNK - 1) / CHUNK;
  auto chunk_span = [&](int64_t i, int64_t* off, int64_t* len) {
    *off = i * CHUNK;
    *len = std::min(CHUNK, bytes - *off);
  };
  if (is_root) {
    // Send CHUNK-sized pieces, matching the forwarders' chunked
    // receives: over TCP the stream hides the boundaries, but the
    // external (message) transport requires every send to pair with an
    // equal-length recv.
    for (int64_t i = 0; i < nchunks; i++) {
      int64_t off, len;
      chunk_span(i, &off, &len);
      Status s = SendAll(right_fd(), base + off, (size_t)len);
      if (!s.ok()) return s;
    }
    return Status::OK();
  }
  for (int64_t i = 0; i < nchunks; i++) {
    int64_t off, len;
    chunk_span(i, &off, &len);
    if (forwards && i > 0) {
      int64_t poff, plen;
      chunk_span(i - 1, &poff, &plen);
      Status s = DuplexTransfer(right_fd(), base + poff, (size_t)plen,
                                left_fd(), base + off, (size_t)len);
      if (!s.ok()) return s;
    } else {
      Status s = RecvAll(left_fd(), base + off, (size_t)len);
      if (!s.ok()) return s;
    }
  }
  if (forwards) {
    int64_t off, len;
    chunk_span(nchunks - 1, &off, &len);
    return SendAll(right_fd(), base + off, (size_t)len);
  }
  return Status::OK();
}

Status DataPlane::Alltoallv(const void* input,
                            const std::vector<int64_t>& send_bytes,
                            void* output,
                            const std::vector<int64_t>& recv_bytes) {
  auto* in = (const uint8_t*)input;
  auto* out = (uint8_t*)output;
  std::vector<int64_t> send_off(size_), recv_off(size_);
  int64_t so = 0, ro = 0;
  for (int i = 0; i < size_; i++) {
    send_off[i] = so;
    so += send_bytes[i];
    recv_off[i] = ro;
    ro += recv_bytes[i];
  }
  std::memcpy(out + recv_off[rank_], in + send_off[rank_],
              (size_t)send_bytes[rank_]);
  // Symmetric pairing: in round r, rank i partners with (r - i) mod size —
  // an involution, so each unordered pair {i, j} exchanges exactly once, in
  // round (i + j) mod size.
  for (int round = 0; round < size_; round++) {
    int partner = (round - rank_ + size_) % size_;
    if (partner == rank_) continue;
    int fd = peer_fds_[partner];
    Status s = DuplexTransfer(fd, in + send_off[partner],
                              (size_t)send_bytes[partner], fd,
                              out + recv_off[partner],
                              (size_t)recv_bytes[partner]);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status DataPlane::ReduceScatterv(const void* input, void* output,
                                 const std::vector<int64_t>& elems_per_rank,
                                 DataType dt, ReduceOp op, bool destructive) {
  const int64_t elem = DataTypeSize(dt);
  if (size_ == 1) {
    std::memcpy(output, input, (size_t)(elems_per_rank[0] * elem));
    return Status::OK();
  }
  std::vector<int64_t> seg_off(size_);
  int64_t off = 0, max_seg = 0;
  for (int i = 0; i < size_; i++) {
    seg_off[i] = off;
    off += elems_per_rank[i];
    max_seg = std::max(max_seg, elems_per_rank[i]);
  }
  // Destructive mode clobbers the caller's buffer in place (hierarchical
  // allreduce rewrites it in phase 3 anyway); otherwise work in a
  // private copy so the caller's input is untouched.
  std::vector<uint8_t> work;
  uint8_t* base;
  if (destructive) {
    base = (uint8_t*)const_cast<void*>(input);
  } else {
    work.assign((const uint8_t*)input, (const uint8_t*)input + off * elem);
    base = work.data();
  }
  if ((int64_t)scratch_.size() < max_seg * elem) {
    scratch_.resize((size_t)(max_seg * elem));
  }
  // Segment rotation offset of -1: after size-1 steps the segment that has
  // accumulated all `size` contributions at rank r is exactly segment r.
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = (rank_ - step - 1 + 2 * size_) % size_;
    int recv_seg = (rank_ - step - 2 + 2 * size_) % size_;
    Status s = DuplexTransfer(
        right_fd(), base + seg_off[send_seg] * elem,
        (size_t)(elems_per_rank[send_seg] * elem), left_fd(), scratch_.data(),
        (size_t)(elems_per_rank[recv_seg] * elem));
    if (!s.ok()) return s;
    ReduceInto(base + seg_off[recv_seg] * elem, scratch_.data(),
               elems_per_rank[recv_seg], dt, op);
  }
  std::memcpy(output, base + seg_off[rank_] * elem,
              (size_t)(elems_per_rank[rank_] * elem));
  return Status::OK();
}

Status DataPlane::Barrier() {
  uint8_t token = 1;
  return Allreduce(&token, 1, DataType::HVDTPU_UINT8, ReduceOp::SUM);
}

}  // namespace hvdtpu
