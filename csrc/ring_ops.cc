#include "ring_ops.h"

#include <errno.h>
#include <poll.h>
#include <sys/socket.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <limits>
#include <mutex>
#include <thread>

#include "half.h"
#include "events.h"
#include "metrics.h"
#include "simd.h"
#include "wire.h"

namespace hvdtpu {

namespace {

std::atomic<int64_t> g_ring_chunk_bytes{kDefaultRingChunkBytes};
std::atomic<int> g_wire_codec{0};  // 0 none, 1 bf16, 2 int8
// SIMD toggle (HOROVOD_SIMD): -1 = not yet folded from env.
std::atomic<int> g_simd{-1};

template <typename T, typename Acc = T>
void ReduceTyped(T* dst, const T* src, int64_t count, ReduceOp op) {
  switch (op) {
    case ReduceOp::AVERAGE:  // accumulate as sum; caller scales
    case ReduceOp::SUM:
    case ReduceOp::ADASUM:  // Adasum blending handled above this layer
      for (int64_t i = 0; i < count; i++) {
        dst[i] = (T)((Acc)dst[i] + (Acc)src[i]);
      }
      break;
    case ReduceOp::MIN:
      for (int64_t i = 0; i < count; i++) dst[i] = std::min(dst[i], src[i]);
      break;
    case ReduceOp::MAX:
      for (int64_t i = 0; i < count; i++) dst[i] = std::max(dst[i], src[i]);
      break;
    case ReduceOp::PRODUCT:
      for (int64_t i = 0; i < count; i++) {
        dst[i] = (T)((Acc)dst[i] * (Acc)src[i]);
      }
      break;
  }
}

template <uint16_t (*ToBits)(float), float (*FromBits)(uint16_t)>
void ReduceHalfLike(uint16_t* dst, const uint16_t* src, int64_t count,
                    ReduceOp op) {
  for (int64_t i = 0; i < count; i++) {
    float a = FromBits(dst[i]);
    float b = FromBits(src[i]);
    float r;
    switch (op) {
      case ReduceOp::MIN: r = std::min(a, b); break;
      case ReduceOp::MAX: r = std::max(a, b); break;
      case ReduceOp::PRODUCT: r = a * b; break;
      default: r = a + b; break;
    }
    dst[i] = ToBits(r);
  }
}

template <uint16_t (*ToBits)(float), float (*FromBits)(uint16_t)>
void ScaleHalfLike(uint16_t* p, int64_t count, double factor) {
  // Blocked decode -> scale -> encode through an f32 staging array: the
  // three narrow loops vectorize, where the old fused per-element loop
  // serialized a decode/multiply/encode dependency chain per lane.
  // Values are bit-identical to the fused form (decode is exact, one
  // f32-rounded multiply, one encode rounding).
  constexpr int64_t kBlock = 256;
  float tmp[kBlock];
  for (int64_t i = 0; i < count; i += kBlock) {
    int64_t n = std::min(kBlock, count - i);
    for (int64_t j = 0; j < n; j++) tmp[j] = FromBits(p[i + j]);
    for (int64_t j = 0; j < n; j++) tmp[j] = (float)(tmp[j] * factor);
    for (int64_t j = 0; j < n; j++) p[i + j] = ToBits(tmp[j]);
  }
}

// Identical clamped chunk spans over the two directions of one hop:
// fn(i, soff, slen, roff, rlen) per chunk index, offsets/lengths in
// the caller's units. Both ends of a hop share the segment lengths,
// so this span table IS the external transport's message framing —
// every chunked path must slice through here.
template <typename Fn>
Status ForEachChunkSpan(int64_t send_len, int64_t recv_len, int64_t chunk,
                        Fn&& fn) {
  const int64_t nchunks = (std::max(send_len, recv_len) + chunk - 1) / chunk;
  for (int64_t i = 0; i < nchunks; i++) {
    int64_t soff = std::min(i * chunk, send_len);
    int64_t slen = std::min(chunk, send_len - soff);
    int64_t roff = std::min(i * chunk, recv_len);
    int64_t rlen = std::min(chunk, recv_len - roff);
    Status s = fn(i, soff, slen, roff, rlen);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

}  // namespace

int64_t RingChunkBytes() {
  return g_ring_chunk_bytes.load(std::memory_order_relaxed);
}

void SetRingChunkBytes(int64_t bytes) {
  g_ring_chunk_bytes.store(bytes, std::memory_order_relaxed);
}

bool WireCompression() { return WireCodec() != 0; }

void SetWireCompression(bool on) { SetWireCodec(on ? 1 : 0); }

int WireCodec() { return g_wire_codec.load(std::memory_order_relaxed); }

void SetWireCodec(int mode) {
  if (mode < 0 || mode > 2) mode = 0;
  g_wire_codec.store(mode, std::memory_order_relaxed);
}

bool SimdEnabled() {
  int v = g_simd.load(std::memory_order_relaxed);
  if (v == -1) {
    // Lazy env fold, same pattern as the wire knobs: valid pre-init
    // (the selftests run without a controller). Unparseable values
    // keep the default (ON) — strtoll's 0-on-garbage must not turn
    // "HOROVOD_SIMD=true" into a silent scalar downgrade.
    v = 1;
    const char* env = std::getenv("HOROVOD_SIMD");
    if (env != nullptr) {
      char* end = nullptr;
      long long parsed = std::strtoll(env, &end, 10);
      if (end != env) v = parsed != 0 ? 1 : 0;
    }
    g_simd.store(v, std::memory_order_relaxed);
  }
  return v != 0;
}

void SetSimdEnabled(bool on) {
  g_simd.store(on ? 1 : 0, std::memory_order_relaxed);
}

// ---- bf16 wire codec (compressed allreduce) --------------------------
// SIMD-dispatched (simd.h); the scalar branches are the bit-identity
// reference the HOROVOD_SIMD=0 escape hatch and the selftest pin run.

void EncodeBF16(uint16_t* dst, const float* src, int64_t n) {
  if (SimdEnabled()) {
    simd::EncodeBF16(dst, src, n);
    return;
  }
  for (int64_t i = 0; i < n; i++) dst[i] = FloatToBF16Bits(src[i]);
}

void DecodeAccumBF16(float* dst, const uint16_t* src, int64_t n) {
  // Full-precision accumulation: the bf16 hop payload is widened back
  // to f32 before the add, so only the WIRE is narrow (EQuARX recipe).
  if (SimdEnabled()) {
    simd::DecodeAccumBF16(dst, src, n);
    return;
  }
  for (int64_t i = 0; i < n; i++) dst[i] += BF16BitsToFloat(src[i]);
}

void DecodeScaleBF16(float* dst, const uint16_t* src, int64_t n,
                     double post) {
  if (SimdEnabled()) {
    simd::DecodeScaleBF16(dst, src, n, post);
    return;
  }
  if (post == 1.0) {
    for (int64_t i = 0; i < n; i++) dst[i] = BF16BitsToFloat(src[i]);
  } else {
    // Same rounding as ScaleBuffer's f32 case (double multiply, one
    // f32 cast) so folding the postscale here is bit-identical to
    // scaling after the decode — it only saves the extra memory pass.
    for (int64_t i = 0; i < n; i++) {
      dst[i] = (float)((double)BF16BitsToFloat(src[i]) * post);
    }
  }
}

// ---- int8 blockwise-scaled wire codec (EQuARX, arXiv:2506.17615) -----
// Wire image: [f32 scale LE | B int8 quants] per block of
// B = kInt8CodecBlock elems (tail block holds the remainder). One
// scale per block keeps the quantization range local (a single hot
// gradient cannot wash out a whole segment), decode accumulates in
// f32, and — like the bf16 codec — the allgather phase forwards the
// wire image verbatim, so every rank decodes the SAME bits and results
// stay rank-consistent bitwise.

int64_t Int8WireLen(int64_t n) {
  if (n <= 0) return 0;
  const int64_t blocks = (n + kInt8CodecBlock - 1) / kInt8CodecBlock;
  return blocks * 4 + n;
}

void EncodeInt8(uint8_t* dst, const float* src, int64_t n) {
  for (int64_t b = 0; b < n; b += kInt8CodecBlock) {
    const int64_t m = std::min(kInt8CodecBlock, n - b);
    float amax = 0.0f;
    bool finite = true;
    for (int64_t i = 0; i < m; i++) {
      finite = finite && std::isfinite(src[b + i]);
      amax = std::max(amax, std::fabs(src[b + i]));
    }
    if (!finite) {
      // A non-finite input must POISON the block, not quantize to a
      // clean-looking number (a NaN gradient mapping to -128*scale
      // would dodge every divergence tripwire; casting a NaN float to
      // int8 is UB besides). NaN scale + zero quants decode to NaN
      // for the whole block — deterministic on every rank, like the
      // bf16 codec's NaN propagation at block granularity.
      const float scale = std::numeric_limits<float>::quiet_NaN();
      std::memcpy(dst, &scale, 4);
      dst += 4;
      std::memset(dst, 0, (size_t)m);
      dst += m;
      continue;
    }
    // amax == 0 degrades to scale 1: all-zero quants, no divide by
    // zero; the deterministic choice every rank reproduces. The
    // FLT_MIN floor keeps an all-denormal block's scale from
    // underflowing amax/127 to 0.0f — 0/0 would be NaN and the int8
    // cast UB, with target-dependent wire bytes.
    const float scale =
        amax > 0.0f
            ? std::max(amax / 127.0f, std::numeric_limits<float>::min())
            : 1.0f;
    std::memcpy(dst, &scale, 4);
    dst += 4;
    for (int64_t i = 0; i < m; i++) {
      float q = std::nearbyintf(src[b + i] / scale);
      if (q > 127.0f) q = 127.0f;
      if (q < -127.0f) q = -127.0f;
      *dst++ = (uint8_t)(int8_t)q;
    }
  }
}

namespace {
// Shared record walk for the two span decoders: `fn(elem_idx, scale,
// quant)` per element of each whole record in [woff, woff + wlen).
template <typename Fn>
void ForEachInt8Record(const uint8_t* wire, int64_t woff, int64_t wlen,
                       int64_t seg_elems, Fn&& fn) {
  const int64_t rec = 4 + kInt8CodecBlock;
  int64_t block = woff / rec;   // records before the tail are full
  const uint8_t* p = wire + woff;
  const uint8_t* end = wire + woff + wlen;
  while (p < end) {
    const int64_t e0 = block * kInt8CodecBlock;
    const int64_t m = std::min(kInt8CodecBlock, seg_elems - e0);
    float scale;
    std::memcpy(&scale, p, 4);
    p += 4;
    for (int64_t i = 0; i < m; i++) {
      fn(e0 + i, scale, (int8_t)p[i]);
    }
    p += m;
    block++;
  }
}
}  // namespace

void DecodeAccumInt8Span(float* dst, const uint8_t* wire, int64_t woff,
                         int64_t wlen, int64_t seg_elems) {
  ForEachInt8Record(wire, woff, wlen, seg_elems,
                    [dst](int64_t e, float scale, int8_t q) {
                      dst[e] += scale * (float)q;
                    });
}

void DecodeScaleInt8Span(float* dst, const uint8_t* wire, int64_t woff,
                         int64_t wlen, int64_t seg_elems, double post) {
  if (post == 1.0) {
    ForEachInt8Record(wire, woff, wlen, seg_elems,
                      [dst](int64_t e, float scale, int8_t q) {
                        dst[e] = scale * (float)q;
                      });
  } else {
    ForEachInt8Record(wire, woff, wlen, seg_elems,
                      [dst, post](int64_t e, float scale, int8_t q) {
                        dst[e] =
                            (float)((double)(scale * (float)q) * post);
                      });
  }
}

// Overlap worker: one thread, FIFO tasks, started lazily on first
// Submit so planes that never run a chunked reduce cost nothing. The
// transfer threads own the transport (wire.h contract); the worker
// only touches host memory (ReduceInto / codec decode), and every
// public collective drains the queue before returning, so no task
// outlives the buffers it references.
class ReduceWorker {
 public:
  ~ReduceWorker() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    if (thread_.joinable()) thread_.join();
  }

  void Submit(std::function<void()> fn) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!thread_.joinable()) thread_ = std::thread(&ReduceWorker::Loop, this);
    tasks_.push_back(std::move(fn));
    pending_++;
    cv_.notify_one();
  }

  void Drain() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this] { return pending_ == 0; });
  }

 private:
  void Loop() {
    std::unique_lock<std::mutex> lk(mu_);
    while (true) {
      cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
      while (!tasks_.empty()) {
        std::function<void()> fn = std::move(tasks_.front());
        tasks_.pop_front();
        lk.unlock();
        fn();
        lk.lock();
        pending_--;
        if (pending_ == 0) done_cv_.notify_all();
      }
      if (stop_) return;
    }
  }

  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  std::deque<std::function<void()>> tasks_;
  int pending_ = 0;  // queued + running
  bool stop_ = false;
  std::thread thread_;
};

// One ReduceWorker per stripe channel (ring_ops.h): chunk i % K
// reduces on worker i % K, so reduction parallelism tracks the stripe
// width. Threads start lazily per worker; DrainAll on idle workers is
// free (pending == 0 returns immediately).
class WorkerPool {
 public:
  void Submit(int channel, std::function<void()> fn) {
    workers_[channel % kMaxWireChannels].Submit(std::move(fn));
  }
  void DrainAll() {
    for (auto& w : workers_) w.Drain();
  }

 private:
  ReduceWorker workers_[kMaxWireChannels];
};

namespace {

// Run one striped transfer as a set of concurrent legs: leg 0 on the
// caller thread, the rest on transient threads. Each leg owns its fds
// (and, in split mode, its DIRECTION of an fd) exclusively for the
// duration (the wire.h single-caller contract, per fd per direction),
// and every thread joins before return, so no transport state
// outlives the call. The first non-OK status wins, leg order
// (deterministic enough for attribution: all legs fail against the
// same dead peer).
Status RunLegs(int wire_plane, std::vector<std::function<Status()>>& legs) {
  if (legs.empty()) return Status::OK();
  if (legs.size() == 1) return legs[0]();
  std::vector<Status> sts(legs.size(), Status::OK());
  std::vector<std::thread> threads;
  threads.reserve(legs.size() - 1);
  for (size_t i = 1; i < legs.size(); i++) {
    threads.emplace_back([&, i] {
      // kWireChunk events record the plane from a thread_local the
      // caller thread set — replicate it on the leg's thread.
      SetEventWirePlane(wire_plane);
      sts[i] = legs[i]();
      SetEventWirePlane(0);
    });
  }
  sts[0] = legs[0]();
  for (auto& t : threads) t.join();
  for (auto& s : sts) {
    if (!s.ok()) return s;
  }
  return Status::OK();
}

// Build the leg set of one striped hop: per channel, either one
// duplex leg (CRC mode — acks ride the data socket's reverse
// direction, so ONE reader must own each fd), or a send-only and a
// recv-only leg on separate threads (plain mode; the two directions
// are independent byte streams even when they share one socket at
// N=2, and splitting them doubles the user<->kernel copy parallelism
// per channel — the loopback bottleneck in practice). `send_fd_of` /
// `recv_fd_of` map a channel to its fds; `on_chunk` fires on the leg
// that received the chunk.
void BuildStripedLegs(
    int stripe_k, const std::function<int(int)>& send_fd_of,
    const void* send_buf, size_t send_len,
    const std::function<int(int)>& recv_fd_of, void* recv_buf,
    size_t recv_len, size_t chunk,
    const std::function<void(size_t off, size_t len, int c)>& on_chunk,
    std::vector<std::function<Status()>>* legs) {
  const bool crc = WireCrc();
  for (int c = 0; c < stripe_k; c++) {
    const int sfd = send_fd_of(c);
    const int rfd = recv_fd_of(c);
    // Split only when the lane's two directions ride DIFFERENT fds:
    // CRC needs one reader per fd (ack demux), and a shared fd (the
    // size-2 ring / alltoall self-pair below the paired-plan width)
    // must keep ONE leg — two legs' ScopedNonblock guards would race
    // the fd's fcntl flags (the finisher restores blocking mode under
    // the still-running leg, and the wire deadline stops firing).
    const bool split = !crc && sfd != rfd;
    auto chunk_cb = on_chunk
                        ? std::function<void(size_t, size_t)>(
                              [on_chunk, c](size_t off, size_t len) {
                                on_chunk(off, len, c);
                              })
                        : std::function<void(size_t, size_t)>();
    if (split) {
      // Recv legs first: they carry the reduce callbacks and finish
      // last — the caller thread should drive one of them.
      if (recv_len > 0) {
        legs->push_back([=] {
          return DuplexTransferStriped(-1, nullptr, 0, rfd, recv_buf,
                                       recv_len, chunk, stripe_k, c,
                                       chunk_cb);
        });
      }
      if (send_len > 0) {
        legs->push_back([=] {
          return DuplexTransferStriped(sfd, send_buf, send_len, -1,
                                       nullptr, 0, chunk, stripe_k, c,
                                       nullptr);
        });
      }
    } else {
      legs->push_back([=] {
        return DuplexTransferStriped(sfd, send_buf, send_len, rfd,
                                     recv_buf, recv_len, chunk, stripe_k,
                                     c, chunk_cb);
      });
    }
  }
}

// Bytes channel `c` carries of a `total`-byte stream striped at
// `chunk` granularity over `k` channels (the deterministic schedule
// both ends derive) — the per-channel wire accounting the stripe
// imbalance view reads.
int64_t StripeShareBytes(int64_t total, int64_t chunk, int k, int c) {
  if (total <= 0) return 0;
  if (k <= 1 || chunk <= 0) return c == 0 ? total : 0;
  int64_t share = 0;
  const int64_t nchunks = (total + chunk - 1) / chunk;
  for (int64_t i = c; i < nchunks; i += k) {
    share += std::min(chunk, total - i * chunk);
  }
  return share;
}

}  // namespace

// Per-collective wire accounting, flushed into the metrics registry on
// scope exit (error paths included): `tx/rx` are bytes that actually
// crossed the transport, `*_logical` what they would be at full tensor
// width — the pair the wire-vs-logical reconciliation in telemetry
// reads (compression_ratio = tx / tx_logical).
struct DataPlane::WireTally {
  int plane = 0;  // 0 intra/flat, 1 cross-slice (set from wire_plane_)
  int channels = 1;  // widest stripe this collective ran (span tag)
  int64_t tx = 0, rx = 0, tx_logical = 0, rx_logical = 0;
  // Per-stripe-channel wire bytes (chunk schedule share): channel 0
  // also books every unstriped path, so the channel buckets always sum
  // to tx/rx exactly — the reconciliation that makes a dead or slow
  // channel VISIBLE instead of averaged away.
  int64_t chan_tx[kMaxWireChannels] = {0};
  int64_t chan_rx[kMaxWireChannels] = {0};
  int64_t start_us = MetricsNowUs();

  // Book one hop's wire + logical bytes, splitting the wire bytes over
  // the hop's stripe schedule onto the PHYSICAL channels each lane
  // rides (the parity-split pairwise plan maps lane i to channel
  // 2i + parity; everything else is identity — DataPlane::HopStripe).
  void BookTx(int64_t wire, int64_t logical, int64_t chunk,
              const DataPlane::HopStripe& h) {
    tx += wire;
    tx_logical += logical;
    for (int i = 0; i < h.width; i++) {
      int phys = h.tx_chan(i);
      if (phys >= kMaxWireChannels) continue;
      if (phys + 1 > channels) channels = phys + 1;
      chan_tx[phys] += StripeShareBytes(wire, chunk, h.width, i);
    }
  }
  void BookRx(int64_t wire, int64_t logical, int64_t chunk,
              const DataPlane::HopStripe& h) {
    rx += wire;
    rx_logical += logical;
    for (int i = 0; i < h.width; i++) {
      int phys = h.rx_chan(i);
      if (phys >= kMaxWireChannels) continue;
      if (phys + 1 > channels) channels = phys + 1;
      chan_rx[phys] += StripeShareBytes(wire, chunk, h.width, i);
    }
  }

  ~WireTally() {
    // Restore the default plane tag for whatever the thread runs next
    // (the hierarchical engine nests intra/cross tallies).
    SetEventWirePlane(0);
    if (tx || rx || tx_logical || rx_logical) {
      GlobalMetrics().AccountWire(plane, tx, rx, tx_logical, rx_logical);
      GlobalMetrics().AccountWireChannels(chan_tx, chan_rx);
      int64_t end_us = MetricsNowUs();
      // The span interval feeds the per-step overlap ledger — the SAME
      // [start,end) the kWireSpan event encodes, so the ledger and the
      // flight recorder can never disagree about what the wire did.
      // Spans stay CHANNEL-MERGED (one span per collective, stripe
      // width in the high bits of the plane arg): the ledger's
      // exposed/hidden math wants wall intervals, not per-socket ones.
      GlobalLedger().AddSpan(plane, start_us, end_us);
      GlobalEvents().Record(
          EventType::kWireSpan, (int32_t)(plane | (channels << 1)),
          (int32_t)std::min<int64_t>(end_us - start_us, INT32_MAX), tx,
          rx);
    }
  }
};

void ReduceInto(void* dst, const void* src, int64_t count, DataType dt,
                ReduceOp op) {
  switch (dt) {
    case DataType::HVDTPU_UINT8:
      ReduceTyped((uint8_t*)dst, (const uint8_t*)src, count, op);
      break;
    case DataType::HVDTPU_INT8:
      ReduceTyped((int8_t*)dst, (const int8_t*)src, count, op);
      break;
    case DataType::HVDTPU_INT32:
      ReduceTyped((int32_t*)dst, (const int32_t*)src, count, op);
      break;
    case DataType::HVDTPU_INT64:
      ReduceTyped((int64_t*)dst, (const int64_t*)src, count, op);
      break;
    case DataType::HVDTPU_FLOAT16:
      ReduceHalfLike<FloatToHalfBits, HalfBitsToFloat>(
          (uint16_t*)dst, (const uint16_t*)src, count, op);
      break;
    case DataType::HVDTPU_BFLOAT16:
      // SUM-family bf16 takes the vectorized decode-add-encode path
      // (bit-identical to ReduceHalfLike's sequence, pinned by
      // hvdtpu_simd_selftest); MIN/MAX/PRODUCT stay scalar.
      if ((op == ReduceOp::SUM || op == ReduceOp::AVERAGE ||
           op == ReduceOp::ADASUM) &&
          SimdEnabled()) {
        simd::ReduceSumBF16((uint16_t*)dst, (const uint16_t*)src, count);
        break;
      }
      ReduceHalfLike<FloatToBF16Bits, BF16BitsToFloat>(
          (uint16_t*)dst, (const uint16_t*)src, count, op);
      break;
    case DataType::HVDTPU_FLOAT32:
      if ((op == ReduceOp::SUM || op == ReduceOp::AVERAGE ||
           op == ReduceOp::ADASUM) &&
          SimdEnabled()) {
        simd::AddF32((float*)dst, (const float*)src, count);
        break;
      }
      ReduceTyped((float*)dst, (const float*)src, count, op);
      break;
    case DataType::HVDTPU_FLOAT64:
      ReduceTyped((double*)dst, (const double*)src, count, op);
      break;
    case DataType::HVDTPU_BOOL: {
      // bool: SUM/PRODUCT behave as OR/AND (matches logical expectations).
      auto* d = (uint8_t*)dst;
      auto* s = (const uint8_t*)src;
      for (int64_t i = 0; i < count; i++) {
        switch (op) {
          case ReduceOp::MIN:
          case ReduceOp::PRODUCT: d[i] = d[i] && s[i]; break;
          default: d[i] = d[i] || s[i]; break;
        }
      }
      break;
    }
    case DataType::HVDTPU_UINT16:
      ReduceTyped((uint16_t*)dst, (const uint16_t*)src, count, op);
      break;
  }
}

void ScaleBuffer(void* buf, int64_t count, DataType dt, double factor) {
  if (factor == 1.0) return;
  switch (dt) {
    case DataType::HVDTPU_FLOAT32: {
      auto* p = (float*)buf;
      if (SimdEnabled()) {
        simd::ScaleF32(p, count, factor);
        break;
      }
      for (int64_t i = 0; i < count; i++) p[i] = (float)(p[i] * factor);
      break;
    }
    case DataType::HVDTPU_FLOAT64: {
      auto* p = (double*)buf;
      for (int64_t i = 0; i < count; i++) p[i] *= factor;
      break;
    }
    case DataType::HVDTPU_FLOAT16:
      ScaleHalfLike<FloatToHalfBits, HalfBitsToFloat>((uint16_t*)buf, count,
                                                      factor);
      break;
    case DataType::HVDTPU_BFLOAT16:
      ScaleHalfLike<FloatToBF16Bits, BF16BitsToFloat>((uint16_t*)buf, count,
                                                      factor);
      break;
    case DataType::HVDTPU_INT32: {
      auto* p = (int32_t*)buf;
      for (int64_t i = 0; i < count; i++) p[i] = (int32_t)(p[i] * factor);
      break;
    }
    case DataType::HVDTPU_INT64: {
      auto* p = (int64_t*)buf;
      for (int64_t i = 0; i < count; i++) p[i] = (int64_t)(p[i] * factor);
      break;
    }
    default:
      break;  // scaling integral small types is not meaningful
  }
}

DataPlane::DataPlane(int rank, int size, std::vector<int> peer_fds)
    : DataPlane(rank, size, std::move(peer_fds), /*owns_fds=*/true) {}

DataPlane::DataPlane(int rank, int size, std::vector<int> peer_fds,
                     bool owns_fds)
    : rank_(rank), size_(size), peer_fds_(std::move(peer_fds)),
      owns_fds_(owns_fds), workers_(std::make_shared<WorkerPool>()) {
  global_ranks_.resize(size_);
  for (int i = 0; i < size_; i++) global_ranks_[i] = i;
  if (owns_fds_) {
    // Peer attribution for wire timeouts/EOF (see wire.h). Subset views
    // share fds the root already registered with GLOBAL ranks. The fd
    // table may be empty (placeholder planes for unknown process sets).
    for (size_t i = 0; i < peer_fds_.size(); i++) {
      if (peer_fds_[i] >= 0) RegisterFdRank(peer_fds_[i], (int)i);
    }
  }
}

void DataPlane::AdoptExtraChannelFds(
    std::vector<std::vector<int>> chan_fds) {
  extra_fds_ = std::move(chan_fds);
  if (owns_fds_) {
    for (size_t c = 0; c < extra_fds_.size(); c++) {
      for (size_t i = 0; i < extra_fds_[c].size(); i++) {
        if (extra_fds_[c][i] >= 0) {
          RegisterFdRank(extra_fds_[c][i], (int)i, (int)c + 1);
        }
      }
    }
  }
}

int DataPlane::ActiveStripe(int64_t chunk_bytes) const {
  // Striping needs chunk framing (the stripe schedule IS chunk
  // round-robin) and real sockets; the external transport's mailbox
  // fds carry no channel id. Rank-uniform: both inputs are
  // (docs/wire.md).
  if (chunk_bytes <= 0 || extra_fds_.empty() ||
      ExternalTransportActive()) {
    return 1;
  }
  int k = (int)WireChannels();
  if (k > channels()) k = channels();
  return k < 1 ? 1 : k;
}

DataPlane::HopStripe DataPlane::StripeFor(int send_peer, int recv_peer,
                                          int64_t chunk_bytes) const {
  HopStripe h;
  const int k = ActiveStripe(chunk_bytes);
  if (k <= 1) return h;
  if (send_peer == recv_peer && k >= 4) {
    // Pairwise hop at k >= 4: direction-split the channels (see
    // ring_ops.h). Group ranks order both ends identically, so the
    // two sides pick opposite parities and each socket carries exactly
    // one direction. At k < 4 the split would leave ONE stream per
    // direction — measurably slower than two duplexed ones — so small
    // widths keep duplex lanes.
    h.paired = true;
    h.width = k / 2;  // the last lane's physical channel is
    h.tx_base = rank_ > send_peer ? 1 : 0;  // k-1 <= channels()-1
    h.rx_base = 1 - h.tx_base;
  } else {
    h.width = k;
  }
  return h;
}

std::vector<int32_t> DataPlane::ProbeDeadPeers() const {
  // The sweep is O(peer fds) of nonblocking poll+MSG_PEEK syscalls —
  // one of the large-world control-plane suspects the per-phase
  // profile tracks (docs/scale.md).
  const int64_t t0 = MetricsNowUs();
  std::vector<int32_t> dead;
  for (int i = 0; i < (int)peer_fds_.size() && i < size_; i++) {
    int fd = peer_fds_[i];
    if (fd < 0 || i == rank_) continue;  // self / external / absent
    pollfd p{};
    p.fd = fd;
    p.events = POLLIN;
    int rc = poll(&p, 1, 0);
    if (rc <= 0) continue;  // no events pending -> no evidence of death
    if (p.revents & (POLLERR | POLLNVAL)) {
      dead.push_back(global_ranks_[i]);
      continue;
    }
    if (p.revents & (POLLIN | POLLHUP)) {
      // Distinguish EOF from pending (stale) ring bytes without
      // consuming them: a live-but-stalled peer's stream peeks > 0.
      char probe;
      ssize_t n = recv(fd, &probe, 1, MSG_PEEK | MSG_DONTWAIT);
      if (n == 0 || (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                     errno != EINTR)) {
        dead.push_back(global_ranks_[i]);
      }
    }
  }
  RecordControlPhase(kPhaseProbeSweep, MetricsNowUs() - t0);
  return dead;
}

DataPlane::~DataPlane() {
  if (!owns_fds_) return;
  for (int fd : peer_fds_) TcpClose(fd);
  for (auto& chan : extra_fds_) {
    for (int fd : chan) TcpClose(fd);
  }
}

DataPlane DataPlane::Subset(const std::vector<int32_t>& members) const {
  std::vector<int> fds(members.size(), -1);
  std::vector<std::vector<int>> extra(extra_fds_.size(),
                                      std::vector<int>(members.size(), -1));
  int my_idx = -1;
  for (size_t i = 0; i < members.size(); i++) {
    if (members[i] == rank_) {
      my_idx = (int)i;
    } else {
      fds[i] = peer_fds_[members[i]];
      for (size_t c = 0; c < extra_fds_.size(); c++) {
        extra[c][i] = extra_fds_[c][members[i]];
      }
    }
  }
  // All ring algorithms index peer_fds_ by (group-relative) rank, so a
  // remapped fd table + group rank/size is a fully working data plane.
  DataPlane sub(my_idx, (int)members.size(), std::move(fds),
                /*owns_fds=*/false);
  sub.extra_fds_ = std::move(extra);  // shared, like the primary mesh
  sub.global_ranks_ = members;
  // Views inherit the parent's wire plane + compression override;
  // HierarchicalAllreduce re-tags its inter-slice subset explicitly.
  sub.wire_plane_ = wire_plane_;
  sub.force_compression_ = force_compression_;
  // Share the parent's worker pool: per-response subset views never
  // spawn (and tear down) their own reduce threads.
  sub.workers_ = workers_;
  return sub;
}

Status DataPlane::HierarchicalAllreduce(void* buf, int64_t count, DataType dt,
                                        ReduceOp op, int local_size,
                                        double postscale,
                                        bool compress_cross) {
  if (size_ == 1 || count == 0) {
    ScaleBuffer(buf, count, dt, postscale);
    return Status::OK();
  }
  if (local_size <= 1 || size_ % local_size != 0 ||
      op == ReduceOp::ADASUM) {
    return Allreduce(buf, count, dt, op, postscale);
  }
  const int cross_size = size_ / local_size;
  if (cross_size <= 1) return Allreduce(buf, count, dt, op, postscale);
  const int local_rank = rank_ % local_size;
  const int node = rank_ / local_size;
  const int64_t elem = DataTypeSize(dt);

  // Local group: the ranks on this slice; cross group: same local_rank
  // on every slice (host-major layout). The cross subset is the
  // CROSS-PLANE hop: its wire bytes are booked under the cross
  // counters, and `compress_cross` puts the bf16 codec on it alone.
  std::vector<int32_t> local_members(local_size);
  for (int i = 0; i < local_size; i++) {
    local_members[i] = node * local_size + i;
  }
  std::vector<int32_t> cross_members(cross_size);
  for (int k = 0; k < cross_size; k++) {
    cross_members[k] = k * local_size + local_rank;
  }
  DataPlane local = Subset(local_members);
  DataPlane cross = Subset(cross_members);
  cross.set_wire_plane(1);
  if (compress_cross) cross.set_force_compression(true);

  // Phase 1: local reduce-scatter — this rank ends with its segment
  // reduced across the node.
  std::vector<int64_t> seg(local_size);
  int64_t q = count / local_size, r = count % local_size;
  for (int i = 0; i < local_size; i++) {
    seg[i] = q + (i < r ? 1 : 0);
  }
  std::vector<uint8_t> my_seg((size_t)(seg[local_rank] * elem));
  Status s = local.ReduceScatterv(buf, my_seg.data(), seg, dt, op,
                                  /*destructive=*/true);
  if (!s.ok()) return s;

  // Phase 2: allreduce the segment across nodes (1/local_size of the
  // payload crosses the node boundary). The postscale rides here: each
  // element passes through exactly one cross-allreduce, so it is
  // applied exactly once before the allgather distributes it.
  s = cross.Allreduce(my_seg.data(), seg[local_rank], dt, op, postscale);
  if (!s.ok()) return s;

  // Phase 3: local allgather of the fully-reduced segments — rank-order
  // concatenation is exactly the original buffer layout.
  std::vector<int64_t> seg_bytes(local_size);
  for (int i = 0; i < local_size; i++) seg_bytes[i] = seg[i] * elem;
  return local.Allgatherv(my_seg.data(), buf, seg_bytes);
}

Status DataPlane::PipelinedReduceChunks(int send_peer, const uint8_t* send_buf,
                                        int64_t send_bytes, int recv_peer,
                                        uint8_t* reduce_dst,
                                        int64_t recv_count, DataType dt,
                                        ReduceOp op, int64_t chunk_bytes,
                                        WireTally* tally) {
  const int64_t elem = DataTypeSize(dt);
  const int64_t recv_bytes = recv_count * elem;
  const int send_fd = peer_fd(0, send_peer);
  const int recv_fd = peer_fd(0, recv_peer);
  if (chunk_bytes <= 0 ||
      (send_bytes <= chunk_bytes && recv_bytes <= chunk_bytes)) {
    // Bulk path: one whole-segment transfer, then a serial reduce —
    // same framing and bit-identical results as the pre-chunking ring.
    tally->BookTx(send_bytes, send_bytes, 0, HopStripe{});
    tally->BookRx(recv_bytes, recv_bytes, 0, HopStripe{});
    if ((int64_t)scratch_.size() < recv_bytes) scratch_.resize(recv_bytes);
    Status s = DuplexTransfer(send_fd, send_buf, (size_t)send_bytes, recv_fd,
                              scratch_.data(), (size_t)recv_bytes);
    if (!s.ok()) return s;
    ReduceInto(reduce_dst, scratch_.data(), recv_count, dt, op);
    return Status::OK();
  }
  // Chunk on element boundaries (ReduceInto takes whole elements).
  const int64_t chunk_elems = std::max<int64_t>(chunk_bytes / elem, 1);
  const int64_t cbytes = chunk_elems * elem;
  if (!IsExtFd(send_fd) && !IsExtFd(recv_fd)) {
    // TCP: ONE continuous duplex per stripe lane for the whole
    // segment — each lane's send streams with no per-chunk lockstep
    // (chunk i rides lane i % width; the K=1 stream is byte-identical
    // to the pre-striping engine), while every completed recv chunk
    // fires a ReduceInto on ITS LANE's worker, overlapping reduction
    // with the rest of the transfer at stripe parallelism.
    const HopStripe hop = StripeFor(send_peer, recv_peer, chunk_bytes);
    tally->BookTx(send_bytes, send_bytes, cbytes, hop);
    tally->BookRx(recv_bytes, recv_bytes, cbytes, hop);
    if ((int64_t)scratch_.size() < recv_bytes) scratch_.resize(recv_bytes);
    uint8_t* rbuf = scratch_.data();
    std::vector<std::function<Status()>> legs;
    BuildStripedLegs(
        hop.width,
        [&](int i) { return peer_fd(hop.tx_chan(i), send_peer); },
        send_buf, (size_t)send_bytes,
        [&](int i) { return peer_fd(hop.rx_chan(i), recv_peer); }, rbuf,
        (size_t)recv_bytes, (size_t)cbytes,
        [&](size_t off, size_t len, int c) {
          uint8_t* dst = reduce_dst + off;
          const uint8_t* src = rbuf + off;
          const int64_t n = (int64_t)len / elem;
          workers_->Submit(
              c, [dst, src, n, dt, op] { ReduceInto(dst, src, n, dt, op); });
        },
        &legs);
    Status s = RunLegs(wire_plane_, legs);
    workers_->DrainAll();  // the segment is fully reduced before the
    return s;              // caller forwards it on the next ring step
  }
  // External (message) transport: the mailbox preserves boundaries, so
  // both ends cut identical chunk spans into equal-length paired
  // messages, double-buffered so the reduce of chunk i-1 overlaps the
  // exchange of chunk i. Never striped (ActiveStripe == 1 there).
  tally->BookTx(send_bytes, send_bytes, 0, HopStripe{});
  tally->BookRx(recv_bytes, recv_bytes, 0, HopStripe{});
  if ((int64_t)chunk_scratch_.size() < 2 * cbytes) {
    chunk_scratch_.resize((size_t)(2 * cbytes));
  }
  Status s = ForEachChunkSpan(
      send_bytes, recv_bytes, cbytes,
      [&](int64_t i, int64_t soff, int64_t slen, int64_t roff,
          int64_t rlen) {
        uint8_t* rscratch = chunk_scratch_.data() + (i & 1) * cbytes;
        // While this transfer runs, the worker reduces chunk i-1
        // (submitted below last iteration) out of the other half.
        Status t = DuplexTransfer(send_fd, send_buf + soff, (size_t)slen,
                                  recv_fd, rscratch, (size_t)rlen);
        workers_->DrainAll();  // chunk i-1 reduced; its half is free
        if (!t.ok()) return t;
        if (rlen > 0) {
          uint8_t* dst = reduce_dst + roff;
          const int64_t n = rlen / elem;
          workers_->Submit(0, [dst, rscratch, n, dt, op] {
            ReduceInto(dst, rscratch, n, dt, op);
          });
        }
        return Status::OK();
      });
  workers_->DrainAll();
  return s;
}

Status DataPlane::ChunkedDuplex(int send_peer, const uint8_t* send_buf,
                                int64_t send_bytes, int recv_peer,
                                uint8_t* recv_buf, int64_t recv_bytes,
                                int64_t chunk_bytes, WireTally* tally) {
  const int send_fd = peer_fd(0, send_peer);
  const int recv_fd = peer_fd(0, recv_peer);
  const bool tcp = !IsExtFd(send_fd) && !IsExtFd(recv_fd);
  const bool small =
      send_bytes <= chunk_bytes && recv_bytes <= chunk_bytes;
  const HopStripe hop = small || !tcp
                            ? HopStripe{}
                            : StripeFor(send_peer, recv_peer, chunk_bytes);
  if (hop.width > 1 || hop.paired) {
    // No reduction to overlap, but the stripe lanes (x2 direction
    // legs) multiply the raw socket parallelism — the allgather phase
    // is pure wire time.
    tally->BookTx(send_bytes, send_bytes, chunk_bytes, hop);
    tally->BookRx(recv_bytes, recv_bytes, chunk_bytes, hop);
    std::vector<std::function<Status()>> legs;
    BuildStripedLegs(
        hop.width,
        [&](int i) { return peer_fd(hop.tx_chan(i), send_peer); },
        send_buf, (size_t)send_bytes,
        [&](int i) { return peer_fd(hop.rx_chan(i), recv_peer); },
        recv_buf, (size_t)recv_bytes, (size_t)chunk_bytes, nullptr,
        &legs);
    return RunLegs(wire_plane_, legs);
  }
  tally->BookTx(send_bytes, send_bytes, 0, HopStripe{});
  tally->BookRx(recv_bytes, recv_bytes, 0, HopStripe{});
  // Single channel: the knob only matters where the transport frames
  // messages — on TCP the byte stream hides chunk boundaries and one
  // duplex is strictly cheaper.
  if (chunk_bytes <= 0 ||
      (send_bytes <= chunk_bytes && recv_bytes <= chunk_bytes) || tcp) {
    return DuplexTransfer(send_fd, send_buf, (size_t)send_bytes, recv_fd,
                          recv_buf, (size_t)recv_bytes);
  }
  return ForEachChunkSpan(
      send_bytes, recv_bytes, chunk_bytes,
      [&](int64_t, int64_t soff, int64_t slen, int64_t roff, int64_t rlen) {
        return DuplexTransfer(send_fd, send_buf + soff, (size_t)slen,
                              recv_fd, recv_buf + roff, (size_t)rlen);
      });
}

Status DataPlane::CompressedReducePhase(
    float* base, const std::vector<int64_t>& seg_count,
    const std::vector<int64_t>& seg_off, int64_t chunk_elems, int rot,
    int codec, WireTally* tally) {
  int64_t max_seg = 0;
  for (int i = 0; i < size_; i++) max_seg = std::max(max_seg, seg_count[i]);
  const bool tcp = !IsExtFd(right_fd()) && !IsExtFd(left_fd());
  const bool i8 = codec == 2;
  // The int8 image is [scale | block] records: chunk boundaries must
  // cut at record multiples so every wire chunk decodes
  // self-contained (ring_ops.h codec contract).
  if (i8) {
    chunk_elems =
        std::max<int64_t>((chunk_elems / kInt8CodecBlock) * kInt8CodecBlock,
                          kInt8CodecBlock);
  }
  // Wire bytes of an n-elem segment under this codec, and the wire
  // chunk granularity matching `chunk_elems`.
  auto wlen = [&](int64_t n) { return i8 ? Int8WireLen(n) : n * 2; };
  const int64_t wire_chunk = wlen(chunk_elems);
  const int64_t send_scratch = tcp ? wlen(max_seg) : wire_chunk;
  const int64_t recv_scratch = tcp ? wlen(max_seg) : 2 * wire_chunk;
  if ((int64_t)comp_send_scratch_.size() < send_scratch) {
    comp_send_scratch_.resize((size_t)send_scratch);
  }
  if ((int64_t)chunk_scratch_.size() < recv_scratch) {
    chunk_scratch_.resize((size_t)recv_scratch);
  }
  auto encode = [&](uint8_t* dst, const float* src, int64_t n) {
    if (i8) {
      EncodeInt8(dst, src, n);
    } else {
      EncodeBF16((uint16_t*)dst, src, n);
    }
  };
  // N-1 ring reduce steps at rotation `rot`. Each hop ships the
  // current f32 partial narrow; the receiver widens back to f32 and
  // accumulates at full precision, overlapped with the remaining
  // transfer on the per-channel workers.
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = RingSendSegment(rank_, step, size_, rot);
    int recv_seg = RingRecvSegment(rank_, step, size_, rot);
    const float* sbase = base + seg_off[send_seg];
    float* rbase = base + seg_off[recv_seg];
    const int64_t scount = seg_count[send_seg];
    const int64_t rcount = seg_count[recv_seg];
    if (tcp) {
      // Encode the whole outgoing segment once, then stream it —
      // striped over the active channels — while completed recv chunks
      // decode+accumulate on their channel's worker.
      const HopStripe hop =
          StripeFor(right_peer(), left_peer(), wire_chunk);
      tally->BookTx(wlen(scount), scount * 4, wire_chunk, hop);
      tally->BookRx(wlen(rcount), rcount * 4, wire_chunk, hop);
      uint8_t* senc = comp_send_scratch_.data();
      encode(senc, sbase, scount);
      uint8_t* rdec = chunk_scratch_.data();
      std::vector<std::function<Status()>> legs;
      BuildStripedLegs(
          hop.width,
          [&](int i) { return right_fd(hop.tx_chan(i)); }, senc,
          (size_t)wlen(scount),
          [&](int i) { return left_fd(hop.rx_chan(i)); }, rdec,
          (size_t)wlen(rcount), (size_t)wire_chunk,
          [&](size_t off, size_t len, int c) {
            if (i8) {
              workers_->Submit(c, [=] {
                DecodeAccumInt8Span(rbase, rdec, (int64_t)off,
                                    (int64_t)len, rcount);
              });
            } else {
              float* dst = rbase + off / 2;
              const uint16_t* src = (const uint16_t*)rdec + off / 2;
              const int64_t n = (int64_t)len / 2;
              workers_->Submit(
                  c, [dst, src, n] { DecodeAccumBF16(dst, src, n); });
            }
          },
          &legs);
      Status s = RunLegs(wire_plane_, legs);
      workers_->DrainAll();  // next step sends what this accumulated
      if (!s.ok()) return s;
      continue;
    }
    tally->BookTx(wlen(scount), scount * 4, 0, HopStripe{});
    tally->BookRx(wlen(rcount), rcount * 4, 0, HopStripe{});
    Status s = ForEachChunkSpan(
        scount, rcount, chunk_elems,
        [&](int64_t i, int64_t soff, int64_t sn, int64_t roff, int64_t rn) {
          uint8_t* senc = comp_send_scratch_.data();
          encode(senc, sbase + soff, sn);
          uint8_t* rdec = chunk_scratch_.data() + (i & 1) * wire_chunk;
          Status t =
              DuplexTransfer(right_fd(), senc, (size_t)wlen(sn),
                             left_fd(), rdec, (size_t)wlen(rn));
          workers_->DrainAll();  // chunk i-1 accumulated; half is free
          if (!t.ok()) return t;
          if (rn > 0) {
            float* dst = rbase + roff;
            if (i8) {
              workers_->Submit(0, [=] {
                DecodeAccumInt8Span(dst, rdec, 0, wlen(rn), rn);
              });
            } else {
              workers_->Submit(0, [dst, rdec, rn] {
                DecodeAccumBF16(dst, (const uint16_t*)rdec, rn);
              });
            }
          }
          return Status::OK();
        });
    workers_->DrainAll();  // next step sends what this accumulated
    if (!s.ok()) return s;
  }
  return Status::OK();
}

static int64_t CompressedChunkElems(int64_t chunk_bytes,
                                    const std::vector<int64_t>& seg_count) {
  // Chunk in elements derived from the LOGICAL byte knob, so the
  // tunable keeps one meaning whether or not compression is on.
  int64_t max_seg = 0;
  for (int64_t c : seg_count) max_seg = std::max(max_seg, c);
  return chunk_bytes > 0 ? std::max<int64_t>(chunk_bytes / 4, 1)
                         : std::max<int64_t>(max_seg, 1);
}

Status DataPlane::CompressedRingReduceScatter(
    float* base, const std::vector<int64_t>& seg_count,
    const std::vector<int64_t>& seg_off, int64_t chunk_bytes, int codec,
    WireTally* tally) {
  // rot = -1: rank r's fully-accumulated segment is its own segment r —
  // the reduce-scatter output contract (see RingOwnedSegment).
  return CompressedReducePhase(base, seg_count, seg_off,
                               CompressedChunkElems(chunk_bytes, seg_count),
                               /*rot=*/-1, codec, tally);
}

Status DataPlane::CompressedRingAllreduce(
    float* base, const std::vector<int64_t>& seg_count,
    const std::vector<int64_t>& seg_off, double postscale,
    int64_t chunk_bytes, int codec, WireTally* tally) {
  int64_t chunk_elems = CompressedChunkElems(chunk_bytes, seg_count);
  const bool i8 = codec == 2;
  if (i8) {
    chunk_elems =
        std::max<int64_t>((chunk_elems / kInt8CodecBlock) * kInt8CodecBlock,
                          kInt8CodecBlock);
  }
  // Phase 1: ring reduce-scatter (rot = 0 — rank r ends owning segment
  // (r+1)%N, which phase 2 sends first).
  Status ph1 = CompressedReducePhase(base, seg_count, seg_off, chunk_elems,
                                     /*rot=*/0, codec, tally);
  if (!ph1.ok()) return ph1;
  const bool tcp = !IsExtFd(right_fd()) && !IsExtFd(left_fd());
  auto wlen = [&](int64_t n) { return i8 ? Int8WireLen(n) : n * 2; };
  const int64_t wire_chunk = wlen(chunk_elems);
  // Phase 2: ring allgather of the finalized segments, compressed. The
  // narrow wire image is forwarded VERBATIM (no hop re-encodes), and
  // every rank — the owner included — decodes the SAME bits, so the
  // result is rank-consistent: each element is exactly one codec
  // rounding of its full-precision f32 reduction, times the postscale.
  // The plane holds every segment's wire image at its wire offset.
  std::vector<int64_t> woff(size_);
  int64_t wtotal = 0;
  for (int i = 0; i < size_; i++) {
    woff[i] = wtotal;
    wtotal += wlen(seg_count[i]);
  }
  if ((int64_t)comp_plane_.size() < wtotal) {
    comp_plane_.resize((size_t)wtotal);
  }
  uint8_t* comp = comp_plane_.data();
  auto decode_scale = [&](int seg, int64_t off, int64_t len) {
    // Decode `len` wire bytes at wire offset `off` of segment `seg`
    // into its f32 region, with the postscale folded in.
    if (i8) {
      DecodeScaleInt8Span(base + seg_off[seg], comp + woff[seg], off, len,
                          seg_count[seg], postscale);
    } else {
      DecodeScaleBF16(base + seg_off[seg] + off / 2,
                      (const uint16_t*)(comp + woff[seg]) + off / 2,
                      len / 2, postscale);
    }
  };
  // After size-1 reduce-scatter steps the fully-accumulated segment at
  // rank r is (r+1) mod size — exactly the first segment phase 2 sends.
  const int own_seg = RingOwnedSegment(rank_, size_);
  if (i8) {
    EncodeInt8(comp + woff[own_seg], base + seg_off[own_seg],
               seg_count[own_seg]);
  } else {
    EncodeBF16((uint16_t*)(comp + woff[own_seg]), base + seg_off[own_seg],
               seg_count[own_seg]);
  }
  decode_scale(own_seg, 0, wlen(seg_count[own_seg]));
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = RingSendSegment(rank_, step, size_, /*rot=*/1);
    int recv_seg = RingSendSegment(rank_, step, size_, /*rot=*/0);
    const int64_t scount = seg_count[send_seg];
    const int64_t rcount = seg_count[recv_seg];
    // Receive straight into the compressed plane (it is forwarded next
    // step); the f32 decode overlaps the remaining transfer. No
    // per-step drain: every chunk decodes from its own plane region.
    if (tcp) {
      const HopStripe hop =
          StripeFor(right_peer(), left_peer(), wire_chunk);
      tally->BookTx(wlen(scount), scount * 4, wire_chunk, hop);
      tally->BookRx(wlen(rcount), rcount * 4, wire_chunk, hop);
      std::vector<std::function<Status()>> legs;
      BuildStripedLegs(
          hop.width,
          [&](int i) { return right_fd(hop.tx_chan(i)); },
          comp + woff[send_seg], (size_t)wlen(scount),
          [&](int i) { return left_fd(hop.rx_chan(i)); },
          comp + woff[recv_seg], (size_t)wlen(rcount),
          (size_t)wire_chunk,
          [&, recv_seg](size_t off, size_t len, int c) {
            workers_->Submit(c, [=] {
              decode_scale(recv_seg, (int64_t)off, (int64_t)len);
            });
          },
          &legs);
      Status s = RunLegs(wire_plane_, legs);
      if (!s.ok()) {
        workers_->DrainAll();
        return s;
      }
      continue;
    }
    tally->BookTx(wlen(scount), scount * 4, 0, HopStripe{});
    tally->BookRx(wlen(rcount), rcount * 4, 0, HopStripe{});
    Status s = ForEachChunkSpan(
        scount, rcount, chunk_elems,
        [&](int64_t, int64_t soff, int64_t sn, int64_t roff, int64_t rn) {
          // Elem spans map onto the wire image at codec record
          // granularity (chunk_elems is block-aligned under int8, so
          // both offsets are record boundaries).
          const int64_t swoff = i8 ? (soff / kInt8CodecBlock) *
                                         (4 + kInt8CodecBlock)
                                   : soff * 2;
          const int64_t rwoff = i8 ? (roff / kInt8CodecBlock) *
                                         (4 + kInt8CodecBlock)
                                   : roff * 2;
          const int64_t swl = wlen(soff + sn) - wlen(soff);
          const int64_t rwl = wlen(roff + rn) - wlen(roff);
          Status t = DuplexTransfer(
              right_fd(), comp + woff[send_seg] + swoff, (size_t)swl,
              left_fd(), comp + woff[recv_seg] + rwoff, (size_t)rwl);
          if (!t.ok()) return t;
          if (rn > 0) {
            workers_->Submit(0, [=] {
              decode_scale(recv_seg, rwoff, rwl);
            });
          }
          return Status::OK();
        });
    if (!s.ok()) {
      workers_->DrainAll();
      return s;
    }
  }
  workers_->DrainAll();
  return Status::OK();
}

Status DataPlane::Allreduce(void* buf, int64_t count, DataType dt,
                            ReduceOp op, double postscale) {
  if (size_ == 1 || count == 0) {
    ScaleBuffer(buf, count, dt, postscale);
    return Status::OK();
  }
  if (op == ReduceOp::ADASUM) {
    Status s = AdasumAllreduce(buf, count, dt);
    if (s.ok()) ScaleBuffer(buf, count, dt, postscale);
    return s;
  }
  const int64_t elem = DataTypeSize(dt);
  auto* base = (uint8_t*)buf;
  // Segment the buffer into `size_` near-equal chunks.
  std::vector<int64_t> seg_count(size_), seg_off(size_);
  int64_t q = count / size_, r = count % size_, off = 0;
  for (int i = 0; i < size_; i++) {
    seg_count[i] = q + (i < r ? 1 : 0);
    seg_off[i] = off;
    off += seg_count[i];
  }
  const int64_t chunk = RingChunkBytes();
  WireTally tally;
  tally.plane = wire_plane_;
  SetEventWirePlane(wire_plane_);
  const int codec = force_compression_ ? 1 : WireCodec();
  if (codec != 0 && dt == DataType::HVDTPU_FLOAT32 &&
      (op == ReduceOp::SUM || op == ReduceOp::AVERAGE)) {
    // Linear ops only: the per-hop codec rounding composes with sums
    // (full-precision accumulate), and AVERAGE is sum + postscale.
    return CompressedRingAllreduce((float*)buf, seg_count, seg_off,
                                   postscale, chunk, codec, &tally);
  }
  // Phase 1: ring reduce-scatter, chunk-pipelined (each chunk's reduce
  // overlaps the remaining transfer on its stripe channel's worker).
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = RingSendSegment(rank_, step, size_);
    int recv_seg = RingRecvSegment(rank_, step, size_);
    Status s = PipelinedReduceChunks(
        right_peer(), base + seg_off[send_seg] * elem,
        seg_count[send_seg] * elem, left_peer(),
        base + seg_off[recv_seg] * elem, seg_count[recv_seg], dt, op, chunk,
        &tally);
    if (!s.ok()) return s;
  }
  // Phase 2: ring allgather of the reduced segments, starting from the
  // segment this rank just finished owning (RingOwnedSegment).
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = RingSendSegment(rank_, step, size_, /*rot=*/1);
    int recv_seg = RingSendSegment(rank_, step, size_, /*rot=*/0);
    Status s = ChunkedDuplex(
        right_peer(), base + seg_off[send_seg] * elem,
        seg_count[send_seg] * elem, left_peer(),
        base + seg_off[recv_seg] * elem, seg_count[recv_seg] * elem, chunk,
        &tally);
    if (!s.ok()) return s;
  }
  ScaleBuffer(buf, count, dt, postscale);
  return Status::OK();
}

Status DataPlane::Allgatherv(const void* input, void* output,
                             const std::vector<int64_t>& bytes_per_rank) {
  auto* out = (uint8_t*)output;
  std::vector<int64_t> offs(size_);
  int64_t off = 0;
  for (int i = 0; i < size_; i++) {
    offs[i] = off;
    off += bytes_per_rank[i];
  }
  std::memcpy(out + offs[rank_], input, (size_t)bytes_per_rank[rank_]);
  if (size_ == 1) return Status::OK();
  const int64_t chunk = RingChunkBytes();
  WireTally tally;
  tally.plane = wire_plane_;
  SetEventWirePlane(wire_plane_);
  for (int step = 0; step < size_ - 1; step++) {
    int send_blk = (rank_ - step + size_) % size_;
    int recv_blk = (rank_ - step - 1 + size_) % size_;
    Status s = ChunkedDuplex(right_peer(), out + offs[send_blk],
                             bytes_per_rank[send_blk], left_peer(),
                             out + offs[recv_blk], bytes_per_rank[recv_blk],
                             chunk, &tally);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status DataPlane::Broadcast(void* buf, int64_t bytes, int root) {
  if (size_ == 1 || bytes == 0) return Status::OK();
  // Pipelined ring from root: each rank receives from the left and forwards
  // to the right (unless the right neighbor is the root). Chunked so the
  // pipeline overlaps recv(i) with forward(i-1) via the duplex primitive.
  // Granularity comes from the one shared knob (HOROVOD_RING_CHUNK_BYTES;
  // <= 0 degrades to a single whole-buffer chunk).
  const int64_t knob = RingChunkBytes();
  const int64_t CHUNK = knob > 0 ? knob : bytes;
  auto* base = (uint8_t*)buf;
  int right = (rank_ + 1) % size_;
  bool is_root = rank_ == root;
  bool forwards = !is_root && right != root;
  WireTally tally;
  tally.plane = wire_plane_;
  SetEventWirePlane(wire_plane_);
  if (is_root || forwards) {
    tally.tx += bytes;
    tally.tx_logical += bytes;
  }
  if (!is_root) {
    tally.rx += bytes;
    tally.rx_logical += bytes;
  }
  int64_t nchunks = (bytes + CHUNK - 1) / CHUNK;
  auto chunk_span = [&](int64_t i, int64_t* off, int64_t* len) {
    *off = i * CHUNK;
    *len = std::min(CHUNK, bytes - *off);
  };
  // Every hop goes through the duplex entry (one-sided where only one
  // direction is live): the head/tail pieces used to be raw
  // SendAll/RecvAll, which under HOROVOD_WIRE_CRC would frame one end
  // of a socket and not the other. On the external transport and the
  // plain TCP path a one-sided duplex degrades to exactly the old
  // send/recv.
  if (is_root) {
    // Send CHUNK-sized pieces, matching the forwarders' chunked
    // receives: over TCP the stream hides the boundaries, but the
    // external (message) transport requires every send to pair with an
    // equal-length recv.
    for (int64_t i = 0; i < nchunks; i++) {
      int64_t off, len;
      chunk_span(i, &off, &len);
      Status s = DuplexTransfer(right_fd(), base + off, (size_t)len, -1,
                                nullptr, 0);
      if (!s.ok()) return s;
    }
    return Status::OK();
  }
  for (int64_t i = 0; i < nchunks; i++) {
    int64_t off, len;
    chunk_span(i, &off, &len);
    if (forwards && i > 0) {
      int64_t poff, plen;
      chunk_span(i - 1, &poff, &plen);
      Status s = DuplexTransfer(right_fd(), base + poff, (size_t)plen,
                                left_fd(), base + off, (size_t)len);
      if (!s.ok()) return s;
    } else {
      Status s = DuplexTransfer(-1, nullptr, 0, left_fd(), base + off,
                                (size_t)len);
      if (!s.ok()) return s;
    }
  }
  if (forwards) {
    int64_t off, len;
    chunk_span(nchunks - 1, &off, &len);
    return DuplexTransfer(right_fd(), base + off, (size_t)len, -1,
                          nullptr, 0);
  }
  return Status::OK();
}

Status DataPlane::Alltoallv(const void* input,
                            const std::vector<int64_t>& send_bytes,
                            void* output,
                            const std::vector<int64_t>& recv_bytes) {
  auto* in = (const uint8_t*)input;
  auto* out = (uint8_t*)output;
  std::vector<int64_t> send_off(size_), recv_off(size_);
  int64_t so = 0, ro = 0;
  for (int i = 0; i < size_; i++) {
    send_off[i] = so;
    so += send_bytes[i];
    recv_off[i] = ro;
    ro += recv_bytes[i];
  }
  std::memcpy(out + recv_off[rank_], in + send_off[rank_],
              (size_t)send_bytes[rank_]);
  const int64_t chunk = RingChunkBytes();
  WireTally tally;
  tally.plane = wire_plane_;
  SetEventWirePlane(wire_plane_);
  // Symmetric pairing: in round r, rank i partners with (r - i) mod size —
  // an involution, so each unordered pair {i, j} exchanges exactly once, in
  // round (i + j) mod size.
  for (int round = 0; round < size_; round++) {
    int partner = (round - rank_ + size_) % size_;
    if (partner == rank_) continue;
    Status s = ChunkedDuplex(partner, in + send_off[partner],
                             send_bytes[partner], partner,
                             out + recv_off[partner], recv_bytes[partner],
                             chunk, &tally);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status DataPlane::ReduceScatterv(const void* input, void* output,
                                 const std::vector<int64_t>& elems_per_rank,
                                 DataType dt, ReduceOp op, bool destructive) {
  const int64_t elem = DataTypeSize(dt);
  if (size_ == 1) {
    std::memcpy(output, input, (size_t)(elems_per_rank[0] * elem));
    return Status::OK();
  }
  std::vector<int64_t> seg_off(size_);
  int64_t off = 0;
  for (int i = 0; i < size_; i++) {
    seg_off[i] = off;
    off += elems_per_rank[i];
  }
  // Destructive mode clobbers the caller's buffer in place (hierarchical
  // allreduce rewrites it in phase 3 anyway); otherwise work in a
  // private copy so the caller's input is untouched.
  std::vector<uint8_t> work;
  uint8_t* base;
  if (destructive) {
    base = (uint8_t*)const_cast<void*>(input);
  } else {
    work.assign((const uint8_t*)input, (const uint8_t*)input + off * elem);
    base = work.data();
  }
  const int64_t chunk = RingChunkBytes();
  WireTally tally;
  tally.plane = wire_plane_;
  SetEventWirePlane(wire_plane_);
  // rot = -1: after size-1 steps the segment that has accumulated all
  // `size` contributions at rank r is exactly segment r (the API output
  // segment — see RingOwnedSegment).
  const int own = RingOwnedSegment(rank_, size_, /*rot=*/-1);
  const int codec = force_compression_ ? 1 : WireCodec();
  if (codec != 0 && dt == DataType::HVDTPU_FLOAT32 &&
      (op == ReduceOp::SUM || op == ReduceOp::AVERAGE)) {
    // Linear ops only, same contract as the compressed allreduce: the
    // per-hop codec rounding composes with sums (full-precision f32
    // accumulate), AVERAGE is sum + the caller's postscale.
    Status s = CompressedRingReduceScatter((float*)base, elems_per_rank,
                                           seg_off, chunk, codec, &tally);
    if (!s.ok()) return s;
    std::memcpy(output, base + seg_off[own] * elem,
                (size_t)(elems_per_rank[own] * elem));
    return Status::OK();
  }
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = RingSendSegment(rank_, step, size_, /*rot=*/-1);
    int recv_seg = RingRecvSegment(rank_, step, size_, /*rot=*/-1);
    Status s = PipelinedReduceChunks(
        right_peer(), base + seg_off[send_seg] * elem,
        elems_per_rank[send_seg] * elem, left_peer(),
        base + seg_off[recv_seg] * elem, elems_per_rank[recv_seg], dt, op,
        chunk, &tally);
    if (!s.ok()) return s;
  }
  std::memcpy(output, base + seg_off[own] * elem,
              (size_t)(elems_per_rank[own] * elem));
  return Status::OK();
}

Status DataPlane::Barrier() {
  uint8_t token = 1;
  return Allreduce(&token, 1, DataType::HVDTPU_UINT8, ReduceOp::SUM);
}

}  // namespace hvdtpu
