#include "ring_ops.h"

#include <errno.h>
#include <poll.h>
#include <sys/socket.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>

#include "half.h"
#include "events.h"
#include "metrics.h"
#include "wire.h"

namespace hvdtpu {

namespace {

std::atomic<int64_t> g_ring_chunk_bytes{kDefaultRingChunkBytes};
std::atomic<bool> g_wire_compression{false};

template <typename T, typename Acc = T>
void ReduceTyped(T* dst, const T* src, int64_t count, ReduceOp op) {
  switch (op) {
    case ReduceOp::AVERAGE:  // accumulate as sum; caller scales
    case ReduceOp::SUM:
    case ReduceOp::ADASUM:  // Adasum blending handled above this layer
      for (int64_t i = 0; i < count; i++) {
        dst[i] = (T)((Acc)dst[i] + (Acc)src[i]);
      }
      break;
    case ReduceOp::MIN:
      for (int64_t i = 0; i < count; i++) dst[i] = std::min(dst[i], src[i]);
      break;
    case ReduceOp::MAX:
      for (int64_t i = 0; i < count; i++) dst[i] = std::max(dst[i], src[i]);
      break;
    case ReduceOp::PRODUCT:
      for (int64_t i = 0; i < count; i++) {
        dst[i] = (T)((Acc)dst[i] * (Acc)src[i]);
      }
      break;
  }
}

template <uint16_t (*ToBits)(float), float (*FromBits)(uint16_t)>
void ReduceHalfLike(uint16_t* dst, const uint16_t* src, int64_t count,
                    ReduceOp op) {
  for (int64_t i = 0; i < count; i++) {
    float a = FromBits(dst[i]);
    float b = FromBits(src[i]);
    float r;
    switch (op) {
      case ReduceOp::MIN: r = std::min(a, b); break;
      case ReduceOp::MAX: r = std::max(a, b); break;
      case ReduceOp::PRODUCT: r = a * b; break;
      default: r = a + b; break;
    }
    dst[i] = ToBits(r);
  }
}

template <uint16_t (*ToBits)(float), float (*FromBits)(uint16_t)>
void ScaleHalfLike(uint16_t* p, int64_t count, double factor) {
  // Blocked decode -> scale -> encode through an f32 staging array: the
  // three narrow loops vectorize, where the old fused per-element loop
  // serialized a decode/multiply/encode dependency chain per lane.
  // Values are bit-identical to the fused form (decode is exact, one
  // f32-rounded multiply, one encode rounding).
  constexpr int64_t kBlock = 256;
  float tmp[kBlock];
  for (int64_t i = 0; i < count; i += kBlock) {
    int64_t n = std::min(kBlock, count - i);
    for (int64_t j = 0; j < n; j++) tmp[j] = FromBits(p[i + j]);
    for (int64_t j = 0; j < n; j++) tmp[j] = (float)(tmp[j] * factor);
    for (int64_t j = 0; j < n; j++) p[i + j] = ToBits(tmp[j]);
  }
}

// ---- bf16 wire codec (compressed allreduce) --------------------------

void EncodeBF16(uint16_t* dst, const float* src, int64_t n) {
  for (int64_t i = 0; i < n; i++) dst[i] = FloatToBF16Bits(src[i]);
}

void DecodeAccumBF16(float* dst, const uint16_t* src, int64_t n) {
  // Full-precision accumulation: the bf16 hop payload is widened back
  // to f32 before the add, so only the WIRE is narrow (EQuARX recipe).
  for (int64_t i = 0; i < n; i++) dst[i] += BF16BitsToFloat(src[i]);
}

void DecodeScaleBF16(float* dst, const uint16_t* src, int64_t n,
                     double post) {
  if (post == 1.0) {
    for (int64_t i = 0; i < n; i++) dst[i] = BF16BitsToFloat(src[i]);
  } else {
    // Same rounding as ScaleBuffer's f32 case (double multiply, one
    // f32 cast) so folding the postscale here is bit-identical to
    // scaling after the decode — it only saves the extra memory pass.
    for (int64_t i = 0; i < n; i++) {
      dst[i] = (float)((double)BF16BitsToFloat(src[i]) * post);
    }
  }
}

// Identical clamped chunk spans over the two directions of one hop:
// fn(i, soff, slen, roff, rlen) per chunk index, offsets/lengths in
// the caller's units. Both ends of a hop share the segment lengths,
// so this span table IS the external transport's message framing —
// every chunked path must slice through here.
template <typename Fn>
Status ForEachChunkSpan(int64_t send_len, int64_t recv_len, int64_t chunk,
                        Fn&& fn) {
  const int64_t nchunks = (std::max(send_len, recv_len) + chunk - 1) / chunk;
  for (int64_t i = 0; i < nchunks; i++) {
    int64_t soff = std::min(i * chunk, send_len);
    int64_t slen = std::min(chunk, send_len - soff);
    int64_t roff = std::min(i * chunk, recv_len);
    int64_t rlen = std::min(chunk, recv_len - roff);
    Status s = fn(i, soff, slen, roff, rlen);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

}  // namespace

int64_t RingChunkBytes() {
  return g_ring_chunk_bytes.load(std::memory_order_relaxed);
}

void SetRingChunkBytes(int64_t bytes) {
  g_ring_chunk_bytes.store(bytes, std::memory_order_relaxed);
}

bool WireCompression() {
  return g_wire_compression.load(std::memory_order_relaxed);
}

void SetWireCompression(bool on) {
  g_wire_compression.store(on, std::memory_order_relaxed);
}

// Overlap worker: one thread, FIFO tasks, started lazily on first
// Submit so planes that never run a chunked reduce cost nothing. The
// caller thread owns the transport (wire.h contract); the worker only
// touches host memory (ReduceInto / bf16 decode), and every public
// collective drains the queue before returning, so no task outlives
// the buffers it references.
class ReduceWorker {
 public:
  ~ReduceWorker() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    if (thread_.joinable()) thread_.join();
  }

  void Submit(std::function<void()> fn) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!thread_.joinable()) thread_ = std::thread(&ReduceWorker::Loop, this);
    tasks_.push_back(std::move(fn));
    pending_++;
    cv_.notify_one();
  }

  void Drain() {
    std::unique_lock<std::mutex> lk(mu_);
    done_cv_.wait(lk, [this] { return pending_ == 0; });
  }

 private:
  void Loop() {
    std::unique_lock<std::mutex> lk(mu_);
    while (true) {
      cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
      while (!tasks_.empty()) {
        std::function<void()> fn = std::move(tasks_.front());
        tasks_.pop_front();
        lk.unlock();
        fn();
        lk.lock();
        pending_--;
        if (pending_ == 0) done_cv_.notify_all();
      }
      if (stop_) return;
    }
  }

  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  std::deque<std::function<void()>> tasks_;
  int pending_ = 0;  // queued + running
  bool stop_ = false;
  std::thread thread_;
};

// Per-collective wire accounting, flushed into the metrics registry on
// scope exit (error paths included): `tx/rx` are bytes that actually
// crossed the transport, `*_logical` what they would be at full tensor
// width — the pair the wire-vs-logical reconciliation in telemetry
// reads (compression_ratio = tx / tx_logical).
struct DataPlane::WireTally {
  int plane = 0;  // 0 intra/flat, 1 cross-slice (set from wire_plane_)
  int64_t tx = 0, rx = 0, tx_logical = 0, rx_logical = 0;
  int64_t start_us = MetricsNowUs();
  ~WireTally() {
    // Restore the default plane tag for whatever the thread runs next
    // (the hierarchical engine nests intra/cross tallies).
    SetEventWirePlane(0);
    if (tx || rx || tx_logical || rx_logical) {
      GlobalMetrics().AccountWire(plane, tx, rx, tx_logical, rx_logical);
      int64_t end_us = MetricsNowUs();
      // The span interval feeds the per-step overlap ledger — the SAME
      // [start,end) the kWireSpan event encodes, so the ledger and the
      // flight recorder can never disagree about what the wire did.
      GlobalLedger().AddSpan(plane, start_us, end_us);
      GlobalEvents().Record(
          EventType::kWireSpan, plane,
          (int32_t)std::min<int64_t>(end_us - start_us, INT32_MAX), tx,
          rx);
    }
  }
};

void ReduceInto(void* dst, const void* src, int64_t count, DataType dt,
                ReduceOp op) {
  switch (dt) {
    case DataType::HVDTPU_UINT8:
      ReduceTyped((uint8_t*)dst, (const uint8_t*)src, count, op);
      break;
    case DataType::HVDTPU_INT8:
      ReduceTyped((int8_t*)dst, (const int8_t*)src, count, op);
      break;
    case DataType::HVDTPU_INT32:
      ReduceTyped((int32_t*)dst, (const int32_t*)src, count, op);
      break;
    case DataType::HVDTPU_INT64:
      ReduceTyped((int64_t*)dst, (const int64_t*)src, count, op);
      break;
    case DataType::HVDTPU_FLOAT16:
      ReduceHalfLike<FloatToHalfBits, HalfBitsToFloat>(
          (uint16_t*)dst, (const uint16_t*)src, count, op);
      break;
    case DataType::HVDTPU_BFLOAT16:
      ReduceHalfLike<FloatToBF16Bits, BF16BitsToFloat>(
          (uint16_t*)dst, (const uint16_t*)src, count, op);
      break;
    case DataType::HVDTPU_FLOAT32:
      ReduceTyped((float*)dst, (const float*)src, count, op);
      break;
    case DataType::HVDTPU_FLOAT64:
      ReduceTyped((double*)dst, (const double*)src, count, op);
      break;
    case DataType::HVDTPU_BOOL: {
      // bool: SUM/PRODUCT behave as OR/AND (matches logical expectations).
      auto* d = (uint8_t*)dst;
      auto* s = (const uint8_t*)src;
      for (int64_t i = 0; i < count; i++) {
        switch (op) {
          case ReduceOp::MIN:
          case ReduceOp::PRODUCT: d[i] = d[i] && s[i]; break;
          default: d[i] = d[i] || s[i]; break;
        }
      }
      break;
    }
    case DataType::HVDTPU_UINT16:
      ReduceTyped((uint16_t*)dst, (const uint16_t*)src, count, op);
      break;
  }
}

void ScaleBuffer(void* buf, int64_t count, DataType dt, double factor) {
  if (factor == 1.0) return;
  switch (dt) {
    case DataType::HVDTPU_FLOAT32: {
      auto* p = (float*)buf;
      for (int64_t i = 0; i < count; i++) p[i] = (float)(p[i] * factor);
      break;
    }
    case DataType::HVDTPU_FLOAT64: {
      auto* p = (double*)buf;
      for (int64_t i = 0; i < count; i++) p[i] *= factor;
      break;
    }
    case DataType::HVDTPU_FLOAT16:
      ScaleHalfLike<FloatToHalfBits, HalfBitsToFloat>((uint16_t*)buf, count,
                                                      factor);
      break;
    case DataType::HVDTPU_BFLOAT16:
      ScaleHalfLike<FloatToBF16Bits, BF16BitsToFloat>((uint16_t*)buf, count,
                                                      factor);
      break;
    case DataType::HVDTPU_INT32: {
      auto* p = (int32_t*)buf;
      for (int64_t i = 0; i < count; i++) p[i] = (int32_t)(p[i] * factor);
      break;
    }
    case DataType::HVDTPU_INT64: {
      auto* p = (int64_t*)buf;
      for (int64_t i = 0; i < count; i++) p[i] = (int64_t)(p[i] * factor);
      break;
    }
    default:
      break;  // scaling integral small types is not meaningful
  }
}

DataPlane::DataPlane(int rank, int size, std::vector<int> peer_fds)
    : DataPlane(rank, size, std::move(peer_fds), /*owns_fds=*/true) {}

DataPlane::DataPlane(int rank, int size, std::vector<int> peer_fds,
                     bool owns_fds)
    : rank_(rank), size_(size), peer_fds_(std::move(peer_fds)),
      owns_fds_(owns_fds), worker_(std::make_shared<ReduceWorker>()) {
  global_ranks_.resize(size_);
  for (int i = 0; i < size_; i++) global_ranks_[i] = i;
  if (owns_fds_) {
    // Peer attribution for wire timeouts/EOF (see wire.h). Subset views
    // share fds the root already registered with GLOBAL ranks. The fd
    // table may be empty (placeholder planes for unknown process sets).
    for (size_t i = 0; i < peer_fds_.size(); i++) {
      if (peer_fds_[i] >= 0) RegisterFdRank(peer_fds_[i], (int)i);
    }
  }
}

std::vector<int32_t> DataPlane::ProbeDeadPeers() const {
  // The sweep is O(peer fds) of nonblocking poll+MSG_PEEK syscalls —
  // one of the large-world control-plane suspects the per-phase
  // profile tracks (docs/scale.md).
  const int64_t t0 = MetricsNowUs();
  std::vector<int32_t> dead;
  for (int i = 0; i < (int)peer_fds_.size() && i < size_; i++) {
    int fd = peer_fds_[i];
    if (fd < 0 || i == rank_) continue;  // self / external / absent
    pollfd p{};
    p.fd = fd;
    p.events = POLLIN;
    int rc = poll(&p, 1, 0);
    if (rc <= 0) continue;  // no events pending -> no evidence of death
    if (p.revents & (POLLERR | POLLNVAL)) {
      dead.push_back(global_ranks_[i]);
      continue;
    }
    if (p.revents & (POLLIN | POLLHUP)) {
      // Distinguish EOF from pending (stale) ring bytes without
      // consuming them: a live-but-stalled peer's stream peeks > 0.
      char probe;
      ssize_t n = recv(fd, &probe, 1, MSG_PEEK | MSG_DONTWAIT);
      if (n == 0 || (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK &&
                     errno != EINTR)) {
        dead.push_back(global_ranks_[i]);
      }
    }
  }
  RecordControlPhase(kPhaseProbeSweep, MetricsNowUs() - t0);
  return dead;
}

DataPlane::~DataPlane() {
  if (!owns_fds_) return;
  for (int fd : peer_fds_) TcpClose(fd);
}

DataPlane DataPlane::Subset(const std::vector<int32_t>& members) const {
  std::vector<int> fds(members.size(), -1);
  int my_idx = -1;
  for (size_t i = 0; i < members.size(); i++) {
    if (members[i] == rank_) {
      my_idx = (int)i;
    } else {
      fds[i] = peer_fds_[members[i]];
    }
  }
  // All ring algorithms index peer_fds_ by (group-relative) rank, so a
  // remapped fd table + group rank/size is a fully working data plane.
  DataPlane sub(my_idx, (int)members.size(), std::move(fds),
                /*owns_fds=*/false);
  sub.global_ranks_ = members;
  // Views inherit the parent's wire plane + compression override;
  // HierarchicalAllreduce re-tags its inter-slice subset explicitly.
  sub.wire_plane_ = wire_plane_;
  sub.force_compression_ = force_compression_;
  // Share the parent's overlap worker: the core's single background
  // thread is the only caller on both, so per-response subset views
  // never spawn (and tear down) their own thread.
  sub.worker_ = worker_;
  return sub;
}

Status DataPlane::HierarchicalAllreduce(void* buf, int64_t count, DataType dt,
                                        ReduceOp op, int local_size,
                                        double postscale,
                                        bool compress_cross) {
  if (size_ == 1 || count == 0) {
    ScaleBuffer(buf, count, dt, postscale);
    return Status::OK();
  }
  if (local_size <= 1 || size_ % local_size != 0 ||
      op == ReduceOp::ADASUM) {
    return Allreduce(buf, count, dt, op, postscale);
  }
  const int cross_size = size_ / local_size;
  if (cross_size <= 1) return Allreduce(buf, count, dt, op, postscale);
  const int local_rank = rank_ % local_size;
  const int node = rank_ / local_size;
  const int64_t elem = DataTypeSize(dt);

  // Local group: the ranks on this slice; cross group: same local_rank
  // on every slice (host-major layout). The cross subset is the
  // CROSS-PLANE hop: its wire bytes are booked under the cross
  // counters, and `compress_cross` puts the bf16 codec on it alone.
  std::vector<int32_t> local_members(local_size);
  for (int i = 0; i < local_size; i++) {
    local_members[i] = node * local_size + i;
  }
  std::vector<int32_t> cross_members(cross_size);
  for (int k = 0; k < cross_size; k++) {
    cross_members[k] = k * local_size + local_rank;
  }
  DataPlane local = Subset(local_members);
  DataPlane cross = Subset(cross_members);
  cross.set_wire_plane(1);
  if (compress_cross) cross.set_force_compression(true);

  // Phase 1: local reduce-scatter — this rank ends with its segment
  // reduced across the node.
  std::vector<int64_t> seg(local_size);
  int64_t q = count / local_size, r = count % local_size;
  for (int i = 0; i < local_size; i++) {
    seg[i] = q + (i < r ? 1 : 0);
  }
  std::vector<uint8_t> my_seg((size_t)(seg[local_rank] * elem));
  Status s = local.ReduceScatterv(buf, my_seg.data(), seg, dt, op,
                                  /*destructive=*/true);
  if (!s.ok()) return s;

  // Phase 2: allreduce the segment across nodes (1/local_size of the
  // payload crosses the node boundary). The postscale rides here: each
  // element passes through exactly one cross-allreduce, so it is
  // applied exactly once before the allgather distributes it.
  s = cross.Allreduce(my_seg.data(), seg[local_rank], dt, op, postscale);
  if (!s.ok()) return s;

  // Phase 3: local allgather of the fully-reduced segments — rank-order
  // concatenation is exactly the original buffer layout.
  std::vector<int64_t> seg_bytes(local_size);
  for (int i = 0; i < local_size; i++) seg_bytes[i] = seg[i] * elem;
  return local.Allgatherv(my_seg.data(), buf, seg_bytes);
}

Status DataPlane::PipelinedReduceChunks(int send_fd, const uint8_t* send_buf,
                                        int64_t send_bytes, int recv_fd,
                                        uint8_t* reduce_dst,
                                        int64_t recv_count, DataType dt,
                                        ReduceOp op, int64_t chunk_bytes,
                                        WireTally* tally) {
  const int64_t elem = DataTypeSize(dt);
  const int64_t recv_bytes = recv_count * elem;
  tally->tx += send_bytes;
  tally->tx_logical += send_bytes;
  tally->rx += recv_bytes;
  tally->rx_logical += recv_bytes;
  if (chunk_bytes <= 0 ||
      (send_bytes <= chunk_bytes && recv_bytes <= chunk_bytes)) {
    // Bulk path: one whole-segment transfer, then a serial reduce —
    // same framing and bit-identical results as the pre-chunking ring.
    if ((int64_t)scratch_.size() < recv_bytes) scratch_.resize(recv_bytes);
    Status s = DuplexTransfer(send_fd, send_buf, (size_t)send_bytes, recv_fd,
                              scratch_.data(), (size_t)recv_bytes);
    if (!s.ok()) return s;
    ReduceInto(reduce_dst, scratch_.data(), recv_count, dt, op);
    return Status::OK();
  }
  // Chunk on element boundaries (ReduceInto takes whole elements).
  const int64_t chunk_elems = std::max<int64_t>(chunk_bytes / elem, 1);
  const int64_t cbytes = chunk_elems * elem;
  if (!IsExtFd(send_fd) && !IsExtFd(recv_fd)) {
    // TCP: ONE continuous duplex for the whole segment — the send
    // streams with no per-chunk lockstep or fcntl churn (byte-stream
    // framing is unchanged vs the bulk path), while every completed
    // recv chunk fires a ReduceInto on the worker, overlapping the
    // reduction with the rest of the transfer.
    if ((int64_t)scratch_.size() < recv_bytes) scratch_.resize(recv_bytes);
    uint8_t* rbuf = scratch_.data();
    Status s = DuplexTransferChunked(
        send_fd, send_buf, (size_t)send_bytes, recv_fd, rbuf,
        (size_t)recv_bytes, (size_t)cbytes,
        [&](size_t off, size_t len) {
          uint8_t* dst = reduce_dst + off;
          const uint8_t* src = rbuf + off;
          const int64_t n = (int64_t)len / elem;
          worker_->Submit(
              [dst, src, n, dt, op] { ReduceInto(dst, src, n, dt, op); });
        });
    worker_->Drain();  // the segment is fully reduced before the caller
    return s;          // forwards it on the next ring step
  }
  // External (message) transport: the mailbox preserves boundaries, so
  // both ends cut identical chunk spans into equal-length paired
  // messages, double-buffered so the reduce of chunk i-1 overlaps the
  // exchange of chunk i.
  if ((int64_t)chunk_scratch_.size() < 2 * cbytes) {
    chunk_scratch_.resize((size_t)(2 * cbytes));
  }
  Status s = ForEachChunkSpan(
      send_bytes, recv_bytes, cbytes,
      [&](int64_t i, int64_t soff, int64_t slen, int64_t roff,
          int64_t rlen) {
        uint8_t* rscratch = chunk_scratch_.data() + (i & 1) * cbytes;
        // While this transfer runs, the worker reduces chunk i-1
        // (submitted below last iteration) out of the other half.
        Status t = DuplexTransfer(send_fd, send_buf + soff, (size_t)slen,
                                  recv_fd, rscratch, (size_t)rlen);
        worker_->Drain();  // chunk i-1 reduced; its scratch half is free
        if (!t.ok()) return t;
        if (rlen > 0) {
          uint8_t* dst = reduce_dst + roff;
          const int64_t n = rlen / elem;
          worker_->Submit([dst, rscratch, n, dt, op] {
            ReduceInto(dst, rscratch, n, dt, op);
          });
        }
        return Status::OK();
      });
  worker_->Drain();
  return s;
}

Status DataPlane::ChunkedDuplex(int send_fd, const uint8_t* send_buf,
                                int64_t send_bytes, int recv_fd,
                                uint8_t* recv_buf, int64_t recv_bytes,
                                int64_t chunk_bytes, WireTally* tally) {
  tally->tx += send_bytes;
  tally->tx_logical += send_bytes;
  tally->rx += recv_bytes;
  tally->rx_logical += recv_bytes;
  // No reduction to overlap here, so the knob only matters where the
  // transport frames messages: on TCP the byte stream hides chunk
  // boundaries and one duplex is strictly cheaper.
  if (chunk_bytes <= 0 ||
      (send_bytes <= chunk_bytes && recv_bytes <= chunk_bytes) ||
      (!IsExtFd(send_fd) && !IsExtFd(recv_fd))) {
    return DuplexTransfer(send_fd, send_buf, (size_t)send_bytes, recv_fd,
                          recv_buf, (size_t)recv_bytes);
  }
  return ForEachChunkSpan(
      send_bytes, recv_bytes, chunk_bytes,
      [&](int64_t, int64_t soff, int64_t slen, int64_t roff, int64_t rlen) {
        return DuplexTransfer(send_fd, send_buf + soff, (size_t)slen,
                              recv_fd, recv_buf + roff, (size_t)rlen);
      });
}

Status DataPlane::CompressedReducePhase(
    float* base, const std::vector<int64_t>& seg_count,
    const std::vector<int64_t>& seg_off, int64_t chunk_elems, int rot,
    WireTally* tally) {
  int64_t max_seg = 0;
  for (int i = 0; i < size_; i++) max_seg = std::max(max_seg, seg_count[i]);
  const bool tcp = !IsExtFd(right_fd()) && !IsExtFd(left_fd());
  // Scratch: the TCP path encodes/receives whole segments (one
  // streaming duplex per step); the external path works chunk-by-chunk
  // with a double-buffered recv half.
  const int64_t send_scratch_elems = tcp ? max_seg : chunk_elems;
  const int64_t recv_scratch_elems =
      tcp ? max_seg : 2 * chunk_elems;
  if ((int64_t)comp_send_scratch_.size() < send_scratch_elems * 2) {
    comp_send_scratch_.resize((size_t)(send_scratch_elems * 2));
  }
  if ((int64_t)chunk_scratch_.size() < recv_scratch_elems * 2) {
    chunk_scratch_.resize((size_t)(recv_scratch_elems * 2));
  }
  // N-1 ring reduce steps at rotation `rot`. Each hop ships the current
  // f32 partial as bf16; the receiver widens back to f32 and
  // accumulates at full precision, overlapped with the remaining
  // transfer.
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = RingSendSegment(rank_, step, size_, rot);
    int recv_seg = RingRecvSegment(rank_, step, size_, rot);
    const float* sbase = base + seg_off[send_seg];
    float* rbase = base + seg_off[recv_seg];
    const int64_t scount = seg_count[send_seg];
    const int64_t rcount = seg_count[recv_seg];
    tally->tx += scount * 2;
    tally->tx_logical += scount * 4;
    tally->rx += rcount * 2;
    tally->rx_logical += rcount * 4;
    if (tcp) {
      // Encode the whole outgoing segment once, then stream it in one
      // duplex while completed recv chunks decode+accumulate on the
      // worker.
      auto* senc = (uint16_t*)comp_send_scratch_.data();
      EncodeBF16(senc, sbase, scount);
      auto* rdec = (uint16_t*)chunk_scratch_.data();
      Status s = DuplexTransferChunked(
          right_fd(), senc, (size_t)(scount * 2), left_fd(), rdec,
          (size_t)(rcount * 2), (size_t)(chunk_elems * 2),
          [&](size_t off, size_t len) {
            float* dst = rbase + off / 2;
            const uint16_t* src = rdec + off / 2;
            const int64_t n = (int64_t)len / 2;
            worker_->Submit([dst, src, n] { DecodeAccumBF16(dst, src, n); });
          });
      worker_->Drain();  // next step sends what this step accumulated
      if (!s.ok()) return s;
      continue;
    }
    Status s = ForEachChunkSpan(
        scount, rcount, chunk_elems,
        [&](int64_t i, int64_t soff, int64_t sn, int64_t roff, int64_t rn) {
          auto* senc = (uint16_t*)comp_send_scratch_.data();
          EncodeBF16(senc, sbase + soff, sn);
          auto* rdec =
              (uint16_t*)chunk_scratch_.data() + (i & 1) * chunk_elems;
          Status t = DuplexTransfer(right_fd(), senc, (size_t)(sn * 2),
                                    left_fd(), rdec, (size_t)(rn * 2));
          worker_->Drain();  // chunk i-1 accumulated; its half is free
          if (!t.ok()) return t;
          if (rn > 0) {
            float* dst = rbase + roff;
            worker_->Submit(
                [dst, rdec, rn] { DecodeAccumBF16(dst, rdec, rn); });
          }
          return Status::OK();
        });
    worker_->Drain();  // next step sends what this step accumulated
    if (!s.ok()) return s;
  }
  return Status::OK();
}

static int64_t CompressedChunkElems(int64_t chunk_bytes,
                                    const std::vector<int64_t>& seg_count) {
  // Chunk in elements derived from the LOGICAL byte knob, so the
  // tunable keeps one meaning whether or not compression is on.
  int64_t max_seg = 0;
  for (int64_t c : seg_count) max_seg = std::max(max_seg, c);
  return chunk_bytes > 0 ? std::max<int64_t>(chunk_bytes / 4, 1)
                         : std::max<int64_t>(max_seg, 1);
}

Status DataPlane::CompressedRingReduceScatter(
    float* base, const std::vector<int64_t>& seg_count,
    const std::vector<int64_t>& seg_off, int64_t chunk_bytes,
    WireTally* tally) {
  // rot = -1: rank r's fully-accumulated segment is its own segment r —
  // the reduce-scatter output contract (see RingOwnedSegment).
  return CompressedReducePhase(base, seg_count, seg_off,
                               CompressedChunkElems(chunk_bytes, seg_count),
                               /*rot=*/-1, tally);
}

Status DataPlane::CompressedRingAllreduce(
    float* base, const std::vector<int64_t>& seg_count,
    const std::vector<int64_t>& seg_off, double postscale,
    int64_t chunk_bytes, WireTally* tally) {
  const int64_t chunk_elems = CompressedChunkElems(chunk_bytes, seg_count);
  // Phase 1: ring reduce-scatter (rot = 0 — rank r ends owning segment
  // (r+1)%N, which phase 2 sends first).
  Status ph1 = CompressedReducePhase(base, seg_count, seg_off, chunk_elems,
                                     /*rot=*/0, tally);
  if (!ph1.ok()) return ph1;
  const bool tcp = !IsExtFd(right_fd()) && !IsExtFd(left_fd());
  // Phase 2: ring allgather of the finalized segments, compressed. The
  // bf16 wire image is forwarded verbatim (re-encoding a decoded bf16
  // value is lossless, so no rounding compounds across hops), and every
  // rank — the owner included — decodes the SAME bits, so the result is
  // rank-consistent: each element is exactly one bf16 rounding of its
  // full-precision f32 reduction, times the postscale.
  const int64_t total = seg_off[size_ - 1] + seg_count[size_ - 1];
  if ((int64_t)comp_plane_.size() < total * 2) {
    comp_plane_.resize((size_t)(total * 2));
  }
  auto* comp = (uint16_t*)comp_plane_.data();
  // After size-1 reduce-scatter steps the fully-accumulated segment at
  // rank r is (r+1) mod size — exactly the first segment phase 2 sends.
  const int own_seg = RingOwnedSegment(rank_, size_);
  EncodeBF16(comp + seg_off[own_seg], base + seg_off[own_seg],
             seg_count[own_seg]);
  DecodeScaleBF16(base + seg_off[own_seg], comp + seg_off[own_seg],
                  seg_count[own_seg], postscale);
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = RingSendSegment(rank_, step, size_, /*rot=*/1);
    int recv_seg = RingSendSegment(rank_, step, size_, /*rot=*/0);
    const int64_t scount = seg_count[send_seg];
    const int64_t rcount = seg_count[recv_seg];
    tally->tx += scount * 2;
    tally->tx_logical += scount * 4;
    tally->rx += rcount * 2;
    tally->rx_logical += rcount * 4;
    // Receive straight into the compressed plane (it is forwarded next
    // step); the f32 decode overlaps the remaining transfer. No
    // per-step drain: every chunk decodes from its own plane region.
    if (tcp) {
      uint16_t* rplane = comp + seg_off[recv_seg];
      float* rbase = base + seg_off[recv_seg];
      Status s = DuplexTransferChunked(
          right_fd(), comp + seg_off[send_seg], (size_t)(scount * 2),
          left_fd(), rplane, (size_t)(rcount * 2),
          (size_t)(chunk_elems * 2),
          [&](size_t off, size_t len) {
            float* dst = rbase + off / 2;
            const uint16_t* src = rplane + off / 2;
            const int64_t n = (int64_t)len / 2;
            worker_->Submit([dst, src, n, postscale] {
              DecodeScaleBF16(dst, src, n, postscale);
            });
          });
      if (!s.ok()) {
        worker_->Drain();
        return s;
      }
      continue;
    }
    Status s = ForEachChunkSpan(
        scount, rcount, chunk_elems,
        [&](int64_t, int64_t soff, int64_t sn, int64_t roff, int64_t rn) {
          Status t = DuplexTransfer(
              right_fd(), comp + seg_off[send_seg] + soff,
              (size_t)(sn * 2), left_fd(),
              comp + seg_off[recv_seg] + roff, (size_t)(rn * 2));
          if (!t.ok()) return t;
          if (rn > 0) {
            float* dst = base + seg_off[recv_seg] + roff;
            const uint16_t* src = comp + seg_off[recv_seg] + roff;
            worker_->Submit([dst, src, rn, postscale] {
              DecodeScaleBF16(dst, src, rn, postscale);
            });
          }
          return Status::OK();
        });
    if (!s.ok()) {
      worker_->Drain();
      return s;
    }
  }
  worker_->Drain();
  return Status::OK();
}

Status DataPlane::Allreduce(void* buf, int64_t count, DataType dt,
                            ReduceOp op, double postscale) {
  if (size_ == 1 || count == 0) {
    ScaleBuffer(buf, count, dt, postscale);
    return Status::OK();
  }
  if (op == ReduceOp::ADASUM) {
    Status s = AdasumAllreduce(buf, count, dt);
    if (s.ok()) ScaleBuffer(buf, count, dt, postscale);
    return s;
  }
  const int64_t elem = DataTypeSize(dt);
  auto* base = (uint8_t*)buf;
  // Segment the buffer into `size_` near-equal chunks.
  std::vector<int64_t> seg_count(size_), seg_off(size_);
  int64_t q = count / size_, r = count % size_, off = 0;
  for (int i = 0; i < size_; i++) {
    seg_count[i] = q + (i < r ? 1 : 0);
    seg_off[i] = off;
    off += seg_count[i];
  }
  const int64_t chunk = RingChunkBytes();
  WireTally tally;
  tally.plane = wire_plane_;
  SetEventWirePlane(wire_plane_);
  if ((WireCompression() || force_compression_) &&
      dt == DataType::HVDTPU_FLOAT32 &&
      (op == ReduceOp::SUM || op == ReduceOp::AVERAGE)) {
    // Linear ops only: the per-hop bf16 rounding composes with sums
    // (full-precision accumulate), and AVERAGE is sum + postscale.
    return CompressedRingAllreduce((float*)buf, seg_count, seg_off,
                                   postscale, chunk, &tally);
  }
  // Phase 1: ring reduce-scatter, chunk-pipelined (reduce of chunk i-1
  // overlaps the transfer of chunk i on the worker thread).
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = RingSendSegment(rank_, step, size_);
    int recv_seg = RingRecvSegment(rank_, step, size_);
    Status s = PipelinedReduceChunks(
        right_fd(), base + seg_off[send_seg] * elem,
        seg_count[send_seg] * elem, left_fd(),
        base + seg_off[recv_seg] * elem, seg_count[recv_seg], dt, op, chunk,
        &tally);
    if (!s.ok()) return s;
  }
  // Phase 2: ring allgather of the reduced segments, starting from the
  // segment this rank just finished owning (RingOwnedSegment).
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = RingSendSegment(rank_, step, size_, /*rot=*/1);
    int recv_seg = RingSendSegment(rank_, step, size_, /*rot=*/0);
    Status s = ChunkedDuplex(
        right_fd(), base + seg_off[send_seg] * elem,
        seg_count[send_seg] * elem, left_fd(),
        base + seg_off[recv_seg] * elem, seg_count[recv_seg] * elem, chunk,
        &tally);
    if (!s.ok()) return s;
  }
  ScaleBuffer(buf, count, dt, postscale);
  return Status::OK();
}

Status DataPlane::Allgatherv(const void* input, void* output,
                             const std::vector<int64_t>& bytes_per_rank) {
  auto* out = (uint8_t*)output;
  std::vector<int64_t> offs(size_);
  int64_t off = 0;
  for (int i = 0; i < size_; i++) {
    offs[i] = off;
    off += bytes_per_rank[i];
  }
  std::memcpy(out + offs[rank_], input, (size_t)bytes_per_rank[rank_]);
  if (size_ == 1) return Status::OK();
  const int64_t chunk = RingChunkBytes();
  WireTally tally;
  tally.plane = wire_plane_;
  SetEventWirePlane(wire_plane_);
  for (int step = 0; step < size_ - 1; step++) {
    int send_blk = (rank_ - step + size_) % size_;
    int recv_blk = (rank_ - step - 1 + size_) % size_;
    Status s = ChunkedDuplex(right_fd(), out + offs[send_blk],
                             bytes_per_rank[send_blk], left_fd(),
                             out + offs[recv_blk], bytes_per_rank[recv_blk],
                             chunk, &tally);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status DataPlane::Broadcast(void* buf, int64_t bytes, int root) {
  if (size_ == 1 || bytes == 0) return Status::OK();
  // Pipelined ring from root: each rank receives from the left and forwards
  // to the right (unless the right neighbor is the root). Chunked so the
  // pipeline overlaps recv(i) with forward(i-1) via the duplex primitive.
  // Granularity comes from the one shared knob (HOROVOD_RING_CHUNK_BYTES;
  // <= 0 degrades to a single whole-buffer chunk).
  const int64_t knob = RingChunkBytes();
  const int64_t CHUNK = knob > 0 ? knob : bytes;
  auto* base = (uint8_t*)buf;
  int right = (rank_ + 1) % size_;
  bool is_root = rank_ == root;
  bool forwards = !is_root && right != root;
  WireTally tally;
  tally.plane = wire_plane_;
  SetEventWirePlane(wire_plane_);
  if (is_root || forwards) {
    tally.tx += bytes;
    tally.tx_logical += bytes;
  }
  if (!is_root) {
    tally.rx += bytes;
    tally.rx_logical += bytes;
  }
  int64_t nchunks = (bytes + CHUNK - 1) / CHUNK;
  auto chunk_span = [&](int64_t i, int64_t* off, int64_t* len) {
    *off = i * CHUNK;
    *len = std::min(CHUNK, bytes - *off);
  };
  // Every hop goes through the duplex entry (one-sided where only one
  // direction is live): the head/tail pieces used to be raw
  // SendAll/RecvAll, which under HOROVOD_WIRE_CRC would frame one end
  // of a socket and not the other. On the external transport and the
  // plain TCP path a one-sided duplex degrades to exactly the old
  // send/recv.
  if (is_root) {
    // Send CHUNK-sized pieces, matching the forwarders' chunked
    // receives: over TCP the stream hides the boundaries, but the
    // external (message) transport requires every send to pair with an
    // equal-length recv.
    for (int64_t i = 0; i < nchunks; i++) {
      int64_t off, len;
      chunk_span(i, &off, &len);
      Status s = DuplexTransfer(right_fd(), base + off, (size_t)len, -1,
                                nullptr, 0);
      if (!s.ok()) return s;
    }
    return Status::OK();
  }
  for (int64_t i = 0; i < nchunks; i++) {
    int64_t off, len;
    chunk_span(i, &off, &len);
    if (forwards && i > 0) {
      int64_t poff, plen;
      chunk_span(i - 1, &poff, &plen);
      Status s = DuplexTransfer(right_fd(), base + poff, (size_t)plen,
                                left_fd(), base + off, (size_t)len);
      if (!s.ok()) return s;
    } else {
      Status s = DuplexTransfer(-1, nullptr, 0, left_fd(), base + off,
                                (size_t)len);
      if (!s.ok()) return s;
    }
  }
  if (forwards) {
    int64_t off, len;
    chunk_span(nchunks - 1, &off, &len);
    return DuplexTransfer(right_fd(), base + off, (size_t)len, -1,
                          nullptr, 0);
  }
  return Status::OK();
}

Status DataPlane::Alltoallv(const void* input,
                            const std::vector<int64_t>& send_bytes,
                            void* output,
                            const std::vector<int64_t>& recv_bytes) {
  auto* in = (const uint8_t*)input;
  auto* out = (uint8_t*)output;
  std::vector<int64_t> send_off(size_), recv_off(size_);
  int64_t so = 0, ro = 0;
  for (int i = 0; i < size_; i++) {
    send_off[i] = so;
    so += send_bytes[i];
    recv_off[i] = ro;
    ro += recv_bytes[i];
  }
  std::memcpy(out + recv_off[rank_], in + send_off[rank_],
              (size_t)send_bytes[rank_]);
  const int64_t chunk = RingChunkBytes();
  WireTally tally;
  tally.plane = wire_plane_;
  SetEventWirePlane(wire_plane_);
  // Symmetric pairing: in round r, rank i partners with (r - i) mod size —
  // an involution, so each unordered pair {i, j} exchanges exactly once, in
  // round (i + j) mod size.
  for (int round = 0; round < size_; round++) {
    int partner = (round - rank_ + size_) % size_;
    if (partner == rank_) continue;
    int fd = peer_fds_[partner];
    Status s = ChunkedDuplex(fd, in + send_off[partner], send_bytes[partner],
                             fd, out + recv_off[partner],
                             recv_bytes[partner], chunk, &tally);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status DataPlane::ReduceScatterv(const void* input, void* output,
                                 const std::vector<int64_t>& elems_per_rank,
                                 DataType dt, ReduceOp op, bool destructive) {
  const int64_t elem = DataTypeSize(dt);
  if (size_ == 1) {
    std::memcpy(output, input, (size_t)(elems_per_rank[0] * elem));
    return Status::OK();
  }
  std::vector<int64_t> seg_off(size_);
  int64_t off = 0;
  for (int i = 0; i < size_; i++) {
    seg_off[i] = off;
    off += elems_per_rank[i];
  }
  // Destructive mode clobbers the caller's buffer in place (hierarchical
  // allreduce rewrites it in phase 3 anyway); otherwise work in a
  // private copy so the caller's input is untouched.
  std::vector<uint8_t> work;
  uint8_t* base;
  if (destructive) {
    base = (uint8_t*)const_cast<void*>(input);
  } else {
    work.assign((const uint8_t*)input, (const uint8_t*)input + off * elem);
    base = work.data();
  }
  const int64_t chunk = RingChunkBytes();
  WireTally tally;
  tally.plane = wire_plane_;
  SetEventWirePlane(wire_plane_);
  // rot = -1: after size-1 steps the segment that has accumulated all
  // `size` contributions at rank r is exactly segment r (the API output
  // segment — see RingOwnedSegment).
  const int own = RingOwnedSegment(rank_, size_, /*rot=*/-1);
  if ((WireCompression() || force_compression_) &&
      dt == DataType::HVDTPU_FLOAT32 &&
      (op == ReduceOp::SUM || op == ReduceOp::AVERAGE)) {
    // Linear ops only, same contract as the compressed allreduce: the
    // per-hop bf16 rounding composes with sums (full-precision f32
    // accumulate), AVERAGE is sum + the caller's postscale.
    Status s = CompressedRingReduceScatter((float*)base, elems_per_rank,
                                           seg_off, chunk, &tally);
    if (!s.ok()) return s;
    std::memcpy(output, base + seg_off[own] * elem,
                (size_t)(elems_per_rank[own] * elem));
    return Status::OK();
  }
  for (int step = 0; step < size_ - 1; step++) {
    int send_seg = RingSendSegment(rank_, step, size_, /*rot=*/-1);
    int recv_seg = RingRecvSegment(rank_, step, size_, /*rot=*/-1);
    Status s = PipelinedReduceChunks(
        right_fd(), base + seg_off[send_seg] * elem,
        elems_per_rank[send_seg] * elem, left_fd(),
        base + seg_off[recv_seg] * elem, elems_per_rank[recv_seg], dt, op,
        chunk, &tally);
    if (!s.ok()) return s;
  }
  std::memcpy(output, base + seg_off[own] * elem,
              (size_t)(elems_per_rank[own] * elem));
  return Status::OK();
}

Status DataPlane::Barrier() {
  uint8_t token = 1;
  return Allreduce(&token, 1, DataType::HVDTPU_UINT8, ReduceOp::SUM);
}

}  // namespace hvdtpu
