#include "events.h"

#include "metrics.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace hvdtpu {

namespace {

// ONE table: type name + the JSON key of each of the four args (empty =
// arg unused for that type). Order must match EventType.
struct EventSpec {
  const char* name;
  const char* a;
  const char* b;
  const char* c;
  const char* d;
};

const EventSpec kEventSpecs[(int)EventType::kTypeCount] = {
    {"negotiate_begin", "requests", "", "", ""},
    {"negotiate_end", "responses", "shutdown", "", ""},
    {"response_launch", "op_class", "device", "tensors", "bytes"},
    // wire_chunk packs b = (stripe channel << 1) | crc-verified;
    // wire_span packs a = plane | (stripe width << 1). Both decode to
    // named JSON keys below (the packed args stay "" here so the
    // generic emitter skips them).
    {"wire_chunk", "plane", "", "offset", "len"},
    {"wire_span", "", "dur_us", "tx_bytes", "rx_bytes"},
    // NB: no event arg may be named "rank" — the post-mortem merge
    // tags every timeline entry with its SOURCE rank under that key.
    {"crc_error", "sender", "fails", "chunk", ""},
    {"crc_resend", "", "", "chunk", ""},
    {"retry_window", "attempt", "window_ms", "", ""},
    {"wire_heal", "", "", "", ""},
    {"fault", "kind", "certain", "epoch", "fault_rank"},
    {"epoch", "", "", "epoch", "old_epoch"},
    {"reinit_begin", "size", "", "epoch", ""},
    {"reinit_end", "rc", "size", "epoch", ""},
    {"rejoin", "slots", "", "epoch", ""},
    {"knob_adopt", "knob", "", "value", ""},
    {"inject", "action", "", "op_index", ""},
    {"stall", "waited_s", "missing", "", ""},
    {"fault_notice", "fault_rank", "received", "", ""},
    {"phase", "phase", "", "dur_us", ""},
    // Step scoping (docs/metrics.md "Step anatomy"): every other event
    // attributes to the step window its timestamp falls inside.
    {"step_begin", "", "", "step", ""},
    {"step_end", "", "", "step", "dur_us"},
    // Serving-request lifecycle transition (docs/serving.md): rid in c
    // (an int64 request id), phase-specific aux in d.
    {"request", "phase", "", "rid", "aux"},
    // One hvdtpu_wait block, stamped at its END (wire_span convention):
    // ts_us - dur_us opens the interval. int64 c — long stalls overflow
    // an int32 microsecond arg in ~36 minutes.
    {"wait", "", "", "dur_us", ""},
    // SLO breach (docs/fleet.md): breach_rank names the breaching rank
    // ("rank" itself is reserved for the post-mortem merge), phase the
    // dominant rank-seconds bucket. Decoded names appended below.
    {"slo_breach", "objective", "breach_rank", "value", "phase"},
};

// Order is ABI with RequestPhase (events.h) and mirrored by
// telemetry.reqtrace.REQUEST_PHASES.
const char* kRequestPhaseNames[kReqPhaseCount] = {
    "queued",        "prefill",         "kv_ship",       "decode_wait",
    "decode_active", "evicted_requeue", "fault_requeue", "done",
};

const char* kKnobNames[] = {"fusion_bytes", "cycle_time_us", "ring_chunk",
                            "wire_compression", "hier_split",
                            "wire_channels"};

// Order is ABI with SloObjective (events.h) and mirrored by
// telemetry.slo.OBJECTIVES (analysis/model/abi.py pins both sides).
const char* kSloObjectiveNames[kSloObjectiveCount] = {
    "serving_p99_ms", "step_time_ewma_ms", "overlap_efficiency",
    "queued_idle_share", "stall_ms",
};

// Rank-seconds ledger buckets (docs/fleet.md), mirrored by
// telemetry.fleet.BUCKETS — the kSloBreach dominant-phase vocabulary.
const char* kRankBucketNames[] = {
    "compute",        "exposed_wire",  "negotiation",
    "serving_prefill", "serving_decode", "serving_queued",
    "stall",          "idle",          "unattributed",
};

thread_local int t_event_plane = 0;

}  // namespace

const char* RequestPhaseName(int phase) {
  if (phase < 0 || phase >= kReqPhaseCount) return "unknown";
  return kRequestPhaseNames[phase];
}

const char* SloObjectiveName(int objective) {
  if (objective < 0 || objective >= kSloObjectiveCount) return "unknown";
  return kSloObjectiveNames[objective];
}

const char* RankBucketName(int bucket) {
  constexpr int n = sizeof(kRankBucketNames) / sizeof(kRankBucketNames[0]);
  if (bucket < 0 || bucket >= n) return "unknown";
  return kRankBucketNames[bucket];
}

const char* EventTypeName(EventType t) {
  int i = (int)t;
  if (i < 0 || i >= (int)EventType::kTypeCount) return "unknown";
  return kEventSpecs[i].name;
}

void SetEventWirePlane(int plane) { t_event_plane = plane; }
int EventWirePlane() { return t_event_plane; }

bool EventRing::enabled() const {
  int32_t en = enabled_.load(std::memory_order_relaxed);
  if (en != -1) return en != 0;
  // Not yet resolved (no Record ran): answer from the env directly so
  // pre-init queries don't misreport HOROVOD_EVENTS=0 as enabled.
  const char* v = std::getenv("HOROVOD_EVENTS");
  return !(v != nullptr && std::strtoll(v, nullptr, 10) == 0);
}

void EventRing::Record(EventType t, int32_t a, int32_t b, int64_t c,
                       int64_t d) {
  int32_t en = enabled_.load(std::memory_order_relaxed);
  if (en == -1) {
    // Lazy env read, same pattern as the wire knobs: valid before init
    // and from any thread (the race writes the same value twice).
    const char* v = std::getenv("HOROVOD_EVENTS");
    en = (v != nullptr && std::strtoll(v, nullptr, 10) == 0) ? 0 : 1;
    enabled_.store(en, std::memory_order_relaxed);
  }
  if (en == 0) return;
  int64_t seq = head_.fetch_add(1, std::memory_order_acq_rel);
  Slot& s = slots_[seq % kCapacity];
  // Invalidate first so a concurrent reader can never stitch this
  // write's payload to the previous occupant's seq. The release fence
  // keeps the payload stores below from becoming visible BEFORE the
  // invalidation on weakly-ordered CPUs (a release store alone does
  // not order later stores) — the Boehm seqlock writer protocol.
  s.seq.store(-1, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
  s.ts_us.store(MetricsNowUs(), std::memory_order_relaxed);
  s.type.store((int32_t)t, std::memory_order_relaxed);
  s.a.store(a, std::memory_order_relaxed);
  s.b.store(b, std::memory_order_relaxed);
  s.c.store(c, std::memory_order_relaxed);
  s.d.store(d, std::memory_order_relaxed);
  // Publish only if the slot still holds OUR invalidation: a writer
  // descheduled long enough for the ring to lap a full kCapacity back
  // onto its slot would otherwise claim the lapping writer's payload
  // (or a mix) under its own stale seq — a torn record readers could
  // validate. On CAS failure poison the slot instead: one event is
  // dropped (forensically honest) rather than returned corrupt.
  int64_t expect = -1;
  if (!s.seq.compare_exchange_strong(expect, seq,
                                     std::memory_order_release,
                                     std::memory_order_relaxed)) {
    s.seq.store(-1, std::memory_order_release);
  }
}

bool EventRing::ReadSlot(int64_t seq, EventRecord* out) const {
  const Slot& s = slots_[seq % kCapacity];
  if (s.seq.load(std::memory_order_acquire) != seq) return false;
  out->seq = seq;
  out->ts_us = s.ts_us.load(std::memory_order_relaxed);
  out->type = (EventType)s.type.load(std::memory_order_relaxed);
  out->a = s.a.load(std::memory_order_relaxed);
  out->b = s.b.load(std::memory_order_relaxed);
  out->c = s.c.load(std::memory_order_relaxed);
  out->d = s.d.load(std::memory_order_relaxed);
  // Re-check: a writer may have lapped the ring mid-read. The acquire
  // fence pins the relaxed payload loads above ordering-wise BEFORE
  // this load — without it they may sink below the re-check and a torn
  // slot could pass validation (Boehm seqlock reader protocol).
  std::atomic_thread_fence(std::memory_order_acquire);
  return s.seq.load(std::memory_order_relaxed) == seq;
}

int64_t EventRing::Snapshot(int64_t from_seq,
                            std::vector<EventRecord>* out) const {
  int64_t h = head();
  int64_t lo = h > kCapacity ? h - kCapacity : 0;
  if (from_seq < lo) from_seq = lo;
  for (int64_t seq = from_seq; seq < h; seq++) {
    EventRecord e;
    if (ReadSlot(seq, &e)) out->push_back(e);
  }
  return h;
}

std::string EventJson(const EventRecord& e) {
  int i = (int)e.type;
  char buf[256];
  if (i < 0 || i >= (int)EventType::kTypeCount) {
    snprintf(buf, sizeof(buf),
             "{\"seq\":%lld,\"ts_us\":%lld,\"type\":\"unknown\"}",
             (long long)e.seq, (long long)e.ts_us);
    return buf;
  }
  const EventSpec& spec = kEventSpecs[i];
  std::string out;
  snprintf(buf, sizeof(buf), "{\"seq\":%lld,\"ts_us\":%lld,\"type\":\"%s\"",
           (long long)e.seq, (long long)e.ts_us, spec.name);
  out = buf;
  auto arg = [&](const char* key, long long v) {
    if (key[0] == '\0') return;
    snprintf(buf, sizeof(buf), ",\"%s\":%lld", key, v);
    out += buf;
  };
  arg(spec.a, e.a);
  arg(spec.b, e.b);
  arg(spec.c, e.c);
  arg(spec.d, e.d);
  // Unpack the stripe-channel tags (spec table note above): consumers
  // see plain "channel"/"crc"/"plane"/"channels" keys, never the
  // packed ints.
  if (e.type == EventType::kWireChunk) {
    arg("crc", e.b & 1);
    arg("channel", e.b >> 1);
  }
  if (e.type == EventType::kWireSpan) {
    arg("plane", e.a & 1);
    arg("channels", e.a >> 1);
  }
  // Decode the knob id inline so consumers never need the enum.
  if (e.type == EventType::kKnobAdopt && e.a >= 0 &&
      e.a < (int32_t)(sizeof(kKnobNames) / sizeof(kKnobNames[0]))) {
    out += ",\"knob_name\":\"";
    out += kKnobNames[e.a];
    out += "\"";
  }
  // Same courtesy for the control-plane phase id (ONE name table,
  // metrics.cc — the snapshot keys and the event decode cannot skew).
  if (e.type == EventType::kPhase) {
    out += ",\"phase_name\":\"";
    out += ControlPhaseName(e.a);
    out += "\"";
  }
  // And for the serving-request lifecycle phase (ONE table again —
  // reqtrace's stitcher reads the decoded name, never the id).
  if (e.type == EventType::kRequest) {
    out += ",\"phase_name\":\"";
    out += RequestPhaseName(e.a);
    out += "\"";
  }
  // SLO breach: decode both vocabulary ids (objective table and the
  // rank-seconds bucket table) — consumers read names, never indices.
  if (e.type == EventType::kSloBreach) {
    out += ",\"objective_name\":\"";
    out += SloObjectiveName(e.a);
    out += "\",\"phase_name\":\"";
    out += RankBucketName((int)e.d);
    out += "\"";
  }
  out += "}";
  return out;
}

std::string EventRing::Json(int64_t from_seq, int64_t* next_seq,
                            int64_t max_events) const {
  std::vector<EventRecord> evs;
  evs.reserve(256);
  int64_t h = Snapshot(from_seq, &evs);
  if (next_seq != nullptr) *next_seq = h;
  size_t start = 0;
  if (max_events > 0 && (int64_t)evs.size() > max_events) {
    start = evs.size() - (size_t)max_events;  // newest wins
  }
  std::string out = "[";
  for (size_t i = start; i < evs.size(); i++) {
    if (i > start) out += ",";
    out += EventJson(evs[i]);
  }
  out += "]";
  return out;
}

void EventRing::Reset() {
  // head_ keeps counting (cursors stay monotonic); slots are simply
  // invalidated so old payloads stop being readable.
  for (auto& s : slots_) s.seq.store(-1, std::memory_order_release);
}

EventRing& GlobalEvents() {
  static EventRing* r = new EventRing();  // never destroyed: the wire
  return *r;  // hot path may record during process teardown
}

}  // namespace hvdtpu
