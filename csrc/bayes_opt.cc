#include "bayes_opt.h"

#include <cmath>
#include <limits>

namespace hvdtpu {

BayesOpt::BayesOpt(std::vector<std::vector<double>> candidates,
                   double length_scale, double noise)
    : cand_(std::move(candidates)),
      ls2_(2.0 * length_scale * length_scale),
      noise_(noise) {}

BayesOpt::BayesOpt(std::vector<std::array<double, 2>> candidates,
                   double length_scale, double noise)
    : ls2_(2.0 * length_scale * length_scale), noise_(noise) {
  cand_.reserve(candidates.size());
  for (auto& c : candidates) cand_.push_back({c[0], c[1]});
}

double BayesOpt::Kernel(const std::vector<double>& a,
                        const std::vector<double>& b) const {
  double sq = 0;
  for (size_t i = 0; i < a.size(); i++) {
    double d = a[i] - b[i];
    sq += d * d;
  }
  return std::exp(-sq / ls2_);
}

void BayesOpt::AddSample(size_t idx, double y) {
  xs_.push_back(idx);
  ys_.push_back(y);
}

namespace {

// Standard normal pdf/cdf (cdf via erf).
double Phi(double z) { return 0.5 * std::erfc(-z / std::sqrt(2.0)); }
double phi(double z) {
  return std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
}

// Cholesky factorization of a (small) SPD matrix in place; returns false
// if the matrix is not positive definite.
bool Cholesky(std::vector<double>& m, size_t n) {
  for (size_t i = 0; i < n; i++) {
    for (size_t j = 0; j <= i; j++) {
      double s = m[i * n + j];
      for (size_t k = 0; k < j; k++) s -= m[i * n + k] * m[j * n + k];
      if (i == j) {
        if (s <= 0) return false;
        m[i * n + i] = std::sqrt(s);
      } else {
        m[i * n + j] = s / m[j * n + j];
      }
    }
  }
  return true;
}

// Solve L x = b (lower triangular), in place into b.
void SolveLower(const std::vector<double>& L, size_t n,
                std::vector<double>& b) {
  for (size_t i = 0; i < n; i++) {
    double s = b[i];
    for (size_t k = 0; k < i; k++) s -= L[i * n + k] * b[k];
    b[i] = s / L[i * n + i];
  }
}

// Solve L^T x = b, in place into b.
void SolveUpperT(const std::vector<double>& L, size_t n,
                 std::vector<double>& b) {
  for (size_t i = n; i-- > 0;) {
    double s = b[i];
    for (size_t k = i + 1; k < n; k++) s -= L[k * n + i] * b[k];
    b[i] = s / L[i * n + i];
  }
}

}  // namespace

size_t BayesOpt::Suggest() const {
  size_t n = xs_.size();
  if (n == 0) return 0;

  // Normalize observations to zero mean / unit variance so the unit-
  // variance RBF prior is well matched regardless of the score scale.
  double mean = 0;
  for (double y : ys_) mean += y;
  mean /= (double)n;
  double var = 0;
  for (double y : ys_) var += (y - mean) * (y - mean);
  double sd = n > 1 ? std::sqrt(var / (double)n) : 1.0;
  if (sd <= 0) sd = 1.0;
  std::vector<double> yn(n);
  double best_y = -std::numeric_limits<double>::infinity();
  for (size_t i = 0; i < n; i++) {
    yn[i] = (ys_[i] - mean) / sd;
    if (yn[i] > best_y) best_y = yn[i];
  }

  // GP fit: K = k(X,X) + noise*I, alpha = K^-1 y (via Cholesky).
  std::vector<double> K(n * n);
  for (size_t i = 0; i < n; i++) {
    for (size_t j = 0; j < n; j++) {
      K[i * n + j] = Kernel(cand_[xs_[i]], cand_[xs_[j]]) +
                     (i == j ? noise_ : 0.0);
    }
  }
  std::vector<double> alpha = yn;
  if (!Cholesky(K, n)) {
    // Numerically degenerate (e.g. identical repeated samples): fall
    // back to the best observed point.
    return Best();
  }
  SolveLower(K, n, alpha);
  SolveUpperT(K, n, alpha);

  // Expected improvement over the grid. Unseen candidates win exact EI
  // ties (flat posteriors would otherwise resample the lowest index).
  constexpr double kXi = 0.01;  // exploration margin
  double best_ei = -1;
  size_t best_idx = Best();
  std::vector<char> seen(cand_.size(), 0);
  for (size_t i : xs_) seen[i] = 1;
  std::vector<double> kstar(n), v(n);
  for (size_t c = 0; c < cand_.size(); c++) {
    for (size_t i = 0; i < n; i++) kstar[i] = Kernel(cand_[c], cand_[xs_[i]]);
    double mu = 0;
    for (size_t i = 0; i < n; i++) mu += kstar[i] * alpha[i];
    v = kstar;
    SolveLower(K, n, v);
    double var_c = Kernel(cand_[c], cand_[c]);
    for (size_t i = 0; i < n; i++) var_c -= v[i] * v[i];
    double sigma = var_c > 1e-12 ? std::sqrt(var_c) : 0.0;
    double ei;
    if (sigma == 0.0) {
      ei = mu - best_y - kXi > 0 ? mu - best_y - kXi : 0.0;
    } else {
      double z = (mu - best_y - kXi) / sigma;
      ei = (mu - best_y - kXi) * Phi(z) + sigma * phi(z);
    }
    if (ei > best_ei ||
        (ei == best_ei && !seen[c] && seen[best_idx])) {
      best_ei = ei;
      best_idx = c;
    }
  }
  return best_idx;
}

size_t BayesOpt::Best() const {
  // Mean observed score per candidate (repeat samples average).
  double best = -std::numeric_limits<double>::infinity();
  size_t best_idx = 0;
  for (size_t c = 0; c < cand_.size(); c++) {
    double sum = 0;
    int cnt = 0;
    for (size_t i = 0; i < xs_.size(); i++) {
      if (xs_[i] == c) {
        sum += ys_[i];
        cnt++;
      }
    }
    if (cnt && sum / cnt > best) {
      best = sum / cnt;
      best_idx = c;
    }
  }
  return best_idx;
}

double BayesOpt::MeanScore(size_t idx) const {
  double sum = 0;
  int cnt = 0;
  for (size_t i = 0; i < xs_.size(); i++) {
    if (xs_[i] == idx) {
      sum += ys_[i];
      cnt++;
    }
  }
  return cnt ? sum / cnt : 0.0;
}

}  // namespace hvdtpu
