// Adasum: adaptive-summation allreduce (scale-invariant gradient combine).
// Reference analog: horovod/common/ops/adasum/adasum.h (templated
// Adasum::DispatchFusedAllreduce) + adasum_mpi_operations.cc — there a
// recursive vector-halving distance-doubling over MPI; here full-vector
// recursive doubling over the TCP data plane (correctness-first; segments
// are host-memory bound, not wire bound, at test scale).
//
// Pairwise combine (Maleki et al., "Scaling Distributed Training with
// Adaptive Summation"): given partner gradients a, b,
//   adasum(a, b) = (1 - a.b / (2|a|^2)) a + (1 - a.b / (2|b|^2)) b
// which sums orthogonal gradients and averages parallel ones.

#include <cstring>
#include <vector>

#include "half.h"
#include "ring_ops.h"
#include "wire.h"

namespace hvdtpu {

namespace {

template <typename T>
void AdasumCombine(T* a, const T* b, int64_t count) {
  double dot = 0, na = 0, nb = 0;
  for (int64_t i = 0; i < count; i++) {
    double da = (double)a[i], db = (double)b[i];
    dot += da * db;
    na += da * da;
    nb += db * db;
  }
  // Zero-norm side contributes nothing to the projection: plain add.
  double ca = na == 0.0 ? 1.0 : 1.0 - dot / (2.0 * na);
  double cb = nb == 0.0 ? 1.0 : 1.0 - dot / (2.0 * nb);
  for (int64_t i = 0; i < count; i++) {
    a[i] = (T)(ca * (double)a[i] + cb * (double)b[i]);
  }
}

// f16/bf16 combine in float32 working precision.
template <uint16_t (*ToBits)(float), float (*FromBits)(uint16_t)>
void AdasumCombineHalfLike(uint16_t* a, const uint16_t* b, int64_t count) {
  std::vector<float> fa(count), fb(count);
  for (int64_t i = 0; i < count; i++) {
    fa[i] = FromBits(a[i]);
    fb[i] = FromBits(b[i]);
  }
  AdasumCombine(fa.data(), fb.data(), count);
  for (int64_t i = 0; i < count; i++) a[i] = ToBits(fa[i]);
}

Status AdasumDispatchCombine(void* a, const void* b, int64_t count,
                             DataType dt) {
  switch (dt) {
    case DataType::HVDTPU_FLOAT32:
      AdasumCombine((float*)a, (const float*)b, count);
      return Status::OK();
    case DataType::HVDTPU_FLOAT64:
      AdasumCombine((double*)a, (const double*)b, count);
      return Status::OK();
    case DataType::HVDTPU_FLOAT16:
      AdasumCombineHalfLike<FloatToHalfBits, HalfBitsToFloat>(
          (uint16_t*)a, (const uint16_t*)b, count);
      return Status::OK();
    case DataType::HVDTPU_BFLOAT16:
      AdasumCombineHalfLike<FloatToBF16Bits, BF16BitsToFloat>(
          (uint16_t*)a, (const uint16_t*)b, count);
      return Status::OK();
    default:
      return Status::InvalidArgument(
          "Adasum requires a floating-point dtype, got " +
          std::string(DataTypeName(dt)));
  }
}

}  // namespace

Status DataPlane::AdasumAllreduce(void* buf, int64_t count, DataType dt) {
  // Validate the dtype BEFORE any wire traffic: every rank must make the
  // same go/no-go decision or the exchange pattern desynchronizes (ranks
  // that only relay, e.g. the extras fold, would hang on dead partners).
  switch (dt) {
    case DataType::HVDTPU_FLOAT16:
    case DataType::HVDTPU_BFLOAT16:
    case DataType::HVDTPU_FLOAT32:
    case DataType::HVDTPU_FLOAT64:
      break;
    default:
      return Status::InvalidArgument(
          "Adasum requires a floating-point dtype, got " +
          std::string(DataTypeName(dt)));
  }
  if (size_ == 1 || count == 0) return Status::OK();
  const int64_t bytes = count * DataTypeSize(dt);
  std::vector<uint8_t> remote((size_t)bytes);

  // p = largest power of two <= size; the `extras` (ranks >= p) fold into
  // their partner below p first, then receive the final result back.
  int p = 1;
  while (p * 2 <= size_) p *= 2;
  const int extras = size_ - p;

  if (rank_ >= p) {
    Status s = SendAll(peer_fds_[rank_ - p], buf, (size_t)bytes);
    if (!s.ok()) return s;
    return RecvAll(peer_fds_[rank_ - p], buf, (size_t)bytes);
  }
  if (rank_ < extras) {
    Status s = RecvAll(peer_fds_[rank_ + p], remote.data(), (size_t)bytes);
    if (!s.ok()) return s;
    s = AdasumDispatchCombine(buf, remote.data(), count, dt);
    if (!s.ok()) return s;
  }

  // Recursive doubling among ranks < p. Both partners compute the same
  // symmetric combine, so no result exchange is needed per level.
  for (int dist = 1; dist < p; dist *= 2) {
    int partner = rank_ ^ dist;
    int fd = peer_fds_[partner];
    Status s = DuplexTransfer(fd, buf, (size_t)bytes, fd, remote.data(),
                              (size_t)bytes);
    if (!s.ok()) return s;
    s = AdasumDispatchCombine(buf, remote.data(), count, dt);
    if (!s.ok()) return s;
  }

  if (rank_ < extras) {
    return SendAll(peer_fds_[rank_ + p], buf, (size_t)bytes);
  }
  return Status::OK();
}

}  // namespace hvdtpu
