// Leveled stderr logging.
// Reference analog: horovod/common/logging.h LOG(level) macros controlled by
// HOROVOD_LOG_LEVEL / HOROVOD_LOG_TIMESTAMP.

#ifndef HVDTPU_LOGGING_H
#define HVDTPU_LOGGING_H

#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>

namespace hvdtpu {

enum class LogLevel : int { TRACE = 0, DEBUG = 1, INFO = 2, WARN = 3, ERROR = 4, NONE = 5 };

inline LogLevel GlobalLogLevel() {
  static LogLevel level = [] {
    const char* env = std::getenv("HOROVOD_LOG_LEVEL");
    if (!env) return LogLevel::WARN;
    if (!strcasecmp(env, "trace")) return LogLevel::TRACE;
    if (!strcasecmp(env, "debug")) return LogLevel::DEBUG;
    if (!strcasecmp(env, "info")) return LogLevel::INFO;
    if (!strcasecmp(env, "warning") || !strcasecmp(env, "warn"))
      return LogLevel::WARN;
    if (!strcasecmp(env, "error")) return LogLevel::ERROR;
    if (!strcasecmp(env, "none")) return LogLevel::NONE;
    return LogLevel::WARN;
  }();
  return level;
}

inline void LogWrite(LogLevel lvl, const char* tag, const char* fmt, ...) {
  if ((int)lvl < (int)GlobalLogLevel()) return;
  char msg[2048];
  va_list ap;
  va_start(ap, fmt);
  vsnprintf(msg, sizeof(msg), fmt, ap);
  va_end(ap);
  const char* ts_env = std::getenv("HOROVOD_LOG_TIMESTAMP");
  if (ts_env && ts_env[0] == '1') {
    time_t t = time(nullptr);
    struct tm tmv;
    localtime_r(&t, &tmv);
    char ts[32];
    strftime(ts, sizeof(ts), "%F %T", &tmv);
    fprintf(stderr, "[%s] [hvdtpu %s] %s\n", ts, tag, msg);
  } else {
    fprintf(stderr, "[hvdtpu %s] %s\n", tag, msg);
  }
}

#define LOG_TRACE(...) ::hvdtpu::LogWrite(::hvdtpu::LogLevel::TRACE, "TRACE", __VA_ARGS__)
#define LOG_DEBUG(...) ::hvdtpu::LogWrite(::hvdtpu::LogLevel::DEBUG, "DEBUG", __VA_ARGS__)
#define LOG_INFO(...) ::hvdtpu::LogWrite(::hvdtpu::LogLevel::INFO, "INFO", __VA_ARGS__)
#define LOG_WARN(...) ::hvdtpu::LogWrite(::hvdtpu::LogLevel::WARN, "WARN", __VA_ARGS__)
#define LOG_ERROR(...) ::hvdtpu::LogWrite(::hvdtpu::LogLevel::ERROR, "ERROR", __VA_ARGS__)

}  // namespace hvdtpu

#endif  // HVDTPU_LOGGING_H
