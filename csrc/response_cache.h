// Response cache: the steady-state fast path of the negotiation protocol.
// After a tensor's first full negotiation, every rank caches the resulting
// single-tensor Response at an agreed bit position; subsequent cycles send a
// bitvector of positions instead of full Requests, and the coordinator
// completes a position once every member of its process set has submitted
// the bit (or joined).
// Reference analog: horovod/common/response_cache.h (ResponseCache,
// CacheCoordinator). Rebuilt deterministically over the broadcast
// ResponseList: insertions and evictions are driven only by bytes every rank
// sees, so cache state stays bit-identical across ranks with no extra
// synchronization round.

#ifndef HVDTPU_RESPONSE_CACHE_H
#define HVDTPU_RESPONSE_CACHE_H

#include <atomic>
#include <string>
#include <unordered_map>
#include <vector>

#include "message.h"

namespace hvdtpu {

class ResponseCache {
 public:
  enum class LookupResult { MISS, HIT, INVALID };

  void SetCapacity(int64_t cap) { capacity_ = cap; }
  bool enabled() const { return capacity_ > 0; }

  // Classify an outgoing request against the cache. HIT: *pos is the cached
  // bit position and the metadata matches, send the bit. INVALID: the key is
  // cached at *pos but shape/dtype/op changed — send the bit as invalid plus
  // the full request so the coordinator evicts everywhere and renegotiates.
  LookupResult Lookup(const Request& req, int32_t* pos);

  // Deterministic insertion of eligible tensors of a freshly negotiated
  // (broadcast) response list; fused responses are split per tensor. Every
  // rank calls this with identical bytes in the same cycle.
  void InsertFromResponses(const std::vector<Response>& responses);

  void Evict(int32_t pos);
  bool Has(int32_t pos) const;
  // Single-tensor cached response at pos (valid only when Has(pos)).
  const Response& Get(int32_t pos) const;

  int64_t hits() const { return hits_.load(); }
  int64_t misses() const { return misses_.load(); }
  int64_t entries() const { return entries_count_.load(); }
  // Payload bytes whose negotiation was skipped by a cache hit — the
  // wire traffic the bitvector path saved from full renegotiation
  // (metrics snapshot: cache.hit_bytes).
  int64_t hit_bytes() const { return hit_bytes_.load(); }

 private:
  struct Slot {
    Response response;
    std::string key;
    bool valid = false;
  };
  static std::string KeyOf(const std::string& name, int32_t process_set_id);
  static bool Eligible(const Response& r);

  int64_t capacity_ = 1024;  // HOROVOD_CACHE_CAPACITY; 0 disables
  std::vector<Slot> slots_;             // index == bit position
  std::vector<int32_t> free_positions_;  // ascending; reuse smallest first
  std::unordered_map<std::string, int32_t> index_;
  std::atomic<int64_t> hits_{0}, misses_{0}, entries_count_{0};
  std::atomic<int64_t> hit_bytes_{0};
  bool warned_full_ = false;
};

}  // namespace hvdtpu

#endif  // HVDTPU_RESPONSE_CACHE_H
