#include "response_cache.h"

#include <algorithm>

#include "logging.h"

namespace hvdtpu {

namespace {

// First tensor's shape in a single-tensor response.
std::vector<int64_t> FirstShape(const Response& r) {
  size_t pos = 0;
  return DecodeShapeAt(r, &pos);
}

Response::ResponseType ExpectedType(RequestType t) {
  switch (t) {
    case RequestType::ALLREDUCE: return Response::ResponseType::ALLREDUCE;
    case RequestType::BROADCAST: return Response::ResponseType::BROADCAST;
    case RequestType::ALLTOALL: return Response::ResponseType::ALLTOALL;
    case RequestType::REDUCESCATTER:
      return Response::ResponseType::REDUCESCATTER;
    default: return Response::ResponseType::ERROR;  // never cached
  }
}

}  // namespace

std::string ResponseCache::KeyOf(const std::string& name,
                                 int32_t process_set_id) {
  // Same key scheme as Controller::TableKey ('\x1f' cannot appear in a
  // Python-supplied tensor name).
  return name + '\x1f' + std::to_string(process_set_id);
}

bool ResponseCache::Eligible(const Response& r) {
  switch (r.response_type) {
    case Response::ResponseType::ALLREDUCE:
      // Adasum responses never fuse and carry per-tensor normalization;
      // keep them on the full negotiation path.
      return r.reduce_op != ReduceOp::ADASUM;
    case Response::ResponseType::BROADCAST:
    case Response::ResponseType::REDUCESCATTER:
      // Fixed-shape collectives. Allgather has data-dependent first
      // dims, so it renegotiates every time.
      return true;
    case Response::ResponseType::ALLTOALL:
      // Host alltoall has data-dependent splits; DEVICE alltoall is
      // equal-split with identical shapes on every rank (the controller
      // enforces it), which is exactly what the cache's shape match
      // needs.
      return r.device == 1;
    default:
      return false;
  }
}

ResponseCache::LookupResult ResponseCache::Lookup(const Request& req,
                                                  int32_t* pos) {
  if (!enabled() || req.group_id >= 0) {
    // Grouped tensors renegotiate every time: the per-tensor cache-hit
    // bitvector cannot preserve group atomicity.
    misses_++;
    return LookupResult::MISS;
  }
  auto it = index_.find(KeyOf(req.tensor_name, req.process_set_id));
  if (it == index_.end()) {
    misses_++;
    return LookupResult::MISS;
  }
  *pos = it->second;
  const Response& r = slots_[it->second].response;
  bool match = r.response_type == ExpectedType(req.request_type) &&
               r.tensor_type == req.tensor_type &&
               r.device == req.device &&
               FirstShape(r) == req.tensor_shape;
  if (match) {
    switch (r.response_type) {
      case Response::ResponseType::ALLREDUCE:
      case Response::ResponseType::REDUCESCATTER:
        match = r.reduce_op == req.reduce_op;
        break;
      case Response::ResponseType::BROADCAST:
        match = r.root_rank == req.root_rank;
        break;
      default:
        break;
    }
  }
  if (match) {
    hits_++;
    hit_bytes_ += ShapesTotalBytes(r);
    return LookupResult::HIT;
  }
  // Metadata changed (new shape/dtype/op under an old name): coordinate a
  // global eviction, then renegotiate via the accompanying full request.
  misses_++;
  return LookupResult::INVALID;
}

void ResponseCache::InsertFromResponses(
    const std::vector<Response>& responses) {
  if (!enabled()) return;
  for (const Response& res : responses) {
    // Grouped responses are never cached (see Lookup).
    if (res.group_id >= 0 || !Eligible(res)) continue;
    // Split a fused response into per-tensor cache entries.
    size_t shape_pos = 0;
    for (size_t i = 0; i < res.tensor_names.size(); i++) {
      std::vector<int64_t> shape = DecodeShapeAt(res, &shape_pos);
      std::string key = KeyOf(res.tensor_names[i], res.process_set_id);
      if (index_.count(key)) continue;  // already cached (shouldn't happen)
      int32_t pos;
      if (!free_positions_.empty()) {
        pos = free_positions_.front();
        free_positions_.erase(free_positions_.begin());
      } else if ((int64_t)slots_.size() < capacity_) {
        pos = (int32_t)slots_.size();
        slots_.emplace_back();
      } else {
        if (!warned_full_) {
          warned_full_ = true;
          LOG_WARN(
              "response cache full (%lld entries); further tensors take the "
              "full negotiation path every cycle. Raise "
              "HOROVOD_CACHE_CAPACITY.",
              (long long)capacity_);
        }
        return;
      }
      Slot& slot = slots_[pos];
      slot.key = key;
      slot.valid = true;
      slot.response.response_type = res.response_type;
      slot.response.tensor_names = {res.tensor_names[i]};
      slot.response.tensor_type = res.tensor_type;
      slot.response.tensor_shapes.clear();
      slot.response.tensor_shapes.push_back((int64_t)shape.size());
      slot.response.tensor_shapes.insert(slot.response.tensor_shapes.end(),
                                         shape.begin(), shape.end());
      slot.response.reduce_op = res.reduce_op;
      slot.response.root_rank = res.root_rank;
      slot.response.process_set_id = res.process_set_id;
      slot.response.device = res.device;
      slot.response.tensor_sizes.clear();
      slot.response.error_message.clear();
      index_[key] = pos;
      entries_count_++;
    }
  }
}

void ResponseCache::Evict(int32_t pos) {
  if (pos < 0 || (size_t)pos >= slots_.size() || !slots_[pos].valid) return;
  index_.erase(slots_[pos].key);
  slots_[pos].valid = false;
  slots_[pos].key.clear();
  auto it = std::lower_bound(free_positions_.begin(), free_positions_.end(),
                             pos);
  free_positions_.insert(it, pos);
  entries_count_--;
}

bool ResponseCache::Has(int32_t pos) const {
  return pos >= 0 && (size_t)pos < slots_.size() && slots_[pos].valid;
}

const Response& ResponseCache::Get(int32_t pos) const {
  return slots_[pos].response;
}

}  // namespace hvdtpu
