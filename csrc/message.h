// Negotiation wire protocol: Request / Response (+ lists) and their binary
// serialization.
// Reference analog: horovod/common/message.h (Request, Response,
// RequestList, ResponseList, SerializeToString/ParseFromBytes). Rebuilt with
// a simple custom LE binary format (the reference dropped flatbuffers for a
// custom format too).

#ifndef HVDTPU_MESSAGE_H
#define HVDTPU_MESSAGE_H

#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

enum class RequestType : int32_t {
  ALLREDUCE = 0,
  ALLGATHER = 1,
  BROADCAST = 2,
  ALLTOALL = 3,
  REDUCESCATTER = 4,
  JOIN = 5,
  BARRIER = 6,
};

const char* RequestTypeName(RequestType t);

// One rank announcing one tensor is ready.
struct Request {
  int32_t request_rank = 0;
  RequestType request_type = RequestType::ALLREDUCE;
  DataType tensor_type = DataType::HVDTPU_FLOAT32;
  std::string tensor_name;
  int32_t root_rank = 0;
  ReduceOp reduce_op = ReduceOp::SUM;
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
  std::vector<int64_t> tensor_shape;
  int32_t process_set_id = 0;
  int32_t group_id = -1;  // grouped allreduce: negotiate atomically
  // Number of tensors in the group (all members carry it; lets the
  // coordinator hold the group back until every member is ready on
  // every rank).
  int32_t group_size = 0;
  std::vector<int64_t> splits;  // alltoall send splits
  // 1 = execute on the registered device data plane (XLA/ICI), 0 = host
  // ring. All ranks must agree per tensor (validated like dtype/shape).
  int32_t device = 0;
};

// Coordinator verdict: a (possibly fused) set of tensors to execute, or an
// error.
struct Response {
  enum class ResponseType : int32_t {
    ALLREDUCE = 0,
    ALLGATHER = 1,
    BROADCAST = 2,
    ALLTOALL = 3,
    REDUCESCATTER = 4,
    JOIN = 5,
    BARRIER = 6,
    ERROR = 7,
  };
  ResponseType response_type = ResponseType::ALLREDUCE;
  std::vector<std::string> tensor_names;  // >1 => fused
  std::string error_message;
  DataType tensor_type = DataType::HVDTPU_FLOAT32;
  // Allgather/reducescatter: per tensor, per rank first-dimension sizes, laid
  // out [tensor0_rank0, tensor0_rank1, ..., tensor1_rank0, ...].
  std::vector<int64_t> tensor_sizes;
  // Per-tensor full shapes, flattened [ndim0, dims0..., ndim1, dims1...].
  // Lets a joined rank synthesize a zero contribution for a tensor it never
  // enqueued (reference analog: Response::tensor_sizes use in join path).
  std::vector<int64_t> tensor_shapes;
  ReduceOp reduce_op = ReduceOp::SUM;
  int32_t root_rank = 0;  // broadcast: joined ranks need it to synthesize
  int32_t process_set_id = 0;
  int32_t last_joined_rank = -1;
  // Mirrors Request::device: 1 routes the fused group to the registered
  // device data plane instead of the host ring ops.
  int32_t device = 0;
  // >= 0 marks an atomically-negotiated group's fused response; such
  // responses are pure (only group members) and are never cached.
  int32_t group_id = -1;
};

// Decoders for Response::tensor_shapes's flattened [ndim, dims...] layout —
// the one place that knows it (controller fusion accounting, response-cache
// shape checks, and autotune scoring all decode through these).

// Shape of the tensor starting at *pos; advances *pos past it.
inline std::vector<int64_t> DecodeShapeAt(const Response& r, size_t* pos) {
  std::vector<int64_t> shape;
  if (*pos >= r.tensor_shapes.size()) return shape;
  int64_t ndim = r.tensor_shapes[(*pos)++];
  for (int64_t i = 0; i < ndim && *pos < r.tensor_shapes.size(); i++) {
    shape.push_back(r.tensor_shapes[(*pos)++]);
  }
  return shape;
}

// Total payload bytes across every tensor encoded in the response.
inline int64_t ShapesTotalBytes(const Response& r) {
  int64_t total = 0;
  size_t pos = 0;
  while (pos < r.tensor_shapes.size()) {
    int64_t elems = 1;
    for (int64_t d : DecodeShapeAt(r, &pos)) elems *= d;
    total += elems * DataTypeSize(r.tensor_type);
  }
  return total;
}

// Everything one worker sends the coordinator in one cycle.
struct RequestList {
  std::vector<Request> requests;
  bool shutdown = false;
  // Origin rank of this list. The flat star gather knows the sender
  // from the socket it read; the tree gather (HOROVOD_CONTROL_TREE)
  // relays frames through interior workers, so the frame itself must
  // name its origin. -1 = unset (pre-tree frames; the flat path keeps
  // using the positional fd).
  int32_t rank = -1;
  // Membership epoch this worker believes it is in. The coordinator
  // rejects frames from any other epoch, so a half-dead rank from a
  // previous ring generation cannot poison the re-formed ring
  // (docs/elastic.md). Bumped by hvdtpu_reinit; 0 for a fresh init.
  int64_t epoch = 0;
  // Response-cache bitvector: positions (in the shared cache order) of
  // cache-hit tensors ready this cycle. Reference analog:
  // horovod/common/response_cache.cc CacheCoordinator bit vectors.
  std::vector<int64_t> cache_hits;
  // Cached positions whose metadata no longer matches (shape/dtype/op
  // changed): the coordinator broadcasts an eviction and the full request
  // (also in `requests`) renegotiates.
  std::vector<int64_t> cache_invalid;
};

// Everything the coordinator broadcasts back in one cycle.
struct ResponseList {
  std::vector<Response> responses;
  bool shutdown = false;
  // Mirrors RequestList::epoch: workers reject responses from a stale
  // epoch the same way the coordinator fences stale requests.
  int64_t epoch = 0;
  // Nonempty = fault notice: the coordinator detected these (global)
  // ranks dead/unresponsive and is tearing this epoch down. Workers
  // stop their loop with a typed PeerFailure instead of waiting out
  // their own wire timeout against the broken ring.
  std::vector<int64_t> fault_ranks;
  // Autotuned runtime knobs, pushed coordinator -> workers (0 = unset).
  // Reference analog: parameter_manager.cc values synced via the controller.
  int64_t fusion_threshold_bytes = 0;
  double cycle_time_ms = 0;
  // Ring transport knobs (-1 = unset; chunk 0 is a legal value — the
  // bulk-synchronous path). These MUST stay rank-uniform: the chunk
  // split is the message framing on the external transport, and the
  // compression flag decides the per-hop wire width, so the autotuner
  // syncs them the same way it syncs fusion/cycle.
  int64_t ring_chunk_bytes = -1;
  int32_t wire_compression = -1;  // -1 unset, 0 off, 1 on
  // Hierarchy split point of the cross-plane allreduce (-1 unset,
  // 0 = flat ring, >= 2 = intra-slice group size). Rank-uniform for
  // the same reason as the ring knobs: every rank must decompose the
  // SAME collective into the SAME plane sequence in the same cycle.
  int32_t hier_split = -1;
  // Active stripe width of the multi-channel wire transport (-1 unset,
  // >= 1 = channels; clamped to the established socket count at use
  // sites). Rank-uniform: the chunk->channel round-robin IS the
  // framing, so the autotuner flips it in the same lockstep cycle as
  // the chunk knob (docs/wire.md).
  int32_t wire_channels = -1;
  // Response-cache verdicts. Positions ready on every member rank this
  // cycle, grouped for fusion: group_sizes partitions cache_hit_positions
  // (e.g. [3,1] = first three fuse into one allreduce, next is alone).
  // Every rank rebuilds identical Responses from its local cache copy.
  std::vector<int64_t> cache_hit_positions;
  std::vector<int64_t> cache_hit_group_sizes;
  // Positions every rank must evict before processing hits/insertions.
  std::vector<int64_t> cache_evictions;
};

std::string SerializeRequestList(const RequestList& list);
Status ParseRequestList(const std::string& buf, RequestList* list);
std::string SerializeResponseList(const ResponseList& list);
Status ParseResponseList(const std::string& buf, ResponseList* list);

}  // namespace hvdtpu

#endif  // HVDTPU_MESSAGE_H
