#include "timeline.h"

#include <chrono>
#include <cstdio>

namespace hvdtpu {

namespace {
int64_t NowMicros() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Escape a string for embedding in JSON.
std::string JsonEscape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    if (c == '"' || c == '\\') {
      out.push_back('\\');
      out.push_back(c);
    } else if ((unsigned char)c < 0x20) {
      char buf[8];
      snprintf(buf, sizeof(buf), "\\u%04x", c);
      out += buf;
    } else {
      out.push_back(c);
    }
  }
  return out;
}
}  // namespace

Timeline::~Timeline() { Shutdown(); }

void Timeline::Initialize(const std::string& path, int rank) {
  file_ = fopen(path.c_str(), "w");
  if (!file_) return;
  rank_ = rank;
  start_us_ = NowMicros();
  fputs("[\n", file_);
  // Header events, written before the async writer starts:
  // - process_name metadata so Perfetto labels each pid as its rank;
  // - CLOCK_SYNC carrying this trace's t=0 as wall-clock unix us, the
  //   anchor horovod_tpu.telemetry.report uses to put per-rank traces
  //   (whose ts are steady-clock-relative) on one time axis.
  int64_t unix_us = std::chrono::duration_cast<std::chrono::microseconds>(
                        std::chrono::system_clock::now().time_since_epoch())
                        .count();
  fprintf(file_,
          "{\"name\": \"process_name\", \"ph\": \"M\", \"pid\": %d, "
          "\"args\": {\"name\": \"rank %d\"}},\n"
          "{\"name\": \"CLOCK_SYNC\", \"ph\": \"i\", \"ts\": 0, "
          "\"pid\": %d, \"tid\": 0, \"s\": \"p\", "
          "\"args\": {\"unix_us\": %lld, \"rank\": %d}},\n",
          rank, rank, rank, (long long)unix_us, rank);
  enabled_ = true;
  stop_ = false;
  writer_ = std::thread(&Timeline::WriterLoop, this);
}

void Timeline::Shutdown() {
  if (!enabled_.exchange(false)) return;
  {
    std::lock_guard<std::mutex> lk(mutex_);
    stop_ = true;
  }
  cv_.notify_all();
  if (writer_.joinable()) writer_.join();
  if (file_) {
    fputs("{}]\n", file_);
    fclose(file_);
    file_ = nullptr;
  }
}

void Timeline::WriterLoop() {
  // Async writer thread so trace IO never blocks the coordination loop.
  // Reference analog: horovod/common/timeline.cc TimelineWriter.
  std::unique_lock<std::mutex> lk(mutex_);
  while (true) {
    cv_.wait(lk, [&] { return stop_ || !queue_.empty(); });
    while (!queue_.empty()) {
      std::string ev = std::move(queue_.front());
      queue_.pop_front();
      lk.unlock();
      fputs(ev.c_str(), file_);
      lk.lock();
    }
    if (stop_) break;
  }
  fflush(file_);
}

void Timeline::Emit(const std::string& tensor, char phase,
                    const std::string& label) {
  if (!enabled_.load()) return;
  char buf[512];
  // tid: stable per-tensor lane so each tensor renders as one row.
  size_t tid = std::hash<std::string>{}(tensor) % 997;
  snprintf(buf, sizeof(buf),
           "{\"name\": \"%s\", \"ph\": \"%c\", \"ts\": %lld, \"pid\": %d, "
           "\"tid\": %zu, \"args\": {\"tensor\": \"%s\"}},\n",
           JsonEscape(label).c_str(), phase,
           (long long)(NowMicros() - start_us_), rank_, tid,
           JsonEscape(tensor).c_str());
  {
    std::lock_guard<std::mutex> lk(mutex_);
    queue_.emplace_back(buf);
  }
  cv_.notify_one();
}

void Timeline::MarkCycle() { Emit("__cycle__", 'i', "CYCLE"); }
void Timeline::NegotiateStart(const std::string& t) { Emit(t, 'B', "NEGOTIATE"); }
void Timeline::NegotiateEnd(const std::string& t) { Emit(t, 'E', "NEGOTIATE"); }
void Timeline::EntryQueued(const std::string& t) { Emit(t, 'i', "QUEUED"); }
void Timeline::ActivityStart(const std::string& t, const std::string& a) {
  Emit(t, 'B', a);
}
void Timeline::ActivityEnd(const std::string& t) { Emit(t, 'E', "ACTIVITY"); }
void Timeline::EntryDone(const std::string& t) { Emit(t, 'i', "DONE"); }

}  // namespace hvdtpu
