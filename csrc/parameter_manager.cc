#include "parameter_manager.h"

#include <algorithm>

#include "logging.h"

namespace hvdtpu {

namespace {
constexpr int64_t kMinWindowBytes = 1 << 20;   // score only meaningful windows
constexpr int kMinWindowCycles = 20;
constexpr double kMaxWindowSecs = 5.0;
constexpr double kImprovementEps = 1.05;       // 5% better = accept move
}  // namespace

void ParameterManager::Initialize(int64_t fusion_bytes, double cycle_ms,
                                  const std::string& log_path) {
  for (int64_t v = 1 << 20; v <= (64 << 20); v *= 2) {
    fusion_values_.push_back(v);
  }
  cycle_values_ = {0.5, 1.0, 2.5, 5.0, 10.0};
  // Start from the user-provided operating point (snap onto the grids).
  fusion_idx_ = 0;
  for (size_t i = 0; i < fusion_values_.size(); i++) {
    if (fusion_values_[i] <= fusion_bytes) fusion_idx_ = i;
  }
  cycle_idx_ = 0;
  for (size_t i = 0; i < cycle_values_.size(); i++) {
    if (cycle_values_[i] <= cycle_ms) cycle_idx_ = i;
  }
  if (!log_path.empty()) {
    log_ = fopen(log_path.c_str(), "w");
    if (log_) {
      fprintf(log_, "fusion_threshold_bytes,cycle_time_ms,score_bytes_per_sec\n");
      fflush(log_);
    }
  }
  active_ = true;
}

ParameterManager::~ParameterManager() {
  if (log_) fclose(log_);
}

void ParameterManager::Log(double score) {
  if (!log_) return;
  fprintf(log_, "%lld,%.3f,%.0f\n",
          (long long)fusion_threshold_bytes(), cycle_time_ms(), score);
  fflush(log_);
}

bool ParameterManager::Move(int direction) {
  if (axis_ == 0) {
    size_t prev = fusion_idx_;
    fusion_idx_ = (size_t)std::clamp<int64_t>(
        (int64_t)fusion_idx_ + direction, 0,
        (int64_t)fusion_values_.size() - 1);
    return fusion_idx_ != prev;
  }
  size_t prev = cycle_idx_;
  cycle_idx_ = (size_t)std::clamp<int64_t>(
      (int64_t)cycle_idx_ + direction, 0, (int64_t)cycle_values_.size() - 1);
  return cycle_idx_ != prev;
}

void ParameterManager::AdvanceAxis() {
  axis_ = 1 - axis_;
  have_baseline_ = false;
  tries_ = 0;
  if (axis_ == 0 && --sweeps_left_ <= 0) {
    done_ = true;
    LOG_INFO("autotune converged: fusion=%lld bytes, cycle=%.2f ms",
             (long long)fusion_threshold_bytes(), cycle_time_ms());
  }
}

void ParameterManager::TryProbe() {
  // Place the next probe; a clamped (no-op) Move means the grid edge —
  // skip straight to the other direction or the next axis, so an "undo"
  // is only ever applied to a probe that actually moved.
  while (!done_) {
    if (Move(direction_)) return;  // probe placed; next window scores it
    if (++tries_ < 2) {
      direction_ = -direction_;
      continue;
    }
    AdvanceAxis();
    return;  // new axis re-baselines on the next window
  }
}

void ParameterManager::Score(double bytes_per_sec) {
  Log(bytes_per_sec);
  if (done_) return;
  if (!have_baseline_) {
    // First scored window at the current point: probe up the active axis.
    baseline_score_ = bytes_per_sec;
    have_baseline_ = true;
    direction_ = +1;
    tries_ = 0;
    TryProbe();
    return;
  }
  if (bytes_per_sec > baseline_score_ * kImprovementEps) {
    // Improvement: adopt the probed point, keep walking this direction.
    baseline_score_ = bytes_per_sec;
    tries_ = 0;
    TryProbe();
    return;
  }
  // Not better: undo the probe (guaranteed to have moved — see TryProbe),
  // then try the other direction once, else advance to the next axis.
  Move(-direction_);
  if (++tries_ < 2) {
    direction_ = -direction_;
    TryProbe();
    return;
  }
  AdvanceAxis();
}

bool ParameterManager::Update(int64_t bytes) {
  if (!active_ || done_) return false;
  auto now = std::chrono::steady_clock::now();
  if (!window_started_) {
    window_start_ = now;
    window_started_ = true;
    window_bytes_ = 0;
    window_cycles_ = 0;
  }
  window_bytes_ += bytes;
  window_cycles_++;
  double secs = std::chrono::duration<double>(now - window_start_).count();
  bool window_full = (window_bytes_ >= kMinWindowBytes &&
                      window_cycles_ >= kMinWindowCycles) ||
                     secs >= kMaxWindowSecs;
  if (!window_full || secs <= 0) return false;
  int64_t prev_fusion = fusion_threshold_bytes();
  double prev_cycle = cycle_time_ms();
  if (warmup_windows_ > 0) {
    warmup_windows_--;  // discard: startup warmup pollutes the score
  } else if (window_bytes_ > 0) {
    Score((double)window_bytes_ / secs);
  }
  window_started_ = false;
  return fusion_threshold_bytes() != prev_fusion ||
         cycle_time_ms() != prev_cycle;
}

}  // namespace hvdtpu
