#include "parameter_manager.h"

#include <algorithm>
#include <cmath>

#include "logging.h"

namespace hvdtpu {

namespace {
constexpr double kMaxWindowSecs = 5.0;
}  // namespace

void ParameterManager::Initialize(int64_t fusion_bytes, double cycle_ms,
                                  const std::string& log_path,
                                  int max_samples, int64_t window_bytes,
                                  int window_cycles,
                                  int64_t ring_chunk_bytes,
                                  int wire_codec,
                                  bool tune_wire_codec,
                                  std::vector<int64_t> hier_values,
                                  int64_t hier_split,
                                  int64_t wire_channels,
                                  int64_t max_wire_channels) {
  min_window_bytes_ = std::max<int64_t>(window_bytes, 1);
  min_window_cycles_ = std::max(window_cycles, 1);
  for (int64_t v = 1 << 20; v <= (64 << 20); v *= 2) {
    fusion_values_.push_back(v);
  }
  cycle_values_ = {0.5, 1.0, 2.5, 5.0, 10.0};
  if (ring_chunk_bytes > 0) {
    chunk_values_ = {64 << 10, 256 << 10, 1 << 20, 4 << 20};
  } else {
    // The user explicitly configured the legacy bulk path (chunk
    // <= 0): it has no point on a log-scaled grid, so pin the
    // dimension rather than silently abandon an explicit choice
    // (same philosophy as the compression guard below).
    chunk_values_ = {ring_chunk_bytes};
  }
  // Compression flips numerics: only the user's enablement puts the
  // off/codec choice on the grid; otherwise the dimension is a single
  // fixed point and the GP never varies it. The tuner may settle on
  // OFF (strictly more accurate), never on a codec the user did not
  // pick.
  if (tune_wire_codec && wire_codec != 0) {
    comp_values_ = {0, wire_codec};
  } else {
    comp_values_ = {wire_codec};
  }
  // Hierarchy split point of the cross-plane allreduce: the caller
  // (operations.cc) passes the eligible splits for THIS layout — empty
  // or single-valued pins the dimension (flat-only layouts, or an
  // explicit HOROVOD_CROSS_PLANE=ring/hier choice the tuner must not
  // override beyond the split itself).
  if (!hier_values.empty()) hier_values_ = std::move(hier_values);
  // Stripe width (6th dimension): powers of two up to the sockets
  // actually established — a single-socket mesh pins it at {1}.
  chan_values_.clear();
  for (int64_t k = 1; k <= std::max<int64_t>(max_wire_channels, 1);
       k *= 2) {
    chan_values_.push_back(k);
  }
  max_samples_ = std::max(max_samples, 2);

  // Candidate grid in a normalized space: log2 of each byte/ms knob
  // scaled to [0,1] (compression is already {0,1}) so one RBF length
  // scale covers every dimension.
  std::vector<std::vector<double>> cands;
  double f_lo = std::log2((double)fusion_values_.front());
  double f_hi = std::log2((double)fusion_values_.back());
  double c_lo = std::log2(cycle_values_.front());
  double c_hi = std::log2(cycle_values_.back());
  // A pinned (single-value) dimension gets the constant coordinate 0
  // — no log2 of a possibly-non-positive pinned value.
  bool chunk_pinned = chunk_values_.size() == 1;
  double k_lo = chunk_pinned ? 0 : std::log2((double)chunk_values_.front());
  double k_hi = chunk_pinned ? 1 : std::log2((double)chunk_values_.back());
  // Hier coordinate: split index scaled to [0,1] (the split grid is
  // small and ordered flat < divisors ascending, so the index is a
  // monotone proxy for "how local the decomposition is"). Channel
  // coordinate: same index treatment over the power-of-two widths.
  bool hier_pinned = hier_values_.size() <= 1;
  bool chan_pinned = chan_values_.size() <= 1;
  for (size_t fi = 0; fi < fusion_values_.size(); fi++) {
    for (size_t ci = 0; ci < cycle_values_.size(); ci++) {
      for (size_t ki = 0; ki < chunk_values_.size(); ki++) {
        for (size_t mi = 0; mi < comp_values_.size(); mi++) {
          for (size_t hi = 0; hi < hier_values_.size(); hi++) {
            for (size_t ni = 0; ni < chan_values_.size(); ni++) {
              cands.push_back(
                  {(std::log2((double)fusion_values_[fi]) - f_lo) /
                       (f_hi - f_lo),
                   (std::log2(cycle_values_[ci]) - c_lo) / (c_hi - c_lo),
                   chunk_pinned
                       ? 0.0
                       : (std::log2((double)chunk_values_[ki]) - k_lo) /
                             (k_hi - k_lo),
                   comp_values_[mi] != 0 ? 1.0 : 0.0,
                   hier_pinned
                       ? 0.0
                       : (double)hi / (double)(hier_values_.size() - 1),
                   chan_pinned
                       ? 0.0
                       : (double)ni /
                             (double)(chan_values_.size() - 1)});
            }
          }
        }
      }
    }
  }
  opt_ = std::make_unique<BayesOpt>(std::move(cands));

  // Start from the user-provided operating point (snap onto the grids).
  fusion_idx_ = 0;
  for (size_t i = 0; i < fusion_values_.size(); i++) {
    if (fusion_values_[i] <= fusion_bytes) fusion_idx_ = i;
  }
  cycle_idx_ = 0;
  for (size_t i = 0; i < cycle_values_.size(); i++) {
    if (cycle_values_[i] <= cycle_ms) cycle_idx_ = i;
  }
  chunk_idx_ = 0;
  for (size_t i = 0; i < chunk_values_.size(); i++) {
    if (chunk_values_[i] <= ring_chunk_bytes) chunk_idx_ = i;
  }
  comp_idx_ = 0;
  for (size_t i = 0; i < comp_values_.size(); i++) {
    if (comp_values_[i] == wire_codec) comp_idx_ = i;
  }
  hier_idx_ = 0;
  for (size_t i = 0; i < hier_values_.size(); i++) {
    if (hier_values_[i] == hier_split) hier_idx_ = i;
  }
  chan_idx_ = 0;
  for (size_t i = 0; i < chan_values_.size(); i++) {
    if (chan_values_[i] <= wire_channels) chan_idx_ = i;
  }
  current_candidate_ =
      ((((fusion_idx_ * cycle_values_.size() + cycle_idx_) *
             chunk_values_.size() +
         chunk_idx_) *
            comp_values_.size() +
        comp_idx_) *
           hier_values_.size() +
       hier_idx_) *
          chan_values_.size() +
      chan_idx_;

  if (!log_path.empty()) {
    log_ = fopen(log_path.c_str(), "w");
    if (log_) {
      fprintf(log_, "fusion_threshold_bytes,cycle_time_ms,"
                    "ring_chunk_bytes,wire_compression,hier_split,"
                    "wire_channels,score_bytes_per_sec\n");
      fflush(log_);
    }
  }
  active_ = true;
}

ParameterManager::~ParameterManager() {
  if (log_) fclose(log_);
}

void ParameterManager::Log(double score) {
  if (!log_) return;
  fprintf(log_, "%lld,%.3f,%lld,%d,%lld,%lld,%.0f\n",
          (long long)fusion_threshold_bytes(), cycle_time_ms(),
          (long long)ring_chunk_bytes(), wire_codec(),
          (long long)hier_split(), (long long)wire_channels(), score);
  fflush(log_);
}

void ParameterManager::MoveTo(size_t candidate) {
  current_candidate_ = candidate;
  chan_idx_ = candidate % chan_values_.size();
  candidate /= chan_values_.size();
  hier_idx_ = candidate % hier_values_.size();
  candidate /= hier_values_.size();
  comp_idx_ = candidate % comp_values_.size();
  candidate /= comp_values_.size();
  chunk_idx_ = candidate % chunk_values_.size();
  candidate /= chunk_values_.size();
  cycle_idx_ = candidate % cycle_values_.size();
  fusion_idx_ = candidate / cycle_values_.size();
}

void ParameterManager::Score(double bytes_per_sec) {
  Log(bytes_per_sec);
  if (done_) return;
  opt_->AddSample(current_candidate_, bytes_per_sec);
  if ((int)opt_->num_samples() >= max_samples_) {
    MoveTo(opt_->Best());
    done_ = true;
    // Final log row = the CONVERGED operating point (with its mean
    // observed score), not the 20th sampled candidate — consumers
    // read rows[-1] as "what the tuner settled on".
    Log(opt_->MeanScore(current_candidate_));
    LOG_INFO("autotune converged: fusion=%lld bytes, cycle=%.2f ms, "
             "ring_chunk=%lld bytes, wire_codec=%d, hier_split=%lld, "
             "wire_channels=%lld",
             (long long)fusion_threshold_bytes(), cycle_time_ms(),
             (long long)ring_chunk_bytes(), wire_codec(),
             (long long)hier_split(), (long long)wire_channels());
    return;
  }
  MoveTo(opt_->Suggest());
}

bool ParameterManager::Update(int64_t bytes) {
  if (!active_ || done_) return false;
  auto now = std::chrono::steady_clock::now();
  if (!window_started_) {
    // A window's clock starts where the PREVIOUS window closed, not at
    // its own first enqueue: eager training traffic is bursty (a long
    // gradient-compute phase, then a flood of allreduces), and a
    // first-enqueue clock silently drops the idle phase from the
    // score. That bias made bytes/sec REWARD small cycle times —
    // windows close inside the burst where instantaneous throughput
    // is high — while the realized step time is worst exactly there
    // (measured r6, benchmarks/results_r06_autotune.json: the
    // per-grad lane's knob landscape inverts). Wall-clock windows
    // make the score proportional to end-to-end training throughput,
    // which is the number the tuner exists to move. Exception: a
    // carried-over gap of a whole window or more is a knob-UNRELATED
    // stall (eval loop, checkpoint, re-jit) — charging it to whatever
    // candidate happens to be active would feed the optimizer a
    // near-zero garbage sample (the window would close on its first
    // Update via the kMaxWindowSecs cap), so such gaps start fresh.
    auto start = window_ended_ ? window_end_ : now;
    if (std::chrono::duration<double>(now - start).count() >=
        kMaxWindowSecs) {
      start = now;
    }
    window_start_ = start;
    window_started_ = true;
    window_bytes_ = 0;
    window_cycles_ = 0;
  }
  window_bytes_ += bytes;
  window_cycles_++;
  double secs = std::chrono::duration<double>(now - window_start_).count();
  bool window_full = (window_bytes_ >= min_window_bytes_ &&
                      window_cycles_ >= min_window_cycles_) ||
                     secs >= kMaxWindowSecs;
  if (!window_full || secs <= 0) return false;
  int64_t prev_fusion = fusion_threshold_bytes();
  double prev_cycle = cycle_time_ms();
  int64_t prev_chunk = ring_chunk_bytes();
  int prev_comp = wire_codec();
  int64_t prev_hier = hier_split();
  int64_t prev_chan = wire_channels();
  if (warmup_windows_ > 0) {
    warmup_windows_--;  // discard: startup warmup pollutes the score
  } else if (window_bytes_ >= min_window_bytes_ ||
             window_cycles_ >= min_window_cycles_) {
    Score((double)window_bytes_ / secs);
  }
  // else: a window that hit the kMaxWindowSecs cap with traffic below
  // BOTH floors is a stall artifact (a sub-cap pause carried into the
  // window start plus one or two enqueues) — discard it rather than
  // feed the optimizer a near-zero sample charged to an innocent
  // candidate. Genuinely slow workloads still score: their cap-closed
  // windows clear the cycle floor.
  window_started_ = false;
  window_end_ = now;
  window_ended_ = true;
  return fusion_threshold_bytes() != prev_fusion ||
         cycle_time_ms() != prev_cycle ||
         ring_chunk_bytes() != prev_chunk ||
         wire_codec() != prev_comp ||
         hier_split() != prev_hier ||
         wire_channels() != prev_chan;
}

}  // namespace hvdtpu
