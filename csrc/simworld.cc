// Simulated large-world harness: stand up a 64-256-rank world as
// thread-per-rank controllers in ONE process, connected over the same
// socketpair machinery as ring_selftest.cc — no TCP rendezvous, no
// ephemeral-port exhaustion, no process fleet. The point is control-
// plane CHARACTERIZATION (docs/scale.md): every rank runs the real
// Controller negotiation (flat star or HOROVOD_CONTROL_TREE bundles)
// and the real DataPlane ring allreduce, so the per-phase latency
// profile (ControlPhase histograms, metrics.h) measured here is the
// same code that runs at production scale — only the transport hops
// are loopback.
//
// Topology budget: the control star is O(N) socketpairs; the data
// plane is a full mesh up to kFullMeshRanks (matching the selftest)
// and ring-neighbors-only above it — the ring allreduce touches only
// neighbors, and a neighbors-only probe sweep still converges on the
// dead set (it just names fewer witnesses). RLIMIT_NOFILE is raised
// toward the hard limit before building.
//
// Reference analog: none upstream — Horovod's scalability was proved
// on real clusters (arXiv:1802.05799 §5); the characterization-first
// discipline here follows arXiv:1810.11112 (profile the phases at
// target scale, then fix what the curves indict).

#include <sys/resource.h>
#include <sys/socket.h>

#include <algorithm>
#include <atomic>
#include <cstdarg>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include "common.h"
#include "controller.h"
#include "events.h"
#include "logging.h"
#include "message.h"
#include "metrics.h"
#include "ring_ops.h"
#include "wire.h"

extern "C" int hvdtpu_is_initialized();

namespace hvdtpu {
namespace {

// Above this, the data plane is ring-neighbors-only (fd budget: a full
// mesh is N^2 fds; 256 ranks would need ~65k).
constexpr int kFullMeshRanks = 32;

// One simulated world run at a time: the harness resets the
// control-phase histograms for a clean profile.
std::mutex g_simworld_mutex;

struct SimWorld {
  int size = 0;
  int fanout = 0;
  // Per-rank fd sets, handed to InitializeFromFds (owned there).
  std::vector<std::vector<int>> control_fds;
  std::vector<std::vector<int>> peer_fds;
  std::vector<int> tree_parent_fd;
  std::vector<std::vector<std::pair<int, int>>> tree_children;
  bool full_mesh = false;

  bool Build(int ranks, int tree_fanout) {
    size = ranks;
    fanout = tree_fanout;
    control_fds.assign(ranks, {});
    peer_fds.assign(ranks, std::vector<int>(ranks, -1));
    tree_parent_fd.assign(ranks, -1);
    tree_children.assign(ranks, {});
    control_fds[0].assign(ranks, -1);

    // Control star: coordinator side in control_fds[0][r], worker side
    // as the worker's single entry. Both ends register their peer rank
    // (unique fd numbers in one process) so EOF/timeout statuses name
    // the casualty exactly like the TCP bootstrap's registrations.
    for (int r = 1; r < ranks; r++) {
      int sv[2];
      if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) return false;
      control_fds[0][r] = sv[0];
      control_fds[r].assign(1, sv[1]);
      RegisterFdRank(sv[0], r);
      RegisterFdRank(sv[1], 0);
    }
    // Tree edges between two WORKERS (edges touching rank 0 reuse the
    // star, exactly as the TCP path shares them).
    if (tree_fanout >= 2) {
      for (int r = 1; r < ranks; r++) {
        int parent = (r - 1) / tree_fanout;
        if (parent == 0) continue;
        int sv[2];
        if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) return false;
        tree_children[parent].emplace_back(r, sv[0]);
        tree_parent_fd[r] = sv[1];
        RegisterFdRank(sv[0], r);
        RegisterFdRank(sv[1], parent);
      }
      // Children must be in rank order (the gather iterates in order).
      for (auto& kids : tree_children) {
        std::sort(kids.begin(), kids.end());
      }
    }
    // Data plane: full mesh small, ring neighbors large.
    full_mesh = ranks <= kFullMeshRanks;
    for (int i = 0; i < ranks; i++) {
      for (int j = i + 1; j < ranks; j++) {
        bool neighbor = (j == i + 1) || (i == 0 && j == ranks - 1);
        if (!full_mesh && !neighbor) continue;
        int sv[2];
        if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) return false;
        peer_fds[i][j] = sv[0];
        peer_fds[j][i] = sv[1];
        RegisterFdRank(sv[0], j);
        RegisterFdRank(sv[1], i);
      }
    }
    return true;
  }

  // Close everything NOT yet handed to a controller (build failure).
  void CloseAll() {
    for (auto& row : control_fds) {
      for (int fd : row) TcpClose(fd);
    }
    for (auto& row : peer_fds) {
      for (int fd : row) TcpClose(fd);
    }
    for (int fd : tree_parent_fd) TcpClose(fd);
    for (auto& kids : tree_children) {
      for (auto& kv : kids) TcpClose(kv.second);
    }
  }
};

// Raise the fd soft limit toward the hard limit when the build needs
// more than we have. Returns false when even the hard limit is short.
bool EnsureFdBudget(int64_t needed) {
  rlimit rl{};
  if (getrlimit(RLIMIT_NOFILE, &rl) != 0) return true;  // best effort
  if ((int64_t)rl.rlim_cur >= needed) return true;
  if ((int64_t)rl.rlim_max < needed &&
      rl.rlim_max != RLIM_INFINITY) {
    return false;
  }
  rlimit want = rl;
  want.rlim_cur = (rl.rlim_max == RLIM_INFINITY)
                      ? (rlim_t)needed
                      : std::min<rlim_t>((rlim_t)needed, rl.rlim_max);
  return setrlimit(RLIMIT_NOFILE, &want) == 0 ||
         (int64_t)rl.rlim_cur >= needed;
}

struct RankResult {
  bool ok = false;           // every round completed
  bool data_ok = true;       // allreduce results verified
  bool fault_typed = false;  // ended with a typed PeerFailure
  int fault_rank = -1;
  std::string reason;
  int rounds_done = 0;
};

void RunRank(int rank, SimWorld& w, int64_t elems, int rounds,
             int kill_rank, int kill_round, std::atomic<int>* up,
             std::atomic<int>* init_failed,
             std::vector<int64_t>* round_us, RankResult* res) {
  ControllerConfig cfg;
  cfg.rank = rank;
  cfg.size = w.size;
  cfg.tree_fanout = w.fanout;
  Controller ctl(cfg);
  Status st = ctl.InitializeFromFds(
      std::move(w.control_fds[rank]), std::move(w.peer_fds[rank]),
      w.tree_parent_fd[rank], std::move(w.tree_children[rank]));
  if (!st.ok()) {
    res->reason = st.reason();
    init_failed->fetch_add(1);
    return;
  }
  up->fetch_add(1);
  std::vector<float> buf((size_t)elems);
  const double expect = (double)w.size * (w.size + 1) / 2.0;
  for (int round = 0; round < rounds; round++) {
    if (rank == kill_rank && round == kill_round) {
      // Simulated SIGKILL: scope exit closes every fd this rank owns
      // (controller star/tree + data plane) — peers see EOF, the
      // certain-attribution path, exactly like a dead process.
      res->rounds_done = round;
      res->reason = "killed";
      return;
    }
    Request req;
    req.request_rank = rank;
    req.request_type = RequestType::ALLREDUCE;
    req.tensor_type = DataType::HVDTPU_FLOAT32;
    req.tensor_name = "simworld.grad";
    req.tensor_shape = {elems};
    const int64_t t0 = MetricsNowUs();
    ResponseList out;
    st = ctl.ComputeResponseList({req}, false, &out);
    if (!st.ok()) {
      res->fault_typed = st.peer_failure();
      res->fault_rank = st.fault_rank();
      res->reason = st.reason();
      res->rounds_done = round;
      return;
    }
    for (auto& resp : out.responses) {
      if (resp.response_type == Response::ResponseType::ERROR) {
        res->reason = resp.error_message;
        res->rounds_done = round;
        return;
      }
      if (resp.response_type != Response::ResponseType::ALLREDUCE ||
          elems == 0) {
        continue;
      }
      std::fill(buf.begin(), buf.end(), (float)(rank + 1));
      st = ctl.data_plane()->Allreduce(buf.data(), elems,
                                       DataType::HVDTPU_FLOAT32,
                                       ReduceOp::SUM, 1.0);
      if (!st.ok()) {
        res->fault_typed = st.peer_failure();
        res->fault_rank = st.fault_rank();
        res->reason = st.reason();
        res->rounds_done = round;
        return;
      }
      if (buf[0] != (float)expect ||
          buf[(size_t)elems - 1] != (float)expect) {
        res->data_ok = false;
      }
    }
    if (rank == 0) round_us->push_back(MetricsNowUs() - t0);
    res->rounds_done = round + 1;
  }
  res->ok = true;
}

// Measure-then-format (the shared AppendFmtV, metrics.h): a fixed
// stack buffer here would silently truncate — corrupt — the report
// JSON the moment a row outgrew it.
void AppendJson(std::string& out, const char* fmt, ...) {
  va_list args;
  va_start(args, fmt);
  AppendFmtV(out, fmt, args);
  va_end(args);
}

}  // namespace
}  // namespace hvdtpu

using namespace hvdtpu;

extern "C" {

// Run one simulated world: `ranks` thread-per-rank controllers over
// socketpairs, `rounds` negotiation+allreduce cycles of an
// `elems`-float32 gradient, optionally killing `kill_rank` at the top
// of `kill_round`. tree_fanout >= 2 selects the tree-structured
// negotiation gather (HOROVOD_CONTROL_TREE); 0 = flat star baseline.
//
// Writes a JSON report into json_out (truncated to json_cap):
// standup/round latency plus the per-phase control-plane profile
// (ControlPhase histograms — reset at entry for a clean curve, which
// is why a live in-process core refuses the run). Returns:
//   0 ok   -1 bad args   -2 socketpair/fd budget   -3 a rank failed
//   -4 allreduce mismatch   -5 core already initialized
//   -6 kill injected but no survivor saw a typed fault
int hvdtpu_simworld_run(int ranks, int tree_fanout, int64_t elems,
                        int rounds, int kill_rank, int kill_round,
                        char* json_out, int64_t json_cap) {
  if (ranks < 2 || ranks > 1024 || elems < 0 || rounds < 1 ||
      tree_fanout < 0 || kill_rank >= ranks ||
      (kill_rank >= 0 && (kill_round < 0 || kill_round >= rounds))) {
    return -1;
  }
  if (hvdtpu_is_initialized()) return -5;  // would stomp the profile
  std::lock_guard<std::mutex> lock(g_simworld_mutex);

  const bool full_mesh = ranks <= kFullMeshRanks;
  int64_t needed = 4 * (int64_t)ranks +
                   (full_mesh ? (int64_t)ranks * ranks : 4 * (int64_t)ranks)
                   + 256;
  if (!EnsureFdBudget(needed)) return -2;

  // Clean per-phase profile for THIS world size (the whole point of
  // the harness); rendezvous is recorded below as world standup.
  for (auto& h : GlobalMetrics().control_phase_us) h.Reset();

  SimWorld w;
  if (!w.Build(ranks, tree_fanout)) {
    w.CloseAll();
    return -2;
  }

  const int64_t standup_t0 = MetricsNowUs();
  int64_t standup_us = 0;
  std::atomic<int> up{0}, init_failed{0};
  std::vector<int64_t> round_us;
  std::vector<RankResult> results(ranks);
  {
    std::vector<std::thread> threads;
    threads.reserve(ranks);
    for (int r = 0; r < ranks; r++) {
      threads.emplace_back(RunRank, r, std::ref(w), elems, rounds,
                           kill_rank, kill_round, &up, &init_failed,
                           &round_us, &results[r]);
    }
    // Standup = every controller constructed and fd-connected (the
    // TCP analog is the rendezvous fan-in; recorded on its phase).
    while (up.load() + init_failed.load() < ranks) {
      std::this_thread::yield();
    }
    standup_us = MetricsNowUs() - standup_t0;
    RecordControlPhase(kPhaseRendezvous, standup_us);
    for (auto& t : threads) t.join();
  }

  // Probe sweep once on the surviving coordinator-side view is not
  // possible here (planes are gone); the sweep is profiled by the live
  // ranks' elastic path instead. Summarize results.
  int rc = 0;
  bool data_ok = true;
  std::string first_reason;
  int typed_faults = 0, fault_rank_seen = -1;
  for (int r = 0; r < ranks; r++) {
    if (r == kill_rank) continue;
    if (!results[r].data_ok) data_ok = false;
    if (kill_rank < 0) {
      if (!results[r].ok && first_reason.empty()) {
        first_reason = results[r].reason;
        rc = -3;
      }
    } else {
      if (results[r].fault_typed) {
        typed_faults++;
        if (fault_rank_seen < 0) fault_rank_seen = results[r].fault_rank;
      }
    }
  }
  if (rc == 0 && !data_ok) rc = -4;
  if (rc == 0 && kill_rank >= 0 && typed_faults == 0) rc = -6;

  // Round stats (coordinator wall time per negotiation+allreduce).
  int64_t rmin = 0, rmax = 0, rsum = 0;
  for (size_t i = 0; i < round_us.size(); i++) {
    rmin = i == 0 ? round_us[i] : std::min(rmin, round_us[i]);
    rmax = std::max(rmax, round_us[i]);
    rsum += round_us[i];
  }
  std::string json = "{";
  AppendJson(json, "\"ranks\":%d,\"tree_fanout\":%d,\"elems\":%lld,"
                   "\"rounds\":%d,\"data_mesh\":\"%s\",",
             ranks, tree_fanout, (long long)elems, rounds,
             full_mesh ? "full" : "ring");
  AppendJson(json, "\"standup_us\":%lld,", (long long)standup_us);
  AppendJson(json, "\"round_us\":{\"count\":%lld,\"mean\":%lld,"
                   "\"min\":%lld,\"max\":%lld},",
             (long long)round_us.size(),
             (long long)(round_us.empty() ? 0
                                          : rsum / (int64_t)round_us.size()),
             (long long)rmin, (long long)rmax);
  json += "\"phases\":{";
  {
    bool first = true;
    for (int i = 0; i < kPhaseCount; i++) {
      if (GlobalMetrics().control_phase_us[i].count() == 0) continue;
      AppendJson(json, "%s\"%s\":", first ? "" : ",",
                 ControlPhaseName(i));
      json += GlobalMetrics().control_phase_us[i].Json();
      first = false;
    }
  }
  json += "},";
  AppendJson(json, "\"allreduce_ok\":%s,", data_ok ? "true" : "false");
  if (kill_rank >= 0) {
    AppendJson(json, "\"fault\":{\"injected_rank\":%d,\"typed_faults\":"
                     "%d,\"named_rank\":%d},",
               kill_rank, typed_faults, fault_rank_seen);
  }
  // Escape-free by construction: reasons carry rank numbers and fixed
  // text; quotes are stripped to keep the report parseable regardless.
  std::string reason = first_reason.substr(0, 200);
  reason.erase(std::remove(reason.begin(), reason.end(), '"'),
               reason.end());
  reason.erase(std::remove(reason.begin(), reason.end(), '\\'),
               reason.end());
  AppendJson(json, "\"error\":\"%s\",\"rc\":%d}", reason.c_str(), rc);

  if (json_out != nullptr && json_cap > 0) {
    size_t n = std::min((size_t)(json_cap - 1), json.size());
    std::memcpy(json_out, json.data(), n);
    json_out[n] = '\0';
  }
  return rc;
}

}  // extern "C"
