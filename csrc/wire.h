// TCP socket plumbing for the control and data planes.
// Reference analog: horovod vendors Gloo (third_party/gloo) for its MPI-free
// transport and rendezvouses via an HTTP KVStore. Rebuilt: a minimal
// self-contained TCP layer — length-framed messages for the control plane,
// poll()-driven full-duplex transfers for the ring data plane.

#ifndef HVDTPU_WIRE_H
#define HVDTPU_WIRE_H

#include <cstdint>
#include <functional>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

// Create a listening socket on `port` (0 = ephemeral). Returns fd; writes the
// bound port back to `port`.
int TcpListen(int* port);

// Accept one connection (blocking). Returns fd.
int TcpAccept(int listen_fd);

// Accept with a deadline: returns fd, or -1 if no connection arrives
// within timeout_ms (<= 0 = block forever). Bootstrap/re-formation
// rendezvous uses this so a peer dying before it connects fails the
// rendezvous instead of hanging the acceptor.
int TcpAcceptTimeout(int listen_fd, int64_t timeout_ms);

// Connect to host:port, retrying for up to `timeout_ms` (rendezvous races are
// expected at launch). Returns fd or -1.
int TcpConnect(const std::string& host, int port, int timeout_ms = 30000);

void TcpClose(int fd);

// ---- wire deadline (HOROVOD_WIRE_TIMEOUT_MS) -------------------------
// Every wire primitive below is deadline-bound: "no progress on this fd
// for timeout_ms" returns a typed Status::PeerFailure(rank) naming the
// offending peer and the stalled milliseconds, instead of blocking the
// ring forever on a dead peer. The deadline is a PROGRESS bound, not a
// whole-transfer bound — a slow but live link that keeps moving bytes
// never trips it. <= 0 disables the deadline (legacy blocking).
// Process-global (like the ring knobs); env-read lazily and re-read at
// every (re)init.
constexpr int64_t kDefaultWireTimeoutMs = 60000;
// Sentinel for the timeout_ms parameters below: use the global knob.
constexpr int64_t kWireTimeoutGlobal = -2;
int64_t WireTimeoutMs();
void SetWireTimeoutMs(int64_t ms);

// ---- transient-fault healing (HOROVOD_WIRE_RETRY_*) ------------------
// A wire deadline expiring is SUSPICION, not proof (the peer may be
// SIGSTOPped, GC-paused, or riding out a network blip). Before
// escalating a timeout into a PeerFailure, the wire layer waits out up
// to HOROVOD_WIRE_RETRY_ATTEMPTS extra windows of exponentially growing
// patience (HOROVOD_WIRE_RETRY_BACKOFF_MS << attempt). The transfer
// state (sent/received offsets, verified chunks) lives across the
// retries, so a resumed peer continues the in-flight transfer from the
// last acked byte/chunk — no world shrink, no epoch bump. Progress
// resuming after at least one expired window counts as a HEAL
// (metrics "elastic.heals"); exhaustion escalates to the r12 fault
// path. Retries only wrap deadlines resolved from the GLOBAL knob —
// explicit control-plane deadlines (heartbeats) stay crisp. Defaults:
// 0 attempts (healing off), 250 ms base backoff.
int64_t WireRetryAttempts();
void SetWireRetryAttempts(int64_t n);
int64_t WireRetryBackoffMs();
void SetWireRetryBackoffMs(int64_t ms);

// ---- multi-channel striping (HOROVOD_WIRE_CHANNELS) ------------------
// The data plane establishes K parallel sockets per neighbor pair at
// rendezvous (the channel id rides the data-plane hello, epoch-fenced
// like everything else) and stripes every chunked ring step across
// them: chunk i of a segment rides channel i % K, each channel's byte
// stream is framed independently (CRC mode included — per-channel
// [D1|idx|crc|payload]/NAK streams, acks on each channel's own reverse
// direction), and one ReduceWorker per channel keeps reduction
// parallelism matched to the stripe width. K is rank-uniform by
// contract (the stripe split IS the wire framing, like the chunk
// knob). Two values, deliberately distinct:
//   WireChannelsEnv()  — sockets ESTABLISHED per pair, read from the
//                        env once per process (rendezvous and every
//                        reinit rebuild this many; the autotuner can
//                        never ask a re-formation for sockets the env
//                        did not provision);
//   WireChannels()     — the ACTIVE stripe width, autotunable at
//                        runtime (rides the ResponseList like the
//                        chunk knob), clamped to the established count
//                        at use sites.
// External (message) transports do not stripe (K is forced to 1).
constexpr int kMaxWireChannels = 8;
int WireChannelsEnv();
int64_t WireChannels();
void SetWireChannels(int64_t k);

// ---- wire integrity (HOROVOD_WIRE_CRC) -------------------------------
// When on, every DuplexTransfer/DuplexTransferChunked over TCP frames
// its payload as typed per-chunk messages carrying a CRC32C, and the
// receiver acks the transfer: a chunk failing verification is NAKed and
// resent by the sender (which still holds the segment), healing
// transient corruption in place; the same chunk failing more than
// WireRetryAttempts()+1 times escalates to a typed
// Status::WireCorruption(rank, chunk) so corrupted data is NEVER
// silently reduced into a result. Covers the bf16-compressed and
// cross-plane hops (they ride the same duplex entry). Rank-uniform by
// contract (the CRC framing IS the wire format); env-only — the
// autotuner never touches it. Off by default: zero framing overhead.
bool WireCrc();
void SetWireCrc(bool on);
uint32_t Crc32c(const void* data, size_t len);

// Chaos hook (HOROVOD_FAULT_INJECT=rank:op:flip:bit[:skip[:chan]]):
// flip `bit` (modulo the frame's payload bits) in a CRC-framed data
// chunk this process sends, AFTER its CRC is computed — wire
// corruption the receiver must catch. `skip` lets that many data
// frames pass first, so a specific hop of a multi-phase collective
// (e.g. the bf16 cross-plane chunk of a hierarchical allreduce) can be
// targeted deterministically. `channel` >= 0 restricts BOTH the flip
// and the skip count to frames sent on that stripe channel — with K>1
// the channels stream concurrently, so a channel-blind skip counter
// would race; the filter is what makes "fault exactly one channel,
// the other K-1 must not wedge" a deterministic chaos case. bit >= 0
// is one-shot; persistent=true re-flips every subsequent frame
// (including resends), forcing NAK-retry exhaustion so the escalation
// path is testable.
void ArmWireFlip(int64_t bit, bool persistent, int64_t skip = 0,
                 int64_t channel = -1);

// Peer attribution: planes register which GLOBAL rank sits behind each
// connected fd so timeout/EOF statuses can name the casualty, plus the
// stripe channel the fd carries (0 for control fds and the primary
// data mesh). External (message-transport) fds encode the peer
// directly and need no entry.
void RegisterFdRank(int fd, int rank, int channel = 0);
void UnregisterFdRank(int fd);  // TcpClose calls this itself
int FdRank(int fd);             // -1 when unknown
int FdChannel(int fd);          // 0 when unknown
// Every currently registered peer fd (control + data planes) — the
// chaos "reset" action shuts them all down to emulate NIC death.
// channel >= 0 filters to that stripe channel's fds (reset:<chan>
// emulates ONE dead NIC queue while the other stripes stay up).
std::vector<int> RegisteredFds(int channel = -1);

// Exact-length send/recv, deadline-bound (see above). timeout_ms:
// kWireTimeoutGlobal = the knob, <= 0 = block forever, else explicit.
Status SendAll(int fd, const void* buf, size_t len,
               int64_t timeout_ms = kWireTimeoutGlobal);
Status RecvAll(int fd, void* buf, size_t len,
               int64_t timeout_ms = kWireTimeoutGlobal);

// Length-framed messages (uint64 LE length + payload) for the control plane.
Status SendFrame(int fd, const std::string& payload,
                 int64_t timeout_ms = kWireTimeoutGlobal);
Status RecvFrame(int fd, std::string* payload,
                 int64_t timeout_ms = kWireTimeoutGlobal);

// Full-duplex transfer: simultaneously send `send_len` bytes to `send_fd` and
// receive `recv_len` bytes from `recv_fd`, multiplexed with poll() so the
// ring pipeline cannot deadlock on TCP buffer backpressure.
Status DuplexTransfer(int send_fd, const void* send_buf, size_t send_len,
                      int recv_fd, void* recv_buf, size_t recv_len);

// DuplexTransfer plus receive-side chunk completion callbacks: ONE
// nonblocking poll loop for the whole segment (the send streams freely,
// with no per-chunk lockstep or fcntl churn), invoking
// `on_chunk(offset, len)` from the caller thread each time `chunk` more
// bytes of recv_buf are complete (final partial chunk included). The
// chunk-pipelined ring hangs its overlapped ReduceInto/decode work off
// these callbacks. chunk == 0 or a null callback degrades to one
// callback-free DuplexTransfer. On external (message) fds the caller is
// expected to frame chunks itself (chunk-paired messages); this entry
// falls back to one whole-segment exchange + one callback there.
Status DuplexTransferChunked(
    int send_fd, const void* send_buf, size_t send_len, int recv_fd,
    void* recv_buf, size_t recv_len, size_t chunk,
    const std::function<void(size_t off, size_t len)>& on_chunk);

// One channel's share of a `stripe_k`-way striped transfer: of the
// ceil(len / chunk) chunks of each direction, this call moves exactly
// those with index % stripe_k == channel, streaming them in index
// order over ONE socket pair (the channel's). Offsets/lengths handed
// to `on_chunk` are GLOBAL (positions in recv_buf), so K concurrent
// calls — one per channel, each on its own thread owning its own fds —
// reassemble the full segment with no cross-channel coordination: the
// chunk schedule is derived identically at both ends, which makes the
// per-channel byte streams self-framing exactly like the K=1 stream.
// Under HOROVOD_WIRE_CRC each channel carries its own typed frame
// stream (data idx are global; NAKs/done ride this channel's reverse
// direction). A channel with no chunks in either direction returns
// OK immediately. DuplexTransferChunked == stripe_k 1, channel 0.
Status DuplexTransferStriped(
    int send_fd, const void* send_buf, size_t send_len, int recv_fd,
    void* recv_buf, size_t recv_len, size_t chunk, int stripe_k,
    int channel, const std::function<void(size_t off, size_t len)>& on_chunk);

// Best local IP for peers to reach us (first non-loopback, else 127.0.0.1).
std::string LocalAddress();

// ---- external (socket-free) message transport ------------------------
// Bare-MPI fabrics forbid ad-hoc TCP; the frontend can register a
// message transport (mpi4py point-to-point in practice) and the wire
// primitives above route through it for EXTERNAL fds. An external fd
// encodes (peer rank, channel): channel 0 = control frames, 1 = ring
// data — distinct tags keep a peer's next-cycle control traffic from
// racing its in-flight data chunks. Reference analog:
// horovod/common/mpi_controller.cc (MPI_Gatherv-based negotiation) —
// re-founded as a transport seam so ONE controller serves both fabrics.
//
// send: deliver len bytes to peer on tag; must not block against a
//   peer that is itself sending (buffered/async semantics). Returns 0
//   on success.
// recv: cap == 0 -> block for the next message on (peer, tag), hold
//   it, return its length; cap >= len -> copy the held (or next)
//   message into buf, return its length. Negative on error.
// Threading contract: the core invokes BOTH callbacks from its single
//   background thread only — the control (tag 0) and data (tag 1)
//   planes share one caller, and the two-phase recv (length probe,
//   then copy-out) of one message is never interleaved with another
//   call. Implementations may therefore keep per-transport state
//   without synchronization. The chunk-pipelined ring (ring_ops.cc)
//   deliberately preserves this: its overlap worker thread only runs
//   ReduceInto / bf16-decode over host memory, never a transport
//   call — every send/recv stays on the background thread. Any future
//   plane that moves TRANSPORT calls off that thread must revisit
//   this clause (the python mpi4py transport guards its state with a
//   lock regardless — common/mpi_bootstrap.py).
typedef int (*ExternalSendFn)(int peer, int tag, const void* buf,
                              long long len);
typedef long long (*ExternalRecvFn)(int peer, int tag, void* buf,
                                    long long cap);

void SetExternalTransport(ExternalSendFn send, ExternalRecvFn recv);
bool ExternalTransportActive();

// Encode/decode an external fd. Valid fds are <= kExtFdBase.
constexpr int kExtFdBase = -16;
inline int ExtFd(int peer, int tag) {
  return kExtFdBase - (peer * 2 + tag);
}
inline bool IsExtFd(int fd) { return fd <= kExtFdBase; }
inline int ExtFdPeer(int fd) { return (kExtFdBase - fd) / 2; }
inline int ExtFdTag(int fd) { return (kExtFdBase - fd) % 2; }

}  // namespace hvdtpu

#endif  // HVDTPU_WIRE_H
