// TCP socket plumbing for the control and data planes.
// Reference analog: horovod vendors Gloo (third_party/gloo) for its MPI-free
// transport and rendezvouses via an HTTP KVStore. Rebuilt: a minimal
// self-contained TCP layer — length-framed messages for the control plane,
// poll()-driven full-duplex transfers for the ring data plane.

#ifndef HVDTPU_WIRE_H
#define HVDTPU_WIRE_H

#include <cstdint>
#include <functional>
#include <string>
#include <vector>

#include "common.h"

namespace hvdtpu {

// Create a listening socket on `port` (0 = ephemeral). Returns fd; writes the
// bound port back to `port`.
int TcpListen(int* port);

// Accept one connection (blocking). Returns fd.
int TcpAccept(int listen_fd);

// Connect to host:port, retrying for up to `timeout_ms` (rendezvous races are
// expected at launch). Returns fd or -1.
int TcpConnect(const std::string& host, int port, int timeout_ms = 30000);

void TcpClose(int fd);

// Blocking exact-length send/recv. Return OK or an error Status.
Status SendAll(int fd, const void* buf, size_t len);
Status RecvAll(int fd, void* buf, size_t len);

// Length-framed messages (uint64 LE length + payload) for the control plane.
Status SendFrame(int fd, const std::string& payload);
Status RecvFrame(int fd, std::string* payload);

// Full-duplex transfer: simultaneously send `send_len` bytes to `send_fd` and
// receive `recv_len` bytes from `recv_fd`, multiplexed with poll() so the
// ring pipeline cannot deadlock on TCP buffer backpressure.
Status DuplexTransfer(int send_fd, const void* send_buf, size_t send_len,
                      int recv_fd, void* recv_buf, size_t recv_len);

// DuplexTransfer plus receive-side chunk completion callbacks: ONE
// nonblocking poll loop for the whole segment (the send streams freely,
// with no per-chunk lockstep or fcntl churn), invoking
// `on_chunk(offset, len)` from the caller thread each time `chunk` more
// bytes of recv_buf are complete (final partial chunk included). The
// chunk-pipelined ring hangs its overlapped ReduceInto/decode work off
// these callbacks. chunk == 0 or a null callback degrades to one
// callback-free DuplexTransfer. On external (message) fds the caller is
// expected to frame chunks itself (chunk-paired messages); this entry
// falls back to one whole-segment exchange + one callback there.
Status DuplexTransferChunked(
    int send_fd, const void* send_buf, size_t send_len, int recv_fd,
    void* recv_buf, size_t recv_len, size_t chunk,
    const std::function<void(size_t off, size_t len)>& on_chunk);

// Best local IP for peers to reach us (first non-loopback, else 127.0.0.1).
std::string LocalAddress();

// ---- external (socket-free) message transport ------------------------
// Bare-MPI fabrics forbid ad-hoc TCP; the frontend can register a
// message transport (mpi4py point-to-point in practice) and the wire
// primitives above route through it for EXTERNAL fds. An external fd
// encodes (peer rank, channel): channel 0 = control frames, 1 = ring
// data — distinct tags keep a peer's next-cycle control traffic from
// racing its in-flight data chunks. Reference analog:
// horovod/common/mpi_controller.cc (MPI_Gatherv-based negotiation) —
// re-founded as a transport seam so ONE controller serves both fabrics.
//
// send: deliver len bytes to peer on tag; must not block against a
//   peer that is itself sending (buffered/async semantics). Returns 0
//   on success.
// recv: cap == 0 -> block for the next message on (peer, tag), hold
//   it, return its length; cap >= len -> copy the held (or next)
//   message into buf, return its length. Negative on error.
// Threading contract: the core invokes BOTH callbacks from its single
//   background thread only — the control (tag 0) and data (tag 1)
//   planes share one caller, and the two-phase recv (length probe,
//   then copy-out) of one message is never interleaved with another
//   call. Implementations may therefore keep per-transport state
//   without synchronization. The chunk-pipelined ring (ring_ops.cc)
//   deliberately preserves this: its overlap worker thread only runs
//   ReduceInto / bf16-decode over host memory, never a transport
//   call — every send/recv stays on the background thread. Any future
//   plane that moves TRANSPORT calls off that thread must revisit
//   this clause (the python mpi4py transport guards its state with a
//   lock regardless — common/mpi_bootstrap.py).
typedef int (*ExternalSendFn)(int peer, int tag, const void* buf,
                              long long len);
typedef long long (*ExternalRecvFn)(int peer, int tag, void* buf,
                                    long long cap);

void SetExternalTransport(ExternalSendFn send, ExternalRecvFn recv);
bool ExternalTransportActive();

// Encode/decode an external fd. Valid fds are <= kExtFdBase.
constexpr int kExtFdBase = -16;
inline int ExtFd(int peer, int tag) {
  return kExtFdBase - (peer * 2 + tag);
}
inline bool IsExtFd(int fd) { return fd <= kExtFdBase; }
inline int ExtFdPeer(int fd) { return (kExtFdBase - fd) / 2; }
inline int ExtFdTag(int fd) { return (kExtFdBase - fd) % 2; }

}  // namespace hvdtpu

#endif  // HVDTPU_WIRE_H
