// Process sets: collectives over subgroups of ranks.
// Reference analog: horovod/common/process_set.h (ProcessSet,
// ProcessSetTable) — there each set owns its own communicator + controller
// state; here a set is a membership list, negotiation is per-set readiness in
// the (single) controller, and execution runs ring collectives over a
// non-owning subset view of the global data plane.

#ifndef HVDTPU_PROCESS_SET_H
#define HVDTPU_PROCESS_SET_H

#include <cstdint>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace hvdtpu {

class ProcessSetTable {
 public:
  explicit ProcessSetTable(int world_size) {
    std::vector<int32_t> all(world_size);
    for (int i = 0; i < world_size; i++) all[i] = i;
    sets_[0] = std::move(all);
  }

  // Register a new set. Must be called in the same order with the same
  // ranks on every process (ids are assigned locally; the reference has the
  // same same-order requirement for hvd.add_process_set).
  int32_t Add(std::vector<int32_t> ranks) {
    std::lock_guard<std::mutex> lk(mutex_);
    int32_t id = next_id_++;
    sets_[id] = std::move(ranks);
    return id;
  }

  bool Remove(int32_t id) {
    std::lock_guard<std::mutex> lk(mutex_);
    if (id == 0) return false;  // the global set is permanent
    return sets_.erase(id) > 0;
  }

  // Copy of the member list (global ranks, registration order), empty if the
  // id is unknown.
  std::vector<int32_t> Ranks(int32_t id) const {
    std::lock_guard<std::mutex> lk(mutex_);
    auto it = sets_.find(id);
    return it == sets_.end() ? std::vector<int32_t>{} : it->second;
  }

  bool Known(int32_t id) const {
    std::lock_guard<std::mutex> lk(mutex_);
    return sets_.count(id) > 0;
  }

  // Index of `rank` within the set, or -1.
  int32_t RankIn(int32_t id, int32_t rank) const {
    std::lock_guard<std::mutex> lk(mutex_);
    auto it = sets_.find(id);
    if (it == sets_.end()) return -1;
    for (size_t i = 0; i < it->second.size(); i++) {
      if (it->second[i] == rank) return (int32_t)i;
    }
    return -1;
  }

 private:
  mutable std::mutex mutex_;
  std::unordered_map<int32_t, std::vector<int32_t>> sets_;
  int32_t next_id_ = 1;
};

}  // namespace hvdtpu

#endif  // HVDTPU_PROCESS_SET_H
