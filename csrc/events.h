// Always-on structured event ring for the native core: a fixed-size
// per-process lock-free buffer of typed, timestamped events — the
// black-box flight recorder behind post-mortem fault forensics
// (docs/metrics.md "Event ring & black-box post-mortem").
//
// Reference analog: none in upstream Horovod — its timeline records
// per-tensor spans to a file on ONE rank when the operator asked in
// advance. The ring is the inverse trade: always recording, bounded
// memory, no IO on the hot path, drained only when someone asks
// (hvdtpu_events_drain) or when a fault makes the tail forensically
// valuable (the black-box dump in operations.cc).
//
// Concurrency: Record() is WAIT-FREE (one fetch_add + fenced relaxed
// stores + one CAS publish) — it runs on the wire hot path (per-chunk)
// and on the background loop; readers (drain/peek, any API thread, the
// debug server) are lock-free and never block a writer. Torn slots are
// detected by a seq re-check and skipped; a writer that finds its slot
// lapped while it was descheduled poisons it rather than claiming
// mixed payload (the only residual tear window needs a full-kCapacity
// lap during one preemption AND a reader racing the poison store).

#ifndef HVDTPU_EVENTS_H
#define HVDTPU_EVENTS_H

#include <atomic>
#include <cstdint>
#include <string>
#include <vector>

namespace hvdtpu {

// Typed events, one per observable runtime transition. Argument
// meanings per type live in kEventArgNames (events.cc) — the ONE table
// the JSON serializer, docs/metrics.md, and telemetry/postmortem.py
// field handling all follow.
enum class EventType : int32_t {
  kNegotiateBegin = 0,  // a=requests popped this cycle
  kNegotiateEnd,        // a=responses, b=shutdown bit
  kResponseLaunch,      // a=op_class, b=device plane, c=tensors, d=bytes
  kWireChunk,           // a=plane, b=crc framed, c=offset, d=len (rx verified)
  kWireSpan,            // a=plane, b=dur_us, c=tx_bytes, d=rx_bytes
  kCrcError,            // a=sender, b=fails so far, c=chunk idx
  kCrcResend,           // c=chunk idx (sender side: NAK received)
  kRetryWindow,         // a=attempt, b=window_ms (healing ladder step)
  kWireHeal,            // progress resumed after >=1 expired window
  kFault,               // a=kind(0 peer,1 corruption), b=certain,
                        // c=epoch, d=first fault rank (-1 none)
  kEpoch,               // c=new epoch, d=old epoch
  kReinitBegin,         // a=new size, c=target epoch
  kReinitEnd,           // a=rc (0 ok), b=new size, c=epoch
  kRejoin,              // a=joiner slots absorbed, c=epoch
  kKnobAdopt,           // a=knob id (kKnob*), c=new value
  kInject,              // a=chaos action, c=collective index
  kStall,               // a=waited seconds, b=missing/blocking ranks
  kFaultNotice,         // a=fault rank, b=0 broadcast / 1 received
  kPhase,               // a=ControlPhase (metrics.h), c=dur_us
  kStepBegin,           // c=step id (monotonic, hvdtpu_step_mark)
  kStepEnd,             // c=step id, d=dur_us
  kRequest,             // a=RequestPhase, c=rid, d=aux (phase-specific:
                        // tokens/bytes) — serving-lane lifecycle
                        // transition (hvdtpu_record_request)
  kWait,                // c=dur_us — one hvdtpu_wait block, stamped at
                        // its END like wire_span; the fused-lane truth
                        // for exposed wire (telemetry/critpath.py)
  kSloBreach,           // a=SloObjective, b=breaching rank, c=observed
                        // value (integral: ms, us, or permille per
                        // objective), d=dominant rank-seconds bucket
                        // (kRankBucketNames) — hvdtpu_record_slo
  kTypeCount
};

// Serving-request lifecycle phases for kRequest (docs/serving.md): each
// event marks the instant a request ENTERS the phase, so a rid's span
// chain is the gaps between its consecutive transitions — gap-free by
// construction (telemetry/reqtrace.py stitches them across ranks).
// Order is ABI: telemetry.reqtrace.REQUEST_PHASES mirrors it by index
// (pinned in tests/single/test_reqtrace.py).
enum RequestPhase : int32_t {
  kReqQueued = 0,      // admitted to the frontend's pending line
  kReqPrefill,         // prefill compute started for this request
  kReqKvShip,          // packed; KV payload in flight to a decode rank
  kReqDecodeWait,      // adopted/admitted, between decode steps
  kReqDecodeActive,    // inside a decode step's batch this instant
  kReqEvictedRequeue,  // LIFO-evicted; waiting for re-prefill
  kReqFaultRequeue,    // orphaned by a peer fault; re-queued
  kReqDone,            // terminal: completion reached the scoreboard
  kReqPhaseCount
};

const char* RequestPhaseName(int phase);

// SLO objective ids for kSloBreach (docs/fleet.md): the declarative
// SLO engine (telemetry/slo.py) evaluates these by name and records
// breaches by id — index-ABI with kSloObjectiveNames (events.cc),
// mirrored by telemetry.slo.OBJECTIVES (pinned in analysis/model/abi).
enum SloObjective : int32_t {
  kSloServingP99 = 0,     // "serving_p99_ms" (value: ms)
  kSloStepTimeEwma,       // "step_time_ewma_ms" drift (value: permille
                          // of the engine's own baseline)
  kSloOverlapEfficiency,  // "overlap_efficiency" (value: permille)
  kSloQueuedIdleShare,    // "queued_idle_share" (value: permille)
  kSloStallMs,            // "stall_ms" (value: ms)
  kSloObjectiveCount
};

const char* SloObjectiveName(int objective);

// Rank-seconds ledger bucket ids for kSloBreach's dominant-phase arg —
// index-ABI with telemetry.fleet.BUCKETS (same abi.py pin).
const char* RankBucketName(int bucket);

// Knob ids for kKnobAdopt (autotuner moves + worker lockstep adoption).
enum EventKnob : int32_t {
  kKnobFusionBytes = 0,
  kKnobCycleTimeMs,   // value in microseconds (integer event args)
  kKnobRingChunk,
  kKnobCompression,
  kKnobHierSplit,
  kKnobWireChannels,  // active stripe width (HOROVOD_WIRE_CHANNELS)
};

const char* EventTypeName(EventType t);

struct EventRecord {
  int64_t seq = 0;
  int64_t ts_us = 0;  // steady clock (MetricsNowUs) — wall-aligned by
                      // the black-box header / CLOCK_SYNC anchors
  EventType type = EventType::kTypeCount;
  int32_t a = 0, b = 0;
  int64_t c = 0, d = 0;
};

class EventRing {
 public:
  // ~8k events x 56 B = bounded, covers minutes of steady-state
  // traffic and the full causal window of any fault sequence.
  static constexpr int64_t kCapacity = 8192;

  // Wait-free; drops silently when disabled (HOROVOD_EVENTS=0).
  void Record(EventType t, int32_t a = 0, int32_t b = 0, int64_t c = 0,
              int64_t d = 0);

  // Resolves the HOROVOD_EVENTS env lazily like Record does, so it
  // answers correctly before the first record (and before init).
  bool enabled() const;
  void set_enabled(bool on) {
    enabled_.store(on ? 1 : 0, std::memory_order_relaxed);
  }

  // Next sequence number to be written (== total events recorded).
  int64_t head() const { return head_.load(std::memory_order_acquire); }

  // Copy every intact event with seq >= from_seq (clamped to the live
  // window) into `out`, oldest first; returns the next cursor (head at
  // read time). Slots overwritten or mid-write during the scan are
  // skipped — a snapshot is forensically consistent, not linearizable.
  int64_t Snapshot(int64_t from_seq, std::vector<EventRecord>* out) const;

  // JSON array of events from `from_seq`, capped to the newest
  // `max_events` (<= 0 = everything live). Writes the next cursor to
  // *next_seq when non-null. One line per event is the JSONL the
  // black-box dump writes; here they are comma-joined into an array.
  std::string Json(int64_t from_seq, int64_t* next_seq,
                   int64_t max_events = 0) const;

  void Reset();  // test isolation only (concurrent writers tolerated)

 private:
  struct Slot {
    // seq == -1 while a writer is mid-update; readers re-check seq
    // after reading the payload and discard on mismatch.
    std::atomic<int64_t> seq{-1};
    std::atomic<int64_t> ts_us{0};
    std::atomic<int32_t> type{0};
    std::atomic<int32_t> a{0}, b{0};
    std::atomic<int64_t> c{0}, d{0};
  };
  std::atomic<int64_t> head_{0};
  std::atomic<int32_t> enabled_{-1};  // -1 = read HOROVOD_EVENTS lazily
  Slot slots_[kCapacity];

  bool ReadSlot(int64_t seq, EventRecord* out) const;
};

// Process-wide ring; like the metrics registry it outlives
// init/shutdown so a post-mortem can still read a dying process.
EventRing& GlobalEvents();

// Serialize one event as a JSON object with per-type named args —
// shared by the ring serializer and the black-box JSONL dump.
std::string EventJson(const EventRecord& e);

// Wire-plane tag for events recorded inside wire.cc, which has no
// DataPlane context: the ring engine (ring_ops.cc) sets it around its
// transport calls. thread_local on purpose — all of a plane's
// transport calls run on one thread (wire.h threading contract), and
// the in-process selftests drive several planes from distinct threads.
void SetEventWirePlane(int plane);
int EventWirePlane();

}  // namespace hvdtpu

#endif  // HVDTPU_EVENTS_H
