// Thread-safe pending-tensor table between the enqueue API and the
// background coordination thread.
// Reference analog: horovod/common/tensor_queue.h (TensorQueue,
// AddToTensorQueue, GetTensorEntriesFromResponse).

#ifndef HVDTPU_TENSOR_QUEUE_H
#define HVDTPU_TENSOR_QUEUE_H

#include <deque>
#include <mutex>
#include <unordered_map>

#include "common.h"
#include "message.h"

namespace hvdtpu {

class TensorQueue {
 public:
  // Returns PRECONDITION_ERROR if a tensor of the same name is already
  // pending (names must be unique among in-flight ops, as in the reference).
  Status AddToTensorQueue(TensorTableEntry entry, Request message);

  // Drain all requests queued since the last cycle.
  std::vector<Request> PopMessages();

  // Remove + return the entries named in a response (they are about to
  // execute).
  std::vector<TensorTableEntry> GetTensorEntriesFromResponse(
      const Response& response);

  // Abort every pending entry with `status` (elastic reset / shutdown).
  std::vector<TensorTableEntry> RemoveAllEntries();

  size_t Size();

  // Whether a tensor of this name is in flight (grouped enqueue
  // pre-validation — a half-enqueued atomic group can never complete).
  bool Contains(const std::string& name);

 private:
  std::mutex mutex_;
  std::unordered_map<std::string, TensorTableEntry> tensor_table_;
  std::deque<Request> message_queue_;
};

}  // namespace hvdtpu

#endif  // HVDTPU_TENSOR_QUEUE_H
