// Gaussian-process Bayesian optimization over a small discrete
// candidate grid. Reference analog: horovod/common/optim/
// bayesian_optimization.cc + gaussian_process.cc (the autotuner's
// sample proposer) — re-founded compactly for the TPU build's needs:
// the design space is a few dozen (fusion threshold, cycle time)
// pairs, so the Expected-Improvement acquisition is argmaxed over the
// grid directly instead of gradient-optimized, and the GP posterior is
// an exact small-N Cholesky solve. Deterministic: no random restarts.

#ifndef HVDTPU_BAYES_OPT_H
#define HVDTPU_BAYES_OPT_H

#include <array>
#include <cstddef>
#include <vector>

namespace hvdtpu {

class BayesOpt {
 public:
  // candidates: points in the (already normalized, ~[0,1]^d) knob space.
  // Arbitrary dimension — the r10 ring-knob grid is 4-D (fusion, cycle,
  // chunk, compression); all points must share one length.
  explicit BayesOpt(std::vector<std::vector<double>> candidates,
                    double length_scale = 0.3, double noise = 1e-3);
  // Convenience for the original 2-D (fusion, cycle) grids.
  explicit BayesOpt(std::vector<std::array<double, 2>> candidates,
                    double length_scale = 0.3, double noise = 1e-3);

  // Record an observation at candidates[idx] (y in any scale; it is
  // re-normalized internally before each fit).
  void AddSample(size_t idx, double y);

  // Next candidate to evaluate: argmax Expected Improvement under the
  // GP posterior. Unseen candidates win ties. Valid after >=1 sample.
  size_t Suggest() const;

  // Best candidate so far: the argmax of observed mean score.
  size_t Best() const;

  // Mean observed score at candidates[idx] (0 if never sampled).
  double MeanScore(size_t idx) const;

  size_t num_samples() const { return xs_.size(); }

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;

  std::vector<std::vector<double>> cand_;
  double ls2_;    // 2 * length_scale^2
  double noise_;
  std::vector<size_t> xs_;   // sampled candidate indices
  std::vector<double> ys_;   // raw scores
};

}  // namespace hvdtpu

#endif  // HVDTPU_BAYES_OPT_H
