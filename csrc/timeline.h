// Chrome-trace (chrome://tracing) timeline of per-tensor lifecycle.
// Reference analog: horovod/common/timeline.h (Timeline, TimelineWriter with
// its async writer thread). Activated by HOROVOD_TIMELINE=/path.json; events
// cover NEGOTIATE -> QUEUE -> MEMCPY_IN_FUSION_BUFFER -> RING_ALLREDUCE ->
// MEMCPY_OUT_FUSION_BUFFER per tensor.

#ifndef HVDTPU_TIMELINE_H
#define HVDTPU_TIMELINE_H

#include <atomic>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>

namespace hvdtpu {

class Timeline {
 public:
  ~Timeline();
  void Initialize(const std::string& path, int rank);
  void Shutdown();
  bool Enabled() const { return enabled_.load(); }

  void MarkCycle();  // HOROVOD_TIMELINE_MARK_CYCLES instant event
  void NegotiateStart(const std::string& tensor);
  void NegotiateEnd(const std::string& tensor);
  void EntryQueued(const std::string& tensor);
  void ActivityStart(const std::string& tensor, const std::string& activity);
  void ActivityEnd(const std::string& tensor);
  void EntryDone(const std::string& tensor);

 private:
  void Emit(const std::string& tensor, char phase, const std::string& label);
  void WriterLoop();

  std::atomic<bool> enabled_{false};
  std::atomic<bool> stop_{false};
  int rank_ = 0;
  FILE* file_ = nullptr;
  int64_t start_us_ = 0;
  std::mutex mutex_;
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  std::thread writer_;
};

}  // namespace hvdtpu

#endif  // HVDTPU_TIMELINE_H
