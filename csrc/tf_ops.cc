// Native TensorFlow collective ops over the hvdtpu core runtime.
//
// Reference analogs: horovod/tensorflow/mpi_ops.cc (TF custom ops that
// enqueue to the C++ core) and horovod/tensorflow/xla_mpi_ops.cc (the
// HOROVOD_ENABLE_XLA_OPS custom-call bridge that lets collectives live
// inside XLA-compiled programs). Re-founded for this build:
//
// - Each op registers BOTH a regular CPU kernel and a tf2xla kernel.
//   The same graph node therefore works eagerly, inside tf.function,
//   and inside tf.function(jit_compile=True): the TF executor picks the
//   CPU kernel, the XLA bridge picks the tf2xla kernel.
// - The CPU kernel calls the core's enqueue C API directly and waits on
//   the handle — no Python, no GIL, no numpy round-trip (upstream's
//   py_function limitation this file replaces).
// - The tf2xla kernel lowers to an XLA CustomCall whose host callback
//   re-enters the same core. Operand/attr metadata (shapes, dtype,
//   names, reduce op, scale factors) is serialized into a trailing
//   constant byte operand because the XLA:CPU legacy custom-call ABI
//   does not pass `opaque`.
// - Grouped allreduce is ONE op (variadic inputs) on both paths, so a
//   gradient-tape group negotiates atomically as a single fused
//   collective exactly like the eager grouped path.
//
// Ordering contract (same as upstream Horovod's): collectives must be
// issued in a consistent order on every rank. Inside XLA programs this
// holds when ranks compile the same program (SPMD), since XLA:CPU
// executes custom-call thunks in schedule order.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "tensorflow/core/framework/op.h"
#include "tensorflow/core/framework/op_kernel.h"
#include "tensorflow/core/framework/shape_inference.h"

#include "tensorflow/compiler/tf2xla/xla_op_kernel.h"
#include "tensorflow/compiler/tf2xla/xla_op_registry.h"
#include "xla/hlo/builder/xla_builder.h"
#include "xla/service/custom_call_target_registry.h"

// Core C API + dtype enum (single source of truth; linked against
// libhvdtpu_core.so).
#include "common.h"
#include "operations.h"

namespace hvdtpu_tf {

using tensorflow::AsyncOpKernel;
using tensorflow::OpKernel;
using tensorflow::OpKernelConstruction;
using tensorflow::OpKernelContext;
using tensorflow::Tensor;

// Shape pointer for rank-0 (scalar) tensors: the core's group
// validation rejects null shape pointers, and std::vector::data() on an
// empty vector is null.
static const int64_t kScalarShape[1] = {0};

static const int64_t* ShapeData(const std::vector<int64_t>& dims) {
  return dims.empty() ? kScalarShape : dims.data();
}

// ---- dtype mapping --------------------------------------------------------

static int ToHvdDtype(tensorflow::DataType dt) {
  using hvdtpu::DataType;
  switch (dt) {
    case tensorflow::DT_UINT8: return (int)DataType::HVDTPU_UINT8;
    case tensorflow::DT_INT8: return (int)DataType::HVDTPU_INT8;
    case tensorflow::DT_INT32: return (int)DataType::HVDTPU_INT32;
    case tensorflow::DT_INT64: return (int)DataType::HVDTPU_INT64;
    case tensorflow::DT_HALF: return (int)DataType::HVDTPU_FLOAT16;
    case tensorflow::DT_BFLOAT16: return (int)DataType::HVDTPU_BFLOAT16;
    case tensorflow::DT_FLOAT: return (int)DataType::HVDTPU_FLOAT32;
    case tensorflow::DT_DOUBLE: return (int)DataType::HVDTPU_FLOAT64;
    case tensorflow::DT_BOOL: return (int)DataType::HVDTPU_BOOL;
    case tensorflow::DT_UINT16: return (int)DataType::HVDTPU_UINT16;
    default: return -1;
  }
}

// ---- status helpers -------------------------------------------------------

// Failures carry the canonical "HorovodInternalError:" marker inside
// the TF OpError message: that is the wrapped form the elastic
// recovery loop (common/elastic.py:_is_internal_error) classifies as
// recoverable, mirroring how the reference's TF ops surface runtime
// collective failures.
static tensorflow::Status WaitHandle(int handle, const char* what) {
  if (handle < 0) {
    return tensorflow::errors::Internal(
        what, ": HorovodInternalError: enqueue failed "
        "(is horovod initialized?)");
  }
  int rc = hvdtpu_wait(handle);
  if (rc != 0) {
    const char* msg = hvdtpu_error_string(handle);
    std::string reason = msg ? msg : "collective failed";
    hvdtpu_release(handle);
    return tensorflow::errors::Internal(what, ": HorovodInternalError: ",
                                        reason);
  }
  hvdtpu_release(handle);
  return tensorflow::OkStatus();
}

// ---- op registrations -----------------------------------------------------

REGISTER_OP("HvdTpuAllreduce")
    .Input("tensor: T")
    .Output("output: T")
    .Attr("T: {uint8, int8, uint16, int32, int64, half, bfloat16, float, "
          "double}")
    .Attr("tensor_name: string")
    .Attr("reduce_op: int = 0")  // csrc ReduceOp: AVERAGE=0
    .Attr("prescale_factor: float = 1.0")
    .Attr("postscale_factor: float = 1.0")
    .Attr("process_set_id: int = 0")
    .SetShapeFn(tensorflow::shape_inference::UnchangedShape);

REGISTER_OP("HvdTpuGroupedAllreduce")
    .Input("tensors: N * T")
    .Output("outputs: N * T")
    .Attr("N: int >= 1")
    .Attr("T: {uint8, int8, uint16, int32, int64, half, bfloat16, float, "
          "double}")
    .Attr("tensor_names: list(string)")
    .Attr("reduce_op: int = 0")
    .Attr("prescale_factor: float = 1.0")
    .Attr("postscale_factor: float = 1.0")
    .Attr("process_set_id: int = 0")
    .SetShapeFn([](tensorflow::shape_inference::InferenceContext* c) {
      for (int i = 0; i < c->num_inputs(); i++) c->set_output(i, c->input(i));
      return tensorflow::OkStatus();
    });

REGISTER_OP("HvdTpuBroadcast")
    .Input("tensor: T")
    .Output("output: T")
    .Attr("T: {uint8, int8, uint16, int32, int64, half, bfloat16, float, "
          "double, bool}")
    .Attr("tensor_name: string")
    .Attr("root_rank: int")
    .Attr("process_set_id: int = 0")
    .SetShapeFn(tensorflow::shape_inference::UnchangedShape);

// ---- CPU kernels ----------------------------------------------------------

// CPU kernels are ASYNC: Compute must not block the (possibly single)
// executor thread in hvdtpu_wait — with inter-op parallelism 1, two
// ranks blocking on differently-ordered independent collectives would
// deadlock. ComputeAsync enqueues, releases the thread, and a detached
// waiter fires `done` on completion (reference analog: the
// AsyncOpKernel pattern of horovod/tensorflow/mpi_ops.cc; TF keeps the
// context and its tensors alive until `done`).
static void WaitAsync(OpKernelContext* c, AsyncOpKernel::DoneCallback done,
                      std::vector<int> handles, const char* what) {
  std::thread([c, done = std::move(done), handles = std::move(handles),
               what]() {
    tensorflow::Status status = tensorflow::OkStatus();
    for (int h : handles) {  // drain every handle even when one fails
      auto s = WaitHandle(h, what);
      if (!s.ok()) status = s;
    }
    if (!status.ok()) c->SetStatus(status);
    done();
  }).detach();
}

class AllreduceCpuKernel : public AsyncOpKernel {
 public:
  explicit AllreduceCpuKernel(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void ComputeAsync(OpKernelContext* c, DoneCallback done) override {
    const Tensor& in = c->input(0);
    Tensor* out;
    OP_REQUIRES_OK_ASYNC(c, c->allocate_output(0, in.shape(), &out), done);
    int dtype = ToHvdDtype(in.dtype());
    OP_REQUIRES_ASYNC(
        c, dtype >= 0,
        tensorflow::errors::InvalidArgument("unsupported dtype"), done);
    auto dims = in.shape().dim_sizes();
    std::vector<int64_t> shape(dims.begin(), dims.end());
    int h = hvdtpu_enqueue_allreduce(
        name_.c_str(), in.tensor_data().data(),
        const_cast<char*>(out->tensor_data().data()), (int)shape.size(),
        ShapeData(shape), dtype, reduce_op_, prescale_, postscale_,
        process_set_id_);
    WaitAsync(c, std::move(done), {h}, "HvdTpuAllreduce");
  }

 private:
  std::string name_;
  int reduce_op_, process_set_id_;
  float prescale_, postscale_;
};
REGISTER_KERNEL_BUILDER(Name("HvdTpuAllreduce").Device(tensorflow::DEVICE_CPU),
                        AllreduceCpuKernel);

class GroupedAllreduceCpuKernel : public AsyncOpKernel {
 public:
  explicit GroupedAllreduceCpuKernel(OpKernelConstruction* c)
      : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_names", &names_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void ComputeAsync(OpKernelContext* c, DoneCallback done) override {
    int n = c->num_inputs();
    OP_REQUIRES_ASYNC(c, (int)names_.size() == n,
                      tensorflow::errors::InvalidArgument(
                          "tensor_names size must match input count"),
                      done);
    std::vector<const char*> names(n);
    std::vector<const void*> ins(n);
    std::vector<void*> outs(n);
    std::vector<int> ndims(n);
    std::vector<std::vector<int64_t>> shapes(n);
    std::vector<const int64_t*> shape_ptrs(n);
    int dtype = -1;
    for (int i = 0; i < n; i++) {
      const Tensor& in = c->input(i);
      Tensor* out;
      OP_REQUIRES_OK_ASYNC(c, c->allocate_output(i, in.shape(), &out),
                           done);
      names[i] = names_[i].c_str();
      ins[i] = in.tensor_data().data();
      outs[i] = const_cast<char*>(out->tensor_data().data());
      auto dims = in.shape().dim_sizes();
      shapes[i].assign(dims.begin(), dims.end());
      ndims[i] = (int)shapes[i].size();
      shape_ptrs[i] = ShapeData(shapes[i]);
      dtype = ToHvdDtype(in.dtype());
      OP_REQUIRES_ASYNC(
          c, dtype >= 0,
          tensorflow::errors::InvalidArgument("unsupported dtype"), done);
    }
    std::vector<int> handles(n, -1);
    // Returns the enqueued-tensor count; unqueued members get handle
    // -1, which WaitHandle reports — so draining every handle both
    // surfaces failure and avoids leaking live handles on partial
    // enqueue.
    (void)hvdtpu_enqueue_grouped_allreduce(
        n, names.data(), ins.data(), outs.data(), ndims.data(),
        shape_ptrs.data(), dtype, reduce_op_, prescale_, postscale_,
        process_set_id_, handles.data());
    WaitAsync(c, std::move(done), std::move(handles),
              "HvdTpuGroupedAllreduce");
  }

 private:
  std::vector<std::string> names_;
  int reduce_op_, process_set_id_;
  float prescale_, postscale_;
};
REGISTER_KERNEL_BUILDER(
    Name("HvdTpuGroupedAllreduce").Device(tensorflow::DEVICE_CPU),
    GroupedAllreduceCpuKernel);

class BroadcastCpuKernel : public AsyncOpKernel {
 public:
  explicit BroadcastCpuKernel(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("root_rank", &root_rank_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void ComputeAsync(OpKernelContext* c, DoneCallback done) override {
    const Tensor& in = c->input(0);
    Tensor* out;
    OP_REQUIRES_OK_ASYNC(c, c->allocate_output(0, in.shape(), &out), done);
    int dtype = ToHvdDtype(in.dtype());
    OP_REQUIRES_ASYNC(
        c, dtype >= 0,
        tensorflow::errors::InvalidArgument("unsupported dtype"), done);
    // Core broadcast is in-place: seed the output with this rank's value.
    std::memcpy(const_cast<char*>(out->tensor_data().data()),
                in.tensor_data().data(), in.tensor_data().size());
    auto dims = in.shape().dim_sizes();
    std::vector<int64_t> shape(dims.begin(), dims.end());
    int h = hvdtpu_enqueue_broadcast(
        name_.c_str(), const_cast<char*>(out->tensor_data().data()),
        (int)shape.size(), ShapeData(shape), dtype, root_rank_,
        process_set_id_);
    WaitAsync(c, std::move(done), {h}, "HvdTpuBroadcast");
  }

 private:
  std::string name_;
  int root_rank_, process_set_id_;
};
REGISTER_KERNEL_BUILDER(Name("HvdTpuBroadcast").Device(tensorflow::DEVICE_CPU),
                        BroadcastCpuKernel);

// ---- XLA custom-call metadata --------------------------------------------
//
// The XLA:CPU legacy custom-call ABI is `void fn(void* out, const void**
// ins)` with no opaque payload, so per-call metadata travels as a
// trailing constant u8[] operand:
//
//   i64 kind (0=allreduce, 1=broadcast)
//   i64 num_tensors
//   i64 dtype            (csrc/common.h enum)
//   i64 reduce_op_or_root
//   i64 process_set_id
//   f64 prescale, postscale
//   per tensor: i64 ndim, i64 dims[ndim], i64 name_len, name bytes
//               (zero-padded to an 8-byte boundary)

namespace meta {

static void PutI64(std::vector<uint8_t>& b, int64_t v) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
  b.insert(b.end(), p, p + 8);
}

static void PutF64(std::vector<uint8_t>& b, double v) {
  int64_t bits;
  std::memcpy(&bits, &v, 8);
  PutI64(b, bits);
}

struct TensorMeta {
  std::vector<int64_t> dims;
  std::string name;
};

struct CallMeta {
  int64_t kind = 0;
  int64_t dtype = 0;
  int64_t reduce_op_or_root = 0;
  int64_t process_set_id = 0;
  double prescale = 1.0, postscale = 1.0;
  std::vector<TensorMeta> tensors;
};

static std::vector<uint8_t> Serialize(const CallMeta& m) {
  std::vector<uint8_t> b;
  PutI64(b, m.kind);
  PutI64(b, (int64_t)m.tensors.size());
  PutI64(b, m.dtype);
  PutI64(b, m.reduce_op_or_root);
  PutI64(b, m.process_set_id);
  PutF64(b, m.prescale);
  PutF64(b, m.postscale);
  for (const auto& t : m.tensors) {
    PutI64(b, (int64_t)t.dims.size());
    for (int64_t d : t.dims) PutI64(b, d);
    PutI64(b, (int64_t)t.name.size());
    b.insert(b.end(), t.name.begin(), t.name.end());
    while (b.size() % 8) b.push_back(0);
  }
  return b;
}

class Reader {
 public:
  explicit Reader(const uint8_t* p) : p_(p) {}
  int64_t I64() {
    int64_t v;
    std::memcpy(&v, p_, 8);
    p_ += 8;
    return v;
  }
  double F64() {
    double v;
    std::memcpy(&v, p_, 8);
    p_ += 8;
    return v;
  }
  std::string Str(int64_t n) {
    std::string s(reinterpret_cast<const char*>(p_), n);
    p_ += (n + 7) / 8 * 8;
    return s;
  }

 private:
  const uint8_t* p_;
};

static CallMeta Parse(const uint8_t* p) {
  Reader r(p);
  CallMeta m;
  m.kind = r.I64();
  int64_t n = r.I64();
  m.dtype = r.I64();
  m.reduce_op_or_root = r.I64();
  m.process_set_id = r.I64();
  m.prescale = r.F64();
  m.postscale = r.F64();
  m.tensors.resize(n);
  for (auto& t : m.tensors) {
    int64_t ndim = r.I64();
    t.dims.resize(ndim);
    for (auto& d : t.dims) d = r.I64();
    t.name = r.Str(r.I64());
  }
  return m;
}

}  // namespace meta

// ---- XLA host callbacks ---------------------------------------------------

// Failure inside a compiled program cannot surface a Status through the
// legacy ABI; dying loudly is the HorovodInternalError analog (peers see
// the broken control plane and elastic mode recovers by respawn).
static void DieInXla(const std::string& what, const std::string& why) {
  std::fprintf(stderr, "hvdtpu %s failed inside an XLA program: %s\n",
               what.c_str(), why.c_str());
  std::abort();
}

extern "C" void hvdtpu_tf_xla_collective(void* out, const void** ins) {
  // Operand layout: ins[0] = metadata bytes, ins[1..N] = tensor buffers.
  // N==1 results are a bare buffer; N>1 results arrive as a tuple
  // (void** of leaf buffers).
  meta::CallMeta m = meta::Parse(reinterpret_cast<const uint8_t*>(ins[0]));
  int n = (int)m.tensors.size();
  void** outs_tuple = reinterpret_cast<void**>(out);
  if (!hvdtpu_is_initialized()) {
    DieInXla("collective", "horovod is not initialized");
  }
  if (m.kind == 1) {  // broadcast (always n==1)
    void* dst = n == 1 ? out : outs_tuple[0];
    const auto& t = m.tensors[0];
    int64_t bytes = hvdtpu::DataTypeSize((hvdtpu::DataType)m.dtype);
    for (int64_t d : t.dims) bytes *= d;
    std::memcpy(dst, ins[1], bytes);
    int h = hvdtpu_enqueue_broadcast(
        t.name.c_str(), dst, (int)t.dims.size(), ShapeData(t.dims),
        (int)m.dtype, (int)m.reduce_op_or_root, (int)m.process_set_id);
    auto s = WaitHandle(h, "xla broadcast");
    if (!s.ok()) DieInXla("broadcast", s.ToString());
    return;
  }
  // allreduce (grouped when n > 1): enqueue all, then wait all — one
  // atomic negotiation, and no cross-rank deadlock from wait order.
  std::vector<const char*> names(n);
  std::vector<const void*> inputs(n);
  std::vector<void*> outputs(n);
  std::vector<int> ndims(n);
  std::vector<const int64_t*> shapes(n);
  for (int i = 0; i < n; i++) {
    names[i] = m.tensors[i].name.c_str();
    inputs[i] = ins[1 + i];
    outputs[i] = n == 1 ? out : outs_tuple[i];
    ndims[i] = (int)m.tensors[i].dims.size();
    shapes[i] = ShapeData(m.tensors[i].dims);
  }
  std::vector<int> handles(n, -1);
  if (n == 1) {
    handles[0] = hvdtpu_enqueue_allreduce(
        names[0], inputs[0], outputs[0], ndims[0], shapes[0],
        (int)m.dtype, (int)m.reduce_op_or_root, m.prescale, m.postscale,
        (int)m.process_set_id);
  } else {
    // Returns the enqueued count; unqueued members get handle -1 and
    // fail in the wait loop below.
    (void)hvdtpu_enqueue_grouped_allreduce(
        n, names.data(), inputs.data(), outputs.data(), ndims.data(),
        shapes.data(), (int)m.dtype, (int)m.reduce_op_or_root, m.prescale,
        m.postscale, (int)m.process_set_id, handles.data());
  }
  for (int h : handles) {
    auto s = WaitHandle(h, "xla allreduce");
    if (!s.ok()) DieInXla("allreduce", s.ToString());
  }
}

static bool g_registered = [] {
  xla::CustomCallTargetRegistry::Global()->Register(
      "hvdtpu_tf_xla_collective",
      reinterpret_cast<void*>(&hvdtpu_tf_xla_collective), "Host");
  return true;
}();

// ---- tf2xla kernels -------------------------------------------------------

static xla::XlaOp MetaConstant(xla::XlaBuilder* b,
                               const meta::CallMeta& m) {
  std::vector<uint8_t> bytes = meta::Serialize(m);
  return xla::ConstantR1<uint8_t>(b, bytes);
}

class AllreduceXlaKernel : public tensorflow::XlaOpKernel {
 public:
  explicit AllreduceXlaKernel(OpKernelConstruction* c)
      : tensorflow::XlaOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void Compile(tensorflow::XlaOpKernelContext* ctx) override {
    xla::XlaBuilder* b = ctx->builder();
    auto shape_or = b->GetShape(ctx->Input(0));
    OP_REQUIRES_OK(ctx, shape_or.status());
    const xla::Shape& shape = shape_or.value();
    meta::CallMeta m;
    m.kind = 0;
    m.dtype = ToHvdDtype(ctx->input_type(0));
    OP_REQUIRES(ctx, m.dtype >= 0,
                tensorflow::errors::InvalidArgument("unsupported dtype"));
    m.reduce_op_or_root = reduce_op_;
    m.process_set_id = process_set_id_;
    m.prescale = prescale_;
    m.postscale = postscale_;
    meta::TensorMeta t;
    t.dims.assign(shape.dimensions().begin(), shape.dimensions().end());
    t.name = name_;
    m.tensors.push_back(std::move(t));
    auto out = xla::CustomCall(
        b, "hvdtpu_tf_xla_collective", {MetaConstant(b, m), ctx->Input(0)},
        shape, /*opaque=*/"", /*has_side_effect=*/true);
    ctx->SetOutput(0, out);
  }

 private:
  std::string name_;
  int reduce_op_, process_set_id_;
  float prescale_, postscale_;
};
REGISTER_XLA_OP(Name("HvdTpuAllreduce").Device(tensorflow::DEVICE_CPU_XLA_JIT),
                AllreduceXlaKernel);

class GroupedAllreduceXlaKernel : public tensorflow::XlaOpKernel {
 public:
  explicit GroupedAllreduceXlaKernel(OpKernelConstruction* c)
      : tensorflow::XlaOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_names", &names_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void Compile(tensorflow::XlaOpKernelContext* ctx) override {
    xla::XlaBuilder* b = ctx->builder();
    int n = ctx->num_inputs();
    OP_REQUIRES(ctx, (int)names_.size() == n,
                tensorflow::errors::InvalidArgument(
                    "tensor_names size must match input count"));
    meta::CallMeta m;
    m.kind = 0;
    m.dtype = ToHvdDtype(ctx->input_type(0));
    OP_REQUIRES(ctx, m.dtype >= 0,
                tensorflow::errors::InvalidArgument("unsupported dtype"));
    m.reduce_op_or_root = reduce_op_;
    m.process_set_id = process_set_id_;
    m.prescale = prescale_;
    m.postscale = postscale_;
    std::vector<xla::XlaOp> operands = {xla::XlaOp()};  // meta, below
    std::vector<xla::Shape> shapes;
    for (int i = 0; i < n; i++) {
      auto shape_or = b->GetShape(ctx->Input(i));
      OP_REQUIRES_OK(ctx, shape_or.status());
      meta::TensorMeta t;
      t.dims.assign(shape_or.value().dimensions().begin(),
                    shape_or.value().dimensions().end());
      t.name = names_[i];
      m.tensors.push_back(std::move(t));
      operands.push_back(ctx->Input(i));
      shapes.push_back(shape_or.value());
    }
    operands[0] = MetaConstant(b, m);
    if (n == 1) {
      auto out = xla::CustomCall(b, "hvdtpu_tf_xla_collective", operands,
                                 shapes[0], "", /*has_side_effect=*/true);
      ctx->SetOutput(0, out);
      return;
    }
    xla::Shape tuple = xla::ShapeUtil::MakeTupleShape(shapes);
    auto out = xla::CustomCall(b, "hvdtpu_tf_xla_collective", operands,
                               tuple, "", /*has_side_effect=*/true);
    for (int i = 0; i < n; i++) {
      ctx->SetOutput(i, xla::GetTupleElement(out, i));
    }
  }

 private:
  std::vector<std::string> names_;
  int reduce_op_, process_set_id_;
  float prescale_, postscale_;
};
REGISTER_XLA_OP(
    Name("HvdTpuGroupedAllreduce").Device(tensorflow::DEVICE_CPU_XLA_JIT),
    GroupedAllreduceXlaKernel);

class BroadcastXlaKernel : public tensorflow::XlaOpKernel {
 public:
  explicit BroadcastXlaKernel(OpKernelConstruction* c)
      : tensorflow::XlaOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("root_rank", &root_rank_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void Compile(tensorflow::XlaOpKernelContext* ctx) override {
    xla::XlaBuilder* b = ctx->builder();
    auto shape_or = b->GetShape(ctx->Input(0));
    OP_REQUIRES_OK(ctx, shape_or.status());
    meta::CallMeta m;
    m.kind = 1;
    m.dtype = ToHvdDtype(ctx->input_type(0));
    OP_REQUIRES(ctx, m.dtype >= 0,
                tensorflow::errors::InvalidArgument("unsupported dtype"));
    m.reduce_op_or_root = root_rank_;
    m.process_set_id = process_set_id_;
    meta::TensorMeta t;
    t.dims.assign(shape_or.value().dimensions().begin(),
                  shape_or.value().dimensions().end());
    t.name = name_;
    m.tensors.push_back(std::move(t));
    auto out = xla::CustomCall(
        b, "hvdtpu_tf_xla_collective", {MetaConstant(b, m), ctx->Input(0)},
        shape_or.value(), "", /*has_side_effect=*/true);
    ctx->SetOutput(0, out);
  }

 private:
  std::string name_;
  int root_rank_, process_set_id_;
};
REGISTER_XLA_OP(Name("HvdTpuBroadcast").Device(tensorflow::DEVICE_CPU_XLA_JIT),
                BroadcastXlaKernel);

}  // namespace hvdtpu_tf
