// Native TensorFlow collective ops over the hvdtpu core runtime.
//
// Reference analogs: horovod/tensorflow/mpi_ops.cc (TF custom ops that
// enqueue to the C++ core) and horovod/tensorflow/xla_mpi_ops.cc (the
// HOROVOD_ENABLE_XLA_OPS custom-call bridge that lets collectives live
// inside XLA-compiled programs). Re-founded for this build:
//
// - Each op registers BOTH a regular CPU kernel and a tf2xla kernel.
//   The same graph node therefore works eagerly, inside tf.function,
//   and inside tf.function(jit_compile=True): the TF executor picks the
//   CPU kernel, the XLA bridge picks the tf2xla kernel.
// - The CPU kernel calls the core's enqueue C API directly and waits on
//   the handle — no Python, no GIL, no numpy round-trip (upstream's
//   py_function limitation this file replaces).
// - The tf2xla kernel lowers to an XLA CustomCall whose host callback
//   re-enters the same core. Operand/attr metadata (shapes, dtype,
//   names, reduce op, scale factors) is serialized into a trailing
//   constant byte operand because the XLA:CPU legacy custom-call ABI
//   does not pass `opaque`.
// - Grouped allreduce is ONE op (variadic inputs) on both paths, so a
//   gradient-tape group negotiates atomically as a single fused
//   collective exactly like the eager grouped path.
//
// Ordering contract (same as upstream Horovod's): collectives must be
// issued in a consistent order on every rank. Inside XLA programs this
// holds when ranks compile the same program (SPMD), since XLA:CPU
// executes custom-call thunks in schedule order.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "tensorflow/core/framework/op.h"
#include "tensorflow/core/framework/op_kernel.h"
#include "tensorflow/core/framework/shape_inference.h"

#include "tensorflow/compiler/tf2xla/xla_op_kernel.h"
#include "tensorflow/compiler/tf2xla/xla_op_registry.h"
#include "xla/hlo/builder/xla_builder.h"
#include "xla/service/custom_call_target_registry.h"

// Core C API + dtype enum (single source of truth; linked against
// libhvdtpu_core.so).
#include "common.h"
#include "operations.h"

namespace hvdtpu_tf {

using tensorflow::AsyncOpKernel;
using tensorflow::OpKernel;
using tensorflow::OpKernelConstruction;
using tensorflow::OpKernelContext;
using tensorflow::Tensor;

// Shape pointer for rank-0 (scalar) tensors: the core's group
// validation rejects null shape pointers, and std::vector::data() on an
// empty vector is null.
static const int64_t kScalarShape[1] = {0};

static const int64_t* ShapeData(const std::vector<int64_t>& dims) {
  return dims.empty() ? kScalarShape : dims.data();
}

// ---- dtype mapping --------------------------------------------------------

static int ToHvdDtype(tensorflow::DataType dt) {
  using hvdtpu::DataType;
  switch (dt) {
    case tensorflow::DT_UINT8: return (int)DataType::HVDTPU_UINT8;
    case tensorflow::DT_INT8: return (int)DataType::HVDTPU_INT8;
    case tensorflow::DT_INT32: return (int)DataType::HVDTPU_INT32;
    case tensorflow::DT_INT64: return (int)DataType::HVDTPU_INT64;
    case tensorflow::DT_HALF: return (int)DataType::HVDTPU_FLOAT16;
    case tensorflow::DT_BFLOAT16: return (int)DataType::HVDTPU_BFLOAT16;
    case tensorflow::DT_FLOAT: return (int)DataType::HVDTPU_FLOAT32;
    case tensorflow::DT_DOUBLE: return (int)DataType::HVDTPU_FLOAT64;
    case tensorflow::DT_BOOL: return (int)DataType::HVDTPU_BOOL;
    case tensorflow::DT_UINT16: return (int)DataType::HVDTPU_UINT16;
    default: return -1;
  }
}

// ---- status helpers -------------------------------------------------------

// Failures carry the canonical "HorovodInternalError:" marker inside
// the TF OpError message: that is the wrapped form the elastic
// recovery loop (common/elastic.py:_is_internal_error) classifies as
// recoverable, mirroring how the reference's TF ops surface runtime
// collective failures.
static tensorflow::Status WaitImpl(int handle, const char* what,
                                   bool release_on_success) {
  if (handle < 0) {
    return tensorflow::errors::Internal(
        what, ": HorovodInternalError: enqueue failed "
        "(is horovod initialized?)");
  }
  int rc = hvdtpu_wait(handle);
  if (rc != 0) {
    const char* msg = hvdtpu_error_string(handle);
    std::string reason = msg ? msg : "collective failed";
    hvdtpu_release(handle);
    return tensorflow::errors::Internal(what, ": HorovodInternalError: ",
                                        reason);
  }
  if (release_on_success) hvdtpu_release(handle);
  return tensorflow::OkStatus();
}

static tensorflow::Status WaitHandle(int handle, const char* what) {
  return WaitImpl(handle, what, /*release_on_success=*/true);
}

// Wait WITHOUT releasing on success: managed-result ops (allgather /
// reducescatter / alltoall) still need the handle to query/copy the
// core-owned output buffer; callers release after the copy.
static tensorflow::Status WaitManaged(int handle, const char* what) {
  return WaitImpl(handle, what, /*release_on_success=*/false);
}

// Upstream's HOROVOD_ENABLE_XLA_OPS=0 disables collectives inside
// XLA-compiled functions (they fail to compile with a clear message)
// while the regular kernels keep working — mirror that contract.
static bool XlaOpsEnabled() {
  const char* v = std::getenv("HOROVOD_ENABLE_XLA_OPS");
  return v == nullptr || std::string(v) != "0";
}

#define HVDTPU_REQUIRE_XLA_OPS(ctx)                                     \
  OP_REQUIRES(ctx, XlaOpsEnabled(),                                     \
              tensorflow::errors::FailedPrecondition(                   \
                  "horovod collectives inside jit-compiled functions "  \
                  "are disabled (HOROVOD_ENABLE_XLA_OPS=0); run this "  \
                  "function without jit_compile"))

// ---- op registrations -----------------------------------------------------

REGISTER_OP("HvdTpuAllreduce")
    .Input("tensor: T")
    .Output("output: T")
    .Attr("T: {uint8, int8, uint16, int32, int64, half, bfloat16, float, "
          "double}")
    .Attr("tensor_name: string")
    .Attr("reduce_op: int = 0")  // csrc ReduceOp: AVERAGE=0
    .Attr("prescale_factor: float = 1.0")
    .Attr("postscale_factor: float = 1.0")
    .Attr("process_set_id: int = 0")
    .SetShapeFn(tensorflow::shape_inference::UnchangedShape);

REGISTER_OP("HvdTpuGroupedAllreduce")
    .Input("tensors: N * T")
    .Output("outputs: N * T")
    .Attr("N: int >= 1")
    .Attr("T: {uint8, int8, uint16, int32, int64, half, bfloat16, float, "
          "double}")
    .Attr("tensor_names: list(string)")
    .Attr("reduce_op: int = 0")
    .Attr("prescale_factor: float = 1.0")
    .Attr("postscale_factor: float = 1.0")
    .Attr("process_set_id: int = 0")
    .SetShapeFn([](tensorflow::shape_inference::InferenceContext* c) {
      for (int i = 0; i < c->num_inputs(); i++) c->set_output(i, c->input(i));
      return tensorflow::OkStatus();
    });

REGISTER_OP("HvdTpuBroadcast")
    .Input("tensor: T")
    .Output("output: T")
    .Attr("T: {uint8, int8, uint16, int32, int64, half, bfloat16, float, "
          "double, bool}")
    .Attr("tensor_name: string")
    .Attr("root_rank: int")
    .Attr("process_set_id: int = 0")
    .SetShapeFn(tensorflow::shape_inference::UnchangedShape);

// Output rank matches the input; the first dim is only known at run
// time (ragged allgather / rank-dependent reducescatter share).
static tensorflow::Status UnknownFirstDimShape(
    tensorflow::shape_inference::InferenceContext* c) {
  tensorflow::shape_inference::ShapeHandle in = c->input(0);
  if (!c->RankKnown(in) || c->Rank(in) == 0) {
    c->set_output(0, c->UnknownShape());
    return tensorflow::OkStatus();
  }
  tensorflow::shape_inference::ShapeHandle out;
  TF_RETURN_IF_ERROR(c->ReplaceDim(in, 0, c->UnknownDim(), &out));
  c->set_output(0, out);
  return tensorflow::OkStatus();
}

REGISTER_OP("HvdTpuAllgather")
    .Input("tensor: T")
    .Output("output: T")
    .Attr("T: {uint8, int8, uint16, int32, int64, half, bfloat16, float, "
          "double, bool}")
    .Attr("tensor_name: string")
    .Attr("process_set_id: int = 0")
    .SetShapeFn(UnknownFirstDimShape);

REGISTER_OP("HvdTpuReducescatter")
    .Input("tensor: T")
    .Output("output: T")
    .Attr("T: {uint8, int8, uint16, int32, int64, half, bfloat16, float, "
          "double}")
    .Attr("tensor_name: string")
    .Attr("reduce_op: int = 0")
    .Attr("prescale_factor: float = 1.0")
    .Attr("postscale_factor: float = 1.0")
    .Attr("process_set_id: int = 0")
    .SetShapeFn(UnknownFirstDimShape);

// splits: per-destination-rank first-dim row counts; EMPTY means equal
// split. Output first dim depends on peers' splits -> unknown.
REGISTER_OP("HvdTpuAlltoall")
    .Input("tensor: T")
    .Input("splits: int64")
    .Output("output: T")
    .Attr("T: {uint8, int8, uint16, int32, int64, half, bfloat16, float, "
          "double, bool}")
    .Attr("tensor_name: string")
    .Attr("process_set_id: int = 0")
    .SetShapeFn(UnknownFirstDimShape);

// ---- CPU kernels ----------------------------------------------------------

// CPU kernels are ASYNC: Compute must not block the (possibly single)
// executor thread in hvdtpu_wait — with inter-op parallelism 1, two
// ranks blocking on differently-ordered independent collectives would
// deadlock. ComputeAsync enqueues, releases the thread, and a detached
// waiter fires `done` on completion (reference analog: the
// AsyncOpKernel pattern of horovod/tensorflow/mpi_ops.cc; TF keeps the
// context and its tensors alive until `done`).
static void WaitAsync(OpKernelContext* c, AsyncOpKernel::DoneCallback done,
                      std::vector<int> handles, const char* what) {
  std::thread([c, done = std::move(done), handles = std::move(handles),
               what]() {
    tensorflow::Status status = tensorflow::OkStatus();
    for (int h : handles) {  // drain every handle even when one fails
      auto s = WaitHandle(h, what);
      if (!s.ok()) status = s;
    }
    if (!status.ok()) c->SetStatus(status);
    done();
  }).detach();
}

class AllreduceCpuKernel : public AsyncOpKernel {
 public:
  explicit AllreduceCpuKernel(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void ComputeAsync(OpKernelContext* c, DoneCallback done) override {
    const Tensor& in = c->input(0);
    Tensor* out;
    OP_REQUIRES_OK_ASYNC(c, c->allocate_output(0, in.shape(), &out), done);
    int dtype = ToHvdDtype(in.dtype());
    OP_REQUIRES_ASYNC(
        c, dtype >= 0,
        tensorflow::errors::InvalidArgument("unsupported dtype"), done);
    auto dims = in.shape().dim_sizes();
    std::vector<int64_t> shape(dims.begin(), dims.end());
    int h = hvdtpu_enqueue_allreduce(
        name_.c_str(), in.tensor_data().data(),
        const_cast<char*>(out->tensor_data().data()), (int)shape.size(),
        ShapeData(shape), dtype, reduce_op_, prescale_, postscale_,
        process_set_id_);
    WaitAsync(c, std::move(done), {h}, "HvdTpuAllreduce");
  }

 private:
  std::string name_;
  int reduce_op_, process_set_id_;
  float prescale_, postscale_;
};
REGISTER_KERNEL_BUILDER(Name("HvdTpuAllreduce").Device(tensorflow::DEVICE_CPU),
                        AllreduceCpuKernel);

class GroupedAllreduceCpuKernel : public AsyncOpKernel {
 public:
  explicit GroupedAllreduceCpuKernel(OpKernelConstruction* c)
      : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_names", &names_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void ComputeAsync(OpKernelContext* c, DoneCallback done) override {
    int n = c->num_inputs();
    OP_REQUIRES_ASYNC(c, (int)names_.size() == n,
                      tensorflow::errors::InvalidArgument(
                          "tensor_names size must match input count"),
                      done);
    std::vector<const char*> names(n);
    std::vector<const void*> ins(n);
    std::vector<void*> outs(n);
    std::vector<int> ndims(n);
    std::vector<std::vector<int64_t>> shapes(n);
    std::vector<const int64_t*> shape_ptrs(n);
    int dtype = -1;
    for (int i = 0; i < n; i++) {
      const Tensor& in = c->input(i);
      Tensor* out;
      OP_REQUIRES_OK_ASYNC(c, c->allocate_output(i, in.shape(), &out),
                           done);
      names[i] = names_[i].c_str();
      ins[i] = in.tensor_data().data();
      outs[i] = const_cast<char*>(out->tensor_data().data());
      auto dims = in.shape().dim_sizes();
      shapes[i].assign(dims.begin(), dims.end());
      ndims[i] = (int)shapes[i].size();
      shape_ptrs[i] = ShapeData(shapes[i]);
      dtype = ToHvdDtype(in.dtype());
      OP_REQUIRES_ASYNC(
          c, dtype >= 0,
          tensorflow::errors::InvalidArgument("unsupported dtype"), done);
    }
    std::vector<int> handles(n, -1);
    // Returns the enqueued-tensor count; unqueued members get handle
    // -1, which WaitHandle reports — so draining every handle both
    // surfaces failure and avoids leaking live handles on partial
    // enqueue.
    (void)hvdtpu_enqueue_grouped_allreduce(
        n, names.data(), ins.data(), outs.data(), ndims.data(),
        shape_ptrs.data(), dtype, reduce_op_, prescale_, postscale_,
        process_set_id_, handles.data());
    WaitAsync(c, std::move(done), std::move(handles),
              "HvdTpuGroupedAllreduce");
  }

 private:
  std::vector<std::string> names_;
  int reduce_op_, process_set_id_;
  float prescale_, postscale_;
};
REGISTER_KERNEL_BUILDER(
    Name("HvdTpuGroupedAllreduce").Device(tensorflow::DEVICE_CPU),
    GroupedAllreduceCpuKernel);

// Managed-result completion: the core owns the output buffer (its size
// depends on peers), so the waiter allocates the TF output from the
// result shape and copies once.
static void WaitManagedAsync(OpKernelContext* c,
                             AsyncOpKernel::DoneCallback done, int handle,
                             const char* what) {
  std::thread([c, done = std::move(done), handle, what]() {
    auto s = WaitManaged(handle, what);
    if (!s.ok()) {
      c->SetStatus(s);
      done();
      return;
    }
    int nd = hvdtpu_result_ndim(handle);
    std::vector<int64_t> dims(nd > 0 ? nd : 0);
    if (nd > 0) hvdtpu_result_shape(handle, dims.data());
    tensorflow::TensorShape shape;
    for (int64_t d : dims) shape.AddDim(d);
    Tensor* out = nullptr;
    auto as = c->allocate_output(0, shape, &out);
    if (!as.ok()) {
      hvdtpu_release(handle);
      c->SetStatus(as);
      done();
      return;
    }
    if (hvdtpu_result_copy(
            handle, const_cast<char*>(out->tensor_data().data()),
            (int64_t)out->tensor_data().size()) != 0) {
      c->SetStatus(tensorflow::errors::Internal(
          what, ": HorovodInternalError: result copy failed"));
    }
    hvdtpu_release(handle);
    done();
  }).detach();
}

class AllgatherCpuKernel : public AsyncOpKernel {
 public:
  explicit AllgatherCpuKernel(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void ComputeAsync(OpKernelContext* c, DoneCallback done) override {
    const Tensor& in = c->input(0);
    int dtype = ToHvdDtype(in.dtype());
    OP_REQUIRES_ASYNC(
        c, dtype >= 0,
        tensorflow::errors::InvalidArgument("unsupported dtype"), done);
    auto dims = in.shape().dim_sizes();
    std::vector<int64_t> shape(dims.begin(), dims.end());
    int h = hvdtpu_enqueue_allgather(
        name_.c_str(), in.tensor_data().data(), (int)shape.size(),
        ShapeData(shape), dtype, process_set_id_, /*group_id=*/-1,
        /*group_size=*/0);
    WaitManagedAsync(c, std::move(done), h, "HvdTpuAllgather");
  }

 private:
  std::string name_;
  int process_set_id_;
};
REGISTER_KERNEL_BUILDER(Name("HvdTpuAllgather").Device(tensorflow::DEVICE_CPU),
                        AllgatherCpuKernel);

class ReducescatterCpuKernel : public AsyncOpKernel {
 public:
  explicit ReducescatterCpuKernel(OpKernelConstruction* c)
      : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void ComputeAsync(OpKernelContext* c, DoneCallback done) override {
    const Tensor& in = c->input(0);
    int dtype = ToHvdDtype(in.dtype());
    OP_REQUIRES_ASYNC(
        c, dtype >= 0,
        tensorflow::errors::InvalidArgument("unsupported dtype"), done);
    auto dims = in.shape().dim_sizes();
    std::vector<int64_t> shape(dims.begin(), dims.end());
    int h = hvdtpu_enqueue_reducescatter(
        name_.c_str(), in.tensor_data().data(), (int)shape.size(),
        ShapeData(shape), dtype, reduce_op_, prescale_, postscale_,
        process_set_id_, /*group_id=*/-1, /*group_size=*/0);
    WaitManagedAsync(c, std::move(done), h, "HvdTpuReducescatter");
  }

 private:
  std::string name_;
  int reduce_op_, process_set_id_;
  float prescale_, postscale_;
};
REGISTER_KERNEL_BUILDER(
    Name("HvdTpuReducescatter").Device(tensorflow::DEVICE_CPU),
    ReducescatterCpuKernel);

class AlltoallCpuKernel : public AsyncOpKernel {
 public:
  explicit AlltoallCpuKernel(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void ComputeAsync(OpKernelContext* c, DoneCallback done) override {
    const Tensor& in = c->input(0);
    const Tensor& splits = c->input(1);
    int dtype = ToHvdDtype(in.dtype());
    OP_REQUIRES_ASYNC(
        c, dtype >= 0,
        tensorflow::errors::InvalidArgument("unsupported dtype"), done);
    auto dims = in.shape().dim_sizes();
    std::vector<int64_t> shape(dims.begin(), dims.end());
    // Empty splits tensor = equal split across the set; otherwise the
    // core reads exactly process-set-size entries.
    const int64_t* sp = nullptr;
    if (splits.NumElements() > 0) {
      int group = hvdtpu_process_set_size(process_set_id_);
      OP_REQUIRES_ASYNC(
          c, (int64_t)splits.NumElements() == (int64_t)group,
          tensorflow::errors::InvalidArgument(
              "alltoall splits must have one entry per process-set "
              "member (", group, "), got ", splits.NumElements()),
          done);
      sp = splits.flat<int64_t>().data();
    }
    int h = hvdtpu_enqueue_alltoall(
        name_.c_str(), in.tensor_data().data(), (int)shape.size(),
        ShapeData(shape), dtype, sp, process_set_id_);
    WaitManagedAsync(c, std::move(done), h, "HvdTpuAlltoall");
  }

 private:
  std::string name_;
  int process_set_id_;
};
REGISTER_KERNEL_BUILDER(Name("HvdTpuAlltoall").Device(tensorflow::DEVICE_CPU),
                        AlltoallCpuKernel);

class BroadcastCpuKernel : public AsyncOpKernel {
 public:
  explicit BroadcastCpuKernel(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("root_rank", &root_rank_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void ComputeAsync(OpKernelContext* c, DoneCallback done) override {
    const Tensor& in = c->input(0);
    Tensor* out;
    OP_REQUIRES_OK_ASYNC(c, c->allocate_output(0, in.shape(), &out), done);
    int dtype = ToHvdDtype(in.dtype());
    OP_REQUIRES_ASYNC(
        c, dtype >= 0,
        tensorflow::errors::InvalidArgument("unsupported dtype"), done);
    // Core broadcast is in-place: seed the output with this rank's value.
    std::memcpy(const_cast<char*>(out->tensor_data().data()),
                in.tensor_data().data(), in.tensor_data().size());
    auto dims = in.shape().dim_sizes();
    std::vector<int64_t> shape(dims.begin(), dims.end());
    int h = hvdtpu_enqueue_broadcast(
        name_.c_str(), const_cast<char*>(out->tensor_data().data()),
        (int)shape.size(), ShapeData(shape), dtype, root_rank_,
        process_set_id_);
    WaitAsync(c, std::move(done), {h}, "HvdTpuBroadcast");
  }

 private:
  std::string name_;
  int root_rank_, process_set_id_;
};
REGISTER_KERNEL_BUILDER(Name("HvdTpuBroadcast").Device(tensorflow::DEVICE_CPU),
                        BroadcastCpuKernel);

// ---- XLA custom-call metadata --------------------------------------------
//
// The XLA:CPU legacy custom-call ABI is `void fn(void* out, const void**
// ins)` with no opaque payload, so per-call metadata travels as a
// trailing constant u8[] operand:
//
//   i64 kind (0=allreduce, 1=broadcast)
//   i64 num_tensors
//   i64 dtype            (csrc/common.h enum)
//   i64 reduce_op_or_root
//   i64 process_set_id
//   f64 prescale, postscale
//   per tensor: i64 ndim, i64 dims[ndim], i64 name_len, name bytes
//               (zero-padded to an 8-byte boundary)

namespace meta {

static void PutI64(std::vector<uint8_t>& b, int64_t v) {
  const uint8_t* p = reinterpret_cast<const uint8_t*>(&v);
  b.insert(b.end(), p, p + 8);
}

static void PutF64(std::vector<uint8_t>& b, double v) {
  int64_t bits;
  std::memcpy(&bits, &v, 8);
  PutI64(b, bits);
}

struct TensorMeta {
  std::vector<int64_t> dims;
  std::string name;
};

struct CallMeta {
  int64_t kind = 0;
  int64_t dtype = 0;
  int64_t reduce_op_or_root = 0;
  int64_t process_set_id = 0;
  double prescale = 1.0, postscale = 1.0;
  std::vector<TensorMeta> tensors;
};

static std::vector<uint8_t> Serialize(const CallMeta& m) {
  std::vector<uint8_t> b;
  PutI64(b, 0);  // total byte length, patched in below
  PutI64(b, m.kind);
  PutI64(b, (int64_t)m.tensors.size());
  PutI64(b, m.dtype);
  PutI64(b, m.reduce_op_or_root);
  PutI64(b, m.process_set_id);
  PutF64(b, m.prescale);
  PutF64(b, m.postscale);
  for (const auto& t : m.tensors) {
    PutI64(b, (int64_t)t.dims.size());
    for (int64_t d : t.dims) PutI64(b, d);
    PutI64(b, (int64_t)t.name.size());
    b.insert(b.end(), t.name.begin(), t.name.end());
    while (b.size() % 8) b.push_back(0);
  }
  int64_t total = (int64_t)b.size();
  std::memcpy(b.data(), &total, 8);
  return b;
}

// Sanity caps for the self-declared metadata: nothing legitimate comes
// close, and a corrupted buffer can't make the parser walk far past it.
constexpr int64_t kMaxMetaBytes = int64_t(64) << 20;  // 64 MiB
constexpr int64_t kMaxMetaTensors = 1 << 20;
constexpr int64_t kMaxMetaNdim = 255;

// Bounds-checked reader over the self-framing metadata buffer (ADVICE
// r2): every read validates against the declared total, and any
// inconsistency poisons the reader instead of walking off the buffer.
class Reader {
 public:
  Reader(const uint8_t* p, int64_t len) : p_(p), end_(p + len) {}
  bool ok() const { return ok_; }
  int64_t I64() {
    if (!Need(8)) return 0;
    int64_t v;
    std::memcpy(&v, p_, 8);
    p_ += 8;
    return v;
  }
  double F64() {
    if (!Need(8)) return 0.0;
    double v;
    std::memcpy(&v, p_, 8);
    p_ += 8;
    return v;
  }
  std::string Str(int64_t n) {
    int64_t padded = (n + 7) / 8 * 8;
    if (n < 0 || padded < n || !Need(padded)) {
      ok_ = false;
      return std::string();
    }
    std::string s(reinterpret_cast<const char*>(p_), n);
    p_ += padded;
    return s;
  }

 private:
  bool Need(int64_t n) {
    if (!ok_ || end_ - p_ < n) {
      ok_ = false;
      return false;
    }
    return true;
  }
  const uint8_t* p_;
  const uint8_t* end_;
  bool ok_ = true;
};

// Parse with validation; returns false (and a reason) on any
// inconsistency so the caller can die loudly instead of reading OOB.
static bool Parse(const uint8_t* p, CallMeta* m, std::string* why) {
  int64_t total;
  std::memcpy(&total, p, 8);
  if (total < 8 * 8 || total > kMaxMetaBytes || total % 8) {
    *why = "implausible metadata length " + std::to_string(total);
    return false;
  }
  Reader r(p, total);
  r.I64();  // the length header itself
  m->kind = r.I64();
  int64_t n = r.I64();
  m->dtype = r.I64();
  m->reduce_op_or_root = r.I64();
  m->process_set_id = r.I64();
  m->prescale = r.F64();
  m->postscale = r.F64();
  if (m->kind < 0 || m->kind > 4) {
    *why = "unknown collective kind " + std::to_string(m->kind);
    return false;
  }
  // Managed-result ops (kind>=2) carry [input dims, output dims]; the
  // others need at least the one tensor the callback dereferences.
  int64_t min_tensors = m->kind >= 2 ? 2 : 1;
  if (n < min_tensors || n > kMaxMetaTensors) {
    *why = "implausible tensor count " + std::to_string(n) +
           " for kind " + std::to_string(m->kind);
    return false;
  }
  m->tensors.resize(n);
  for (auto& t : m->tensors) {
    int64_t ndim = r.I64();
    if (!r.ok() || ndim < 0 || ndim > kMaxMetaNdim) {
      *why = "implausible ndim " + std::to_string(ndim);
      return false;
    }
    t.dims.resize(ndim);
    for (auto& d : t.dims) d = r.I64();
    t.name = r.Str(r.I64());
  }
  if (!r.ok()) {
    *why = "metadata truncated relative to declared length";
    return false;
  }
  return true;
}

}  // namespace meta

// ---- XLA host callbacks ---------------------------------------------------

// Failure inside a compiled program cannot surface a Status through the
// legacy ABI; dying loudly is the HorovodInternalError analog (peers see
// the broken control plane and elastic mode recovers by respawn).
static void DieInXla(const std::string& what, const std::string& why) {
  std::fprintf(stderr, "hvdtpu %s failed inside an XLA program: %s\n",
               what.c_str(), why.c_str());
  std::abort();
}

extern "C" void hvdtpu_tf_xla_collective(void* out, const void** ins) {
  // Operand layout: ins[0] = metadata bytes, ins[1..N] = tensor buffers.
  // N==1 results are a bare buffer; N>1 results arrive as a tuple
  // (void** of leaf buffers).
  meta::CallMeta m;
  std::string why;
  if (!meta::Parse(reinterpret_cast<const uint8_t*>(ins[0]), &m, &why)) {
    DieInXla("metadata parse", why);
  }
  int n = (int)m.tensors.size();
  void** outs_tuple = reinterpret_cast<void**>(out);
  if (!hvdtpu_is_initialized()) {
    DieInXla("collective", "horovod is not initialized");
  }
  if (m.kind >= 2) {
    // Managed-result ops (2=allgather, 3=reducescatter, 4=alltoall):
    // tensors[0] = input dims, tensors[1] = the COMPILE-TIME output
    // dims; the core-owned result must match them exactly (in-jit these
    // ops require shapes to be equal across ranks — XLA buffers are
    // static).
    const auto& tin = m.tensors[0];
    const auto& tout = m.tensors[1];
    int h = -1;
    if (m.kind == 2) {
      h = hvdtpu_enqueue_allgather(
          tin.name.c_str(), ins[1], (int)tin.dims.size(),
          ShapeData(tin.dims), (int)m.dtype, (int)m.process_set_id,
          /*group_id=*/-1, /*group_size=*/0);
    } else if (m.kind == 3) {
      h = hvdtpu_enqueue_reducescatter(
          tin.name.c_str(), ins[1], (int)tin.dims.size(),
          ShapeData(tin.dims), (int)m.dtype, (int)m.reduce_op_or_root,
          m.prescale, m.postscale, (int)m.process_set_id,
          /*group_id=*/-1, /*group_size=*/0);
    } else {
      h = hvdtpu_enqueue_alltoall(
          tin.name.c_str(), ins[1], (int)tin.dims.size(),
          ShapeData(tin.dims), (int)m.dtype, nullptr,
          (int)m.process_set_id);
    }
    auto s = WaitManaged(h, "xla managed collective");
    if (!s.ok()) DieInXla("managed collective", s.ToString());
    int64_t expect =
        hvdtpu::DataTypeSize((hvdtpu::DataType)m.dtype);
    for (int64_t d : tout.dims) expect *= d;
    if (hvdtpu_result_size_bytes(h) != expect) {
      hvdtpu_release(h);
      DieInXla("managed collective",
               "result shape differs from the compiled one — in-jit "
               "allgather/reducescatter/alltoall require identical "
               "shapes on every rank");
    }
    if (hvdtpu_result_copy(h, out, expect) != 0) {
      hvdtpu_release(h);
      DieInXla("managed collective", "result copy failed");
    }
    hvdtpu_release(h);
    return;
  }
  if (m.kind == 1) {  // broadcast (always n==1)
    void* dst = n == 1 ? out : outs_tuple[0];
    const auto& t = m.tensors[0];
    int64_t bytes = hvdtpu::DataTypeSize((hvdtpu::DataType)m.dtype);
    for (int64_t d : t.dims) bytes *= d;
    std::memcpy(dst, ins[1], bytes);
    int h = hvdtpu_enqueue_broadcast(
        t.name.c_str(), dst, (int)t.dims.size(), ShapeData(t.dims),
        (int)m.dtype, (int)m.reduce_op_or_root, (int)m.process_set_id);
    auto s = WaitHandle(h, "xla broadcast");
    if (!s.ok()) DieInXla("broadcast", s.ToString());
    return;
  }
  // allreduce (grouped when n > 1): enqueue all, then wait all — one
  // atomic negotiation, and no cross-rank deadlock from wait order.
  std::vector<const char*> names(n);
  std::vector<const void*> inputs(n);
  std::vector<void*> outputs(n);
  std::vector<int> ndims(n);
  std::vector<const int64_t*> shapes(n);
  for (int i = 0; i < n; i++) {
    names[i] = m.tensors[i].name.c_str();
    inputs[i] = ins[1 + i];
    outputs[i] = n == 1 ? out : outs_tuple[i];
    ndims[i] = (int)m.tensors[i].dims.size();
    shapes[i] = ShapeData(m.tensors[i].dims);
  }
  std::vector<int> handles(n, -1);
  if (n == 1) {
    handles[0] = hvdtpu_enqueue_allreduce(
        names[0], inputs[0], outputs[0], ndims[0], shapes[0],
        (int)m.dtype, (int)m.reduce_op_or_root, m.prescale, m.postscale,
        (int)m.process_set_id);
  } else {
    // Returns the enqueued count; unqueued members get handle -1 and
    // fail in the wait loop below.
    (void)hvdtpu_enqueue_grouped_allreduce(
        n, names.data(), inputs.data(), outputs.data(), ndims.data(),
        shapes.data(), (int)m.dtype, (int)m.reduce_op_or_root, m.prescale,
        m.postscale, (int)m.process_set_id, handles.data());
  }
  for (int h : handles) {
    auto s = WaitHandle(h, "xla allreduce");
    if (!s.ok()) DieInXla("allreduce", s.ToString());
  }
}

static bool g_registered = [] {
  xla::CustomCallTargetRegistry::Global()->Register(
      "hvdtpu_tf_xla_collective",
      reinterpret_cast<void*>(&hvdtpu_tf_xla_collective), "Host");
  return true;
}();

// ---- tf2xla kernels -------------------------------------------------------

static xla::XlaOp MetaConstant(xla::XlaBuilder* b,
                               const meta::CallMeta& m) {
  std::vector<uint8_t> bytes = meta::Serialize(m);
  return xla::ConstantR1<uint8_t>(b, bytes);
}

class AllreduceXlaKernel : public tensorflow::XlaOpKernel {
 public:
  explicit AllreduceXlaKernel(OpKernelConstruction* c)
      : tensorflow::XlaOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void Compile(tensorflow::XlaOpKernelContext* ctx) override {
    HVDTPU_REQUIRE_XLA_OPS(ctx);
    xla::XlaBuilder* b = ctx->builder();
    auto shape_or = b->GetShape(ctx->Input(0));
    OP_REQUIRES_OK(ctx, shape_or.status());
    const xla::Shape& shape = shape_or.value();
    meta::CallMeta m;
    m.kind = 0;
    m.dtype = ToHvdDtype(ctx->input_type(0));
    OP_REQUIRES(ctx, m.dtype >= 0,
                tensorflow::errors::InvalidArgument("unsupported dtype"));
    m.reduce_op_or_root = reduce_op_;
    m.process_set_id = process_set_id_;
    m.prescale = prescale_;
    m.postscale = postscale_;
    meta::TensorMeta t;
    t.dims.assign(shape.dimensions().begin(), shape.dimensions().end());
    t.name = name_;
    m.tensors.push_back(std::move(t));
    auto out = xla::CustomCall(
        b, "hvdtpu_tf_xla_collective", {MetaConstant(b, m), ctx->Input(0)},
        shape, /*opaque=*/"", /*has_side_effect=*/true);
    ctx->SetOutput(0, out);
  }

 private:
  std::string name_;
  int reduce_op_, process_set_id_;
  float prescale_, postscale_;
};
REGISTER_XLA_OP(Name("HvdTpuAllreduce").Device(tensorflow::DEVICE_CPU_XLA_JIT),
                AllreduceXlaKernel);

class GroupedAllreduceXlaKernel : public tensorflow::XlaOpKernel {
 public:
  explicit GroupedAllreduceXlaKernel(OpKernelConstruction* c)
      : tensorflow::XlaOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_names", &names_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void Compile(tensorflow::XlaOpKernelContext* ctx) override {
    HVDTPU_REQUIRE_XLA_OPS(ctx);
    xla::XlaBuilder* b = ctx->builder();
    int n = ctx->num_inputs();
    OP_REQUIRES(ctx, (int)names_.size() == n,
                tensorflow::errors::InvalidArgument(
                    "tensor_names size must match input count"));
    meta::CallMeta m;
    m.kind = 0;
    m.dtype = ToHvdDtype(ctx->input_type(0));
    OP_REQUIRES(ctx, m.dtype >= 0,
                tensorflow::errors::InvalidArgument("unsupported dtype"));
    m.reduce_op_or_root = reduce_op_;
    m.process_set_id = process_set_id_;
    m.prescale = prescale_;
    m.postscale = postscale_;
    std::vector<xla::XlaOp> operands = {xla::XlaOp()};  // meta, below
    std::vector<xla::Shape> shapes;
    for (int i = 0; i < n; i++) {
      auto shape_or = b->GetShape(ctx->Input(i));
      OP_REQUIRES_OK(ctx, shape_or.status());
      meta::TensorMeta t;
      t.dims.assign(shape_or.value().dimensions().begin(),
                    shape_or.value().dimensions().end());
      t.name = names_[i];
      m.tensors.push_back(std::move(t));
      operands.push_back(ctx->Input(i));
      shapes.push_back(shape_or.value());
    }
    operands[0] = MetaConstant(b, m);
    if (n == 1) {
      auto out = xla::CustomCall(b, "hvdtpu_tf_xla_collective", operands,
                                 shapes[0], "", /*has_side_effect=*/true);
      ctx->SetOutput(0, out);
      return;
    }
    xla::Shape tuple = xla::ShapeUtil::MakeTupleShape(shapes);
    auto out = xla::CustomCall(b, "hvdtpu_tf_xla_collective", operands,
                               tuple, "", /*has_side_effect=*/true);
    for (int i = 0; i < n; i++) {
      ctx->SetOutput(i, xla::GetTupleElement(out, i));
    }
  }

 private:
  std::vector<std::string> names_;
  int reduce_op_, process_set_id_;
  float prescale_, postscale_;
};
REGISTER_XLA_OP(
    Name("HvdTpuGroupedAllreduce").Device(tensorflow::DEVICE_CPU_XLA_JIT),
    GroupedAllreduceXlaKernel);

class BroadcastXlaKernel : public tensorflow::XlaOpKernel {
 public:
  explicit BroadcastXlaKernel(OpKernelConstruction* c)
      : tensorflow::XlaOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("root_rank", &root_rank_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void Compile(tensorflow::XlaOpKernelContext* ctx) override {
    HVDTPU_REQUIRE_XLA_OPS(ctx);
    xla::XlaBuilder* b = ctx->builder();
    auto shape_or = b->GetShape(ctx->Input(0));
    OP_REQUIRES_OK(ctx, shape_or.status());
    meta::CallMeta m;
    m.kind = 1;
    m.dtype = ToHvdDtype(ctx->input_type(0));
    OP_REQUIRES(ctx, m.dtype >= 0,
                tensorflow::errors::InvalidArgument("unsupported dtype"));
    m.reduce_op_or_root = root_rank_;
    m.process_set_id = process_set_id_;
    meta::TensorMeta t;
    t.dims.assign(shape_or.value().dimensions().begin(),
                  shape_or.value().dimensions().end());
    t.name = name_;
    m.tensors.push_back(std::move(t));
    auto out = xla::CustomCall(
        b, "hvdtpu_tf_xla_collective", {MetaConstant(b, m), ctx->Input(0)},
        shape_or.value(), "", /*has_side_effect=*/true);
    ctx->SetOutput(0, out);
  }

 private:
  std::string name_;
  int root_rank_, process_set_id_;
};
REGISTER_XLA_OP(Name("HvdTpuBroadcast").Device(tensorflow::DEVICE_CPU_XLA_JIT),
                BroadcastXlaKernel);

}  // namespace hvdtpu_tf

namespace hvdtpu_tf {

// In-jit managed-result kernels: output shapes are derived at COMPILE
// time from the process-set geometry (the core is initialized before
// the first XLA compile — init() loads this library), so these require
// shape-identical inputs on every rank; the callback verifies at run
// time and dies loudly on divergence.

static meta::CallMeta ManagedMeta(int64_t kind, int dtype, int ps,
                                  const std::string& name,
                                  const std::vector<int64_t>& in_dims,
                                  const std::vector<int64_t>& out_dims) {
  meta::CallMeta m;
  m.kind = kind;
  m.dtype = dtype;
  m.process_set_id = ps;
  meta::TensorMeta tin;
  tin.dims = in_dims;
  tin.name = name;
  m.tensors.push_back(std::move(tin));
  meta::TensorMeta tout;
  tout.dims = out_dims;
  m.tensors.push_back(std::move(tout));
  return m;
}

class AllgatherXlaKernel : public tensorflow::XlaOpKernel {
 public:
  explicit AllgatherXlaKernel(OpKernelConstruction* c)
      : tensorflow::XlaOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void Compile(tensorflow::XlaOpKernelContext* ctx) override {
    HVDTPU_REQUIRE_XLA_OPS(ctx);
    xla::XlaBuilder* b = ctx->builder();
    auto shape_or = b->GetShape(ctx->Input(0));
    OP_REQUIRES_OK(ctx, shape_or.status());
    int group = hvdtpu_process_set_size(process_set_id_);
    OP_REQUIRES(ctx, group > 0,
                tensorflow::errors::FailedPrecondition(
                    "hvd.init() must run before jit-compiling allgather"));
    std::vector<int64_t> in_dims(shape_or.value().dimensions().begin(),
                                 shape_or.value().dimensions().end());
    std::vector<int64_t> out_dims =
        in_dims.empty() ? std::vector<int64_t>{group} : in_dims;
    if (!in_dims.empty()) out_dims[0] *= group;
    int dtype = ToHvdDtype(ctx->input_type(0));
    OP_REQUIRES(ctx, dtype >= 0,
                tensorflow::errors::InvalidArgument("unsupported dtype"));
    meta::CallMeta m = ManagedMeta(2, dtype, process_set_id_, name_,
                                   in_dims, out_dims);
    xla::Shape out_shape = xla::ShapeUtil::MakeShape(
        shape_or.value().element_type(), out_dims);
    auto res = xla::CustomCall(
        b, "hvdtpu_tf_xla_collective", {MetaConstant(b, m), ctx->Input(0)},
        out_shape, "", /*has_side_effect=*/true);
    ctx->SetOutput(0, res);
  }

 private:
  std::string name_;
  int process_set_id_;
};
REGISTER_XLA_OP(Name("HvdTpuAllgather").Device(tensorflow::DEVICE_CPU_XLA_JIT),
                AllgatherXlaKernel);

class ReducescatterXlaKernel : public tensorflow::XlaOpKernel {
 public:
  explicit ReducescatterXlaKernel(OpKernelConstruction* c)
      : tensorflow::XlaOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("reduce_op", &reduce_op_));
    OP_REQUIRES_OK(c, c->GetAttr("prescale_factor", &prescale_));
    OP_REQUIRES_OK(c, c->GetAttr("postscale_factor", &postscale_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void Compile(tensorflow::XlaOpKernelContext* ctx) override {
    HVDTPU_REQUIRE_XLA_OPS(ctx);
    xla::XlaBuilder* b = ctx->builder();
    auto shape_or = b->GetShape(ctx->Input(0));
    OP_REQUIRES_OK(ctx, shape_or.status());
    int group = hvdtpu_process_set_size(process_set_id_);
    int pos = hvdtpu_process_set_rank(process_set_id_);
    OP_REQUIRES(ctx, group > 0 && pos >= 0,
                tensorflow::errors::FailedPrecondition(
                    "hvd.init() must run before jit-compiling "
                    "reducescatter"));
    std::vector<int64_t> in_dims(shape_or.value().dimensions().begin(),
                                 shape_or.value().dimensions().end());
    OP_REQUIRES(ctx, !in_dims.empty(),
                tensorflow::errors::InvalidArgument(
                    "reducescatter needs a rank>=1 tensor"));
    // First dim split as evenly as possible, remainder to lower member
    // positions — the host-ring convention (csrc/operations.cc).
    int64_t q = in_dims[0] / group, rem = in_dims[0] % group;
    std::vector<int64_t> out_dims = in_dims;
    out_dims[0] = q + (pos < rem ? 1 : 0);
    int dtype = ToHvdDtype(ctx->input_type(0));
    OP_REQUIRES(ctx, dtype >= 0,
                tensorflow::errors::InvalidArgument("unsupported dtype"));
    meta::CallMeta m = ManagedMeta(3, dtype, process_set_id_, name_,
                                   in_dims, out_dims);
    m.reduce_op_or_root = reduce_op_;
    m.prescale = prescale_;
    m.postscale = postscale_;
    xla::Shape out_shape = xla::ShapeUtil::MakeShape(
        shape_or.value().element_type(), out_dims);
    auto res = xla::CustomCall(
        b, "hvdtpu_tf_xla_collective", {MetaConstant(b, m), ctx->Input(0)},
        out_shape, "", /*has_side_effect=*/true);
    ctx->SetOutput(0, res);
  }

 private:
  std::string name_;
  int reduce_op_, process_set_id_;
  float prescale_, postscale_;
};
REGISTER_XLA_OP(
    Name("HvdTpuReducescatter").Device(tensorflow::DEVICE_CPU_XLA_JIT),
    ReducescatterXlaKernel);

class AlltoallXlaKernel : public tensorflow::XlaOpKernel {
 public:
  explicit AlltoallXlaKernel(OpKernelConstruction* c)
      : tensorflow::XlaOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &name_));
    OP_REQUIRES_OK(c, c->GetAttr("process_set_id", &process_set_id_));
  }

  void Compile(tensorflow::XlaOpKernelContext* ctx) override {
    HVDTPU_REQUIRE_XLA_OPS(ctx);
    xla::XlaBuilder* b = ctx->builder();
    auto shape_or = b->GetShape(ctx->Input(0));
    OP_REQUIRES_OK(ctx, shape_or.status());
    OP_REQUIRES(ctx, ctx->InputShape(1).num_elements() == 0,
                tensorflow::errors::InvalidArgument(
                    "in-jit alltoall supports equal splits only (pass "
                    "splits=None)"));
    int group = hvdtpu_process_set_size(process_set_id_);
    OP_REQUIRES(ctx, group > 0,
                tensorflow::errors::FailedPrecondition(
                    "hvd.init() must run before jit-compiling alltoall"));
    std::vector<int64_t> in_dims(shape_or.value().dimensions().begin(),
                                 shape_or.value().dimensions().end());
    OP_REQUIRES(ctx, !in_dims.empty() && in_dims[0] % group == 0,
                tensorflow::errors::InvalidArgument(
                    "alltoall first dim must be divisible by the group "
                    "size"));
    int dtype = ToHvdDtype(ctx->input_type(0));
    OP_REQUIRES(ctx, dtype >= 0,
                tensorflow::errors::InvalidArgument("unsupported dtype"));
    // Equal splits: the output shape equals the input's.
    meta::CallMeta m = ManagedMeta(4, dtype, process_set_id_, name_,
                                   in_dims, in_dims);
    auto res = xla::CustomCall(
        b, "hvdtpu_tf_xla_collective", {MetaConstant(b, m), ctx->Input(0)},
        shape_or.value(), "", /*has_side_effect=*/true);
    ctx->SetOutput(0, res);
  }

 private:
  std::string name_;
  int process_set_id_;
};
REGISTER_XLA_OP(Name("HvdTpuAlltoall").Device(tensorflow::DEVICE_CPU_XLA_JIT),
                AlltoallXlaKernel);

}  // namespace hvdtpu_tf
