// Host data plane: bandwidth-optimal ring collectives over TCP.
// Reference analog: horovod/common/ops/gloo_operations.cc +
// mpi_operations.cc (the CPU backends) — and the ring-allreduce algorithm of
// the Horovod paper (arXiv:1802.05799 §3: reduce-scatter + allgather,
// 2(N-1)/N bandwidth factor). Rebuilt on the wire.h duplex primitive; on TPU
// the analogous data plane is XLA collectives over ICI (horovod_tpu/parallel).

#ifndef HVDTPU_RING_OPS_H
#define HVDTPU_RING_OPS_H

#include <vector>

#include "common.h"

namespace hvdtpu {

// Elementwise dst = dst OP src for `count` elements (host buffers).
// fp16/bf16 accumulate in fp32 (reference: half.h CPU fp16 math for MPI sum).
void ReduceInto(void* dst, const void* src, int64_t count, DataType dt,
                ReduceOp op);

// Multiply `count` elements in-place by `factor` (pre/postscale).
void ScaleBuffer(void* buf, int64_t count, DataType dt, double factor);

class DataPlane {
 public:
  // peer_fds[r] = connected socket to rank r (-1 at index `rank`).
  DataPlane(int rank, int size, std::vector<int> peer_fds);
  ~DataPlane();

  // Non-owning view over a subgroup (global ranks, must contain this rank):
  // collectives on the view run over only those ranks, with this rank's
  // position in `members` as its group rank. The view shares the parent's
  // sockets; destroying it closes nothing.
  // Reference analog: per-process-set communicators (process_set.h).
  DataPlane Subset(const std::vector<int32_t>& members) const;

  // In-place ring allreduce over a contiguous buffer. op == ADASUM routes
  // to AdasumAllreduce.
  Status Allreduce(void* buf, int64_t count, DataType dt, ReduceOp op);

  // Hierarchical allreduce (HOROVOD_HIERARCHICAL_ALLREDUCE): local
  // reduce-scatter -> cross-node allreduce of each segment among
  // same-local-rank peers -> local allgather, cutting cross-node traffic
  // by the local group size. Requires the host-major homogeneous layout
  // (rank = cross_rank * local_size + local_rank) on the GLOBAL plane.
  // Reference analog: NCCLHierarchicalAllreduce (ops/nccl_operations.cc).
  Status HierarchicalAllreduce(void* buf, int64_t count, DataType dt,
                               ReduceOp op, int local_size);

  // Adaptive-summation allreduce (recursive doubling, floats only).
  // Reference analog: ops/adasum/ (see csrc/adasum.cc).
  Status AdasumAllreduce(void* buf, int64_t count, DataType dt);

  // Variable allgather: rank r contributes bytes_per_rank[r] bytes; output is
  // the rank-order concatenation on every rank.
  Status Allgatherv(const void* input, void* output,
                    const std::vector<int64_t>& bytes_per_rank);

  // Pipelined ring broadcast, in-place.
  Status Broadcast(void* buf, int64_t bytes, int root);

  // Pairwise-exchange all-to-all with per-rank byte splits.
  Status Alltoallv(const void* input, const std::vector<int64_t>& send_bytes,
                   void* output, const std::vector<int64_t>& recv_bytes);

  // Ring reduce-scatter: every rank holds the full `input`; rank r's output
  // is its reduced segment of elems_per_rank[r] elements. `destructive`
  // permits clobbering `input` in place (skips the private work copy).
  Status ReduceScatterv(const void* input, void* output,
                        const std::vector<int64_t>& elems_per_rank,
                        DataType dt, ReduceOp op, bool destructive = false);

  Status Barrier();

  int rank() const { return rank_; }
  int size() const { return size_; }

  // Group index of a global rank (identity on the global plane), or -1 if
  // the rank is not in this (sub)group. Callers must translate global rank
  // arguments (e.g. broadcast root) before indexing into a subset view.
  int GroupIndexOf(int global_rank) const {
    for (size_t i = 0; i < global_ranks_.size(); i++) {
      if (global_ranks_[i] == global_rank) return (int)i;
    }
    return -1;
  }

 private:
  DataPlane(int rank, int size, std::vector<int> peer_fds, bool owns_fds);

  int rank_;
  int size_;
  std::vector<int> peer_fds_;
  std::vector<int32_t> global_ranks_;  // group index -> global rank
  bool owns_fds_ = true;
  std::vector<uint8_t> scratch_;

  int right_fd() const { return peer_fds_[(rank_ + 1) % size_]; }
  int left_fd() const { return peer_fds_[(rank_ - 1 + size_) % size_]; }
};

}  // namespace hvdtpu

#endif  // HVDTPU_RING_OPS_H
