// Host data plane: bandwidth-optimal ring collectives over TCP.
// Reference analog: horovod/common/ops/gloo_operations.cc +
// mpi_operations.cc (the CPU backends) — and the ring-allreduce algorithm of
// the Horovod paper (arXiv:1802.05799 §3: reduce-scatter + allgather,
// 2(N-1)/N bandwidth factor). Rebuilt on the wire.h duplex primitive; on TPU
// the analogous data plane is XLA collectives over ICI (horovod_tpu/parallel).
//
// The hot path is pipelined and chunked (HOROVOD_RING_CHUNK_BYTES): each
// ring segment moves in chunks through a double-buffered scratch, and a
// per-plane worker thread reduces chunk i-1 while the caller thread
// transfers chunk i, so the wire never idles during reduction (the
// chunk-pipelining result of arXiv:1810.11112). Opt-in wire compression
// (HOROVOD_WIRE_COMPRESSION) ships fp32 allreduce payloads as bf16 per
// hop with full-precision f32 accumulation (the EQuARX recipe,
// arXiv:2506.17615), halving wire bytes for the dominant gradient dtype.

#ifndef HVDTPU_RING_OPS_H
#define HVDTPU_RING_OPS_H

#include <memory>
#include <vector>

#include "common.h"

namespace hvdtpu {

// Elementwise dst = dst OP src for `count` elements (host buffers).
// fp16/bf16 accumulate in fp32 (reference: half.h CPU fp16 math for MPI sum).
void ReduceInto(void* dst, const void* src, int64_t count, DataType dt,
                ReduceOp op);

// Multiply `count` elements in-place by `factor` (pre/postscale).
void ScaleBuffer(void* buf, int64_t count, DataType dt, double factor);

// ---- ring transport knobs (process-global, relaxed atomics) ----------
// Chunk granularity of every chunked host-ring path (allreduce,
// reduce-scatter, broadcast, allgather, alltoall). <= 0 selects the
// legacy bulk-synchronous path (one whole-segment transfer per ring
// step, no overlap). Must be uniform across ranks: the chunk split is
// the message framing on the external (message) transport, and the
// autotuner keeps it in sync by riding new values on the ResponseList.
constexpr int64_t kDefaultRingChunkBytes = 256 * 1024;
int64_t RingChunkBytes();
void SetRingChunkBytes(int64_t bytes);

// fp32 allreduce payloads cross the wire as bf16 (decode + accumulate
// in f32 on receive); see docs/wire.md for the numerics contract.
bool WireCompression();
void SetWireCompression(bool on);

// Wire codec selector behind the compression knob: 0 = none, 1 = bf16
// (2 bytes/elem, the default when HOROVOD_WIRE_COMPRESSION=1), 2 =
// int8 blockwise-scaled (1 byte/elem + one f32 scale per
// kInt8CodecBlock elems — the EQuARX recipe; f32 accumulate, wire
// ratio ~0.26). HOROVOD_WIRE_COMPRESSION accepts 0/1/2 or the
// spellings "bf16"/"int8". WireCompression() == (WireCodec() != 0),
// kept for the existing bool surfaces; SetWireCompression(true)
// selects bf16.
constexpr int64_t kInt8CodecBlock = 256;
int WireCodec();
void SetWireCodec(int mode);

// Explicit-SIMD toggle for the reduce/codec hot loops (HOROVOD_SIMD,
// default on; simd.h has the kernels and the bit-identity contract).
bool SimdEnabled();
void SetSimdEnabled(bool on);

// ---- bf16/int8 wire codec primitives (the codec seam) ----------------
// Exposed for the SIMD-vs-scalar bit-identity selftest and the int8
// codec's span decoders; the compressed ring engines are the only
// production callers. Encode/decode dispatch to the simd.h kernels
// when SimdEnabled() (bit-identical by contract, pinned by
// hvdtpu_simd_selftest).
void EncodeBF16(uint16_t* dst, const float* src, int64_t n);
void DecodeAccumBF16(float* dst, const uint16_t* src, int64_t n);
void DecodeScaleBF16(float* dst, const uint16_t* src, int64_t n,
                     double post);
// int8 blockwise codec: the wire image is a sequence of
// [f32 scale | kInt8CodecBlock int8 quants] records (the last record
// holds the segment tail). Int8WireLen gives the image size for n
// elems; encode/decode work on whole records, so chunk boundaries cut
// at record multiples are self-contained (the striping contract).
int64_t Int8WireLen(int64_t n);
void EncodeInt8(uint8_t* dst, const float* src, int64_t n);
// Decode the record span starting at wire offset `woff` (a record
// boundary) covering `wlen` wire bytes of a segment of `seg_elems`
// total elems, accumulating (dst[i] += scale * q) or assigning with
// the folded postscale. `dst` is the SEGMENT element base.
void DecodeAccumInt8Span(float* dst, const uint8_t* wire, int64_t woff,
                         int64_t wlen, int64_t seg_elems);
void DecodeScaleInt8Span(float* dst, const uint8_t* wire, int64_t woff,
                         int64_t wlen, int64_t seg_elems, double post);

// ---- ring segment-ownership rotation (ONE place, by design) ----------
// Every ring reduce phase here walks the same rotation: at step s a rank
// sends segment (rank - s + rot) mod N and receives segment
// (rank - s + rot - 1) mod N, reducing into it. After the N-1 steps the
// segment holding EVERY rank's contribution at rank r is therefore
// (r + 1 + rot) mod N:
//   - Allreduce / CompressedRingAllreduce run rot = 0: rank r finishes
//     owning segment (r+1)%N — exactly the first segment its allgather
//     phase sends, and the ONLY segment the compressed finalize may
//     bf16-round locally (the r10 off-by-one trap);
//   - ReduceScatterv / CompressedRingReduceScatter run rot = -1: rank r
//     finishes owning segment r, its API output.
// Do not re-derive these indices inline — use the helpers (pinned by
// tests/single/test_zero.py via the hvdtpu_ring_* C ABI and replayed
// against numpy ring order in tests/parallel/test_ring_wire.py).
inline int RingSendSegment(int rank, int step, int size, int rot = 0) {
  return ((rank - step + rot) % size + 2 * size) % size;
}
inline int RingRecvSegment(int rank, int step, int size, int rot = 0) {
  return RingSendSegment(rank, step + 1, size, rot);
}
inline int RingOwnedSegment(int rank, int size, int rot = 0) {
  return ((rank + 1 + rot) % size + size) % size;
}

// Overlap workers: run ReduceInto / bf16-decode tasks for one data
// plane while the plane's transfer threads drive the next chunk's
// DuplexTransfer. Workers never touch the transport. One worker PER
// STRIPE CHANNEL (chunk i % K reduces on worker i % K), so reduction
// parallelism scales with the stripe width; the pool is shared between
// a root DataPlane and its Subset views, and worker threads start
// lazily on first use. Channel I/O itself runs on transient per-call
// threads (channel 0 on the caller thread), each owning its channel's
// fds exclusively for the duration — the wire.h single-caller contract
// holds per fd.
class WorkerPool;

class DataPlane {
 public:
  // peer_fds[r] = connected socket to rank r (-1 at index `rank`).
  // This is stripe channel 0; AdoptExtraChannelFds installs channels
  // 1..K-1.
  DataPlane(int rank, int size, std::vector<int> peer_fds);
  ~DataPlane();

  // Install the extra stripe channels established at rendezvous:
  // chan_fds[c][r] = the channel-(c+1) socket to rank r. Owned (and
  // registered fd->rank/channel) exactly like the primary mesh. The
  // plane stripes chunked transfers over min(WireChannels(),
  // 1 + chan_fds.size()) channels — a plane without extra channels
  // (selftests at K=1, simworld, external transport) is exactly the
  // single-channel engine.
  void AdoptExtraChannelFds(std::vector<std::vector<int>> chan_fds);

  // Established stripe channels (sockets per neighbor pair).
  int channels() const { return 1 + (int)extra_fds_.size(); }

  DataPlane(DataPlane&&) = default;
  DataPlane& operator=(DataPlane&&) = default;
  DataPlane(const DataPlane&) = delete;
  DataPlane& operator=(const DataPlane&) = delete;

  // Non-owning view over a subgroup (global ranks, must contain this rank):
  // collectives on the view run over only those ranks, with this rank's
  // position in `members` as its group rank. The view shares the parent's
  // sockets AND overlap worker; destroying it closes nothing.
  // Reference analog: per-process-set communicators (process_set.h).
  DataPlane Subset(const std::vector<int32_t>& members) const;

  // In-place ring allreduce over a contiguous buffer. op == ADASUM routes
  // to AdasumAllreduce. `postscale` (e.g. 1/size for AVERAGE) is applied
  // exactly once before returning; the compressed engine folds it into
  // the final bf16->f32 decode pass so averaging costs no extra
  // traversal (bit-identical to scaling afterwards — both round once in
  // f32 — it only saves the memory pass).
  Status Allreduce(void* buf, int64_t count, DataType dt, ReduceOp op,
                   double postscale = 1.0);

  // Hierarchical cross-plane allreduce (HOROVOD_CROSS_PLANE=hier, or
  // the legacy HOROVOD_HIERARCHICAL_ALLREDUCE spelling): intra-slice
  // reduce-scatter -> inter-slice allreduce of each 1/local_size shard
  // among same-local-rank peers -> intra-slice allgather, cutting
  // cross-slice traffic by the local group size. Requires the
  // host-major homogeneous layout (rank = cross_rank * local_size +
  // local_rank) on the GLOBAL plane. The inter-slice subset is tagged
  // as the CROSS wire plane (metrics book its bytes separately), and
  // `compress_cross` puts the bf16 wire codec on that hop alone — the
  // EQuARX cheap-wire recipe applied to the expensive fabric only
  // (docs/redistribute.md).
  // Reference analog: NCCLHierarchicalAllreduce (ops/nccl_operations.cc).
  Status HierarchicalAllreduce(void* buf, int64_t count, DataType dt,
                               ReduceOp op, int local_size,
                               double postscale = 1.0,
                               bool compress_cross = false);

  // Adaptive-summation allreduce (recursive doubling, floats only).
  // Reference analog: ops/adasum/ (see csrc/adasum.cc).
  Status AdasumAllreduce(void* buf, int64_t count, DataType dt);

  // Variable allgather: rank r contributes bytes_per_rank[r] bytes; output is
  // the rank-order concatenation on every rank.
  Status Allgatherv(const void* input, void* output,
                    const std::vector<int64_t>& bytes_per_rank);

  // Pipelined ring broadcast, in-place.
  Status Broadcast(void* buf, int64_t bytes, int root);

  // Pairwise-exchange all-to-all with per-rank byte splits.
  Status Alltoallv(const void* input, const std::vector<int64_t>& send_bytes,
                   void* output, const std::vector<int64_t>& recv_bytes);

  // Ring reduce-scatter: every rank holds the full `input`; rank r's output
  // is its reduced segment of elems_per_rank[r] elements. `destructive`
  // permits clobbering `input` in place (skips the private work copy).
  Status ReduceScatterv(const void* input, void* output,
                        const std::vector<int64_t>& elems_per_rank,
                        DataType dt, ReduceOp op, bool destructive = false);

  Status Barrier();

  // Fault sweep (elastic): poll every TCP peer fd for EOF/RST without
  // consuming ring bytes (MSG_PEEK) and return the GLOBAL ranks whose
  // processes are provably gone — the kernel closes a SIGKILLed peer's
  // sockets, so every survivor sees the same dead set and can agree on
  // the N-1 membership without a coordinator round (docs/elastic.md).
  // Silent failures (partition, SIGSTOP) do not show here; those are
  // only caught by the wire deadline with neighbor-level attribution.
  // External (message-transport) fds cannot be probed and are skipped.
  std::vector<int32_t> ProbeDeadPeers() const;

  int rank() const { return rank_; }
  int size() const { return size_; }

  // Wire-plane tag for metrics accounting: 0 = intra/flat (the default
  // ring), 1 = cross (the inter-slice hop of the hierarchical
  // decomposition). Subset views inherit the parent's tag;
  // HierarchicalAllreduce overrides it on its inter-slice subset so
  // telemetry can reconcile per-plane logical-vs-wire bytes exactly.
  void set_wire_plane(int plane) { wire_plane_ = plane; }
  int wire_plane() const { return wire_plane_; }

  // Per-plane compression override: when set, fp32 SUM/AVERAGE
  // collectives on THIS plane ride the bf16 wire codec even with the
  // process-global knob off (used for the cross-plane hop; per-plane
  // state, so concurrent planes — the selftest mesh — cannot race a
  // global toggle).
  void set_force_compression(bool on) { force_compression_ = on; }

  // Group index of a global rank (identity on the global plane), or -1 if
  // the rank is not in this (sub)group. Callers must translate global rank
  // arguments (e.g. broadcast root) before indexing into a subset view.
  int GroupIndexOf(int global_rank) const {
    for (size_t i = 0; i < global_ranks_.size(); i++) {
      if (global_ranks_[i] == global_rank) return (int)i;
    }
    return -1;
  }

 private:
  DataPlane(int rank, int size, std::vector<int> peer_fds, bool owns_fds);

  struct WireTally;  // per-collective wire/logical byte accumulator

  // Active stripe width for a chunked transfer on this plane:
  // min(WireChannels(), channels()), forced to 1 on the external
  // transport and on the bulk (chunk <= 0) path. Rank-uniform because
  // every input is (knob rides the ResponseList; channels() comes from
  // the shared env contract).
  int ActiveStripe(int64_t chunk_bytes) const;

  // Stripe plan of one hop. On a PAIRWISE hop (send peer == recv peer:
  // the size-2 ring, alltoall partners) every socket would carry both
  // directions at once, and a duplexed loopback/NIC stream runs far
  // below two unidirectional ones — so the channel set is split by
  // direction instead: logical lane i sends on physical channel
  // 2i + tx_base and receives on 2i + rx_base, with the parity chosen
  // by group-rank order (both ends derive opposite parities from the
  // same comparison, so the schedules agree). Width halves (K/2);
  // each socket runs one-way. Non-pairwise hops use lane i == channel
  // i at full width.
  struct HopStripe {
    int width = 1;
    bool paired = false;
    int tx_base = 0, rx_base = 0;
    int tx_chan(int i) const { return paired ? 2 * i + tx_base : i; }
    int rx_chan(int i) const { return paired ? 2 * i + rx_base : i; }
  };
  HopStripe StripeFor(int send_peer, int recv_peer,
                      int64_t chunk_bytes) const;

  // One reduce-scatter ring step: send `send_bytes` from `send_buf` to
  // peer `send_peer` (group index) while receiving `recv_count`
  // elements from `recv_peer` and reducing them into `reduce_dst`,
  // chunk-striped over the active channels with each chunk's reduce
  // overlapped on its channel's worker.
  Status PipelinedReduceChunks(int send_peer, const uint8_t* send_buf,
                               int64_t send_bytes, int recv_peer,
                               uint8_t* reduce_dst, int64_t recv_count,
                               DataType dt, ReduceOp op, int64_t chunk_bytes,
                               WireTally* tally);

  // Plain chunked duplex (no reduction): allgather phases, alltoall.
  // Peers are group indices (fds resolved per channel).
  Status ChunkedDuplex(int send_peer, const uint8_t* send_buf,
                       int64_t send_bytes, int recv_peer,
                       uint8_t* recv_buf, int64_t recv_bytes,
                       int64_t chunk_bytes, WireTally* tally);

  // fp32 allreduce with a narrow wire codec (1 = bf16, 2 = int8
  // blockwise-scaled): reduce-scatter accumulates in f32 from per-hop
  // narrow partials; allgather ships the finalized (codec-rounded)
  // segments compressed. `postscale` folds into the final decode.
  Status CompressedRingAllreduce(float* base,
                                 const std::vector<int64_t>& seg_count,
                                 const std::vector<int64_t>& seg_off,
                                 double postscale, int64_t chunk_bytes,
                                 int codec, WireTally* tally);

  // fp32 reduce-scatter with bf16 wire encoding: the N-1 reduce steps of
  // CompressedRingAllreduce, run at the reduce-scatter rotation (rot=-1,
  // so rank r finishes owning segment r) and WITHOUT the allgather
  // phase — the ZeRO gradient-shard path (docs/zero.md). Accumulation
  // is full-precision f32 from per-hop bf16 partials; `base` is the
  // caller's working copy and ends with this rank's segment finalized.
  Status CompressedRingReduceScatter(float* base,
                                     const std::vector<int64_t>& seg_count,
                                     const std::vector<int64_t>& seg_off,
                                     int64_t chunk_bytes, int codec,
                                     WireTally* tally);

  // Shared N-1-step compressed reduce phase at rotation `rot` (see
  // RingSendSegment): narrow codec per hop, f32 accumulate, decode
  // overlapped on the per-channel workers. Both compressed engines
  // slice through here.
  Status CompressedReducePhase(float* base,
                               const std::vector<int64_t>& seg_count,
                               const std::vector<int64_t>& seg_off,
                               int64_t chunk_elems, int rot, int codec,
                               WireTally* tally);

  int rank_;
  int size_;
  std::vector<int> peer_fds_;          // stripe channel 0
  // Channels 1..K-1: extra_fds_[c-1][r] = channel-c socket to group
  // member r. Subset views remap every channel like peer_fds_.
  std::vector<std::vector<int>> extra_fds_;
  std::vector<int32_t> global_ranks_;  // group index -> global rank
  bool owns_fds_ = true;
  int wire_plane_ = 0;              // 0 intra/flat, 1 cross-slice
  bool force_compression_ = false;  // per-plane bf16-on-wire override
  std::vector<uint8_t> scratch_;        // bulk-path recv segment
  std::vector<uint8_t> chunk_scratch_;  // 2 chunks (double-buffered recv)
  std::vector<uint8_t> comp_send_scratch_;  // encoded send segment
  std::vector<uint8_t> comp_plane_;  // encoded allgather plane
  std::shared_ptr<WorkerPool> workers_;

  int peer_fd(int channel, int peer) const {
    return channel == 0 ? peer_fds_[peer] : extra_fds_[channel - 1][peer];
  }
  int right_peer() const { return (rank_ + 1) % size_; }
  int left_peer() const { return (rank_ - 1 + size_) % size_; }
  int right_fd(int channel = 0) const {
    return peer_fd(channel, right_peer());
  }
  int left_fd(int channel = 0) const {
    return peer_fd(channel, left_peer());
  }
};

}  // namespace hvdtpu

#endif  // HVDTPU_RING_OPS_H
