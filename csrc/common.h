// Core shared types for the hvdtpu native runtime.
// Reference analog: horovod/common/common.h (Status, DataType,
// TensorTableEntry, framework enums). Rebuilt from scratch for a
// framework-agnostic ctypes ABI: tensors are raw host pointers; the TPU
// data plane lives in XLA programs above this layer.

#ifndef HVDTPU_COMMON_H
#define HVDTPU_COMMON_H

#include <cstdint>
#include <cstring>
#include <functional>
#include <memory>
#include <string>
#include <vector>

namespace hvdtpu {

enum class DataType : int32_t {
  HVDTPU_UINT8 = 0,
  HVDTPU_INT8 = 1,
  HVDTPU_INT32 = 2,
  HVDTPU_INT64 = 3,
  HVDTPU_FLOAT16 = 4,
  HVDTPU_BFLOAT16 = 5,
  HVDTPU_FLOAT32 = 6,
  HVDTPU_FLOAT64 = 7,
  HVDTPU_BOOL = 8,
  HVDTPU_UINT16 = 9,
};

// Cross-plane topology descriptor (HOROVOD_CROSS_PLANE,
// docs/redistribute.md) in enum order: 0 auto, 1 ici, 2 ring, 3 hier.
// THE one name table — operations.cc parses against it, metrics.cc
// labels with it, and Python's HorovodBasics.CROSS_PLANE_MODES
// (common/basics.py) mirrors it by documented contract.
constexpr int kCrossPlaneModeCount = 4;
inline const char* const* CrossPlaneModeNames() {
  static const char* const names[kCrossPlaneModeCount] = {
      "auto", "ici", "ring", "hier"};
  return names;
}

inline int64_t DataTypeSize(DataType dt) {
  switch (dt) {
    case DataType::HVDTPU_UINT8:
    case DataType::HVDTPU_INT8:
    case DataType::HVDTPU_BOOL:
      return 1;
    case DataType::HVDTPU_FLOAT16:
    case DataType::HVDTPU_BFLOAT16:
    case DataType::HVDTPU_UINT16:
      return 2;
    case DataType::HVDTPU_INT32:
    case DataType::HVDTPU_FLOAT32:
      return 4;
    case DataType::HVDTPU_INT64:
    case DataType::HVDTPU_FLOAT64:
      return 8;
  }
  return 0;
}

inline const char* DataTypeName(DataType dt) {
  switch (dt) {
    case DataType::HVDTPU_UINT8: return "uint8";
    case DataType::HVDTPU_INT8: return "int8";
    case DataType::HVDTPU_INT32: return "int32";
    case DataType::HVDTPU_INT64: return "int64";
    case DataType::HVDTPU_FLOAT16: return "float16";
    case DataType::HVDTPU_BFLOAT16: return "bfloat16";
    case DataType::HVDTPU_FLOAT32: return "float32";
    case DataType::HVDTPU_FLOAT64: return "float64";
    case DataType::HVDTPU_BOOL: return "bool";
    case DataType::HVDTPU_UINT16: return "uint16";
  }
  return "unknown";
}

// Reduction op for allreduce/reducescatter.
// Reference analog: horovod ReduceOp (Average/Sum/Adasum/Min/Max/Product).
enum class ReduceOp : int32_t {
  AVERAGE = 0,
  SUM = 1,
  MIN = 2,
  MAX = 3,
  PRODUCT = 4,
  ADASUM = 5,
};

enum class StatusType : int32_t {
  OK = 0,
  UNKNOWN_ERROR = 1,
  PRECONDITION_ERROR = 2,
  ABORTED = 3,
  INVALID_ARGUMENT = 4,
  IN_PROGRESS = 5,
  // A specific peer is gone or unresponsive (EOF/RST on its socket, or
  // no wire progress for HOROVOD_WIRE_TIMEOUT_MS). The elastic-
  // recoverable condition: the background loop stops, records the fault
  // at the current membership epoch, and survivors re-form the ring via
  // hvdtpu_reinit (docs/elastic.md).
  PEER_FAILURE = 6,
  // A CRC-protected wire chunk failed its integrity check past the
  // retry budget (HOROVOD_WIRE_CRC, docs/wire.md): the link to a LIVE
  // peer is corrupting data. Typed so silent corruption can never be
  // reduced into a result; recovery follows the same elastic path as a
  // peer failure (the stream is poisoned at this epoch).
  WIRE_CORRUPTION = 7,
};

class Status {
 public:
  Status() = default;
  static Status OK() { return Status(); }
  static Status Error(const std::string& msg) {
    return Status(StatusType::UNKNOWN_ERROR, msg);
  }
  static Status PreconditionError(const std::string& msg) {
    return Status(StatusType::PRECONDITION_ERROR, msg);
  }
  static Status InvalidArgument(const std::string& msg) {
    return Status(StatusType::INVALID_ARGUMENT, msg);
  }
  static Status Aborted(const std::string& msg) {
    return Status(StatusType::ABORTED, msg);
  }
  // `rank` is the GLOBAL rank this failure is attributed to (-1 when the
  // transport cannot name one). `certain` separates PROOF from
  // suspicion: EOF/RST/transport errors are proof the peer's process is
  // gone (the kernel closed its sockets) — a pure stall only proves the
  // timed-out NEIGHBOR stopped sending, and that neighbor may itself be
  // blocked on the real casualty. The fault resolution in operations.cc
  // combines certain attributions with a socket probe sweep so every
  // survivor converges on the same dead set; suspected ranks are only a
  // fallback when no proof exists anywhere (docs/elastic.md).
  static Status PeerFailure(int rank, const std::string& msg,
                            bool certain = false) {
    Status s(StatusType::PEER_FAILURE, msg);
    s.fault_rank_ = rank;
    s.fault_certain_ = certain;
    return s;
  }
  // `rank` = the sending peer whose frames failed verification, `chunk`
  // = the chunk index within the failing transfer. Not "certain" in the
  // membership sense: the peer process is alive — only the link is bad —
  // so driver-less survivor agreement must not treat it as a dead rank.
  static Status WireCorruption(int rank, int64_t chunk,
                               const std::string& msg) {
    Status s(StatusType::WIRE_CORRUPTION, msg);
    s.fault_rank_ = rank;
    s.fault_chunk_ = chunk;
    return s;
  }
  bool ok() const { return type_ == StatusType::OK; }
  bool peer_failure() const { return type_ == StatusType::PEER_FAILURE; }
  bool wire_corruption() const {
    return type_ == StatusType::WIRE_CORRUPTION;
  }
  StatusType type() const { return type_; }
  int fault_rank() const { return fault_rank_; }
  int64_t fault_chunk() const { return fault_chunk_; }
  bool fault_certain() const { return fault_certain_; }
  const std::string& reason() const { return reason_; }

 private:
  Status(StatusType type, std::string reason)
      : type_(type), reason_(std::move(reason)) {}
  StatusType type_ = StatusType::OK;
  int fault_rank_ = -1;
  int64_t fault_chunk_ = -1;
  bool fault_certain_ = false;
  std::string reason_;
};

// A pending collective on this rank.
// Reference analog: horovod/common/common.h TensorTableEntry — but tensors
// are raw host buffers (the Python binding pins them until completion).
struct TensorTableEntry {
  std::string name;
  int32_t handle = -1;
  // 1 = accelerator-resident tensor: the registered device data plane
  // (XLA executable over ICI) executes it; input/output stay null and the
  // payload never touches these host pointers.
  int32_t device = 0;
  const void* input = nullptr;   // caller-owned input buffer
  void* output = nullptr;        // caller-owned output buffer (allreduce)
  std::vector<int64_t> shape;
  DataType dtype = DataType::HVDTPU_FLOAT32;
  ReduceOp reduce_op = ReduceOp::SUM;
  int32_t root_rank = 0;         // broadcast
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
  int32_t process_set_id = 0;
  // alltoall: number of elements sent to each rank (first-dim splits).
  std::vector<int64_t> splits;
  // Output managed by the core for ops whose size is known only after
  // negotiation (allgather/alltoall). Copied out via the handle API.
  std::vector<uint8_t> managed_output;
  std::vector<int64_t> output_shape;
  // received splits for alltoall
  std::vector<int64_t> recv_splits;
  // Steady-clock enqueue time (us), set by EnqueueEntry; 0 on entries the
  // core synthesizes itself (joined-rank zeros). Feeds the queue-latency
  // histogram in the metrics registry (metrics.h).
  int64_t enqueue_us = 0;

  int64_t NumElements() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
  int64_t SizeBytes() const { return NumElements() * DataTypeSize(dtype); }
};

}  // namespace hvdtpu

#endif  // HVDTPU_COMMON_H
