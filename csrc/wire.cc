#include "wire.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <ifaddrs.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <thread>
#include <unordered_map>

namespace hvdtpu {

namespace {
ExternalSendFn g_ext_send = nullptr;
ExternalRecvFn g_ext_recv = nullptr;

// Wire progress deadline (see wire.h). -1 in the atomic = not yet
// initialized from env; first reader folds HOROVOD_WIRE_TIMEOUT_MS in,
// so the ring selftest and other pre-init paths honor the knob too.
std::atomic<int64_t> g_wire_timeout_ms{-1};

// fd -> global rank, for peer attribution in timeout/EOF statuses.
// Registered by the controller (control fds) and the root data plane;
// small and cold (touched at plane setup and on failure paths only).
std::mutex g_fd_rank_mutex;
std::unordered_map<int, int> g_fd_ranks;

// External-transport failures name the peer directly from the fd
// encoding: a callback error means that peer's mailbox is gone.
Status ExtSend(int fd, const void* buf, size_t len) {
  if (!g_ext_send) return Status::Error("external transport not set");
  int rc = g_ext_send(ExtFdPeer(fd), ExtFdTag(fd), buf, (long long)len);
  if (rc != 0) {
    return Status::PeerFailure(
        ExtFdPeer(fd), "external transport send to rank " +
                           std::to_string(ExtFdPeer(fd)) +
                           " failed rc=" + std::to_string(rc),
        /*certain=*/true);
  }
  return Status::OK();
}

// Exact-length receive: the senders' messages are 1:1 with the
// receivers' expected lengths on both planes (control frames are sent
// as one message; ring chunks pair SendAll/RecvAll of equal size).
Status ExtRecvExact(int fd, void* buf, size_t len) {
  if (!g_ext_recv) return Status::Error("external transport not set");
  long long got = g_ext_recv(ExtFdPeer(fd), ExtFdTag(fd), buf,
                             (long long)len);
  if (got < 0) {
    return Status::PeerFailure(
        ExtFdPeer(fd), "external transport recv from rank " +
                           std::to_string(ExtFdPeer(fd)) + " failed",
        /*certain=*/true);
  }
  if ((size_t)got != len) {
    return Status::Error("external transport message length mismatch: "
                         "expected " + std::to_string(len) + ", got " +
                         std::to_string(got));
  }
  return Status::OK();
}

int64_t ResolveTimeout(int64_t timeout_ms) {
  return timeout_ms == kWireTimeoutGlobal ? WireTimeoutMs() : timeout_ms;
}

Status PeerTimeout(int fd, const char* what, int64_t stalled_ms) {
  int rank = FdRank(fd);
  return Status::PeerFailure(
      rank, std::string(what) + " made no progress for " +
                std::to_string(stalled_ms) + " ms waiting on rank " +
                (rank >= 0 ? std::to_string(rank) : "<unknown>") +
                " (HOROVOD_WIRE_TIMEOUT_MS)");
}

Status PeerClosed(int fd) {
  int rank = FdRank(fd);
  return Status::PeerFailure(
      rank, "peer" + (rank >= 0 ? " rank " + std::to_string(rank)
                                : std::string("")) +
                " closed connection",
      /*certain=*/true);
}

Status PeerIoError(int fd, const char* what) {
  int rank = FdRank(fd);
  return Status::PeerFailure(
      rank, std::string(what) + " to rank " +
                (rank >= 0 ? std::to_string(rank) : "<unknown>") +
                " failed: " + strerror(errno),
      /*certain=*/true);
}

// Wait for `events` on fd for up to timeout_ms (<= 0 = forever).
// Returns 1 ready, 0 timed out, -1 poll error (errno set).
int WaitFd(int fd, short events, int64_t timeout_ms) {
  pollfd p{};
  p.fd = fd;
  p.events = events;
  while (true) {
    int rc = poll(&p, 1, timeout_ms <= 0 ? -1 : (int)timeout_ms);
    if (rc < 0 && errno == EINTR) continue;
    return rc < 0 ? -1 : (rc == 0 ? 0 : 1);
  }
}
}  // namespace

int64_t WireTimeoutMs() {
  int64_t v = g_wire_timeout_ms.load(std::memory_order_relaxed);
  if (v == -1) {
    const char* env = std::getenv("HOROVOD_WIRE_TIMEOUT_MS");
    v = kDefaultWireTimeoutMs;
    if (env != nullptr) {
      char* end = nullptr;
      int64_t parsed = strtoll(env, &end, 10);
      if (end != env) v = parsed;  // non-numeric keeps the default
    }
    if (v == -1) v = 0;  // same normalization as SetWireTimeoutMs
    g_wire_timeout_ms.store(v, std::memory_order_relaxed);
  }
  return v;
}

void SetWireTimeoutMs(int64_t ms) {
  // -1 is the "uninitialized" sentinel; normalize a literal -1 to the
  // equivalent "no deadline" 0.
  g_wire_timeout_ms.store(ms == -1 ? 0 : ms, std::memory_order_relaxed);
}

void RegisterFdRank(int fd, int rank) {
  if (fd < 0) return;  // external fds self-encode their peer
  std::lock_guard<std::mutex> lk(g_fd_rank_mutex);
  g_fd_ranks[fd] = rank;
}

void UnregisterFdRank(int fd) {
  if (fd < 0) return;
  std::lock_guard<std::mutex> lk(g_fd_rank_mutex);
  g_fd_ranks.erase(fd);
}

int FdRank(int fd) {
  if (IsExtFd(fd)) return ExtFdPeer(fd);
  if (fd < 0) return -1;
  std::lock_guard<std::mutex> lk(g_fd_rank_mutex);
  auto it = g_fd_ranks.find(fd);
  return it == g_fd_ranks.end() ? -1 : it->second;
}

void SetExternalTransport(ExternalSendFn send, ExternalRecvFn recv) {
  g_ext_send = send;
  g_ext_recv = recv;
}

bool ExternalTransportActive() { return g_ext_send && g_ext_recv; }

static void SetSockOpts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

int TcpListen(int* port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons((uint16_t)*port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  if (listen(fd, 128) != 0) {
    close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &len);
  *port = ntohs(addr.sin_port);
  return fd;
}

int TcpAccept(int listen_fd) {
  int fd = accept(listen_fd, nullptr, nullptr);
  if (fd >= 0) SetSockOpts(fd);
  return fd;
}

int TcpAcceptTimeout(int listen_fd, int64_t timeout_ms) {
  if (timeout_ms > 0) {
    int w = WaitFd(listen_fd, POLLIN, timeout_ms);
    if (w <= 0) return -1;
  }
  return TcpAccept(listen_fd);
}

int TcpConnect(const std::string& host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  while (true) {
    addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) == 0 && res) {
      int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0) {
        if (connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
          freeaddrinfo(res);
          SetSockOpts(fd);
          return fd;
        }
        close(fd);
      }
      freeaddrinfo(res);
    }
    if (std::chrono::steady_clock::now() > deadline) return -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void TcpClose(int fd) {
  if (fd >= 0) {  // external fds (< 0) have nothing to close
    UnregisterFdRank(fd);
    close(fd);
  }
}

// Deadline-bound exact-length I/O: MSG_DONTWAIT attempts with a poll()
// wait between them, so "no progress for timeout_ms" surfaces as a
// typed PeerFailure naming the fd's registered peer instead of blocking
// the background thread forever on a dead rank.
Status SendAll(int fd, const void* buf, size_t len, int64_t timeout_ms) {
  if (IsExtFd(fd)) return ExtSend(fd, buf, len);
  timeout_ms = ResolveTimeout(timeout_ms);
  const char* p = (const char*)buf;
  while (len > 0) {
    ssize_t n = send(fd, p, len, MSG_NOSIGNAL | MSG_DONTWAIT);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int w = WaitFd(fd, POLLOUT, timeout_ms);
        if (w == 0) return PeerTimeout(fd, "send", timeout_ms);
        if (w < 0) {
          return Status::Error(std::string("poll failed: ") +
                               strerror(errno));
        }
        continue;
      }
      return PeerIoError(fd, "send");
    }
    p += n;
    len -= (size_t)n;
  }
  return Status::OK();
}

Status RecvAll(int fd, void* buf, size_t len, int64_t timeout_ms) {
  if (IsExtFd(fd)) return ExtRecvExact(fd, buf, len);
  timeout_ms = ResolveTimeout(timeout_ms);
  char* p = (char*)buf;
  while (len > 0) {
    ssize_t n = recv(fd, p, len, MSG_DONTWAIT);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        int w = WaitFd(fd, POLLIN, timeout_ms);
        if (w == 0) return PeerTimeout(fd, "recv", timeout_ms);
        if (w < 0) {
          return Status::Error(std::string("poll failed: ") +
                               strerror(errno));
        }
        continue;
      }
      return PeerIoError(fd, "recv");
    }
    if (n == 0) return PeerClosed(fd);
    p += n;
    len -= (size_t)n;
  }
  return Status::OK();
}

Status SendFrame(int fd, const std::string& payload, int64_t timeout_ms) {
  if (IsExtFd(fd)) {
    // One message per frame: the transport preserves boundaries, so no
    // length prefix is needed.
    return ExtSend(fd, payload.data(), payload.size());
  }
  uint64_t len = payload.size();
  Status s = SendAll(fd, &len, sizeof(len), timeout_ms);
  if (!s.ok()) return s;
  return SendAll(fd, payload.data(), payload.size(), timeout_ms);
}

Status RecvFrame(int fd, std::string* payload, int64_t timeout_ms) {
  if (IsExtFd(fd)) {
    if (!g_ext_recv) return Status::Error("external transport not set");
    // Two-phase: probe the next message's length (cap 0 holds it on
    // the Python side), then copy it out.
    long long len = g_ext_recv(ExtFdPeer(fd), ExtFdTag(fd), nullptr, 0);
    if (len < 0) {
      return Status::PeerFailure(
          ExtFdPeer(fd), "external transport recv from rank " +
                             std::to_string(ExtFdPeer(fd)) + " failed",
          /*certain=*/true);
    }
    payload->resize((size_t)len);
    if (len == 0) return Status::OK();
    return ExtRecvExact(fd, payload->data(), (size_t)len);
  }
  uint64_t len = 0;
  Status s = RecvAll(fd, &len, sizeof(len), timeout_ms);
  if (!s.ok()) return s;
  payload->resize(len);
  if (len == 0) return Status::OK();
  return RecvAll(fd, payload->data(), len, timeout_ms);
}

namespace {
// Make fds non-blocking for the duration of a duplex transfer; restore after.
// Without this, a blocking send() of a large segment can fill the kernel
// buffer and stall every rank in the ring simultaneously (circular deadlock),
// since nobody would be draining its recv side meanwhile.
class ScopedNonblock {
 public:
  ScopedNonblock(int fd1, int fd2) : fd1_(fd1), fd2_(fd2) {
    flags1_ = fcntl(fd1_, F_GETFL, 0);
    fcntl(fd1_, F_SETFL, flags1_ | O_NONBLOCK);
    if (fd2_ != fd1_) {
      flags2_ = fcntl(fd2_, F_GETFL, 0);
      fcntl(fd2_, F_SETFL, flags2_ | O_NONBLOCK);
    }
  }
  ~ScopedNonblock() {
    fcntl(fd1_, F_SETFL, flags1_);
    if (fd2_ != fd1_) fcntl(fd2_, F_SETFL, flags2_);
  }

 private:
  int fd1_, fd2_, flags1_ = 0, flags2_ = 0;
};
}  // namespace

Status DuplexTransfer(int send_fd, const void* send_buf, size_t send_len,
                      int recv_fd, void* recv_buf, size_t recv_len) {
  if (IsExtFd(send_fd) || IsExtFd(recv_fd)) {
    // The external transport's sends are buffered/asynchronous by
    // contract, so send-then-recv cannot deadlock the ring.
    if (send_len > 0) {
      Status s = SendAll(send_fd, send_buf, send_len);
      if (!s.ok()) return s;
    }
    if (recv_len > 0) return RecvAll(recv_fd, recv_buf, recv_len);
    return Status::OK();
  }
  return DuplexTransferChunked(send_fd, send_buf, send_len, recv_fd,
                               recv_buf, recv_len, 0, nullptr);
}

Status DuplexTransferChunked(
    int send_fd, const void* send_buf, size_t send_len, int recv_fd,
    void* recv_buf, size_t recv_len, size_t chunk,
    const std::function<void(size_t off, size_t len)>& on_chunk) {
  if (IsExtFd(send_fd) || IsExtFd(recv_fd)) {
    // Message transports frame per send: chunk boundaries there are the
    // CALLER's business (equal-length paired messages); this fallback
    // keeps the entry safe if one slips through.
    Status s =
        DuplexTransfer(send_fd, send_buf, send_len, recv_fd, recv_buf,
                       recv_len);
    if (s.ok() && on_chunk && recv_len > 0) on_chunk(0, recv_len);
    return s;
  }
  ScopedNonblock nb(send_fd, recv_fd);
  const int64_t timeout_ms = WireTimeoutMs();
  const char* sp = (const char*)send_buf;
  char* rp = (char*)recv_buf;
  size_t sent = 0, recvd = 0, fired = 0;
  while (sent < send_len || recvd < recv_len) {
    pollfd fds[2];
    int n = 0;
    int send_idx = -1, recv_idx = -1;
    if (sent < send_len) {
      fds[n].fd = send_fd;
      fds[n].events = POLLOUT;
      send_idx = n++;
    }
    if (recvd < recv_len) {
      fds[n].fd = recv_fd;
      fds[n].events = POLLIN;
      recv_idx = n++;
    }
    int rc = poll(fds, (nfds_t)n, timeout_ms <= 0 ? -1 : (int)timeout_ms);
    if (rc < 0) {
      if (errno == EINTR) continue;
      return Status::Error(std::string("poll failed: ") + strerror(errno));
    }
    if (rc == 0) {
      // Attribute the stall to the inbound peer when we are waiting on
      // one (data starvation is the usual failure shape); otherwise the
      // outbound peer stopped draining its side.
      return PeerTimeout(recv_idx >= 0 ? recv_fd : send_fd,
                         "duplex transfer", timeout_ms);
    }
    if (send_idx >= 0 && (fds[send_idx].revents & (POLLOUT | POLLERR))) {
      ssize_t k = send(send_fd, sp + sent, send_len - sent, MSG_NOSIGNAL);
      if (k < 0 && errno != EINTR && errno != EAGAIN) {
        return PeerIoError(send_fd, "duplex send");
      }
      if (k > 0) sent += (size_t)k;
    }
    if (recv_idx >= 0 && (fds[recv_idx].revents & (POLLIN | POLLHUP))) {
      ssize_t k = recv(recv_fd, rp + recvd, recv_len - recvd, 0);
      if (k == 0) return PeerClosed(recv_fd);
      if (k < 0 && errno != EINTR && errno != EAGAIN) {
        return PeerIoError(recv_fd, "duplex recv");
      }
      if (k > 0) recvd += (size_t)k;
      if (chunk > 0 && on_chunk) {
        while (recvd - fired >= chunk) {
          on_chunk(fired, chunk);
          fired += chunk;
        }
      }
    }
  }
  if (on_chunk && recvd > fired) on_chunk(fired, recvd - fired);
  return Status::OK();
}

std::string LocalAddress() {
  ifaddrs* ifs = nullptr;
  std::string best = "127.0.0.1";
  if (getifaddrs(&ifs) == 0) {
    for (ifaddrs* it = ifs; it; it = it->ifa_next) {
      if (!it->ifa_addr || it->ifa_addr->sa_family != AF_INET) continue;
      char buf[INET_ADDRSTRLEN];
      auto* sin = (sockaddr_in*)it->ifa_addr;
      inet_ntop(AF_INET, &sin->sin_addr, buf, sizeof(buf));
      std::string a(buf);
      if (a != "127.0.0.1") {
        best = a;
        break;
      }
    }
    freeifaddrs(ifs);
  }
  return best;
}

}  // namespace hvdtpu
