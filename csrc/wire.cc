#include "wire.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <ifaddrs.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "events.h"
#include "metrics.h"

namespace hvdtpu {

namespace {
ExternalSendFn g_ext_send = nullptr;
ExternalRecvFn g_ext_recv = nullptr;

// Wire progress deadline (see wire.h). -1 in the atomic = not yet
// initialized from env; first reader folds HOROVOD_WIRE_TIMEOUT_MS in,
// so the ring selftest and other pre-init paths honor the knob too.
std::atomic<int64_t> g_wire_timeout_ms{-1};

// Transient-fault healing + wire-integrity knobs (wire.h). Same lazy
// env-fold pattern as the deadline; re-read at every (re)init.
std::atomic<int64_t> g_wire_retry_attempts{-2};  // -2 = uninitialized
std::atomic<int64_t> g_wire_retry_backoff_ms{-2};
std::atomic<int> g_wire_crc{-1};  // -1 = uninitialized

// Active stripe width (wire.h). -1 = not yet initialized from
// HOROVOD_WIRE_CHANNELS; the established socket count is resolved
// separately (WireChannelsEnv) so a tuned-down active width can never
// shrink what a re-formation provisions.
std::atomic<int64_t> g_wire_channels{-1};

// Chaos: flip one bit of the next CRC-framed outgoing data chunk
// (ArmWireFlip). Relaxed atomics: armed by the background thread; with
// striping the frames are built by per-channel transfer threads, so
// the optional channel filter is what keeps the skip count
// deterministic (channel-blind counting would race across stripes).
std::atomic<int64_t> g_flip_bit{-1};
std::atomic<bool> g_flip_persistent{false};
std::atomic<int64_t> g_flip_skip{0};
std::atomic<int64_t> g_flip_channel{-1};

int64_t EnvInt64OrDefault(const char* name, int64_t dflt) {
  const char* env = std::getenv(name);
  if (env == nullptr) return dflt;
  char* end = nullptr;
  int64_t parsed = strtoll(env, &end, 10);
  return end != env ? parsed : dflt;
}

// fd -> (global rank, stripe channel), for peer attribution in
// timeout/EOF statuses and channel-targeted chaos. Registered by the
// controller (control fds) and the root data plane; small and cold
// (touched at plane setup and on failure paths only).
std::mutex g_fd_rank_mutex;
struct FdInfo {
  int rank = -1;
  int channel = 0;
};
std::unordered_map<int, FdInfo> g_fd_ranks;

// External-transport failures name the peer directly from the fd
// encoding: a callback error means that peer's mailbox is gone.
Status ExtSend(int fd, const void* buf, size_t len) {
  if (!g_ext_send) return Status::Error("external transport not set");
  // External transports never stripe (the data plane forces K=1):
  // their calls book channel 0, same as every unstriped TCP path.
  GlobalMetrics().AccountWireSyscall(EventWirePlane(), 0, /*tx=*/true);
  int rc = g_ext_send(ExtFdPeer(fd), ExtFdTag(fd), buf, (long long)len);
  if (rc != 0) {
    return Status::PeerFailure(
        ExtFdPeer(fd), "external transport send to rank " +
                           std::to_string(ExtFdPeer(fd)) +
                           " failed rc=" + std::to_string(rc),
        /*certain=*/true);
  }
  return Status::OK();
}

// Exact-length receive: the senders' messages are 1:1 with the
// receivers' expected lengths on both planes (control frames are sent
// as one message; ring chunks pair SendAll/RecvAll of equal size).
Status ExtRecvExact(int fd, void* buf, size_t len) {
  if (!g_ext_recv) return Status::Error("external transport not set");
  GlobalMetrics().AccountWireSyscall(EventWirePlane(), 0, /*tx=*/false);
  long long got = g_ext_recv(ExtFdPeer(fd), ExtFdTag(fd), buf,
                             (long long)len);
  if (got < 0) {
    return Status::PeerFailure(
        ExtFdPeer(fd), "external transport recv from rank " +
                           std::to_string(ExtFdPeer(fd)) + " failed",
        /*certain=*/true);
  }
  if ((size_t)got != len) {
    return Status::Error("external transport message length mismatch: "
                         "expected " + std::to_string(len) + ", got " +
                         std::to_string(got));
  }
  return Status::OK();
}

int64_t ResolveTimeout(int64_t timeout_ms) {
  return timeout_ms == kWireTimeoutGlobal ? WireTimeoutMs() : timeout_ms;
}

Status PeerTimeout(int fd, const char* what, int64_t stalled_ms) {
  int rank = FdRank(fd);
  return Status::PeerFailure(
      rank, std::string(what) + " made no progress for " +
                std::to_string(stalled_ms) + " ms waiting on rank " +
                (rank >= 0 ? std::to_string(rank) : "<unknown>") +
                " (HOROVOD_WIRE_TIMEOUT_MS)");
}

Status PeerClosed(int fd) {
  int rank = FdRank(fd);
  return Status::PeerFailure(
      rank, "peer" + (rank >= 0 ? " rank " + std::to_string(rank)
                                : std::string("")) +
                " closed connection",
      /*certain=*/true);
}

Status PeerIoError(int fd, const char* what) {
  int rank = FdRank(fd);
  return Status::PeerFailure(
      rank, std::string(what) + " to rank " +
                (rank >= 0 ? std::to_string(rank) : "<unknown>") +
                " failed: " + strerror(errno),
      /*certain=*/true);
}

// Wait for `events` on fd for up to timeout_ms (<= 0 = forever).
// Returns 1 ready, 0 timed out, -1 poll error (errno set).
int WaitFd(int fd, short events, int64_t timeout_ms) {
  pollfd p{};
  p.fd = fd;
  p.events = events;
  while (true) {
    int rc = poll(&p, 1, timeout_ms <= 0 ? -1 : (int)timeout_ms);
    if (rc < 0 && errno == EINTR) continue;
    return rc < 0 ? -1 : (rc == 0 ? 0 : 1);
  }
}

// One poll() over `n` fds honoring EINTR. Same return contract as
// WaitFd.
int PollOnce(pollfd* fds, int n, int64_t timeout_ms) {
  while (true) {
    int rc = poll(fds, (nfds_t)n, timeout_ms <= 0 ? -1 : (int)timeout_ms);
    if (rc < 0 && errno == EINTR) continue;
    return rc < 0 ? -1 : (rc == 0 ? 0 : 1);
  }
}

// The healing ladder (wire.h): base deadline first, then up to
// WireRetryAttempts() extra windows of WireRetryBackoffMs() << attempt.
// A window that turns ready after at least one expiry books a HEAL; an
// expired window books a RETRY. `allow_retry` is false for explicit
// (control-plane) deadlines — those stay single-window.
int PollHealing(pollfd* fds, int n, int64_t timeout_ms, bool allow_retry) {
  int rc = PollOnce(fds, n, timeout_ms);
  if (rc != 0 || !allow_retry || timeout_ms <= 0) return rc;
  const int64_t attempts = WireRetryAttempts();
  const int64_t backoff = std::max<int64_t>(WireRetryBackoffMs(), 1);
  Metrics& m = GlobalMetrics();
  for (int64_t a = 0; a < attempts; a++) {
    m.wire_retries.fetch_add(1, std::memory_order_relaxed);
    // Exponential patience, capped so the ladder stays responsive to a
    // genuinely dead peer: one window never exceeds 64x the base.
    int64_t window = backoff << std::min<int64_t>(a, 6);
    GlobalEvents().Record(EventType::kRetryWindow, (int32_t)a,
                          (int32_t)window);
    rc = PollOnce(fds, n, window);
    if (rc != 0) {
      if (rc == 1) {
        m.wire_heals.fetch_add(1, std::memory_order_relaxed);
        GlobalEvents().Record(EventType::kWireHeal);
      }
      return rc;
    }
  }
  return 0;
}
}  // namespace

int64_t WireTimeoutMs() {
  int64_t v = g_wire_timeout_ms.load(std::memory_order_relaxed);
  if (v == -1) {
    v = EnvInt64OrDefault("HOROVOD_WIRE_TIMEOUT_MS",
                          kDefaultWireTimeoutMs);
    if (v == -1) v = 0;  // same normalization as SetWireTimeoutMs
    g_wire_timeout_ms.store(v, std::memory_order_relaxed);
  }
  return v;
}

void SetWireTimeoutMs(int64_t ms) {
  // -1 is the "uninitialized" sentinel; normalize a literal -1 to the
  // equivalent "no deadline" 0.
  g_wire_timeout_ms.store(ms == -1 ? 0 : ms, std::memory_order_relaxed);
}

int64_t WireRetryAttempts() {
  int64_t v = g_wire_retry_attempts.load(std::memory_order_relaxed);
  if (v == -2) {
    v = std::max<int64_t>(
        EnvInt64OrDefault("HOROVOD_WIRE_RETRY_ATTEMPTS", 0), 0);
    g_wire_retry_attempts.store(v, std::memory_order_relaxed);
  }
  return v;
}

void SetWireRetryAttempts(int64_t n) {
  g_wire_retry_attempts.store(std::max<int64_t>(n, 0),
                              std::memory_order_relaxed);
}

int64_t WireRetryBackoffMs() {
  int64_t v = g_wire_retry_backoff_ms.load(std::memory_order_relaxed);
  if (v == -2) {
    v = std::max<int64_t>(
        EnvInt64OrDefault("HOROVOD_WIRE_RETRY_BACKOFF_MS", 250), 1);
    g_wire_retry_backoff_ms.store(v, std::memory_order_relaxed);
  }
  return v;
}

void SetWireRetryBackoffMs(int64_t ms) {
  g_wire_retry_backoff_ms.store(std::max<int64_t>(ms, 1),
                                std::memory_order_relaxed);
}

int WireChannelsEnv() {
  // Process-lifetime: the established socket count must be the same at
  // init and every reinit, whatever the tuner did to the active width
  // in between.
  static const int k = [] {
    int64_t v = EnvInt64OrDefault("HOROVOD_WIRE_CHANNELS", 1);
    if (v < 1) v = 1;
    if (v > kMaxWireChannels) v = kMaxWireChannels;
    return (int)v;
  }();
  return k;
}

int64_t WireChannels() {
  int64_t v = g_wire_channels.load(std::memory_order_relaxed);
  if (v == -1) {
    v = WireChannelsEnv();
    g_wire_channels.store(v, std::memory_order_relaxed);
  }
  return v;
}

void SetWireChannels(int64_t k) {
  if (k < 1) k = 1;
  if (k > kMaxWireChannels) k = kMaxWireChannels;
  g_wire_channels.store(k, std::memory_order_relaxed);
}

bool WireCrc() {
  int v = g_wire_crc.load(std::memory_order_relaxed);
  if (v == -1) {
    v = EnvInt64OrDefault("HOROVOD_WIRE_CRC", 0) != 0 ? 1 : 0;
    g_wire_crc.store(v, std::memory_order_relaxed);
  }
  return v != 0;
}

void SetWireCrc(bool on) {
  g_wire_crc.store(on ? 1 : 0, std::memory_order_relaxed);
}

// CRC32C (Castagnoli, reflected 0x82F63B78) — the iSCSI/ext4 polynomial,
// table-driven software implementation (no SSE4.2 dependency so the
// sanitizer and portable builds stay identical).
uint32_t Crc32c(const void* data, size_t len) {
  static const uint32_t* table = [] {
    static uint32_t t[256];
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) {
        c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
      }
      t[i] = c;
    }
    return t;
  }();
  uint32_t crc = 0xFFFFFFFFu;
  const auto* p = (const uint8_t*)data;
  for (size_t i = 0; i < len; i++) {
    crc = table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  }
  return crc ^ 0xFFFFFFFFu;
}

void ArmWireFlip(int64_t bit, bool persistent, int64_t skip,
                 int64_t channel) {
  g_flip_persistent.store(persistent, std::memory_order_relaxed);
  g_flip_skip.store(skip, std::memory_order_relaxed);
  g_flip_channel.store(channel, std::memory_order_relaxed);
  g_flip_bit.store(bit, std::memory_order_relaxed);
}

void RegisterFdRank(int fd, int rank, int channel) {
  if (fd < 0) return;  // external fds self-encode their peer
  std::lock_guard<std::mutex> lk(g_fd_rank_mutex);
  g_fd_ranks[fd] = {rank, channel};
}

void UnregisterFdRank(int fd) {
  if (fd < 0) return;
  std::lock_guard<std::mutex> lk(g_fd_rank_mutex);
  g_fd_ranks.erase(fd);
}

int FdRank(int fd) {
  if (IsExtFd(fd)) return ExtFdPeer(fd);
  if (fd < 0) return -1;
  std::lock_guard<std::mutex> lk(g_fd_rank_mutex);
  auto it = g_fd_ranks.find(fd);
  return it == g_fd_ranks.end() ? -1 : it->second.rank;
}

int FdChannel(int fd) {
  if (fd < 0) return 0;  // external transport never stripes
  std::lock_guard<std::mutex> lk(g_fd_rank_mutex);
  auto it = g_fd_ranks.find(fd);
  return it == g_fd_ranks.end() ? 0 : it->second.channel;
}

std::vector<int> RegisteredFds(int channel) {
  std::lock_guard<std::mutex> lk(g_fd_rank_mutex);
  std::vector<int> fds;
  fds.reserve(g_fd_ranks.size());
  for (auto& kv : g_fd_ranks) {
    if (channel < 0 || kv.second.channel == channel) {
      fds.push_back(kv.first);
    }
  }
  return fds;
}

void SetExternalTransport(ExternalSendFn send, ExternalRecvFn recv) {
  g_ext_send = send;
  g_ext_recv = recv;
}

bool ExternalTransportActive() { return g_ext_send && g_ext_recv; }

static void SetSockOpts(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

int TcpListen(int* port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons((uint16_t)*port);
  if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  if (listen(fd, 128) != 0) {
    close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  getsockname(fd, (sockaddr*)&addr, &len);
  *port = ntohs(addr.sin_port);
  return fd;
}

int TcpAccept(int listen_fd) {
  int fd = accept(listen_fd, nullptr, nullptr);
  if (fd >= 0) SetSockOpts(fd);
  return fd;
}

int TcpAcceptTimeout(int listen_fd, int64_t timeout_ms) {
  if (timeout_ms > 0) {
    int w = WaitFd(listen_fd, POLLIN, timeout_ms);
    if (w <= 0) return -1;
  }
  return TcpAccept(listen_fd);
}

int TcpConnect(const std::string& host, int port, int timeout_ms) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  while (true) {
    addrinfo* res = nullptr;
    std::string port_s = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_s.c_str(), &hints, &res) == 0 && res) {
      int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0) {
        if (connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
          freeaddrinfo(res);
          SetSockOpts(fd);
          return fd;
        }
        close(fd);
      }
      freeaddrinfo(res);
    }
    if (std::chrono::steady_clock::now() > deadline) return -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void TcpClose(int fd) {
  if (fd >= 0) {  // external fds (< 0) have nothing to close
    UnregisterFdRank(fd);
    close(fd);
  }
}

// Deadline-bound exact-length I/O: MSG_DONTWAIT attempts with a poll()
// wait between them, so "no progress for timeout_ms" surfaces as a
// typed PeerFailure naming the fd's registered peer instead of blocking
// the background thread forever on a dead rank.
Status SendAll(int fd, const void* buf, size_t len, int64_t timeout_ms) {
  if (IsExtFd(fd)) return ExtSend(fd, buf, len);
  // The healing ladder only wraps deadlines resolved from the GLOBAL
  // knob: explicit control-plane deadlines (heartbeats, rendezvous
  // budgets) must stay single-window.
  const bool global_deadline = timeout_ms == kWireTimeoutGlobal;
  timeout_ms = ResolveTimeout(timeout_ms);
  const char* p = (const char*)buf;
  while (len > 0) {
    // One per INVOCATION (short writes and would-blocks included) —
    // the syscall budget counts calls issued, not calls that moved
    // payload (docs/wire.md "Syscall budget").
    GlobalMetrics().AccountWireSyscall(EventWirePlane(), 0, /*tx=*/true);
    ssize_t n = send(fd, p, len, MSG_NOSIGNAL | MSG_DONTWAIT);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        pollfd pf{};
        pf.fd = fd;
        pf.events = POLLOUT;
        int w = PollHealing(&pf, 1, timeout_ms, global_deadline);
        if (w == 0) return PeerTimeout(fd, "send", timeout_ms);
        if (w < 0) {
          return Status::Error(std::string("poll failed: ") +
                               strerror(errno));
        }
        continue;
      }
      return PeerIoError(fd, "send");
    }
    p += n;
    len -= (size_t)n;
  }
  return Status::OK();
}

Status RecvAll(int fd, void* buf, size_t len, int64_t timeout_ms) {
  if (IsExtFd(fd)) return ExtRecvExact(fd, buf, len);
  const bool global_deadline = timeout_ms == kWireTimeoutGlobal;
  timeout_ms = ResolveTimeout(timeout_ms);
  char* p = (char*)buf;
  while (len > 0) {
    GlobalMetrics().AccountWireSyscall(EventWirePlane(), 0,
                                       /*tx=*/false);
    ssize_t n = recv(fd, p, len, MSG_DONTWAIT);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        pollfd pf{};
        pf.fd = fd;
        pf.events = POLLIN;
        int w = PollHealing(&pf, 1, timeout_ms, global_deadline);
        if (w == 0) return PeerTimeout(fd, "recv", timeout_ms);
        if (w < 0) {
          return Status::Error(std::string("poll failed: ") +
                               strerror(errno));
        }
        continue;
      }
      return PeerIoError(fd, "recv");
    }
    if (n == 0) return PeerClosed(fd);
    p += n;
    len -= (size_t)n;
  }
  return Status::OK();
}

Status SendFrame(int fd, const std::string& payload, int64_t timeout_ms) {
  if (IsExtFd(fd)) {
    // One message per frame: the transport preserves boundaries, so no
    // length prefix is needed.
    return ExtSend(fd, payload.data(), payload.size());
  }
  uint64_t len = payload.size();
  Status s = SendAll(fd, &len, sizeof(len), timeout_ms);
  if (!s.ok()) return s;
  return SendAll(fd, payload.data(), payload.size(), timeout_ms);
}

Status RecvFrame(int fd, std::string* payload, int64_t timeout_ms) {
  if (IsExtFd(fd)) {
    if (!g_ext_recv) return Status::Error("external transport not set");
    // Two-phase: probe the next message's length (cap 0 holds it on
    // the Python side), then copy it out. The probe is a transport
    // call too — it lands on the syscall budget like any other.
    GlobalMetrics().AccountWireSyscall(EventWirePlane(), 0,
                                       /*tx=*/false);
    long long len = g_ext_recv(ExtFdPeer(fd), ExtFdTag(fd), nullptr, 0);
    if (len < 0) {
      return Status::PeerFailure(
          ExtFdPeer(fd), "external transport recv from rank " +
                             std::to_string(ExtFdPeer(fd)) + " failed",
          /*certain=*/true);
    }
    payload->resize((size_t)len);
    if (len == 0) return Status::OK();
    return ExtRecvExact(fd, payload->data(), (size_t)len);
  }
  uint64_t len = 0;
  Status s = RecvAll(fd, &len, sizeof(len), timeout_ms);
  if (!s.ok()) return s;
  payload->resize(len);
  if (len == 0) return Status::OK();
  return RecvAll(fd, payload->data(), len, timeout_ms);
}

namespace {
// Make fds non-blocking for the duration of a duplex transfer; restore after.
// Without this, a blocking send() of a large segment can fill the kernel
// buffer and stall every rank in the ring simultaneously (circular deadlock),
// since nobody would be draining its recv side meanwhile.
class ScopedNonblock {
 public:
  // fd < 0 (one-sided transfers — e.g. the Broadcast head/tail hops)
  // is skipped.
  ScopedNonblock(int fd1, int fd2) : fd1_(fd1), fd2_(fd2) {
    if (fd1_ >= 0) {
      flags1_ = fcntl(fd1_, F_GETFL, 0);
      fcntl(fd1_, F_SETFL, flags1_ | O_NONBLOCK);
    }
    if (fd2_ != fd1_ && fd2_ >= 0) {
      flags2_ = fcntl(fd2_, F_GETFL, 0);
      fcntl(fd2_, F_SETFL, flags2_ | O_NONBLOCK);
    }
  }
  ~ScopedNonblock() {
    if (fd1_ >= 0) fcntl(fd1_, F_SETFL, flags1_);
    if (fd2_ != fd1_ && fd2_ >= 0) fcntl(fd2_, F_SETFL, flags2_);
  }

 private:
  int fd1_, fd2_, flags1_ = 0, flags2_ = 0;
};

// ---- CRC-framed duplex (HOROVOD_WIRE_CRC, wire.h) --------------------
// Wire format (TCP only; the knob is rank-uniform by contract — this IS
// the framing):
//   data frame: 'D1' | u32 idx (LE) | u32 crc32c(payload) (LE) | payload
//   nak frame:  'A7' | u32 idx      (receiver -> sender: resend idx)
//   done frame: '5E'                (receiver -> sender: all verified)
// Payload length is derived from idx (every chunk is `chunk` bytes, the
// last the remainder), so frames are self-describing. Data flows on the
// forward direction of the data socket; acks ride the SAME socket's
// reverse direction (in a ring, the socket a rank receives on is the
// one its upstream neighbor sends on — which that neighbor polls for
// acks). At size 2 (and pairwise exchange) both directions share one
// socket; the type byte demultiplexes. The receiver writes payloads
// into their final offsets but hands a chunk onward (on_chunk /
// returning) ONLY after its CRC verifies — corrupted bytes can never be
// reduced into a result. A NAKed chunk is resent from the caller's
// still-live segment buffer (idempotent: same offset, same bytes); the
// same chunk failing more than WireRetryAttempts()+1 times escalates to
// a typed WireCorruption naming (rank, chunk).

constexpr uint8_t kCrcData = 0xD1;
constexpr uint8_t kCrcNak = 0xA7;
constexpr uint8_t kCrcDone = 0x5E;

uint32_t LoadLE32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

void StoreLE32(uint8_t* p, uint32_t v) {
  p[0] = (uint8_t)v;
  p[1] = (uint8_t)(v >> 8);
  p[2] = (uint8_t)(v >> 16);
  p[3] = (uint8_t)(v >> 24);
}

struct CrcFrameRef {
  uint8_t type;
  uint32_t idx;
};

// Outgoing frame stream for one fd: a queue of frame refs plus the
// partial-write state of the frame currently on the wire. Data payloads
// stream straight from the caller's segment buffer (no copy) except
// when the chaos flip hook stages a corrupted image.
struct CrcOutgoing {
  std::deque<CrcFrameRef> q;
  bool active = false;
  uint8_t hdr[9];
  size_t hdr_len = 0, hdr_sent = 0;
  const uint8_t* pay = nullptr;
  size_t pay_len = 0, pay_sent = 0;
  bool done_flushed = false;
  std::vector<uint8_t> flip_scratch;
};

// Incoming parser state for one fd.
struct CrcIncoming {
  int stage = 0;  // 0 = type byte, 1 = header, 2 = payload
  uint8_t type = 0;
  uint8_t hdr[8];
  size_t hdr_need = 0, hdr_got = 0;
  uint32_t idx = 0, crc = 0;
  size_t pay_got = 0, pay_len = 0;
  uint8_t* pay_dst = nullptr;
};

// Chunks of one channel's stripe subsequence: ceil over the global
// chunk count of the indices congruent to `channel` mod `stripe_k`.
size_t StripeChunkCount(size_t nchunks, int stripe_k, int channel) {
  if ((size_t)channel >= nchunks) return 0;
  return (nchunks - (size_t)channel + (size_t)stripe_k - 1) /
         (size_t)stripe_k;
}

Status DuplexCrcTransfer(
    int send_fd, const uint8_t* send_buf, size_t send_len, int recv_fd,
    uint8_t* recv_buf, size_t recv_len, size_t chunk, int stripe_k,
    int channel,
    const std::function<void(size_t off, size_t len)>& on_chunk) {
  if (chunk == 0) chunk = std::max(send_len, recv_len);
  // Chunk indices are GLOBAL; this call owns those congruent to
  // `channel` mod `stripe_k` of both directions (everything at K=1).
  const size_t ns = send_len ? (send_len + chunk - 1) / chunk : 0;
  const size_t nr = recv_len ? (recv_len + chunk - 1) / chunk : 0;
  const size_t ns_mine = StripeChunkCount(ns, stripe_k, channel);
  const size_t nr_mine = StripeChunkCount(nr, stripe_k, channel);

  struct Slot {
    int fd = -1;
    bool send_role = false, recv_role = false;
    CrcOutgoing out;
    CrcIncoming in;
  };
  Slot slots[2];
  int nslots = 0;
  auto slot_for = [&](int fd) -> Slot* {
    for (int i = 0; i < nslots; i++) {
      if (slots[i].fd == fd) return &slots[i];
    }
    slots[nslots].fd = fd;
    return &slots[nslots++];
  };
  Slot* ssend = ns_mine > 0 ? slot_for(send_fd) : nullptr;
  if (ssend != nullptr) ssend->send_role = true;
  Slot* srecv = nr_mine > 0 ? slot_for(recv_fd) : nullptr;
  if (srecv != nullptr) srecv->recv_role = true;
  if (nslots == 0) return Status::OK();

  // Indexed by GLOBAL chunk idx; only this channel's entries move.
  std::vector<uint8_t> verified(nr, 0);
  std::vector<int64_t> failures(nr, 0);
  size_t n_verified = 0;
  bool peer_done = ns_mine == 0;  // nothing sent -> no ack expected
  const int64_t max_fails = 1 + WireRetryAttempts();
  Metrics& m = GlobalMetrics();

  if (ssend != nullptr) {
    for (size_t i = (size_t)channel; i < ns; i += (size_t)stripe_k) {
      ssend->out.q.push_back({kCrcData, (uint32_t)i});
    }
  }

  auto send_chunk_len = [&](uint32_t idx) {
    return std::min(chunk, send_len - (size_t)idx * chunk);
  };
  auto recv_chunk_len = [&](uint32_t idx) {
    return std::min(chunk, recv_len - (size_t)idx * chunk);
  };

  // Pop the next queued frame on `s` and build its header (staging a
  // flipped payload image when the chaos hook is armed — the CRC is
  // computed over the TRUE payload first, so the receiver must catch
  // the mismatch).
  auto begin_frame = [&](Slot* s) {
    CrcFrameRef f = s->out.q.front();
    s->out.q.pop_front();
    s->out.active = true;
    s->out.hdr_sent = 0;
    s->out.pay_sent = 0;
    s->out.hdr[0] = f.type;
    s->out.pay = nullptr;
    s->out.pay_len = 0;
    if (f.type == kCrcData) {
      size_t len = send_chunk_len(f.idx);
      const uint8_t* pay = send_buf + (size_t)f.idx * chunk;
      uint32_t crc = Crc32c(pay, len);
      int64_t bit = g_flip_bit.load(std::memory_order_relaxed);
      const int64_t flip_chan =
          g_flip_channel.load(std::memory_order_relaxed);
      if (flip_chan >= 0 && flip_chan != channel) bit = -1;
      if (bit >= 0 && len > 0) {
        if (g_flip_skip.load(std::memory_order_relaxed) > 0) {
          g_flip_skip.fetch_sub(1, std::memory_order_relaxed);
        } else {
          s->out.flip_scratch.assign(pay, pay + len);
          size_t b = (size_t)(bit % (int64_t)(len * 8));
          s->out.flip_scratch[b / 8] ^= (uint8_t)(1u << (b % 8));
          pay = s->out.flip_scratch.data();
          if (!g_flip_persistent.load(std::memory_order_relaxed)) {
            g_flip_bit.store(-1, std::memory_order_relaxed);
          }
        }
      }
      StoreLE32(s->out.hdr + 1, f.idx);
      StoreLE32(s->out.hdr + 5, crc);
      s->out.hdr_len = 9;
      s->out.pay = pay;
      s->out.pay_len = len;
    } else if (f.type == kCrcNak) {
      StoreLE32(s->out.hdr + 1, f.idx);
      s->out.hdr_len = 5;
    } else {  // kCrcDone
      s->out.hdr_len = 1;
    }
  };

  // Flush frames until the socket would block. Returns false with *st
  // set on a fatal transport error.
  auto writable = [&](Slot* s, Status* st) -> bool {
    while (true) {
      if (!s->out.active) {
        if (s->out.q.empty()) return true;
        begin_frame(s);
      }
      bool blocked = false;
      while (s->out.hdr_sent < s->out.hdr_len) {
        m.AccountWireSyscall(EventWirePlane(), channel, /*tx=*/true);
        ssize_t k = send(s->fd, s->out.hdr + s->out.hdr_sent,
                         s->out.hdr_len - s->out.hdr_sent, MSG_NOSIGNAL);
        if (k < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) {
            blocked = true;
            break;
          }
          *st = PeerIoError(s->fd, "crc duplex send");
          return false;
        }
        s->out.hdr_sent += (size_t)k;
      }
      if (blocked) return true;
      while (s->out.pay_sent < s->out.pay_len) {
        m.AccountWireSyscall(EventWirePlane(), channel, /*tx=*/true);
        ssize_t k = send(s->fd, s->out.pay + s->out.pay_sent,
                         s->out.pay_len - s->out.pay_sent, MSG_NOSIGNAL);
        if (k < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) {
            blocked = true;
            break;
          }
          *st = PeerIoError(s->fd, "crc duplex send");
          return false;
        }
        s->out.pay_sent += (size_t)k;
      }
      if (blocked) return true;
      if (s->out.hdr[0] == kCrcDone) s->out.done_flushed = true;
      s->out.active = false;
    }
  };

  // Everything this call needs from `s` has arrived: the peer's ack of
  // our send and/or every chunk verified. CRITICAL stop condition for
  // the reader — bytes beyond this point belong to the NEXT transfer
  // on this socket (the peer moves on as soon as its own conditions
  // are met), and draining them here would corrupt that call's frames.
  auto slot_satisfied = [&](Slot* s) {
    return (!s->send_role || peer_done) &&
           (!s->recv_role || n_verified >= nr_mine);
  };

  // Dispatch complete frames until the socket would block or the slot
  // is satisfied. Returns false with *st set on a fatal error (EOF,
  // protocol violation, CRC retry exhaustion).
  auto readable = [&](Slot* s, Status* st) -> bool {
    while (!slot_satisfied(s)) {
      CrcIncoming& in = s->in;
      if (in.stage == 0) {
        uint8_t t = 0;
        m.AccountWireSyscall(EventWirePlane(), channel, /*tx=*/false);
        ssize_t k = recv(s->fd, &t, 1, MSG_DONTWAIT);
        if (k == 0) {
          *st = PeerClosed(s->fd);
          return false;
        }
        if (k < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
          *st = PeerIoError(s->fd, "crc duplex recv");
          return false;
        }
        in.type = t;
        in.hdr_got = 0;
        if (t == kCrcDone) {
          peer_done = true;
          continue;
        }
        if (t == kCrcData) {
          in.hdr_need = 8;
        } else if (t == kCrcNak) {
          in.hdr_need = 4;
        } else {
          *st = Status::Error("crc duplex: unknown frame type " +
                              std::to_string((int)t) + " from rank " +
                              std::to_string(FdRank(s->fd)));
          return false;
        }
        in.stage = 1;
      }
      if (in.stage == 1) {
        bool blocked = false;
        while (in.hdr_got < in.hdr_need) {
          m.AccountWireSyscall(EventWirePlane(), channel, /*tx=*/false);
          ssize_t k = recv(s->fd, in.hdr + in.hdr_got,
                           in.hdr_need - in.hdr_got, MSG_DONTWAIT);
          if (k == 0) {
            *st = PeerClosed(s->fd);
            return false;
          }
          if (k < 0) {
            if (errno == EINTR) continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
              blocked = true;
              break;
            }
            *st = PeerIoError(s->fd, "crc duplex recv");
            return false;
          }
          in.hdr_got += (size_t)k;
        }
        if (blocked) return true;
        in.idx = LoadLE32(in.hdr);
        if (in.type == kCrcNak) {
          if (ssend == nullptr || (size_t)in.idx >= ns ||
              in.idx % (uint32_t)stripe_k != (uint32_t)channel) {
            *st = Status::Error("crc duplex: NAK for chunk " +
                                std::to_string(in.idx) +
                                " of a " + std::to_string(ns) +
                                "-chunk transfer (channel " +
                                std::to_string(channel) + ")");
            return false;
          }
          ssend->out.q.push_back({kCrcData, in.idx});
          GlobalEvents().Record(EventType::kCrcResend, 0, 0,
                                (int64_t)in.idx);
          in.stage = 0;
          continue;
        }
        if (!s->recv_role || (size_t)in.idx >= nr ||
            in.idx % (uint32_t)stripe_k != (uint32_t)channel) {
          *st = Status::Error("crc duplex: data chunk " +
                              std::to_string(in.idx) +
                              " outside the expected " +
                              std::to_string(nr) + "-chunk transfer");
          return false;
        }
        in.crc = LoadLE32(in.hdr + 4);
        in.pay_len = recv_chunk_len(in.idx);
        in.pay_dst = recv_buf + (size_t)in.idx * chunk;
        in.pay_got = 0;
        in.stage = 2;
      }
      bool blocked = false;
      while (in.pay_got < in.pay_len) {
        m.AccountWireSyscall(EventWirePlane(), channel, /*tx=*/false);
        ssize_t k = recv(s->fd, in.pay_dst + in.pay_got,
                         in.pay_len - in.pay_got, MSG_DONTWAIT);
        if (k == 0) {
          *st = PeerClosed(s->fd);
          return false;
        }
        if (k < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) {
            blocked = true;
            break;
          }
          *st = PeerIoError(s->fd, "crc duplex recv");
          return false;
        }
        in.pay_got += (size_t)k;
      }
      if (blocked) return true;
      in.stage = 0;
      // (the slot_satisfied loop condition re-checks after this frame)
      if (Crc32c(in.pay_dst, in.pay_len) == in.crc) {
        if (!verified[in.idx]) {
          verified[in.idx] = 1;
          n_verified++;
          if (failures[in.idx] > 0) {
            m.wire_heals.fetch_add(1, std::memory_order_relaxed);
            GlobalEvents().Record(EventType::kWireHeal);
          }
          GlobalEvents().Record(EventType::kWireChunk, EventWirePlane(),
                                (int32_t)((channel << 1) | 1),
                                (int64_t)in.idx * (int64_t)chunk,
                                (int64_t)in.pay_len);
          if (on_chunk) on_chunk((size_t)in.idx * chunk, in.pay_len);
          if (n_verified == nr_mine) {
            srecv->out.q.push_back({kCrcDone, 0});
          }
        }
        continue;
      }
      m.crc_errors.fetch_add(1, std::memory_order_relaxed);
      GlobalEvents().Record(EventType::kCrcError, FdRank(s->fd),
                            (int32_t)(failures[in.idx] + 1),
                            (int64_t)in.idx);
      if (++failures[in.idx] > max_fails) {
        int rank = FdRank(s->fd);
        *st = Status::WireCorruption(
            rank, (int64_t)in.idx,
            "wire chunk " + std::to_string(in.idx) + " from rank " +
                (rank >= 0 ? std::to_string(rank) : "<unknown>") +
                " failed CRC32C verification " +
                std::to_string(failures[in.idx]) +
                " times (HOROVOD_WIRE_CRC; retry budget "
                "HOROVOD_WIRE_RETRY_ATTEMPTS exhausted)");
        return false;
      }
      m.wire_retries.fetch_add(1, std::memory_order_relaxed);
      srecv->out.q.push_back({kCrcNak, in.idx});
    }
    return true;  // slot satisfied: later bytes belong to the NEXT call
  };

  ScopedNonblock nb(ssend != nullptr ? send_fd : -1,
                    srecv != nullptr ? recv_fd : -1);
  const int64_t timeout_ms = WireTimeoutMs();
  Status st = Status::OK();
  while (true) {
    const bool send_side_done = ns_mine == 0 || peer_done;
    const bool recv_side_done =
        nr_mine == 0 ||
        (n_verified == nr_mine && srecv->out.done_flushed);
    if (send_side_done && recv_side_done) return Status::OK();
    pollfd fds[2];
    Slot* by[2];
    int n = 0;
    for (int i = 0; i < nslots; i++) {
      Slot& s = slots[i];
      short ev = 0;
      if (s.out.active || !s.out.q.empty()) ev |= POLLOUT;
      if ((s.recv_role && n_verified < nr_mine) ||
          (s.send_role && !peer_done)) {
        ev |= POLLIN;
      }
      if (ev == 0) continue;
      fds[n].fd = s.fd;
      fds[n].events = ev;
      fds[n].revents = 0;
      by[n] = &s;
      n++;
    }
    if (n == 0) {
      return Status::Error("crc duplex: internal protocol stall");
    }
    int rc = PollHealing(fds, n, timeout_ms, /*allow_retry=*/true);
    if (rc < 0) {
      return Status::Error(std::string("poll failed: ") + strerror(errno));
    }
    if (rc == 0) {
      return PeerTimeout(
          nr_mine > 0 && n_verified < nr_mine ? recv_fd : send_fd,
          "crc duplex transfer", timeout_ms);
    }
    for (int i = 0; i < n; i++) {
      if (fds[i].revents & (POLLOUT | POLLERR)) {
        if (!writable(by[i], &st)) return st;
      }
      if (fds[i].revents & (POLLIN | POLLHUP)) {
        if (!readable(by[i], &st)) return st;
      }
    }
  }
}
}  // namespace

Status DuplexTransfer(int send_fd, const void* send_buf, size_t send_len,
                      int recv_fd, void* recv_buf, size_t recv_len) {
  if (IsExtFd(send_fd) || IsExtFd(recv_fd)) {
    // The external transport's sends are buffered/asynchronous by
    // contract, so send-then-recv cannot deadlock the ring.
    if (send_len > 0) {
      Status s = SendAll(send_fd, send_buf, send_len);
      if (!s.ok()) return s;
    }
    if (recv_len > 0) return RecvAll(recv_fd, recv_buf, recv_len);
    return Status::OK();
  }
  return DuplexTransferChunked(send_fd, send_buf, send_len, recv_fd,
                               recv_buf, recv_len, 0, nullptr);
}

Status DuplexTransferChunked(
    int send_fd, const void* send_buf, size_t send_len, int recv_fd,
    void* recv_buf, size_t recv_len, size_t chunk,
    const std::function<void(size_t off, size_t len)>& on_chunk) {
  return DuplexTransferStriped(send_fd, send_buf, send_len, recv_fd,
                               recv_buf, recv_len, chunk, 1, 0, on_chunk);
}

namespace {
// Walks one channel's chunk subsequence of one direction: global chunk
// indices congruent to `channel` mod `stripe_k`, in index order. Both
// ends derive the identical schedule from (len, chunk, K), so the
// channel's byte stream needs no extra framing — the K=1 walk is
// byte-for-byte the legacy contiguous stream.
struct StripeCursor {
  size_t total, chunk, nchunks;
  size_t k, idx;   // stride and current global chunk index
  size_t done = 0; // bytes complete of the current chunk
  StripeCursor(size_t total, size_t chunk, int stripe_k, int channel)
      : total(total), chunk(chunk),
        nchunks(total ? (total + chunk - 1) / chunk : 0),
        k((size_t)stripe_k), idx((size_t)channel) {}
  bool finished() const { return idx >= nchunks; }
  size_t off() const { return idx * chunk; }
  size_t len() const { return std::min(chunk, total - off()); }
  size_t remaining() const { return len() - done; }
  // Advance past `n` more bytes of the current chunk; returns true
  // when that completed the chunk (cursor moved to the next one).
  bool Advance(size_t n) {
    done += n;
    if (done < len()) return false;
    done = 0;
    idx += k;
    return true;
  }
};
}  // namespace

Status DuplexTransferStriped(
    int send_fd, const void* send_buf, size_t send_len, int recv_fd,
    void* recv_buf, size_t recv_len, size_t chunk, int stripe_k,
    int channel,
    const std::function<void(size_t off, size_t len)>& on_chunk) {
  if (IsExtFd(send_fd) || IsExtFd(recv_fd)) {
    // Message transports frame per send and never stripe (the data
    // plane forces K=1 on them): chunk boundaries there are the
    // CALLER's business (equal-length paired messages); this fallback
    // keeps the entry safe if one slips through.
    Status s =
        DuplexTransfer(send_fd, send_buf, send_len, recv_fd, recv_buf,
                       recv_len);
    if (s.ok() && on_chunk && recv_len > 0) on_chunk(0, recv_len);
    return s;
  }
  if (WireCrc()) {
    // Integrity mode: typed per-chunk frames with CRC32C + NAK/resend
    // (wire.h). Chunk 0 degrades to one whole-segment frame.
    return DuplexCrcTransfer(send_fd, (const uint8_t*)send_buf, send_len,
                             recv_fd, (uint8_t*)recv_buf, recv_len, chunk,
                             stripe_k, channel, on_chunk);
  }
  if (chunk == 0) chunk = std::max(send_len, recv_len);
  if (chunk == 0) return Status::OK();
  const char* sp = (const char*)send_buf;
  char* rp = (char*)recv_buf;
  StripeCursor snd(send_len, chunk, stripe_k, channel);
  StripeCursor rcv(recv_len, chunk, stripe_k, channel);
  if (snd.finished() && rcv.finished()) return Status::OK();
  ScopedNonblock nb(snd.finished() ? -1 : send_fd,
                    rcv.finished() ? -1 : recv_fd);
  const int64_t timeout_ms = WireTimeoutMs();
  while (!snd.finished() || !rcv.finished()) {
    pollfd fds[2];
    int n = 0;
    int send_idx = -1, recv_idx = -1;
    if (!snd.finished()) {
      fds[n].fd = send_fd;
      fds[n].events = POLLOUT;
      send_idx = n++;
    }
    if (!rcv.finished()) {
      fds[n].fd = recv_fd;
      fds[n].events = POLLIN;
      recv_idx = n++;
    }
    int rc = PollHealing(fds, n, timeout_ms, /*allow_retry=*/true);
    if (rc < 0) {
      return Status::Error(std::string("poll failed: ") + strerror(errno));
    }
    if (rc == 0) {
      // Attribute the stall to the inbound peer when we are waiting on
      // one (data starvation is the usual failure shape); otherwise the
      // outbound peer stopped draining its side.
      return PeerTimeout(recv_idx >= 0 ? recv_fd : send_fd,
                         "duplex transfer", timeout_ms);
    }
    if (send_idx >= 0 && (fds[send_idx].revents & (POLLOUT | POLLERR))) {
      // Stream until the socket would block: successive chunks of this
      // channel are sent back to back (at K=1 that is the legacy
      // contiguous byte stream).
      while (!snd.finished()) {
        GlobalMetrics().AccountWireSyscall(EventWirePlane(), channel,
                                           /*tx=*/true);
        ssize_t k = send(send_fd, sp + snd.off() + snd.done,
                         snd.remaining(), MSG_NOSIGNAL);
        if (k < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          return PeerIoError(send_fd, "duplex send");
        }
        snd.Advance((size_t)k);
      }
    }
    if (recv_idx >= 0 && (fds[recv_idx].revents & (POLLIN | POLLHUP))) {
      while (!rcv.finished()) {
        const size_t coff = rcv.off(), clen = rcv.len();
        GlobalMetrics().AccountWireSyscall(EventWirePlane(), channel,
                                           /*tx=*/false);
        ssize_t k = recv(recv_fd, rp + coff + rcv.done, rcv.remaining(),
                         0);
        if (k == 0) return PeerClosed(recv_fd);
        if (k < 0) {
          if (errno == EINTR) continue;
          if (errno == EAGAIN || errno == EWOULDBLOCK) break;
          return PeerIoError(recv_fd, "duplex recv");
        }
        if (rcv.Advance((size_t)k) && on_chunk) {
          GlobalEvents().Record(EventType::kWireChunk, EventWirePlane(),
                                (int32_t)(channel << 1), (int64_t)coff,
                                (int64_t)clen);
          on_chunk(coff, clen);
        }
      }
    }
  }
  return Status::OK();
}

std::string LocalAddress() {
  ifaddrs* ifs = nullptr;
  std::string best = "127.0.0.1";
  if (getifaddrs(&ifs) == 0) {
    for (ifaddrs* it = ifs; it; it = it->ifa_next) {
      if (!it->ifa_addr || it->ifa_addr->sa_family != AF_INET) continue;
      char buf[INET_ADDRSTRLEN];
      auto* sin = (sockaddr_in*)it->ifa_addr;
      inet_ntop(AF_INET, &sin->sin_addr, buf, sizeof(buf));
      std::string a(buf);
      if (a != "127.0.0.1") {
        best = a;
        break;
      }
    }
    freeifaddrs(ifs);
  }
  return best;
}

}  // namespace hvdtpu
