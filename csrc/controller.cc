#include "controller.h"

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <sstream>

#include "logging.h"
#include "events.h"
#include "metrics.h"
#include "wire.h"

namespace hvdtpu {

namespace {

// Hello exchanged at bootstrap: rank + data-plane listen address, plus
// the membership epoch — the coordinator refuses hellos from any other
// epoch, so a half-dead rank of a previous ring generation (or a
// blacklisted straggler retrying its old assignment) can never join the
// re-formed ring.
struct Hello {
  int32_t rank;
  int32_t epoch_lo;  // low/high halves keep the struct packing simple
  int32_t epoch_hi;
  char addr[64];
  int32_t port;
  // Control-tree listen port of this rank (HOROVOD_CONTROL_TREE):
  // interior workers accept their tree children here. 0 = not an
  // interior worker (leaf, rank 0, or tree mode off).
  int32_t tree_port;
};

// Bundle format for the tree gather: one wire frame holding a sequence
// of [u32 LE len][serialized RequestList] entries. A relay appends its
// children's bundles VERBATIM after its own entry — no re-parse on the
// way up; only the coordinator unpacks.
void AppendBundleEntry(std::string* bundle, const std::string& frame) {
  uint32_t len = (uint32_t)frame.size();
  bundle->append(reinterpret_cast<const char*>(&len), sizeof(len));
  bundle->append(frame);
}

bool SplitBundle(const std::string& bundle,
                 std::vector<std::string>* frames) {
  size_t off = 0;
  while (off < bundle.size()) {
    if (off + sizeof(uint32_t) > bundle.size()) return false;
    uint32_t len;
    std::memcpy(&len, bundle.data() + off, sizeof(len));
    off += sizeof(len);
    if (off + len > bundle.size()) return false;
    frames->emplace_back(bundle.substr(off, len));
    off += len;
  }
  return true;
}

void SetHelloEpoch(Hello* h, int64_t epoch) {
  h->epoch_lo = (int32_t)(epoch & 0xffffffff);
  h->epoch_hi = (int32_t)(epoch >> 32);
}

int64_t HelloEpoch(const Hello& h) {
  return ((int64_t)h.epoch_hi << 32) | (uint32_t)h.epoch_lo;
}

bool ShapesMatch(const std::vector<int64_t>& a, const std::vector<int64_t>& b,
                 bool ignore_first_dim) {
  if (a.size() != b.size()) return false;
  for (size_t i = ignore_first_dim ? 1 : 0; i < a.size(); i++) {
    if (a[i] != b[i]) return false;
  }
  return true;
}

// Byte size of a cached single-tensor response.
int64_t CachedEntryBytes(const Response& r) { return ShapesTotalBytes(r); }

// Scope-exit cleanup for the bootstrap's many error returns: failed
// rendezvous attempts (reinit retries especially) must not leak the
// data-plane listen socket or half-built peer connections.
struct Cleanup {
  std::function<void()> fn;
  ~Cleanup() {
    if (fn) fn();
  }
  void release() { fn = nullptr; }
};

// Shared fusion predicate for the cached and freshly-negotiated allreduce
// paths — one site so the two fusion paths cannot diverge.
bool FusableAllreducePair(DataType dtype_a, int32_t ps_a, ReduceOp op_a,
                          int32_t dev_a, DataType dtype_b, int32_t ps_b,
                          ReduceOp op_b, int32_t dev_b) {
  // Host and device tensors never share a fused group: the former moves
  // through the host ring, the latter through one XLA program.
  return dtype_a == dtype_b && ps_a == ps_b && op_a == op_b &&
         dev_a == dev_b;
}

}  // namespace

Controller::Controller(ControllerConfig cfg) : cfg_(std::move(cfg)) {
  shutdown_flags_.assign(cfg_.size, false);
  last_stall_check_ = std::chrono::steady_clock::now();
  cache_.SetCapacity(cfg_.cache_capacity);
}

Controller::~Controller() {
  for (int fd : control_fds_) TcpClose(fd);
  for (int fd : tree_owned_fds_) TcpClose(fd);
}

std::vector<int> Controller::TreeChildren(int r) const {
  std::vector<int> out;
  if (cfg_.tree_fanout < 2) return out;
  for (int i = 1; i <= cfg_.tree_fanout; i++) {
    int c = r * cfg_.tree_fanout + i;
    if (c < cfg_.size) out.push_back(c);
  }
  return out;
}

int Controller::SubtreeSize(int r) const {
  int n = 1;
  for (int c : TreeChildren(r)) n += SubtreeSize(c);
  return n;
}

Status Controller::Initialize() {
  const int rank = cfg_.rank, size = cfg_.size;
  if (size == 1) {
    data_plane_ = std::make_unique<DataPlane>(0, 1, std::vector<int>{-1});
    return Status::OK();
  }
  // Rendezvous fan-in is the first O(N) control-plane suspect on the
  // scaling profile (docs/scale.md) — time the whole bootstrap.
  const int64_t rdzv_start_us = MetricsNowUs();

  if (cfg_.use_external_transport) {
    // Bare-MPI mode: no rendezvous, no sockets. Ranks and sizes come
    // from the launcher env; both planes address peers through the
    // registered message transport (control = tag 0, data = tag 1).
    if (!ExternalTransportActive()) {
      return Status::Error(
          "HOROVOD_CONTROLLER=mpi but no external transport registered "
          "(the frontend registers mpi4py callbacks before init)");
    }
    if (rank == 0) {
      control_fds_.assign(size, -1);
      for (int i = 1; i < size; i++) control_fds_[i] = ExtFd(i, 0);
    } else {
      control_fds_.assign(1, ExtFd(0, 0));
    }
    std::vector<int> peers(size, -1);
    for (int j = 0; j < size; j++) {
      if (j != rank) peers[j] = ExtFd(j, 1);
    }
    data_plane_ = std::make_unique<DataPlane>(rank, size,
                                              std::move(peers));
    LOG_DEBUG("rank %d: external-transport planes up (size=%d)", rank,
              size);
    return Status::OK();
  }

  // 1) Data-plane listen socket (ephemeral port), plus the control-tree
  // listen socket when this rank is an interior tree worker (its tree
  // children connect here; the port rides the hello/address book).
  int data_port = 0;
  int data_listen = TcpListen(&data_port);
  if (data_listen < 0) return Status::Error("failed to open data-plane port");
  std::string my_addr = LocalAddress();
  const bool tree = TreeEnabled();
  std::vector<int> my_tree_children = tree ? TreeChildren(rank)
                                           : std::vector<int>();
  int tree_port = 0, tree_listen = -1;
  if (tree && rank != 0 && !my_tree_children.empty()) {
    tree_listen = TcpListen(&tree_port);
    if (tree_listen < 0) {
      TcpClose(data_listen);
      return Status::Error("failed to open control-tree port");
    }
  }
  // Full-mesh peer fds, filled in step 3 (declared here so the error
  // cleanup covers every return below; -1 entries are no-ops to close).
  // Channel 0 is `peers`; stripe channels 1..K-1 (HOROVOD_WIRE_-
  // CHANNELS) live in `extra_peers[c-1]` — same mesh, K sockets per
  // pair, the channel id riding the data-plane hello.
  const int wire_channels =
      std::min(std::max(cfg_.wire_channels, 1), kMaxWireChannels);
  std::vector<int> peers(size, -1);
  std::vector<std::vector<int>> extra_peers(
      wire_channels - 1, std::vector<int>(size, -1));
  // Tree edges built in step 4; owned here until handoff.
  std::vector<int> tree_fds;
  Cleanup cleanup{[&] {
    TcpClose(data_listen);
    TcpClose(tree_listen);
    for (int fd : peers) TcpClose(fd);
    for (auto& chan : extra_peers) {
      for (int fd : chan) TcpClose(fd);
    }
    for (int fd : tree_fds) TcpClose(fd);
  }};

  // 2) Control-plane rendezvous + address-book broadcast. Bootstrap
  // I/O runs under the start timeout (launch stragglers are expected);
  // hellos are validated against the current epoch so stale-generation
  // ranks are turned away at the door instead of corrupting the book.
  const int64_t start_ms = cfg_.start_timeout_ms;
  const auto start_deadline =
      std::chrono::steady_clock::now() + std::chrono::milliseconds(start_ms);
  auto remaining_ms = [&]() -> int64_t {
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    start_deadline - std::chrono::steady_clock::now())
                    .count();
    return left > 0 ? left : 1;  // past-deadline accepts fail fast
  };
  std::vector<Hello> book(size);
  if (rank == 0) {
    int port = cfg_.controller_port;
    int lfd = TcpListen(&port);
    if (lfd < 0) {
      return Status::Error("coordinator failed to listen on port " +
                           std::to_string(cfg_.controller_port));
    }
    control_fds_.assign(size, -1);
    Hello mine{0, 0, 0, {0}, data_port, 0};
    SetHelloEpoch(&mine, cfg_.epoch);
    snprintf(mine.addr, sizeof(mine.addr), "%s", my_addr.c_str());
    book[0] = mine;
    int accepted = 0;
    while (accepted < size - 1) {
      // Deadline-bound: a member dying before it connects must FAIL
      // the rendezvous (reinit returns -4), never hang the acceptor.
      int fd = TcpAcceptTimeout(lfd, remaining_ms());
      if (fd < 0) {
        TcpClose(lfd);
        return Status::Error(
            "coordinator rendezvous timed out with " +
            std::to_string(size - 1 - accepted) +
            " member(s) missing (HOROVOD_START_TIMEOUT)");
      }
      Hello h{};
      // remaining_ms, not the full budget: a connector that never
      // sends its hello must not extend the rendezvous past the
      // configured deadline.
      Status s = RecvAll(fd, &h, sizeof(h), remaining_ms());
      if (!s.ok()) {
        TcpClose(fd);
        continue;  // connector vanished mid-hello; keep waiting
      }
      if (HelloEpoch(h) != cfg_.epoch) {
        LOG_WARN("rejecting hello from rank %d at stale epoch %lld "
                 "(current %lld)",
                 h.rank, (long long)HelloEpoch(h), (long long)cfg_.epoch);
        TcpClose(fd);
        continue;
      }
      if (h.rank < 1 || h.rank >= size || control_fds_[h.rank] != -1) {
        LOG_WARN("rejecting bad/duplicate hello rank %d", h.rank);
        TcpClose(fd);
        continue;
      }
      control_fds_[h.rank] = fd;
      RegisterFdRank(fd, h.rank);
      book[h.rank] = h;
      accepted++;
    }
    TcpClose(lfd);
    for (int i = 1; i < size; i++) {
      Status s = SendAll(control_fds_[i], book.data(), sizeof(Hello) * size,
                         remaining_ms());
      if (!s.ok()) return s;
    }
  } else {
    int fd = TcpConnect(cfg_.controller_addr, cfg_.controller_port,
                        (int)start_ms);
    if (fd < 0) {
      return Status::Error("worker failed to reach coordinator at " +
                           cfg_.controller_addr + ":" +
                           std::to_string(cfg_.controller_port));
    }
    RegisterFdRank(fd, 0);
    Hello mine{(int32_t)rank, 0, 0, {0}, data_port, tree_port};
    SetHelloEpoch(&mine, cfg_.epoch);
    snprintf(mine.addr, sizeof(mine.addr), "%s", my_addr.c_str());
    Status s = SendAll(fd, &mine, sizeof(mine), remaining_ms());
    if (s.ok()) {
      s = RecvAll(fd, book.data(), sizeof(Hello) * size, remaining_ms());
    }
    if (!s.ok()) {
      TcpClose(fd);
      return s;
    }
    control_fds_.assign(1, fd);
  }

  // 3) Full-mesh data plane: rank i accepts from all j > i, connects to
  // all j < i — K times per pair (one connection per stripe channel).
  // Each connection is identified by a (rank, epoch, channel) hello;
  // the channel id is what lets both ends bind socket k to stripe k,
  // so the chunk round-robin schedules agree end to end.
  auto chan_slot = [&](int c, int r) -> int* {
    return c == 0 ? &peers[r] : &extra_peers[c - 1][r];
  };
  for (int j = 0; j < rank; j++) {
    for (int c = 0; c < wire_channels; c++) {
      int fd = TcpConnect(book[j].addr, book[j].port, (int)remaining_ms());
      if (fd < 0) {
        return Status::Error("data-plane connect to rank " +
                             std::to_string(j) + " channel " +
                             std::to_string(c) + " failed");
      }
      *chan_slot(c, j) = fd;  // owned by the cleanup guard from here on
      int64_t me[3] = {(int64_t)rank, cfg_.epoch, (int64_t)c};
      Status s = SendAll(fd, me, sizeof(me), remaining_ms());
      if (!s.ok()) return s;
      RegisterFdRank(fd, j, c);
    }
  }
  int connected = 0;
  const int expect = (size - 1 - rank) * wire_channels;
  while (connected < expect) {
    int fd = TcpAcceptTimeout(data_listen, remaining_ms());
    if (fd < 0) {
      return Status::Error(
          "data-plane rendezvous timed out with " +
          std::to_string(expect - connected) +
          " connection(s) missing (HOROVOD_START_TIMEOUT)");
    }
    int64_t who[3] = {-1, -1, -1};
    Status s = RecvAll(fd, who, sizeof(who), remaining_ms());
    if (!s.ok()) {
      TcpClose(fd);
      continue;
    }
    if (who[1] != cfg_.epoch || who[0] <= rank || who[0] >= size ||
        who[2] < 0 || who[2] >= wire_channels ||
        *chan_slot((int)who[2], (int)who[0]) != -1) {
      LOG_WARN("rejecting data-plane hello from rank %lld epoch %lld "
               "channel %lld",
               (long long)who[0], (long long)who[1], (long long)who[2]);
      TcpClose(fd);
      continue;
    }
    *chan_slot((int)who[2], (int)who[0]) = fd;
    RegisterFdRank(fd, (int)who[0], (int)who[2]);
    connected++;
  }
  // 4) Control-tree edges (HOROVOD_CONTROL_TREE). Edges touching rank
  // 0 reuse the star sockets; a deeper child connects to its parent's
  // tree port from the book. Children connect upward, parents accept —
  // acyclic, so no connect/accept deadlock.
  if (tree) {
    const int parent = TreeParent(rank);
    if (rank != 0) {
      if (parent == 0) {
        tree_parent_fd_ = control_fds_[0];  // shared with the star
      } else {
        int fd = TcpConnect(book[parent].addr, book[parent].tree_port,
                            (int)remaining_ms());
        if (fd < 0) {
          return Status::Error("control-tree connect to rank " +
                               std::to_string(parent) + " failed");
        }
        tree_fds.push_back(fd);
        int64_t me[2] = {(int64_t)rank, cfg_.epoch};
        Status s = SendAll(fd, me, sizeof(me), remaining_ms());
        if (!s.ok()) return s;
        RegisterFdRank(fd, parent);
        tree_parent_fd_ = fd;
      }
    }
    if (rank == 0) {
      for (int c : my_tree_children) {
        tree_children_.emplace_back(c, control_fds_[c]);
      }
    } else {
      std::vector<int> child_fd(size, -1);
      int accepted = 0;
      while (accepted < (int)my_tree_children.size()) {
        int fd = TcpAcceptTimeout(tree_listen, remaining_ms());
        if (fd < 0) {
          return Status::Error(
              "control-tree rendezvous timed out with " +
              std::to_string((int)my_tree_children.size() - accepted) +
              " child(ren) missing (HOROVOD_START_TIMEOUT)");
        }
        int64_t who[2] = {-1, -1};
        Status s = RecvAll(fd, who, sizeof(who), remaining_ms());
        bool expected = s.ok() && who[1] == cfg_.epoch;
        if (expected) {
          expected = false;
          for (int c : my_tree_children) expected |= c == (int)who[0];
          expected = expected && child_fd[who[0]] == -1;
        }
        if (!expected) {
          LOG_WARN("rejecting control-tree hello from rank %lld epoch "
                   "%lld", (long long)who[0], (long long)who[1]);
          TcpClose(fd);
          continue;
        }
        child_fd[who[0]] = fd;
        tree_fds.push_back(fd);
        RegisterFdRank(fd, (int)who[0]);
        accepted++;
      }
      for (int c : my_tree_children) {
        tree_children_.emplace_back(c, child_fd[c]);
      }
    }
  }
  cleanup.release();  // mesh complete: the DataPlane owns the fds now
  tree_owned_fds_ = std::move(tree_fds);  // closed by the destructor
  TcpClose(data_listen);
  TcpClose(tree_listen);
  data_plane_ = std::make_unique<DataPlane>(rank, size, std::move(peers));
  if (wire_channels > 1) {
    data_plane_->AdoptExtraChannelFds(std::move(extra_peers));
  }
  RecordControlPhase(kPhaseRendezvous, MetricsNowUs() - rdzv_start_us);
  LOG_DEBUG("rank %d: control+data planes up (size=%d, epoch=%lld, "
            "tree_fanout=%d)", rank, size, (long long)cfg_.epoch,
            cfg_.tree_fanout);
  return Status::OK();
}

Status Controller::InitializeFromFds(
    std::vector<int> control_fds, std::vector<int> peer_fds,
    int tree_parent_fd, std::vector<std::pair<int, int>> tree_children) {
  control_fds_ = std::move(control_fds);
  if (TreeEnabled()) {
    if (cfg_.rank == 0) {
      for (int c : TreeChildren(0)) {
        tree_children_.emplace_back(c, control_fds_[c]);
      }
    } else {
      if (tree_parent_fd >= 0) {
        tree_parent_fd_ = tree_parent_fd;
        tree_owned_fds_.push_back(tree_parent_fd);
      } else {
        tree_parent_fd_ = control_fds_[0];  // parent is the coordinator
      }
      tree_children_ = std::move(tree_children);
      for (auto& kv : tree_children_) tree_owned_fds_.push_back(kv.second);
    }
  }
  data_plane_ = std::make_unique<DataPlane>(cfg_.rank, cfg_.size,
                                            std::move(peer_fds));
  return Status::OK();
}

std::vector<int32_t> Controller::MembersOf(int32_t process_set_id) const {
  if (process_set_id == 0 || cfg_.process_sets == nullptr) {
    std::vector<int32_t> all(cfg_.size);
    for (int i = 0; i < cfg_.size; i++) all[i] = i;
    return all;
  }
  return cfg_.process_sets->Ranks(process_set_id);
}

void Controller::MaybePromote(const std::string& key, PendingTensor& pt) {
  if (pt.queued) return;
  std::vector<int32_t> members =
      MembersOf(pt.requests.front().process_set_id);
  // Unknown/removed set, or a submitter outside the set: promote
  // immediately so BuildResponse can surface an ERROR instead of the
  // tensor silently pending forever (set members would never cover it).
  if (!members.empty()) {
    bool foreign = false;
    for (int32_t seen : pt.ranks_seen) {
      bool member = false;
      for (int32_t r : members) member = member || r == seen;
      foreign = foreign || !member;
    }
    if (!foreign) {
      for (int32_t r : members) {
        if (!pt.ranks_seen.count(r) && !joined_ranks_.count(r)) return;
      }
    }
  }
  pt.queued = true;
  const Request& first = pt.requests.front();
  // Ranks disagreeing on the grouping must surface BuildResponse's
  // mismatch ERROR, not sit in group_table_ waiting for members that
  // will never arrive — promote such keys directly.
  for (const auto& req : pt.requests) {
    if (req.group_id != first.group_id ||
        req.group_size != first.group_size) {
      ready_queue_.push_back(key);
      return;
    }
  }
  if (first.group_id >= 0 && first.group_size > 1) {
    // Hold group members until the whole group is ready, then release
    // them contiguously so FuseResponses emits one pure group response.
    std::string gkey = std::to_string(first.process_set_id) + ':' +
                       std::to_string(first.group_id);
    GroupState& gs = group_table_[gkey];
    gs.size = first.group_size;
    gs.ready_keys.push_back(key);
    if ((int32_t)gs.ready_keys.size() >= gs.size) {
      for (auto& k : gs.ready_keys) ready_queue_.push_back(k);
      group_table_.erase(gkey);
    }
    return;
  }
  ready_queue_.push_back(key);
}

// Negotiation state is keyed by (process set, name) so disjoint sets can
// run same-named collectives concurrently — the reference gets this from
// per-process-set controllers (process_set.h). '\x1f' cannot appear in a
// Python-supplied tensor name.
std::string Controller::TableKey(const Request& req) {
  return req.tensor_name + '\x1f' + std::to_string(req.process_set_id);
}

void Controller::HandleRequestList(const RequestList& list, int from_rank) {
  if (list.shutdown) shutdown_flags_[from_rank] = true;
  bool new_join = false;
  for (const auto& req : list.requests) {
    if (req.request_type == RequestType::JOIN) {
      // Reference analog: controller.cc join accounting (EnqueueJoin).
      if (!joined_ranks_.count(req.request_rank)) {
        joined_ranks_.insert(req.request_rank);
        last_joined_rank_ = req.request_rank;
        new_join = true;
      }
      continue;
    }
    auto& pt = message_table_[TableKey(req)];
    if (pt.ranks_seen.empty()) {
      pt.first_seen = std::chrono::steady_clock::now();
      pt.first_round = round_;
    }
    if (pt.ranks_seen.count(req.request_rank)) continue;  // duplicate
    pt.ranks_seen.insert(req.request_rank);
    pt.requests.push_back(req);
    bool was_queued = pt.queued;
    MaybePromote(TableKey(req), pt);
    if (!was_queued && pt.queued && pt.ranks_seen.size() > 1 &&
        round_ > pt.first_round) {
      // This request completed readiness in a LATER round than the
      // first arrival: its rank genuinely kept the tensor waiting, and
      // first->last spread is the negotiation skew. Same-round
      // completions are not attributable (the gather's fixed rank
      // order would masquerade as lateness). Aggregated per rank this
      // is the coordinator's live straggler table (the trace-merge
      // report computes the same offline).
      GlobalMetrics().RecordStraggler(
          req.request_rank,
          std::chrono::duration_cast<std::chrono::microseconds>(
              std::chrono::steady_clock::now() - pt.first_seen)
              .count());
    }
  }
  if (new_join) {
    // A new join can complete readiness for any pending tensor.
    for (auto& kv : message_table_) MaybePromote(kv.first, kv.second);
  }
}

Response Controller::BuildResponse(const std::string& key) {
  auto& pt = message_table_[key];
  const Request& first = pt.requests.front();
  Response res;
  res.tensor_names = {first.tensor_name};
  res.tensor_type = first.tensor_type;
  res.reduce_op = first.reduce_op;
  res.root_rank = first.root_rank;
  res.process_set_id = first.process_set_id;
  res.device = first.device;
  res.group_id = first.group_id;
  res.tensor_shapes.push_back((int64_t)first.tensor_shape.size());
  res.tensor_shapes.insert(res.tensor_shapes.end(),
                           first.tensor_shape.begin(),
                           first.tensor_shape.end());
  std::vector<int32_t> members = MembersOf(first.process_set_id);
  if (members.empty()) {
    res.response_type = Response::ResponseType::ERROR;
    res.error_message =
        "tensor " + first.tensor_name + ": unknown process set " +
        std::to_string(first.process_set_id) +
        " (add_process_set must complete on every rank first)";
    return res;
  }
  for (const auto& req : pt.requests) {
    bool member = false;
    for (int32_t r : members) member = member || r == req.request_rank;
    if (!member) {
      res.response_type = Response::ResponseType::ERROR;
      res.error_message =
          "tensor " + first.tensor_name + ": rank " +
          std::to_string(req.request_rank) + " is not a member of process "
          "set " + std::to_string(first.process_set_id);
      return res;
    }
  }
  // A member not in ranks_seen can only be covered by a join; alltoall
  // needs real splits from every member, so that combination is an error.
  bool member_joined = false;
  for (int32_t r : members) {
    if (!pt.ranks_seen.count(r)) member_joined = true;
  }
  if (member_joined && first.request_type == RequestType::ALLTOALL) {
    res.response_type = Response::ResponseType::ERROR;
    res.error_message = "tensor " + first.tensor_name +
                        ": alltoall is not supported with joined ranks";
    return res;
  }

  // Cross-rank validation.
  // Reference analog: Controller::ConstructResponse error paths.
  std::string err;
  for (const auto& req : pt.requests) {
    if (req.request_type != first.request_type) {
      err = "mismatched collective types across ranks";
    } else if (req.tensor_type != first.tensor_type) {
      err = "mismatched tensor dtypes across ranks";
    } else if (req.process_set_id != first.process_set_id) {
      err = "mismatched process sets across ranks";
    } else if (req.device != first.device) {
      err = "mismatched device placement across ranks";
    } else if (req.group_id != first.group_id ||
               req.group_size != first.group_size) {
      err = "mismatched allreduce grouping across ranks (grouped calls "
            "must happen in the same order on every rank)";
    } else if (req.request_type == RequestType::ALLREDUCE ||
               req.request_type == RequestType::BROADCAST ||
               req.request_type == RequestType::REDUCESCATTER) {
      if (!ShapesMatch(req.tensor_shape, first.tensor_shape, false)) {
        err = "mismatched tensor shapes across ranks";
      }
      if (req.request_type == RequestType::BROADCAST &&
          req.root_rank != first.root_rank) {
        err = "mismatched broadcast root ranks";
      }
    } else if (req.request_type == RequestType::ALLGATHER ||
               req.request_type == RequestType::ALLTOALL) {
      if (!ShapesMatch(req.tensor_shape, first.tensor_shape, true)) {
        err = "mismatched tensor shapes (non-first dims) across ranks";
      }
      // Device alltoall is equal-split (one static XLA program): every
      // rank must contribute the same first dim too.
      if (req.request_type == RequestType::ALLTOALL && first.device == 1 &&
          !ShapesMatch(req.tensor_shape, first.tensor_shape, false)) {
        err = "device alltoall requires identical shapes on every rank "
              "(ragged splits ride the host path)";
      }
    }
    if (!err.empty()) break;
  }
  if (!err.empty()) {
    res.response_type = Response::ResponseType::ERROR;
    res.error_message = "tensor " + first.tensor_name + ": " + err;
    return res;
  }

  switch (first.request_type) {
    case RequestType::ALLREDUCE:
      res.response_type = Response::ResponseType::ALLREDUCE;
      break;
    case RequestType::ALLGATHER: {
      res.response_type = Response::ResponseType::ALLGATHER;
      // Per-member first-dim sizes in set order (joined members stay 0).
      std::vector<int32_t> members = MembersOf(first.process_set_id);
      res.tensor_sizes.assign(members.size(), 0);
      for (const auto& req : pt.requests) {
        for (size_t i = 0; i < members.size(); i++) {
          if (members[i] == req.request_rank) {
            res.tensor_sizes[i] =
                req.tensor_shape.empty() ? 1 : req.tensor_shape[0];
          }
        }
      }
      break;
    }
    case RequestType::BROADCAST:
      res.response_type = Response::ResponseType::BROADCAST;
      break;
    case RequestType::ALLTOALL:
      res.response_type = Response::ResponseType::ALLTOALL;
      break;
    case RequestType::REDUCESCATTER:
      res.response_type = Response::ResponseType::REDUCESCATTER;
      break;
    case RequestType::BARRIER:
      res.response_type = Response::ResponseType::BARRIER;
      break;
    case RequestType::JOIN:
      // JOIN never reaches BuildResponse: HandleRequestList diverts it to
      // joined_ranks_ and FuseResponses emits the JOIN response directly.
      res.response_type = Response::ResponseType::ERROR;
      res.error_message = "internal: JOIN request in BuildResponse";
      break;
  }
  return res;
}

ResponseList Controller::FuseResponses() {
  ResponseList list;
  while (!ready_queue_.empty()) {
    std::string key = ready_queue_.front();
    ready_queue_.pop_front();
    Response res = BuildResponse(key);
    const Request& first = message_table_[key].requests.front();
    int64_t bytes = 1;
    for (auto d : first.tensor_shape) bytes *= d;
    bytes *= DataTypeSize(first.tensor_type);
    // Tensor fusion: keep folding subsequent ready ALLREDUCEs of the same
    // dtype/process-set into this response while under the threshold.
    // Reference analog: Controller::FuseResponses + fusion_buffer_manager.
    // Adasum is per-gradient (the combine normalizes per tensor), so those
    // responses stay unfused. Reference analog: adasum.h takes per-tensor
    // counts inside the fused buffer; we keep v1 simpler.
    if (res.response_type == Response::ResponseType::ALLREDUCE &&
        first.reduce_op != ReduceOp::ADASUM) {
      while (!ready_queue_.empty()) {
        const std::string& next_key = ready_queue_.front();
        auto& npt = message_table_[next_key];
        const Request& nreq = npt.requests.front();
        // Atomic groups fuse completely (no threshold) and stay PURE —
        // never mixed with other tensors — so the response is exactly
        // the group and can be skipped by the cache as a unit.
        bool same_group = first.group_id >= 0 &&
                          nreq.group_id == first.group_id &&
                          nreq.process_set_id == first.process_set_id;
        if (first.group_id >= 0 && !same_group) break;
        if (first.group_id < 0 && nreq.group_id >= 0) break;
        if (nreq.request_type != RequestType::ALLREDUCE ||
            !FusableAllreducePair(nreq.tensor_type, nreq.process_set_id,
                                  nreq.reduce_op, nreq.device,
                                  first.tensor_type, first.process_set_id,
                                  first.reduce_op, first.device)) {
          break;
        }
        Response nres = BuildResponse(next_key);
        if (nres.response_type == Response::ResponseType::ERROR) break;
        int64_t nbytes = 1;
        for (auto d : nreq.tensor_shape) nbytes *= d;
        nbytes *= DataTypeSize(nreq.tensor_type);
        if (!same_group &&
            (bytes >= cfg_.fusion_threshold_bytes ||
             bytes + nbytes > cfg_.fusion_threshold_bytes)) {
          break;
        }
        res.tensor_names.push_back(nreq.tensor_name);
        res.tensor_shapes.push_back((int64_t)nreq.tensor_shape.size());
        res.tensor_shapes.insert(res.tensor_shapes.end(),
                                 nreq.tensor_shape.begin(),
                                 nreq.tensor_shape.end());
        bytes += nbytes;
        message_table_.erase(next_key);
        ready_queue_.pop_front();
      }
    }
    message_table_.erase(key);
    list.responses.push_back(std::move(res));
  }
  // All ranks joined: complete every rank's pending join.
  // Reference analog: controller.cc join completion (last_joined_rank).
  if ((int)joined_ranks_.size() == cfg_.size) {
    Response join;
    join.response_type = Response::ResponseType::JOIN;
    join.tensor_names = {"__join__"};
    join.last_joined_rank = last_joined_rank_;
    list.responses.push_back(std::move(join));
    joined_ranks_.clear();
    last_joined_rank_ = -1;
  }
  return list;
}

RequestList Controller::BuildRequestList(std::vector<Request> requests,
                                         bool should_shutdown) {
  RequestList my_list;
  my_list.shutdown = should_shutdown;
  if (!resubmit_.empty()) {
    // Requests whose cached position was evicted mid-flight renegotiate now.
    requests.insert(requests.begin(),
                    std::make_move_iterator(resubmit_.begin()),
                    std::make_move_iterator(resubmit_.end()));
    resubmit_.clear();
  }
  for (auto& req : requests) {
    if (req.request_type == RequestType::JOIN) {
      my_list.requests.push_back(std::move(req));
      continue;
    }
    int32_t pos = -1;
    switch (cache_.Lookup(req, &pos)) {
      case ResponseCache::LookupResult::HIT:
        my_list.cache_hits.push_back(pos);
        inflight_hits_[pos] = std::move(req);
        break;
      case ResponseCache::LookupResult::INVALID:
        my_list.cache_invalid.push_back(pos);
        my_list.requests.push_back(std::move(req));
        break;
      case ResponseCache::LookupResult::MISS:
        my_list.requests.push_back(std::move(req));
        break;
    }
  }
  return my_list;
}

void Controller::HandleCacheBits(const RequestList& list, int from_rank,
                                 std::vector<int64_t>* evictions) {
  for (int64_t pos : list.cache_invalid) {
    if (std::find(evictions->begin(), evictions->end(), pos) ==
        evictions->end()) {
      evictions->push_back(pos);
    }
    bit_table_.erase((int32_t)pos);
  }
  for (int64_t pos : list.cache_hits) {
    // Stale bits (position evicted this cycle, or by an earlier eviction the
    // sender raced with) are dropped; the sender resubmits a full request
    // when it processes the broadcast eviction.
    if (!cache_.Has((int32_t)pos)) continue;
    if (std::find(evictions->begin(), evictions->end(), pos) !=
        evictions->end()) {
      continue;
    }
    auto& pb = bit_table_[(int32_t)pos];
    if (pb.ranks.empty()) {
      pb.first_seen = std::chrono::steady_clock::now();
      pb.first_round = round_;
    }
    if (pb.ranks.insert(from_rank).second) pb.last_rank = from_rank;
  }
}

void Controller::CollectCacheHits(ResponseList* list) {
  if (bit_table_.empty()) return;
  std::vector<int32_t> pending;
  pending.reserve(bit_table_.size());
  for (auto& kv : bit_table_) pending.push_back(kv.first);
  std::sort(pending.begin(), pending.end());
  std::vector<int32_t> completed;
  for (int32_t pos : pending) {
    const Response& r = cache_.Get(pos);
    bool done = true;
    for (int32_t m : MembersOf(r.process_set_id)) {
      if (!bit_table_[pos].ranks.count(m) && !joined_ranks_.count(m)) {
        done = false;
        break;
      }
    }
    if (done) {
      completed.push_back(pos);
      const PendingBits& pb = bit_table_[pos];
      if (pb.ranks.size() > 1 && round_ > pb.first_round) {
        // Steady-state (bitvector) stragglers matter most: a training
        // loop spends nearly every cycle here, so skew measured only on
        // full negotiations would go blind after warmup.
        GlobalMetrics().RecordStraggler(
            pb.last_rank,
            std::chrono::duration_cast<std::chrono::microseconds>(
                std::chrono::steady_clock::now() - pb.first_seen)
                .count());
      }
    }
  }
  // Group consecutive fusable allreduce hits; every rank rebuilds the same
  // fused Response from the group. Reference analog: cached responses join
  // the same FuseResponses path (controller.cc); here the coordinator owns
  // the grouping so the fusion threshold needs no cross-rank sync.
  size_t i = 0;
  while (i < completed.size()) {
    const Response& r0 = cache_.Get(completed[i]);
    int64_t group = 1;
    if (r0.response_type == Response::ResponseType::ALLREDUCE) {
      int64_t bytes = CachedEntryBytes(r0);
      while (i + group < completed.size()) {
        const Response& rn = cache_.Get(completed[i + group]);
        if (rn.response_type != Response::ResponseType::ALLREDUCE ||
            !FusableAllreducePair(rn.tensor_type, rn.process_set_id,
                                  rn.reduce_op, rn.device, r0.tensor_type,
                                  r0.process_set_id, r0.reduce_op,
                                  r0.device)) {
          break;
        }
        int64_t nb = CachedEntryBytes(rn);
        if (bytes + nb > cfg_.fusion_threshold_bytes) break;
        bytes += nb;
        group++;
      }
    }
    for (int64_t k = 0; k < group; k++) {
      list->cache_hit_positions.push_back(completed[i + k]);
      bit_table_.erase(completed[i + k]);
    }
    list->cache_hit_group_sizes.push_back(group);
    i += group;
  }
}

void Controller::ApplyCacheVerdicts(ResponseList* out) {
  for (int64_t pos : out->cache_evictions) {
    cache_.Evict((int32_t)pos);
    auto it = inflight_hits_.find((int32_t)pos);
    if (it != inflight_hits_.end()) {
      resubmit_.push_back(std::move(it->second));
      inflight_hits_.erase(it);
    }
  }
  std::vector<Response> hit_responses;
  size_t idx = 0;
  for (int64_t gs : out->cache_hit_group_sizes) {
    if (idx + (size_t)gs > out->cache_hit_positions.size()) break;
    int32_t pos0 = (int32_t)out->cache_hit_positions[idx];
    if (!cache_.Has(pos0)) {  // cannot happen with consistent caches
      idx += gs;
      continue;
    }
    Response merged = cache_.Get(pos0);
    inflight_hits_.erase(pos0);
    for (int64_t k = 1; k < gs; k++) {
      int32_t pos = (int32_t)out->cache_hit_positions[idx + k];
      const Response& nxt = cache_.Get(pos);
      merged.tensor_names.push_back(nxt.tensor_names[0]);
      merged.tensor_shapes.insert(merged.tensor_shapes.end(),
                                  nxt.tensor_shapes.begin(),
                                  nxt.tensor_shapes.end());
      inflight_hits_.erase(pos);
    }
    idx += gs;
    hit_responses.push_back(std::move(merged));
  }
  // Fresh negotiated responses become cache entries for the next cycle —
  // identical insertion order on every rank (driven by the broadcast bytes).
  cache_.InsertFromResponses(out->responses);
  if (!hit_responses.empty()) {
    // Execution order: steady-state hits first, then new negotiations.
    hit_responses.insert(hit_responses.end(),
                         std::make_move_iterator(out->responses.begin()),
                         std::make_move_iterator(out->responses.end()));
    out->responses = std::move(hit_responses);
  }
}

void Controller::CheckForStalledTensors() {
  if (!cfg_.stall_check_enabled) return;
  auto now = std::chrono::steady_clock::now();
  // Check at half the configured warning time (capped at 10s) so a
  // sub-10s HOROVOD_STALL_CHECK_TIME fires on schedule instead of
  // silently rounding up to the next 10s boundary. Floored at 100ms:
  // a zero/tiny warning time must not turn the sweep into a per-cycle
  // log flood (default cycle time is 1ms).
  double interval =
      std::min(10.0, std::max(0.1, cfg_.stall_warning_secs / 2.0));
  if (std::chrono::duration<double>(now - last_stall_check_).count() <
      interval) {
    return;
  }
  last_stall_check_ = now;
  for (auto& kv : message_table_) {
    double waited =
        std::chrono::duration<double>(now - kv.second.first_seen).count();
    if (waited > cfg_.stall_warning_secs) {
      std::ostringstream missing;
      int n_missing = 0;
      for (int32_t r :
           MembersOf(kv.second.requests.front().process_set_id)) {
        if (!kv.second.ranks_seen.count(r) && !joined_ranks_.count(r)) {
          missing << r << " ";
          n_missing++;
        }
      }
      GlobalEvents().Record(EventType::kStall, (int32_t)waited,
                            n_missing);
      LOG_WARN(
          "Stall detected: tensor %s has waited %.0fs; missing ranks: %s"
          " (one or more ranks did not submit this collective)",
          kv.second.requests.front().tensor_name.c_str(), waited,
          missing.str().c_str());
    }
  }
  // Cache-hit bits stall the same way full requests do.
  for (auto& kv : bit_table_) {
    double waited =
        std::chrono::duration<double>(now - kv.second.first_seen).count();
    if (waited > cfg_.stall_warning_secs && cache_.Has(kv.first)) {
      const Response& r = cache_.Get(kv.first);
      std::ostringstream missing;
      int n_missing = 0;
      for (int32_t m : MembersOf(r.process_set_id)) {
        if (!kv.second.ranks.count(m) && !joined_ranks_.count(m)) {
          missing << m << " ";
          n_missing++;
        }
      }
      // Steady-state (cache-bit) stalls are the common production
      // case — they must reach the flight recorder like full-request
      // stalls do.
      GlobalEvents().Record(EventType::kStall, (int32_t)waited,
                            n_missing);
      LOG_WARN(
          "Stall detected: cached tensor %s has waited %.0fs; missing "
          "ranks: %s (one or more ranks did not submit this collective)",
          r.tensor_names[0].c_str(), waited, missing.str().c_str());
    }
  }
}

Status Controller::ComputeResponseList(std::vector<Request> requests,
                                       bool should_shutdown,
                                       ResponseList* out) {
  if (cfg_.size == 1) {
    RequestList my_list;
    my_list.requests = std::move(requests);
    my_list.shutdown = should_shutdown;
    HandleRequestList(my_list, 0);
    *out = FuseResponses();
    out->shutdown = should_shutdown;
    out->epoch = cfg_.epoch;
    return Status::OK();
  }

  RequestList my_list = BuildRequestList(std::move(requests), should_shutdown);
  my_list.epoch = cfg_.epoch;
  my_list.rank = cfg_.rank;
  // Control-plane deadline: the per-cycle gather/bcast round IS the
  // heartbeat (idle workers still send an empty list every cycle), so
  // bounding each frame bounds failure detection.
  const int64_t hb_ms = cfg_.heartbeat_timeout_ms > 0
                            ? cfg_.heartbeat_timeout_ms
                            : WireTimeoutMs();
  // A worker waiting for the broadcast is implicitly waiting on EVERY
  // other rank's frame reaching the coordinator first — the sequential
  // gather may legitimately take up to (size-1) per-peer deadlines
  // with benign stragglers, so the worker's recv budget scales with
  // size (a spurious coordinator-death verdict here would tear down a
  // healthy ring).
  const int64_t worker_recv_ms = hb_ms <= 0 ? 0 : hb_ms * cfg_.size;

  if (cfg_.rank == 0) {
    round_++;
    std::vector<int64_t> evictions;
    HandleCacheBits(my_list, 0, &evictions);
    HandleRequestList(my_list, 0);
    // The gather is THE O(N) coordinator suspect at large worlds:
    // per-cycle latency lands on the control_phase profile either way,
    // so the flat-vs-tree scaling curves come from one instrumentation
    // site (docs/scale.md).
    const int64_t gather_t0 = MetricsNowUs();
    if (TreeEnabled()) {
      Status s = TreeCoordinatorGather(hb_ms, &evictions);
      if (!s.ok()) {
        BroadcastFaultNotice(s);
        return s;
      }
    } else {
      for (int r = 1; r < cfg_.size; r++) {
        std::string frame;
        Status s = RecvFrame(control_fds_[r], &frame, hb_ms);
        RequestList rl;
        if (s.ok()) {
          s = ParseRequestList(frame, &rl);
          if (s.ok() && rl.epoch != cfg_.epoch) {
            s = Status::PeerFailure(
                r, "rank " + std::to_string(r) + " sent a stale-epoch " +
                       "request (epoch " + std::to_string(rl.epoch) +
                       ", current " + std::to_string(cfg_.epoch) + ")");
          }
        } else if (!s.peer_failure()) {
          s = Status::PeerFailure(r, "control-plane gather from rank " +
                                         std::to_string(r) +
                                         " failed: " + s.reason());
        }
        if (!s.ok()) {
          BroadcastFaultNotice(s);
          return s;
        }
        HandleCacheBits(rl, r, &evictions);
        HandleRequestList(rl, r);
      }
    }
    const int64_t gather_dur_us = MetricsNowUs() - gather_t0;
    CheckForStalledTensors();
    ResponseList list;
    list.epoch = cfg_.epoch;
    list.cache_evictions = std::move(evictions);
    // Hits must complete BEFORE FuseResponses: the all-ranks-joined cycle
    // clears joined_ranks_ there, and pending bits rely on join coverage the
    // same way MaybePromote does for full requests.
    CollectCacheHits(&list);
    list.responses = FuseResponses().responses;
    // Idle cycles (nothing negotiated, no cache traffic) stay on the
    // gather/broadcast latency histograms but skip the ring events —
    // the flight recorder keeps its tail for events that carry signal
    // (RecordControlPhase).
    const bool busy_cycle = !list.responses.empty() ||
                            !list.cache_hit_positions.empty() ||
                            !list.cache_evictions.empty();
    RecordControlPhase(kPhaseGather, gather_dur_us, busy_cycle);
    list.shutdown = std::all_of(shutdown_flags_.begin(), shutdown_flags_.end(),
                                [](bool b) { return b; });
    list.fusion_threshold_bytes = bcast_fusion_bytes_;
    list.cycle_time_ms = bcast_cycle_ms_;
    list.ring_chunk_bytes = bcast_ring_chunk_bytes_;
    list.wire_compression = bcast_wire_compression_;
    list.hier_split = bcast_hier_split_;
    list.wire_channels = bcast_wire_channels_;
    // Serialize before ApplyCacheVerdicts: the broadcast carries only
    // negotiated responses + cache verdicts; every rank (this one included)
    // then rebuilds hit responses and inserts new entries identically.
    std::string payload = SerializeResponseList(list);
    const int64_t bcast_t0 = MetricsNowUs();
    // Tree mode: send to the direct children only (they relay down);
    // flat mode: one frame per worker.
    std::vector<std::pair<int, int>> targets;
    if (TreeEnabled()) {
      targets = tree_children_;
    } else {
      for (int r = 1; r < cfg_.size; r++) {
        targets.emplace_back(r, control_fds_[r]);
      }
    }
    for (auto& target : targets) {
      Status s = SendFrame(target.second, payload, hb_ms);
      if (!s.ok()) {
        if (!s.peer_failure()) {
          s = Status::PeerFailure(
              target.first, "control-plane broadcast to rank " +
                                std::to_string(target.first) +
                                " failed: " + s.reason());
        }
        BroadcastFaultNotice(s);
        return s;
      }
    }
    RecordControlPhase(kPhaseBroadcast, MetricsNowUs() - bcast_t0,
                       busy_cycle);
    *out = std::move(list);
    ApplyCacheVerdicts(out);
    return Status::OK();
  }

  if (TreeEnabled()) {
    return TreeWorkerCycle(my_list, hb_ms, worker_recv_ms, out);
  }

  // Worker: one send + one receive per cycle (the gather/bcast round).
  Status s = SendFrame(control_fds_[0], SerializeRequestList(my_list),
                       hb_ms);
  if (s.ok()) {
    std::string frame;
    s = RecvFrame(control_fds_[0], &frame, worker_recv_ms);
    if (s.ok()) s = ParseResponseList(frame, out);
  }
  if (!s.ok()) {
    // The coordinator itself is the casualty (or unreachable): a
    // worker's only control peer is rank 0.
    if (!s.peer_failure()) {
      s = Status::PeerFailure(0, "control-plane round with coordinator "
                                 "failed: " + s.reason());
    }
    return s;
  }
  if (out->epoch != cfg_.epoch) {
    return Status::PeerFailure(
        0, "coordinator response at stale epoch " +
               std::to_string(out->epoch) + " (current " +
               std::to_string(cfg_.epoch) + ")");
  }
  if (!out->fault_ranks.empty()) {
    // Coordinator-relayed fault notice: fail fast with its attribution
    // instead of waiting out our own wire deadline against the broken
    // ring. The full set stays in out->fault_ranks for the caller.
    GlobalEvents().Record(EventType::kFaultNotice,
                          (int32_t)out->fault_ranks[0], 1);
    return Status::PeerFailure(
        (int)out->fault_ranks[0],
        "coordinator reported peer failure (rank " +
            std::to_string(out->fault_ranks[0]) + ") at epoch " +
            std::to_string(cfg_.epoch));
  }
  ApplyCacheVerdicts(out);
  return Status::OK();
}

Status Controller::TreeCoordinatorGather(int64_t hb_ms,
                                         std::vector<int64_t>* evictions) {
  std::vector<bool> seen(cfg_.size, false);
  seen[0] = true;
  int got = 1;
  for (auto& child : tree_children_) {
    const int crank = child.first;
    std::string bundle;
    // The child's bundle carries its whole subtree, so the deadline
    // scales with the subtree's aggregate budget (failure detection in
    // tree mode is bounded by the deepest subtree, not one frame).
    Status s = RecvFrame(child.second, &bundle,
                         hb_ms <= 0 ? hb_ms : hb_ms * SubtreeSize(crank));
    if (!s.ok()) {
      if (!s.peer_failure()) {
        s = Status::PeerFailure(
            crank, "control-tree gather from rank " +
                       std::to_string(crank) + " failed: " + s.reason());
      }
      return s;
    }
    std::vector<std::string> frames;
    if (!SplitBundle(bundle, &frames)) {
      return Status::PeerFailure(
          crank, "malformed control-tree bundle from rank " +
                     std::to_string(crank));
    }
    for (auto& frame : frames) {
      RequestList rl;
      Status ps = ParseRequestList(frame, &rl);
      if (!ps.ok()) {
        return Status::PeerFailure(
            crank, "unparseable control-tree entry via rank " +
                       std::to_string(crank) + ": " + ps.reason());
      }
      if (rl.rank < 1 || rl.rank >= cfg_.size || seen[rl.rank]) {
        return Status::PeerFailure(
            crank, "control-tree entry with bad/duplicate origin rank " +
                       std::to_string(rl.rank) + " via rank " +
                       std::to_string(crank));
      }
      if (rl.epoch != cfg_.epoch) {
        return Status::PeerFailure(
            rl.rank, "rank " + std::to_string(rl.rank) +
                         " sent a stale-epoch request (epoch " +
                         std::to_string(rl.epoch) + ", current " +
                         std::to_string(cfg_.epoch) + ")");
      }
      seen[rl.rank] = true;
      got++;
      HandleCacheBits(rl, rl.rank, evictions);
      HandleRequestList(rl, rl.rank);
    }
  }
  if (got < cfg_.size) {
    // A relay forwarded a partial bundle (one of its children died):
    // the first absent origin IS the casualty — or its subtree root.
    int missing = 1;
    while (missing < cfg_.size && seen[missing]) missing++;
    return Status::PeerFailure(
        missing, "control-tree gather missing rank " +
                     std::to_string(missing) + " (" +
                     std::to_string(cfg_.size - got) + " absent)");
  }
  return Status::OK();
}

Status Controller::TreeWorkerCycle(const RequestList& my_list,
                                   int64_t hb_ms, int64_t worker_recv_ms,
                                   ResponseList* out) {
  // Gather: own entry first, then each child's bundle verbatim.
  std::string bundle;
  AppendBundleEntry(&bundle, SerializeRequestList(my_list));
  Status child_failure = Status::OK();
  for (auto& child : tree_children_) {
    const int crank = child.first;
    std::string child_bundle;
    Status s = RecvFrame(child.second, &child_bundle,
                         hb_ms <= 0 ? hb_ms : hb_ms * SubtreeSize(crank));
    if (!s.ok()) {
      // Keep gathering and FORWARD what arrived: the coordinator then
      // names the exact missing member instead of writing off this
      // whole subtree on a timeout.
      if (!s.peer_failure()) {
        s = Status::PeerFailure(
            crank, "control-tree gather from rank " +
                       std::to_string(crank) + " failed: " + s.reason());
      }
      child_failure = s;
      continue;
    }
    bundle += child_bundle;  // entries are self-delimiting
  }
  Status s = SendFrame(tree_parent_fd_, bundle, hb_ms);
  if (!s.ok()) {
    if (!s.peer_failure()) {
      s = Status::PeerFailure(
          TreeParent(cfg_.rank),
          "control-tree relay to parent failed: " + s.reason());
    }
    return s;
  }

  // Response: receive from the parent and relay down FIRST — even when
  // a child already failed. The coordinator answers a partial gather
  // with a fault notice (over the star for depth-1 workers, relayed
  // here for deeper ones), and the SURVIVING children are blocked on
  // this relay: returning early would starve them for a full timeout.
  std::string frame;
  s = RecvFrame(tree_parent_fd_, &frame, worker_recv_ms);
  if (s.ok()) s = ParseResponseList(frame, out);
  if (!s.ok()) {
    if (!child_failure.ok()) return child_failure;
    if (!s.peer_failure()) {
      s = Status::PeerFailure(
          TreeParent(cfg_.rank),
          "control-tree round with parent failed: " + s.reason());
    }
    return s;
  }
  Status relay_failure = Status::OK();
  for (auto& child : tree_children_) {
    // Send errors to an already-failed child are expected; the first
    // failure on a HEALTHY child is reported after local processing.
    Status rs = SendFrame(child.second, frame, hb_ms);
    if (!rs.ok() && relay_failure.ok()) {
      relay_failure = Status::PeerFailure(
          child.first, "control-tree relay to rank " +
                           std::to_string(child.first) +
                           " failed: " + rs.reason());
    }
  }
  if (!child_failure.ok()) return child_failure;
  if (out->epoch != cfg_.epoch) {
    return Status::PeerFailure(
        0, "coordinator response at stale epoch " +
               std::to_string(out->epoch) + " (current " +
               std::to_string(cfg_.epoch) + ")");
  }
  if (!out->fault_ranks.empty()) {
    GlobalEvents().Record(EventType::kFaultNotice,
                          (int32_t)out->fault_ranks[0], 1);
    return Status::PeerFailure(
        (int)out->fault_ranks[0],
        "coordinator reported peer failure (rank " +
            std::to_string(out->fault_ranks[0]) + ") at epoch " +
            std::to_string(cfg_.epoch));
  }
  if (!relay_failure.ok()) return relay_failure;
  ApplyCacheVerdicts(out);
  return Status::OK();
}

void Controller::BroadcastFaultNotice(const Status& failure) {
  // Best-effort: tell every still-reachable worker the epoch is dead so
  // they stop within one control round instead of one wire timeout.
  // Send errors are ignored — the target may be the casualty itself.
  if (cfg_.rank != 0) return;
  GlobalEvents().Record(EventType::kFaultNotice, failure.fault_rank(), 0);
  ResponseList notice;
  notice.epoch = cfg_.epoch;
  notice.fault_ranks.push_back(failure.fault_rank());
  std::string payload = SerializeResponseList(notice);
  for (int r = 1; r < cfg_.size; r++) {
    if (failure.fault_rank() == r) continue;
    // Short leash: the ring is already broken, don't stack full
    // timeouts per peer while tearing down.
    SendFrame(control_fds_[r], payload, /*timeout_ms=*/1000);
  }
}

}  // namespace hvdtpu
