// In-process loopback harness for the chunked/compressed ring engine.
// Builds a full socketpair mesh, runs one DataPlane per rank on its own
// thread (each plane keeps the single-caller transport contract), and
// checks the allreduce result against a bulk ring-order reference
// computed with the same ReduceInto primitive — so "pass" means
// BIT-IDENTICAL to the pre-chunking bulk-synchronous ring for every
// dtype/op, independent of chunk size. With compression on it reports
// the max absolute error vs the exact-f32 reference instead (callers
// assert the documented bf16-on-wire bound, docs/wire.md) and still
// requires every rank to hold bitwise-identical results.
//
// Exposed as a C-ABI entry (no controller/init needed) so the python
// test matrix and the TSan smoke can hammer the overlap worker and the
// compressed path directly. Reference analog: none upstream — the
// reference trusts MPI/Gloo; our transport is ours to prove.

#include <sys/socket.h>

#include <algorithm>
#include <cmath>
#include <cstring>
#include <limits>
#include <mutex>
#include <thread>
#include <vector>

#include "common.h"
#include "half.h"
#include "logging.h"
#include "ring_ops.h"
#include "wire.h"

namespace hvdtpu {
namespace {

// Deterministic per-(rank, element) fill in [-2, 2] — sign changes and
// non-dyadic values so rounding bugs cannot hide behind exact sums.
double FillValue(int rank, int64_t e) {
  uint64_t h = (uint64_t)(rank + 1) * 1315423911ull +
               (uint64_t)(e + 1) * 2654435761ull;
  return (double)(h % 2001) / 500.0 - 2.0;
}

void StoreAs(DataType dt, uint8_t* buf, int64_t idx, double v) {
  switch (dt) {
    case DataType::HVDTPU_UINT8: ((uint8_t*)buf)[idx] = (uint8_t)((int)v & 7); break;
    case DataType::HVDTPU_INT8: ((int8_t*)buf)[idx] = (int8_t)v; break;
    case DataType::HVDTPU_INT32: ((int32_t*)buf)[idx] = (int32_t)(v * 4); break;
    case DataType::HVDTPU_INT64: ((int64_t*)buf)[idx] = (int64_t)(v * 4); break;
    case DataType::HVDTPU_FLOAT16:
      ((uint16_t*)buf)[idx] = FloatToHalfBits((float)v);
      break;
    case DataType::HVDTPU_BFLOAT16:
      ((uint16_t*)buf)[idx] = FloatToBF16Bits((float)v);
      break;
    case DataType::HVDTPU_FLOAT32: ((float*)buf)[idx] = (float)v; break;
    case DataType::HVDTPU_FLOAT64: ((double*)buf)[idx] = v; break;
    case DataType::HVDTPU_BOOL: ((uint8_t*)buf)[idx] = ((int64_t)v) & 1; break;
    case DataType::HVDTPU_UINT16: ((uint16_t*)buf)[idx] = (uint16_t)(v * 4 + 8); break;
  }
}

double LoadAs(DataType dt, const uint8_t* buf, int64_t idx) {
  switch (dt) {
    case DataType::HVDTPU_UINT8: return ((const uint8_t*)buf)[idx];
    case DataType::HVDTPU_INT8: return ((const int8_t*)buf)[idx];
    case DataType::HVDTPU_INT32: return ((const int32_t*)buf)[idx];
    case DataType::HVDTPU_INT64: return (double)((const int64_t*)buf)[idx];
    case DataType::HVDTPU_FLOAT16:
      return HalfBitsToFloat(((const uint16_t*)buf)[idx]);
    case DataType::HVDTPU_BFLOAT16:
      return BF16BitsToFloat(((const uint16_t*)buf)[idx]);
    case DataType::HVDTPU_FLOAT32: return ((const float*)buf)[idx];
    case DataType::HVDTPU_FLOAT64: return ((const double*)buf)[idx];
    case DataType::HVDTPU_BOOL: return ((const uint8_t*)buf)[idx];
    case DataType::HVDTPU_UINT16: return ((const uint16_t*)buf)[idx];
  }
  return 0;
}

// The ring accumulation order for segment j (owner = rank j): the
// partial starts as rank j's own values and each later owner computes
// dst(own) OP src(partial) — replayed here with the SAME ReduceInto so
// the reference captures the exact rounding sequence.
void RingOrderReference(int ranks, int64_t count, DataType dt, ReduceOp op,
                        double postscale,
                        const std::vector<std::vector<uint8_t>>& inputs,
                        std::vector<uint8_t>* ref) {
  const int64_t elem = DataTypeSize(dt);
  ref->resize((size_t)(count * elem));
  std::vector<int64_t> seg_count(ranks), seg_off(ranks);
  int64_t q = count / ranks, r = count % ranks, off = 0;
  for (int i = 0; i < ranks; i++) {
    seg_count[i] = q + (i < r ? 1 : 0);
    seg_off[i] = off;
    off += seg_count[i];
  }
  for (int j = 0; j < ranks; j++) {
    const int64_t n = seg_count[j], o = seg_off[j] * elem;
    std::vector<uint8_t> acc(inputs[j].begin() + o,
                             inputs[j].begin() + o + n * elem);
    for (int t = 1; t < ranks; t++) {
      int owner = (j + t) % ranks;
      std::vector<uint8_t> own(inputs[owner].begin() + o,
                               inputs[owner].begin() + o + n * elem);
      ReduceInto(own.data(), acc.data(), n, dt, op);
      acc = std::move(own);
    }
    std::memcpy(ref->data() + o, acc.data(), (size_t)(n * elem));
  }
  // DataPlane::Allreduce applies `postscale` verbatim (the AVERAGE
  // 1/size division happens in operations.cc, above this layer).
  ScaleBuffer(ref->data(), count, dt, postscale);
}

// Integer-valued fill in [-4, 4]: every partial sum is exact in f32
// (and in bf16, for the magnitudes the selftests use), so ANY
// association order — flat ring, hierarchical, compressed — must land
// on bit-identical results. The hierarchical bit-exactness pin rides
// this: float addition is non-associative in general, but exact
// arithmetic erases the association, leaving only real bugs visible.
double ExactFillValue(int rank, int64_t e) {
  uint64_t h = (uint64_t)(rank + 1) * 1315423911ull +
               (uint64_t)(e + 1) * 2654435761ull;
  return (double)((int64_t)(h % 9) - 4);
}

// Serializes concurrent selftests: the ring knobs are process-global,
// and two overlapping runs with different framing would cross wires.
std::mutex g_selftest_mutex;

// Full socketpair mesh for `ranks` planes; false on socketpair failure
// (already-created fds closed).
bool BuildMesh(int ranks, std::vector<std::vector<int>>* fds) {
  fds->assign(ranks, std::vector<int>(ranks, -1));
  for (int i = 0; i < ranks; i++) {
    for (int j = i + 1; j < ranks; j++) {
      int sv[2];
      if (socketpair(AF_UNIX, SOCK_STREAM, 0, sv) != 0) {
        for (auto& row : *fds) {
          for (int fd : row) TcpClose(fd);
        }
        return false;
      }
      (*fds)[i][j] = sv[0];
      (*fds)[j][i] = sv[1];
    }
  }
  return true;
}

// K socketpair meshes (stripe channels): meshes[c][rank] is one rank's
// fd row for channel c. False on failure (everything built so far is
// closed).
bool BuildChannelMeshes(int ranks, int channels,
                        std::vector<std::vector<std::vector<int>>>* m) {
  m->resize(channels);
  for (int c = 0; c < channels; c++) {
    if (!BuildMesh(ranks, &(*m)[c])) {
      for (int p = 0; p < c; p++) {
        for (auto& row : (*m)[p]) {
          for (int fd : row) TcpClose(fd);
        }
      }
      return false;
    }
  }
  return true;
}

// Hand rank r its fd rows: channel 0 into the DataPlane ctor, channels
// 1.. via AdoptExtraChannelFds — exactly how the controller wires the
// production mesh.
DataPlane MakePlane(int r, int ranks,
                    std::vector<std::vector<std::vector<int>>>& meshes) {
  DataPlane dp(r, ranks, std::move(meshes[0][r]));
  if (meshes.size() > 1) {
    std::vector<std::vector<int>> extra;
    extra.reserve(meshes.size() - 1);
    for (size_t c = 1; c < meshes.size(); c++) {
      extra.push_back(std::move(meshes[c][r]));
    }
    dp.AdoptExtraChannelFds(std::move(extra));
  }
  return dp;
}

}  // namespace
}  // namespace hvdtpu

using namespace hvdtpu;

extern "C" {

// Run one in-process allreduce over `ranks` socketpair-connected data
// planes with explicit knobs. `channels` = stripe sockets per pair
// (HOROVOD_WIRE_CHANNELS; <= 1 is the single-channel engine) —
// striped runs must land on the SAME bits as K=1, because the chunk
// schedule only changes which socket carries a chunk, never the
// reduce order. `compression`: 0 none, 1 bf16, 2 int8 blockwise.
// Returns 0 on success; negative codes:
//   -1 bad arguments      -2 socketpair() failed
//   -3 a rank's Allreduce returned an error status
//   -4 uncompressed result not bit-identical to the ring-order reference
//   -5 compressed results differ BETWEEN ranks (must be rank-consistent)
// `max_abs_err_out` (optional) receives the max |result - reference|
// across all ranks and elements; with compression OFF a passing run
// always writes 0.0.
int hvdtpu_ring_selftest(int ranks, int64_t count, int dtype, int reduce_op,
                         int64_t chunk_bytes, int compression,
                         double postscale, int channels,
                         double* max_abs_err_out) {
  if (max_abs_err_out != nullptr) *max_abs_err_out = 0.0;
  if (ranks < 1 || ranks > 64 || count < 0 || dtype < 0 || dtype > 9 ||
      channels > kMaxWireChannels) {
    return -1;
  }
  if (channels < 1) channels = 1;
  DataType dt = (DataType)dtype;
  ReduceOp op = (ReduceOp)reduce_op;
  const int64_t elem = DataTypeSize(dt);

  std::lock_guard<std::mutex> lock(g_selftest_mutex);
  const int64_t saved_chunk = RingChunkBytes();
  const int saved_comp = WireCodec();
  const int64_t saved_chan = WireChannels();
  SetRingChunkBytes(chunk_bytes);
  SetWireCodec(compression);
  SetWireChannels(channels);

  // Full socketpair mesh per channel (the ring only uses neighbors,
  // but Subset and future paths index arbitrary peers).
  std::vector<std::vector<std::vector<int>>> meshes;
  if (!BuildChannelMeshes(ranks, channels, &meshes)) {
    SetRingChunkBytes(saved_chunk);
    SetWireCodec(saved_comp);
    SetWireChannels(saved_chan);
    return -2;
  }

  std::vector<std::vector<uint8_t>> inputs(ranks);
  for (int r = 0; r < ranks; r++) {
    inputs[r].resize((size_t)(count * elem));
    for (int64_t e = 0; e < count; e++) {
      StoreAs(dt, inputs[r].data(), e, FillValue(r, e));
    }
  }
  std::vector<uint8_t> ref;
  RingOrderReference(ranks, count, dt, op, postscale, inputs, &ref);

  std::vector<std::vector<uint8_t>> results = inputs;  // reduced in place
  std::vector<Status> statuses(ranks);
  {
    // Each plane owns its fd rows and its own worker pool; threads
    // join (and workers drain) before the results are inspected.
    std::vector<std::thread> threads;
    threads.reserve(ranks);
    for (int r = 0; r < ranks; r++) {
      threads.emplace_back([&, r] {
        DataPlane dp = MakePlane(r, ranks, meshes);
        statuses[r] =
            dp.Allreduce(results[r].data(), count, dt, op, postscale);
      });
    }
    for (auto& t : threads) t.join();
  }
  SetRingChunkBytes(saved_chunk);
  SetWireCodec(saved_comp);
  SetWireChannels(saved_chan);

  for (int r = 0; r < ranks; r++) {
    if (!statuses[r].ok()) {
      LOG_WARN("ring selftest rank %d failed: %s", r,
               statuses[r].reason().c_str());
      return -3;
    }
  }
  double max_err = 0.0;
  int rc = 0;
  for (int r = 0; r < ranks; r++) {
    for (int64_t e = 0; e < count; e++) {
      double err =
          std::fabs(LoadAs(dt, results[r].data(), e) -
                    LoadAs(dt, ref.data(), e));
      max_err = std::max(max_err, err);
    }
    if (std::memcmp(results[r].data(), ref.data(), ref.size()) != 0) {
      // The compressed path is bf16-rounded by design; every other
      // configuration must be bit-identical to the reference.
      bool compressed_path = compression != 0 &&
                             dt == DataType::HVDTPU_FLOAT32 &&
                             (op == ReduceOp::SUM ||
                              op == ReduceOp::AVERAGE);
      if (!compressed_path) rc = -4;
    }
    if (r > 0 && std::memcmp(results[r].data(), results[0].data(),
                             results[r].size()) != 0) {
      rc = -5;  // ranks must agree bitwise, compressed or not
    }
  }
  if (max_abs_err_out != nullptr) *max_abs_err_out = max_err;
  return rc;
}

// In-process loopback proof of the CROSS-PLANE hierarchical allreduce
// (DataPlane::HierarchicalAllreduce) at an emulated `ranks/local_size`
// slices x `local_size` ranks topology. `compression`: 0 = none,
// 1 = every hop (the global HOROVOD_WIRE_COMPRESSION path), 2 = the
// inter-slice hop only (HOROVOD_CROSS_PLANE_COMPRESSION). `exact_fill`
// != 0 fills with small integers whose partial sums are exact in f32
// AND bf16 — under exact arithmetic every association order collapses
// to the same bits, so the hierarchical result must be BIT-IDENTICAL
// to the flat ring-order reference (rc -4 otherwise; enforced for
// compression == 0). Ranks must agree bitwise in every configuration
// (rc -5). `max_abs_err_out` receives max |result - flat reference|
// for the compressed-bound assertions (docs/wire.md: N^2 * 2^-7 on
// values in [-2, 2]).
int hvdtpu_hier_selftest(int ranks, int local_size, int64_t count,
                         int dtype, int reduce_op, int64_t chunk_bytes,
                         int compression, int exact_fill,
                         double postscale, int channels,
                         double* max_abs_err_out) {
  if (max_abs_err_out != nullptr) *max_abs_err_out = 0.0;
  if (ranks < 1 || ranks > 64 || count < 0 || dtype < 0 || dtype > 9 ||
      local_size < 1 || ranks % local_size != 0 ||
      channels > kMaxWireChannels) {
    return -1;
  }
  if (channels < 1) channels = 1;
  DataType dt = (DataType)dtype;
  ReduceOp op = (ReduceOp)reduce_op;
  const int64_t elem = DataTypeSize(dt);

  std::lock_guard<std::mutex> lock(g_selftest_mutex);
  const int64_t saved_chunk = RingChunkBytes();
  const int saved_comp = WireCodec();
  const int64_t saved_chan = WireChannels();
  SetRingChunkBytes(chunk_bytes);
  SetWireCodec(compression == 1 ? 1 : 0);
  SetWireChannels(channels);
  const bool compress_cross = compression == 2;

  std::vector<std::vector<std::vector<int>>> meshes;
  if (!BuildChannelMeshes(ranks, channels, &meshes)) {
    SetRingChunkBytes(saved_chunk);
    SetWireCodec(saved_comp);
    SetWireChannels(saved_chan);
    return -2;
  }

  std::vector<std::vector<uint8_t>> inputs(ranks);
  for (int r = 0; r < ranks; r++) {
    inputs[r].resize((size_t)(count * elem));
    for (int64_t e = 0; e < count; e++) {
      StoreAs(dt, inputs[r].data(), e,
              exact_fill ? ExactFillValue(r, e) : FillValue(r, e));
    }
  }
  // The FLAT ring-order reference: with exact fills any association is
  // bit-identical to it; with real fills it anchors the error bound.
  std::vector<uint8_t> ref;
  RingOrderReference(ranks, count, dt, op, postscale, inputs, &ref);

  std::vector<std::vector<uint8_t>> results = inputs;
  std::vector<Status> statuses(ranks);
  {
    std::vector<std::thread> threads;
    threads.reserve(ranks);
    for (int r = 0; r < ranks; r++) {
      threads.emplace_back([&, r] {
        DataPlane dp = MakePlane(r, ranks, meshes);
        statuses[r] = dp.HierarchicalAllreduce(
            results[r].data(), count, dt, op, local_size, postscale,
            compress_cross);
      });
    }
    for (auto& t : threads) t.join();
  }
  SetRingChunkBytes(saved_chunk);
  SetWireCodec(saved_comp);
  SetWireChannels(saved_chan);

  for (int r = 0; r < ranks; r++) {
    if (!statuses[r].ok()) return -3;
  }
  double max_err = 0.0;
  int rc = 0;
  for (int r = 0; r < ranks; r++) {
    for (int64_t e = 0; e < count; e++) {
      max_err = std::max(max_err,
                         std::fabs(LoadAs(dt, results[r].data(), e) -
                                   LoadAs(dt, ref.data(), e)));
    }
    if (exact_fill && compression == 0 &&
        std::memcmp(results[r].data(), ref.data(), ref.size()) != 0) {
      rc = -4;  // exact arithmetic: association cannot explain a diff
    }
    if (r > 0 && std::memcmp(results[r].data(), results[0].data(),
                             results[r].size()) != 0) {
      rc = -5;  // ranks must agree bitwise, compressed or not
    }
  }
  if (max_abs_err_out != nullptr) *max_abs_err_out = max_err;
  return rc;
}

// int8 codec roundtrip (encode -> wire image -> decode-with-postscale)
// over a caller buffer, for the Python-side numerics pins the striped
// matrix can't reach (NaN poison, scale/2 bounds): returns the wire
// image length, or -1 on bad args. `out` receives the decoded segment.
int64_t hvdtpu_int8_roundtrip(const float* src, int64_t n, float* out,
                              double postscale) {
  if (src == nullptr || out == nullptr || n < 0) return -1;
  const int64_t wlen = Int8WireLen(n);
  std::vector<uint8_t> wire((size_t)wlen);
  EncodeInt8(wire.data(), src, n);
  DecodeScaleInt8Span(out, wire.data(), 0, wlen, n, postscale);
  return wlen;
}

// Pin the explicit-SIMD kernels (csrc/simd.h) BIT-IDENTICAL to the
// scalar reference paths across unaligned start offsets and tail
// lengths, including non-finite values through the bf16 codec. Runs
// each kernel twice — HOROVOD_SIMD on, then forced scalar — over the
// same bytes and memcmps. Returns 0, or a negative code naming the
// first divergent kernel:
//   -2 ReduceInto f32 SUM        -3 ReduceInto bf16 SUM
//   -4 EncodeBF16                -5 DecodeAccumBF16
//   -6 DecodeScaleBF16           -7 ScaleBuffer f32
int hvdtpu_simd_selftest() {
  std::lock_guard<std::mutex> lock(g_selftest_mutex);
  const bool saved = SimdEnabled();
  const int64_t lens[] = {0, 1, 7, 8, 9, 15, 16, 17, 31, 64, 1000, 1025};
  int rc = 0;
  // Base buffers with deterministic fills plus specials the codec
  // rounding must preserve (signed zero, inf, NaN, denormal).
  const int64_t kMax = 1025 + 16;
  std::vector<float> fa(kMax), fb(kMax);
  std::vector<uint16_t> ha(kMax), hb(kMax);
  for (int64_t i = 0; i < kMax; i++) {
    fa[i] = (float)FillValue(0, i);
    fb[i] = (float)FillValue(1, i);
    ha[i] = FloatToBF16Bits((float)FillValue(2, i));
    hb[i] = FloatToBF16Bits((float)FillValue(3, i));
  }
  const float specials[] = {0.0f, -0.0f, 1e30f, -1e30f,
                            std::numeric_limits<float>::infinity(),
                            -std::numeric_limits<float>::infinity(),
                            std::numeric_limits<float>::quiet_NaN(),
                            1e-42f};
  for (size_t i = 0; i < sizeof(specials) / sizeof(specials[0]); i++) {
    fa[7 + 13 * i] = specials[i];
    fb[11 + 17 * i] = specials[i];
  }
  for (int64_t n : lens) {
    for (int64_t off = 0; off < 9 && rc == 0; off++) {
      // (1) ReduceInto f32 SUM.
      {
        std::vector<float> d1(fa.begin() + off, fa.begin() + off + n);
        std::vector<float> d2 = d1;
        SetSimdEnabled(true);
        ReduceInto(d1.data(), fb.data() + off, n,
                   DataType::HVDTPU_FLOAT32, ReduceOp::SUM);
        SetSimdEnabled(false);
        ReduceInto(d2.data(), fb.data() + off, n,
                   DataType::HVDTPU_FLOAT32, ReduceOp::SUM);
        if (n && std::memcmp(d1.data(), d2.data(), (size_t)n * 4)) {
          rc = -2;
          break;
        }
      }
      // (2) ReduceInto bf16 SUM.
      {
        std::vector<uint16_t> d1(ha.begin() + off, ha.begin() + off + n);
        std::vector<uint16_t> d2 = d1;
        SetSimdEnabled(true);
        ReduceInto(d1.data(), hb.data() + off, n,
                   DataType::HVDTPU_BFLOAT16, ReduceOp::SUM);
        SetSimdEnabled(false);
        ReduceInto(d2.data(), hb.data() + off, n,
                   DataType::HVDTPU_BFLOAT16, ReduceOp::SUM);
        if (n && std::memcmp(d1.data(), d2.data(), (size_t)n * 2)) {
          rc = -3;
          break;
        }
      }
      // (3) EncodeBF16 (specials included: NaN quieting, inf carry).
      {
        std::vector<uint16_t> e1(n ? n : 1), e2(n ? n : 1);
        SetSimdEnabled(true);
        EncodeBF16(e1.data(), fa.data() + off, n);
        SetSimdEnabled(false);
        EncodeBF16(e2.data(), fa.data() + off, n);
        if (n && std::memcmp(e1.data(), e2.data(), (size_t)n * 2)) {
          rc = -4;
          break;
        }
      }
      // (4) DecodeAccumBF16.
      {
        std::vector<float> d1(fa.begin() + off, fa.begin() + off + n);
        std::vector<float> d2 = d1;
        SetSimdEnabled(true);
        DecodeAccumBF16(d1.data(), ha.data() + off, n);
        SetSimdEnabled(false);
        DecodeAccumBF16(d2.data(), ha.data() + off, n);
        if (n && std::memcmp(d1.data(), d2.data(), (size_t)n * 4)) {
          rc = -5;
          break;
        }
      }
      // (5) DecodeScaleBF16, identity and folded postscale.
      for (double post : {1.0, 0.25, 1.0 / 3.0}) {
        std::vector<float> d1(n ? n : 1), d2(n ? n : 1);
        SetSimdEnabled(true);
        DecodeScaleBF16(d1.data(), ha.data() + off, n, post);
        SetSimdEnabled(false);
        DecodeScaleBF16(d2.data(), ha.data() + off, n, post);
        if (n && std::memcmp(d1.data(), d2.data(), (size_t)n * 4)) {
          rc = -6;
          break;
        }
      }
      if (rc != 0) break;
      // (6) ScaleBuffer f32 (the double-multiply rounding contract).
      {
        std::vector<float> d1(fa.begin() + off, fa.begin() + off + n);
        std::vector<float> d2 = d1;
        SetSimdEnabled(true);
        ScaleBuffer(d1.data(), n, DataType::HVDTPU_FLOAT32, 0.3);
        SetSimdEnabled(false);
        ScaleBuffer(d2.data(), n, DataType::HVDTPU_FLOAT32, 0.3);
        if (n && std::memcmp(d1.data(), d2.data(), (size_t)n * 4)) {
          rc = -7;
          break;
        }
      }
    }
    if (rc != 0) break;
  }
  SetSimdEnabled(saved);
  return rc;
}

}  // extern "C"
