// Coordination protocol: rank 0 is the coordinator; every cycle all workers
// send a RequestList (tensors newly ready on that rank), the coordinator
// waits until a tensor is ready on ALL ranks, fuses ready tensors into
// Responses, and broadcasts an ordered ResponseList that every rank executes
// identically.
// Reference analog: horovod/common/controller.h (Controller::
// ComputeResponseList, FuseResponses) + mpi_controller / gloo_controller for
// the transport. Rebuilt over the TCP control plane in wire.h; the reference's
// MPI_Gatherv round becomes a frame gather over per-worker sockets.

#ifndef HVDTPU_CONTROLLER_H
#define HVDTPU_CONTROLLER_H

#include <chrono>
#include <deque>
#include <memory>
#include <unordered_map>
#include <unordered_set>

#include "message.h"
#include "process_set.h"
#include "response_cache.h"
#include "ring_ops.h"

namespace hvdtpu {

struct ControllerConfig {
  int rank = 0;
  int size = 1;
  std::string controller_addr = "127.0.0.1";
  int controller_port = 0;
  int64_t fusion_threshold_bytes = 64 * 1024 * 1024;
  // Response cache capacity in entries (HOROVOD_CACHE_CAPACITY; 0 disables).
  int64_t cache_capacity = 1024;
  double stall_warning_secs = 60.0;
  bool stall_check_enabled = true;
  // Membership epoch of this ring generation (0 = fresh init; bumped by
  // hvdtpu_reinit). Hellos and control frames from any other epoch are
  // rejected — the fence that keeps a half-dead previous-generation
  // rank out of the re-formed ring (docs/elastic.md).
  int64_t epoch = 0;
  // Control-plane liveness deadline: every negotiation cycle doubles as
  // a heartbeat (idle workers still send an empty RequestList each
  // cycle), so "no control frame for this long" marks the peer dead.
  // 0 = use HOROVOD_WIRE_TIMEOUT_MS (the common case); the separate
  // knob (HOROVOD_HEARTBEAT_TIMEOUT_MS) lets operators detect control-
  // plane death faster than the data-plane transfer bound.
  int64_t heartbeat_timeout_ms = 0;
  // Rendezvous patience at Initialize (HOROVOD_START_TIMEOUT seconds):
  // launch stragglers are expected, so bootstrap I/O uses this instead
  // of the steady-state wire deadline.
  int64_t start_timeout_ms = 60000;
  // Readiness for a tensor on process set S waits only on S's members.
  // Not owned; outlives the controller (lives in GlobalState).
  const ProcessSetTable* process_sets = nullptr;
  // HOROVOD_CONTROLLER=mpi: route control frames AND ring data through
  // the registered external message transport (wire.h) — zero TCP
  // sockets, for firewalled MPI-only fabrics.
  bool use_external_transport = false;
  // HOROVOD_CONTROL_TREE=<fanout>: tree-structured negotiation round.
  // The flat star gather/broadcast is O(N) sequential frames at the
  // coordinator — the dominant control-plane cost at 64-256 ranks
  // (docs/scale.md scaling curves). fanout >= 2 arranges workers as a
  // fanout-ary tree rooted at rank 0: interior workers gather their
  // children's frame bundles and relay one concatenated bundle up, and
  // relay the response broadcast down, so the coordinator touches only
  // `fanout` sockets per cycle. 0/1 = flat (default). Fault
  // attribution coarsens to the first missing subtree member (the
  // probe sweep and post-mortem refine it); fault NOTICES still ride
  // the star, which every rank keeps for exactly that.
  int tree_fanout = 0;
  // Stripe sockets to establish per data-plane neighbor pair
  // (HOROVOD_WIRE_CHANNELS, wire.h): rendezvous builds K connections
  // per pair — the channel id rides the data-plane hello, epoch-fenced
  // like the rank — and every reinit rebuilds all K per survivor pair.
  // 1 on the external transport (mailbox fds carry no channel id).
  int wire_channels = 1;
};

class Controller {
 public:
  explicit Controller(ControllerConfig cfg);
  ~Controller();

  // Rendezvous with the coordinator, exchange data-plane addresses, and
  // build the full-mesh data-plane sockets. Blocking; collective.
  Status Initialize();

  // In-process harness entry (csrc/simworld.cc): adopt pre-connected
  // socketpair fds instead of the TCP rendezvous. `control_fds` uses
  // the control_fds_ layout (coordinator: fd per worker; worker: one
  // fd to the coordinator). Tree edges between two WORKERS arrive in
  // `tree_parent_fd` / `tree_children` (rank, fd); edges that touch
  // rank 0 are resolved from the star fds internally, exactly as the
  // TCP path shares them. All fds (including `peer_fds`) are owned by
  // the controller/data plane from here on.
  Status InitializeFromFds(std::vector<int> control_fds,
                           std::vector<int> peer_fds,
                           int tree_parent_fd,
                           std::vector<std::pair<int, int>> tree_children);

  // Tree topology helpers (rank numbering; heap layout rooted at 0).
  bool TreeEnabled() const {
    return cfg_.tree_fanout >= 2 && cfg_.size > 2 &&
           !cfg_.use_external_transport;
  }
  int TreeParent(int r) const { return (r - 1) / cfg_.tree_fanout; }
  std::vector<int> TreeChildren(int r) const;
  int SubtreeSize(int r) const;  // members of the subtree rooted at r

  // One negotiation round (blocking, collective): submit this rank's new
  // requests, get back the globally-agreed ResponseList.
  // `should_shutdown`: this rank wants to shut down (sticky at coordinator;
  // the returned list has .shutdown once ALL ranks have asked).
  Status ComputeResponseList(std::vector<Request> requests,
                             bool should_shutdown, ResponseList* out);

  DataPlane* data_plane() { return data_plane_.get(); }
  int rank() const { return cfg_.rank; }
  int size() const { return cfg_.size; }
  const ResponseCache& response_cache() const { return cache_; }

  // Coordinator only: adopt autotuned knobs locally (fusion decisions are
  // made here) and piggyback them on every subsequent ResponseList.
  // ring_chunk_bytes/wire_compression/wire_channels keep their unset
  // sentinels (-1) until the tuner actually moves them, so
  // non-autotuned runs broadcast nothing and workers keep their
  // env-derived values. wire_compression carries the full codec mode
  // (0 off / 1 bf16 / 2 int8); wire_channels the active stripe width.
  void SetAutotunedParams(int64_t fusion_bytes, double cycle_ms,
                          int64_t ring_chunk_bytes = -1,
                          int32_t wire_compression = -1,
                          int32_t hier_split = -1,
                          int32_t wire_channels = -1) {
    cfg_.fusion_threshold_bytes = fusion_bytes;
    bcast_fusion_bytes_ = fusion_bytes;
    bcast_cycle_ms_ = cycle_ms;
    bcast_ring_chunk_bytes_ = ring_chunk_bytes;
    bcast_wire_compression_ = wire_compression;
    bcast_hier_split_ = hier_split;
    bcast_wire_channels_ = wire_channels;
  }

 private:
  // Split this rank's ready requests into cache-hit bits, invalid bits, and
  // full requests (the outgoing RequestList for this cycle).
  RequestList BuildRequestList(std::vector<Request> requests,
                               bool should_shutdown);
  // Coordinator side: fold one rank's cache bits + evictions into the
  // pending-bit table; full requests go through HandleRequestList.
  void HandleCacheBits(const RequestList& list, int from_rank,
                       std::vector<int64_t>* evictions);
  // Coordinator side: completed positions (all set members submitted the bit
  // or joined), in ascending position order, grouped for fusion.
  void CollectCacheHits(ResponseList* list);
  // All ranks: apply broadcast evictions (requeuing any in-flight hit of an
  // evicted position), rebuild hit Responses from the local cache copy, and
  // insert freshly negotiated responses. `out` gains the hit responses.
  void ApplyCacheVerdicts(ResponseList* out);
  // Coordinator side: fold one rank's RequestList into the message table,
  // tracking newly all-ready tensors in arrival order.
  void HandleRequestList(const RequestList& list, int from_rank);
  // Coordinator side: build fused responses from the ready queue.
  // Reference analog: Controller::FuseResponses.
  ResponseList FuseResponses();
  Response BuildResponse(const std::string& name);
  void CheckForStalledTensors();  // reference: common/stall_inspector.cc
  // Coordinator only, best-effort: push a fault-notice ResponseList
  // (nonempty fault_ranks) to every still-reachable worker so ranks
  // idling in the control round fail fast with the coordinator's
  // attribution. Ranks stuck inside a data-plane transfer still detect
  // via their own wire deadline/EOF.
  void BroadcastFaultNotice(const Status& failure);

  // Tree-mode cycle halves (coordinator unpacks bundles; workers
  // gather children, relay up, relay the response down).
  Status TreeCoordinatorGather(int64_t hb_ms,
                               std::vector<int64_t>* evictions);
  Status TreeWorkerCycle(const RequestList& my_list, int64_t hb_ms,
                         int64_t worker_recv_ms, ResponseList* out);

  ControllerConfig cfg_;
  std::unique_ptr<DataPlane> data_plane_;
  // Worker: control_fds_[0] = socket to coordinator.
  // Coordinator: control_fds_[r] = socket to worker r (r >= 1).
  std::vector<int> control_fds_;
  // Tree edges (HOROVOD_CONTROL_TREE). Fds shared with the star
  // (every edge touching rank 0) are NOT in tree_owned_fds_ — the
  // destructor closes each fd exactly once.
  int tree_parent_fd_ = -1;
  std::vector<std::pair<int, int>> tree_children_;  // (child rank, fd)
  std::vector<int> tree_owned_fds_;

  // --- Coordinator state (rank 0 only) ---
  struct PendingTensor {
    std::vector<Request> requests;          // one per reporting rank
    std::unordered_set<int32_t> ranks_seen;
    std::chrono::steady_clock::time_point first_seen;
    int64_t first_round = 0;  // negotiation round of the first request
    bool queued = false;  // already pushed on ready_queue_
  };
  // A tensor is ready once every member of its process set has either
  // requested it or joined. Reference analog: controller.cc join handling +
  // per-process-set controller state.
  void MaybePromote(const std::string& key, PendingTensor& pt);
  std::vector<int32_t> MembersOf(int32_t process_set_id) const;
  // message_table_ key: tensor name + '\x1f' + process_set_id (disjoint sets
  // may negotiate same-named tensors concurrently).
  static std::string TableKey(const Request& req);
  std::unordered_map<std::string, PendingTensor> message_table_;
  std::deque<std::string> ready_queue_;  // all-ranks-ready, FIFO order
  // Atomic grouped negotiation (reference analog: group_table.cc): ready
  // group members are held back here until the WHOLE group is ready on
  // every rank, then pushed onto ready_queue_ together so they fuse into
  // one pure response regardless of the fusion threshold.
  struct GroupState {
    int32_t size = 0;
    std::vector<std::string> ready_keys;  // coordinator insertion order
  };
  std::unordered_map<std::string, GroupState> group_table_;
  std::vector<bool> shutdown_flags_;
  std::unordered_set<int32_t> joined_ranks_;
  int32_t last_joined_rank_ = -1;
  int64_t bcast_fusion_bytes_ = 0;  // 0 = nothing to broadcast
  double bcast_cycle_ms_ = 0;
  int64_t bcast_ring_chunk_bytes_ = -1;  // -1 = nothing to broadcast
  int32_t bcast_wire_compression_ = -1;
  int32_t bcast_hier_split_ = -1;
  int32_t bcast_wire_channels_ = -1;
  std::chrono::steady_clock::time_point last_stall_check_;

  // --- Response cache (all ranks; state bit-identical by construction) ---
  ResponseCache cache_;
  // Bits this rank has submitted but not yet seen complete: pos -> the full
  // request to resubmit if the position is evicted mid-flight.
  std::unordered_map<int32_t, Request> inflight_hits_;
  std::vector<Request> resubmit_;  // queued for next cycle
  // Coordinator only: pos -> ranks that have submitted the bit, plus when
  // the first bit arrived (stall reporting).
  struct PendingBits {
    std::unordered_set<int32_t> ranks;
    std::chrono::steady_clock::time_point first_seen;
    int64_t first_round = 0;  // negotiation round of the first bit
    int32_t last_rank = -1;  // most recent bit's sender (straggler table)
  };
  std::unordered_map<int32_t, PendingBits> bit_table_;
  // Coordinator negotiation-round counter: straggler attribution only
  // records arrivals that completed in a LATER round than they opened —
  // within one round the gather processes ranks in fixed order, so
  // "last arrival" would just mean "highest rank number".
  int64_t round_ = 0;
};

}  // namespace hvdtpu

#endif  // HVDTPU_CONTROLLER_H
