// CPU float16 / bfloat16 conversion for reductions on the host data plane.
// Reference analog: horovod/common/half.h (HalfBits2Float / Float2HalfBits),
// used so MPI/Gloo CPU paths can sum fp16 tensors. Rewritten: bit-twiddling
// fp16<->fp32, and trivial bf16 (truncation with round-to-nearest-even).

#ifndef HVDTPU_HALF_H
#define HVDTPU_HALF_H

#include <cstdint>
#include <cstring>

namespace hvdtpu {

inline float HalfBitsToFloat(uint16_t h) {
  uint32_t sign = (uint32_t)(h >> 15) & 1u;
  uint32_t exp = (uint32_t)(h >> 10) & 0x1Fu;
  uint32_t mant = (uint32_t)h & 0x3FFu;
  uint32_t f;
  if (exp == 0) {
    if (mant == 0) {
      f = sign << 31;  // +-0
    } else {
      // subnormal: normalize
      int e = -1;
      uint32_t m = mant;
      do {
        e++;
        m <<= 1;
      } while ((m & 0x400u) == 0);
      f = (sign << 31) | ((uint32_t)(127 - 15 - e) << 23) |
          ((m & 0x3FFu) << 13);
    }
  } else if (exp == 0x1F) {
    f = (sign << 31) | 0x7F800000u | (mant << 13);  // inf/nan
  } else {
    f = (sign << 31) | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  std::memcpy(&out, &f, 4);
  return out;
}

inline uint16_t FloatToHalfBits(float x) {
  uint32_t f;
  std::memcpy(&f, &x, 4);
  uint32_t sign = (f >> 31) & 1u;
  int32_t exp = (int32_t)((f >> 23) & 0xFFu) - 127 + 15;
  uint32_t mant = f & 0x7FFFFFu;
  if (((f >> 23) & 0xFFu) == 0xFFu) {  // inf/nan
    return (uint16_t)((sign << 15) | 0x7C00u | (mant ? 0x200u : 0));
  }
  if (exp >= 0x1F) {  // overflow -> inf
    return (uint16_t)((sign << 15) | 0x7C00u);
  }
  if (exp <= 0) {  // subnormal or zero
    if (exp < -10) return (uint16_t)(sign << 15);
    mant |= 0x800000u;
    int shift = 14 - exp;
    uint32_t sub = mant >> shift;
    // round to nearest even
    uint32_t rem = mant & ((1u << shift) - 1);
    uint32_t half = 1u << (shift - 1);
    if (rem > half || (rem == half && (sub & 1u))) sub++;
    return (uint16_t)((sign << 15) | sub);
  }
  uint16_t out = (uint16_t)((sign << 15) | ((uint32_t)exp << 10) | (mant >> 13));
  // round to nearest even on the dropped 13 bits
  uint32_t rem = mant & 0x1FFFu;
  if (rem > 0x1000u || (rem == 0x1000u && (out & 1u))) out++;
  return out;
}

inline float BF16BitsToFloat(uint16_t b) {
  uint32_t f = (uint32_t)b << 16;
  float out;
  std::memcpy(&out, &f, 4);
  return out;
}

inline uint16_t FloatToBF16Bits(float x) {
  uint32_t f;
  std::memcpy(&f, &x, 4);
  if ((f & 0x7F800000u) == 0x7F800000u && (f & 0x7FFFFFu)) {
    return (uint16_t)((f >> 16) | 0x40u);  // quiet nan
  }
  // round to nearest even
  uint32_t lsb = (f >> 16) & 1u;
  f += 0x7FFFu + lsb;
  return (uint16_t)(f >> 16);
}

}  // namespace hvdtpu

#endif  // HVDTPU_HALF_H
