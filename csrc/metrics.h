// Runtime metrics registry for the native core: per-op-class counters,
// latency histograms, fusion/cycle/cache accounting, and coordinator-side
// straggler attribution, exported as one JSON snapshot through
// hvdtpu_metrics_snapshot() (operations.cc).
//
// Reference analog: none in-core — upstream Horovod's only windows are the
// Chrome timeline and the autotune log. This registry is the live-counter
// layer those artifacts lack: everything the background loop already
// computes to make decisions (response-cache verdicts, fusion packing,
// cycle pacing, arrival order at the coordinator) becomes observable.
//
// Concurrency: recorders are called from the background coordination
// thread and (enqueue timestamps aside) never from API threads; the
// snapshot reader runs on an arbitrary API thread. All counters are
// relaxed atomics — a snapshot is a consistent-enough view, not a
// linearizable one — except the per-rank straggler table, which is small
// and mutex-guarded.

#ifndef HVDTPU_METRICS_H
#define HVDTPU_METRICS_H

#include <atomic>
#include <cstdarg>
#include <cstdint>
#include <mutex>
#include <string>
#include <vector>

namespace hvdtpu {

int64_t MetricsNowUs();  // steady-clock microseconds (monotonic)

// Control-plane phases profiled for large-world scaling (docs/scale.md):
// each is an O(N) suspect in the coordinator/elastic machinery, and the
// per-phase histograms below are how the scaling curves indict (or
// clear) them at 64-256 ranks. kPhaseParoleFreeze is recorded from
// Python (common/elastic.py) through the hvdtpu_record_phase C-ABI —
// the parole door lives above the core but its latency belongs on the
// same profile.
enum ControlPhase : int32_t {
  kPhaseRendezvous = 0,  // Controller::Initialize bootstrap fan-in
  kPhaseGather,          // coordinator: per-cycle request gather
  kPhaseBroadcast,       // coordinator: per-cycle response broadcast
  kPhaseProbeSweep,      // DataPlane::ProbeDeadPeers fault sweep
  kPhaseReinit,          // hvdtpu_reinit ring re-formation
  kPhaseParoleFreeze,    // parole-door freeze/poll (python side)
  kPhaseCount
};
const char* ControlPhaseName(int phase);

// Record one phase duration into the metrics histogram AND the event
// ring (EventType::kPhase) — one call keeps the two views consistent.
// `emit_event=false` updates only the histogram: the coordinator's
// idle negotiation cycles still belong on the latency profile, but two
// ring events per cycle would lap the flight recorder in seconds and
// evict the forensic tail the black box exists to keep.
void RecordControlPhase(int phase, int64_t dur_us, bool emit_event = true);

// Measure-then-format printf append (definition rationale in
// metrics.cc): the shared primitive for every JSON producer — fixed
// stack buffers silently truncate, i.e. corrupt, the output.
void AppendFmtV(std::string& out, const char* fmt, va_list args);

// Log2-bucketed microsecond histogram: bucket i holds values in
// [2^i, 2^(i+1)). Percentiles are read off the bucket CDF at upper bucket
// bounds — exact enough for latency triage, constant memory, lock-free.
class LatencyHistogram {
 public:
  static constexpr int kBuckets = 40;  // covers ~2^39 us (~6 days)

  void Record(int64_t us);
  void Reset();
  int64_t count() const { return count_.load(std::memory_order_relaxed); }
  // {"count":..,"sum_us":..,"min_us":..,"max_us":..,"p50_us":..,
  //  "p90_us":..,"p99_us":..}
  std::string Json() const;

 private:
  int64_t Percentile(double q, const int64_t* b, int64_t total) const;

  std::atomic<int64_t> count_{0};
  std::atomic<int64_t> sum_{0};
  std::atomic<int64_t> min_{0};  // valid only when count_ > 0
  std::atomic<int64_t> max_{0};
  std::atomic<int64_t> buckets_[kBuckets] = {};
};

// Counts for one op class on one plane (host ring / device XLA).
struct OpCounters {
  std::atomic<int64_t> responses{0};  // fused responses executed
  std::atomic<int64_t> tensors{0};    // tensors covered (>= responses)
  std::atomic<int64_t> bytes{0};      // payload bytes moved
};

class Metrics {
 public:
  // Indexed by Response::ResponseType (0..7; 7 = ERROR).
  static constexpr int kOpClasses = 8;

  OpCounters host_ops[kOpClasses];
  OpCounters device_ops[kOpClasses];

  LatencyHistogram negotiation_us;  // per-cycle ComputeResponseList wall
  LatencyHistogram queue_us;        // tensor enqueue -> execution start
  LatencyHistogram wire_us;         // one host transport call (ring span)
  LatencyHistogram straggler_skew_us;  // coordinator: first->last arrival
  // Elastic: how long the failing operation ran before the typed
  // PeerFailure surfaced (EOF ~ instant; stalls ~ the wire deadline).
  LatencyHistogram fault_detect_us;
  // Per-phase control-plane latency (ControlPhase above): the scaling
  // profile the simworld harness and `bench.py --scale` read to indict
  // O(N) suspects at 64-256 ranks (docs/scale.md).
  LatencyHistogram control_phase_us[kPhaseCount];

  std::atomic<int64_t> cycles{0};
  std::atomic<int64_t> cycle_stalls{0};      // loop overran its budget
  std::atomic<int64_t> cycle_overrun_us{0};  // total overrun beyond budget

  std::atomic<int64_t> fused_responses{0};   // multi-tensor allreduces
  std::atomic<int64_t> fusion_fill_bytes{0};     // packed payload
  std::atomic<int64_t> fusion_capacity_bytes{0};  // threshold at pack time

  std::atomic<int64_t> errors{0};  // ERROR responses surfaced

  // Elastic fault accounting (docs/elastic.md): faults the loop stopped
  // on, successful ring re-formations (hvdtpu_reinit), and ranks fenced
  // out of re-formed rings (dead peers dropped at an epoch bump).
  std::atomic<int64_t> faults_detected{0};
  std::atomic<int64_t> faults_recovered{0};
  std::atomic<int64_t> ranks_blacklisted{0};
  // Self-healing accounting (docs/elastic.md "heal vs shrink vs
  // rejoin"): transfers that resumed IN PLACE after a stall or a CRC
  // NAK-resend (no fault recorded, no epoch bump), extra patience/
  // resend windows spent getting there, chunks that failed CRC32C
  // verification (HOROVOD_WIRE_CRC), and joiner slots absorbed by a
  // grow re-formation (blacklist parole).
  std::atomic<int64_t> wire_heals{0};
  std::atomic<int64_t> wire_retries{0};
  std::atomic<int64_t> crc_errors{0};
  std::atomic<int64_t> ranks_rejoined{0};

  // Host-ring transport accounting, kept SEPARATE from the per-op-class
  // logical payload bytes above: `wire_*_bytes` is what actually
  // crossed the transport, `wire_*_logical_bytes` what the same
  // traffic would be at full tensor width. They differ exactly by the
  // wire-compression saving (bf16-on-wire halves fp32 hops) — the pair
  // telemetry needs to keep wire_goodput_gbps and byte reconciliation
  // honest when HOROVOD_WIRE_COMPRESSION is on. Note the ring moves
  // ~2(N-1)/N x payload per rank, so wire_logical != ops.bytes either.
  std::atomic<int64_t> wire_tx_bytes{0};
  std::atomic<int64_t> wire_rx_bytes{0};
  std::atomic<int64_t> wire_tx_logical_bytes{0};
  std::atomic<int64_t> wire_rx_logical_bytes{0};

  // Cross-plane slice of the wire counters above (already included in
  // them): bytes that crossed the INTER-SLICE hop of the hierarchical
  // decomposition (DataPlane wire plane 1 — the DCN-priced fabric).
  // intra = total - cross; the pair is what lets telemetry reconcile
  // per-plane logical-vs-wire exactly (docs/redistribute.md).
  std::atomic<int64_t> wire_cross_tx_bytes{0};
  std::atomic<int64_t> wire_cross_rx_bytes{0};
  std::atomic<int64_t> wire_cross_tx_logical_bytes{0};
  std::atomic<int64_t> wire_cross_rx_logical_bytes{0};

  // Per-stripe-channel slice of the wire counters (HOROVOD_WIRE_-
  // CHANNELS, docs/wire.md): channel c's share of the chunk schedule,
  // with every unstriped path booked to channel 0 — so the buckets sum
  // EXACTLY to wire_tx/rx_bytes and a dead or slow channel shows as
  // imbalance instead of averaging away. Slot count mirrors
  // kMaxWireChannels (wire.h; static_assert in metrics.cc).
  static constexpr int kWireChannelSlots = 8;
  std::atomic<int64_t> wire_chan_tx_bytes[kWireChannelSlots] = {};
  std::atomic<int64_t> wire_chan_rx_bytes[kWireChannelSlots] = {};

  // Transport syscall accounting (docs/wire.md "Syscall budget"): one
  // increment per send()/recv() INVOCATION — including short writes,
  // EAGAIN spins, and CRC control frames — because the number ROADMAP
  // item 3 (io_uring kernel-bypass) must beat is calls issued, not
  // calls that moved payload. Same slicing conventions as the byte
  // counters: cross is the plane-1 slice of the totals, per-channel
  // buckets sum exactly to them (unstriped paths book channel 0).
  std::atomic<int64_t> wire_syscalls_tx{0};
  std::atomic<int64_t> wire_syscalls_rx{0};
  std::atomic<int64_t> wire_cross_syscalls_tx{0};
  std::atomic<int64_t> wire_cross_syscalls_rx{0};
  std::atomic<int64_t> wire_chan_syscalls_tx[kWireChannelSlots] = {};
  std::atomic<int64_t> wire_chan_syscalls_rx[kWireChannelSlots] = {};

  // Hot-path inline: one relaxed fetch_add per counter touched.
  void AccountWireSyscall(int plane, int channel, bool tx) {
    auto& total = tx ? wire_syscalls_tx : wire_syscalls_rx;
    total.fetch_add(1, std::memory_order_relaxed);
    if (plane == 1) {
      auto& cross = tx ? wire_cross_syscalls_tx : wire_cross_syscalls_rx;
      cross.fetch_add(1, std::memory_order_relaxed);
    }
    if (channel < 0 || channel >= kWireChannelSlots) channel = 0;
    auto* chan = tx ? wire_chan_syscalls_tx : wire_chan_syscalls_rx;
    chan[channel].fetch_add(1, std::memory_order_relaxed);
  }

  void AccountWire(int plane, int64_t tx, int64_t rx, int64_t tx_logical,
                   int64_t rx_logical);
  void AccountWireChannels(const int64_t* tx, const int64_t* rx);
  void RecordStraggler(int rank, int64_t skew_us);
  void Reset();

  // Runtime context the snapshot embeds alongside the counters (the
  // registry itself outlives init/shutdown; these belong to GlobalState).
  struct RuntimeInfo {
    bool initialized = false;
    int rank = -1, size = 0;
    int64_t fusion_threshold_bytes = 0;
    double cycle_time_ms = 0;
    int64_t ring_chunk_bytes = 0;
    bool wire_compression = false;
    int wire_codec = 0;  // 0 off, 1 bf16, 2 int8 blockwise
    // Stripe transport: active width (autotunable) vs sockets
    // established per neighbor pair (env, fixed per process).
    int64_t wire_channels = 1;
    int64_t wire_channels_established = 1;
    bool simd = true;  // HOROVOD_SIMD vectorized reduce/codec paths
    int64_t wire_timeout_ms = 0;
    int64_t wire_retry_attempts = 0;   // healing ladder depth
    int64_t wire_retry_backoff_ms = 0;
    bool wire_crc = false;             // per-chunk CRC32C framing
    int cross_plane = 0;       // HOROVOD_CROSS_PLANE (0 auto, 1 ici,
                               // 2 ring, 3 hier)
    int64_t hier_split = 0;    // active hierarchy split (0 = flat)
    bool cross_compression = false;  // bf16 on the cross hop only
    int64_t epoch = 0;  // current membership epoch (bumped by reinit)
    int64_t cache_hits = 0, cache_misses = 0, cache_entries = 0;
    int64_t cache_hit_bytes = 0;
  };
  std::string SnapshotJson(const RuntimeInfo& info) const;

 private:
  mutable std::mutex straggler_mutex_;
  std::vector<int64_t> straggler_counts_;  // index = rank arriving last
};

// Process-wide registry; survives shutdown/re-init so counters stay
// monotonic for the lifetime of the process (scrapers diff snapshots).
Metrics& GlobalMetrics();

// Per-step overlap ledger (docs/metrics.md "Overlap ledger"):
// interval-union math over the wire spans recorded inside one step
// window [hvdtpu_step_mark(1), hvdtpu_step_mark(0)]. Per plane
// (0 intra/flat, 1 cross-slice), per step:
//
//   total    = sum of wire-span durations (the serial wire cost)
//   exposed  = the part of each wire span that ran while an API
//              thread sat BLOCKED on the core (inside hvdtpu_wait —
//              the host had nothing better to do than watch the wire)
//   hidden   = total - exposed (wire time that ran while the host
//              kept computing/dispatching — the compute/collective
//              overlap win the jit-lane fusion work exists to move;
//              docs/fusion.md)
//
// The single background execution thread runs collectives strictly
// sequentially, so wire spans themselves never overlap in wall time —
// which is why the pre-fusion definition (union overlap among wire
// spans) read hidden == 0 on every real run. Exposure is therefore
// measured against the WAIT spans hvdtpu_wait records: a bulk-
// synchronous step (issue everything, then synchronize) exposes its
// whole wire total; a fused step whose collectives drain while the
// host dispatches the next compute segment hides it.
//
// exposed + hidden == total EXACTLY by construction (both are computed
// from the same clipped interval set) — the reconciliation the
// perf-smoke/reshard-smoke lanes assert against the wire_us histogram.
// overlap_efficiency = hidden / total (0 with no wire traffic).
//
// Concurrency: spans arrive from the background loop / reduce-worker
// threads (WireTally destructors), waits from blocking API threads,
// step marks from whichever API thread drives the loop — one small
// mutex; every call is O(spans in the open step) at worst, and the
// hot paths (AddSpan/AddWait) are O(1).
class OverlapLedger {
 public:
  void StepBegin(int64_t ts_us);
  // Close the open step: computes the per-plane union accounting over
  // the spans recorded since StepBegin. Returns the step duration in
  // us, or -1 when no step was open.
  int64_t StepEnd(int64_t ts_us);
  // One completed wire span. Outside any step window the duration is
  // booked as `unattributed` (still reconcilable against wire_us).
  void AddSpan(int plane, int64_t start_us, int64_t end_us);
  // One completed API-thread blocking interval (hvdtpu_wait entry ->
  // return). Wire time under the union of these is `exposed`; waits
  // are not wire time themselves, so outside-window waits are simply
  // dropped (no unattributed contract to keep).
  void AddWait(int64_t start_us, int64_t end_us);
  void Reset();
  // The "overlap" object embedded in the snapshot's wire section:
  // {"steps":..,"unattributed_us":..,"exposed_wire_ms":..,
  //  "hidden_wire_ms":..,"overlap_efficiency":..,
  //  "intra":{exposed_us,hidden_us,total_us,overlap_efficiency,
  //           last_exposed_us,last_hidden_us,last_total_us},
  //  "cross":{...}}
  std::string Json() const;

  // Open-window span cap: beyond this, AddSpan books straight to
  // unattributed (a never-closed window must not grow without bound).
  static constexpr int64_t kMaxSpansPerPlane = 65536;

 private:
  struct PlaneLedger {
    int64_t exposed_us = 0, hidden_us = 0, total_us = 0;  // cumulative
    int64_t last_exposed_us = 0, last_hidden_us = 0,      // last step
        last_total_us = 0;
  };
  mutable std::mutex mu_;
  bool open_ = false;
  int64_t begin_us_ = 0;
  int64_t steps_ = 0;           // completed step windows
  int64_t unattributed_us_ = 0;  // span time outside any step window
  std::vector<std::pair<int64_t, int64_t>> spans_[2];  // open step
  std::vector<std::pair<int64_t, int64_t>> waits_;     // open step
  PlaneLedger planes_[2];
};

// Process-wide ledger, same lifetime contract as the registry.
OverlapLedger& GlobalLedger();

// RAII wall-clock span recorded into a histogram on destruction.
class ScopedLatency {
 public:
  explicit ScopedLatency(LatencyHistogram& h)
      : hist_(h), start_us_(MetricsNowUs()) {}
  ~ScopedLatency() { hist_.Record(MetricsNowUs() - start_us_); }
  ScopedLatency(const ScopedLatency&) = delete;
  ScopedLatency& operator=(const ScopedLatency&) = delete;

 private:
  LatencyHistogram& hist_;
  int64_t start_us_;
};

}  // namespace hvdtpu

#endif  // HVDTPU_METRICS_H
