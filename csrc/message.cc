#include "message.h"

#include <cstring>

namespace hvdtpu {

const char* RequestTypeName(RequestType t) {
  switch (t) {
    case RequestType::ALLREDUCE: return "ALLREDUCE";
    case RequestType::ALLGATHER: return "ALLGATHER";
    case RequestType::BROADCAST: return "BROADCAST";
    case RequestType::ALLTOALL: return "ALLTOALL";
    case RequestType::REDUCESCATTER: return "REDUCESCATTER";
    case RequestType::JOIN: return "JOIN";
    case RequestType::BARRIER: return "BARRIER";
  }
  return "UNKNOWN";
}

namespace {

// Minimal binary writer/reader (little-endian host assumed; all ranks run the
// same architecture, matching the reference's same-arch custom format).
class Writer {
 public:
  template <typename T>
  void Put(T v) {
    size_t off = buf_.size();
    buf_.resize(off + sizeof(T));
    std::memcpy(buf_.data() + off, &v, sizeof(T));
  }
  void PutString(const std::string& s) {
    Put<uint32_t>((uint32_t)s.size());
    buf_.append(s);
  }
  void PutI64Vec(const std::vector<int64_t>& v) {
    Put<uint32_t>((uint32_t)v.size());
    for (int64_t x : v) Put<int64_t>(x);
  }
  std::string Take() { return std::move(buf_); }

 private:
  std::string buf_;
};

class Reader {
 public:
  explicit Reader(const std::string& buf) : buf_(buf) {}
  template <typename T>
  bool Get(T* v) {
    if (pos_ + sizeof(T) > buf_.size()) return false;
    std::memcpy(v, buf_.data() + pos_, sizeof(T));
    pos_ += sizeof(T);
    return true;
  }
  bool GetString(std::string* s) {
    uint32_t n;
    if (!Get(&n) || pos_ + n > buf_.size()) return false;
    s->assign(buf_.data() + pos_, n);
    pos_ += n;
    return true;
  }
  bool GetI64Vec(std::vector<int64_t>* v) {
    uint32_t n;
    if (!Get(&n)) return false;
    v->resize(n);
    for (uint32_t i = 0; i < n; i++) {
      if (!Get(&(*v)[i])) return false;
    }
    return true;
  }

 private:
  const std::string& buf_;
  size_t pos_ = 0;
};

void WriteRequest(Writer& w, const Request& r) {
  w.Put<int32_t>(r.request_rank);
  w.Put<int32_t>((int32_t)r.request_type);
  w.Put<int32_t>((int32_t)r.tensor_type);
  w.PutString(r.tensor_name);
  w.Put<int32_t>(r.root_rank);
  w.Put<int32_t>((int32_t)r.reduce_op);
  w.Put<double>(r.prescale_factor);
  w.Put<double>(r.postscale_factor);
  w.PutI64Vec(r.tensor_shape);
  w.Put<int32_t>(r.process_set_id);
  w.Put<int32_t>(r.group_id);
  w.Put<int32_t>(r.group_size);
  w.PutI64Vec(r.splits);
  w.Put<int32_t>(r.device);
}

bool ReadRequest(Reader& rd, Request* r) {
  int32_t t = 0;
  bool ok = rd.Get(&r->request_rank);
  ok = ok && rd.Get(&t);
  r->request_type = (RequestType)t;
  ok = ok && rd.Get(&t);
  r->tensor_type = (DataType)t;
  ok = ok && rd.GetString(&r->tensor_name);
  ok = ok && rd.Get(&r->root_rank);
  ok = ok && rd.Get(&t);
  r->reduce_op = (ReduceOp)t;
  ok = ok && rd.Get(&r->prescale_factor);
  ok = ok && rd.Get(&r->postscale_factor);
  ok = ok && rd.GetI64Vec(&r->tensor_shape);
  ok = ok && rd.Get(&r->process_set_id);
  ok = ok && rd.Get(&r->group_id);
  ok = ok && rd.Get(&r->group_size);
  ok = ok && rd.GetI64Vec(&r->splits);
  ok = ok && rd.Get(&r->device);
  return ok;
}

void WriteResponse(Writer& w, const Response& r) {
  w.Put<int32_t>((int32_t)r.response_type);
  w.Put<uint32_t>((uint32_t)r.tensor_names.size());
  for (auto& n : r.tensor_names) w.PutString(n);
  w.PutString(r.error_message);
  w.Put<int32_t>((int32_t)r.tensor_type);
  w.PutI64Vec(r.tensor_sizes);
  w.PutI64Vec(r.tensor_shapes);
  w.Put<int32_t>((int32_t)r.reduce_op);
  w.Put<int32_t>(r.root_rank);
  w.Put<int32_t>(r.process_set_id);
  w.Put<int32_t>(r.last_joined_rank);
  w.Put<int32_t>(r.device);
  w.Put<int32_t>(r.group_id);
}

bool ReadResponse(Reader& rd, Response* r) {
  int32_t t = 0;
  bool ok = rd.Get(&t);
  r->response_type = (Response::ResponseType)t;
  uint32_t n = 0;
  ok = ok && rd.Get(&n);
  r->tensor_names.resize(n);
  for (uint32_t i = 0; ok && i < n; i++) ok = rd.GetString(&r->tensor_names[i]);
  ok = ok && rd.GetString(&r->error_message);
  ok = ok && rd.Get(&t);
  r->tensor_type = (DataType)t;
  ok = ok && rd.GetI64Vec(&r->tensor_sizes);
  ok = ok && rd.GetI64Vec(&r->tensor_shapes);
  ok = ok && rd.Get(&t);
  r->reduce_op = (ReduceOp)t;
  ok = ok && rd.Get(&r->root_rank);
  ok = ok && rd.Get(&r->process_set_id);
  ok = ok && rd.Get(&r->last_joined_rank);
  ok = ok && rd.Get(&r->device);
  ok = ok && rd.Get(&r->group_id);
  return ok;
}

}  // namespace

std::string SerializeRequestList(const RequestList& list) {
  Writer w;
  w.Put<uint8_t>(list.shutdown ? 1 : 0);
  w.Put<int64_t>(list.epoch);
  w.Put<int32_t>(list.rank);
  w.PutI64Vec(list.cache_hits);
  w.PutI64Vec(list.cache_invalid);
  w.Put<uint32_t>((uint32_t)list.requests.size());
  for (auto& r : list.requests) WriteRequest(w, r);
  return w.Take();
}

Status ParseRequestList(const std::string& buf, RequestList* list) {
  Reader rd(buf);
  uint8_t shutdown;
  if (!rd.Get(&shutdown)) return Status::Error("truncated RequestList");
  list->shutdown = shutdown != 0;
  if (!rd.Get(&list->epoch)) return Status::Error("truncated RequestList");
  if (!rd.Get(&list->rank)) return Status::Error("truncated RequestList");
  if (!rd.GetI64Vec(&list->cache_hits) ||
      !rd.GetI64Vec(&list->cache_invalid)) {
    return Status::Error("truncated RequestList");
  }
  uint32_t n;
  if (!rd.Get(&n)) return Status::Error("truncated RequestList");
  list->requests.resize(n);
  for (uint32_t i = 0; i < n; i++) {
    if (!ReadRequest(rd, &list->requests[i])) {
      return Status::Error("truncated Request");
    }
  }
  return Status::OK();
}

std::string SerializeResponseList(const ResponseList& list) {
  Writer w;
  w.Put<uint8_t>(list.shutdown ? 1 : 0);
  w.Put<int64_t>(list.epoch);
  w.PutI64Vec(list.fault_ranks);
  w.Put<int64_t>(list.fusion_threshold_bytes);
  w.Put<double>(list.cycle_time_ms);
  w.Put<int64_t>(list.ring_chunk_bytes);
  w.Put<int32_t>(list.wire_compression);
  w.Put<int32_t>(list.hier_split);
  w.Put<int32_t>(list.wire_channels);
  w.PutI64Vec(list.cache_hit_positions);
  w.PutI64Vec(list.cache_hit_group_sizes);
  w.PutI64Vec(list.cache_evictions);
  w.Put<uint32_t>((uint32_t)list.responses.size());
  for (auto& r : list.responses) WriteResponse(w, r);
  return w.Take();
}

Status ParseResponseList(const std::string& buf, ResponseList* list) {
  Reader rd(buf);
  uint8_t shutdown;
  if (!rd.Get(&shutdown)) return Status::Error("truncated ResponseList");
  list->shutdown = shutdown != 0;
  if (!rd.Get(&list->epoch) || !rd.GetI64Vec(&list->fault_ranks)) {
    return Status::Error("truncated ResponseList");
  }
  if (!rd.Get(&list->fusion_threshold_bytes) ||
      !rd.Get(&list->cycle_time_ms)) {
    return Status::Error("truncated ResponseList");
  }
  if (!rd.Get(&list->ring_chunk_bytes) ||
      !rd.Get(&list->wire_compression) ||
      !rd.Get(&list->hier_split) ||
      !rd.Get(&list->wire_channels)) {
    return Status::Error("truncated ResponseList");
  }
  if (!rd.GetI64Vec(&list->cache_hit_positions) ||
      !rd.GetI64Vec(&list->cache_hit_group_sizes) ||
      !rd.GetI64Vec(&list->cache_evictions)) {
    return Status::Error("truncated ResponseList");
  }
  uint32_t n;
  if (!rd.Get(&n)) return Status::Error("truncated ResponseList");
  list->responses.resize(n);
  for (uint32_t i = 0; i < n; i++) {
    if (!ReadResponse(rd, &list->responses[i])) {
      return Status::Error("truncated Response");
    }
  }
  return Status::OK();
}

}  // namespace hvdtpu
