#include "tensor_queue.h"

namespace hvdtpu {

Status TensorQueue::AddToTensorQueue(TensorTableEntry entry, Request message) {
  std::lock_guard<std::mutex> lk(mutex_);
  if (tensor_table_.count(entry.name)) {
    return Status::PreconditionError(
        "Duplicate tensor name in queue: " + entry.name +
        " (a collective with this name is already in flight)");
  }
  tensor_table_.emplace(entry.name, std::move(entry));
  message_queue_.push_back(std::move(message));
  return Status::OK();
}

std::vector<Request> TensorQueue::PopMessages() {
  std::lock_guard<std::mutex> lk(mutex_);
  std::vector<Request> out(message_queue_.begin(), message_queue_.end());
  message_queue_.clear();
  return out;
}

std::vector<TensorTableEntry> TensorQueue::GetTensorEntriesFromResponse(
    const Response& response) {
  std::lock_guard<std::mutex> lk(mutex_);
  std::vector<TensorTableEntry> entries;
  entries.reserve(response.tensor_names.size());
  for (auto& name : response.tensor_names) {
    auto it = tensor_table_.find(name);
    // Match the process set too: a same-named tensor pending on a DIFFERENT
    // set (legal for disjoint sets) must not be consumed by this response.
    if (it != tensor_table_.end() &&
        it->second.process_set_id == response.process_set_id) {
      entries.push_back(std::move(it->second));
      tensor_table_.erase(it);
    }
  }
  return entries;
}

std::vector<TensorTableEntry> TensorQueue::RemoveAllEntries() {
  std::lock_guard<std::mutex> lk(mutex_);
  std::vector<TensorTableEntry> entries;
  entries.reserve(tensor_table_.size());
  for (auto& kv : tensor_table_) entries.push_back(std::move(kv.second));
  tensor_table_.clear();
  message_queue_.clear();
  return entries;
}

size_t TensorQueue::Size() {
  std::lock_guard<std::mutex> lk(mutex_);
  return tensor_table_.size();
}

bool TensorQueue::Contains(const std::string& name) {
  std::lock_guard<std::mutex> lk(mutex_);
  return tensor_table_.count(name) != 0;
}

}  // namespace hvdtpu
