// Explicit SIMD paths for the host data plane's hot loops: f32/bf16
// accumulation (ReduceInto) and the bf16 wire codec. Built on GCC/Clang
// portable vector extensions — the compiler lowers 8-lane ops to
// whatever the target offers (AVX2 on x86-64, paired NEON on aarch64,
// synthesized scalar otherwise), and every lane op is the IEEE scalar
// op, so results are BIT-IDENTICAL to the scalar reference loops on
// every target (pinned by hvdtpu_simd_selftest across unaligned
// offsets and tail lengths). Loads/stores go through memcpy into
// vector temporaries, which lowers to unaligned vector moves —
// alignment-safe by construction; tails run the scalar reference.
//
// HOROVOD_SIMD=0 (or SetSimdEnabled(false)) forces the scalar paths at
// runtime — the fallback the bit-identity pins compare against, and
// the escape hatch if a target's vector lowering ever misbehaves.
//
// Reference analog: none upstream — horovod's CPU reductions lean on
// MPI; NCCL's reduce kernels are the spiritual ancestor (vectorized
// elementwise reduce folded into the transport pipeline).

#ifndef HVDTPU_SIMD_H
#define HVDTPU_SIMD_H

#include <cstdint>
#include <cstring>

#include "half.h"

namespace hvdtpu {

// Runtime SIMD toggle (HOROVOD_SIMD, default on) — ring_ops.cc owns
// the atomic; declared here so the kernels and their call sites share
// one switch.
bool SimdEnabled();
void SetSimdEnabled(bool on);

// GCC warns that passing 32-byte vectors by value has a different ABI
// with/without AVX (-Wpsabi). Every vector-typed function here is
// inline and internal to one TU — no cross-TU vector ABI exists to
// break — so the warning is noise by construction. It fires at the
// INSTANTIATION site (end of the including TU), so the suppression is
// deliberately not push/pop'd: it must cover the whole TU.
#pragma GCC diagnostic ignored "-Wpsabi"

namespace simd {

constexpr int kLanes = 8;

typedef float Vf32 __attribute__((vector_size(32)));
typedef uint32_t Vu32 __attribute__((vector_size(32)));
typedef uint16_t Vu16 __attribute__((vector_size(16)));
typedef double Vf64 __attribute__((vector_size(64)));  // 8 x f64

inline Vf32 LoadF32(const float* p) {
  Vf32 v;
  std::memcpy(&v, p, sizeof(v));
  return v;
}
inline void StoreF32(float* p, Vf32 v) { std::memcpy(p, &v, sizeof(v)); }
inline Vu32 LoadU32(const void* p) {
  Vu32 v;
  std::memcpy(&v, p, sizeof(v));
  return v;
}
inline Vu16 LoadU16(const uint16_t* p) {
  Vu16 v;
  std::memcpy(&v, p, sizeof(v));
  return v;
}
inline void StoreU16(uint16_t* p, Vu16 v) {
  std::memcpy(p, &v, sizeof(v));
}

// f32 bit pattern -> bf16 bits, 8 lanes: the exact FloatToBF16Bits
// sequence (quiet-NaN force, else round-to-nearest-even via the
// +0x7FFF+lsb carry trick) applied per lane.
inline Vu16 Bf16FromF32Bits(Vu32 f) {
  Vu32 is_nan = (Vu32)((f & 0x7F800000u) == 0x7F800000u) &
                (Vu32)((f & 0x007FFFFFu) != 0u);
  Vu32 lsb = (f >> 16) & 1u;
  Vu32 rounded = (f + 0x7FFFu + lsb) >> 16;
  Vu32 nan_bits = (f >> 16) | 0x40u;
  Vu32 r = (is_nan & nan_bits) | (~is_nan & rounded);
  return __builtin_convertvector(r, Vu16);
}

// bf16 bits -> f32, 8 lanes (exact: left shift into the exponent).
inline Vf32 F32FromBf16Bits(Vu16 h) {
  Vu32 w = __builtin_convertvector(h, Vu32) << 16;
  return (Vf32)w;
}

// dst[i] += src[i], f32. Per-lane IEEE add == the scalar loop.
inline void AddF32(float* dst, const float* src, int64_t n) {
  int64_t i = 0;
  for (; i + kLanes <= n; i += kLanes) {
    StoreF32(dst + i, LoadF32(dst + i) + LoadF32(src + i));
  }
  for (; i < n; i++) dst[i] = dst[i] + src[i];
}

// bf16 SUM: widen both sides to f32, add, re-encode — the
// ReduceHalfLike<FloatToBF16Bits, BF16BitsToFloat> SUM sequence.
inline void ReduceSumBF16(uint16_t* dst, const uint16_t* src, int64_t n) {
  int64_t i = 0;
  for (; i + kLanes <= n; i += kLanes) {
    Vf32 a = F32FromBf16Bits(LoadU16(dst + i));
    Vf32 b = F32FromBf16Bits(LoadU16(src + i));
    Vf32 r = a + b;
    StoreU16(dst + i, Bf16FromF32Bits((Vu32)r));
  }
  for (; i < n; i++) {
    dst[i] = FloatToBF16Bits(BF16BitsToFloat(dst[i]) +
                             BF16BitsToFloat(src[i]));
  }
}

// bf16 wire encode (EncodeBF16's loop).
inline void EncodeBF16(uint16_t* dst, const float* src, int64_t n) {
  int64_t i = 0;
  for (; i + kLanes <= n; i += kLanes) {
    StoreU16(dst + i, Bf16FromF32Bits(LoadU32(src + i)));
  }
  for (; i < n; i++) dst[i] = FloatToBF16Bits(src[i]);
}

// bf16 wire decode + f32 accumulate (DecodeAccumBF16's loop).
inline void DecodeAccumBF16(float* dst, const uint16_t* src, int64_t n) {
  int64_t i = 0;
  for (; i + kLanes <= n; i += kLanes) {
    StoreF32(dst + i, LoadF32(dst + i) + F32FromBf16Bits(LoadU16(src + i)));
  }
  for (; i < n; i++) dst[i] += BF16BitsToFloat(src[i]);
}

// bf16 wire decode with folded postscale: the post != 1.0 lane math is
// (float)((double)x * post) exactly like the scalar reference — widen
// to f64, multiply once, narrow once.
inline void DecodeScaleBF16(float* dst, const uint16_t* src, int64_t n,
                            double post) {
  int64_t i = 0;
  if (post == 1.0) {
    for (; i + kLanes <= n; i += kLanes) {
      StoreF32(dst + i, F32FromBf16Bits(LoadU16(src + i)));
    }
    for (; i < n; i++) dst[i] = BF16BitsToFloat(src[i]);
    return;
  }
  for (; i + kLanes <= n; i += kLanes) {
    Vf64 d = __builtin_convertvector(F32FromBf16Bits(LoadU16(src + i)),
                                     Vf64);
    d = d * post;
    StoreF32(dst + i, __builtin_convertvector(d, Vf32));
  }
  for (; i < n; i++) {
    dst[i] = (float)((double)BF16BitsToFloat(src[i]) * post);
  }
}

// f32 in-place scale (ScaleBuffer's f32 case: double multiply, one
// f32 rounding per element).
inline void ScaleF32(float* p, int64_t n, double factor) {
  int64_t i = 0;
  for (; i + kLanes <= n; i += kLanes) {
    Vf64 d = __builtin_convertvector(LoadF32(p + i), Vf64);
    d = d * factor;
    StoreF32(p + i, __builtin_convertvector(d, Vf32));
  }
  for (; i < n; i++) p[i] = (float)(p[i] * factor);
}

}  // namespace simd
}  // namespace hvdtpu

#endif  // HVDTPU_SIMD_H
