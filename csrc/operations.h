// Public C API of the native core, loaded from Python via ctypes.
// Reference analog: horovod/common/operations.h (horovod_init,
// EnqueueTensorAllreduce, ...) + the torch binding's integer-handle pattern
// (horovod/torch/handle_manager.h) — chosen here as the universal ABI so no
// per-framework C extension is needed.

#ifndef HVDTPU_OPERATIONS_H
#define HVDTPU_OPERATIONS_H

#include <cstdint>

extern "C" {

// Initialization / identity. Reads HOROVOD_RANK/SIZE/... env (set by
// horovodrun). Returns 0 on success, <0 on failure.
int hvdtpu_init();
int hvdtpu_shutdown();
int hvdtpu_is_initialized();
// 1 when the background loop exited on a control- or data-plane
// failure (peer lost) — the elastic-recoverable state; 0 otherwise.
int hvdtpu_loop_failed();

// ---- elastic fault surface (docs/elastic.md) ------------------------
// Membership epoch of the current ring generation (0 = fresh init;
// bumped by hvdtpu_reinit). Stale-epoch traffic is fenced out.
int64_t hvdtpu_epoch();
// Last fault record as JSON, two-call pattern like the metrics
// snapshot: (nullptr, 0) sizes it, a second call copies NUL-terminated.
// {"faulted":false} until the loop has stopped on a peer failure.
int64_t hvdtpu_last_fault(char* buf, int64_t cap);
// Re-form the ring over the surviving OLD ranks (-1 entries = joiner
// slots taken by fresh HOROVOD_JOIN_EPOCH processes — scale-up) at a
// new epoch without process restart. Collective among members; a
// healthy loop drains via the negotiated shutdown first. 0 on success,
// negative codes in operations.cc.
int hvdtpu_reinit(const int32_t* ranks, int nranks, int64_t epoch);
// Wire progress deadline (HOROVOD_WIRE_TIMEOUT_MS; <= 0 disables).
// Process-global, valid before init, like the ring knobs.
int64_t hvdtpu_wire_timeout_ms();
void hvdtpu_set_wire_timeout_ms(int64_t ms);
// Transient-fault healing ladder + per-chunk CRC32C wire integrity
// (HOROVOD_WIRE_RETRY_ATTEMPTS / _BACKOFF_MS / HOROVOD_WIRE_CRC;
// docs/wire.md). Same process-global contract as the deadline.
int64_t hvdtpu_wire_retry_attempts();
void hvdtpu_set_wire_retry_attempts(int64_t n);
int64_t hvdtpu_wire_retry_backoff_ms();
void hvdtpu_set_wire_retry_backoff_ms(int64_t ms);
int hvdtpu_wire_crc();
void hvdtpu_set_wire_crc(int on);
// Deterministic fault injection (HOROVOD_FAULT_INJECT's programmatic
// twin): `rank` SIGKILLs itself at its op_index-th executed collective.
// rank < 0 disarms. One-shot per ring generation.
int hvdtpu_set_fault_inject(int rank, int64_t op_index);
// Full chaos grammar: "<rank>:<op>[:kill|stop:<ms>|reset|flip:<bit>|
// delay:<ms>]". 0 armed, -1 not initialized, -2 malformed (disarmed).
int hvdtpu_set_fault_inject_spec(const char* spec);
int hvdtpu_rank();
int hvdtpu_size();
int hvdtpu_local_rank();
int hvdtpu_local_size();
int hvdtpu_cross_rank();
int hvdtpu_cross_size();

// Async collective enqueue: returns a handle (>= 0) or <0 on error.
// Buffers must stay alive until the handle completes.
int hvdtpu_enqueue_allreduce(const char* name, const void* input, void* output,
                             int ndim, const int64_t* shape, int dtype,
                             int reduce_op, double prescale, double postscale,
                             int process_set_id);
int hvdtpu_enqueue_grouped_allreduce(int num_tensors, const char** names,
                                     const void** inputs, void** outputs,
                                     const int* ndims, const int64_t** shapes,
                                     int dtype, int reduce_op, double prescale,
                                     double postscale, int process_set_id,
                                     int* handles_out);
int hvdtpu_enqueue_allgather(const char* name, const void* input, int ndim,
                             const int64_t* shape, int dtype,
                             int process_set_id, int group_id,
                             int group_size);
int hvdtpu_enqueue_broadcast(const char* name, void* buffer, int ndim,
                             const int64_t* shape, int dtype, int root_rank,
                             int process_set_id);
int hvdtpu_enqueue_alltoall(const char* name, const void* input, int ndim,
                            const int64_t* shape, int dtype,
                            const int64_t* splits, int process_set_id);
int hvdtpu_enqueue_reducescatter(const char* name, const void* input, int ndim,
                                 const int64_t* shape, int dtype,
                                 int reduce_op, double prescale,
                                 double postscale, int process_set_id,
                                 int group_id, int group_size);
int hvdtpu_enqueue_barrier(int process_set_id);

// Device data plane (xla_ici backend). Python registers one callback
// (ctypes CFUNCTYPE matching DeviceExecFn in operations.cc); device
// enqueues are negotiation-only — payloads stay in HBM on the Python
// side, and the callback executes each fused group as one XLA program.
int hvdtpu_set_device_callback(void* fn);
int hvdtpu_enqueue_device(int op_class, const char* name, int ndim,
                          const int64_t* shape, int dtype, int reduce_op,
                          int root_rank, int process_set_id, int group_id,
                          int group_size);
int hvdtpu_next_group_id();
// Join: this rank is out of data; returns a handle that completes once every
// rank has joined. After completion, hvdtpu_last_joined_rank() gives the
// last rank to join. Reference analog: horovod_join (operations.cc).
int hvdtpu_enqueue_join();
int hvdtpu_last_joined_rank();

// Process sets (reference analog: horovod_add_process_set etc. via
// horovod/common/process_sets.py). Registration must happen in the same
// order on every rank; synchronize (e.g. barrier) before first use.
int hvdtpu_add_process_set(const int32_t* ranks, int nranks);
int hvdtpu_remove_process_set(int process_set_id);
int hvdtpu_process_set_size(int process_set_id);
int hvdtpu_process_set_rank(int process_set_id);

// Handle API (reference analog: horovod/torch/handle_manager.h).
int hvdtpu_poll(int handle);                  // 1 done, 0 in flight, <0 bad
int hvdtpu_wait(int handle);                  // 0 ok, <0 error
const char* hvdtpu_error_string(int handle);  // valid until release
// Managed results (allgather/alltoall/reducescatter outputs):
int hvdtpu_result_ndim(int handle);
int hvdtpu_result_shape(int handle, int64_t* shape_out);
int64_t hvdtpu_result_size_bytes(int handle);
int hvdtpu_result_copy(int handle, void* dst, int64_t nbytes);
int hvdtpu_release(int handle);

// Runtime knobs (reference: HOROVOD_FUSION_THRESHOLD / HOROVOD_CYCLE_TIME).
// Runtime timeline control (reference analog: hvd.start_timeline /
// hvd.stop_timeline via TimelineController).
int hvdtpu_start_timeline(const char* path);
int hvdtpu_stop_timeline();

int64_t hvdtpu_fusion_threshold_bytes();
double hvdtpu_cycle_time_ms();
void hvdtpu_set_fusion_threshold_bytes(int64_t v);
void hvdtpu_set_cycle_time_ms(double v);

// Cross-plane collective engine (HOROVOD_CROSS_PLANE, docs/
// redistribute.md): mode (0 auto, 1 ici, 2 ring, 3 hier), the active
// hierarchy split point (0 flat; s >= 2 intra-slice group size;
// rank-uniform — the autotuner syncs it via the ResponseList), and the
// cross-hop-only bf16 wire codec flag.
int hvdtpu_cross_plane();
int hvdtpu_hier_split();
void hvdtpu_set_hier_split(int split);
int hvdtpu_cross_compression();

// Response-cache introspection (reference analog: the cache stats the
// timeline/autotune read from response_cache.h). Capacity via
// HOROVOD_CACHE_CAPACITY (default 1024; 0 disables).
int64_t hvdtpu_response_cache_hits();
int64_t hvdtpu_response_cache_misses();
int64_t hvdtpu_response_cache_entries();

// Metrics registry (csrc/metrics.h): one JSON snapshot of every core
// counter — per-op-class counts/bytes, negotiation/queue/wire latency
// histograms, fusion fill, cycle stalls, cache hit rate, coordinator
// straggler attribution. Two-call pattern: (nullptr, 0) returns the JSON
// length; a second call with a buffer of at least len+1 copies it
// NUL-terminated. Usable before init (zeroed counters). Surfaced as
// hvd.metrics() through horovod_tpu/telemetry.
int64_t hvdtpu_metrics_snapshot(char* buf, int64_t cap);
int hvdtpu_metrics_reset();

// Step scoping (docs/metrics.md "Step anatomy"): mark a training-step
// boundary. begin != 0 opens a new step window with a fresh monotonic
// id (closing any still-open one — boundary semantics) and returns the
// id; begin == 0 closes the open window and returns its id (-1 if
// none). kStepBegin/kStepEnd land in the event ring and the per-step
// wire overlap ledger aggregates between the marks. Valid before init.
int64_t hvdtpu_step_mark(int begin);
// The currently open step id, or -1.
int64_t hvdtpu_step_id();
}

#endif  // HVDTPU_OPERATIONS_H
