// Online autotuning of fusion threshold + cycle time + ring transport
// knobs (chunk granularity, wire compression).
// Reference analog: horovod/common/parameter_manager.h (ParameterManager,
// driven by HOROVOD_AUTOTUNE) with the same optimizer family: Bayesian
// optimization (GP + Expected Improvement — csrc/bayes_opt.h, the analog
// of common/optim/bayesian_optimization.cc) over the discrete
// (fusion threshold, cycle time, ring chunk bytes[, wire compression])
// grid, scoring sample windows by allreduced bytes/sec. Runs on the
// coordinator only; chosen values ride to workers on every ResponseList.

#ifndef HVDTPU_PARAMETER_MANAGER_H
#define HVDTPU_PARAMETER_MANAGER_H

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <memory>
#include <string>
#include <vector>

#include "bayes_opt.h"

namespace hvdtpu {

class ParameterManager {
 public:
  // log_path empty = no CSV log (HOROVOD_AUTOTUNE_LOG). max_samples is
  // HOROVOD_AUTOTUNE_STEPS: scored windows before fixing the knobs.
  // window_bytes/window_cycles (HOROVOD_AUTOTUNE_WINDOW_BYTES /
  // _WINDOW_CYCLES) are the floors a window must clear before it is
  // scored: bursty eager workloads want windows spanning SEVERAL
  // steps, or per-window bytes/sec is dominated by where in the
  // compute/allreduce burst cycle the window boundary lands.
  // ring_chunk_bytes seeds the chunk-granularity grid dimension.
  // tune_wire_compression adds the on/off compression dimension — only
  // set when the USER enabled HOROVOD_WIRE_COMPRESSION (the tuner may
  // then fall back to the strictly-more-accurate uncompressed wire,
  // but never silently narrows a run the user wanted full-width).
  // hier_values is the hierarchy-split-point grid of the cross-plane
  // allreduce (0 = flat ring, d >= 2 = intra-slice group size; the
  // eligible divisors of local_size — operations.cc builds it). A
  // single value pins the dimension; hier_split seeds the start point.
  // wire_codec is the full codec mode (0 off / 1 bf16 / 2 int8);
  // tune_wire_codec puts {0, codec} on the grid (OFF is always the
  // safe fallback, the tuner never narrows an uncompressed run).
  // wire_channels seeds the stripe-width dimension; its grid is the
  // powers of two up to max_wire_channels (the sockets actually
  // established), pinned when max == 1.
  void Initialize(int64_t fusion_bytes, double cycle_ms,
                  const std::string& log_path, int max_samples = 20,
                  int64_t window_bytes = 1 << 20,
                  int window_cycles = 20,
                  int64_t ring_chunk_bytes = 256 * 1024,
                  int wire_codec = 0,
                  bool tune_wire_codec = false,
                  std::vector<int64_t> hier_values = {},
                  int64_t hier_split = 0,
                  int64_t wire_channels = 1,
                  int64_t max_wire_channels = 1);
  ~ParameterManager();

  bool active() const { return active_; }
  int64_t fusion_threshold_bytes() const { return fusion_values_[fusion_idx_]; }
  double cycle_time_ms() const { return cycle_values_[cycle_idx_]; }
  int64_t ring_chunk_bytes() const { return chunk_values_[chunk_idx_]; }
  bool wire_compression() const { return comp_values_[comp_idx_] != 0; }
  int wire_codec() const { return comp_values_[comp_idx_]; }
  int64_t hier_split() const { return hier_values_[hier_idx_]; }
  int64_t wire_channels() const { return chan_values_[chan_idx_]; }

  // Record bytes moved by allreduce responses this cycle; returns true when
  // a tuning window closed and the recommended parameters may have changed.
  bool Update(int64_t bytes);

 private:
  void Score(double bytes_per_sec);
  void MoveTo(size_t candidate);
  void Log(double score);

  bool active_ = false;
  bool done_ = false;

  std::vector<int64_t> fusion_values_;
  std::vector<double> cycle_values_;
  std::vector<int64_t> chunk_values_;
  std::vector<int> comp_values_;  // {0}/{mode} fixed, or {0,mode} tuned
  std::vector<int64_t> hier_values_ = {0};  // {0} fixed, else split grid
  std::vector<int64_t> chan_values_ = {1};  // stripe widths <= max
  size_t fusion_idx_ = 0, cycle_idx_ = 0, chunk_idx_ = 0, comp_idx_ = 0;
  size_t hier_idx_ = 0, chan_idx_ = 0;

  // Bayesian optimization over the flattened grid: candidate index
  // c = ((((fusion_i * |cycle| + cycle_i) * |chunk| + chunk_i) * |comp|
  //     + comp_i) * |hier| + hier_i) * |chan| + chan_i.
  std::unique_ptr<BayesOpt> opt_;
  size_t current_candidate_ = 0;
  int max_samples_ = 20;

  // Window accumulation. Windows are scored over WALL time: each
  // window's clock starts where the previous one closed (see Update),
  // so compute-phase idle counts against the knobs that caused it.
  int64_t window_bytes_ = 0;
  int window_cycles_ = 0;
  int64_t min_window_bytes_ = 1 << 20;
  int min_window_cycles_ = 20;
  int warmup_windows_ = 3;
  std::chrono::steady_clock::time_point window_start_;
  std::chrono::steady_clock::time_point window_end_;
  bool window_started_ = false;
  bool window_ended_ = false;

  FILE* log_ = nullptr;
};

}  // namespace hvdtpu

#endif  // HVDTPU_PARAMETER_MANAGER_H
