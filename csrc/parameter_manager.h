// Online autotuning of fusion threshold + cycle time.
// Reference analog: horovod/common/parameter_manager.h (ParameterManager,
// driven by HOROVOD_AUTOTUNE) — there Bayesian optimization over warmup
// samples (common/optim/bayesian_optimization.cc); here deterministic
// coordinate descent over the same discrete grids, scoring windows by
// allreduced bytes/sec. Runs on the coordinator only; chosen values ride to
// workers on every ResponseList.

#ifndef HVDTPU_PARAMETER_MANAGER_H
#define HVDTPU_PARAMETER_MANAGER_H

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <string>
#include <vector>

namespace hvdtpu {

class ParameterManager {
 public:
  // log_path empty = no CSV log (HOROVOD_AUTOTUNE_LOG).
  void Initialize(int64_t fusion_bytes, double cycle_ms,
                  const std::string& log_path);
  ~ParameterManager();

  bool active() const { return active_; }
  int64_t fusion_threshold_bytes() const { return fusion_values_[fusion_idx_]; }
  double cycle_time_ms() const { return cycle_values_[cycle_idx_]; }

  // Record bytes moved by allreduce responses this cycle; returns true when
  // a tuning window closed and the recommended parameters may have changed.
  bool Update(int64_t bytes);

 private:
  void Score(double bytes_per_sec);
  bool Move(int direction);  // step the active axis by +-1; false if clamped
  void TryProbe();           // place next probe, skipping clamped edges
  void AdvanceAxis();
  void Log(double score);

  bool active_ = false;
  bool done_ = false;

  std::vector<int64_t> fusion_values_;
  std::vector<double> cycle_values_;
  size_t fusion_idx_ = 0, cycle_idx_ = 0;

  // Coordinate descent: tune fusion axis, then cycle axis, two sweeps.
  int axis_ = 0;             // 0 = fusion, 1 = cycle
  int sweeps_left_ = 2;      // full (fusion+cycle) passes remaining
  int direction_ = +1;       // current probe direction on the axis
  bool have_baseline_ = false;
  double baseline_score_ = 0;  // score at current best point
  int tries_ = 0;            // direction flips tried at this point

  // Window accumulation.
  int64_t window_bytes_ = 0;
  int window_cycles_ = 0;
  int warmup_windows_ = 3;
  std::chrono::steady_clock::time_point window_start_;
  bool window_started_ = false;

  FILE* log_ = nullptr;
};

}  // namespace hvdtpu

#endif  // HVDTPU_PARAMETER_MANAGER_H
