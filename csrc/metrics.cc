#include "metrics.h"

#include "common.h"
#include "events.h"
#include "wire.h"

static_assert(hvdtpu::Metrics::kWireChannelSlots ==
                  hvdtpu::kMaxWireChannels,
              "per-channel counter slots must match the wire's stripe cap");

#include <algorithm>
#include <chrono>
#include <cstdarg>
#include <cstdio>
#include <cstring>

namespace hvdtpu {

int64_t MetricsNowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

namespace {
// Snapshot/docs keys for the control-plane phase profile. Order must
// match ControlPhase (metrics.h).
const char* kPhaseNames[kPhaseCount] = {
    "rendezvous", "gather", "broadcast", "probe_sweep", "reinit",
    "parole_freeze"};
}  // namespace

const char* ControlPhaseName(int phase) {
  if (phase < 0 || phase >= kPhaseCount) return "unknown";
  return kPhaseNames[phase];
}

void RecordControlPhase(int phase, int64_t dur_us, bool emit_event) {
  if (phase < 0 || phase >= kPhaseCount) return;
  GlobalMetrics().control_phase_us[phase].Record(dur_us);
  if (emit_event) {
    GlobalEvents().Record(EventType::kPhase, phase, 0, dur_us);
  }
}

// Dynamically sized append: measure first, then format straight into
// the string. The previous fixed stack buffer (256, then 768 bytes,
// grown by hand whenever a section gained rows) silently truncated —
// and thereby corrupted — the snapshot JSON the moment a row outgrew
// it; measuring makes the buffer a non-decision forever. Shared by
// every printf-style JSON producer in the core (metrics snapshot,
// simworld report).
void AppendFmtV(std::string& out, const char* fmt, va_list args) {
  va_list measure;
  va_copy(measure, args);
  int need = vsnprintf(nullptr, 0, fmt, measure);
  va_end(measure);
  if (need > 0) {
    size_t old = out.size();
    out.resize(old + (size_t)need + 1);
    vsnprintf(&out[old], (size_t)need + 1, fmt, args);
    out.resize(old + (size_t)need);
  }
}

namespace {

int BucketOf(int64_t us) {
  int b = 0;
  while (us > 1 && b < LatencyHistogram::kBuckets - 1) {
    us >>= 1;
    b++;
  }
  return b;
}

void AtomicMin(std::atomic<int64_t>& a, int64_t v) {
  int64_t cur = a.load(std::memory_order_relaxed);
  while (v < cur &&
         !a.compare_exchange_weak(cur, v, std::memory_order_relaxed)) {
  }
}

void AtomicMax(std::atomic<int64_t>& a, int64_t v) {
  int64_t cur = a.load(std::memory_order_relaxed);
  while (v > cur &&
         !a.compare_exchange_weak(cur, v, std::memory_order_relaxed)) {
  }
}

// Local shorthand for the shared AppendFmtV (metrics.h) — every JSON
// producer in the core uses the measure-then-format append; a fixed
// stack buffer silently truncates (= corrupts) the JSON the moment a
// row outgrows it.
void Append(std::string& out, const char* fmt, ...) {
  va_list args;
  va_start(args, fmt);
  AppendFmtV(out, fmt, args);
  va_end(args);
}

// Op-class names aligned with Response::ResponseType values.
const char* kOpNames[Metrics::kOpClasses] = {
    "allreduce", "allgather", "broadcast", "alltoall",
    "reducescatter", "join", "barrier", "error"};

void AppendOps(std::string& out, const char* key,
               const OpCounters (&ops)[Metrics::kOpClasses]) {
  Append(out, "\"%s\":{", key);
  bool first = true;
  for (int i = 0; i < Metrics::kOpClasses; i++) {
    int64_t r = ops[i].responses.load(std::memory_order_relaxed);
    int64_t t = ops[i].tensors.load(std::memory_order_relaxed);
    int64_t b = ops[i].bytes.load(std::memory_order_relaxed);
    if (r == 0 && t == 0 && b == 0) continue;  // keep snapshots compact
    Append(out, "%s\"%s\":{\"responses\":%lld,\"tensors\":%lld,"
                "\"bytes\":%lld}",
           first ? "" : ",", kOpNames[i], (long long)r, (long long)t,
           (long long)b);
    first = false;
  }
  out += "},";
}

}  // namespace

void LatencyHistogram::Record(int64_t us) {
  if (us < 0) us = 0;
  count_.fetch_add(1, std::memory_order_relaxed);
  sum_.fetch_add(us, std::memory_order_relaxed);
  if (count_.load(std::memory_order_relaxed) == 1) {
    min_.store(us, std::memory_order_relaxed);
  }
  AtomicMin(min_, us);
  AtomicMax(max_, us);
  buckets_[BucketOf(us)].fetch_add(1, std::memory_order_relaxed);
}

void LatencyHistogram::Reset() {
  count_.store(0);
  sum_.store(0);
  min_.store(0);
  max_.store(0);
  for (auto& b : buckets_) b.store(0);
}

int64_t LatencyHistogram::Percentile(double q, const int64_t* b,
                                     int64_t total) const {
  if (total <= 0) return 0;
  int64_t target = (int64_t)(q * (double)total);
  if (target < 1) target = 1;
  int64_t seen = 0;
  int64_t mx = max_.load(std::memory_order_relaxed);
  for (int i = 0; i < kBuckets; i++) {
    seen += b[i];
    if (seen >= target) {
      // Upper bound of bucket i (2^(i+1) us; bucket 0 covers [0,2)),
      // clamped to the observed max so p50 can never exceed it.
      int64_t bound = i >= 62 ? INT64_MAX : ((int64_t)1 << (i + 1));
      return bound < mx ? bound : mx;
    }
  }
  return mx;
}

std::string LatencyHistogram::Json() const {
  // Copy buckets once so count/percentiles come from one view.
  int64_t b[kBuckets];
  int64_t total = 0;
  for (int i = 0; i < kBuckets; i++) {
    b[i] = buckets_[i].load(std::memory_order_relaxed);
    total += b[i];
  }
  std::string out = "{";
  Append(out, "\"count\":%lld,\"sum_us\":%lld,\"min_us\":%lld,"
              "\"max_us\":%lld,\"p50_us\":%lld,\"p90_us\":%lld,"
              "\"p99_us\":%lld}",
         (long long)total, (long long)sum_.load(std::memory_order_relaxed),
         (long long)(total ? min_.load(std::memory_order_relaxed) : 0),
         (long long)max_.load(std::memory_order_relaxed),
         (long long)Percentile(0.50, b, total),
         (long long)Percentile(0.90, b, total),
         (long long)Percentile(0.99, b, total));
  return out;
}

void Metrics::AccountWire(int plane, int64_t tx, int64_t rx,
                          int64_t tx_logical, int64_t rx_logical) {
  wire_tx_bytes.fetch_add(tx, std::memory_order_relaxed);
  wire_rx_bytes.fetch_add(rx, std::memory_order_relaxed);
  wire_tx_logical_bytes.fetch_add(tx_logical, std::memory_order_relaxed);
  wire_rx_logical_bytes.fetch_add(rx_logical, std::memory_order_relaxed);
  if (plane == 1) {
    wire_cross_tx_bytes.fetch_add(tx, std::memory_order_relaxed);
    wire_cross_rx_bytes.fetch_add(rx, std::memory_order_relaxed);
    wire_cross_tx_logical_bytes.fetch_add(tx_logical,
                                          std::memory_order_relaxed);
    wire_cross_rx_logical_bytes.fetch_add(rx_logical,
                                          std::memory_order_relaxed);
  }
}

void Metrics::AccountWireChannels(const int64_t* tx, const int64_t* rx) {
  for (int c = 0; c < kWireChannelSlots; c++) {
    if (tx[c]) {
      wire_chan_tx_bytes[c].fetch_add(tx[c], std::memory_order_relaxed);
    }
    if (rx[c]) {
      wire_chan_rx_bytes[c].fetch_add(rx[c], std::memory_order_relaxed);
    }
  }
}

void Metrics::RecordStraggler(int rank, int64_t skew_us) {
  {
    std::lock_guard<std::mutex> lk(straggler_mutex_);
    if ((int)straggler_counts_.size() <= rank) {
      straggler_counts_.resize(rank + 1, 0);
    }
    straggler_counts_[rank]++;
  }
  straggler_skew_us.Record(skew_us);
}

void Metrics::Reset() {
  for (auto& o : host_ops) {
    o.responses.store(0);
    o.tensors.store(0);
    o.bytes.store(0);
  }
  for (auto& o : device_ops) {
    o.responses.store(0);
    o.tensors.store(0);
    o.bytes.store(0);
  }
  negotiation_us.Reset();
  queue_us.Reset();
  wire_us.Reset();
  straggler_skew_us.Reset();
  fault_detect_us.Reset();
  for (auto& h : control_phase_us) h.Reset();
  faults_detected.store(0);
  faults_recovered.store(0);
  ranks_blacklisted.store(0);
  wire_heals.store(0);
  wire_retries.store(0);
  crc_errors.store(0);
  ranks_rejoined.store(0);
  cycles.store(0);
  cycle_stalls.store(0);
  cycle_overrun_us.store(0);
  fused_responses.store(0);
  fusion_fill_bytes.store(0);
  fusion_capacity_bytes.store(0);
  errors.store(0);
  wire_tx_bytes.store(0);
  wire_rx_bytes.store(0);
  wire_tx_logical_bytes.store(0);
  wire_rx_logical_bytes.store(0);
  wire_cross_tx_bytes.store(0);
  wire_cross_rx_bytes.store(0);
  wire_cross_tx_logical_bytes.store(0);
  wire_cross_rx_logical_bytes.store(0);
  for (auto& c : wire_chan_tx_bytes) c.store(0);
  for (auto& c : wire_chan_rx_bytes) c.store(0);
  wire_syscalls_tx.store(0);
  wire_syscalls_rx.store(0);
  wire_cross_syscalls_tx.store(0);
  wire_cross_syscalls_rx.store(0);
  for (auto& c : wire_chan_syscalls_tx) c.store(0);
  for (auto& c : wire_chan_syscalls_rx) c.store(0);
  std::lock_guard<std::mutex> lk(straggler_mutex_);
  straggler_counts_.clear();
}

std::string Metrics::SnapshotJson(const RuntimeInfo& info) const {
  std::string out = "{";
  Append(out, "\"initialized\":%s,\"rank\":%d,\"size\":%d,",
         info.initialized ? "true" : "false", info.rank, info.size);

  AppendOps(out, "ops", host_ops);
  AppendOps(out, "device_ops", device_ops);

  out += "\"negotiation_us\":" + negotiation_us.Json() + ",";
  out += "\"queue_us\":" + queue_us.Json() + ",";
  out += "\"wire_us\":" + wire_us.Json() + ",";

  // Control-plane phase profile (docs/scale.md). Zero-count phases are
  // skipped like empty op classes — snapshots stay compact.
  out += "\"control_phase\":{";
  {
    bool first = true;
    for (int i = 0; i < kPhaseCount; i++) {
      if (control_phase_us[i].count() == 0) continue;
      Append(out, "%s\"%s\":", first ? "" : ",", ControlPhaseName(i));
      out += control_phase_us[i].Json();
      first = false;
    }
  }
  out += "},";

  int64_t fr = fused_responses.load(std::memory_order_relaxed);
  int64_t fb = fusion_fill_bytes.load(std::memory_order_relaxed);
  int64_t fc = fusion_capacity_bytes.load(std::memory_order_relaxed);
  Append(out, "\"fusion\":{\"fused_responses\":%lld,\"fill_bytes\":%lld,"
              "\"capacity_bytes\":%lld,\"fill_ratio\":%.6f},",
         (long long)fr, (long long)fb, (long long)fc,
         fc > 0 ? (double)fb / (double)fc : 0.0);

  Append(out, "\"cycle\":{\"count\":%lld,\"stalls\":%lld,"
              "\"overrun_us\":%lld},",
         (long long)cycles.load(std::memory_order_relaxed),
         (long long)cycle_stalls.load(std::memory_order_relaxed),
         (long long)cycle_overrun_us.load(std::memory_order_relaxed));

  double lookups = (double)(info.cache_hits + info.cache_misses);
  Append(out, "\"cache\":{\"hits\":%lld,\"misses\":%lld,\"entries\":%lld,"
              "\"hit_bytes\":%lld,\"hit_rate\":%.6f},",
         (long long)info.cache_hits, (long long)info.cache_misses,
         (long long)info.cache_entries, (long long)info.cache_hit_bytes,
         lookups > 0 ? (double)info.cache_hits / lookups : 0.0);

  {
    std::lock_guard<std::mutex> lk(straggler_mutex_);
    out += "\"straggler\":{\"last_rank_counts\":[";
    for (size_t i = 0; i < straggler_counts_.size(); i++) {
      Append(out, "%s%lld", i ? "," : "",
             (long long)straggler_counts_[i]);
    }
    out += "],\"skew_us\":" + straggler_skew_us.Json() + "},";
  }

  int64_t wtx = wire_tx_bytes.load(std::memory_order_relaxed);
  int64_t wrx = wire_rx_bytes.load(std::memory_order_relaxed);
  int64_t wtxl = wire_tx_logical_bytes.load(std::memory_order_relaxed);
  int64_t wrxl = wire_rx_logical_bytes.load(std::memory_order_relaxed);
  int64_t ctx = wire_cross_tx_bytes.load(std::memory_order_relaxed);
  int64_t crx = wire_cross_rx_bytes.load(std::memory_order_relaxed);
  int64_t ctxl =
      wire_cross_tx_logical_bytes.load(std::memory_order_relaxed);
  int64_t crxl =
      wire_cross_rx_logical_bytes.load(std::memory_order_relaxed);
  Append(out, "\"wire\":{\"tx_bytes\":%lld,\"rx_bytes\":%lld,"
              "\"tx_logical_bytes\":%lld,\"rx_logical_bytes\":%lld,"
              "\"compression_ratio\":%.6f,"
              "\"cross_tx_bytes\":%lld,\"cross_rx_bytes\":%lld,"
              "\"cross_tx_logical_bytes\":%lld,"
              "\"cross_rx_logical_bytes\":%lld,"
              "\"cross_compression_ratio\":%.6f,",
         (long long)wtx, (long long)wrx, (long long)wtxl, (long long)wrxl,
         wtxl > 0 ? (double)wtx / (double)wtxl : 1.0,
         (long long)ctx, (long long)crx, (long long)ctxl, (long long)crxl,
         ctxl > 0 ? (double)ctx / (double)ctxl : 1.0);
  {
    // Per-stripe-channel tx/rx (docs/wire.md): emitted through the
    // highest slot that ever moved bytes (channel 0 always present),
    // summing exactly to tx/rx_bytes — stripe imbalance is a first-
    // class signal, not an average.
    int hi = 0;
    for (int c = 1; c < kWireChannelSlots; c++) {
      if (wire_chan_tx_bytes[c].load(std::memory_order_relaxed) ||
          wire_chan_rx_bytes[c].load(std::memory_order_relaxed)) {
        hi = c;
      }
    }
    out += "\"channels\":[";
    for (int c = 0; c <= hi; c++) {
      Append(out, "%s{\"channel\":%d,\"tx_bytes\":%lld,"
                  "\"rx_bytes\":%lld}",
             c ? "," : "", c,
             (long long)wire_chan_tx_bytes[c].load(
                 std::memory_order_relaxed),
             (long long)wire_chan_rx_bytes[c].load(
                 std::memory_order_relaxed));
    }
    out += "],";
  }
  {
    // Transport syscall budget (docs/wire.md "Syscall budget"): calls
    // ISSUED (EAGAIN spins included) — the io_uring baseline (ROADMAP
    // item 3). Same conventions as the byte buckets: cross is the
    // plane-1 slice, channels sum exactly to the totals.
    int64_t stx = wire_syscalls_tx.load(std::memory_order_relaxed);
    int64_t srx = wire_syscalls_rx.load(std::memory_order_relaxed);
    int64_t cstx =
        wire_cross_syscalls_tx.load(std::memory_order_relaxed);
    int64_t csrx =
        wire_cross_syscalls_rx.load(std::memory_order_relaxed);
    double gb = (double)(wtx + wrx) / 1e9;
    Append(out, "\"syscalls\":{\"tx_calls\":%lld,\"rx_calls\":%lld,"
                "\"cross_tx_calls\":%lld,\"cross_rx_calls\":%lld,"
                "\"per_gb\":%.3f,",
           (long long)stx, (long long)srx, (long long)cstx,
           (long long)csrx,
           gb > 0 ? (double)(stx + srx) / gb : 0.0);
    int hi = 0;
    for (int c = 1; c < kWireChannelSlots; c++) {
      if (wire_chan_syscalls_tx[c].load(std::memory_order_relaxed) ||
          wire_chan_syscalls_rx[c].load(std::memory_order_relaxed)) {
        hi = c;
      }
    }
    out += "\"channels\":[";
    for (int c = 0; c <= hi; c++) {
      Append(out, "%s{\"channel\":%d,\"tx_calls\":%lld,"
                  "\"rx_calls\":%lld}",
             c ? "," : "", c,
             (long long)wire_chan_syscalls_tx[c].load(
                 std::memory_order_relaxed),
             (long long)wire_chan_syscalls_rx[c].load(
                 std::memory_order_relaxed));
    }
    out += "]},";
  }
  // Step-anatomy overlap ledger (docs/metrics.md): how much of the
  // wire time above was hidden under concurrent wire activity, per
  // step window and plane.
  out += "\"overlap\":" + GlobalLedger().Json() + "},";

  Append(out, "\"elastic\":{\"epoch\":%lld,\"faults_detected\":%lld,"
              "\"faults_recovered\":%lld,\"ranks_blacklisted\":%lld,"
              "\"ranks_rejoined\":%lld,\"heals\":%lld,\"retries\":%lld,"
              "\"crc_errors\":%lld,\"detect_us\":",
         (long long)info.epoch,
         (long long)faults_detected.load(std::memory_order_relaxed),
         (long long)faults_recovered.load(std::memory_order_relaxed),
         (long long)ranks_blacklisted.load(std::memory_order_relaxed),
         (long long)ranks_rejoined.load(std::memory_order_relaxed),
         (long long)wire_heals.load(std::memory_order_relaxed),
         (long long)wire_retries.load(std::memory_order_relaxed),
         (long long)crc_errors.load(std::memory_order_relaxed));
  out += fault_detect_us.Json() + "},";

  Append(out, "\"errors\":%lld,",
         (long long)errors.load(std::memory_order_relaxed));
  const char* cp =
      (info.cross_plane >= 0 && info.cross_plane < kCrossPlaneModeCount)
          ? CrossPlaneModeNames()[info.cross_plane]
          : "auto";
  const char* codec_name =
      info.wire_codec == 2 ? "int8" : (info.wire_codec == 1 ? "bf16"
                                                            : "off");
  Append(out, "\"knobs\":{\"fusion_threshold_bytes\":%lld,"
              "\"cycle_time_ms\":%.6f,\"ring_chunk_bytes\":%lld,"
              "\"wire_compression\":%s,\"wire_codec\":\"%s\","
              "\"wire_channels\":%lld,"
              "\"wire_channels_established\":%lld,\"simd\":%s,"
              "\"wire_timeout_ms\":%lld,"
              "\"wire_retry_attempts\":%lld,"
              "\"wire_retry_backoff_ms\":%lld,\"wire_crc\":%s,"
              "\"cross_plane\":\"%s\",\"hier_split\":%lld,"
              "\"cross_compression\":%s}}",
         (long long)info.fusion_threshold_bytes, info.cycle_time_ms,
         (long long)info.ring_chunk_bytes,
         info.wire_compression ? "true" : "false", codec_name,
         (long long)info.wire_channels,
         (long long)info.wire_channels_established,
         info.simd ? "true" : "false",
         (long long)info.wire_timeout_ms,
         (long long)info.wire_retry_attempts,
         (long long)info.wire_retry_backoff_ms,
         info.wire_crc ? "true" : "false", cp,
         (long long)info.hier_split,
         info.cross_compression ? "true" : "false");
  return out;
}

Metrics& GlobalMetrics() {
  static Metrics* m = new Metrics();  // never destroyed: API threads may
  return *m;                          // record during process teardown
}

// ---- per-step overlap ledger ------------------------------------------

void OverlapLedger::StepBegin(int64_t ts_us) {
  std::lock_guard<std::mutex> lk(mu_);
  open_ = true;
  begin_us_ = ts_us;
  for (auto& s : spans_) s.clear();
  waits_.clear();
}

int64_t OverlapLedger::StepEnd(int64_t ts_us) {
  std::lock_guard<std::mutex> lk(mu_);
  if (!open_) return -1;
  open_ = false;
  // The union of API-thread wait intervals, clipped to the window —
  // shared across both planes (a blocked thread is blocked regardless
  // of which plane's bytes are moving). Wire time under this union is
  // `exposed`; the remainder ran while the host kept computing.
  std::vector<std::pair<int64_t, int64_t>> wait_union;
  wait_union.reserve(waits_.size());
  for (auto& [a, b] : waits_) {
    int64_t lo = a < begin_us_ ? begin_us_ : a;
    int64_t hi = b > ts_us ? ts_us : b;
    if (hi > lo) wait_union.emplace_back(lo, hi);
  }
  std::sort(wait_union.begin(), wait_union.end());
  size_t w = 0;
  for (size_t i = 1; i < wait_union.size(); i++) {
    if (wait_union[i].first <= wait_union[w].second) {
      if (wait_union[i].second > wait_union[w].second)
        wait_union[w].second = wait_union[i].second;
    } else {
      wait_union[++w] = wait_union[i];
    }
  }
  if (!wait_union.empty()) wait_union.resize(w + 1);
  waits_.clear();
  for (int p = 0; p < 2; p++) {
    auto& spans = spans_[p];
    int64_t total = 0, exposed = 0;
    // Clip to the window. total and exposed come from the SAME
    // clipped set, so exposed + hidden == total is exact by
    // construction (the reconciliation contract). Time clipped OFF
    // (a span straddling the step boundary, or a racing span entirely
    // outside) books as unattributed — every span microsecond lands
    // somewhere, so the ledger stays reconcilable against the wire_us
    // histogram.
    std::vector<std::pair<int64_t, int64_t>> clipped;
    clipped.reserve(spans.size());
    for (auto& [a, b] : spans) {
      int64_t lo = a < begin_us_ ? begin_us_ : a;
      int64_t hi = b > ts_us ? ts_us : b;
      if (hi < lo) {
        unattributed_us_ += b - a;  // fully outside (racing span)
        continue;
      }
      clipped.emplace_back(lo, hi);
      total += hi - lo;
      unattributed_us_ += (b - a) - (hi - lo);  // the clipped-off part
    }
    // exposed = measure of (clipped spans) ∩ (wait union): both lists
    // are sorted and disjoint-merged, one linear two-pointer sweep.
    std::sort(clipped.begin(), clipped.end());
    size_t wi = 0;
    for (auto& [lo, hi] : clipped) {
      while (wi < wait_union.size() && wait_union[wi].second <= lo) wi++;
      for (size_t j = wi; j < wait_union.size(); j++) {
        int64_t olo = lo > wait_union[j].first ? lo : wait_union[j].first;
        int64_t ohi = hi < wait_union[j].second ? hi : wait_union[j].second;
        if (olo >= hi) break;
        if (ohi > olo) exposed += ohi - olo;
      }
    }
    PlaneLedger& pl = planes_[p];
    pl.last_total_us = total;
    pl.last_exposed_us = exposed;
    pl.last_hidden_us = total - exposed;
    pl.total_us += total;
    pl.exposed_us += exposed;
    pl.hidden_us += total - exposed;
    spans.clear();
  }
  steps_++;
  return ts_us - begin_us_;
}

void OverlapLedger::AddSpan(int plane, int64_t start_us, int64_t end_us) {
  if (end_us < start_us) return;
  if (plane != 1) plane = 0;
  std::lock_guard<std::mutex> lk(mu_);
  if (!open_ || end_us <= begin_us_) {
    unattributed_us_ += end_us - start_us;
    return;
  }
  // Bound the open-window span list: a window left open forever (a
  // driver that stopped marking — e.g. the optimizer boundary after
  // the last apply(), with eval traffic still flowing) must not grow
  // memory without limit. Past the cap, span time books unattributed
  // (reconcilable, just not union-decomposed) — 64k spans is ~1 MB
  // and far beyond any real step's collective count.
  if (spans_[plane].size() >= (size_t)kMaxSpansPerPlane) {
    unattributed_us_ += end_us - start_us;
    return;
  }
  spans_[plane].emplace_back(start_us, end_us);
}

void OverlapLedger::AddWait(int64_t start_us, int64_t end_us) {
  if (end_us <= start_us) return;
  std::lock_guard<std::mutex> lk(mu_);
  // Waits outside any window are dropped, not unattributed: they are
  // host time, not wire time — nothing to reconcile. Same cap story
  // as AddSpan; a dropped wait under-reports exposure, never breaks
  // exposed + hidden == total.
  if (!open_ || end_us <= begin_us_) return;
  if (waits_.size() >= (size_t)kMaxSpansPerPlane) return;
  waits_.emplace_back(start_us, end_us);
}

void OverlapLedger::Reset() {
  std::lock_guard<std::mutex> lk(mu_);
  open_ = false;
  begin_us_ = 0;
  steps_ = 0;
  unattributed_us_ = 0;
  for (auto& s : spans_) s.clear();
  waits_.clear();
  for (auto& p : planes_) p = PlaneLedger();
}

std::string OverlapLedger::Json() const {
  std::lock_guard<std::mutex> lk(mu_);
  int64_t exp_us = planes_[0].exposed_us + planes_[1].exposed_us;
  int64_t hid_us = planes_[0].hidden_us + planes_[1].hidden_us;
  int64_t tot_us = exp_us + hid_us;
  std::string out = "{";
  Append(out, "\"steps\":%lld,\"unattributed_us\":%lld,"
              "\"exposed_wire_ms\":%.3f,\"hidden_wire_ms\":%.3f,"
              "\"overlap_efficiency\":%.6f",
         (long long)steps_, (long long)unattributed_us_,
         (double)exp_us / 1000.0, (double)hid_us / 1000.0,
         tot_us > 0 ? (double)hid_us / (double)tot_us : 0.0);
  const char* names[2] = {"intra", "cross"};
  for (int p = 0; p < 2; p++) {
    const PlaneLedger& pl = planes_[p];
    Append(out, ",\"%s\":{\"exposed_us\":%lld,\"hidden_us\":%lld,"
                "\"total_us\":%lld,\"overlap_efficiency\":%.6f,"
                "\"last_exposed_us\":%lld,\"last_hidden_us\":%lld,"
                "\"last_total_us\":%lld}",
           names[p], (long long)pl.exposed_us, (long long)pl.hidden_us,
           (long long)pl.total_us,
           pl.total_us > 0 ? (double)pl.hidden_us / (double)pl.total_us
                           : 0.0,
           (long long)pl.last_exposed_us, (long long)pl.last_hidden_us,
           (long long)pl.last_total_us);
  }
  out += "}";
  return out;
}

OverlapLedger& GlobalLedger() {
  static OverlapLedger* l = new OverlapLedger();  // lifetime contract
  return *l;                                      // as GlobalMetrics
}

}  // namespace hvdtpu
