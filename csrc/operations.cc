// Global state + background coordination loop + C API implementation.
// Reference analog: horovod/common/operations.cc (InitializeHorovodOnce,
// BackgroundThreadLoop, RunLoopOnce, EnqueueTensorAllreduce, horovod_init,
// ...) and horovod/common/global_state.h (HorovodGlobalState).

#include "operations.h"

#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/wait.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cctype>
#include <csignal>
#include <unordered_set>
#include <condition_variable>
#include <cstring>
#include <mutex>
#include <thread>
#include <unordered_map>

#include "controller.h"
#include "events.h"
#include "logging.h"
#include "message.h"
#include "metrics.h"
#include "parameter_manager.h"
#include "process_set.h"
#include "ring_ops.h"
#include "tensor_queue.h"
#include "timeline.h"
#include "wire.h"

namespace hvdtpu {
namespace {

int64_t EnvInt64(const char* name, int64_t dflt) {
  const char* v = std::getenv(name);
  return v ? strtoll(v, nullptr, 10) : dflt;
}

double EnvDouble(const char* name, double dflt) {
  const char* v = std::getenv(name);
  return v ? strtod(v, nullptr) : dflt;
}

std::string EnvStr(const char* name, const std::string& dflt) {
  const char* v = std::getenv(name);
  return v ? std::string(v) : dflt;
}

// Completed-op records, polled from Python by integer handle.
// Reference analog: horovod/torch/handle_manager.cc.
class HandleManager {
 public:
  int Allocate() {
    std::lock_guard<std::mutex> lk(mutex_);
    int h = next_++;
    records_[h];  // default: in-flight
    return h;
  }
  void MarkDone(int handle, const Status& status, TensorTableEntry* entry) {
    std::lock_guard<std::mutex> lk(mutex_);
    auto it = records_.find(handle);
    if (it == records_.end()) return;
    it->second.done = true;
    it->second.status = status;
    if (entry != nullptr) {
      it->second.managed_output = std::move(entry->managed_output);
      it->second.output_shape = std::move(entry->output_shape);
    }
    cv_.notify_all();
  }
  bool Poll(int handle, bool* done) {
    std::lock_guard<std::mutex> lk(mutex_);
    auto it = records_.find(handle);
    if (it == records_.end()) return false;
    *done = it->second.done;
    return true;
  }
  bool Wait(int handle, Status* status) {
    std::unique_lock<std::mutex> lk(mutex_);
    auto it = records_.find(handle);
    if (it == records_.end()) return false;
    // Pointer, not iterator: unordered_map rehash (concurrent Allocate)
    // invalidates iterators but element addresses are stable.
    Record* rec = &it->second;
    cv_.wait(lk, [rec] { return rec->done; });
    *status = rec->status;
    return true;
  }
  struct Record {
    bool done = false;
    Status status;
    std::vector<uint8_t> managed_output;
    std::vector<int64_t> output_shape;
  };
  Record* GetLocked(int handle) {  // caller must hold lock via WithRecord
    auto it = records_.find(handle);
    return it == records_.end() ? nullptr : &it->second;
  }
  template <typename F>
  auto WithRecord(int handle, F&& f) {
    std::lock_guard<std::mutex> lk(mutex_);
    return f(GetLocked(handle));
  }
  void Release(int handle) {
    std::lock_guard<std::mutex> lk(mutex_);
    records_.erase(handle);
  }

 private:
  std::mutex mutex_;
  std::condition_variable cv_;
  std::unordered_map<int, Record> records_;
  int next_ = 0;
};

struct GlobalState {
  std::unique_ptr<Controller> controller;
  std::unique_ptr<ProcessSetTable> process_sets;
  std::unique_ptr<ParameterManager> param_manager;  // HOROVOD_AUTOTUNE
  bool timeline_mark_cycles = false;
  TensorQueue tensor_queue;
  HandleManager handles;
  Timeline timeline;
  std::thread background_thread;
  std::atomic<bool> initialized{false};
  std::atomic<bool> shutdown_requested{false};
  std::atomic<bool> loop_exited{false};
  // Loop exited because of a control- or data-plane failure (peer
  // lost) rather than a requested shutdown — enqueue failures in this
  // state are the elastic-recoverable condition (HorovodInternalError
  // in Python; details via hvdtpu_last_fault).
  std::atomic<bool> loop_failed{false};
  // Membership epoch of the current ring generation: 0 at init, bumped
  // by every hvdtpu_reinit. Stale-epoch traffic is fenced out by the
  // controller (docs/elastic.md).
  std::atomic<int64_t> epoch{0};
  int base_controller_port = 29500;  // epoch e listens on base + e
  // Last fault record, written once by the background loop when it
  // stops on a peer failure; read by hvdtpu_last_fault from API
  // threads. fault_ranks holds GLOBAL ranks (current numbering).
  std::mutex fault_mutex;
  bool faulted = false;
  bool fault_recovered = false;
  // True when every recorded rank is PROVABLY dead (EOF/RST, probe
  // sweep, coordinator notice) — the precondition for survivors to
  // agree on a survivor set without a coordinator. False = the record
  // holds only a timeout suspicion; recovery must go through the
  // driver (or full re-init), never driver-less reinit.
  bool fault_certain = false;
  int64_t fault_epoch = 0;
  std::vector<int32_t> fault_ranks;
  std::string fault_reason;
  // "peer" (process gone/unresponsive) or "corruption" (a live link
  // failed CRC verification past the retry budget, HOROVOD_WIRE_CRC).
  std::string fault_kind;
  int64_t fault_chunk = -1;  // corrupted chunk index (corruption only)
  int64_t fault_detect_us = 0;
  // Deterministic fault injection — the chaos-matrix grammar
  // (HOROVOD_FAULT_INJECT="<rank>:<op>[:<action>[:<param>]]"): when this
  // rank's op_counter reaches inject_op it executes the armed action at
  // the top of that collective — kill (SIGKILL, the r12 default),
  // stop:<ms> (SIGSTOP + forked SIGCONT waker: the transient stall the
  // healing ladder must ride out), reset (shutdown(2) every peer
  // socket: NIC death with the process alive), flip:<bit> (corrupt one
  // bit of the next CRC-framed wire chunk; negative bit = persistent,
  // forcing retry exhaustion), delay:<ms> (straggler sleep).
  // One-shot per ring generation (cleared at reinit so a renumbered
  // survivor can never inherit the victim's trigger).
  std::atomic<int32_t> inject_rank{-1};
  std::atomic<int64_t> inject_op{-1};
  std::atomic<int32_t> inject_action{0};  // FaultAction enum below
  std::atomic<int64_t> inject_param{0};
  std::atomic<int64_t> op_counter{0};  // executed collective responses
  int rank = 0, size = 1, local_rank = 0, local_size = 1;
  int cross_rank = 0, cross_size = 1;
  // Stripe sockets ESTABLISHED per neighbor pair this generation
  // (WireChannelsEnv at rendezvous; reinit rebuilds the same count).
  // The ACTIVE width is the process-global WireChannels() knob,
  // autotuned within [1, established].
  int wire_channels_established = 1;
  std::atomic<int64_t> fusion_threshold{64 * 1024 * 1024};
  std::atomic<double> cycle_time_ms{1.0};
  std::vector<uint8_t> fusion_buffer;  // reference: fusion_buffer_manager.cc
  // Join state: set once this rank's JOIN request is in flight; while set,
  // the bg thread synthesizes zero contributions for collectives this rank
  // never enqueued. Reference analog: global_state.h joined flag.
  std::atomic<bool> joined{false};
  std::atomic<int> last_joined_rank{-1};
  // Cross-plane collective engine (HOROVOD_CROSS_PLANE, docs/
  // redistribute.md): how host allreduces decompose over the two
  // transport planes. 0 auto (hierarchical when the layout tiles),
  // 1 ici (device plane preferred — enforced by the Python frontends;
  // host ops stay flat), 2 ring (always the flat host ring), 3 hier
  // (hierarchical required; warn + flat when the layout cannot tile).
  // The legacy HOROVOD_HIERARCHICAL_ALLREDUCE=1 spelling maps to hier.
  int cross_plane_mode = 0;
  // Active hierarchy split point: 0/1 = flat ring, s >= 2 = intra-slice
  // reduce-scatter over contiguous groups of s ranks, inter-slice
  // allreduce of the 1/s shards among same-local-rank peers, intra-
  // slice allgather. Atomic: the autotuner moves it mid-run (rides the
  // ResponseList like the ring knobs — rank-uniform per cycle).
  std::atomic<int32_t> hier_split{0};
  // bf16 wire codec on the INTER-SLICE hop only
  // (HOROVOD_CROSS_PLANE_COMPRESSION): the EQuARX cheap-wire recipe
  // applied to the DCN-priced fabric while intra-slice hops stay full
  // width. Independent of HOROVOD_WIRE_COMPRESSION (which compresses
  // every hop).
  bool cross_compression = false;
  // Barrier sequence numbers, PER process set; must stay aligned across a
  // set's members, including barriers a joined rank participated in only
  // via synthesis. A global counter would desync when only a subset of
  // ranks runs a set-scoped barrier.
  std::mutex barrier_mutex;
  std::unordered_map<int32_t, int64_t> barrier_counters;

  int64_t NextBarrierSeq(int32_t ps) {
    std::lock_guard<std::mutex> lk(barrier_mutex);
    return barrier_counters[ps]++;
  }
  void FastForwardBarrier(int32_t ps, int64_t seen) {
    std::lock_guard<std::mutex> lk(barrier_mutex);
    int64_t& c = barrier_counters[ps];
    if (c < seen + 1) c = seen + 1;
  }
};

GlobalState* g_state = nullptr;
std::mutex g_init_mutex;

// Chaos-matrix fault actions (HOROVOD_FAULT_INJECT grammar).
enum FaultAction : int32_t {
  kFaultKill = 0,
  kFaultStop = 1,
  kFaultReset = 2,
  kFaultFlip = 3,
  kFaultDelay = 4,
};

// flip's packed param: low 20 bits = bit index, bits 20..43 = frames
// to skip before flipping, bits 44+ = (stripe channel + 1) for a
// channel-filtered flip (0 = no filter; ArmWireFlip). 2^20 bits = a
// 128 KiB chunk — comfortably past any bit the modulo will keep
// anyway.
constexpr int kFlipSkipShift = 20;
constexpr int kFlipChanShift = 44;
constexpr int64_t kFlipBitMask = (1 << kFlipSkipShift) - 1;
constexpr int64_t kFlipSkipMask =
    (1LL << (kFlipChanShift - kFlipSkipShift)) - 1;

// reset's param: -1 = every registered peer fd (the NIC-died shape),
// >= 0 = only that stripe channel's fds (ONE dead NIC queue while the
// other K-1 stripes stay up — docs/wire.md).

// Strict grammar parse:
// "<rank>:<op>[:<action>[:<param>[:<skip>[:<chan>]]]]". Returns false
// on ANY malformed spec — the trigger must stay disarmed (a lenient
// parse reading garbage as 0:0 would kill rank 0 at its first
// collective). stop/delay require a positive ms param; flip requires a
// bit (negative = persistent |bit|) and takes an optional skip count
// and stripe channel (one-shot only); reset takes an optional stripe
// channel; kill takes none.
bool ParseFaultSpec(const std::string& spec, int32_t* rank, int64_t* op,
                    int32_t* action, int64_t* param) {
  std::vector<std::string> parts;
  size_t start = 0;
  while (true) {
    size_t colon = spec.find(':', start);
    if (colon == std::string::npos) {
      parts.push_back(spec.substr(start));
      break;
    }
    parts.push_back(spec.substr(start, colon - start));
    start = colon + 1;
  }
  if (parts.size() < 2 || parts.size() > 6) return false;
  auto parse_i64 = [](const std::string& s, int64_t* out) {
    if (s.empty()) return false;
    char* end = nullptr;
    int64_t v = strtoll(s.c_str(), &end, 10);
    if (end != s.c_str() + s.size()) return false;
    *out = v;
    return true;
  };
  int64_t rank_v = 0, op_v = 0, param_v = 0;
  if (!parse_i64(parts[0], &rank_v) || rank_v < 0) return false;
  if (!parse_i64(parts[1], &op_v) || op_v < 0) return false;
  int32_t action_v = kFaultKill;
  bool has_param = parts.size() >= 4;
  if (parts.size() >= 5 && parts[2] != "flip") return false;
  if (parts.size() >= 3) {
    if (parts[2] == "kill") {
      action_v = kFaultKill;
      if (has_param) return false;
    } else if (parts[2] == "stop") {
      action_v = kFaultStop;
      if (!has_param || !parse_i64(parts[3], &param_v) || param_v <= 0) {
        return false;
      }
    } else if (parts[2] == "reset") {
      action_v = kFaultReset;
      // Optional stripe channel: reset:<chan> aborts only that
      // channel's sockets.
      param_v = -1;
      if (has_param &&
          (!parse_i64(parts[3], &param_v) || param_v < 0 ||
           param_v >= kMaxWireChannels)) {
        return false;
      }
    } else if (parts[2] == "flip") {
      action_v = kFaultFlip;
      if (!has_param || !parse_i64(parts[3], &param_v)) return false;
      // A non-negative bit must fit the packed low field even WITHOUT
      // a skip — otherwise the decode would read phantom skip frames
      // out of the high bits and flip the wrong bit of the wrong
      // frame. (Negative = persistent |bit|, never packed.)
      if (param_v > kFlipBitMask) return false;
      if (parts.size() >= 5) {
        // flip:<bit>:<skip>[:<chan>] — skip data frames first,
        // optionally counting (and flipping) only on one stripe
        // channel (one-shot only).
        int64_t skip_v = 0;
        if (param_v < 0 || !parse_i64(parts[4], &skip_v) || skip_v < 0 ||
            skip_v > kFlipSkipMask) {
          return false;
        }
        param_v |= skip_v << kFlipSkipShift;
        if (parts.size() == 6) {
          int64_t chan_v = -1;
          if (!parse_i64(parts[5], &chan_v) || chan_v < 0 ||
              chan_v >= kMaxWireChannels) {
            return false;
          }
          param_v |= (chan_v + 1) << kFlipChanShift;
        }
      }
    } else if (parts[2] == "delay") {
      action_v = kFaultDelay;
      if (!has_param || !parse_i64(parts[3], &param_v) || param_v <= 0) {
        return false;
      }
    } else {
      return false;
    }
  }
  *rank = (int32_t)rank_v;
  *op = op_v;
  *action = action_v;
  *param = param_v;
  return true;
}

// ONE construction site for the controller config, shared by init and
// reinit so a knob added to one can never silently diverge in the
// other (a re-formed ring must behave exactly like a fresh one).
ControllerConfig MakeControllerConfig(GlobalState& st, int rank, int size,
                                      int64_t epoch, int port) {
  ControllerConfig cfg;
  cfg.rank = rank;
  cfg.size = size;
  cfg.process_sets = st.process_sets.get();
  cfg.controller_addr = EnvStr("HOROVOD_CONTROLLER_ADDR", "127.0.0.1");
  cfg.controller_port = port;
  cfg.fusion_threshold_bytes = st.fusion_threshold;
  cfg.cache_capacity = EnvInt64("HOROVOD_CACHE_CAPACITY", 1024);
  cfg.stall_warning_secs = EnvDouble("HOROVOD_STALL_CHECK_TIME", 60.0);
  cfg.stall_check_enabled =
      EnvInt64("HOROVOD_STALL_CHECK_DISABLE", 0) == 0;
  cfg.epoch = epoch;
  cfg.heartbeat_timeout_ms = EnvInt64("HOROVOD_HEARTBEAT_TIMEOUT_MS", 0);
  cfg.start_timeout_ms =
      (int64_t)(EnvDouble("HOROVOD_START_TIMEOUT", 60.0) * 1000.0);
  // HOROVOD_CONTROLLER=mpi: zero-TCP mode — control negotiation AND
  // ring data ride the registered external transport (mpi4py
  // point-to-point; the frontend registers callbacks before init).
  cfg.use_external_transport = EnvStr("HOROVOD_CONTROLLER", "") == "mpi";
  // HOROVOD_CONTROL_TREE=<fanout>: tree-structured negotiation round
  // (docs/scale.md) — 0/1 keeps the flat star.
  cfg.tree_fanout = (int)EnvInt64("HOROVOD_CONTROL_TREE", 0);
  // Stripe sockets per neighbor pair (HOROVOD_WIRE_CHANNELS). From the
  // ENV, not the active knob: a reinit must provision what the env
  // promised even if the tuner had narrowed the active width. The
  // external transport's mailbox fds carry no channel id — K stays 1.
  cfg.wire_channels =
      cfg.use_external_transport ? 1 : WireChannelsEnv();
  st.wire_channels_established = cfg.wire_channels;
  return cfg;
}

DataType ToDataType(int dtype) { return (DataType)dtype; }

// ONE construction site for the autotuner, shared by init and reinit:
// the hier-split grid is derived from the CURRENT layout, so a re-formed
// world tunes over ITS divisors instead of stomping the reinit-derived
// split with a value from the dead layout's grid (and the old samples
// scored a different world anyway — fresh sampling is the honest
// restart).
void InitAutotune(GlobalState& st) {
  if (EnvInt64("HOROVOD_AUTOTUNE", 0) == 0) {
    st.param_manager.reset();
    return;
  }
  st.param_manager = std::make_unique<ParameterManager>();
  // Hierarchy-split grid: on an eligible layout the split point is a
  // scored knob — flat (0) plus every divisor of local_size >= 2
  // (contiguous groups under host-major never straddle a host). An
  // explicit HOROVOD_CROSS_PLANE=hier keeps flat OFF the grid (the
  // user demanded the decomposition; the tuner may only move the
  // split point), mirroring the wire-compression philosophy.
  std::vector<int64_t> hier_values;
  int64_t split = st.hier_split.load();
  if (split > 1) {
    if (st.cross_plane_mode != 3) hier_values.push_back(0);
    for (int64_t d = 2; d <= st.local_size; d++) {
      if (st.local_size % d == 0 && st.size % d == 0) {
        hier_values.push_back(d);
      }
    }
  }
  st.param_manager->Initialize(
      st.fusion_threshold.load(), st.cycle_time_ms.load(),
      EnvStr("HOROVOD_AUTOTUNE_LOG", ""),
      (int)EnvInt64("HOROVOD_AUTOTUNE_STEPS", 20),
      EnvInt64("HOROVOD_AUTOTUNE_WINDOW_BYTES", 1 << 20),
      (int)EnvInt64("HOROVOD_AUTOTUNE_WINDOW_CYCLES", 20),
      RingChunkBytes(), WireCodec(),
      // Compression joins the grid only when the user opted into
      // compressed numerics; the tuner may still settle on OFF
      // (strictly more accurate), never the other way around.
      /*tune_wire_codec=*/WireCodec() != 0, std::move(hier_values),
      split,
      // 6th dimension: active stripe width, over the powers of two up
      // to the sockets actually established this generation — the
      // tuner can never ask the wire for channels rendezvous did not
      // build.
      WireChannels(), st.wire_channels_established);
}

void ApplyPostOp(TensorTableEntry& e, void* buf, int64_t count, int size) {
  double post = e.postscale_factor;
  if (e.reduce_op == ReduceOp::AVERAGE) post /= (double)size;
  ScaleBuffer(buf, count, e.dtype, post);
}

// Flat ring, or the three-phase cross-plane decomposition when the
// hierarchy split is active and the layout allows (global set, >1
// slice, >1 rank per slice, host-major ranks).
// Reference analog: the NCCLAllreduce vs NCCLHierarchicalAllreduce pick
// under HOROVOD_HIERARCHICAL_ALLREDUCE.
Status RingAllreduce(GlobalState& st, DataPlane* dp, void* buf,
                     int64_t count, DataType dt, ReduceOp op,
                     double postscale = 1.0) {
  // hier_split is only > 1 after the collective eligibility check at
  // init (homogeneous host-major layout) — so the remaining per-call
  // condition is just "global process set". Splits smaller than
  // local_size (autotuned intermediate points) group contiguous ranks,
  // which under the host-major requirement never straddles a host.
  int split = st.hier_split.load(std::memory_order_relaxed);
  if (split > 1 && dp->size() == st.size) {
    return dp->HierarchicalAllreduce(buf, count, dt, op, split,
                                     postscale, st.cross_compression);
  }
  return dp->Allreduce(buf, count, dt, op, postscale);
}

// Effective post-ring scale for one entry (AVERAGE divides by size).
double PostFactor(const TensorTableEntry& e, int size) {
  double post = e.postscale_factor;
  if (e.reduce_op == ReduceOp::AVERAGE) post /= (double)size;
  return post;
}

bool IsLinearOp(ReduceOp op) {
  return op == ReduceOp::SUM || op == ReduceOp::AVERAGE;
}

Status ExecuteAllreduce(GlobalState& st, DataPlane* dp,
                        std::vector<TensorTableEntry>& entries) {
  if (entries.size() == 1) {
    auto& e = entries[0];
    if (e.output != e.input) {
      std::memcpy(e.output, e.input, (size_t)e.SizeBytes());
    }
    double post = PostFactor(e, dp->size());
    if (e.prescale_factor != 1.0 &&
        e.dtype == DataType::HVDTPU_BFLOAT16 && IsLinearOp(e.reduce_op)) {
      // bf16 pre/postscale fold: sum(pre*x) == pre*sum(x) for linear
      // ops, so the pre-ring pass — which would round every element to
      // bf16 once more AND traverse the buffer — folds into the single
      // post-ring scale. bf16 shares f32's exponent range, so deferring
      // the scale cannot overflow a partial the prescaled run would
      // have kept finite (fp16 keeps its overflow-guard prescale).
      post *= e.prescale_factor;
    } else {
      ScaleBuffer(e.output, e.NumElements(), e.dtype, e.prescale_factor);
    }
    st.timeline.ActivityStart(e.name, "RING_ALLREDUCE");
    Status s;
    {
      ScopedLatency wire(GlobalMetrics().wire_us);
      // The postscale rides into the ring: the compressed engine folds
      // it into its final bf16->f32 decode pass, the uncompressed ring
      // applies it after the allgather phase — bit-identical either way.
      s = RingAllreduce(st, dp, e.output, e.NumElements(), e.dtype,
                        e.reduce_op, post);
    }
    st.timeline.ActivityEnd(e.name);
    return s;
  }
  // Fused path: pack into the fusion buffer, one ring allreduce, unpack.
  // Reference analog: MemcpyInFusionBuffer / MemcpyOutFusionBuffer
  // (ops/collective_operations.cc); on GPU a batched CUDA kernel, here memcpy.
  int64_t total = 0;
  for (auto& e : entries) total += e.SizeBytes();
  if ((int64_t)st.fusion_buffer.size() < total) st.fusion_buffer.resize(total);
  uint8_t* base = st.fusion_buffer.data();
  int64_t off = 0;
  for (auto& e : entries) {
    st.timeline.ActivityStart(e.name, "MEMCPY_IN_FUSION_BUFFER");
    std::memcpy(base + off, e.input, (size_t)e.SizeBytes());
    ScaleBuffer(base + off, e.NumElements(), e.dtype, e.prescale_factor);
    st.timeline.ActivityEnd(e.name);
    off += e.SizeBytes();
  }
  // Fusion-buffer fill accounting: how much of the threshold one fused
  // round actually packed (a persistently low ratio means the cycle time
  // is draining the queue before the buffer fills — an autotune signal).
  {
    Metrics& m = GlobalMetrics();
    m.fused_responses.fetch_add(1, std::memory_order_relaxed);
    m.fusion_fill_bytes.fetch_add(total, std::memory_order_relaxed);
    m.fusion_capacity_bytes.fetch_add(st.fusion_threshold.load(),
                                      std::memory_order_relaxed);
  }
  DataType dt = entries[0].dtype;
  int64_t count = total / DataTypeSize(dt);
  // Uniform postscale folding: the common eager case — every gradient
  // averaged, no prescale — applies ONE postscale across the whole
  // fusion buffer inside the ring (the compressed engine does it for
  // free during the final decode pass) instead of per-entry passes.
  bool uniform_post = true;
  for (auto& e : entries) {
    if (e.prescale_factor != 1.0 ||
        e.postscale_factor != entries[0].postscale_factor ||
        e.reduce_op != entries[0].reduce_op) {
      uniform_post = false;
      break;
    }
  }
  double ring_post =
      uniform_post ? PostFactor(entries[0], dp->size()) : 1.0;
  for (auto& e : entries) st.timeline.ActivityStart(e.name, "RING_ALLREDUCE");
  Status s;
  {
    ScopedLatency wire(GlobalMetrics().wire_us);
    s = RingAllreduce(st, dp, base, count, dt, entries[0].reduce_op,
                      ring_post);
  }
  for (auto& e : entries) st.timeline.ActivityEnd(e.name);
  if (!s.ok()) return s;
  off = 0;
  for (auto& e : entries) {
    st.timeline.ActivityStart(e.name, "MEMCPY_OUT_FUSION_BUFFER");
    if (!uniform_post) ApplyPostOp(e, base + off, e.NumElements(), dp->size());
    std::memcpy(e.output, base + off, (size_t)e.SizeBytes());
    st.timeline.ActivityEnd(e.name);
    off += e.SizeBytes();
  }
  return Status::OK();
}

Status ExecuteEntry(GlobalState& st, DataPlane* dp,
                    const Response& response, TensorTableEntry& e) {
  switch (response.response_type) {
    case Response::ResponseType::ALLGATHER: {
      int64_t row_elems = 1;
      for (size_t i = 1; i < e.shape.size(); i++) row_elems *= e.shape[i];
      int64_t row_bytes = row_elems * DataTypeSize(e.dtype);
      std::vector<int64_t> bytes_per_rank(dp->size());
      int64_t total = 0, total_rows = 0;
      for (int r = 0; r < dp->size(); r++) {
        bytes_per_rank[r] = response.tensor_sizes[r] * row_bytes;
        total += bytes_per_rank[r];
        total_rows += response.tensor_sizes[r];
      }
      e.managed_output.resize((size_t)total);
      st.timeline.ActivityStart(e.name, "RING_ALLGATHER");
      Status s;
      {
        ScopedLatency wire(GlobalMetrics().wire_us);
        s = dp->Allgatherv(e.input, e.managed_output.data(),
                           bytes_per_rank);
      }
      st.timeline.ActivityEnd(e.name);
      if (!s.ok()) return s;
      e.output_shape = e.shape;
      if (e.output_shape.empty()) {
        e.output_shape = {total_rows};
      } else {
        e.output_shape[0] = total_rows;
      }
      return Status::OK();
    }
    case Response::ResponseType::BROADCAST: {
      int root = dp->GroupIndexOf(e.root_rank);  // root_rank is global
      if (root < 0) {
        return Status::InvalidArgument(
            "broadcast root rank " + std::to_string(e.root_rank) +
            " is not a member of process set " +
            std::to_string(e.process_set_id));
      }
      st.timeline.ActivityStart(e.name, "RING_BCAST");
      Status s;
      {
        ScopedLatency wire(GlobalMetrics().wire_us);
        s = dp->Broadcast(e.output, e.SizeBytes(), root);
      }
      st.timeline.ActivityEnd(e.name);
      return s;
    }
    case Response::ResponseType::ALLTOALL: {
      int64_t row_elems = 1;
      for (size_t i = 1; i < e.shape.size(); i++) row_elems *= e.shape[i];
      int64_t row_bytes = row_elems * DataTypeSize(e.dtype);
      std::vector<int64_t> splits = e.splits;
      if (splits.empty()) {
        int64_t first = e.shape.empty() ? 0 : e.shape[0];
        if (first % dp->size() != 0) {
          return Status::InvalidArgument(
              "alltoall without splits requires first dim divisible by size");
        }
        splits.assign(dp->size(), first / dp->size());
      }
      // Exchange splits so each rank learns its receive layout.
      // Reference analog: alltoall recvsplits exchange in the op layer.
      std::vector<int64_t> ones(dp->size(), sizeof(int64_t));
      e.recv_splits.assign(dp->size(), 0);
      Status s = dp->Alltoallv(splits.data(), ones, e.recv_splits.data(), ones);
      if (!s.ok()) return s;
      std::vector<int64_t> send_bytes(dp->size()), recv_bytes(dp->size());
      int64_t total_recv_rows = 0, total_recv_bytes = 0;
      for (int r = 0; r < dp->size(); r++) {
        send_bytes[r] = splits[r] * row_bytes;
        recv_bytes[r] = e.recv_splits[r] * row_bytes;
        total_recv_rows += e.recv_splits[r];
        total_recv_bytes += recv_bytes[r];
      }
      e.managed_output.resize((size_t)total_recv_bytes);
      st.timeline.ActivityStart(e.name, "ALLTOALL");
      {
        ScopedLatency wire(GlobalMetrics().wire_us);
        s = dp->Alltoallv(e.input, send_bytes, e.managed_output.data(),
                          recv_bytes);
      }
      st.timeline.ActivityEnd(e.name);
      if (!s.ok()) return s;
      e.output_shape = e.shape;
      if (e.output_shape.empty()) {
        e.output_shape = {total_recv_rows};
      } else {
        e.output_shape[0] = total_recv_rows;
      }
      return Status::OK();
    }
    case Response::ResponseType::REDUCESCATTER: {
      // First dim split as evenly as possible, remainder to lower ranks.
      // Reference analog: horovod reducescatter semantics.
      int64_t first = e.shape.empty() ? 1 : e.shape[0];
      int64_t row_elems = 1;
      for (size_t i = 1; i < e.shape.size(); i++) row_elems *= e.shape[i];
      std::vector<int64_t> elems_per_rank(dp->size());
      int64_t q = first / dp->size(), rem = first % dp->size();
      std::vector<int64_t> rows(dp->size());
      for (int r = 0; r < dp->size(); r++) {
        rows[r] = q + (r < rem ? 1 : 0);
        elems_per_rank[r] = rows[r] * row_elems;
      }
      e.managed_output.resize(
          (size_t)(elems_per_rank[dp->rank()] * DataTypeSize(e.dtype)));
      // Prescale on a copy to keep caller input pristine.
      std::vector<uint8_t> scaled;
      const void* in = e.input;
      if (e.prescale_factor != 1.0) {
        scaled.assign((const uint8_t*)e.input,
                      (const uint8_t*)e.input + e.SizeBytes());
        ScaleBuffer(scaled.data(), e.NumElements(), e.dtype,
                    e.prescale_factor);
        in = scaled.data();
      }
      st.timeline.ActivityStart(e.name, "RING_REDUCESCATTER");
      Status s;
      {
        ScopedLatency wire(GlobalMetrics().wire_us);
        s = dp->ReduceScatterv(in, e.managed_output.data(),
                               elems_per_rank, e.dtype, e.reduce_op);
      }
      st.timeline.ActivityEnd(e.name);
      if (!s.ok()) return s;
      ApplyPostOp(e, e.managed_output.data(), elems_per_rank[dp->rank()],
                  dp->size());
      e.output_shape = e.shape;
      if (e.output_shape.empty()) {
        e.output_shape = {rows[dp->rank()]};
      } else {
        e.output_shape[0] = rows[dp->rank()];
      }
      return Status::OK();
    }
    case Response::ResponseType::BARRIER:
      return dp->Barrier();
    default:
      return Status::Error("unsupported response type");
  }
}

// A joined rank participates in collectives it never enqueued by
// contributing zeros of the negotiated shape/dtype. The synthesized entry
// has handle = -1 (no caller waits on it).
// Reference analog: join support in operations.cc (zero-filled tensors).
void SynthesizeJoinedEntries(GlobalState& st, const Response& response,
                             std::vector<TensorTableEntry>* entries,
                             std::vector<std::vector<uint8_t>>* zero_bufs) {
  // Decode flattened [ndim, dims...] per tensor.
  std::vector<std::vector<int64_t>> shapes;
  size_t pos = 0;
  while (pos < response.tensor_shapes.size()) {
    int64_t ndim = response.tensor_shapes[pos++];
    std::vector<int64_t> shape(response.tensor_shapes.begin() + pos,
                               response.tensor_shapes.begin() + pos + ndim);
    pos += ndim;
    shapes.push_back(std::move(shape));
  }
  std::vector<TensorTableEntry> ordered;
  ordered.reserve(response.tensor_names.size());
  for (size_t i = 0; i < response.tensor_names.size(); i++) {
    const std::string& name = response.tensor_names[i];
    bool found = false;
    for (auto& e : *entries) {
      if (e.name == name) {
        ordered.push_back(std::move(e));
        found = true;
        break;
      }
    }
    if (found) continue;
    TensorTableEntry e;
    e.name = name;
    e.handle = -1;
    e.dtype = response.tensor_type;
    e.reduce_op = response.reduce_op;
    e.root_rank = response.root_rank;
    e.process_set_id = response.process_set_id;
    e.shape = i < shapes.size() ? shapes[i] : std::vector<int64_t>{};
    if (response.response_type == Response::ResponseType::ALLGATHER) {
      // This rank contributes zero rows.
      if (!e.shape.empty()) e.shape[0] = 0;
    }
    if (response.response_type == Response::ResponseType::BARRIER) {
      // Keep the local barrier sequence aligned with the ranks that
      // actually enqueued "__barrier__.N" (else every post-join barrier
      // would negotiate under mismatched names and hang).
      size_t dot = name.rfind('.');
      if (dot != std::string::npos) {
        st.FastForwardBarrier(response.process_set_id,
                              strtoll(name.c_str() + dot + 1, nullptr, 10));
      }
    }
    zero_bufs->emplace_back((size_t)e.SizeBytes(), 0);
    e.input = zero_bufs->back().data();
    e.output = zero_bufs->back().data();
    ordered.push_back(std::move(e));
  }
  *entries = std::move(ordered);
}

// Device data plane hook. The Python layer (horovod_tpu/jax/xla_ici.py)
// registers one callback; the background thread hands it each fused
// device Response in negotiated order — identical on every rank, so the
// per-rank XLA program launches line up into one collective. This is the
// TPU analog of the reference's op dispatch picking NCCL for GPU tensors
// (horovod/common/ops/operation_manager.cc).
typedef int32_t (*DeviceExecFn)(int32_t op_class, int32_t n,
                                const char** names,
                                const int64_t* shapes_flat, int32_t dtype,
                                int32_t reduce_op, int32_t root_rank,
                                int32_t process_set_id,
                                const int64_t* rank_sizes,
                                int32_t n_rank_sizes, char* err,
                                int32_t err_cap);
std::atomic<DeviceExecFn> g_device_exec{nullptr};

// Timeline activity for the device-plane execution phase, mirroring the
// host ring's RING_* spans (reference analog: NCCL_ALLREDUCE etc. marks
// in horovod/common/ops/nccl_operations.cc). Without these the device
// plane's trace showed negotiation then done, with execution invisible.
const char* DeviceActivityName(Response::ResponseType t) {
  switch (t) {
    case Response::ResponseType::ALLREDUCE: return "XLA_ALLREDUCE";
    case Response::ResponseType::ALLGATHER: return "XLA_ALLGATHER";
    case Response::ResponseType::BROADCAST: return "XLA_BROADCAST";
    case Response::ResponseType::ALLTOALL: return "XLA_ALLTOALL";
    case Response::ResponseType::REDUCESCATTER:
      return "XLA_REDUCESCATTER";
    default: return "XLA_COLLECTIVE";
  }
}

Status ExecuteDeviceResponse(GlobalState& st, const Response& response) {
  DeviceExecFn fn = g_device_exec.load();
  if (fn == nullptr) {
    return Status::PreconditionError(
        "device tensor enqueued but no device data plane is registered");
  }
  const char* activity = DeviceActivityName(response.response_type);
  for (auto& n : response.tensor_names) {
    st.timeline.ActivityStart(n, activity);
  }
  std::vector<const char*> names;
  names.reserve(response.tensor_names.size());
  for (auto& n : response.tensor_names) names.push_back(n.c_str());
  char err[512] = {0};
  int32_t rc = fn((int32_t)response.response_type,
                  (int32_t)response.tensor_names.size(), names.data(),
                  response.tensor_shapes.data(),
                  (int32_t)response.tensor_type,
                  (int32_t)response.reduce_op,
                  response.root_rank, response.process_set_id,
                  response.tensor_sizes.data(),
                  (int32_t)response.tensor_sizes.size(), err,
                  (int32_t)sizeof(err) - 1);
  for (auto& n : response.tensor_names) st.timeline.ActivityEnd(n);
  if (rc != 0) {
    return Status::Error(err[0] ? std::string(err)
                                : "device data plane execution failed");
  }
  return Status::OK();
}

// Fold one executed response into the metrics registry: op-class
// counts/tensors, payload bytes per plane, and per-entry queue latency.
void AccountResponse(const Response& response,
                     const std::vector<TensorTableEntry>& entries,
                     const Status& status) {
  Metrics& m = GlobalMetrics();
  int rt = (int)response.response_type;
  if (rt < 0 || rt >= Metrics::kOpClasses) return;
  OpCounters& oc =
      (response.device == 1 ? m.device_ops : m.host_ops)[rt];
  oc.responses.fetch_add(1, std::memory_order_relaxed);
  oc.tensors.fetch_add((int64_t)response.tensor_names.size(),
                       std::memory_order_relaxed);
  int64_t bytes = 0;
  if (response.device == 1) {
    // Device payloads never touch host buffers; the negotiated shapes
    // are the source of truth for what moved over ICI.
    bytes = ShapesTotalBytes(response);
  } else {
    switch (response.response_type) {
      case Response::ResponseType::ALLREDUCE:
      case Response::ResponseType::BROADCAST:
      case Response::ResponseType::REDUCESCATTER:
        for (auto& e : entries) bytes += e.SizeBytes();
        break;
      case Response::ResponseType::ALLGATHER:
      case Response::ResponseType::ALLTOALL:
        for (auto& e : entries) {
          bytes += (int64_t)e.managed_output.size();
        }
        break;
      default:
        break;
    }
  }
  oc.bytes.fetch_add(bytes, std::memory_order_relaxed);
  if (!status.ok()) m.errors.fetch_add(1, std::memory_order_relaxed);
}

// Black-box post-mortem dump (docs/metrics.md): append the live tail
// of the event ring to a per-rank JSONL file the moment a typed fault
// is recorded — BEFORE any handle wakes an API thread, so the causal
// window survives even if the process is about to be torn down by an
// unhandled exception. One header line carries the fault record plus a
// (unix_us, steady_us) clock pair, the same anchor contract as the
// timeline's CLOCK_SYNC event, so telemetry/postmortem.py can put
// every rank's events on one wall-clock axis. Disable with
// HOROVOD_BLACKBOX_DIR=off; default dir is $TMPDIR/hvdtpu_blackbox.
void DumpBlackBox(GlobalState& st, const Status& s,
                  const std::vector<int32_t>& ranks, bool certain,
                  int64_t detect_us) {
  std::string dir = EnvStr("HOROVOD_BLACKBOX_DIR", "");
  if (dir == "off" || dir == "none" || dir == "0") return;
  if (dir.empty()) {
    const char* tmp = std::getenv("TMPDIR");
    dir = std::string(tmp != nullptr && tmp[0] != '\0' ? tmp : "/tmp") +
          "/hvdtpu_blackbox";
  }
  ::mkdir(dir.c_str(), 0777);  // best-effort; open failure is the gate
  std::string path =
      dir + "/blackbox-rank" + std::to_string(st.rank) + ".jsonl";
  FILE* f = fopen(path.c_str(), "a");
  if (f == nullptr) {
    LOG_WARN("black-box dump skipped: cannot open %s", path.c_str());
    return;
  }
  int64_t unix_us =
      std::chrono::duration_cast<std::chrono::microseconds>(
          std::chrono::system_clock::now().time_since_epoch())
          .count();
  std::string hdr = "{\"kind\":\"blackbox_header\",\"rank\":" +
                    std::to_string(st.rank) +
                    ",\"size\":" + std::to_string(st.size) +
                    ",\"epoch\":" + std::to_string(st.epoch.load()) +
                    ",\"unix_us\":" + std::to_string(unix_us) +
                    ",\"steady_us\":" + std::to_string(MetricsNowUs()) +
                    ",\"fault\":{\"kind\":\"" +
                    (s.wire_corruption() ? "corruption" : "peer") +
                    "\",\"certain\":" + (certain ? "true" : "false") +
                    ",\"ranks\":[";
  for (size_t i = 0; i < ranks.size(); i++) {
    if (i) hdr += ',';
    hdr += std::to_string(ranks[i]);
  }
  hdr += "],\"detect_ms\":" + std::to_string(detect_us / 1000) +
         ",\"reason\":\"";
  for (char c : s.reason()) {
    if (c == '"' || c == '\\') hdr += '\\';
    hdr += (unsigned char)c < 0x20 ? ' ' : c;
  }
  hdr += "\"}}\n";
  fputs(hdr.c_str(), f);
  std::vector<EventRecord> evs;
  GlobalEvents().Snapshot(0, &evs);
  for (const auto& e : evs) {
    std::string line = EventJson(e);
    line += '\n';
    fputs(line.c_str(), f);
  }
  fclose(f);
}

// Write the fault record + metrics once the loop decides to stop on a
// peer failure. Attribution = the typed status's rank, any ranks the
// coordinator's fault notice named, plus a liveness probe over every
// data-plane socket (SIGKILLed peers show EOF on all their fds, so
// every survivor converges on the same dead set — the agreement the
// driver-less re-formation path in common/elastic.py relies on).
void RecordFault(GlobalState& st, const Status& s,
                 const std::vector<int64_t>& notice_ranks,
                 int64_t detect_us) {
  // PROOF first: coordinator notices, certain (EOF/RST) attributions,
  // and the socket probe sweep all name provably-dead processes, so
  // every survivor converges on the same set. A timeout's SUSPECTED
  // rank joins only when no proof exists anywhere — it may merely be a
  // live neighbor blocked on the real casualty, and mixing it with
  // proof would give survivors inconsistent survivor sets.
  std::vector<int32_t> ranks;
  for (int64_t r : notice_ranks) {
    if (r >= 0) ranks.push_back((int32_t)r);
  }
  if (s.fault_rank() >= 0 && s.fault_certain()) {
    ranks.push_back(s.fault_rank());
  }
  if (st.controller && st.controller->data_plane()) {
    for (int32_t r : st.controller->data_plane()->ProbeDeadPeers()) {
      ranks.push_back(r);
    }
  }
  bool certain = !ranks.empty();
  if (ranks.empty() && s.fault_rank() >= 0) {
    ranks.push_back(s.fault_rank());  // best-effort fallback, suspicion
  }
  std::sort(ranks.begin(), ranks.end());
  ranks.erase(std::unique(ranks.begin(), ranks.end()), ranks.end());
  // Wire corruption names a LIVE peer (the link is bad, not the
  // process): never "certain" in the membership sense, so driver-less
  // shrink can't misread a corrupting link as a dead rank.
  if (s.wire_corruption()) certain = false;
  {
    std::lock_guard<std::mutex> lk(st.fault_mutex);
    st.faulted = true;
    st.fault_recovered = false;
    st.fault_certain = certain;
    st.fault_epoch = st.epoch.load();
    st.fault_ranks = ranks;
    st.fault_reason = s.reason();
    st.fault_kind = s.wire_corruption() ? "corruption" : "peer";
    st.fault_chunk = s.wire_corruption() ? s.fault_chunk() : -1;
    st.fault_detect_us = detect_us;
  }
  Metrics& m = GlobalMetrics();
  m.faults_detected.fetch_add(1, std::memory_order_relaxed);
  m.fault_detect_us.Record(detect_us);
  // The fault event enters the ring BEFORE the dump so the black-box
  // tail ends with the fault it explains.
  GlobalEvents().Record(EventType::kFault,
                        s.wire_corruption() ? 1 : 0, certain ? 1 : 0,
                        st.epoch.load(),
                        ranks.empty() ? -1 : (int64_t)ranks[0]);
  DumpBlackBox(st, s, ranks, certain, detect_us);
}

// HOROVOD_FAULT_INJECT: execute the armed chaos action at the top of
// the inject_op-th executed collective on the matching rank. Responses
// are negotiated identically on every rank, so the counter indexes the
// same collective everywhere — the precision the chaos lane needs.
// Counted classes: everything that executes (JOIN bookkeeping and ERROR
// verdicts are skipped on every rank alike). Non-kill actions disarm
// before executing (one-shot by construction; kill needs no disarm).
void MaybeInjectFault(GlobalState& st) {
  int64_t idx = st.op_counter.fetch_add(1, std::memory_order_relaxed);
  if (st.inject_rank.load(std::memory_order_relaxed) != st.rank ||
      st.inject_op.load(std::memory_order_relaxed) != idx) {
    return;
  }
  const int32_t action = st.inject_action.load(std::memory_order_relaxed);
  const int64_t param = st.inject_param.load(std::memory_order_relaxed);
  // Forensics: the injection itself is part of the causal record — a
  // post-mortem over a chaos run shows chaos fired, then what broke.
  GlobalEvents().Record(EventType::kInject, action, 0, idx);
  switch (action) {
    case kFaultKill:
      LOG_WARN("HOROVOD_FAULT_INJECT: rank %d dying at collective %lld",
               st.rank, (long long)idx);
      raise(SIGKILL);
      break;
    case kFaultStop: {
      LOG_WARN("HOROVOD_FAULT_INJECT: rank %d SIGSTOPping %lld ms at "
               "collective %lld",
               st.rank, (long long)param, (long long)idx);
      st.inject_rank = -1;
      // A stopped process cannot wake itself: fork a waker that sleeps
      // out the stall and SIGCONTs the parent. The child touches only
      // async-signal-safe calls (we fork from a multi-threaded
      // process).
      pid_t waker = fork();
      if (waker == 0) {
        struct timespec ts;
        ts.tv_sec = param / 1000;
        ts.tv_nsec = (param % 1000) * 1000000L;
        nanosleep(&ts, nullptr);
        kill(getppid(), SIGCONT);
        _exit(0);
      }
      if (waker < 0) {
        // No waker, no SIGCONT: stopping now would turn a bounded
        // stall into a permanent one. Skip the injection loudly.
        LOG_WARN("HOROVOD_FAULT_INJECT: fork for stop waker failed "
                 "(%s); skipping the stall", strerror(errno));
        break;
      }
      raise(SIGSTOP);
      // Resumed: the waker has SIGCONTed us and is exiting — reap it
      // so chaos runs don't accumulate zombies.
      waitpid(waker, nullptr, 0);
      break;
    }
    case kFaultReset:
      LOG_WARN("HOROVOD_FAULT_INJECT: rank %d resetting %s peer "
               "socket(s) at collective %lld",
               st.rank,
               param < 0 ? "every" : "one stripe channel's",
               (long long)idx);
      st.inject_rank = -1;
      // The NIC-died shape: peer connections abort (they see EOF ->
      // certain attribution) while this process stays alive. A
      // channel param scopes the abort to ONE stripe's sockets — the
      // dead-NIC-queue case whose other K-1 channels must stay up.
      for (int fd : RegisteredFds((int)param)) ::shutdown(fd, SHUT_RDWR);
      break;
    case kFaultFlip: {
      const bool persistent = param < 0;
      const int64_t bit = persistent ? -param : (param & kFlipBitMask);
      const int64_t skip =
          persistent ? 0 : (param >> kFlipSkipShift) & kFlipSkipMask;
      const int64_t chan =
          persistent ? -1 : (param >> kFlipChanShift) - 1;
      LOG_WARN("HOROVOD_FAULT_INJECT: rank %d flipping wire bit %lld "
               "(skip %lld frames, channel %lld) at collective %lld%s",
               st.rank, (long long)bit, (long long)skip,
               (long long)chan, (long long)idx,
               persistent ? " (persistent)" : "");
      st.inject_rank = -1;
      ArmWireFlip(bit, persistent, skip, chan);
      break;
    }
    case kFaultDelay:
      LOG_WARN("HOROVOD_FAULT_INJECT: rank %d sleeping %lld ms at "
               "collective %lld",
               st.rank, (long long)param, (long long)idx);
      st.inject_rank = -1;
      std::this_thread::sleep_for(std::chrono::milliseconds(param));
      break;
    default:
      st.inject_rank = -1;
      break;
  }
}

Status ExecuteResponse(GlobalState& st, const Response& response) {
  if (response.response_type == Response::ResponseType::JOIN) {
    auto join_entries = st.tensor_queue.GetTensorEntriesFromResponse(response);
    st.last_joined_rank = response.last_joined_rank;
    st.joined = false;
    Status ok = Status::OK();
    for (auto& e : join_entries) {
      st.timeline.EntryDone(e.name);
      st.handles.MarkDone(e.handle, ok, &e);
    }
    return ok;
  }
  if (response.response_type != Response::ResponseType::ERROR) {
    MaybeInjectFault(st);
    GlobalEvents().Record(EventType::kResponseLaunch,
                          (int32_t)response.response_type,
                          (int32_t)response.device,
                          (int64_t)response.tensor_names.size(),
                          ShapesTotalBytes(response));
  }
  const int64_t exec_start_us = MetricsNowUs();
  // Resolve the data plane for this response's process set BEFORE touching
  // the local tensor queue: non-members get the broadcast ResponseList too,
  // and a same-named tensor of a different set may be in their queue.
  DataPlane* dp = st.controller->data_plane();
  DataPlane sub(0, 1, {});
  Status ps_status = Status::OK();
  if (response.process_set_id != 0 &&
      response.response_type != Response::ResponseType::ERROR) {
    std::vector<int32_t> members =
        st.process_sets->Ranks(response.process_set_id);
    if (members.empty()) {
      ps_status = Status::PreconditionError(
          "unknown process set " + std::to_string(response.process_set_id) +
          " (add_process_set must complete on every rank first)");
    } else {
      bool member = false;
      for (int32_t r : members) member = member || r == st.rank;
      if (!member) {
        // Not a participant: nothing to execute, nothing to resolve.
        return Status::OK();
      }
      sub = dp->Subset(members);
      dp = &sub;
    }
  }
  auto entries = st.tensor_queue.GetTensorEntriesFromResponse(response);
  std::vector<std::vector<uint8_t>> zero_bufs;
  if (st.joined.load() &&
      entries.size() < response.tensor_names.size() &&
      response.response_type != Response::ResponseType::ERROR &&
      response.device == 0) {
    // Device responses need no host zero buffers: the data-plane callback
    // receives every fused name+shape and synthesizes zero contributions
    // on-device for names this rank never enqueued.
    SynthesizeJoinedEntries(st, response, &entries, &zero_bufs);
  }
  {
    // Queue latency: caller enqueue -> execution start (covers local
    // waiting plus the coordinator holding out for straggler ranks).
    int64_t now = MetricsNowUs();
    Metrics& m = GlobalMetrics();
    for (auto& e : entries) {
      if (e.enqueue_us > 0) m.queue_us.Record(now - e.enqueue_us);
    }
  }
  Status status = Status::OK();
  if (!ps_status.ok()) {
    status = ps_status;
  } else if (response.response_type == Response::ResponseType::ERROR) {
    status = Status::PreconditionError(response.error_message);
  } else if (response.device == 1 &&
             response.response_type != Response::ResponseType::BARRIER) {
    status = ExecuteDeviceResponse(st, response);
  } else if (response.response_type == Response::ResponseType::ALLREDUCE) {
    status = ExecuteAllreduce(st, dp, entries);
  } else {
    for (auto& e : entries) {
      status = ExecuteEntry(st, dp, response, e);
      if (!status.ok()) break;
    }
  }
  AccountResponse(response, entries, status);
  if (status.peer_failure() || status.wire_corruption()) {
    // Record the fault BEFORE any handle wakes an API thread: the
    // Python error path reads hvdtpu_last_fault to type the exception,
    // so the record must already exist when synchronize() returns.
    RecordFault(st, status, {}, MetricsNowUs() - exec_start_us);
    st.loop_failed = true;
  }
  for (auto& e : entries) {
    st.timeline.EntryDone(e.name);
    st.handles.MarkDone(e.handle, status, &e);
  }
  return status;
}

// Payload bytes a response moves (autotune scoring input).
int64_t ResponseBytes(const Response& r) {
  if (r.response_type != Response::ResponseType::ALLREDUCE) return 0;
  return ShapesTotalBytes(r);
}

void BackgroundThreadLoop(GlobalState& st) {
  // Reference analog: operations.cc BackgroundThreadLoop / RunLoopOnce —
  // one coordination thread per process; each cycle drains the queue,
  // negotiates, executes, and sleeps out the remainder of the cycle time.
  while (true) {
    auto cycle_start = std::chrono::steady_clock::now();
    if (st.timeline_mark_cycles) st.timeline.MarkCycle();
    std::vector<Request> requests = st.tensor_queue.PopMessages();
    for (auto& r : requests) st.timeline.NegotiateStart(r.tensor_name);
    bool had_requests = !requests.empty();
    int64_t negotiate_start_us = MetricsNowUs();
    // Event-ring policy mirrors the histogram below: only ACTIVE
    // cycles are recorded (idle rounds would lap the ring with noise
    // and erase the causal window a post-mortem needs).
    if (had_requests) {
      GlobalEvents().Record(EventType::kNegotiateBegin,
                            (int32_t)requests.size());
    }
    ResponseList response_list;
    Status s = st.controller->ComputeResponseList(
        std::move(requests), st.shutdown_requested.load(), &response_list);
    // Negotiation latency per ACTIVE cycle (idle gather/bcast rounds
    // would swamp the histogram with sub-cycle-time noise).
    if (had_requests || !response_list.responses.empty()) {
      GlobalMetrics().negotiation_us.Record(MetricsNowUs() -
                                            negotiate_start_us);
      GlobalEvents().Record(EventType::kNegotiateEnd,
                            (int32_t)response_list.responses.size(),
                            response_list.shutdown ? 1 : 0);
    }
    if (!s.ok()) {
      LOG_ERROR("control plane failure: %s", s.reason().c_str());
      if (s.peer_failure()) {
        // fault_ranks rides the coordinator's fault notice when one was
        // received; detection latency = how long this round stalled.
        RecordFault(st, s, response_list.fault_ranks,
                    MetricsNowUs() - negotiate_start_us);
      }
      st.loop_failed = true;
      auto orphans = st.tensor_queue.RemoveAllEntries();
      for (auto& e : orphans) st.handles.MarkDone(e.handle, s, nullptr);
      break;
    }
    // Workers adopt coordinator-autotuned knobs (coordinator already has
    // them via SetAutotunedParams). Adoptions that MOVE a knob are
    // recorded in the event ring — the ResponseList re-broadcasts the
    // current values every cycle, so only changes are forensic signal.
    if (response_list.fusion_threshold_bytes > 0 && st.rank != 0) {
      if (st.fusion_threshold.load() !=
          response_list.fusion_threshold_bytes) {
        GlobalEvents().Record(EventType::kKnobAdopt, kKnobFusionBytes, 0,
                              response_list.fusion_threshold_bytes);
      }
      st.fusion_threshold = response_list.fusion_threshold_bytes;
    }
    if (response_list.cycle_time_ms > 0 && st.rank != 0) {
      if (st.cycle_time_ms.load() != response_list.cycle_time_ms) {
        GlobalEvents().Record(
            EventType::kKnobAdopt, kKnobCycleTimeMs, 0,
            (int64_t)(response_list.cycle_time_ms * 1000.0));
      }
      st.cycle_time_ms = response_list.cycle_time_ms;
    }
    // Ring knobs must flip on every rank in the SAME cycle (the chunk
    // split is the wire framing; compression is the wire width): the
    // coordinator adopted these at the END of the previous cycle, and
    // workers adopt here before executing this cycle's responses.
    if (response_list.ring_chunk_bytes >= 0 && st.rank != 0) {
      if (RingChunkBytes() != response_list.ring_chunk_bytes) {
        GlobalEvents().Record(EventType::kKnobAdopt, kKnobRingChunk, 0,
                              response_list.ring_chunk_bytes);
      }
      SetRingChunkBytes(response_list.ring_chunk_bytes);
    }
    if (response_list.wire_compression >= 0 && st.rank != 0) {
      // The field carries the full codec mode (0 off / 1 bf16 / 2
      // int8) — the wire width every rank must frame with.
      if (WireCodec() != response_list.wire_compression) {
        GlobalEvents().Record(EventType::kKnobAdopt, kKnobCompression, 0,
                              response_list.wire_compression);
      }
      SetWireCodec(response_list.wire_compression);
    }
    // The hierarchy split decides which plane sequence every rank's
    // next collective decomposes into — as framing-critical as the
    // chunk knob, so it flips in the same lockstep cycle.
    if (response_list.hier_split >= 0 && st.rank != 0) {
      if (st.hier_split.load() != response_list.hier_split) {
        GlobalEvents().Record(EventType::kKnobAdopt, kKnobHierSplit, 0,
                              response_list.hier_split);
      }
      st.hier_split = response_list.hier_split;
    }
    // The stripe width is the chunk round-robin framing: every rank
    // must cut the SAME chunk->channel schedule in the same cycle.
    if (response_list.wire_channels >= 1 && st.rank != 0) {
      if (WireChannels() != response_list.wire_channels) {
        GlobalEvents().Record(EventType::kKnobAdopt, kKnobWireChannels,
                              0, response_list.wire_channels);
      }
      SetWireChannels(response_list.wire_channels);
    }
    int64_t cycle_bytes = 0;
    bool faulted = false;
    for (auto& response : response_list.responses) {
      for (auto& n : response.tensor_names) st.timeline.NegotiateEnd(n);
      Status es = ExecuteResponse(st, response);
      cycle_bytes += ResponseBytes(response);
      if (es.peer_failure() || es.wire_corruption()) {
        // A peer died mid-collective: the ring is unrecoverable at this
        // epoch. ExecuteResponse already recorded the fault (before any
        // handle woke an API thread); drain everything still pending
        // with the typed status (no caller may hang) and stop —
        // survivors re-form via hvdtpu_reinit (docs/elastic.md).
        LOG_ERROR("data plane peer failure: %s", es.reason().c_str());
        auto orphans = st.tensor_queue.RemoveAllEntries();
        for (auto& e : orphans) st.handles.MarkDone(e.handle, es, nullptr);
        faulted = true;
        break;
      }
    }
    if (faulted) break;
    if (st.rank == 0 && st.param_manager &&
        st.param_manager->Update(cycle_bytes)) {
      // The coordinator committed a new autotuned config: one knob-
      // adoption event per knob that actually moved.
      EventRing& ev = GlobalEvents();
      if (st.fusion_threshold.load() !=
          st.param_manager->fusion_threshold_bytes()) {
        ev.Record(EventType::kKnobAdopt, kKnobFusionBytes, 0,
                  st.param_manager->fusion_threshold_bytes());
      }
      if (st.cycle_time_ms.load() != st.param_manager->cycle_time_ms()) {
        ev.Record(EventType::kKnobAdopt, kKnobCycleTimeMs, 0,
                  (int64_t)(st.param_manager->cycle_time_ms() * 1000.0));
      }
      if (RingChunkBytes() != st.param_manager->ring_chunk_bytes()) {
        ev.Record(EventType::kKnobAdopt, kKnobRingChunk, 0,
                  st.param_manager->ring_chunk_bytes());
      }
      if (WireCompression() != st.param_manager->wire_compression()) {
        ev.Record(EventType::kKnobAdopt, kKnobCompression, 0,
                  st.param_manager->wire_compression() ? 1 : 0);
      }
      if (st.hier_split.load() != (int32_t)st.param_manager->hier_split()) {
        ev.Record(EventType::kKnobAdopt, kKnobHierSplit, 0,
                  st.param_manager->hier_split());
      }
      if (WireChannels() != st.param_manager->wire_channels()) {
        ev.Record(EventType::kKnobAdopt, kKnobWireChannels, 0,
                  st.param_manager->wire_channels());
      }
      st.fusion_threshold = st.param_manager->fusion_threshold_bytes();
      st.cycle_time_ms = st.param_manager->cycle_time_ms();
      SetRingChunkBytes(st.param_manager->ring_chunk_bytes());
      SetWireCodec(st.param_manager->wire_codec());
      st.hier_split = (int32_t)st.param_manager->hier_split();
      SetWireChannels(st.param_manager->wire_channels());
      st.controller->SetAutotunedParams(
          st.fusion_threshold.load(), st.cycle_time_ms.load(),
          st.param_manager->ring_chunk_bytes(),
          st.param_manager->wire_codec(),
          (int32_t)st.param_manager->hier_split(),
          (int32_t)st.param_manager->wire_channels());
    }
    if (response_list.shutdown) break;
    auto elapsed = std::chrono::steady_clock::now() - cycle_start;
    auto cycle =
        std::chrono::duration<double, std::milli>(st.cycle_time_ms.load());
    {
      Metrics& m = GlobalMetrics();
      m.cycles.fetch_add(1, std::memory_order_relaxed);
      if (elapsed > cycle) {
        // The loop overran its budget: negotiation+execution consumed
        // the whole cycle, so enqueues arriving now wait a full extra
        // round. A rising stall count is the "cycle time too low /
        // fusion buffer too big" autotune smell, now countable.
        m.cycle_stalls.fetch_add(1, std::memory_order_relaxed);
        m.cycle_overrun_us.fetch_add(
            std::chrono::duration_cast<std::chrono::microseconds>(
                elapsed - std::chrono::duration_cast<
                              std::chrono::nanoseconds>(cycle))
                .count(),
            std::memory_order_relaxed);
      }
    }
    if (elapsed < cycle) {
      std::this_thread::sleep_for(cycle - elapsed);
    }
  }
  // Fail anything still pending.
  auto orphans = st.tensor_queue.RemoveAllEntries();
  for (auto& e : orphans) {
    st.handles.MarkDone(e.handle, Status::Aborted("Horovod is shut down"),
                        nullptr);
  }
  st.loop_exited = true;
}

int EnqueueEntry(TensorTableEntry entry, Request message) {
  GlobalState& st = *g_state;
  if (!st.initialized.load() || st.loop_exited.load()) return -1;
  int handle = st.handles.Allocate();
  entry.handle = handle;
  entry.enqueue_us = MetricsNowUs();
  message.request_rank = st.rank;
  st.timeline.EntryQueued(entry.name);
  Status s = st.tensor_queue.AddToTensorQueue(std::move(entry),
                                              std::move(message));
  if (!s.ok()) {
    st.handles.MarkDone(handle, s, nullptr);
  }
  return handle;
}

}  // namespace
}  // namespace hvdtpu

using namespace hvdtpu;

extern "C" {

extern std::atomic<int32_t> g_next_group_id;

int hvdtpu_init() {
  std::lock_guard<std::mutex> lk(g_init_mutex);
  if (g_state && g_state->initialized.load()) return 0;
  // Allocated once and never freed: API threads may still be inside blocking
  // calls (hvdtpu_wait releases the GIL) when shutdown runs, so the state
  // object must outlive them. Re-init (elastic reset) reuses it.
  if (g_state == nullptr) g_state = new GlobalState();
  GlobalState* st = g_state;
  st->shutdown_requested = false;
  st->loop_exited = false;
  st->loop_failed = false;
  st->joined = false;
  // Elastic re-init: grouped-collective ids must restart at 0 on every
  // rank — a surviving worker whose counter kept its pre-failure value
  // would mismatch freshly-respawned peers on every grouped call.
  g_next_group_id = 0;
  {
    // Elastic re-init: new workers start at 0, so everyone must.
    std::lock_guard<std::mutex> lk(st->barrier_mutex);
    st->barrier_counters.clear();
  }
  st->rank = (int)EnvInt64("HOROVOD_RANK", 0);
  st->size = (int)EnvInt64("HOROVOD_SIZE", 1);
  st->local_rank = (int)EnvInt64("HOROVOD_LOCAL_RANK", st->rank);
  st->local_size = (int)EnvInt64("HOROVOD_LOCAL_SIZE", st->size);
  st->cross_rank = (int)EnvInt64("HOROVOD_CROSS_RANK", 0);
  st->cross_size = (int)EnvInt64("HOROVOD_CROSS_SIZE", 1);
  st->fusion_threshold =
      EnvInt64("HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024);
  st->cycle_time_ms = EnvDouble("HOROVOD_CYCLE_TIME", 1.0);
  {
    // HOROVOD_CROSS_PLANE: the topology descriptor selecting how
    // collectives decompose over the transport planes
    // (docs/redistribute.md). Unset falls back to the legacy
    // HOROVOD_HIERARCHICAL_ALLREDUCE spelling (1 -> hier), else auto.
    // Case-insensitive (the Python twin xla_ici.cross_plane_mode
    // lowercases too — the two layers must agree on every spelling);
    // names come from the ONE table in common.h.
    std::string mode = EnvStr("HOROVOD_CROSS_PLANE", "");
    for (auto& c : mode) c = (char)tolower((unsigned char)c);
    if (mode.empty()) {
      mode = EnvInt64("HOROVOD_HIERARCHICAL_ALLREDUCE", 0) != 0 ? "hier"
                                                                : "auto";
    }
    st->cross_plane_mode = -1;
    for (int i = 0; i < kCrossPlaneModeCount; i++) {
      if (mode == CrossPlaneModeNames()[i]) st->cross_plane_mode = i;
    }
    if (st->cross_plane_mode < 0) {
      LOG_WARN("ignoring unknown HOROVOD_CROSS_PLANE=%s "
               "(expected auto|ici|ring|hier)", mode.c_str());
      st->cross_plane_mode = 0;
    }
  }
  st->cross_compression =
      EnvInt64("HOROVOD_CROSS_PLANE_COMPRESSION", 0) != 0;
  st->hier_split = 0;
  // Ring transport knobs (docs/wire.md). Re-read on every (elastic)
  // re-init so a respawned worker matches its peers' env-derived
  // framing even if a prior life's autotuner had moved the globals.
  SetRingChunkBytes(
      EnvInt64("HOROVOD_RING_CHUNK_BYTES", kDefaultRingChunkBytes));
  {
    // HOROVOD_WIRE_COMPRESSION: 0/1/2 or the codec spellings
    // ("bf16" == 1, "int8" == 2 — the EQuARX blockwise codec).
    std::string comp = EnvStr("HOROVOD_WIRE_COMPRESSION", "0");
    if (comp == "bf16") {
      SetWireCodec(1);
    } else if (comp == "int8") {
      SetWireCodec(2);
    } else {
      SetWireCodec((int)EnvInt64("HOROVOD_WIRE_COMPRESSION", 0));
    }
  }
  // Active stripe width re-seeds from the env on every (re)init — a
  // tuned-down width from a previous generation must not leak into a
  // re-formed ring whose peers read the env fresh.
  SetWireChannels(WireChannelsEnv());
  SetWireTimeoutMs(
      EnvInt64("HOROVOD_WIRE_TIMEOUT_MS", kDefaultWireTimeoutMs));
  SetWireRetryAttempts(EnvInt64("HOROVOD_WIRE_RETRY_ATTEMPTS", 0));
  SetWireRetryBackoffMs(EnvInt64("HOROVOD_WIRE_RETRY_BACKOFF_MS", 250));
  SetWireCrc(EnvInt64("HOROVOD_WIRE_CRC", 0) != 0);

  // World epoch: 0 for a fresh launch; a REJOINING process (blacklist
  // parole, docs/elastic.md) is told the survivors' next epoch by the
  // rejoin door and initializes straight into it — same port-shift and
  // hello-fence rules as a survivor's reinit, so stale-generation
  // traffic cannot reach the regrown ring.
  const int64_t join_epoch =
      std::max<int64_t>(EnvInt64("HOROVOD_JOIN_EPOCH", 0), 0);
  st->epoch = join_epoch;
  st->op_counter = 0;
  {
    std::lock_guard<std::mutex> lk(st->fault_mutex);
    st->faulted = false;
    st->fault_recovered = false;
    st->fault_certain = false;
    st->fault_ranks.clear();
    st->fault_reason.clear();
    st->fault_kind.clear();
    st->fault_chunk = -1;
  }
  st->inject_rank = -1;
  st->inject_op = -1;
  st->inject_action = kFaultKill;
  st->inject_param = 0;
  {
    // HOROVOD_FAULT_INJECT="<rank>:<op>[:<action>[:<param>]]" — the
    // chaos grammar (docs/elastic.md). Strictly parsed: a malformed
    // spec must stay DISARMED (a lenient strtol would read garbage as
    // 0:0 and kill rank 0 at its first collective).
    std::string spec = EnvStr("HOROVOD_FAULT_INJECT", "");
    if (!spec.empty()) {
      int32_t rank_v = -1, action_v = kFaultKill;
      int64_t op_v = -1, param_v = 0;
      if (ParseFaultSpec(spec, &rank_v, &op_v, &action_v, &param_v)) {
        st->inject_rank = rank_v;
        st->inject_op = op_v;
        st->inject_action = action_v;
        st->inject_param = param_v;
      } else {
        LOG_WARN("ignoring malformed HOROVOD_FAULT_INJECT=%s (expected "
                 "<rank>:<op>[:kill|stop:<ms>|reset|flip:<bit>|"
                 "delay:<ms>])", spec.c_str());
      }
    }
  }

  st->process_sets = std::make_unique<ProcessSetTable>(st->size);

  st->base_controller_port =
      (int)EnvInt64("HOROVOD_CONTROLLER_PORT", 29500);
  ControllerConfig cfg = MakeControllerConfig(
      *st, st->rank, st->size, join_epoch,
      st->base_controller_port +
          (join_epoch > 0 ? (int)(join_epoch % 512) : 0));
  st->controller = std::make_unique<Controller>(cfg);
  Status s = st->controller->Initialize();
  if (!s.ok()) {
    LOG_ERROR("init failed: %s", s.reason().c_str());
    st->controller.reset();
    return -1;
  }
  // Hierarchical eligibility (auto + hier modes). Must be agreed
  // COLLECTIVELY: a per-rank decision from local env alone deadlocks
  // when ranks diverge (heterogeneous local sizes, non-host-major
  // placement), so the GATE is env-uniform (mode + world size only) and
  // every rank contributes (local_size, -local_size,
  // layout-matches-host-major) to a MIN allreduce that yields the
  // global verdict identically everywhere.
  bool want_hier =
      st->cross_plane_mode == 0 || st->cross_plane_mode == 3;
  // A parole joiner (HOROVOD_JOIN_EPOCH > 0) must NOT run the probe:
  // it is a COLLECTIVE, and the survivors it joined re-formed through
  // hvdtpu_reinit, which never probes — the lone probe allreduce would
  // hang the joiner (and starve its control heartbeat) until the
  // coordinator declared it dead. A grown world is flat by
  // construction (reinit's joiner-slot fallback), so flat is the
  // correct — not just safe — answer here.
  if (want_hier && st->size > 1 && join_epoch == 0) {
    int64_t probe[3] = {
        st->local_size, -(int64_t)st->local_size,
        (st->local_rank == st->rank % std::max(st->local_size, 1) &&
         st->cross_rank == st->rank / std::max(st->local_size, 1))
            ? 1
            : 0};
    Status hs = st->controller->data_plane()->Allreduce(
        probe, 3, DataType::HVDTPU_INT64, ReduceOp::MIN);
    bool homogeneous = hs.ok() && probe[0] == -probe[1];
    bool host_major = hs.ok() && probe[2] == 1;
    if (!hs.ok() || !homogeneous || !host_major || st->local_size <= 1 ||
        st->size % st->local_size != 0 || st->size == st->local_size) {
      // auto degrades silently (flat is the correct plane for this
      // layout); an explicit hier request warns — the user asked for a
      // decomposition the topology cannot tile.
      if (st->rank == 0 && st->cross_plane_mode == 3) {
        LOG_WARN(
            "HOROVOD_CROSS_PLANE=hier disabled: requires a "
            "homogeneous host-major layout with >1 rank per slice on "
            ">1 slices (local sizes %s, layout %s)",
            homogeneous ? "uniform" : "mixed",
            host_major ? "host-major" : "not host-major");
      }
      st->hier_split = 0;
    } else {
      st->hier_split = (int32_t)st->local_size;
    }
  }
  std::string timeline_path = EnvStr("HOROVOD_TIMELINE", "");
  // Env-driven timeline records on the coordinator only: every rank shares
  // the same HOROVOD_TIMELINE path (set once by horovodrun), and concurrent
  // writers would interleave at stdio buffer boundaries. Reference analog:
  // the reference's timeline is a rank-0 artifact too. Per-rank runtime
  // recording is still available via hvd.start_timeline(path) with a
  // rank-unique path.
  if (!timeline_path.empty() && st->rank == 0) {
    st->timeline.Initialize(timeline_path, st->rank);
  }
  st->timeline_mark_cycles =
      EnvInt64("HOROVOD_TIMELINE_MARK_CYCLES", 0) != 0;
  InitAutotune(*st);
  // HOROVOD_EVENTS=0 turns the flight recorder off (on by default;
  // re-read at every (re)init like the ring knobs).
  GlobalEvents().set_enabled(EnvInt64("HOROVOD_EVENTS", 1) != 0);
  GlobalEvents().Record(EventType::kEpoch, 0, 0, join_epoch, -1);
  st->initialized = true;
  st->background_thread = std::thread(BackgroundThreadLoop, std::ref(*st));
  LOG_INFO("initialized rank %d/%d", st->rank, st->size);
  return 0;
}

int hvdtpu_loop_failed() {
  return (g_state != nullptr && g_state->loop_failed.load()) ? 1 : 0;
}

int64_t hvdtpu_epoch() {
  return g_state != nullptr ? g_state->epoch.load() : 0;
}

// Wire progress deadline (HOROVOD_WIRE_TIMEOUT_MS): process-global like
// the ring knobs, valid before init. <= 0 disables the deadline.
int64_t hvdtpu_wire_timeout_ms() { return WireTimeoutMs(); }

void hvdtpu_set_wire_timeout_ms(int64_t ms) { SetWireTimeoutMs(ms); }

// Healing-ladder + integrity knobs (docs/wire.md): process-global like
// the deadline, valid before init, re-read from env at every (re)init.
int64_t hvdtpu_wire_retry_attempts() { return WireRetryAttempts(); }

void hvdtpu_set_wire_retry_attempts(int64_t n) { SetWireRetryAttempts(n); }

int64_t hvdtpu_wire_retry_backoff_ms() { return WireRetryBackoffMs(); }

void hvdtpu_set_wire_retry_backoff_ms(int64_t ms) {
  SetWireRetryBackoffMs(ms);
}

int hvdtpu_wire_crc() { return WireCrc() ? 1 : 0; }

void hvdtpu_set_wire_crc(int on) { SetWireCrc(on != 0); }

// Runtime fault-injection arm/disarm (the env knob's programmatic twin;
// rank < 0 disarms; action defaults to kill). Exposed through basics.py
// for the chaos tests.
int hvdtpu_set_fault_inject(int rank, int64_t op_index) {
  if (g_state == nullptr) return -1;
  g_state->inject_rank = rank;
  g_state->inject_op = op_index;
  g_state->inject_action = kFaultKill;
  g_state->inject_param = 0;
  return 0;
}

// Full chaos-grammar arm: "<rank>:<op>[:<action>[:<param>]]" (see
// MaybeInjectFault). Returns 0 armed, -1 not initialized, -2 malformed
// spec (trigger left untouched — never half-armed).
int hvdtpu_set_fault_inject_spec(const char* spec) {
  if (spec == nullptr) return -2;
  int32_t rank_v = -1, action_v = kFaultKill;
  int64_t op_v = -1, param_v = 0;
  // Parse before the state check so the grammar is validatable from
  // any process (the malformed-spec tests need no ring).
  if (!ParseFaultSpec(spec, &rank_v, &op_v, &action_v, &param_v)) {
    return -2;
  }
  if (g_state == nullptr) return -1;
  g_state->inject_action = action_v;
  g_state->inject_param = param_v;
  g_state->inject_op = op_v;
  g_state->inject_rank = rank_v;
  return 0;
}

static void JsonEscapeInto(std::string& out, const std::string& s) {
  for (char c : s) {
    if (c == '"' || c == '\\') out += '\\';
    if ((unsigned char)c < 0x20) {
      out += ' ';
      continue;
    }
    out += c;
  }
}

// Last fault record as JSON, two-call pattern like the metrics
// snapshot: {"faulted":bool} or {"faulted":true,"epoch":E,
// "ranks":[...],"reason":"...","detect_ms":D,"recovered":bool}.
// "ranks" are GLOBAL ranks in the numbering of the epoch that faulted.
int64_t hvdtpu_last_fault(char* buf, int64_t cap) {
  std::string json;
  if (g_state == nullptr) {
    json = "{\"faulted\":false}";
  } else {
    std::lock_guard<std::mutex> lk(g_state->fault_mutex);
    if (!g_state->faulted) {
      json = "{\"faulted\":false}";
    } else {
      json = "{\"faulted\":true,\"epoch\":" +
             std::to_string(g_state->fault_epoch) + ",\"ranks\":[";
      for (size_t i = 0; i < g_state->fault_ranks.size(); i++) {
        if (i) json += ',';
        json += std::to_string(g_state->fault_ranks[i]);
      }
      json += "],\"certain\":";
      json += g_state->fault_certain ? "true" : "false";
      json += ",\"kind\":\"";
      JsonEscapeInto(json, g_state->fault_kind.empty()
                               ? std::string("peer")
                               : g_state->fault_kind);
      json += "\"";
      if (g_state->fault_chunk >= 0) {
        json += ",\"chunk\":" + std::to_string(g_state->fault_chunk);
      }
      json += ",\"reason\":\"";
      JsonEscapeInto(json, g_state->fault_reason);
      json += "\",\"detect_ms\":" +
              std::to_string(g_state->fault_detect_us / 1000) +
              ",\"recovered\":" +
              (g_state->fault_recovered ? "true" : "false") + "}";
    }
  }
  if (buf != nullptr && cap > 0) {
    int64_t n = std::min<int64_t>((int64_t)json.size(), cap - 1);
    std::memcpy(buf, json.data(), (size_t)n);
    buf[n] = '\0';
  }
  return (int64_t)json.size();
}

// Re-form the ring over `ranks` (OLD global rank numbers, every member
// listing them identically; -1 entries are JOINER slots taken by fresh
// processes initializing with HOROVOD_JOIN_EPOCH — the blacklist-parole
// grow path) at membership epoch `epoch` WITHOUT process restart:
// rebuild controller + full-mesh data plane among the members (a
// shrunk ring reuses the same ring_ops.h rotation helpers, so results
// are bit-identical to a fresh same-size world), and fence the old
// generation out via the epoch (stale hellos and frames are rejected;
// epoch e rendezvouses on base_port + e so the half-dead stragglers'
// retries knock on a dead door). A HEALTHY loop may re-form too (the
// scale-up path): every member sets the negotiated-shutdown bit, so the
// collective call drains the old generation cleanly before rebuilding.
// Returns 0 on success; -1 bad args / not initialized, -3 this rank is
// not a survivor, -4 re-formation rendezvous failed, -5 external (MPI)
// transport.
int hvdtpu_reinit(const int32_t* ranks, int nranks, int64_t epoch) {
  std::lock_guard<std::mutex> lk(g_init_mutex);
  if (g_state == nullptr || !g_state->initialized.load() ||
      ranks == nullptr || nranks <= 0) {
    return -1;
  }
  GlobalState* st = g_state;
  if (EnvStr("HOROVOD_CONTROLLER", "") == "mpi") {
    // External-transport fds encode the launcher's fixed peer ranks;
    // an in-process renumbering would address the wrong mailboxes (and
    // an MPI world cannot shrink anyway). Recover through the driver.
    LOG_ERROR("reinit is not supported on the external (MPI) "
              "transport; use the elastic driver path");
    return -5;
  }
  int new_rank = -1;
  int joiner_slots = 0;
  for (int i = 0; i < nranks; i++) {
    if (ranks[i] < 0) {
      joiner_slots++;
    } else if (ranks[i] == st->rank) {
      new_rank = i;
    }
  }
  if (new_rank < 0) return -3;  // this rank was declared dead
  const int64_t reinit_start_us = MetricsNowUs();
  GlobalEvents().Record(EventType::kReinitBegin, nranks, 0, epoch);
  if (!st->loop_failed.load() && !st->loop_exited.load()) {
    // Healthy loop (voluntary re-formation — absorbing parole
    // joiners): request the NEGOTIATED shutdown. Every member calls
    // reinit at the same logical point, so the coordinator sees all
    // shutdown bits and the loops drain together; a lone caller would
    // block here, which is the correct failure shape for a
    // non-collective misuse.
    st->shutdown_requested = true;
  }
  if (st->background_thread.joinable()) st->background_thread.join();
  st->shutdown_requested = false;
  const int old_size = st->size;
  const int old_rank = st->rank;
  const int old_local_rank = st->local_rank;
  const int old_local_size = st->local_size;
  const int old_cross_rank = st->cross_rank;
  const int old_cross_size = st->cross_size;
  const int32_t old_hier_split = st->hier_split.load();
  const int64_t old_epoch = st->epoch.load();
  // Keep the old generation's sockets OPEN until the new ring is up:
  // closing them now would feed other survivors an EOF on a live
  // rank's fd while they are still classifying their own failure —
  // they would blame this rank and re-form a smaller (wrong) world.
  // The re-formation rendezvous only completes once every survivor has
  // connected (i.e. has finished recording its fault), so deferring
  // the close past Initialize() makes the teardown unobservable. The
  // old process-set table must outlive it too (the old controller
  // holds a non-owning pointer).
  std::unique_ptr<Controller> old_controller = std::move(st->controller);
  std::unique_ptr<ProcessSetTable> old_process_sets =
      std::move(st->process_sets);
  st->rank = new_rank;
  st->size = nranks;
  // Re-derive the slice layout for the survivor world instead of
  // force-flattening it: when the old world was hierarchical (so its
  // rank numbering was PROVABLY host-major), group survivors by their
  // OLD host (old_rank / old_local_size). If the sorted survivor list
  // keeps every remaining host at the same contiguous count L, the
  // renumbered world is host-major again with local_size L and the
  // cross-plane decomposition stays on; any uneven tiling (or a world
  // that was flat to begin with) falls back to the flat ring — the
  // driver path (full re-rendezvous) restores launcher-grade layouts.
  // Pure local math over the shared survivor list, so every survivor
  // derives the SAME layout without another collective.
  int new_local_size = nranks;
  int32_t new_hier_split = 0;
  if (old_hier_split > 1 && old_local_size > 0 && joiner_slots == 0) {
    // Joiner slots have no old host to group by: a grown world starts
    // flat (the driver path restores launcher-grade layouts).
    bool tiles = true;
    for (int i = 1; i < nranks; i++) {
      if (ranks[i] <= ranks[i - 1]) tiles = false;  // must be sorted
    }
    int L = -1, count = 0, prev_host = -1;
    for (int i = 0; i < nranks && tiles; i++) {
      int host = ranks[i] / old_local_size;
      if (host != prev_host) {
        if (prev_host >= 0) {
          if (L < 0) L = count;
          else if (count != L) tiles = false;
        }
        prev_host = host;
        count = 1;
      } else {
        count++;
      }
    }
    if (tiles && prev_host >= 0) {
      if (L < 0) L = count;
      else if (count != L) tiles = false;
    }
    if (tiles && L > 1 && nranks % L == 0 && nranks / L > 1) {
      new_local_size = L;
      new_hier_split = (int32_t)L;
    }
  }
  st->local_rank = new_rank % new_local_size;
  st->local_size = new_local_size;
  st->cross_rank = new_rank / new_local_size;
  st->cross_size = nranks / new_local_size;
  st->hier_split = new_hier_split;
  st->epoch = epoch;
  st->joined = false;
  st->last_joined_rank = -1;
  g_next_group_id = 0;
  st->op_counter = 0;
  st->inject_rank = -1;  // one-shot: a renumbered survivor must never
  st->inject_op = -1;    // inherit the dead rank's trigger
  {
    std::lock_guard<std::mutex> blk(st->barrier_mutex);
    st->barrier_counters.clear();
  }
  // The old world's process sets name dead ranks in dead numbering;
  // Python-side ProcessSet objects must be re-registered.
  st->process_sets = std::make_unique<ProcessSetTable>(nranks);

  ControllerConfig cfg = MakeControllerConfig(
      *st, new_rank, nranks, epoch,
      st->base_controller_port + (int)(epoch % 512));
  st->controller = std::make_unique<Controller>(cfg);
  Status s = st->controller->Initialize();
  if (!s.ok()) {
    LOG_ERROR("reinit failed at epoch %lld: %s", (long long)epoch,
              s.reason().c_str());
    // Restore the old (dead) world wholesale — controller, process
    // sets, identity, epoch — so metrics reads stay safe and a
    // follow-up driver-path recovery sees the pre-attempt state.
    st->controller = std::move(old_controller);
    st->process_sets = std::move(old_process_sets);
    st->rank = old_rank;
    st->size = old_size;
    st->local_rank = old_local_rank;
    st->local_size = old_local_size;
    st->cross_rank = old_cross_rank;
    st->cross_size = old_cross_size;
    st->hier_split = old_hier_split;
    st->epoch = old_epoch;
    GlobalEvents().Record(EventType::kReinitEnd, -4, nranks, epoch);
    return -4;
  }
  old_controller.reset();  // the new ring is up; now drop the old fds
  old_process_sets.reset();
  bool had_fault = false;
  {
    std::lock_guard<std::mutex> flk(st->fault_mutex);
    had_fault = st->faulted && !st->fault_recovered;
    if (st->faulted) st->fault_recovered = true;
  }
  {
    Metrics& m = GlobalMetrics();
    if (had_fault) {
      m.faults_recovered.fetch_add(1, std::memory_order_relaxed);
    }
    // Blacklisted = old ranks absent from the member list; rejoined =
    // parole slots absorbed. A combined shrink+grow books both.
    const int survivors = nranks - joiner_slots;
    if (old_size > survivors) {
      m.ranks_blacklisted.fetch_add(old_size - survivors,
                                    std::memory_order_relaxed);
    }
    if (joiner_slots > 0) {
      m.ranks_rejoined.fetch_add(joiner_slots,
                                 std::memory_order_relaxed);
    }
  }
  RecordControlPhase(kPhaseReinit, MetricsNowUs() - reinit_start_us);
  GlobalEvents().Record(EventType::kReinitEnd, 0, nranks, epoch);
  GlobalEvents().Record(EventType::kEpoch, 0, 0, epoch, old_epoch);
  if (joiner_slots > 0) {
    GlobalEvents().Record(EventType::kRejoin, joiner_slots, 0, epoch);
  }
  st->shutdown_requested = false;
  st->loop_exited = false;
  st->loop_failed = false;
  // Reset the ACTIVE stripe width to the env value, like a fresh init:
  // reinit is collective over the survivors, and a parole JOINER
  // seeds from the same env in its own init — a tuner-narrowed width
  // surviving here would leave survivors and joiners cutting
  // different chunk->channel schedules, and the stripe split IS the
  // wire framing (wire.h). The rebuilt autotuner below re-tunes from
  // this point.
  SetWireChannels(WireChannelsEnv());
  // Rebuild the autotuner for the re-formed world: its hier-split grid
  // must cover the RE-DERIVED layout (a stale grid's next window would
  // stomp the new split with a divisor of the dead layout), and the
  // old samples scored a different world anyway.
  InitAutotune(*st);
  st->background_thread = std::thread(BackgroundThreadLoop, std::ref(*st));
  LOG_INFO("re-formed ring: rank %d/%d at epoch %lld", new_rank, nranks,
           (long long)epoch);
  return 0;
}

int hvdtpu_shutdown() {
  std::lock_guard<std::mutex> lk(g_init_mutex);
  if (!g_state || !g_state->initialized.load()) return 0;
  g_state->shutdown_requested = true;
  if (g_state->background_thread.joinable()) {
    g_state->background_thread.join();
  }
  g_state->timeline.Shutdown();
  g_state->controller.reset();  // closes control/data sockets
  g_state->initialized = false;
  return 0;
}

int hvdtpu_is_initialized() {
  return g_state && g_state->initialized.load() ? 1 : 0;
}

#define CHECK_INIT(ret) \
  if (!g_state || !g_state->initialized.load()) return ret;

int hvdtpu_rank() { CHECK_INIT(-1) return g_state->rank; }
int hvdtpu_size() { CHECK_INIT(-1) return g_state->size; }
int hvdtpu_local_rank() { CHECK_INIT(-1) return g_state->local_rank; }
int hvdtpu_local_size() { CHECK_INIT(-1) return g_state->local_size; }
int hvdtpu_cross_rank() { CHECK_INIT(-1) return g_state->cross_rank; }
int hvdtpu_cross_size() { CHECK_INIT(-1) return g_state->cross_size; }

static int EnqueueAllreduceInternal(const char* name, const void* input,
                                    void* output, int ndim,
                                    const int64_t* shape, int dtype,
                                    int reduce_op, double prescale,
                                    double postscale, int process_set_id,
                                    int group_id, int group_size) {
  TensorTableEntry e;
  e.name = name;
  e.input = input;
  e.output = output;
  e.shape.assign(shape, shape + ndim);
  e.dtype = ToDataType(dtype);
  e.reduce_op = (ReduceOp)reduce_op;
  e.prescale_factor = prescale;
  e.postscale_factor = postscale;
  e.process_set_id = process_set_id;
  Request m;
  m.request_type = RequestType::ALLREDUCE;
  m.tensor_name = e.name;
  m.tensor_type = e.dtype;
  m.tensor_shape = e.shape;
  m.reduce_op = e.reduce_op;
  m.prescale_factor = prescale;
  m.postscale_factor = postscale;
  m.process_set_id = process_set_id;
  m.group_id = group_id;
  m.group_size = group_id >= 0 ? group_size : 0;
  return EnqueueEntry(std::move(e), std::move(m));
}

int hvdtpu_enqueue_allreduce(const char* name, const void* input, void* output,
                             int ndim, const int64_t* shape, int dtype,
                             int reduce_op, double prescale, double postscale,
                             int process_set_id) {
  CHECK_INIT(-1)
  return EnqueueAllreduceInternal(name, input, output, ndim, shape, dtype,
                                  reduce_op, prescale, postscale,
                                  process_set_id, -1, 0);
}

// Process-global group id counter. Matches across ranks as long as
// grouped calls happen in the same order everywhere — the reference's
// group_table.cc carries the identical contract.
std::atomic<int32_t> g_next_group_id{0};

int hvdtpu_enqueue_grouped_allreduce(int num_tensors, const char** names,
                                     const void** inputs, void** outputs,
                                     const int* ndims, const int64_t** shapes,
                                     int dtype, int reduce_op, double prescale,
                                     double postscale, int process_set_id,
                                     int* handles_out) {
  CHECK_INIT(-1)
  // Atomic negotiation (reference analog: group_table.cc): every tensor
  // carries the same fresh group id + the group size; the coordinator
  // holds members back until the whole group is ready on every rank and
  // fuses them into one pure response regardless of the fusion threshold.
  //
  // Returns the number of tensors successfully enqueued (== num_tensors on
  // full success). On partial failure the caller still owns live handles
  // for the first `return value` tensors and must drain them before
  // releasing the underlying buffers.
  // Validate everything BEFORE enqueueing anything: a half-enqueued
  // group can never complete (the coordinator holds it for the missing
  // members), so reject up front. Covers null pointers, duplicate names
  // within the group, and collisions with in-flight tensors (which
  // AddToTensorQueue would otherwise reject member-by-member, silently
  // dropping that member's request).
  {
    std::unordered_set<std::string> seen;
    for (int i = 0; i < num_tensors; i++) {
      bool bad = names[i] == nullptr || inputs[i] == nullptr ||
                 outputs[i] == nullptr || shapes[i] == nullptr;
      if (!bad) {
        bad = !seen.insert(names[i]).second ||
              g_state->tensor_queue.Contains(names[i]);
      }
      if (bad) {
        for (int j = 0; j < num_tensors; j++) handles_out[j] = -1;
        return 0;
      }
    }
  }
  int32_t gid = num_tensors > 1 ? g_next_group_id.fetch_add(1) : -1;
  for (int i = 0; i < num_tensors; i++) {
    handles_out[i] = EnqueueAllreduceInternal(
        names[i], inputs[i], outputs[i], ndims[i], shapes[i], dtype,
        reduce_op, prescale, postscale, process_set_id, gid, num_tensors);
    if (handles_out[i] < 0) {
      // Only possible via the shutdown race; queued members are failed
      // by the loop-exit orphan sweep, so callers draining the prefix
      // see errors, not hangs.
      for (int j = i + 1; j < num_tensors; j++) handles_out[j] = -1;
      return i;
    }
  }
  return num_tensors;
}

int hvdtpu_enqueue_allgather(const char* name, const void* input, int ndim,
                             const int64_t* shape, int dtype,
                             int process_set_id, int group_id,
                             int group_size) {
  CHECK_INIT(-1)
  TensorTableEntry e;
  e.name = name;
  e.input = input;
  e.shape.assign(shape, shape + ndim);
  e.dtype = ToDataType(dtype);
  e.process_set_id = process_set_id;
  Request m;
  m.request_type = RequestType::ALLGATHER;
  m.tensor_name = e.name;
  m.tensor_type = e.dtype;
  m.tensor_shape = e.shape;
  m.process_set_id = process_set_id;
  // Atomic group negotiation (hvd.grouped_allgather): same promotion
  // machinery as grouped allreduce; responses stay per-tensor (only
  // allreduce buffer-fuses), so execution paths are unchanged.
  m.group_id = group_id;
  m.group_size = group_id >= 0 ? group_size : 0;
  return EnqueueEntry(std::move(e), std::move(m));
}

int hvdtpu_enqueue_broadcast(const char* name, void* buffer, int ndim,
                             const int64_t* shape, int dtype, int root_rank,
                             int process_set_id) {
  CHECK_INIT(-1)
  TensorTableEntry e;
  e.name = name;
  e.input = buffer;
  e.output = buffer;  // in-place
  e.shape.assign(shape, shape + ndim);
  e.dtype = ToDataType(dtype);
  e.root_rank = root_rank;
  e.process_set_id = process_set_id;
  Request m;
  m.request_type = RequestType::BROADCAST;
  m.tensor_name = e.name;
  m.tensor_type = e.dtype;
  m.tensor_shape = e.shape;
  m.root_rank = root_rank;
  m.process_set_id = process_set_id;
  return EnqueueEntry(std::move(e), std::move(m));
}

int hvdtpu_enqueue_alltoall(const char* name, const void* input, int ndim,
                            const int64_t* shape, int dtype,
                            const int64_t* splits, int process_set_id) {
  CHECK_INIT(-1)
  TensorTableEntry e;
  e.name = name;
  e.input = input;
  e.shape.assign(shape, shape + ndim);
  e.dtype = ToDataType(dtype);
  e.process_set_id = process_set_id;
  if (splits != nullptr) {
    int n = process_set_id == 0
                ? g_state->size
                : (int)g_state->process_sets->Ranks(process_set_id).size();
    if (n == 0) return -1;  // unknown process set
    e.splits.assign(splits, splits + n);
  }
  Request m;
  m.request_type = RequestType::ALLTOALL;
  m.tensor_name = e.name;
  m.tensor_type = e.dtype;
  m.tensor_shape = e.shape;
  m.splits = e.splits;
  m.process_set_id = process_set_id;
  return EnqueueEntry(std::move(e), std::move(m));
}

int hvdtpu_enqueue_reducescatter(const char* name, const void* input, int ndim,
                                 const int64_t* shape, int dtype,
                                 int reduce_op, double prescale,
                                 double postscale, int process_set_id,
                                 int group_id, int group_size) {
  CHECK_INIT(-1)
  TensorTableEntry e;
  e.name = name;
  e.input = input;
  e.shape.assign(shape, shape + ndim);
  e.dtype = ToDataType(dtype);
  e.reduce_op = (ReduceOp)reduce_op;
  e.prescale_factor = prescale;
  e.postscale_factor = postscale;
  e.process_set_id = process_set_id;
  Request m;
  m.request_type = RequestType::REDUCESCATTER;
  m.tensor_name = e.name;
  m.tensor_type = e.dtype;
  m.tensor_shape = e.shape;
  m.reduce_op = e.reduce_op;
  m.process_set_id = process_set_id;
  m.group_id = group_id;
  m.group_size = group_id >= 0 ? group_size : 0;
  return EnqueueEntry(std::move(e), std::move(m));
}

int hvdtpu_set_device_callback(void* fn) {
  // Register (or clear, with null) the device data plane executor. Called
  // by the Python XLA/ICI layer with a ctypes CFUNCTYPE; the background
  // thread invokes it for every device=1 fused response.
  g_device_exec.store((DeviceExecFn)fn);
  return 0;
}

int hvdtpu_enqueue_device(int op_class, const char* name, int ndim,
                          const int64_t* shape, int dtype, int reduce_op,
                          int root_rank, int process_set_id, int group_id,
                          int group_size) {
  // Negotiation-only enqueue for an accelerator-resident tensor: the
  // payload stays in HBM under the Python data plane's registry; the core
  // contributes ordering, fusion grouping, caching, and join handling.
  // op_class uses Response::ResponseType values (0=allreduce, 1=allgather,
  // 2=broadcast, 4=reducescatter). group_id/group_size (-1/0 = ungrouped,
  // ids from hvdtpu_next_group_id) opt into atomic group negotiation.
  CHECK_INIT(-1)
  if (g_device_exec.load() == nullptr) return -1;
  RequestType rt;
  switch (op_class) {
    case 0: rt = RequestType::ALLREDUCE; break;
    case 1: rt = RequestType::ALLGATHER; break;
    case 2: rt = RequestType::BROADCAST; break;
    case 3: rt = RequestType::ALLTOALL; break;  // equal splits only
    case 4: rt = RequestType::REDUCESCATTER; break;
    default: return -1;
  }
  TensorTableEntry e;
  e.name = name;
  e.device = 1;
  e.shape.assign(shape, shape + ndim);
  e.dtype = ToDataType(dtype);
  e.reduce_op = (ReduceOp)reduce_op;
  e.root_rank = root_rank;
  e.process_set_id = process_set_id;
  Request m;
  m.request_type = rt;
  m.tensor_name = e.name;
  m.tensor_type = e.dtype;
  m.tensor_shape = e.shape;
  m.reduce_op = e.reduce_op;
  m.root_rank = root_rank;
  m.process_set_id = process_set_id;
  m.device = 1;
  m.group_id = group_id;
  m.group_size = group_id >= 0 ? group_size : 0;
  return EnqueueEntry(std::move(e), std::move(m));
}

int hvdtpu_next_group_id() {
  // Fresh group id for device-path grouped enqueues (host grouped
  // enqueues draw from the same counter internally, keeping cross-rank
  // ordering consistent across both paths).
  return g_next_group_id.fetch_add(1);
}

int hvdtpu_add_process_set(const int32_t* ranks, int nranks) {
  CHECK_INIT(-1)
  // Must be called with identical ranks in identical order on EVERY process
  // (ids are assigned locally; the reference has the same requirement for
  // hvd.add_process_set). The Python layer runs a global barrier before
  // first use so no rank races ahead of a lagging registrant.
  std::vector<int32_t> members(ranks, ranks + nranks);
  for (int32_t r : members) {
    if (r < 0 || r >= g_state->size) return -1;
  }
  return g_state->process_sets->Add(std::move(members));
}

int hvdtpu_remove_process_set(int process_set_id) {
  CHECK_INIT(-1)
  return g_state->process_sets->Remove(process_set_id) ? 0 : -1;
}

int hvdtpu_process_set_size(int process_set_id) {
  CHECK_INIT(-1)
  if (process_set_id == 0) return g_state->size;
  int n = (int)g_state->process_sets->Ranks(process_set_id).size();
  return n == 0 ? -1 : n;
}

int hvdtpu_process_set_rank(int process_set_id) {
  CHECK_INIT(-1)
  if (process_set_id == 0) return g_state->rank;
  return g_state->process_sets->RankIn(process_set_id, g_state->rank);
}

int hvdtpu_enqueue_join() {
  CHECK_INIT(-1)
  // Reference analog: horovod_join / EnqueueJoin (operations.cc). The rank
  // stops contributing data; until every rank joins, the bg loop fills in
  // zero contributions for negotiated collectives.
  g_state->joined = true;
  TensorTableEntry e;
  e.name = "__join__";
  Request m;
  m.request_type = RequestType::JOIN;
  m.tensor_name = e.name;
  return EnqueueEntry(std::move(e), std::move(m));
}

int hvdtpu_last_joined_rank() {
  CHECK_INIT(-1)
  return g_state->last_joined_rank.load();
}

int hvdtpu_enqueue_barrier(int process_set_id) {
  CHECK_INIT(-1)
  TensorTableEntry e;
  e.name = "__barrier__." +
           std::to_string(g_state->NextBarrierSeq(process_set_id));
  e.process_set_id = process_set_id;
  Request m;
  m.request_type = RequestType::BARRIER;
  m.tensor_name = e.name;
  m.process_set_id = process_set_id;
  return EnqueueEntry(std::move(e), std::move(m));
}

int hvdtpu_poll(int handle) {
  CHECK_INIT(-1)
  bool done = false;
  if (!g_state->handles.Poll(handle, &done)) return -1;
  return done ? 1 : 0;
}

int hvdtpu_wait(int handle) {
  CHECK_INIT(-1)
  Status s;
  // The blocking interval feeds the overlap ledger's exposure math
  // (metrics.h): wire time under an API-thread wait is `exposed`,
  // wire that drained while the host kept computing is `hidden` —
  // the number the jit-lane fusion schedule exists to move
  // (docs/fusion.md).
  int64_t t0 = MetricsNowUs();
  bool found = g_state->handles.Wait(handle, &s);
  int64_t t1 = MetricsNowUs();
  GlobalLedger().AddWait(t0, t1);
  // The same interval as a typed ring event (stamped at its END, the
  // wire_span convention) so black-box dumps carry the wait blocks the
  // live ledger computed exposure from: offline critpath rebuilds
  // `exposed = wire ∩ waits` instead of misreading fused lanes as
  // compute-bound (docs/metrics.md "Step anatomy").
  if (t1 > t0) {
    GlobalEvents().Record(EventType::kWait, 0, 0, t1 - t0);
  }
  if (!found) return -1;
  return s.ok() ? 0 : -(int)s.type();
}

const char* hvdtpu_error_string(int handle) {
  CHECK_INIT(nullptr)
  return g_state->handles.WithRecord(handle, [](auto* rec) -> const char* {
    if (!rec || rec->status.ok()) return nullptr;
    return rec->status.reason().c_str();
  });
}

int hvdtpu_result_ndim(int handle) {
  CHECK_INIT(-1)
  return g_state->handles.WithRecord(handle, [](auto* rec) {
    return rec ? (int)rec->output_shape.size() : -1;
  });
}

int hvdtpu_result_shape(int handle, int64_t* shape_out) {
  CHECK_INIT(-1)
  return g_state->handles.WithRecord(handle, [&](auto* rec) {
    if (!rec) return -1;
    for (size_t i = 0; i < rec->output_shape.size(); i++) {
      shape_out[i] = rec->output_shape[i];
    }
    return 0;
  });
}

int64_t hvdtpu_result_size_bytes(int handle) {
  CHECK_INIT(-1)
  return g_state->handles.WithRecord(handle, [](auto* rec) -> int64_t {
    return rec ? (int64_t)rec->managed_output.size() : -1;
  });
}

int hvdtpu_result_copy(int handle, void* dst, int64_t nbytes) {
  CHECK_INIT(-1)
  return g_state->handles.WithRecord(handle, [&](auto* rec) {
    if (!rec || (int64_t)rec->managed_output.size() > nbytes) return -1;
    std::memcpy(dst, rec->managed_output.data(), rec->managed_output.size());
    return 0;
  });
}

int hvdtpu_release(int handle) {
  CHECK_INIT(-1)
  g_state->handles.Release(handle);
  return 0;
}

// Register the external (socket-free) message transport BEFORE init —
// used with HOROVOD_CONTROLLER=mpi (bare-MPI fabrics). Function
// pointers are ctypes callbacks; see wire.h for the contract.
void hvdtpu_set_external_transport(void* send_fn, void* recv_fn) {
  SetExternalTransport((ExternalSendFn)send_fn, (ExternalRecvFn)recv_fn);
}

int64_t hvdtpu_fusion_threshold_bytes() {
  CHECK_INIT(-1)
  return g_state->fusion_threshold.load();
}

double hvdtpu_cycle_time_ms() {
  CHECK_INIT(-1)
  return g_state->cycle_time_ms.load();
}

void hvdtpu_set_fusion_threshold_bytes(int64_t v) {
  if (g_state) g_state->fusion_threshold = v;
}

void hvdtpu_set_cycle_time_ms(double v) {
  if (g_state) g_state->cycle_time_ms = v;
}

// Ring transport knobs (process-global, valid before init — the ring
// selftest drives them without a controller). MUST be set identically
// on every rank of a live job: the chunk split is the message framing
// and compression the wire width (docs/wire.md).
int64_t hvdtpu_ring_chunk_bytes() { return RingChunkBytes(); }

void hvdtpu_set_ring_chunk_bytes(int64_t v) { SetRingChunkBytes(v); }

int hvdtpu_wire_compression() { return WireCompression() ? 1 : 0; }

void hvdtpu_set_wire_compression(int v) { SetWireCompression(v != 0); }

// Wire codec mode behind the compression knob: 0 off, 1 bf16, 2 int8
// blockwise-scaled (docs/wire.md).
int hvdtpu_wire_codec() { return WireCodec(); }

void hvdtpu_set_wire_codec(int mode) { SetWireCodec(mode); }

// Active stripe width (HOROVOD_WIRE_CHANNELS; docs/wire.md). MUST be
// rank-uniform like the chunk knob — the stripe split is the wire
// framing; the autotuner syncs it via the ResponseList. Clamped at use
// sites to the sockets actually established per pair.
int64_t hvdtpu_wire_channels() { return WireChannels(); }

void hvdtpu_set_wire_channels(int64_t k) { SetWireChannels(k); }

// Sockets established per neighbor pair this generation (env-derived,
// fixed until the next full init; 1 before init).
int hvdtpu_wire_channels_established() {
  return g_state != nullptr ? g_state->wire_channels_established : 1;
}

// Explicit-SIMD reduce/codec paths (HOROVOD_SIMD; bit-identical to
// scalar by contract — csrc/simd.h).
int hvdtpu_simd_enabled() { return SimdEnabled() ? 1 : 0; }

void hvdtpu_set_simd_enabled(int on) { SetSimdEnabled(on != 0); }

// Cross-plane topology descriptor (HOROVOD_CROSS_PLANE): 0 auto, 1 ici,
// 2 ring, 3 hier — fixed at init (the mode is a per-job choice; the
// SPLIT within hier/auto is the runtime knob below).
int hvdtpu_cross_plane() {
  return g_state != nullptr ? g_state->cross_plane_mode : 0;
}

// Active hierarchy split point: 0 = flat ring, s >= 2 = intra-slice
// group size of the three-phase decomposition. MUST be set identically
// on every rank of a live job (the split decides which plane sequence
// a collective decomposes into); the autotuner syncs it via the
// ResponseList like the ring knobs.
int hvdtpu_hier_split() {
  CHECK_INIT(-1)
  return g_state->hier_split.load();
}

void hvdtpu_set_hier_split(int split) {
  if (g_state) g_state->hier_split = split;
}

// Whether the bf16 wire codec rides the inter-slice hop only
// (HOROVOD_CROSS_PLANE_COMPRESSION; fixed at init).
int hvdtpu_cross_compression() {
  return (g_state != nullptr && g_state->cross_compression) ? 1 : 0;
}

// Ring segment-ownership rotation (pure, valid before init): the ONE
// encoding of "after the reduce phase at rotation `rot`, which segment
// does rank r own / send at step s" — see ring_ops.h. Exposed so
// Python-side shard-boundary math and the tests pin the SAME helper
// the ring engine executes instead of re-deriving the off-by-one.
int hvdtpu_ring_owned_segment(int rank, int size, int rot) {
  if (size <= 0 || rank < 0 || rank >= size) return -1;
  return RingOwnedSegment(rank, size, rot);
}

int hvdtpu_ring_send_segment(int rank, int step, int size, int rot) {
  if (size <= 0 || rank < 0 || rank >= size) return -1;
  return RingSendSegment(rank, step, size, rot);
}

int64_t hvdtpu_response_cache_hits() {
  CHECK_INIT(-1)
  return g_state->controller->response_cache().hits();
}

int64_t hvdtpu_response_cache_misses() {
  CHECK_INIT(-1)
  return g_state->controller->response_cache().misses();
}

int64_t hvdtpu_response_cache_entries() {
  CHECK_INIT(-1)
  return g_state->controller->response_cache().entries();
}

int64_t hvdtpu_metrics_snapshot(char* buf, int64_t cap) {
  // JSON snapshot of the metrics registry. Two-call pattern: pass
  // (nullptr, 0) to size, then a buffer; returns the full JSON length
  // (excluding the NUL) either way. Valid before init (counters zeroed,
  // "initialized": false) — the registry outlives init/shutdown.
  Metrics::RuntimeInfo info;
  {
    // g_init_mutex orders this against hvdtpu_shutdown's
    // controller.reset(): never read cache stats off a dying controller.
    std::lock_guard<std::mutex> lk(g_init_mutex);
    if (g_state && g_state->initialized.load() && g_state->controller) {
      info.initialized = true;
      info.rank = g_state->rank;
      info.size = g_state->size;
      info.fusion_threshold_bytes = g_state->fusion_threshold.load();
      info.cycle_time_ms = g_state->cycle_time_ms.load();
      info.ring_chunk_bytes = RingChunkBytes();
      info.wire_compression = WireCompression();
      info.wire_codec = WireCodec();
      info.wire_channels = WireChannels();
      info.wire_channels_established =
          g_state->wire_channels_established;
      info.simd = SimdEnabled();
      info.wire_timeout_ms = WireTimeoutMs();
      info.wire_retry_attempts = WireRetryAttempts();
      info.wire_retry_backoff_ms = WireRetryBackoffMs();
      info.wire_crc = WireCrc();
      info.cross_plane = g_state->cross_plane_mode;
      info.hier_split = g_state->hier_split.load();
      info.cross_compression = g_state->cross_compression;
      info.epoch = g_state->epoch.load();
      const ResponseCache& c = g_state->controller->response_cache();
      info.cache_hits = c.hits();
      info.cache_misses = c.misses();
      info.cache_entries = c.entries();
      info.cache_hit_bytes = c.hit_bytes();
    }
  }
  std::string json = GlobalMetrics().SnapshotJson(info);
  if (buf != nullptr && cap > 0) {
    int64_t n = std::min<int64_t>((int64_t)json.size(), cap - 1);
    std::memcpy(buf, json.data(), (size_t)n);
    buf[n] = '\0';
  }
  return (int64_t)json.size();
}

// ---- step scoping (docs/metrics.md "Step anatomy") --------------------
// One per-process step cursor, driven from above the core (StepTimer
// boundaries, the eager optimizer step): kStepBegin/kStepEnd events
// bracket every other event's timestamp into a step window, and the
// overlap ledger unions the wire spans inside it. Valid before init —
// the ring and the ledger outlive init/shutdown like the registry.
static std::atomic<int64_t> g_step_counter{0};
static std::atomic<int64_t> g_open_step{-1};

int hvdtpu_metrics_reset() {
  GlobalMetrics().Reset();
  GlobalLedger().Reset();
  // The ledger's open window died with the reset — drop the cursor
  // too, or step_id() keeps advertising a window whose ledger state
  // is gone and the next step_mark(false) books a -1-duration end.
  // The id counter stays monotonic: step ids must never repeat within
  // a process (offline dumps match steps across ranks by id).
  g_open_step.store(-1, std::memory_order_release);
  return 0;
}

int64_t hvdtpu_step_mark(int begin) {
  // begin != 0: open a new step window (a still-open one is closed
  // first — boundary semantics, so a mark-per-iteration driver needs
  // no explicit end). Returns the new step id (monotonic from 1).
  // begin == 0: close the open window; returns its id, or -1 if none.
  int64_t now = MetricsNowUs();
  int64_t open = g_open_step.exchange(-1, std::memory_order_acq_rel);
  if (open >= 0) {
    int64_t dur = GlobalLedger().StepEnd(now);
    GlobalEvents().Record(EventType::kStepEnd, 0, 0, open, dur);
  }
  if (!begin) return open >= 0 ? open : -1;
  int64_t id = g_step_counter.fetch_add(1, std::memory_order_relaxed) + 1;
  GlobalLedger().StepBegin(now);
  GlobalEvents().Record(EventType::kStepBegin, 0, 0, id);
  g_open_step.store(id, std::memory_order_release);
  return id;
}

int64_t hvdtpu_step_id() {
  // The currently open step id, or -1 — how an implicit driver (the
  // eager optimizer boundary) defers to an explicit scope (StepTimer).
  return g_open_step.load(std::memory_order_acquire);
}

// Record one control-plane phase duration from ABOVE the core: the
// parole-door freeze/poll lives in Python (common/elastic.py) but its
// latency belongs on the same per-phase scaling profile as the native
// phases (docs/scale.md). Valid before init like the registry itself.
void hvdtpu_record_phase(int phase, int64_t dur_us) {
  RecordControlPhase(phase, dur_us);
}

// Record one serving-request lifecycle transition (RequestPhase,
// events.h) from the Python serving lane: the rid-tagged kRequest
// family telemetry/reqtrace.py stitches into per-request span chains
// (docs/serving.md "Request lifecycle & tracing"). Wait-free like
// every Record; valid before init like the ring itself.
void hvdtpu_record_request(int phase, int64_t rid, int64_t aux) {
  GlobalEvents().Record(EventType::kRequest, phase, 0, rid, aux);
}

// Record one SLO breach (SloObjective, events.h) from the Python SLO
// engine (telemetry/slo.py): breach_rank names the breaching rank,
// value the observed measurement (integral — ms or permille per
// objective), bucket the dominant rank-seconds ledger bucket
// (kRankBucketNames). Lands in the ring → black-box dumps → the
// post-mortem fold (docs/fleet.md). Valid before init.
void hvdtpu_record_slo(int objective, int breach_rank, int64_t value,
                       int64_t bucket) {
  GlobalEvents().Record(EventType::kSloBreach, objective, breach_rank,
                        value, bucket);
}

// Live pending-tensor gauge: collectives enqueued by API threads that
// the background loop has not finished executing. The queue-depth
// signal the autoscaler's /healthz consumes (docs/scale.md) — a gauge,
// unlike the monotonic counters in the snapshot. 0 before init.
int64_t hvdtpu_queue_depth() {
  if (g_state == nullptr || !g_state->initialized.load()) return 0;
  return (int64_t)g_state->tensor_queue.Size();
}

// Consuming-drain cursor for hvdtpu_events_drain: one per process (the
// drain surface is a single logical consumer — hvd.events_drain(); the
// debug server and black-box dump use the non-consuming peek).
static std::atomic<int64_t> g_events_cursor{0};

int64_t hvdtpu_events_drain(char* buf, int64_t cap) {
  // Structured event ring drain, two-call pattern like the metrics
  // snapshot: (nullptr, 0) sizes the pending JSON WITHOUT advancing
  // the cursor; a buffer call that fits copies the events and advances
  // the cursor past them (consuming). A too-small buffer copies
  // nothing, leaves the cursor alone, and returns the needed size so
  // the caller can retry losslessly. Valid before init.
  int64_t cursor = g_events_cursor.load(std::memory_order_acquire);
  int64_t next = cursor;
  std::string json = GlobalEvents().Json(cursor, &next);
  if (buf == nullptr || cap <= (int64_t)json.size()) {
    return (int64_t)json.size();
  }
  std::memcpy(buf, json.data(), json.size());
  buf[json.size()] = '\0';
  // A concurrent drain may have advanced past us; never move back.
  int64_t cur = cursor;
  while (cur < next && !g_events_cursor.compare_exchange_weak(
                           cur, next, std::memory_order_acq_rel)) {
  }
  return (int64_t)json.size();
}

int64_t hvdtpu_events_peek(char* buf, int64_t cap, int64_t last_n) {
  // Non-consuming tail read: the newest `last_n` events (<= 0 = the
  // whole live window) as a JSON array. Same two-call sizing contract;
  // never touches the drain cursor — the live-introspection surface
  // (/events on the debug server, hvd.events()).
  std::string json = GlobalEvents().Json(0, nullptr, last_n);
  if (buf != nullptr && cap > 0) {
    int64_t n = std::min<int64_t>((int64_t)json.size(), cap - 1);
    std::memcpy(buf, json.data(), (size_t)n);
    buf[n] = '\0';
  }
  return (int64_t)json.size();
}

int hvdtpu_events_enabled() {
  return GlobalEvents().enabled() ? 1 : 0;
}

void hvdtpu_set_events_enabled(int on) {
  GlobalEvents().set_enabled(on != 0);
}

int64_t hvdtpu_events_head() { return GlobalEvents().head(); }

int hvdtpu_start_timeline(const char* path) {
  CHECK_INIT(-1)
  // Reference analog: hvd.start_timeline / horovod_start_timeline
  // (TimelineController). Restartable: stop + start with a new path works.
  if (path == nullptr || path[0] == '\0') return -1;
  g_state->timeline.Shutdown();
  g_state->timeline.Initialize(path, g_state->rank);
  return g_state->timeline.Enabled() ? 0 : -1;
}

int hvdtpu_stop_timeline() {
  CHECK_INIT(-1)
  g_state->timeline.Shutdown();
  return 0;
}

}  // extern "C"
