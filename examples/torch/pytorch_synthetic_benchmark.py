"""Synthetic-data throughput benchmark for the torch frontend.

Reference analog: ``examples/pytorch/pytorch_synthetic_benchmark.py`` —
THE script the reference's headline numbers are measured with
(docs/benchmarks.rst: img/sec scaling across workers on ResNet). Same
CLI shape: fixed random batches, timed allreduce-per-step training,
per-worker img/sec plus the all-worker total.

torchvision isn't required: ``--model resnet50`` uses it when
installed, otherwise a built-in ResNet-ish convnet stands in (declared
in the output so numbers aren't confused with the torchvision model).

Run:
    horovodrun -np 4 python examples/torch/pytorch_synthetic_benchmark.py
"""

import argparse
import time

import numpy as np
import torch
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class SmallResNetish(torch.nn.Module):
    """Stand-in when torchvision is absent: conv stem + 4 residual
    stages + fc, ~11M params."""

    class Block(torch.nn.Module):
        def __init__(self, cin, cout, stride=1):
            super().__init__()
            self.c1 = torch.nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
            self.b1 = torch.nn.BatchNorm2d(cout)
            self.c2 = torch.nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.b2 = torch.nn.BatchNorm2d(cout)
            self.skip = (torch.nn.Conv2d(cin, cout, 1, stride, bias=False)
                         if (stride != 1 or cin != cout)
                         else torch.nn.Identity())

        def forward(self, x):
            h = F.relu(self.b1(self.c1(x)))
            h = self.b2(self.c2(h))
            return F.relu(h + self.skip(x))

    def __init__(self, num_classes=1000):
        super().__init__()
        self.stem = torch.nn.Sequential(
            torch.nn.Conv2d(3, 64, 7, 2, 3, bias=False),
            torch.nn.BatchNorm2d(64), torch.nn.ReLU(),
            torch.nn.MaxPool2d(3, 2, 1))
        stages = []
        cin = 64
        for cout, stride in ((64, 1), (128, 2), (256, 2), (512, 2)):
            stages += [self.Block(cin, cout, stride), self.Block(cout, cout)]
            cin = cout
        self.stages = torch.nn.Sequential(*stages)
        self.fc = torch.nn.Linear(512, num_classes)

    def forward(self, x):
        h = self.stages(self.stem(x))
        return self.fc(h.mean(dim=(2, 3)))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--num-warmup-batches", type=int, default=2)
    p.add_argument("--num-batches-per-iter", type=int, default=5)
    p.add_argument("--num-iters", type=int, default=5)
    p.add_argument("--fp16-allreduce", action="store_true",
                   help="compress gradients to fp16 on the wire")
    args = p.parse_args()

    hvd.init()
    torch.manual_seed(42)

    try:
        from torchvision import models
    except ImportError:
        models = None
    if models is not None:
        # A bad --model name fails loudly rather than silently swapping
        # in the stand-in with a wrong label.
        model = getattr(models, args.model)()
        model_name = args.model
    else:
        model = SmallResNetish()
        model_name = f"{args.model} (builtin stand-in; torchvision absent)"

    optimizer = torch.optim.SGD(model.parameters(), lr=0.01 * hvd.size())
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    optimizer = hvd.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        compression=compression)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)

    data = torch.randn(args.batch_size, 3, 224, 224)
    target = torch.randint(0, 1000, (args.batch_size,))

    def benchmark_step():
        optimizer.zero_grad()
        loss = F.cross_entropy(model(data), target)
        loss.backward()
        optimizer.step()

    if hvd.rank() == 0:
        print(f"Model: {model_name}, batch size {args.batch_size}, "
              f"{hvd.size()} worker(s)")
    for _ in range(args.num_warmup_batches):
        benchmark_step()

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.time()
        for _ in range(args.num_batches_per_iter):
            benchmark_step()
        ips = args.batch_size * args.num_batches_per_iter / (time.time() - t0)
        img_secs.append(ips)
        if hvd.rank() == 0:
            print(f"Iter #{i}: {ips:.1f} img/sec per worker")

    if hvd.rank() == 0:
        mean, conf = np.mean(img_secs), 1.96 * np.std(img_secs)
        print(f"Img/sec per worker: {mean:.1f} +-{conf:.1f}")
        print(f"Total img/sec on {hvd.size()} worker(s): "
              f"{mean * hvd.size():.1f} +-{conf * hvd.size():.1f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
