"""Data-parallel BERT fine-tuning with horovod_tpu.torch + fp16
gradient compression.

Reference analog: examples/pytorch/pytorch_bert.py-style fine-tune —
BASELINE config #3's shape: a transformers BERT encoder, the torch
DistributedOptimizer's per-parameter async allreduce hooks, and
``Compression.fp16`` halving every gradient payload on the wire.
Hermetic: the model is built from a (tiny, random-init) config and the
task is synthetic sequence classification, so nothing downloads.

Run:  horovodrun -np 2 python examples/torch/pytorch_bert_finetune.py
"""

import argparse
import time

import numpy as np
import torch

import horovod_tpu.torch as hvd


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--no-fp16", action="store_true",
                    help="disable fp16 gradient compression")
    args = ap.parse_args()

    hvd.init()
    torch.manual_seed(1234)  # same init everywhere; broadcast confirms

    from transformers import BertConfig, BertForSequenceClassification

    heads = max(args.hidden // 32, 1)
    while args.hidden % heads:
        heads -= 1  # largest head count that divides hidden_size
    cfg = BertConfig(vocab_size=1024, hidden_size=args.hidden,
                     num_hidden_layers=args.layers,
                     num_attention_heads=heads,
                     intermediate_size=4 * args.hidden,
                     max_position_embeddings=args.seq_len, num_labels=2)
    model = BertForSequenceClassification(cfg)

    compression = (hvd.Compression.none if args.no_fp16
                   else hvd.Compression.fp16)
    base_opt = torch.optim.AdamW(model.parameters(), lr=5e-5 * hvd.size())
    opt = hvd.DistributedOptimizer(
        base_opt, named_parameters=model.named_parameters(),
        compression=compression)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(base_opt, root_rank=0)

    rng = np.random.RandomState(100 + hvd.rank())  # rank-local shard
    tokens = torch.from_numpy(
        rng.randint(0, cfg.vocab_size,
                    (args.steps * args.batch_size, args.seq_len)))
    # Synthetic but learnable: the label is a parity bit of the tokens.
    labels = (tokens.sum(1) % 2).long()

    model.train()
    t0 = time.perf_counter()
    for step in range(args.steps):
        i = step * args.batch_size
        opt.zero_grad()
        out = model(input_ids=tokens[i:i + args.batch_size],
                    labels=labels[i:i + args.batch_size])
        out.loss.backward()   # hooks fire async fp16 allreduces here
        opt.step()            # synchronizes + applies averaged grads
        if hvd.rank() == 0:
            print(f"step {step}: loss {out.loss.item():.4f}", flush=True)
    if hvd.rank() == 0:
        dt = time.perf_counter() - t0
        n = args.steps * args.batch_size
        print(f"{n / dt:.1f} seq/sec/rank "
              f"({hvd.size() * n / dt:.1f} aggregate), "
              f"compression={'none' if args.no_fp16 else 'fp16'}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
