"""Data-parallel MNIST with horovod_tpu.torch.

Reference analog: examples/pytorch/pytorch_mnist.py — per-parameter
gradient hooks fire async allreduces during backward; ``opt.step()``
synchronizes them all (SURVEY.md §3.2's hot path).

Run:  horovodrun -np 2 python examples/torch/pytorch_mnist.py
"""

import argparse

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

import horovod_tpu.torch as hvd


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(784, 128)
        self.fc2 = nn.Linear(128, 10)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--fp16-allreduce", action="store_true")
    args = ap.parse_args()

    hvd.init()
    torch.manual_seed(1234)

    rng = np.random.RandomState(42)
    x = torch.from_numpy(rng.rand(4096, 784).astype(np.float32))
    y = torch.from_numpy(rng.randint(0, 10, 4096).astype(np.int64))
    x, y = x[hvd.rank()::hvd.size()], y[hvd.rank()::hvd.size()]

    model = Net()
    compression = (hvd.Compression.fp16 if args.fp16_allreduce
                   else hvd.Compression.none)
    opt = torch.optim.SGD(model.parameters(), lr=args.lr * hvd.size())
    opt = hvd.DistributedOptimizer(
        opt, named_parameters=model.named_parameters(),
        compression=compression)

    # Start everyone from rank 0's weights & optimizer state.
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    step = 0
    for epoch in range(args.epochs):
        for i in range(0, x.shape[0] - args.batch_size, args.batch_size):
            opt.zero_grad()
            out = model(x[i:i + args.batch_size])
            loss = F.cross_entropy(out, y[i:i + args.batch_size])
            loss.backward()          # hooks launch async allreduces
            opt.step()               # synchronize + apply averaged grads
            if step % 50 == 0 and hvd.rank() == 0:
                print(f"epoch {epoch} step {step} loss {loss.item():.4f}")
            step += 1

    final = hvd.allreduce(loss.detach(), name="final_loss")
    if hvd.rank() == 0:
        print(f"done: mean final loss = {final.item():.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
