"""Data-parallel Keras ResNet-50 — the reference's headline workload.

Reference analog: examples/keras/keras_imagenet_resnet50.py +
docs/benchmarks.rst (the ~90%-of-linear scaling chart): stock
tf.keras.applications.ResNet50, hvd.DistributedOptimizer, LR scaled by
world size with warmup, synthetic ImageNet-like data so it runs
hermetically. BASELINE config #2 is this script shape on a TPU pod.

Run:  horovodrun -np 2 python examples/keras/tensorflow2_keras_resnet50.py \
          --image-size 64 --batch-size 8 --steps 4
"""

import argparse
import time

import numpy as np
import tensorflow as tf

import horovod_tpu.keras as hvd


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--batch-size", type=int, default=32,
                    help="per-rank batch size")
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--classes", type=int, default=1000)
    args = ap.parse_args()

    hvd.init()
    tf.random.set_seed(1234)

    # Synthetic ImageNet-shaped shard for this rank.
    rng = np.random.RandomState(100 + hvd.rank())
    n = args.steps * args.batch_size
    x = rng.rand(n, args.image_size, args.image_size, 3).astype(np.float32)
    y = rng.randint(0, args.classes, n).astype(np.int64)

    model = tf.keras.applications.ResNet50(
        weights=None, classes=args.classes,
        input_shape=(args.image_size, args.image_size, 3))

    base_lr = 0.0125 * hvd.size()  # linear LR scaling (reference recipe)
    opt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(base_lr,
                                                           momentum=0.9))
    model.compile(
        optimizer=opt,
        loss=tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"])

    callbacks = [
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        hvd.callbacks.MetricAverageCallback(),
        hvd.callbacks.LearningRateWarmupCallback(
            initial_lr=base_lr, warmup_epochs=3, verbose=0),
    ]

    t0 = time.perf_counter()
    model.fit(x, y, batch_size=args.batch_size, epochs=args.epochs,
              callbacks=callbacks,
              verbose=2 if hvd.rank() == 0 else 0)
    dt = time.perf_counter() - t0
    images = n * args.epochs
    if hvd.rank() == 0:
        print(f"rank0: {images / dt:.1f} images/sec/rank "
              f"({hvd.size() * images / dt:.1f} aggregate)")
    hvd.shutdown()


if __name__ == "__main__":
    main()
