"""Data-parallel Keras MNIST with horovod_tpu.keras.

Reference analog: examples/tensorflow2/tensorflow2_keras_mnist.py —
DistributedOptimizer wrap + the canonical callback trio (broadcast,
metric averaging, LR warmup).

Run:  horovodrun -np 2 python examples/keras/tensorflow2_keras_mnist.py
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.keras as hvd


def main():
    hvd.init()
    tf.random.set_seed(1234)

    rng = np.random.RandomState(42)
    x = rng.rand(4096, 784).astype(np.float32)
    y = rng.randint(0, 10, 4096).astype(np.int64)
    x, y = x[hvd.rank()::hvd.size()], y[hvd.rank()::hvd.size()]

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="relu", input_shape=(784,)),
        tf.keras.layers.Dense(10),
    ])
    base_lr = 0.01
    opt = hvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(base_lr * hvd.size()))
    model.compile(
        optimizer=opt,
        loss=tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"])

    callbacks = [
        # Sync everyone to rank 0's weights before the first batch.
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        # Average epoch metrics across workers.
        hvd.callbacks.MetricAverageCallback(),
        # Ramp LR from base to base*size over the first 3 epochs.
        hvd.callbacks.LearningRateWarmupCallback(
            initial_lr=base_lr * hvd.size(), warmup_epochs=3, verbose=0),
    ]

    model.fit(x, y, batch_size=64, epochs=4,
              callbacks=callbacks,
              verbose=2 if hvd.rank() == 0 else 0)
    hvd.shutdown()


if __name__ == "__main__":
    main()
