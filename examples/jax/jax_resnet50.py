"""Data-parallel ResNet training with horovod_tpu.jax.

Reference analog: the tf_cnn_benchmarks ResNet-50 workload behind the
reference's headline scaling numbers (docs/benchmarks.rst) and
examples/pytorch/pytorch_imagenet_resnet50.py — the classic Horovod
recipe on the TPU-native stack: init, shard data by rank, jit the local
train step, allreduce gradients through the eager core (which rides the
xla_ici device plane on TPU, so gradients never leave HBM), broadcast
initial parameters.

Run:  horovodrun -np 4 python examples/jax/jax_resnet50.py --depth 18
Synthetic imagenet-shaped data keeps it hermetic; swap in a real input
pipeline (e.g. horovod_tpu.data.AsyncDataLoaderMixin) in practice.
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu.jax as hvd
from horovod_tpu.models import ResNetConfig, resnet_init, resnet_loss


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=50,
                    choices=[18, 34, 50, 101, 152])
    ap.add_argument("--batch-size", type=int, default=32,
                    help="per-rank batch size")
    ap.add_argument("--image-size", type=int, default=64,
                    help="synthetic image side (224 for the real thing)")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--base-lr", type=float, default=0.0125)
    args = ap.parse_args()

    hvd.init()
    cfg = ResNetConfig(depth=args.depth, num_classes=1000)

    params, state = resnet_init(cfg, jax.random.PRNGKey(0))
    # Reference recipe: scale the learning rate by world size.
    tx = optax.sgd(args.base_lr * hvd.size(), momentum=0.9)
    opt = tx.init(params)

    # One broadcast so every rank starts from rank 0's init.
    params = hvd.broadcast_parameters(params, root_rank=0)

    @jax.jit
    def local_grads(params, state, batch):
        (loss, state), grads = jax.value_and_grad(
            resnet_loss, has_aux=True)(params, state, batch, cfg)
        return loss, state, grads

    @jax.jit
    def apply(params, opt, grads):
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt

    rng = np.random.RandomState(hvd.rank())  # each rank: its own shard
    s = args.image_size
    t0 = time.perf_counter()
    for step in range(args.steps):
        batch = {
            "images": jnp.asarray(
                rng.rand(args.batch_size, s, s, 3), jnp.float32),
            "labels": jnp.asarray(
                rng.randint(0, 1000, args.batch_size), jnp.int32),
        }
        loss, state, grads = local_grads(params, state, batch)
        # The eager allreduce: negotiation + fusion in the core, payload
        # over ICI (device plane) or the host ring.
        grads = hvd.allreduce_gradients(grads, op=hvd.Average)
        params, opt = apply(params, opt, grads)
        if hvd.rank() == 0:
            print(f"step {step} loss {float(loss):.4f}")
    dt = time.perf_counter() - t0
    imgs = args.steps * args.batch_size * hvd.size()
    if hvd.rank() == 0:
        print(f"{imgs / dt:.1f} images/sec over {hvd.size()} ranks "
              f"(depth {args.depth}, {s}x{s})")
    hvd.shutdown()


if __name__ == "__main__":
    main()
