"""Llama pretraining through the EAGER Horovod path.

Reference analog: the canonical torch example
(``examples/pytorch/pytorch_synthetic_benchmark.py``): wrap the
optimizer, let every step's gradients ride hvd.allreduce. Here the
same shape in jax terms — jitted fwd/bwd, then a grouped DEVICE-PLANE
allreduce of the whole gradient tree (one atomic negotiation, one
cached fused XLA program over ICI), then a jitted optimizer apply.
Measured round 3 at ~99% of the fully-fused SPMD step on one chip
(docs/benchmarks.md) — the eager programming model costs ~nothing.

Run:
    horovodrun -np 4 python examples/jax/jax_llama_eager_hvd.py
    # or on a TPU pod: horovodrun --tpu-pod python ...
"""

import functools

import jax
import jax.numpy as jnp
import optax

import horovod_tpu.jax as hvd
from horovod_tpu.jax.functions import broadcast_parameters
from horovod_tpu.jax.optimizer import allreduce_gradients
from horovod_tpu.models import LlamaConfig, llama_init, llama_loss


def main():
    hvd.init()
    cfg = LlamaConfig.tiny(dtype="float32")  # size up on real hardware
    tx = optax.adam(1e-3)

    # Commit params/opt to the device up front: the data plane's
    # staging commits gradients, and mixing committed/uncommitted
    # trees flips the jit signature after the first step (a silent
    # full recompile — docs/benchmarks.md).
    dev = jax.local_devices()[0]
    params = jax.device_put(llama_init(cfg, jax.random.PRNGKey(0)), dev)
    params = broadcast_parameters(params, root_rank=0)
    opt = jax.device_put(tx.init(params), dev)

    grad_fn = jax.jit(
        lambda p, d: jax.value_and_grad(llama_loss)(p, d, cfg))

    @functools.partial(jax.jit, donate_argnums=(1, 2))
    def apply_fn(grads, params, opt):
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt

    batch, seq = 8, 128
    key = jax.random.PRNGKey(hvd.rank())
    for step in range(30):
        key, k = jax.random.split(key)
        tokens = jax.random.randint(k, (batch, seq), 0, cfg.vocab_size)
        data = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
        loss, grads = grad_fn(params, data)
        # One atomic group: negotiation fuses all tensors, the device
        # plane replays one cached program; donate=True lets it reuse
        # the gradients' HBM for the averaged results.
        grads = allreduce_gradients(grads, op=hvd.Average, donate=True)
        params, opt = apply_fn(grads, params, opt)
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step}: loss {float(loss):.4f}")
    if hvd.rank() == 0:
        print(f"final loss {float(loss):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
