"""Sharded llama-family pretraining — the flagship SPMD path.

This is the TPU-native side of the framework (net-new vs the reference,
which is pure data-parallel — SURVEY.md §5.7): a 4-axis
data/fsdp/tensor/seq ``jax.sharding.Mesh``, megatron-style TP + FSDP
parameter shardings, ring attention over the seq axis for long context,
and one jitted train step that XLA turns into fused compute+collectives
over ICI. ``--experts/--ep`` switch the FFNs to expert-parallel sparse
MoE; ``--pp`` pipelines the layer stack GPipe-style over the pipe axis.

Run on anything (CPU simulates a mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/jax/jax_llama_pretrain.py --dp 2 --fsdp 2 --tp 2 --sp 1
  # sparse-MoE with expert parallelism:
  #   ... --dp 2 --fsdp 1 --tp 2 --ep 2
  # pipeline parallelism:
  #   ... --dp 1 --fsdp 2 --tp 2 --pp 2
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

from horovod_tpu import parallel
from horovod_tpu.models import (
    LlamaConfig,
    llama_init,
    llama_loss,
    llama_partition_rules,
)
from horovod_tpu.parallel.sharding import apply_sharding, named_sharding


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2, help="data-parallel size")
    ap.add_argument("--fsdp", type=int, default=2, help="fsdp shards")
    ap.add_argument("--tp", type=int, default=2, help="tensor parallel")
    ap.add_argument("--sp", type=int, default=1, help="sequence parallel")
    ap.add_argument("--sp-mode", choices=("ring", "ulysses"),
                    default="ring",
                    help="sequence-parallel strategy (--sp > 1)")
    ap.add_argument("--param-dtype", choices=("float32", "bfloat16"),
                    default="float32",
                    help="parameter storage dtype (bfloat16 = pure-bf16 "
                         "training, halves param/grad/opt HBM)")
    ap.add_argument("--master-weights", action="store_true",
                    help="fp32 master params + fp32 adam moments with "
                         "bf16 compute (parallel.master_weights) — the "
                         "numerically safe mixed-precision recipe")
    ap.add_argument("--ep", type=int, default=1, help="expert parallel")
    ap.add_argument("--pp", type=int, default=1, help="pipeline stages")
    ap.add_argument("--pp-schedule", default="gpipe",
                    choices=("gpipe", "1f1b"),
                    help="pipeline schedule: gpipe (AD backward, O(M) "
                         "activation stash) or 1f1b (interleaved "
                         "fwd/bwd, O(stages) stash)")
    ap.add_argument("--experts", type=int, default=0,
                    help="sparse-MoE experts (0 = dense FFN)")
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--n-layers", type=int, default=4)
    args = ap.parse_args()

    if args.pp > 1 and args.sp > 1:
        raise SystemExit("--pp and --sp are mutually exclusive (ring "
                         "attention cannot nest inside the pipeline)")
    if args.ep > 1 and not args.experts:
        args.experts = 2 * args.ep
    n_needed = args.dp * args.fsdp * args.tp * args.sp * args.ep * args.pp
    if len(jax.devices()) < n_needed:
        raise SystemExit(
            f"need {n_needed} devices, have {len(jax.devices())} "
            f"(set XLA_FLAGS=--xla_force_host_platform_device_count=N)")

    mesh = parallel.create_mesh(data=args.dp, fsdp=args.fsdp,
                                tensor=args.tp, seq=args.sp,
                                expert=args.ep, pipe=args.pp,
                                devices=jax.devices()[:n_needed])

    heads = max(8, args.tp * 2)
    n_layers = args.n_layers
    if args.pp > 1 and n_layers % args.pp:
        # Round UP so the requested capacity is never silently shrunk.
        n_layers = args.pp * (n_layers // args.pp + 1)
        print(f"note: --n-layers rounded to {n_layers} "
              f"(must divide into {args.pp} pipeline stages)")
    cfg = LlamaConfig.tiny(
        d_model=args.d_model, n_layers=n_layers, n_heads=heads,
        n_kv_heads=heads, d_ff=4 * args.d_model, vocab_size=512,
        n_experts=args.experts, seq_parallel=args.sp_mode,
        pipeline_schedule=args.pp_schedule,
        param_dtype=args.param_dtype)

    if args.master_weights:
        # bf16 compute; params stay fp32 so the master aliases them at
        # init (no rounding, no transient double tree).
        import dataclasses

        cfg = dataclasses.replace(cfg, dtype="bfloat16",
                                  param_dtype="float32")

    params = llama_init(cfg, jax.random.PRNGKey(0))
    shardings = parallel.shard_params(
        params, mesh, llama_partition_rules(pipeline=args.pp > 1))
    params = apply_sharding(params, shardings)
    tx = optax.adamw(3e-4, weight_decay=0.01)

    # Batch must split into dp*fsdp shards AND pp microbatches.
    per = 2 * args.dp * args.fsdp
    batch_size = per if per % max(args.pp, 1) == 0 else per * args.pp

    if args.master_weights:
        # fp32 master copy + fp32 moments; bf16 cast feeds compute. The
        # master inherits the param shardings (cast preserves them).
        mw = parallel.master_weights(tx)
        state = mw.init(params)

        @jax.jit
        def train_step(state, batch):
            p = mw.compute_params(state)
            loss, grads = jax.value_and_grad(llama_loss)(p, batch, cfg,
                                                         mesh)
            return loss, mw.apply(state, grads)
    else:
        opt_state = tx.init(params)

        @jax.jit
        def train_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(llama_loss)(params, batch,
                                                         cfg, mesh)
            updates, opt_state = tx.update(grads, opt_state, params)
            return loss, optax.apply_updates(params, updates), opt_state

    rng = np.random.RandomState(0)
    for step in range(args.steps):
        tokens = jnp.asarray(
            rng.randint(0, cfg.vocab_size, (batch_size, args.seq_len)),
            jnp.int32)
        batch = {"tokens": tokens, "targets": jnp.roll(tokens, -1, 1)}
        batch = jax.device_put(
            batch, named_sharding(mesh, ("data", "fsdp"), "seq"))
        if args.master_weights:
            loss, state = train_step(state, batch)
        else:
            loss, params, opt_state = train_step(params, opt_state, batch)
        print(f"step {step} mesh={dict(mesh.shape)} loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
