"""Data-parallel MNIST MLP with horovod_tpu.jax.

Reference analog: examples/pytorch/pytorch_mnist.py & examples/
tensorflow2/tensorflow2_mnist.py — the canonical first Horovod script:
init, shard the data by rank, wrap the optimizer, broadcast initial
parameters, train.

Run:  horovodrun -np 4 python examples/jax/jax_mnist.py
(or `python -m horovod_tpu.runner.launch -np 4 ...` without the console
script installed). Uses a synthetic MNIST-shaped dataset so it runs
hermetically; swap `synthetic_mnist` for a real loader in practice.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu.jax as hvd
from horovod_tpu.models import mlp_init, mlp_forward


def synthetic_mnist(n, seed):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 784).astype(np.float32)
    y = rng.randint(0, 10, size=n).astype(np.int32)
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    hvd.init()
    np.random.seed(1234)

    # Shard the dataset by rank (each worker sees 1/size of the data).
    x, y = synthetic_mnist(4096, seed=42)
    x = x[hvd.rank()::hvd.size()]
    y = y[hvd.rank()::hvd.size()]

    params = mlp_init(jax.random.PRNGKey(0), sizes=(784, 128, 10))
    # Scale lr by world size (reference convention for averaged grads).
    opt = hvd.DistributedOptimizer(optax.sgd(args.lr * hvd.size()))
    opt_state = opt.init(params)

    # One-time consistency: everyone starts from rank 0's params.
    params = hvd.broadcast_parameters(params, root_rank=0)

    def loss_fn(p, xb, yb):
        logits = mlp_forward(p, xb)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb).mean()

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    step = 0
    for epoch in range(args.epochs):
        for i in range(0, x.shape[0] - args.batch_size, args.batch_size):
            xb = jnp.asarray(x[i:i + args.batch_size])
            yb = jnp.asarray(y[i:i + args.batch_size])
            loss, grads = grad_fn(params, xb, yb)
            # The optimizer allreduce-averages grads across workers.
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            if step % 50 == 0 and hvd.rank() == 0:
                print(f"epoch {epoch} step {step} loss {float(loss):.4f}")
            step += 1

    # Final sanity: average loss across workers.
    final = hvd.allreduce(jnp.asarray(float(loss)), name="final_loss")
    if hvd.rank() == 0:
        print(f"done: mean final loss across {hvd.size()} workers = "
              f"{float(final):.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
