"""Data-parallel TF2 custom-loop MNIST with horovod_tpu.tensorflow.

Reference analog: examples/tensorflow2/tensorflow2_mnist.py — a
tf.GradientTape training loop wrapped in ``DistributedGradientTape``,
with ``broadcast_variables`` after the first step.

Run:  horovodrun -np 2 python examples/tensorflow/tensorflow2_mnist.py
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def main():
    hvd.init()
    tf.random.set_seed(1234)

    rng = np.random.RandomState(42)
    x_all = rng.rand(4096, 784).astype(np.float32)
    y_all = rng.randint(0, 10, 4096).astype(np.int64)
    # Shard the data by rank.
    x_all, y_all = x_all[hvd.rank()::hvd.size()], y_all[hvd.rank()::hvd.size()]

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="relu", input_shape=(784,)),
        tf.keras.layers.Dense(10),
    ])
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True)
    # Linear LR scaling with world size (the reference's convention).
    opt = tf.keras.optimizers.SGD(0.01 * hvd.size())

    def train_step(xb, yb, first_batch):
        with tf.GradientTape() as tape:
            logits = model(xb, training=True)
            loss = loss_fn(yb, logits)
        # Wrap the tape: gradient() returns allreduce-averaged grads.
        tape = hvd.DistributedGradientTape(tape)
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        if first_batch:
            # After the first step (variables now exist), sync everyone
            # to rank 0 so all ranks optimize identical weights.
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0)
        return loss

    batch = 64
    for epoch in range(4):
        losses = []
        for i in range(0, len(x_all), batch):
            loss = train_step(x_all[i:i + batch], y_all[i:i + batch],
                              first_batch=(epoch == 0 and i == 0))
            losses.append(float(loss))
        # Average the epoch loss across workers for logging.
        avg = float(hvd.allreduce(tf.constant(np.mean(losses))))
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {avg:.4f}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
