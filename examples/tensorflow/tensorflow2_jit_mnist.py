"""XLA-compiled (jit_compile=True) data-parallel TF2 MNIST.

Reference analog: the HOROVOD_ENABLE_XLA_OPS workflow of
examples/tensorflow2/tensorflow2_mnist.py — here the native op library
(csrc/tf_ops.cc) lowers every collective to an XLA custom-call into the
core, so the ENTIRE train step (forward, DistributedGradientTape
gradients + allreduce, optimizer update) is one compiled XLA program.

Run:  horovodrun -np 2 python examples/tensorflow/tensorflow2_jit_mnist.py
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow as hvd


def main():
    hvd.init()
    tf.random.set_seed(1234)

    rng = np.random.RandomState(42 + hvd.rank())
    x = rng.rand(512, 28 * 28).astype("float32")
    y = rng.randint(0, 10, 512).astype("int64")

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(128, activation="relu",
                              input_shape=(28 * 28,)),
        tf.keras.layers.Dense(10),
    ])
    loss_fn = tf.keras.losses.SparseCategoricalCrossentropy(
        from_logits=True)
    opt = tf.keras.optimizers.SGD(0.01 * hvd.size())

    @tf.function(jit_compile=True)
    def train_step(xb, yb):
        with hvd.DistributedGradientTape(tf.GradientTape()) as tape:
            loss = loss_fn(yb, model(xb, training=True))
        grads = tape.gradient(loss, model.trainable_variables)
        opt.apply_gradients(zip(grads, model.trainable_variables))
        return loss

    first = True
    for step in range(50):
        i = (step * 64) % 448
        loss = train_step(x[i:i + 64], y[i:i + 64])
        if first:
            # After the first (compiled) step: everyone adopts rank 0's
            # weights so the replicas stay in lockstep.
            hvd.broadcast_variables(model.variables, root_rank=0)
            hvd.broadcast_variables(opt.variables, root_rank=0,
                                    prefix="opt")
            first = False
        if step % 10 == 0 and hvd.rank() == 0:
            print(f"step {step} loss {float(loss):.4f}")

    if hvd.rank() == 0:
        print("done:", float(loss))
    hvd.shutdown()


if __name__ == "__main__":
    main()
