"""Elastic (fault-tolerant) training with horovod_tpu.jax.elastic.

Reference analog: examples/elastic/pytorch/pytorch_mnist_elastic.py —
wrap the train loop in @hvd.elastic.run with a State; on worker
loss/addition the loop rolls back to the last commit and resumes with the
new world size.

Run (hosts can come and go between polls):
  horovodrun -np 2 --min-np 1 --max-np 4 \
      --host-discovery-script ./discover_hosts.sh \
      python examples/elastic/jax_elastic_mnist.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu.jax as hvd
from horovod_tpu.models import mlp_forward, mlp_init


def main():
    hvd.init()

    rng = np.random.RandomState(42)
    data_x = rng.rand(4096, 784).astype(np.float32)
    data_y = rng.randint(0, 10, 4096).astype(np.int32)

    params = mlp_init(jax.random.PRNGKey(0), sizes=(784, 64, 10))
    opt = hvd.DistributedOptimizer(optax.adam(1e-3))
    state = hvd.elastic.JaxState(
        params=params, opt_state=opt.init(params), epoch=0, batch=0)

    def loss_fn(p, xb, yb):
        return optax.softmax_cross_entropy_with_integer_labels(
            mlp_forward(p, xb), yb).mean()

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    @hvd.elastic.run
    def train(state):
        bs = 64
        while state.epoch < 3:
            # Re-shard for the CURRENT world size each generation.
            x = data_x[hvd.rank()::hvd.size()]
            y = data_y[hvd.rank()::hvd.size()]
            n_batches = x.shape[0] // bs
            while state.batch < n_batches:
                i = state.batch * bs
                loss, grads = grad_fn(state.params,
                                      jnp.asarray(x[i:i + bs]),
                                      jnp.asarray(y[i:i + bs]))
                updates, state.opt_state = opt.update(
                    grads, state.opt_state, state.params)
                state.params = optax.apply_updates(state.params, updates)
                state.batch += 1
                if state.batch % 20 == 0:
                    # Checkpoint progress: rollback target after a failure.
                    state.commit()
                    if hvd.rank() == 0:
                        print(f"epoch {state.epoch} batch {state.batch} "
                              f"np={hvd.size()} loss {float(loss):.4f}")
            state.batch = 0
            state.epoch += 1
            state.commit()

    train(state)
    if hvd.rank() == 0:
        print("elastic training finished")
    hvd.shutdown()


if __name__ == "__main__":
    main()
