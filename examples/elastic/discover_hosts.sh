#!/bin/sh
# Sample host-discovery script for elastic runs (reference analog: the
# --host-discovery-script contract in horovod/runner/elastic/discovery.py).
# Print one "host:slots" line per currently-available host; the elastic
# driver polls this script and grows/shrinks the job to match. Swap the
# body for your autoscaler / resource-manager query. This sample reads a
# plain hosts file so tests (and humans) can add/remove hosts by editing
# it live:
#
#   echo "localhost:2" >  /tmp/hosts.txt
#   horovodrun -np 2 --min-np 1 --max-np 4 \
#       --host-discovery-script examples/elastic/discover_hosts.sh ...
#   echo "localhost:4" >  /tmp/hosts.txt   # scale up mid-run
#
HOSTS_FILE="${HOROVOD_HOSTS_FILE:-/tmp/hosts.txt}"
[ -f "$HOSTS_FILE" ] && cat "$HOSTS_FILE" || echo "localhost:2"
