"""Elastic (fault-tolerant) Keras training with hvd.elastic callbacks.

Reference analog: examples/elastic/tensorflow2/tensorflow2_keras_mnist_elastic.py —
a compiled keras model wrapped in KerasState; CommitStateCallback
checkpoints during fit(), Update{Batch,Epoch}StateCallback keep the
state's position current, and @hvd.elastic.run re-enters fit at
initial_epoch=state.epoch after a worker is lost or added.

Run (hosts can come and go between polls):
  horovodrun -np 2 --min-np 1 --max-np 4 \
      --host-discovery-script ./discover_hosts.sh \
      python examples/elastic/tensorflow2_keras_elastic_mnist.py
"""

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow.keras as hvd


def main():
    hvd.init()
    tf.keras.utils.set_random_seed(1234)

    rng = np.random.RandomState(42)
    data_x = rng.rand(4096, 784).astype(np.float32)
    data_y = rng.randint(0, 10, 4096).astype(np.int64)

    model = tf.keras.Sequential([
        tf.keras.layers.Dense(64, activation="relu", input_shape=(784,)),
        tf.keras.layers.Dense(10),
    ])
    opt = hvd.DistributedOptimizer(tf.keras.optimizers.SGD(0.01))
    model.compile(
        optimizer=opt,
        loss=tf.keras.losses.SparseCategoricalCrossentropy(from_logits=True),
        metrics=["accuracy"])

    state = hvd.elastic.KerasState(model, batch=0, epoch=0)

    @hvd.elastic.run
    def train(state):
        # Re-shard for the CURRENT world size each generation.
        x = data_x[hvd.rank()::hvd.size()]
        y = data_y[hvd.rank()::hvd.size()]
        callbacks = [
            hvd.elastic.UpdateBatchStateCallback(state),
            hvd.elastic.UpdateEpochStateCallback(state),
            # After the update callbacks: commits must snapshot the
            # already-advanced position.
            hvd.elastic.CommitStateCallback(state, batches_per_commit=20),
        ]
        model.fit(x, y, batch_size=64, epochs=3,
                  initial_epoch=state.epoch, callbacks=callbacks,
                  verbose=2 if hvd.rank() == 0 else 0)

    train(state)
    if hvd.rank() == 0:
        print(f"done at epoch {state.epoch} with world size {hvd.size()}")
    hvd.shutdown()


if __name__ == "__main__":
    main()
